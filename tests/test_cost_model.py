"""Dual CPU/device cost model (CostBasedOptimizer.scala:284 CpuCostModel,
:334 GpuCostModel): section-level device-vs-CPU decisions with
transition costs priced in."""

import numpy as np
import pytest

from spark_rapids_tpu.conf import SrtConf
from spark_rapids_tpu.expr import col, lit
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.expr.core import Alias
from spark_rapids_tpu.plan import overrides
from spark_rapids_tpu.plan.cost import device_vs_cpu, estimate_rows
from spark_rapids_tpu.plan.session import TpuSession


def _reasons(meta):
    out = list(meta.reasons)
    for c in meta.child_plans:
        out.extend(_reasons(c))
    return out


@pytest.fixture()
def big_parquet(tmp_path):
    session = TpuSession(SrtConf({}))
    n = 200_000
    rng = np.random.default_rng(3)
    df = session.create_dataframe({
        "k": rng.integers(0, 100, n).tolist(),
        "v": rng.uniform(0, 1, n).tolist(),
    })
    p = str(tmp_path / "big")
    df.write.parquet(p)
    return p


def test_tiny_plan_goes_cpu():
    session = TpuSession(SrtConf({"srt.sql.optimizer.enabled": True}))
    df = session.create_dataframe({"a": [1, 2, 3]}) \
        .select((col("a") + lit(1)).alias("b") if hasattr(col("a") + lit(1), "alias")
                else Alias(col("a") + lit(1), "b"))
    meta = overrides.tag_only(df.plan, session.conf)
    assert any("cost model" in r for r in _reasons(meta)), \
        "tiny plan should be kept off the device by the cost model"
    # and it still runs correctly through the CPU engine
    rows = df.collect()
    assert [r["b"] for r in rows] == [2, 3, 4]


def test_big_scan_stays_on_device(big_parquet):
    """The never-slower property: a scan-heavy aggregation must NOT be
    forced to CPU by the cost model."""
    session = TpuSession(SrtConf({"srt.sql.optimizer.enabled": True}))
    df = session.read.parquet(big_parquet) \
        .group_by("k").agg(Alias(Sum(col("v")), "s"),
                           Alias(CountStar(), "c"))
    meta = overrides.tag_only(df.plan, session.conf)
    assert not any("cost model" in r for r in _reasons(meta)), \
        f"big plan wrongly costed to CPU: {_reasons(meta)}"


def test_dual_model_orders_sections(big_parquet):
    """device_vs_cpu: the device must win big scans and lose tiny
    local relations."""
    session = TpuSession(SrtConf({}))
    big = session.read.parquet(big_parquet).group_by("k") \
        .agg(Alias(Sum(col("v")), "s"))
    cpu_cost, dev_cost = device_vs_cpu(big.plan)
    assert dev_cost < cpu_cost
    tiny = session.create_dataframe({"a": list(range(10))}) \
        .select(Alias(col("a") + lit(1), "b"))
    cpu_cost, dev_cost = device_vs_cpu(tiny.plan)
    assert cpu_cost < dev_cost


def test_estimate_rows_file_scan(big_parquet):
    session = TpuSession(SrtConf({}))
    est = estimate_rows(session.read.parquet(big_parquet).plan)
    # bytes-based estimate: right order of magnitude for 200k rows
    assert 10_000 < est < 2_000_000


def test_results_identical_with_optimizer(big_parquet):
    base = TpuSession(SrtConf({}))
    opt = TpuSession(SrtConf({"srt.sql.optimizer.enabled": True}))

    def run(s):
        return {r["k"]: r for r in
                s.read.parquet(big_parquet).group_by("k")
                .agg(Alias(Sum(col("v")), "s"),
                     Alias(CountStar(), "c")).collect()}
    a, b = run(base), run(opt)
    assert set(a) == set(b)
    for k in a:
        assert a[k]["c"] == b[k]["c"]
        assert a[k]["s"] == pytest.approx(b[k]["s"], rel=1e-9)
