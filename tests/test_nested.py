"""Nested types on device: list/struct columns, collection expressions,
explode/Generate, parquet round-trip — differential vs the CPU oracle
(reference surface: collectionOperations.scala, complexTypeCreator.scala,
complexTypeExtractors.scala, GpuGenerateExec.scala)."""

import datetime

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expr import (ArrayContains, ArrayMax, ArrayMin,
                                   ElementAt, GetArrayItem, GetStructField,
                                   Size, SortArray, array, col, explode,
                                   lit, posexplode, struct)
from spark_rapids_tpu.expr.collections import explode_outer
from spark_rapids_tpu.expr.core import Alias
from spark_rapids_tpu.plan.session import TpuSession
from spark_rapids_tpu.testing import (assert_runs_on_tpu,
                                      assert_tpu_cpu_equal_df)


@pytest.fixture()
def session():
    return TpuSession()


@pytest.fixture()
def arrays_df(session):
    rng = np.random.default_rng(7)
    rows = []
    for i in range(200):
        r = rng.random()
        if r < 0.1:
            rows.append(None)
        elif r < 0.2:
            rows.append([])
        else:
            lst = [int(v) if rng.random() > 0.15 else None
                   for v in rng.integers(-50, 50, int(rng.integers(1, 9)))]
            rows.append(lst)
    return session.create_dataframe(
        {"a": rows, "x": list(range(200))},
        schema=[("a", dt.ArrayType(dt.INT64)), ("x", dt.INT64)])


def test_array_set_functions(arrays_df, session):
    """array_distinct/union/intersect/except/overlap/remove/position/
    slice/reverse differential (collectionOperations.scala family)."""
    from spark_rapids_tpu.expr import (ArrayDistinct, ArrayExcept,
                                       ArrayIntersect, ArrayPosition,
                                       ArrayRemove, ArrayReverse,
                                       ArraysOverlap, ArrayUnion, Slice)
    rng = np.random.default_rng(23)
    rows_a, rows_b = [], []
    for _ in range(150):
        def mk():
            r = rng.random()
            if r < 0.1:
                return None
            if r < 0.2:
                return []
            return [int(v) if rng.random() > 0.2 else None
                    for v in rng.integers(-5, 6,
                                          int(rng.integers(1, 7)))]
        rows_a.append(mk())
        rows_b.append(mk())
    df = session.create_dataframe(
        {"a": rows_a, "b": rows_b,
         "v": [int(v) for v in rng.integers(-5, 6, 150)],
         "s": [int(v) for v in rng.integers(-3, 4, 150)],
         "n": [int(v) for v in rng.integers(0, 4, 150)]},
        schema=[("a", dt.ArrayType(dt.INT64)),
                ("b", dt.ArrayType(dt.INT64)),
                ("v", dt.INT64), ("s", dt.INT64), ("n", dt.INT64)])
    from spark_rapids_tpu.testing import assert_tpu_cpu_equal_df
    assert_tpu_cpu_equal_df(df.select(
        Alias(ArrayDistinct(col("a")), "d"),
        Alias(ArrayUnion(col("a"), col("b")), "u"),
        Alias(ArrayIntersect(col("a"), col("b")), "i"),
        Alias(ArrayExcept(col("a"), col("b")), "e"),
        Alias(ArraysOverlap(col("a"), col("b")), "o"),
        Alias(ArrayRemove(col("a"), col("v")), "r"),
        Alias(ArrayPosition(col("a"), col("v")), "p"),
        Alias(ArrayReverse(col("a")), "rev")))
    # slice: start!=0 (0 is Spark's error case; this engine nulls it)
    df2 = df.filter(col("s") != lit(0))
    assert_tpu_cpu_equal_df(df2.select(
        Alias(Slice(col("a"), col("s"), col("n")), "sl")))


def test_array_repeat(session):
    from spark_rapids_tpu.expr import ArrayRepeat
    df = session.create_dataframe(
        {"v": [1, None, 3], "x": [0, 1, 2]},
        schema=[("v", dt.INT64), ("x", dt.INT64)])
    from spark_rapids_tpu.testing import assert_tpu_cpu_equal_df
    # literal count -> device; column count -> CPU fallback, both match
    assert_tpu_cpu_equal_df(df.select(
        Alias(ArrayRepeat(col("v"), lit(3)), "r")))
    assert_tpu_cpu_equal_df(df.select(
        Alias(ArrayRepeat(col("v"), col("x")), "r")))


def test_size_item_contains(arrays_df):
    df = arrays_df.select(
        col("x"),
        Alias(Size(col("a")), "n"),
        Alias(GetArrayItem(col("a"), lit(0)), "first"),
        Alias(GetArrayItem(col("a"), lit(3)), "fourth"),
        Alias(ElementAt(col("a"), lit(1)), "e1"),
        Alias(ElementAt(col("a"), lit(-2)), "em2"),
        Alias(ArrayContains(col("a"), lit(7)), "has7"))
    assert_runs_on_tpu(df)


def test_array_min_max_sort(arrays_df):
    df = arrays_df.select(
        col("x"),
        Alias(ArrayMin(col("a")), "mn"),
        Alias(ArrayMax(col("a")), "mx"),
        Alias(SortArray(col("a")), "sa"),
        Alias(SortArray(col("a"), False), "sd"))
    assert_runs_on_tpu(df)


def test_create_array_and_struct(session):
    df = session.create_dataframe({"x": list(range(50)),
                                   "y": [i * 1.5 for i in range(50)]})
    out = df.select(
        col("x"),
        Alias(array(col("x"), col("x") * 2, lit(None)), "arr"),
        Alias(struct(a=col("x"), b=col("y")), "st"))
    assert_runs_on_tpu(out)


def test_struct_field_access(session):
    df = session.create_dataframe({"x": list(range(30))})
    st = df.select(col("x"), Alias(struct(u=col("x"), v=col("x") + 5),
                                   "s"))
    out = st.select(col("x"), Alias(GetStructField(col("s"), "v"), "v"))
    assert_runs_on_tpu(out)


def test_struct_column_from_data(session):
    rows = [{"name": f"n{i}", "score": float(i)} if i % 7 else None
            for i in range(60)]
    df = session.create_dataframe(
        {"s": rows, "x": list(range(60))},
        schema=[("s", dt.StructType((("name", dt.STRING),
                                     ("score", dt.FLOAT64)))),
                ("x", dt.INT64)])
    out = df.select(col("x"),
                    Alias(GetStructField(col("s"), "name"), "nm"),
                    Alias(GetStructField(col("s"), "score"), "sc"))
    assert_tpu_cpu_equal_df(out)


@pytest.mark.parametrize("gen", [explode, posexplode, explode_outer])
def test_explode_variants(arrays_df, gen):
    df = arrays_df.select(col("x"), Alias(gen(col("a")), "e"))
    assert_runs_on_tpu(df)


def test_explode_filter_on_device(arrays_df):
    df = arrays_df.select(col("x"), Alias(explode(col("a")), "e")) \
        .filter(col("e") > 0)
    assert_runs_on_tpu(df)


def test_explode_then_aggregate(arrays_df):
    from spark_rapids_tpu.expr.aggregates import CountStar, Sum
    df = arrays_df.select(Alias(explode(col("a")), "e")) \
        .group_by("e").agg(Alias(CountStar(), "c"))
    assert_tpu_cpu_equal_df(df)


def test_string_array_explode(session):
    rows = [["alpha", "beta"], None, ["gamma", None, "delta"], []]
    df = session.create_dataframe(
        {"a": rows * 10, "x": list(range(40))},
        schema=[("a", dt.ArrayType(dt.STRING)), ("x", dt.INT64)])
    out = df.select(col("x"), Alias(explode(col("a")), "s"))
    assert_tpu_cpu_equal_df(out)


def test_filter_carries_list_column(arrays_df):
    # list column flows through a device filter untouched
    df = arrays_df.filter(col("x") % 3 == 0)
    assert_tpu_cpu_equal_df(df)


def test_nested_join_falls_back(session):
    """Nested payload through a join routes to CPU (correct results
    via fallback) until partition/concat support nested columns."""
    from spark_rapids_tpu.testing import assert_falls_back_to_cpu
    left = session.create_dataframe(
        {"k": [1, 2, 3], "a": [[1], [2, 2], None]},
        schema=[("k", dt.INT64), ("a", dt.ArrayType(dt.INT64))])
    right = session.create_dataframe({"k": [1, 2], "w": [10, 20]})
    assert_falls_back_to_cpu(left.join(right, "k"), "nested")


def test_parquet_nested_round_trip(session, tmp_path):
    rows = [[1, 2], None, [3, None, 5], []]
    structs = [{"u": i, "v": f"s{i}"} for i in range(4)]
    df = session.create_dataframe(
        {"a": rows, "s": structs, "x": [1, 2, 3, 4]},
        schema=[("a", dt.ArrayType(dt.INT64)),
                ("s", dt.StructType((("u", dt.INT64), ("v", dt.STRING)))),
                ("x", dt.INT64)])
    path = str(tmp_path / "nested")
    df.write.parquet(path)
    back = session.read.parquet(path)
    got = sorted(back.collect(), key=lambda r: r["x"])
    want = sorted(df.collect(), key=lambda r: r["x"])
    assert got == want
    # and the scan's list column is device-explodable
    out = back.select(col("x"), Alias(explode_outer(col("a")), "e"))
    assert_tpu_cpu_equal_df(out)


def test_date_array_elements(session):
    d = datetime.date
    rows = [[d(2024, 1, 1), d(2023, 5, 5)], None, [d(2020, 2, 29)]]
    df = session.create_dataframe(
        {"a": rows, "x": [1, 2, 3]},
        schema=[("a", dt.ArrayType(dt.DATE)), ("x", dt.INT64)])
    out = df.select(col("x"), Alias(ArrayMin(col("a")), "mn"),
                    Alias(explode_outer(col("a")), "e"))
    assert_tpu_cpu_equal_df(out)
