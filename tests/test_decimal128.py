"""decimal128 (precision 19..38) on device: limb kernels, arithmetic,
casts, comparisons, aggregates — differential vs the exact python-int
CPU oracle, plus direct limb-math unit checks vs python ints.

Reference surface: decimalExpressions.scala, GpuCast.scala decimal
paths, DecimalPrecision result-type rules, aggregate GpuSum/GpuMin/
GpuMax/GpuAverage on DECIMAL128 (SURVEY §7 hard-part 6).
"""

import decimal
import random

import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.columnar import decimal128 as d128
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min, Sum
from spark_rapids_tpu.expr.arithmetic import IntegralDivide, Pmod
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.core import col, lit
from spark_rapids_tpu.plan import TpuSession
from spark_rapids_tpu.testing import (DecimalGen, DoubleGen, IntGen,
                                      LongGen, assert_falls_back_to_cpu,
                                      assert_tpu_cpu_equal_df, gen_table)

N = 128


@pytest.fixture(scope="module")
def session():
    return TpuSession()


def make_df(session, gens, n=N, seed=0):
    data, schema = gen_table(gens, n, seed)
    return session.create_dataframe(data, schema)


def _limbs(vals):
    hi = jnp.asarray([np.int64(v >> 64) for v in vals])
    lo = jnp.asarray([np.uint64(v & ((1 << 64) - 1)) for v in vals])
    return hi, lo


def _ints(hi, lo):
    hi, lo = np.asarray(hi), np.asarray(lo)
    return [int(h) * (1 << 64) + int(l) for h, l in zip(hi, lo)]


# --- limb kernel unit tests ------------------------------------------------

def test_divmod_and_half_up_vs_python():
    rnd = random.Random(7)
    a = [rnd.randint(-10 ** 38 + 1, 10 ** 38 - 1) for _ in range(64)]
    b = [rnd.randint(1, 10 ** 25) * rnd.choice([1, -1]) for _ in range(64)]
    ah, al = _limbs(a)
    bh, bl = _limbs(b)
    qh, ql, ovf = d128.d128_div_exact(ah, al, bh, bl, 0)
    assert not np.asarray(ovf).any()
    for got, x, y in zip(_ints(qh, ql), a, b):
        q, r = divmod(abs(x), abs(y))
        if 2 * r >= abs(y):
            q += 1
        assert got == (q if (x < 0) == (y < 0) else -q)


def test_mul_exact_256bit_vs_python():
    rnd = random.Random(8)
    a = [rnd.randint(-10 ** 38 + 1, 10 ** 38 - 1) for _ in range(64)]
    b = [rnd.randint(-10 ** 38 + 1, 10 ** 38 - 1) for _ in range(64)]
    ah, al = _limbs(a)
    bh, bl = _limbs(b)
    for drop in (0, 9, 38):
        rh, rl, ovf = d128.d128_mul_exact(ah, al, bh, bl, drop)
        for got, o, x, y in zip(_ints(rh, rl), np.asarray(ovf), a, b):
            p = abs(x * y)
            if drop:
                p = (p + 10 ** drop // 2) // 10 ** drop
            exp = p if (x < 0) == (y < 0) else -p
            if abs(exp) < 2 ** 127:
                assert not o and got == exp
            else:
                assert o


def test_seg_sum128_and_minmax_vs_python():
    rnd = np.random.default_rng(9)
    vals = [int(v) * 10 ** 18 + int(w) for v, w in
            zip(rnd.integers(-10 ** 18, 10 ** 18, 100),
                rnd.integers(0, 10 ** 18, 100))]
    gid = jnp.asarray(rnd.integers(0, 5, 100), jnp.int32)
    hi, lo = _limbs(vals)
    sh, sl = d128.seg_sum128(hi, lo, gid, 5)
    mh, ml = d128.seg_minmax128(hi, lo, jnp.ones(100, bool), gid, 5, False)
    xh, xl = d128.seg_minmax128(hi, lo, jnp.ones(100, bool), gid, 5, True)
    sums = _ints(sh, sl)
    mins = _ints(mh, ml)
    maxs = _ints(xh, xl)
    for g in range(5):
        grp = [v for v, gg in zip(vals, np.asarray(gid)) if gg == g]
        assert sums[g] == ((sum(grp) + 2 ** 127) % 2 ** 128) - 2 ** 127
        assert mins[g] == min(grp)
        assert maxs[g] == max(grp)


def test_result_type_rules():
    a = dt.DecimalType(38, 10)
    b = dt.DecimalType(38, 10)
    assert dt.decimal_result_type("add", a, b) == dt.DecimalType(38, 9)
    assert dt.decimal_result_type("mul", a, b) == dt.DecimalType(38, 6)
    assert dt.decimal_result_type("div", a, b) == dt.DecimalType(38, 6)
    c = dt.DecimalType(10, 2)
    d = dt.DecimalType(8, 3)
    assert dt.decimal_result_type("mul", c, d) == dt.DecimalType(19, 5)


# --- differential: arithmetic ---------------------------------------------

def test_wide_add_sub_mul_div(session):
    df = make_df(session, {"a": DecimalGen(30, 4), "b": DecimalGen(25, 2)})
    assert_tpu_cpu_equal_df(df.select(
        (col("a") + col("b")).alias("s"),
        (col("a") - col("b")).alias("d"),
        (col("a") * col("b")).alias("p"),
        (col("a") / col("b")).alias("q")))


def test_max_precision_arithmetic(session):
    df = make_df(session, {"a": DecimalGen(38, 6), "b": DecimalGen(38, 6)},
                 seed=21)
    assert_tpu_cpu_equal_df(df.select(
        (col("a") + col("b")).alias("s"),
        (col("a") * col("b")).alias("p"),
        (col("a") / col("b")).alias("q")))


def test_narrow_to_wide_product(session):
    df = make_df(session, {"a": DecimalGen(10, 2), "b": DecimalGen(10, 2)})
    assert_tpu_cpu_equal_df(df.select(
        (col("a") * col("b")).alias("p"),
        (col("a") / col("b")).alias("q")))


def test_narrow_mod_div_pmod(session):
    df = make_df(session, {"a": DecimalGen(16, 2), "b": DecimalGen(10, 4)},
                 seed=31)
    assert_tpu_cpu_equal_df(df.select(
        (col("a") % col("b")).alias("m"),
        Pmod(col("a"), col("b")).alias("pm"),
        IntegralDivide(col("a"), col("b")).alias("dv")))


def test_wide_unary_and_literal(session):
    from spark_rapids_tpu.expr.arithmetic import Abs, UnaryMinus
    df = make_df(session, {"a": DecimalGen(33, 3)}, seed=41)
    big = decimal.Decimal("123456789012345678901234.567")
    assert_tpu_cpu_equal_df(df.select(
        UnaryMinus(col("a")).alias("neg"),
        Abs(col("a")).alias("ab"),
        (col("a") + lit(big)).alias("plus_lit")))


# --- differential: comparisons / filter ------------------------------------

def test_wide_comparisons_and_filter(session):
    df = make_df(session, {"a": DecimalGen(28, 3), "b": DecimalGen(28, 5)},
                 seed=51)
    assert_tpu_cpu_equal_df(df.select(
        (col("a") < col("b")).alias("lt"),
        (col("a") == col("b")).alias("eq"),
        (col("a") >= col("b")).alias("ge")))
    assert_tpu_cpu_equal_df(df.filter(col("a") > col("b")))
    assert_tpu_cpu_equal_df(df.select(
        col("a").is_null().alias("inull"),
        col("a").is_not_null().alias("nnull")))


# --- differential: cast matrix --------------------------------------------

def test_cast_matrix_wide(session):
    df = make_df(session, {"a": DecimalGen(32, 6), "i": LongGen(),
                           "f": DoubleGen(no_special=True, lo=-1e6,
                                          hi=1e6)}, seed=61)
    assert_tpu_cpu_equal_df(df.select(
        Cast(col("a"), dt.DecimalType(38, 10)).alias("up"),
        Cast(col("a"), dt.DecimalType(20, 1)).alias("down"),
        Cast(col("a"), dt.DecimalType(12, 2)).alias("to_narrow"),
        Cast(col("a"), dt.FLOAT64).alias("to_f"),
        Cast(col("a"), dt.INT64).alias("to_l"),
        Cast(col("a"), dt.INT32).alias("to_i"),
        Cast(col("a"), dt.BOOL).alias("to_b"),
        Cast(col("i"), dt.DecimalType(38, 10)).alias("l_to_wide"),
        Cast(col("f"), dt.DecimalType(30, 8)).alias("f_to_wide")))


def test_cast_overflow_nulls(session):
    df = make_df(session, {"a": DecimalGen(38, 0)}, seed=71)
    # most 38-digit values overflow decimal(20,0) -> nulls on both paths
    assert_tpu_cpu_equal_df(df.select(
        Cast(col("a"), dt.DecimalType(20, 0)).alias("narrowed"),
        Cast(col("a"), dt.INT64).alias("to_long")))


def test_wide_string_cast_falls_back(session):
    df = make_df(session, {"a": DecimalGen(30, 2)})
    assert_falls_back_to_cpu(df.select(
        Cast(col("a"), dt.STRING).alias("s")))


# --- differential: aggregates ----------------------------------------------

def test_wide_aggregates_grouped(session):
    df = make_df(session, {"k": IntGen(lo=0, hi=6), "v": DecimalGen(30, 4)},
                 n=256, seed=81)
    assert_tpu_cpu_equal_df(df.group_by(col("k")).agg(
        Sum(col("v")).alias("s"), Min(col("v")).alias("mn"),
        Max(col("v")).alias("mx"), Average(col("v")).alias("av"),
        Count(col("v")).alias("n")))


def test_narrow_sum_widens_past_long(session):
    # sum(decimal(12,2)) -> decimal(22,2): two-limb accumulator engaged
    df = make_df(session, {"k": IntGen(lo=0, hi=4), "v": DecimalGen(12, 2)},
                 n=256, seed=83)
    assert_tpu_cpu_equal_df(df.group_by(col("k")).agg(
        Sum(col("v")).alias("s"), Average(col("v")).alias("av")))


def test_wide_global_aggregate(session):
    df = make_df(session, {"v": DecimalGen(36, 2)}, n=200, seed=85)
    assert_tpu_cpu_equal_df(df.agg(
        Sum(col("v")).alias("s"), Min(col("v")).alias("mn"),
        Max(col("v")).alias("mx")))


def test_sum_overflow_nulls(session):
    # decimal(38,0) values near the bound: sum overflows decimal(38,0)'s
    # 10^38 precision in one group -> null on both engines
    vals = [decimal.Decimal(10 ** 37 * 9)] * 30
    df = session.create_dataframe(
        {"k": [1] * 30, "v": vals},
        [("k", dt.INT32), ("v", dt.DecimalType(38, 0))])
    assert_tpu_cpu_equal_df(df.group_by(col("k")).agg(
        Sum(col("v")).alias("s")))


# --- fallback routing -------------------------------------------------------

def test_wide_group_key_falls_back(session):
    df = make_df(session, {"k": DecimalGen(25, 2), "v": IntGen()})
    assert_falls_back_to_cpu(df.group_by(col("k")).agg(
        Count(col("v")).alias("n")))


def test_wide_sort_key_falls_back(session):
    df = make_df(session, {"a": DecimalGen(25, 2)})
    assert_falls_back_to_cpu(df.order_by(col("a")))


def test_wide_payload_through_sort_and_union(session):
    # wide decimals as PAYLOAD flow through gather/concat kernels
    df = make_df(session, {"k": IntGen(lo=0, hi=50), "v": DecimalGen(28, 3)})
    assert_tpu_cpu_equal_df(df.order_by(col("k")))
    assert_tpu_cpu_equal_df(df.union(df))


def test_roundtrip_create_collect(session):
    gens = {"v": DecimalGen(38, 10)}
    data, schema = gen_table(gens, 64, seed=91)
    df = session.create_dataframe(data, schema)
    out = df.to_pydict()
    assert out["v"] == data["v"]


def test_parquet_roundtrip_wide(session, tmp_path):
    import pyarrow.parquet as pq
    from spark_rapids_tpu.io.arrow_convert import (arrow_to_host_table,
                                                   host_table_to_arrow)
    from spark_rapids_tpu.plan.host_table import from_pydict, to_pydict
    gens = {"v": DecimalGen(34, 8), "w": DecimalGen(12, 2)}
    data, schema = gen_table(gens, 64, seed=93)
    ht = from_pydict(data, schema)
    path = str(tmp_path / "dec.parquet")
    pq.write_table(host_table_to_arrow(ht), path)
    back = arrow_to_host_table(pq.read_table(path))
    assert to_pydict(back) == data
    # and through the session scan
    df = session.read.parquet(path)
    out = df.to_pydict()
    assert out["v"] == data["v"] and out["w"] == data["w"]


def test_adjusted_scale_add_and_avg(session):
    # decimal(38,10) ops where adjustPrecisionScale trims the result
    # scale below the operand scale: add -> (38,9) (operands rescale
    # DOWN with HALF_UP), avg -> (38,10) (zero scale lift)
    df = make_df(session, {"a": DecimalGen(38, 10), "b": DecimalGen(38, 10),
                           "k": IntGen(lo=0, hi=3)}, seed=97)
    assert_tpu_cpu_equal_df(df.select(
        (col("a") + col("b")).alias("s"),
        (col("a") - col("b")).alias("d")))
    assert_tpu_cpu_equal_df(df.group_by(col("k")).agg(
        Average(col("a")).alias("av")))
    # exact check against python decimal for a known pair
    one = decimal.Decimal("1.0000000000")
    df2 = session.create_dataframe(
        {"a": [one], "b": [one]},
        [("a", dt.DecimalType(38, 10)), ("b", dt.DecimalType(38, 10))])
    out = df2.select((col("a") + col("b")).alias("s")).to_pydict()
    assert out["s"][0] == decimal.Decimal("2.000000000")


def test_wide_vs_float_null_safe_equal(session):
    from spark_rapids_tpu.expr.predicates import EqualNullSafe
    df = session.create_dataframe(
        {"a": [decimal.Decimal("2"), decimal.Decimal("3"), None],
         "f": [2.5, 3.0, 1.0]},
        [("a", dt.DecimalType(20, 0)), ("f", dt.FLOAT64)])
    out = df.select(EqualNullSafe(col("a"), col("f")).alias("e")).to_pydict()
    assert out["e"] == [False, True, False]
    assert_tpu_cpu_equal_df(df.select(
        EqualNullSafe(col("a"), col("f")).alias("e")))
