"""Standard Delta Lake format interchange (io/delta_format.py): log
replay, checkpoints, partition values from add actions, time travel,
and engine-written tables in the standard layout."""

import json
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.conf import SrtConf
from spark_rapids_tpu.io.delta_format import (DeltaFormatTable,
                                              schema_from_string,
                                              schema_to_string)
from spark_rapids_tpu.plan import TpuSession

SCHEMA_STRING = json.dumps({"type": "struct", "fields": [
    {"name": "k", "type": "string", "nullable": True, "metadata": {}},
    {"name": "v", "type": "long", "nullable": True, "metadata": {}},
    {"name": "d", "type": "decimal(10,2)", "nullable": True,
     "metadata": {}},
]})


@pytest.fixture(scope="module")
def session():
    return TpuSession(SrtConf({}))


def _commit(log_dir, version, actions):
    with open(os.path.join(log_dir, f"{version:020d}.json"), "w") as f:
        for a in actions:
            f.write(json.dumps(a) + "\n")


def _external_table(root):
    """Hand-built table in the standard layout (as Spark/delta-rs would
    write it): v0 = f1+f2, v1 = remove f2, add f3."""
    os.makedirs(os.path.join(root, "_delta_log"))
    pq.write_table(pa.table({"v": [1, 2]}), os.path.join(root, "f1.parquet"))
    pq.write_table(pa.table({"v": [3]}), os.path.join(root, "f2.parquet"))
    pq.write_table(pa.table({"v": [4, 5]}), os.path.join(root, "f3.parquet"))
    meta = {"metaData": {
        "id": "t1", "format": {"provider": "parquet", "options": {}},
        "schemaString": json.dumps({"type": "struct", "fields": [
            {"name": "k", "type": "string", "nullable": True,
             "metadata": {}},
            {"name": "v", "type": "long", "nullable": True,
             "metadata": {}}]}),
        "partitionColumns": ["k"], "configuration": {}}}
    _commit(os.path.join(root, "_delta_log"), 0, [
        {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
        meta,
        {"add": {"path": "f1.parquet", "partitionValues": {"k": "a"},
                 "size": 1, "modificationTime": 0, "dataChange": True}},
        {"add": {"path": "f2.parquet", "partitionValues": {"k": "b"},
                 "size": 1, "modificationTime": 0, "dataChange": True}},
    ])
    _commit(os.path.join(root, "_delta_log"), 1, [
        {"remove": {"path": "f2.parquet", "deletionTimestamp": 1,
                    "dataChange": True}},
        {"add": {"path": "f3.parquet", "partitionValues": {"k": "c"},
                 "size": 1, "modificationTime": 1, "dataChange": True}},
    ])
    return root


def test_schema_string_roundtrip():
    schema = schema_from_string(SCHEMA_STRING)
    assert schema == [("k", dt.STRING), ("v", dt.INT64),
                      ("d", dt.DecimalType(10, 2))]
    assert schema_from_string(schema_to_string(schema)) == schema


def test_read_external_table_with_partition_values(session, tmp_path):
    root = _external_table(str(tmp_path / "t"))
    df = session.read.delta(root)
    rows = sorted(df.collect(), key=lambda r: r["v"])
    assert [(r["k"], r["v"]) for r in rows] == \
        [("a", 1), ("a", 2), ("c", 4), ("c", 5)]


def test_time_travel(session, tmp_path):
    root = _external_table(str(tmp_path / "t"))
    v0 = session.read.delta(root, version_as_of=0)
    rows = sorted(v0.collect(), key=lambda r: r["v"])
    assert [(r["k"], r["v"]) for r in rows] == \
        [("a", 1), ("a", 2), ("b", 3)]
    t = DeltaFormatTable(root)
    assert t.version == 1 and t.partition_columns == ["k"]


def test_checkpoint_replay(session, tmp_path):
    root = _external_table(str(tmp_path / "t"))
    log_dir = os.path.join(root, "_delta_log")
    # checkpoint at v1 capturing the state; later v2 adds f2 back
    t = DeltaFormatTable(root)
    # plain pyarrow maps format.options to an empty struct which
    # parquet cannot encode (Spark writes it as map<string,string>);
    # the checkpoint metaData row simply omits it here
    ckpt_meta = {k: v for k, v in t.metadata.items()
                 if k not in ("format", "configuration")}
    rows = [{"metaData": ckpt_meta, "add": None}]
    for a in t.adds:
        rows.append({"metaData": None, "add": a})
    pq.write_table(pa.Table.from_pylist(rows),
                   os.path.join(log_dir, f"{1:020d}.checkpoint.parquet"))
    with open(os.path.join(log_dir, "_last_checkpoint"), "w") as f:
        json.dump({"version": 1, "size": len(rows)}, f)
    _commit(log_dir, 2, [
        {"add": {"path": "f2.parquet", "partitionValues": {"k": "b"},
                 "size": 1, "modificationTime": 2, "dataChange": True}}])
    df = session.read.delta(root)
    vs = sorted(r["v"] for r in df.collect())
    assert vs == [1, 2, 3, 4, 5]
    # time travel BEFORE the checkpoint still replays from json
    v0 = session.read.delta(root, version_as_of=0)
    assert sorted(r["v"] for r in v0.collect()) == [1, 2, 3]


def test_write_and_roundtrip(session, tmp_path):
    root = str(tmp_path / "w")
    df = session.create_dataframe({
        "k": ["x", "x", "y"], "v": [1, 2, 3]})
    version = df.write.partition_by("k").delta(root)
    assert version == 0
    # standard layout on disk
    assert os.path.exists(os.path.join(root, "_delta_log",
                                       f"{0:020d}.json"))
    back = session.read.delta(root)
    assert sorted((r["k"], r["v"]) for r in back.collect()) == \
        [("x", 1), ("x", 2), ("y", 3)]
    # append + overwrite modes
    df2 = session.create_dataframe({"k": ["z"], "v": [9]})
    assert df2.write.mode("append").partition_by("k").delta(root) == 1
    assert sorted(r["v"] for r in session.read.delta(root).collect()) \
        == [1, 2, 3, 9]
    assert df2.write.mode("overwrite").partition_by("k").delta(root) == 2
    assert [r["v"] for r in session.read.delta(root).collect()] == [9]
    # history preserved: version 1 still readable
    assert sorted(r["v"] for r in
                  session.read.delta(root, version_as_of=1).collect()) \
        == [1, 2, 3, 9]


def test_unsupported_reader_version(session, tmp_path):
    root = str(tmp_path / "t3")
    os.makedirs(os.path.join(root, "_delta_log"))
    _commit(os.path.join(root, "_delta_log"), 0, [
        {"protocol": {"minReaderVersion": 3, "minWriterVersion": 7}},
        {"metaData": {"id": "x", "schemaString": SCHEMA_STRING,
                      "partitionColumns": [],
                      "format": {"provider": "parquet", "options": {}},
                      "configuration": {}}}])
    from spark_rapids_tpu.io.delta_format import DeltaFormatError
    with pytest.raises(DeltaFormatError, match="minReaderVersion"):
        session.read.delta(root)
