"""Operator-fusion tests: planner chain matching, fused-vs-unfused
bit-identity (hand-built chains + NDS queries), OOM split-and-retry
re-entering the fused program, and compiled-program reuse through the
shared jit registry."""

import os

import numpy as np
import pytest

from spark_rapids_tpu.columnar.vector import (batch_from_pydict,
                                              batch_to_pydict)
from spark_rapids_tpu.conf import SrtConf
from spark_rapids_tpu.exec import (BatchScanExec, CoalesceBatchesExec,
                                   ExecContext, FilterExec,
                                   FusedPipelineExec, HashAggregateExec,
                                   ProjectExec)
from spark_rapids_tpu.exec.aggregate import FINAL, PARTIAL
from spark_rapids_tpu.expr import col, input_file_name, spark_partition_id
from spark_rapids_tpu.expr.aggregates import CountStar, Sum
from spark_rapids_tpu.memory.budget import reset_task_context
from spark_rapids_tpu.plan.overrides import _insert_fusion


def scan(data, capacity=None, nbatches=1):
    n = len(next(iter(data.values())))
    per = -(-n // nbatches)
    batches = []
    for i in range(0, n, per):
        chunk = {k: v[i:i + per] for k, v in data.items()}
        batches.append(batch_from_pydict(chunk, capacity=capacity))
    schema = batches[0].schema() if batches else []
    return BatchScanExec(batches, schema)


def collect(node):
    ctx = ExecContext()
    names = [n for n, _ in node.output_schema]
    rows = {n: [] for n in names}
    for batch in node.execute(ctx):
        d = batch_to_pydict(batch)
        for n in names:
            rows[n].extend(d[n])
    return rows


def _chain_data(n=200):
    rng = np.random.default_rng(11)
    return {"k": rng.integers(0, 8, n).tolist(),
            "v": rng.integers(-50, 50, n).tolist()}


def _chain(data, nbatches=4, coalesce=None):
    """scan [-> coalesce] -> filter -> project -> partial agg."""
    src = scan(data, nbatches=nbatches)
    if coalesce is not None:
        src = CoalesceBatchesExec(src, target_rows=coalesce)
    filt = FilterExec(src, col("v") > -20)
    proj = ProjectExec(filt, [col("k"), (col("v") * 2).alias("v2")])
    return HashAggregateExec(proj, [col("k")],
                             [(Sum(col("v2")), "s"), (CountStar(), "n")],
                             mode=PARTIAL)


def _totals(out):
    """Sum every packed partial-state column per group key (partial
    aggregate states — sums and counts — merge by addition)."""
    val_cols = [c for c in out if c != "k"]
    agg = {}
    for i, k in enumerate(out["k"]):
        vals = tuple(out[c][i] for c in val_cols)
        cur = agg.get(k)
        agg[k] = vals if cur is None else \
            tuple(a + b for a, b in zip(cur, vals))
    return agg


def _has_fused(node):
    if isinstance(node, FusedPipelineExec):
        return True
    return any(_has_fused(c) for c in getattr(node, "children", []))


# --------------------------------------------------------------------------
# planner matching rules
# --------------------------------------------------------------------------

def test_fuse_filter_project_partial_agg_chain():
    root = _insert_fusion(_chain(_chain_data()), SrtConf({}))
    assert isinstance(root, FusedPipelineExec)
    assert [type(s).__name__ for s in root.stages] == \
        ["FilterExec", "ProjectExec", "HashAggregateExec"]
    # fused node advertises the terminal's schema
    assert [n for n, _ in root.output_schema] == \
        [n for n, _ in root.stages[-1].output_schema]


def test_fusion_conf_disabled_leaves_plan_alone():
    tree = _chain(_chain_data())
    root = _insert_fusion(tree, SrtConf({"srt.exec.fusion.enabled":
                                         "false"}))
    assert root is tree and not _has_fused(root)


def test_context_sensitive_exprs_stay_unfused():
    data = _chain_data()
    # traced partition context in the filter condition
    t1 = FilterExec(ProjectExec(scan(data), [col("k"), col("v")]),
                    (col("v") + spark_partition_id()) > 0)
    assert not _has_fused(_insert_fusion(t1, SrtConf({})))
    # eager host-side expression in the projection
    t2 = ProjectExec(FilterExec(scan(data), col("v") > 0),
                     [col("k"), input_file_name().alias("f")])
    assert not _has_fused(_insert_fusion(t2, SrtConf({})))


def test_exclude_list_breaks_chain():
    conf = SrtConf({"srt.exec.fusion.excludeExecs": "FilterExec"})
    root = _insert_fusion(_chain(_chain_data()), conf)
    assert not _has_fused(root)
    # excluding only the aggregate still fuses the filter->project prefix
    conf2 = SrtConf({"srt.exec.fusion.excludeExecs": "HashAggregateExec"})
    root2 = _insert_fusion(_chain(_chain_data()), conf2)
    assert isinstance(root2, HashAggregateExec)
    assert isinstance(root2.children[0], FusedPipelineExec)
    assert [type(s).__name__ for s in root2.children[0].stages] == \
        ["FilterExec", "ProjectExec"]


def test_final_agg_terminal_not_fused():
    data = _chain_data()
    tree = HashAggregateExec(
        ProjectExec(FilterExec(scan(data), col("v") > 0),
                    [col("k"), col("v")]),
        [col("k")], [(Sum(col("v")), "s")], mode=FINAL)
    root = _insert_fusion(tree, SrtConf({}))
    # the FINAL agg is never a fused terminal; its filter->project
    # child prefix still fuses
    assert isinstance(root, HashAggregateExec) and root.mode == FINAL
    assert isinstance(root.children[0], FusedPipelineExec)


def test_noop_coalesce_seen_through_explicit_blocks():
    data = _chain_data()
    fused = _insert_fusion(_chain(data, coalesce=None), SrtConf({}))
    tree_noop = HashAggregateExec(
        ProjectExec(FilterExec(CoalesceBatchesExec(scan(data, nbatches=4)),
                               col("v") > -20),
                    [col("k"), (col("v") * 2).alias("v2")]),
        [col("k")], [(Sum(col("v2")), "s"), (CountStar(), "n")],
        mode=PARTIAL)
    root = _insert_fusion(tree_noop, SrtConf({}))
    assert isinstance(root, FusedPipelineExec)
    # the no-op coalesce stays in place as the fused node's input
    assert isinstance(root.children[0], CoalesceBatchesExec)
    # regression: see-through must not change results (int aggregates
    # so partial states compare exactly); the noop-coalesced lane
    # produces the same GROUPED TOTALS even though batch boundaries
    # (and so partial-output rows) differ
    assert _totals(collect(root)) == _totals(collect(fused))
    # an explicit repartitioning coalesce breaks the chain
    blocked = _insert_fusion(_chain(data, coalesce=64), SrtConf({}))
    assert not _has_fused(blocked)


# --------------------------------------------------------------------------
# fused-vs-unfused bit-identity
# --------------------------------------------------------------------------

def test_fused_bit_identical_to_unfused_chain():
    data = _chain_data(500)
    unfused = _chain(data, nbatches=5)
    fused = _insert_fusion(_chain(data, nbatches=5), SrtConf({}))
    assert isinstance(fused, FusedPipelineExec)
    assert collect(fused) == collect(unfused)


def test_fused_skips_batches_filtered_to_empty():
    # one batch filters down to zero rows: the unfused partial stream
    # emits no partial for it and the fused lane must match
    data = {"k": [1] * 10 + [2] * 10, "v": [-100] * 10 + [5] * 10}
    unfused = _chain(data, nbatches=2)
    fused = _insert_fusion(_chain(data, nbatches=2), SrtConf({}))
    assert collect(fused) == collect(unfused)


def _nds_bit_identity(tmp_path, scale_rows, qids):
    from spark_rapids_tpu.conf import SrtConf as C
    from spark_rapids_tpu.datagen import generate_table
    from spark_rapids_tpu.models.nds import NDS_QUERIES, nds_specs
    from spark_rapids_tpu.plan.session import TpuSession

    def run(fusion):
        session = TpuSession(C({
            "srt.shuffle.partitions": 2,
            "srt.exec.fusion.enabled": "true" if fusion else "false",
        }))
        data_dir = os.path.join(str(tmp_path), "nds")
        needed = {"store_sales", "date_dim", "item"}
        for spec in nds_specs(scale_rows):
            if spec.name not in needed:
                continue
            out = os.path.join(data_dir, spec.name)
            if not os.path.exists(out):
                generate_table(session, spec, out, chunk_rows=1 << 16)
            session.create_or_replace_temp_view(
                spec.name, session.read.parquet(out))
        return {q: session.sql(NDS_QUERIES[q]).collect() for q in qids}

    fused, unfused = run(True), run(False)
    for q in qids:
        assert fused[q] == unfused[q], f"{q} diverged under fusion"


def test_nds_fusion_bit_identical_quick(tmp_path):
    """Fast tier-1 leg of the differential: 3 star queries at a scale
    that keeps the test in seconds."""
    _nds_bit_identity(tmp_path, 4_000, ("q3", "q42", "q52"))


@pytest.mark.slow
def test_nds_fusion_bit_identical_100k(tmp_path):
    """The ISSUE's differential-proof scale: 100k store_sales rows,
    three NDS queries, fusion on == fusion off bit-identically."""
    _nds_bit_identity(tmp_path, 100_000, ("q3", "q42", "q52"))


# --------------------------------------------------------------------------
# OOM retry through the fused program
# --------------------------------------------------------------------------

def _arm_launch_oom(fused):
    """Make the fused node's first program launch raise
    SplitAndRetryOOM — the input batch is materialized (``sb.get()``)
    but its buffers have NOT been handed to (donated into) the
    program yet, which is exactly where real budget pressure raises.
    Subsequent launches (the split halves) run the real program."""
    from spark_rapids_tpu.memory.budget import SplitAndRetryOOM
    real_fn, armed = fused._fn, [True]

    def flaky(*a, **k):
        if armed[0]:
            armed[0] = False
            raise SplitAndRetryOOM("injected before fused launch")
        return real_fn(*a, **k)
    fused._fn = flaky


def test_fused_split_and_retry_reenters_program():
    """A SplitAndRetryOOM on the first fused launch must split the
    batch and re-enter the fused program on each half, losing no rows
    and changing none."""
    data = _chain_data(400)
    # non-agg chain: filter -> project, so row payloads compare 1:1
    tree = ProjectExec(FilterExec(scan(data, nbatches=1), col("v") > -20),
                       [col("k"), (col("v") * 2).alias("v2")])
    fused = _insert_fusion(tree, SrtConf({}))
    assert isinstance(fused, FusedPipelineExec)
    expected = collect(ProjectExec(
        FilterExec(scan(data, nbatches=1), col("v") > -20),
        [col("k"), (col("v") * 2).alias("v2")]))

    ctx = reset_task_context()
    _arm_launch_oom(fused)
    try:
        got = collect(fused)
    finally:
        reset_task_context()
    assert got == expected
    assert ctx.split_count == 1


def test_fused_agg_split_and_retry():
    """Same injection against an aggregate-terminated chain: the split
    halves each run the fused update pass and the grouped totals
    across all emitted partials are unchanged."""
    data = _chain_data(300)
    fused = _insert_fusion(_chain(data, nbatches=1), SrtConf({}))
    baseline = collect(_chain(data, nbatches=1))

    ctx = reset_task_context()
    _arm_launch_oom(fused)
    try:
        got = collect(fused)
    finally:
        reset_task_context()
    assert _totals(got) == _totals(baseline)
    assert ctx.split_count == 1


# --------------------------------------------------------------------------
# compiled-program reuse
# --------------------------------------------------------------------------

def test_fused_program_shared_across_identical_chains():
    """Two structurally identical chains (= two partitions / two
    queries with the same shape) must share ONE registered fused
    program: the second construction is a registry hit."""
    from spark_rapids_tpu import jit_registry
    data = _chain_data()

    def mk():
        # a chain shape unique to THIS test (output name "v3"), so the
        # first build is a genuine registry miss even when other tests
        # in the session already registered the _chain shape
        proj = ProjectExec(FilterExec(scan(data, nbatches=2),
                                      col("v") > -20),
                           [col("k"), (col("v") * 3).alias("v3")])
        return HashAggregateExec(proj, [col("k")],
                                 [(Sum(col("v3")), "s"),
                                  (CountStar(), "n")], mode=PARTIAL)

    before = jit_registry.stats(module="spark_rapids_tpu.exec.fused")
    f1 = _insert_fusion(mk(), SrtConf({}))
    mid = jit_registry.stats(module="spark_rapids_tpu.exec.fused")
    f2 = _insert_fusion(mk(), SrtConf({}))
    after = jit_registry.stats(module="spark_rapids_tpu.exec.fused")
    assert isinstance(f1, FusedPipelineExec)
    assert isinstance(f2, FusedPipelineExec)
    # first build mints (miss), second reuses (hit, no new entry)
    assert mid["misses"] == before["misses"] + 1
    assert after["hits"] == mid["hits"] + 1
    assert after["misses"] == mid["misses"]
    assert after["entries"] == mid["entries"]
    # and both nodes produce identical output through the shared program
    assert collect(f1) == collect(f2)


# --------------------------------------------------------------------------
# fusion v2: hash-join fusion, FINAL-merge fusion, adaptive composition
# --------------------------------------------------------------------------

def _join_data(n=300, nkeys=20, seed=7):
    rng = np.random.default_rng(seed)
    fact = {"k": rng.integers(0, nkeys, n).tolist(),
            "v": rng.integers(-50, 50, n).tolist()}
    dim = {"dk": list(range(nkeys)),
           "w": rng.integers(1, 5, nkeys).tolist()}
    return fact, dim


def _join_chain(fact, dim, agg=False, nbatches=3):
    """fact ⋈ dim -> filter -> project [-> partial agg]."""
    from spark_rapids_tpu.exec import ShuffledHashJoinExec
    j = ShuffledHashJoinExec(scan(fact, nbatches=nbatches), scan(dim),
                             [col("k")], [col("dk")])
    f = FilterExec(j, col("v") > -20)
    p = ProjectExec(f, [col("k"), (col("v") * col("w")).alias("vw")])
    if not agg:
        return p
    return HashAggregateExec(p, [col("k")],
                             [(Sum(col("vw")), "s"), (CountStar(), "n")],
                             mode=PARTIAL)


def test_fuse_join_suffix_chain():
    from spark_rapids_tpu.exec import FusedHashJoinExec
    fact, dim = _join_data()
    root = _insert_fusion(_join_chain(fact, dim), SrtConf({}))
    assert isinstance(root, FusedHashJoinExec)
    assert root.join._fusion is root
    assert [type(s).__name__ for s in root.suffix] == \
        ["FilterExec", "ProjectExec"]
    assert [n for n, _ in root.output_schema] == ["k", "vw"]
    # conf opt-out leaves the join alone
    off = _insert_fusion(
        _join_chain(fact, dim),
        SrtConf({"srt.exec.fusion.joins": "false"}))
    assert not isinstance(off, FusedHashJoinExec)


def test_fused_join_bit_identical_to_unfused():
    from spark_rapids_tpu.exec import FusedHashJoinExec
    fact, dim = _join_data(400)
    unfused = collect(_join_chain(fact, dim))
    fused = _insert_fusion(_join_chain(fact, dim), SrtConf({}))
    assert isinstance(fused, FusedHashJoinExec)
    assert collect(fused) == unfused


def test_fused_join_agg_bit_identical():
    from spark_rapids_tpu.exec import FusedHashJoinExec
    fact, dim = _join_data(400)
    baseline = collect(_join_chain(fact, dim, agg=True))
    fused = _insert_fusion(_join_chain(fact, dim, agg=True), SrtConf({}))
    assert isinstance(fused, FusedHashJoinExec)
    assert _totals(collect(fused)) == _totals(baseline)


def test_fused_join_split_and_retry_reenters():
    """SplitAndRetryOOM on the first fused join launch must split the
    probe batch and re-enter the fused program on each half."""
    from spark_rapids_tpu.exec import FusedHashJoinExec
    from spark_rapids_tpu.memory.budget import SplitAndRetryOOM
    fact, dim = _join_data(400)
    expected = collect(_join_chain(fact, dim, nbatches=1))
    fused = _insert_fusion(_join_chain(fact, dim, nbatches=1),
                           SrtConf({}))
    assert isinstance(fused, FusedHashJoinExec)
    real, armed = fused._run_pair, [True]

    def flaky(*a, **k):
        if armed[0]:
            armed[0] = False
            raise SplitAndRetryOOM("injected before fused join launch")
        return real(*a, **k)
    fused._run_pair = flaky

    ctx = reset_task_context()
    try:
        got = collect(fused)
    finally:
        reset_task_context()
    assert got == expected
    assert ctx.split_count == 1


def _session(extra=None):
    from spark_rapids_tpu.plan.session import TpuSession
    base = {"srt.shuffle.partitions": 4}
    base.update(extra or {})
    return TpuSession(SrtConf(base))


def test_final_merge_fusion_bit_identical():
    """Session-level join + FINAL aggregate + sort: fusion on (joins,
    final-merge and sort-prefix programs all armed) must match fusion
    off exactly, and the FINAL agg must actually be armed."""
    from spark_rapids_tpu.expr.core import Alias
    from spark_rapids_tpu.plan import overrides

    def q(sess):
        rng = np.random.default_rng(5)
        n = 4000
        fact = sess.create_dataframe({
            "k": rng.integers(0, 30, n).tolist(),
            "v": rng.integers(-100, 100, n).tolist()})
        dim = sess.create_dataframe({
            "dk": list(range(30)), "grp": [i % 7 for i in range(30)]})
        return fact.join(dim, ([col("k")], [col("dk")]), how="inner") \
            .filter(col("v") > -50) \
            .group_by("grp").agg(Alias(Sum(col("v")), "sv"),
                                 Alias(CountStar(), "c")) \
            .sort("grp")

    s_on = _session()
    df_on = q(s_on)
    phys = overrides.apply_overrides(df_on.plan, s_on.conf)

    def armed_final(n):
        if isinstance(n, HashAggregateExec) and n.mode == FINAL \
                and n._merge_fusion is not None:
            return True
        kids = getattr(n, "children", [])
        return any(armed_final(c) for c in kids)
    assert armed_final(phys)
    on = df_on.collect()
    off = q(_session({"srt.exec.fusion.enabled": "false"})).collect()
    assert on == off


def test_adaptive_broadcast_demote_fusion_identical():
    """Adaptive broadcast demotion must still fire under join fusion
    (the decision re-evaluates at execute time, after the fused
    wrapper armed the join) and results must match fusion off."""
    from spark_rapids_tpu.exec.base import ExecContext
    from spark_rapids_tpu.plan import overrides

    def q(sess):
        rng = np.random.default_rng(9)
        fact = sess.create_dataframe({
            "k": rng.integers(0, 30, 1500).tolist(),
            "v": rng.integers(-50, 50, 1500).tolist()})
        dim = sess.create_dataframe({
            "dk": list(range(30)),
            "w": [i * 3 for i in range(30)]})
        return fact.join(dim, ([col("k")], [col("dk")]), how="inner") \
            .filter(col("v") > -40)

    def run(extra):
        sess = _session({"srt.sql.broadcastRowThreshold": 1,
                         "srt.sql.adaptive.autoBroadcastJoinRows": "1000",
                         **extra})
        df = q(sess)
        phys = overrides.apply_overrides(df.plan, sess.conf)
        ctx = ExecContext(sess.conf)
        rows = []
        for b in phys.execute(ctx):
            d = batch_to_pydict(b)
            rows.extend(sorted(zip(*(d[c] for c in sorted(d)))))
        merged = {}
        for em in ctx.metrics.values():
            for name, metric in em.items():
                merged[name] = merged.get(name, 0) + metric.value
        return sorted(rows), merged

    on_rows, on_m = run({})
    off_rows, off_m = run({"srt.exec.fusion.enabled": "false"})
    assert on_m.get("adaptiveBroadcastJoins", 0) == 1, on_m
    assert off_m.get("adaptiveBroadcastJoins", 0) == 1, off_m
    assert on_rows == off_rows


def test_adaptive_skew_split_fusion_identical():
    """Skew splits must still fire under join fusion and produce
    bit-identical rows to the fusion-off run."""
    from spark_rapids_tpu.exec.base import ExecContext
    from spark_rapids_tpu.plan import overrides

    def q(sess):
        rng = np.random.default_rng(3)
        keys = np.where(rng.random(6000) < 0.9, 7,
                        rng.integers(0, 40, 6000))
        fact = sess.create_dataframe({
            "k": keys.tolist(),
            "v": rng.integers(-50, 50, 6000).tolist()})
        dim = sess.create_dataframe({
            "dk": list(range(40)),
            "w": [i * 2 for i in range(40)]})
        return fact.join(dim, ([col("k")], [col("dk")]), how="inner") \
            .filter(col("v") > -40)

    def run(extra):
        sess = _session({
            "srt.shuffle.partitions": 8,
            "srt.sql.broadcastRowThreshold": 1,
            "srt.sql.adaptive.skewJoin.partitionRows": 500,
            "srt.sql.adaptive.coalescePartitions.minPartitionRows": 1,
            **extra})
        df = q(sess)
        phys = overrides.apply_overrides(df.plan, sess.conf)
        ctx = ExecContext(sess.conf)
        rows = []
        for b in phys.execute(ctx):
            d = batch_to_pydict(b)
            rows.extend(sorted(zip(*(d[c] for c in sorted(d)))))
        merged = {}
        for em in ctx.metrics.values():
            for name, metric in em.items():
                merged[name] = merged.get(name, 0) + metric.value
        return sorted(rows), merged

    on_rows, on_m = run({})
    off_rows, off_m = run({"srt.exec.fusion.enabled": "false"})
    assert on_m.get("skewedJoinPartitions", 0) >= 1, on_m
    assert off_m.get("skewedJoinPartitions", 0) >= 1, off_m
    assert on_rows == off_rows
