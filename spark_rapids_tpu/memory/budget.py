"""HBM budget accounting and the OOM exception contract.

The reference hooks RMM's allocation-failure callback
(DeviceMemoryEventHandler.scala:36) and drives a per-thread retry state
machine from native code (RmmSpark; RmmRapidsRetryIterator.scala:27).
XLA's allocator is not user-hookable the same way (SURVEY §7 hard-part
#3), so the TPU design inverts the control flow: batches are *accounted*
against a logical HBM budget at registration time, and crossing the
budget raises ``RetryOOM``/``SplitAndRetryOOM`` **before** the device
allocator would fail. The spill catalog (spill.py) frees accounted bytes
by moving cold batches to host/disk, exactly like the reference's
device→host→disk store chain.

OOM *injection* for tests lives here too: the analogue of
``RmmSpark.forceRetryOOM`` (RmmSparkRetrySuiteBase.scala:48) — tests arm
a countdown and the Nth allocation attempt throws.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..robustness.faults import fault_point


class OutOfDeviceMemory(RuntimeError):
    """Base for device-memory pressure errors (GpuOOM in the JNI)."""


class RetryOOM(OutOfDeviceMemory):
    """Roll back to the last checkpoint and try again at the same size."""


class SplitAndRetryOOM(OutOfDeviceMemory):
    """Roll back, split the input, retry the halves (SplitAndRetryOOM)."""


class TaskContext:
    """Per-task bookkeeping (thread association + retry counters).

    The reference associates JVM threads with Spark task ids inside
    RmmSpark so the native state machine knows which task to interrupt;
    here the context is a thread-local carrying injection state and
    metrics.
    """

    def __init__(self, task_id: int):
        self.task_id = task_id
        self.retry_count = 0
        self.split_count = 0
        self.spilled_bytes = 0
        self.alloc_attempts = 0
        # GpuTaskMetrics.scala:81-146 accumulators
        self.semaphore_wait_ns = 0
        self.spill_time_ns = 0
        self.retry_compute_ns = 0
        # test-only injection counters (None = disarmed)
        self._inject_retry_after: Optional[int] = None
        self._inject_split_after: Optional[int] = None

    def metrics(self) -> dict:
        """Snapshot (surfaced per task, like GpuTaskMetrics in the UI)."""
        return {"retryCount": self.retry_count,
                "splitAndRetryCount": self.split_count,
                "spilledBytes": self.spilled_bytes,
                "semaphoreWaitTimeNs": self.semaphore_wait_ns,
                "spillTimeNs": self.spill_time_ns,
                "retryComputationTimeNs": self.retry_compute_ns}

    # --- fault injection (RmmSpark.forceRetryOOM analogue) ---
    def force_retry_oom(self, num_allocs_before: int = 0) -> None:
        self._inject_retry_after = num_allocs_before

    def force_split_and_retry_oom(self, num_allocs_before: int = 0) -> None:
        self._inject_split_after = num_allocs_before

    def on_alloc_attempt(self) -> None:
        self.alloc_attempts += 1
        if self._inject_retry_after is not None:
            if self._inject_retry_after == 0:
                self._inject_retry_after = None
                raise RetryOOM("injected RetryOOM")
            self._inject_retry_after -= 1
        if self._inject_split_after is not None:
            if self._inject_split_after == 0:
                self._inject_split_after = None
                raise SplitAndRetryOOM("injected SplitAndRetryOOM")
            self._inject_split_after -= 1


_TL = threading.local()


def task_context() -> TaskContext:
    ctx = getattr(_TL, "ctx", None)
    if ctx is None:
        ctx = TaskContext(task_id=threading.get_ident())
        _TL.ctx = ctx
    return ctx


def reset_task_context() -> TaskContext:
    _TL.ctx = TaskContext(task_id=threading.get_ident())
    return _TL.ctx


#: sentinel: "resolve the owner from the thread's current query" —
#: distinct from None, which means an explicitly untagged reservation
_RESOLVE_OWNER = object()


class _QuerySlice:
    """Per-query partition of the device budget: equal ``share`` of
    the pool, plus whatever idle-slot capacity the query borrows."""

    __slots__ = ("query_id", "share", "used")

    def __init__(self, query_id: str, share: int):
        self.query_id = query_id
        self.share = share
        self.used = 0


class MemoryBudget:
    """Logical byte budget over device HBM.

    ``reserve`` is called before building device arrays for a batch;
    if the budget would overflow it first asks the spill catalog to
    release bytes (synchronousSpill, RapidsBufferCatalog.scala:589) and
    only then raises RetryOOM. Thread-safe; shared across tasks like a
    single device pool.

    Multi-tenant isolation (ROADMAP item 1): while queries are
    registered (``register_query``), the pool is carved into
    ``slots`` equal slices — the admission semaphore's permit count —
    and a query's reservations are checked against its own slice.
    Capacity not claimed by a registered query (empty slots + the
    integer-division remainder) forms an idle pool a query may borrow
    from; it may never eat into another *registered* query's share.
    Spill pressure is scoped the same way: ``reserve`` hands the
    requesting query's id and the live-owner set to the spill
    callback, which then refuses to evict batches belonging to other
    live queries. With no queries registered (single-query sessions,
    unit tests, worker processes) every check degrades to the plain
    global budget — bit-identical to the pre-partition behavior.
    """

    def __init__(self, limit_bytes: int):
        self.limit = limit_bytes
        self.used = 0
        self._lock = threading.Lock()
        self._spill_fn = None  # wired by the spill catalog
        self._slices: dict = {}  # query_id -> _QuerySlice
        self._nslots = 1

    def set_spill_callback(self, fn) -> None:
        self._spill_fn = fn

    # --- per-query slices -------------------------------------------------
    def register_query(self, query_id: str,
                       slots: Optional[int] = None) -> None:
        """Claim a budget slice for an admitted query. ``slots`` is the
        admission concurrency (slice count); sticky across calls so
        per-call callers only pass it once per process lifetime."""
        with self._lock:
            if slots is not None:
                self._nslots = max(int(slots), 1)
            share = self.limit // self._nslots
            self._slices[query_id] = _QuerySlice(query_id, share)

    def unregister_query(self, query_id: str) -> None:
        """Release a finished query's slice. Bytes it still holds
        (e.g. shuffle map outputs pending fetch) stay accounted
        globally and become fair spill victims for everyone."""
        with self._lock:
            self._slices.pop(query_id, None)

    def active_owners(self) -> set:
        with self._lock:
            return set(self._slices)

    def query_used(self, query_id: str) -> int:
        with self._lock:
            sl = self._slices.get(query_id)
            return sl.used if sl is not None else 0

    def _slice_cap_locked(self, sl: "_QuerySlice") -> int:
        """Effective byte cap for one slice: its own share plus the
        idle pool (capacity not reserved to any live query), minus
        what other queries already borrowed from that pool."""
        idle_pool = self.limit - sum(
            s.share for s in self._slices.values())
        borrowed_others = sum(
            max(0, s.used - s.share)
            for s in self._slices.values() if s is not sl)
        return sl.share + max(0, idle_pool - borrowed_others)

    def _try_reserve_locked(self, nbytes: int, owner) -> int:
        """Commit the reservation if it fits; else return the byte
        deficit the spill pass must free (>= 1)."""
        sl = self._slices.get(owner) if owner else None
        if self.used + nbytes > self.limit:
            deficit = self.used + nbytes - self.limit
        elif sl is not None and len(self._slices) > 1:
            cap = self._slice_cap_locked(sl)
            deficit = max(0, sl.used + nbytes - cap)
        else:
            # unpartitioned, untagged, or sole tenant: whole pool
            deficit = 0
        if deficit:
            return deficit
        self.used += nbytes
        if sl is not None:
            sl.used += nbytes
        return 0

    def reserve(self, nbytes: int, owner=_RESOLVE_OWNER) -> None:
        task_context().on_alloc_attempt()
        # seeded fault-site: forced RetryOOM/SplitAndRetryOOM at
        # operator granularity (detail defaults to the armed op_scope)
        fault_point("memory.reserve")
        if owner is _RESOLVE_OWNER:
            # un-plumbed call sites charge the thread's current query;
            # spill.py passes the batch's recorded owner explicitly so
            # reserve/release pair up on the same slice regardless of
            # which thread re-materializes
            from ..robustness.admission import current_query
            q = current_query()
            owner = q.query_id if q is not None else None
        with self._lock:
            needed = self._try_reserve_locked(nbytes, owner)
            if not needed:
                return
        # Out of budget: spill-then-recheck in a loop (outside the lock —
        # spilling calls back into release()). A single spill pass can
        # free less than asked — other tasks reserve concurrently, and
        # the catalog frees whole batches — so keep asking until the
        # reservation fits or the catalog frees nothing more. The
        # requester's identity scopes victim selection: other live
        # queries' batches are off the table.
        while self._spill_fn is not None:
            try:
                freed = self._spill_fn(needed, owner,
                                       self.active_owners())
            except TypeError:
                freed = self._spill_fn(needed)  # legacy 1-arg callback
            with self._lock:
                needed = self._try_reserve_locked(nbytes, owner)
                if not needed:
                    return
            if freed <= 0:
                break
        with self._lock:
            sl = self._slices.get(owner) if owner else None
            slice_info = (f" slice[{owner}]={sl.used}/"
                          f"{self._slice_cap_locked(sl)}"
                          if sl is not None else "")
        raise RetryOOM(
            f"device budget exhausted: used={self.used} request={nbytes} "
            f"limit={self.limit}{slice_info}")

    def release(self, nbytes: int, owner: Optional[str] = None) -> None:
        with self._lock:
            self.used = max(0, self.used - nbytes)
            if owner:
                sl = self._slices.get(owner)
                if sl is not None:
                    sl.used = max(0, sl.used - nbytes)


_DEVICE_BUDGET: Optional[MemoryBudget] = None
_BUDGET_LOCK = threading.Lock()


def device_budget() -> MemoryBudget:
    """Process-wide device budget, sized from config on first use
    (GpuDeviceManager.initializeRmm analogue)."""
    global _DEVICE_BUDGET
    with _BUDGET_LOCK:
        if _DEVICE_BUDGET is None:
            from ..conf import (DEVICE_MEMORY_FRACTION, DEVICE_MEMORY_LIMIT,
                                active_conf)
            conf = active_conf()
            limit = conf.get(DEVICE_MEMORY_LIMIT)
            if limit <= 0:
                import jax
                dev = jax.devices()[0]
                stats = {}
                try:
                    stats = dev.memory_stats() or {}
                except Exception:
                    pass
                hbm = stats.get("bytes_limit", 16 << 30)
                limit = int(hbm * conf.get(DEVICE_MEMORY_FRACTION))
            _DEVICE_BUDGET = MemoryBudget(limit)
        return _DEVICE_BUDGET


def reset_device_budget(limit_bytes: Optional[int] = None) -> MemoryBudget:
    """Test hook: replace the global budget."""
    global _DEVICE_BUDGET
    with _BUDGET_LOCK:
        if limit_bytes is None:
            _DEVICE_BUDGET = None
            return None  # re-derived lazily
        _DEVICE_BUDGET = MemoryBudget(limit_bytes)
        return _DEVICE_BUDGET
