"""HBM budget accounting and the OOM exception contract.

The reference hooks RMM's allocation-failure callback
(DeviceMemoryEventHandler.scala:36) and drives a per-thread retry state
machine from native code (RmmSpark; RmmRapidsRetryIterator.scala:27).
XLA's allocator is not user-hookable the same way (SURVEY §7 hard-part
#3), so the TPU design inverts the control flow: batches are *accounted*
against a logical HBM budget at registration time, and crossing the
budget raises ``RetryOOM``/``SplitAndRetryOOM`` **before** the device
allocator would fail. The spill catalog (spill.py) frees accounted bytes
by moving cold batches to host/disk, exactly like the reference's
device→host→disk store chain.

OOM *injection* for tests lives here too: the analogue of
``RmmSpark.forceRetryOOM`` (RmmSparkRetrySuiteBase.scala:48) — tests arm
a countdown and the Nth allocation attempt throws.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..robustness.faults import fault_point


class OutOfDeviceMemory(RuntimeError):
    """Base for device-memory pressure errors (GpuOOM in the JNI)."""


class RetryOOM(OutOfDeviceMemory):
    """Roll back to the last checkpoint and try again at the same size."""


class SplitAndRetryOOM(OutOfDeviceMemory):
    """Roll back, split the input, retry the halves (SplitAndRetryOOM)."""


class TaskContext:
    """Per-task bookkeeping (thread association + retry counters).

    The reference associates JVM threads with Spark task ids inside
    RmmSpark so the native state machine knows which task to interrupt;
    here the context is a thread-local carrying injection state and
    metrics.
    """

    def __init__(self, task_id: int):
        self.task_id = task_id
        self.retry_count = 0
        self.split_count = 0
        self.spilled_bytes = 0
        self.alloc_attempts = 0
        # GpuTaskMetrics.scala:81-146 accumulators
        self.semaphore_wait_ns = 0
        self.spill_time_ns = 0
        self.retry_compute_ns = 0
        # test-only injection counters (None = disarmed)
        self._inject_retry_after: Optional[int] = None
        self._inject_split_after: Optional[int] = None

    def metrics(self) -> dict:
        """Snapshot (surfaced per task, like GpuTaskMetrics in the UI)."""
        return {"retryCount": self.retry_count,
                "splitAndRetryCount": self.split_count,
                "spilledBytes": self.spilled_bytes,
                "semaphoreWaitTimeNs": self.semaphore_wait_ns,
                "spillTimeNs": self.spill_time_ns,
                "retryComputationTimeNs": self.retry_compute_ns}

    # --- fault injection (RmmSpark.forceRetryOOM analogue) ---
    def force_retry_oom(self, num_allocs_before: int = 0) -> None:
        self._inject_retry_after = num_allocs_before

    def force_split_and_retry_oom(self, num_allocs_before: int = 0) -> None:
        self._inject_split_after = num_allocs_before

    def on_alloc_attempt(self) -> None:
        self.alloc_attempts += 1
        if self._inject_retry_after is not None:
            if self._inject_retry_after == 0:
                self._inject_retry_after = None
                raise RetryOOM("injected RetryOOM")
            self._inject_retry_after -= 1
        if self._inject_split_after is not None:
            if self._inject_split_after == 0:
                self._inject_split_after = None
                raise SplitAndRetryOOM("injected SplitAndRetryOOM")
            self._inject_split_after -= 1


_TL = threading.local()


def task_context() -> TaskContext:
    ctx = getattr(_TL, "ctx", None)
    if ctx is None:
        ctx = TaskContext(task_id=threading.get_ident())
        _TL.ctx = ctx
    return ctx


def reset_task_context() -> TaskContext:
    _TL.ctx = TaskContext(task_id=threading.get_ident())
    return _TL.ctx


class MemoryBudget:
    """Logical byte budget over device HBM.

    ``reserve`` is called before building device arrays for a batch;
    if the budget would overflow it first asks the spill catalog to
    release bytes (synchronousSpill, RapidsBufferCatalog.scala:589) and
    only then raises RetryOOM. Thread-safe; shared across tasks like a
    single device pool.
    """

    def __init__(self, limit_bytes: int):
        self.limit = limit_bytes
        self.used = 0
        self._lock = threading.Lock()
        self._spill_fn = None  # wired by the spill catalog

    def set_spill_callback(self, fn) -> None:
        self._spill_fn = fn

    def reserve(self, nbytes: int) -> None:
        task_context().on_alloc_attempt()
        # seeded fault-site: forced RetryOOM/SplitAndRetryOOM at
        # operator granularity (detail defaults to the armed op_scope)
        fault_point("memory.reserve")
        with self._lock:
            if self.used + nbytes <= self.limit:
                self.used += nbytes
                return
            needed = self.used + nbytes - self.limit
        # Out of budget: spill-then-recheck in a loop (outside the lock —
        # spilling calls back into release()). A single spill pass can
        # free less than asked — other tasks reserve concurrently, and
        # the catalog frees whole batches — so keep asking until the
        # reservation fits or the catalog frees nothing more.
        while self._spill_fn is not None:
            freed = self._spill_fn(needed)
            with self._lock:
                if self.used + nbytes <= self.limit:
                    self.used += nbytes
                    return
                needed = self.used + nbytes - self.limit
            if freed <= 0:
                break
        raise RetryOOM(
            f"device budget exhausted: used={self.used} request={nbytes} "
            f"limit={self.limit}")

    def release(self, nbytes: int) -> None:
        with self._lock:
            self.used = max(0, self.used - nbytes)


_DEVICE_BUDGET: Optional[MemoryBudget] = None
_BUDGET_LOCK = threading.Lock()


def device_budget() -> MemoryBudget:
    """Process-wide device budget, sized from config on first use
    (GpuDeviceManager.initializeRmm analogue)."""
    global _DEVICE_BUDGET
    with _BUDGET_LOCK:
        if _DEVICE_BUDGET is None:
            from ..conf import (DEVICE_MEMORY_FRACTION, DEVICE_MEMORY_LIMIT,
                                active_conf)
            conf = active_conf()
            limit = conf.get(DEVICE_MEMORY_LIMIT)
            if limit <= 0:
                import jax
                dev = jax.devices()[0]
                stats = {}
                try:
                    stats = dev.memory_stats() or {}
                except Exception:
                    pass
                hbm = stats.get("bytes_limit", 16 << 30)
                limit = int(hbm * conf.get(DEVICE_MEMORY_FRACTION))
            _DEVICE_BUDGET = MemoryBudget(limit)
        return _DEVICE_BUDGET


def reset_device_budget(limit_bytes: Optional[int] = None) -> MemoryBudget:
    """Test hook: replace the global budget."""
    global _DEVICE_BUDGET
    with _BUDGET_LOCK:
        if limit_bytes is None:
            _DEVICE_BUDGET = None
            return None  # re-derived lazily
        _DEVICE_BUDGET = MemoryBudget(limit_bytes)
        return _DEVICE_BUDGET
