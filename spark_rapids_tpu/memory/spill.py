"""Tiered spill: device (HBM) → host (native pool) → disk.

Rebuild of the reference's spill framework (SURVEY §2.3):
RapidsBufferCatalog.scala (handle-based registry, synchronousSpill:589,
acquire:461), RapidsDeviceMemoryStore / RapidsHostMemoryStore /
RapidsDiskStore, SpillableColumnarBatch.scala, SpillPriorities.scala.

TPU mapping: a "device buffer" is the set of jax.Arrays inside a
ColumnarBatch; spilling to host copies the leaves into slabs of the
native C++ HostMemoryPool (native/tputable.cpp — the pinned-host-pool
role of RapidsHostMemoryStore), so host spill space is a real bounded
allocation: pool exhaustion cascades older host entries to disk, and if
space still cannot be found the entry bypasses the pool (plain numpy)
under the same byte-limit accounting. Disk tier is an .npz file.
Re-materialization is ``jnp.asarray`` back into HBM. All device bytes
are accounted against the shared MemoryBudget so spilling actually
relieves device pressure.
"""

from __future__ import annotations

import atexit
import os
import re
import shutil
import tempfile
import threading
from enum import IntEnum
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..columnar.vector import ColumnarBatch
from ..obs import events as _events
from ..robustness import faults as _faults
from ..robustness.integrity import DataCorruption, array_checksum
from .budget import MemoryBudget, device_budget


class SpillPriority(IntEnum):
    """Lower spills first (SpillPriorities.scala ordering)."""

    SHUFFLE_OUTPUT = 0       # regeneratable / long-lived, cold
    CACHED = 10
    ACTIVE_ON_DECK = 50      # input batches queued behind an operator
    ACTIVE_WORKING = 100     # spills last


def batch_nbytes(batch: ColumnarBatch) -> int:
    leaves = jax.tree_util.tree_leaves(batch)
    return sum(x.size * x.dtype.itemsize for x in leaves
               if hasattr(x, "dtype"))


def _tree_to_host(batch: ColumnarBatch):
    leaves, treedef = jax.tree_util.tree_flatten(batch)
    host = [np.asarray(x) if hasattr(x, "dtype") else x for x in leaves]
    return host, treedef


class _PooledLeaves:
    """Array leaves packed into one native-pool slab."""

    __slots__ = ("pool", "ptr", "total", "metas", "scalars", "nleaves")

    def __init__(self, pool, ptr: int, total: int, metas, scalars,
                 nleaves: int):
        self.pool = pool
        self.ptr = ptr
        self.total = total
        self.metas = metas      # [(leaf_idx, offset, shape, dtype)]
        self.scalars = scalars  # {leaf_idx: value}
        self.nleaves = nleaves

    @classmethod
    def pack(cls, pool, host_leaves) -> Optional["_PooledLeaves"]:
        import ctypes
        arrays = [(i, x) for i, x in enumerate(host_leaves)
                  if isinstance(x, np.ndarray)]
        scalars = {i: x for i, x in enumerate(host_leaves)
                   if not isinstance(x, np.ndarray)}
        total = sum(int(a.nbytes) for _, a in arrays)
        ptr = pool.alloc(max(total, 1))
        if ptr is None:
            return None
        buf = (ctypes.c_char * max(total, 1)).from_address(ptr)
        metas = []
        off = 0
        for i, a in arrays:
            n = int(a.nbytes)
            if n:
                view = np.frombuffer(buf, dtype=np.uint8, count=n,
                                     offset=off)
                view[:] = np.ascontiguousarray(a).view(np.uint8).ravel()
            metas.append((i, off, a.shape, a.dtype))
            off += n
        return cls(pool, ptr, total, metas, scalars, len(host_leaves))

    def unpack(self):
        import ctypes
        buf = (ctypes.c_char * max(self.total, 1)).from_address(self.ptr)
        leaves = [None] * self.nleaves
        for i, v in self.scalars.items():
            leaves[i] = v
        for i, off, shape, dtype in self.metas:
            count = int(np.prod(shape)) if shape else 1
            nbytes = count * dtype.itemsize
            if nbytes:
                # COPY out of the pooled slab: jax.device_put on the CPU
                # backend can be zero-copy, so a view here would alias
                # pool memory that free() hands to the NEXT spill —
                # silent corruption of any batch still referencing it
                arr = np.frombuffer(buf, dtype=dtype, count=count,
                                    offset=off).reshape(shape).copy()
            else:
                arr = np.zeros(shape, dtype)
            leaves[i] = arr
        return leaves

    def free(self) -> None:
        if self.ptr:
            self.pool.free(self.ptr)
            self.ptr = 0


def _tree_to_device(host_leaves, treedef) -> ColumnarBatch:
    dev = [jnp.asarray(x) if isinstance(x, np.ndarray) else x
           for x in host_leaves]
    return jax.tree_util.tree_unflatten(treedef, dev)


class SpillableBatch:
    """A columnar batch registered for spill (SpillableColumnarBatch).

    States: DEVICE (accounted against the HBM budget), HOST (numpy),
    DISK (.npz file). ``get()`` re-materializes on device;
    ``close()`` releases whatever tier holds it.
    """

    __slots__ = ("_batch", "_host", "_pooled", "_treedef", "_path",
                 "_nbytes", "priority", "_lock", "_catalog", "handle",
                 "closed", "_scalars", "_nleaves", "_num_rows",
                 "creation_stack", "_slab", "_crcs", "owner")

    def __init__(self, batch: ColumnarBatch,
                 priority: SpillPriority = SpillPriority.ACTIVE_ON_DECK,
                 catalog: Optional["SpillCatalog"] = None):
        self._nbytes = batch_nbytes(batch)
        self._catalog = catalog or spill_catalog()
        # budget-slice owner: the query whose thread registered this
        # batch. Reserve/release always pair on this tag so slice
        # accounting stays consistent no matter which thread spills or
        # re-materializes; victim selection uses it to keep one
        # tenant's pressure off another's batches.
        from ..robustness.admission import current_query
        q = current_query()
        self.owner: Optional[str] = q.query_id if q is not None else None
        self._catalog.budget.reserve(self._nbytes, owner=self.owner)
        self._batch: Optional[ColumnarBatch] = batch
        self._num_rows = int(batch.num_rows)
        self._host = None
        self._pooled: Optional[_PooledLeaves] = None
        self._treedef = None
        self._path: Optional[str] = None
        self._slab = None  # (metas, scalars, nleaves, total) for .slab
        self._crcs = None  # per-leaf checksums taken at spill time
        self.priority = priority
        self._lock = threading.Lock()
        self.closed = False
        self.creation_stack: Optional[str] = None
        if self._catalog.leak_detection:
            import traceback
            self.creation_stack = "".join(traceback.format_stack(limit=12))
        self.handle = self._catalog.register(self)

    @property
    def nbytes(self) -> int:
        return self._nbytes

    @property
    def tier(self) -> str:
        if self._batch is not None:
            return "device"
        if self._host is not None or self._pooled is not None:
            return "host"
        if self._path is not None:
            return "disk"
        return "closed"

    def num_rows(self) -> int:
        return self._num_rows

    def spill_to_host(self) -> int:
        """Device → host. Returns device bytes freed."""
        import time as _time
        with self._lock:
            if self._batch is None or self.closed:
                return 0
            t0 = _time.perf_counter_ns()
            host, self._treedef = _tree_to_host(self._batch)
            # checksum every leaf the moment it leaves the device: the
            # host and disk tiers both verify against these at
            # re-materialization (device->host->disk chain integrity)
            if self._catalog.verify_checksums:
                self._crcs = tuple(
                    array_checksum(x) if isinstance(x, np.ndarray)
                    else None for x in host)
            # host tier backing: native pool slab when space can be
            # found (cascading older host entries to disk), else plain
            # numpy under the same byte accounting
            self._pooled = self._catalog.try_pool_pack(host)
            if self._pooled is None:
                self._host = host
            self._batch = None
            self._catalog.budget.release(self._nbytes, owner=self.owner)
            from .budget import task_context
            from ..robustness.admission import current_query
            ctx = task_context()
            ctx.spilled_bytes += self._nbytes
            ctx.spill_time_ns += _time.perf_counter_ns() - t0
            rq = current_query()
            _events.emit("SpillToHost", bytes=self._nbytes,
                         time_ns=_time.perf_counter_ns() - t0,
                         priority=int(self.priority),
                         owner=self.owner,
                         requested_by=rq.query_id
                         if rq is not None else None)
            return self._nbytes

    def spill_to_disk(self) -> int:
        """Host → disk. Returns host bytes freed.

        Pool-slab entries stream RAW via the native O_DIRECT writer
        (GDS-spill role: bulk spills bypass the page cache and need no
        npz re-serialization); numpy-fallback entries keep the .npz
        path."""
        with self._lock:
            if (self._host is None and self._pooled is None) or \
                    self.closed:
                return 0
            if self._pooled is not None:
                from ..native import direct_write
                fd, path = tempfile.mkstemp(
                    suffix=".slab", dir=self._catalog.spill_dir)
                os.close(fd)
                if direct_write(path, self._pooled.ptr,
                                max(self._pooled.total, 1)):
                    self._path = path
                    self._slab = (self._pooled.metas,
                                  self._pooled.scalars,
                                  self._pooled.nleaves,
                                  self._pooled.total)
                    self._pooled.free()
                    self._pooled = None
                    _events.emit("SpillToDisk", bytes=self._nbytes,
                                 tier="slab")
                    return self._nbytes
                os.unlink(path)  # direct write failed: npz fallback
            host = self._host if self._host is not None \
                else self._pooled.unpack()
            fd, path = tempfile.mkstemp(suffix=".npz",
                                        dir=self._catalog.spill_dir)
            os.close(fd)
            arrays = {f"a{i}": x for i, x in enumerate(host)
                      if isinstance(x, np.ndarray)}
            scalars = {i: x for i, x in enumerate(host)
                       if not isinstance(x, np.ndarray)}
            np.savez(path, **arrays)
            self._path = path
            self._scalars = scalars
            self._nleaves = len(host)
            self._host = None
            if self._pooled is not None:
                self._pooled.free()
                self._pooled = None
            _events.emit("SpillToDisk", bytes=self._nbytes, tier="npz")
            return self._nbytes

    def get(self) -> ColumnarBatch:
        """Re-materialize on device (unspillBufferToDeviceStore,
        RapidsBufferCatalog.scala:633).

        budget.reserve runs OUTSIDE self._lock: its spill callback may
        call back into this object's spill_to_disk (or another thread's
        get may spill us) — holding the lock across it deadlocks.
        """
        with self._lock:
            if self.closed:
                raise ValueError("SpillableBatch used after close")
            if self._batch is not None:
                return self._batch
        self._catalog.budget.reserve(self._nbytes, owner=self.owner)
        try:
            with self._lock:
                if self.closed:
                    self._catalog.budget.release(self._nbytes,
                                                 owner=self.owner)
                    raise ValueError("SpillableBatch used after close")
                if self._batch is not None:  # raced with another get()
                    self._catalog.budget.release(self._nbytes,
                                                 owner=self.owner)
                    return self._batch
                if self._host is None and self._pooled is None and \
                        self._path is not None:
                    # a corrupt spill file may fail to even PARSE
                    # (flipped npz metadata, short read): any decode
                    # error here is at-rest corruption, same as a
                    # checksum mismatch
                    try:
                        if self._slab is not None:
                            self._host = self._load_slab()
                        else:
                            data = np.load(self._path)
                            leaves = []
                            for i in range(self._nleaves):
                                if i in self._scalars:
                                    leaves.append(self._scalars[i])
                                else:
                                    leaves.append(data[f"a{i}"])
                            self._host = leaves
                    except Exception as e:
                        raise DataCorruption(
                            f"spill entry handle={self.handle} "
                            f"unreadable at re-materialization: "
                            f"{type(e).__name__}: {e}",
                            detail="entry dropped; recompute the "
                                   "batch") from e
                    os.unlink(self._path)
                    self._path = None
                if self._pooled is not None:
                    host = self._pooled.unpack()  # copies out of the slab
                    self._pooled.free()
                    self._pooled = None
                else:
                    host = self._host
                self._host = None
                # every tier funnels through one verification point
                # before touching the device
                host = self._verify_host(host)
                self._batch = _tree_to_device(host, self._treedef)
                return self._batch
        except DataCorruption:
            # the entry's bytes are gone for good — drop it so retries
            # cannot re-read garbage; the caller (retry framework /
            # stage rerun) recomputes the batch from its lineage
            with self._lock:
                self.closed = True
                self._host = None
                if self._pooled is not None:
                    self._pooled.free()
                    self._pooled = None
                if self._path is not None:
                    try:
                        os.unlink(self._path)
                    except OSError:
                        pass
                    self._path = None
            self._catalog.budget.release(self._nbytes, owner=self.owner)
            self._catalog.unregister(self.handle)
            raise

    def _verify_host(self, host):
        """Seeded corruption site plus checksum verification at
        re-materialization — host- and disk-tier entries both pass
        through here on their way back to the device."""
        if _faults.armed():
            host = list(host)
            for idx, leaf in enumerate(host):
                if isinstance(leaf, np.ndarray) and leaf.size:
                    # adopt the return value: read-only leaves are
                    # corrupted on a copy, not in place
                    host[idx] = _faults.corrupt_point(
                        "spill.materialize", leaf,
                        f"handle={self.handle};leaf={idx};")
        if self._crcs is None:
            return host
        for idx, (leaf, crc) in enumerate(zip(host, self._crcs)):
            if crc is None or not isinstance(leaf, np.ndarray):
                continue
            actual = array_checksum(leaf)
            if actual != crc:
                raise DataCorruption(
                    f"spill entry handle={self.handle} leaf={idx} "
                    f"failed verification at re-materialization",
                    expected=crc, actual=actual,
                    detail="entry dropped; recompute the batch")
        return host

    def _load_slab(self):
        """Read a raw .slab spill back (O_DIRECT when the 4K-aligned
        buffer qualifies, buffered otherwise) and rebuild leaves."""
        from ..native import direct_read
        metas, scalars, nleaves, total = self._slab
        # 4096-aligned destination so O_DIRECT reads qualify
        raw = np.empty(max(total, 1) + 4096, np.uint8)
        off = (-raw.ctypes.data) % 4096
        buf = raw[off:off + max(total, 1)]
        if not direct_read(self._path, buf.ctypes.data, max(total, 1)):
            buf = np.fromfile(self._path, np.uint8, count=total)
        leaves = [None] * nleaves
        for i, v in scalars.items():
            leaves[i] = v
        for i, offset, shape, dtype in metas:
            count = int(np.prod(shape)) if shape else 1
            nbytes = count * dtype.itemsize
            if nbytes:
                arr = np.frombuffer(buf.data, dtype=dtype, count=count,
                                    offset=offset).reshape(shape)
            else:
                arr = np.zeros(shape, dtype)
            leaves[i] = arr
        self._slab = None
        return leaves

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            if self._batch is not None:
                self._catalog.budget.release(self._nbytes,
                                             owner=self.owner)
                self._batch = None
            self._host = None
            if self._pooled is not None:
                self._pooled.free()
                self._pooled = None
            if self._path is not None:
                try:
                    os.unlink(self._path)
                except OSError:
                    pass
                self._path = None
        self._catalog.unregister(self.handle)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SpillCatalog:
    """Handle registry + spill policy (RapidsBufferCatalog.scala:62).

    ``synchronous_spill(n)`` frees at least n device bytes by spilling
    registered batches in priority order, then pushes host-tier overflow
    to disk when the host limit is exceeded.
    """

    def __init__(self, budget: Optional[MemoryBudget] = None,
                 host_limit: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        from ..conf import (HOST_SPILL_LIMIT, INTEGRITY_CHECKSUM,
                            SPILL_DIR, active_conf)
        conf = active_conf()
        self.budget = budget or device_budget()
        self.budget.set_spill_callback(self.synchronous_spill)
        self.host_limit = host_limit or conf.get(HOST_SPILL_LIMIT)
        self.verify_checksums = conf.get(INTEGRITY_CHECKSUM)
        # disk-tier entries live in a PER-SESSION directory under the
        # configured root: a process killed mid-query cannot leak
        # orphaned mkstemp files forever — this process removes its own
        # dir at exit, and any dir whose owning pid is gone is swept
        # here on the next catalog init
        base = spill_dir or conf.get(SPILL_DIR)
        os.makedirs(base, exist_ok=True)
        sweep_stale_spill_dirs(base)
        self.spill_root = base
        self.spill_dir = tempfile.mkdtemp(
            prefix=f"session-{os.getpid()}-", dir=base)
        atexit.register(_remove_session_dir, self.spill_dir)
        self._entries: Dict[int, SpillableBatch] = {}
        self._next = 0
        self._lock = threading.Lock()
        self.host_pool = None
        from ..conf import LEAK_DETECTION
        self.leak_detection = conf.get(LEAK_DETECTION)
        from ..native import native_available
        if native_available():
            from ..native import HostMemoryPool
            self.host_pool = HostMemoryPool(self.host_limit)

    def try_pool_pack(self, host_leaves) -> Optional[_PooledLeaves]:
        """Pack spilled leaves into the native host pool; exhaustion
        cascades existing host-tier entries to disk
        (RapidsHostMemoryStore's spill-on-alloc-failure contract).
        None = caller keeps a plain numpy fallback."""
        if self.host_pool is None:
            return None
        pooled = _PooledLeaves.pack(self.host_pool, host_leaves)
        if pooled is not None:
            return pooled
        with self._lock:
            victims = sorted(
                (e for e in self._entries.values() if e.tier == "host"),
                key=lambda e: (e.priority, -e.nbytes))
        for v in victims:
            v.spill_to_disk()
            pooled = _PooledLeaves.pack(self.host_pool, host_leaves)
            if pooled is not None:
                return pooled
        return None

    def register(self, sb: SpillableBatch) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = sb
            return h

    def unregister(self, handle: int) -> None:
        with self._lock:
            self._entries.pop(handle, None)

    def device_candidates(self) -> List[SpillableBatch]:
        with self._lock:
            return sorted(
                (e for e in self._entries.values() if e.tier == "device"),
                key=lambda e: (e.priority, -e.nbytes))

    def synchronous_spill(self, target_bytes: int,
                          requester: Optional[str] = None,
                          active_owners=None) -> int:
        """Free >= target_bytes of device memory if possible
        (RapidsBufferCatalog.synchronousSpill:589).

        Budget-slice isolation: when the budget passes the requesting
        query and the live-owner set, candidates belonging to OTHER
        live queries are skipped — a tenant's pressure spills only its
        own batches, untagged ones, and leftovers of finished queries
        (idle slices). Legacy single-tenant callers pass neither and
        see the original all-candidates behavior. A cancel/deadline on
        the requesting query aborts mid-spill (the reservation that
        triggered this pass is moot)."""
        from ..robustness.admission import current_query
        qc = current_query()
        freed = 0
        for e in self.device_candidates():
            if freed >= target_bytes:
                break
            if qc is not None:
                qc.check()  # teardown point: mid-spill cancellation
            owner = e.owner
            if (active_owners and owner is not None
                    and owner != requester and owner in active_owners):
                continue  # another live query's slice: not evictable
            n = e.spill_to_host()
            if n and owner is not None and owner != requester:
                # observable proof of the isolation contract: only
                # finished queries' leftovers cross tenant lines
                _events.emit("CrossQuerySpill", bytes=n, owner=owner,
                             requested_by=requester,
                             owner_active=bool(
                                 active_owners
                                 and owner in active_owners))
            freed += n
        self._enforce_host_limit()
        return freed

    def _host_used(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values()
                       if e.tier == "host")

    def _enforce_host_limit(self) -> None:
        used = self._host_used()
        if used <= self.host_limit:
            return
        with self._lock:
            host = sorted((e for e in self._entries.values()
                           if e.tier == "host"),
                          key=lambda e: (e.priority, -e.nbytes))
        for e in host:
            if used <= self.host_limit:
                break
            used -= e.spill_to_disk()

    def leak_report(self) -> List[dict]:
        """Entries still registered — each is a leaked (never-closed)
        spillable. With srt.memory.leakDetection.enabled the creation
        stack pinpoints the owner (MemoryCleaner.scala role: the
        reference dumps leaked RapidsBuffers at executor shutdown)."""
        with self._lock:
            entries = list(self._entries.values())
        return [{"handle": e.handle, "tier": e.tier,
                 "nbytes": e.nbytes, "priority": int(e.priority),
                 "creation_stack": e.creation_stack}
                for e in entries if not e.closed]

    def log_leaks(self) -> int:
        import logging
        leaks = self.leak_report()
        log = logging.getLogger("spark_rapids_tpu.memory")
        for lk in leaks:
            log.warning(
                "LEAKED SpillableBatch handle=%s tier=%s bytes=%d%s",
                lk["handle"], lk["tier"], lk["nbytes"],
                ("\n" + lk["creation_stack"])
                if lk["creation_stack"] else "")
        return len(leaks)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            tiers = {"device": 0, "host": 0, "disk": 0}
            for e in self._entries.values():
                t = e.tier
                if t in tiers:
                    tiers[t] += e.nbytes
        tiers["budget_used"] = self.budget.used
        tiers["budget_limit"] = self.budget.limit
        return tiers


_SESSION_DIR_RE = re.compile(r"^session-(\d+)-")


def _remove_session_dir(path: str) -> None:
    shutil.rmtree(path, ignore_errors=True)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # exists but not ours (EPERM etc.)
    return True


def sweep_stale_spill_dirs(base: str) -> int:
    """Remove session spill directories whose owning process is gone
    (killed mid-query before its atexit cleanup could run). Returns the
    number of directories swept."""
    try:
        names = os.listdir(base)
    except OSError:
        return 0
    swept = 0
    for name in names:
        m = _SESSION_DIR_RE.match(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        shutil.rmtree(os.path.join(base, name), ignore_errors=True)
        swept += 1
    return swept


_CATALOG: Optional[SpillCatalog] = None
_CAT_LOCK = threading.Lock()


def spill_catalog() -> SpillCatalog:
    global _CATALOG
    with _CAT_LOCK:
        if _CATALOG is None:
            _CATALOG = SpillCatalog()
        return _CATALOG


def reset_spill_catalog(**kwargs) -> SpillCatalog:
    """Test hook: fresh catalog (optionally with a fresh budget)."""
    global _CATALOG
    with _CAT_LOCK:
        _CATALOG = SpillCatalog(**kwargs)
        return _CATALOG
