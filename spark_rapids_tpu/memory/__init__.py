"""Memory management: HBM budget, tiered spill, split-and-retry.

TPU-native rebuild of SURVEY §2.3 (RapidsBufferCatalog / stores /
RmmRapidsRetryIterator / SpillableColumnarBatch).
"""

from .budget import (MemoryBudget, OutOfDeviceMemory, RetryOOM,
                     SplitAndRetryOOM, TaskContext, device_budget,
                     task_context)
from .spill import SpillableBatch, SpillCatalog, SpillPriority, spill_catalog
from .retry import (split_spillable_in_half_by_rows, with_restore_on_retry,
                    with_retry, with_retry_no_split)

__all__ = [
    "MemoryBudget", "OutOfDeviceMemory", "RetryOOM", "SplitAndRetryOOM",
    "TaskContext", "device_budget", "task_context",
    "SpillableBatch", "SpillCatalog", "SpillPriority", "spill_catalog",
    "split_spillable_in_half_by_rows", "with_restore_on_retry",
    "with_retry", "with_retry_no_split",
]
