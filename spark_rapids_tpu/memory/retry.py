"""Split-and-retry OOM handling.

Rebuild of RmmRapidsRetryIterator.scala (686 LoC in the reference):
``withRetry`` / ``withRetryNoSplit`` / ``withRestoreOnRetry`` plus
``splitSpillableInHalfByRows``. The control flow is identical — attempt
the body; on RetryOOM spill-and-retry at the same size; on
SplitAndRetryOOM split the input and enqueue the halves — but the
*trigger* differs: instead of a native allocator callback interrupting a
JVM thread, OOMs here come from the MemoryBudget (budget.py) or from
kernels whose true output size exceeded the static output capacity
(e.g. join expansion overflow, ops/kernels.py).
"""

from __future__ import annotations

from typing import Callable, Iterator, List, TypeVar, Union

from ..conf import RETRY_MAX_SPLITS, active_conf
from ..obs import events as _events
from .budget import RetryOOM, SplitAndRetryOOM, task_context
from .spill import SpillableBatch, spill_catalog

T = TypeVar("T")
R = TypeVar("R")


def split_spillable_in_half_by_rows(sb: SpillableBatch) -> List[SpillableBatch]:
    """The standard split policy (splitSpillableInHalfByRows,
    RmmRapidsRetryIterator.scala:~447): halve by row count."""
    from ..columnar.vector import choose_capacity
    from ..ops.kernels import slice_batch

    batch = sb.get()
    n = int(batch.num_rows)
    if n <= 1:
        raise SplitAndRetryOOM(
            f"cannot split a batch of {n} rows any further")
    half = n // 2
    lo = slice_batch(batch, 0, half, choose_capacity(half))
    hi = slice_batch(batch, half, n - half, choose_capacity(n - half))
    lo_sb = SpillableBatch(lo, sb.priority)
    try:
        hi_sb = SpillableBatch(hi, sb.priority)
    except BaseException:
        lo_sb.close()
        raise
    sb.close()
    return [lo_sb, hi_sb]


def with_retry(
    inputs: Union[SpillableBatch, List[SpillableBatch]],
    fn: Callable[[SpillableBatch], R],
    split_policy: Callable[[SpillableBatch], List[SpillableBatch]] = None,
) -> Iterator[R]:
    """Run ``fn`` over each input with retry + optional split on OOM.

    Yields one result per (possibly split) attempt. Inputs are consumed:
    each SpillableBatch is closed by fn or by the split. Mirrors
    ``withRetry`` (RmmRapidsRetryIterator.scala:33).
    """
    conf = active_conf()
    max_splits = conf.get(RETRY_MAX_SPLITS)
    max_retries = 8
    pending: List[SpillableBatch] = (
        list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
    ctx = task_context()
    splits_done = 0
    retries_this_attempt = 0

    def close_all(attempt):
        attempt.close()
        for p in pending:
            p.close()

    while pending:
        attempt = pending.pop(0)
        try:
            result = fn(attempt)
            retries_this_attempt = 0
        except RetryOOM:
            ctx.retry_count += 1
            retries_this_attempt += 1
            _events.emit("RetryAttempt", scope="oom", kind="retry",
                         attempt=retries_this_attempt)
            freed = spill_catalog().synchronous_spill(attempt.nbytes)
            if retries_this_attempt > max_retries or (
                    freed == 0 and retries_this_attempt > 1):
                close_all(attempt)
                raise
            pending.insert(0, attempt)
            continue
        except SplitAndRetryOOM:
            retries_this_attempt = 0
            if split_policy is None:
                close_all(attempt)
                raise
            if splits_done >= max_splits:
                close_all(attempt)
                raise SplitAndRetryOOM(
                    f"still OOM after {splits_done} splits")
            ctx.split_count += 1
            splits_done += 1
            _events.emit("RetryAttempt", scope="oom", kind="split",
                         attempt=splits_done)
            try:
                halves = split_policy(attempt)
            except BaseException:
                close_all(attempt)
                raise
            pending[:0] = halves
            continue
        except BaseException:
            close_all(attempt)
            raise
        yield result


def with_retry_no_split(body: Callable[[], R], max_retries: int = 8) -> R:
    """Retry ``body`` on RetryOOM only (withRetryNoSplit). The body must
    be idempotent up to device allocations."""
    ctx = task_context()
    last = None
    for _ in range(max_retries):
        try:
            return body()
        except RetryOOM as e:
            ctx.retry_count += 1
            last = e
            _events.emit("RetryAttempt", scope="oom", kind="retry_no_split")
            spill_catalog().synchronous_spill(1 << 20)
    raise RetryOOM(f"exhausted {max_retries} retries") from last


class with_restore_on_retry:
    """Context manager: snapshot checkpointable state, restore on OOM
    (withRestoreOnRetry for non-deterministic expressions). The target
    must expose checkpoint()/restore()."""

    def __init__(self, target):
        self.target = target

    def __enter__(self):
        self.target.checkpoint()
        return self.target

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None and issubclass(exc_type,
                                               (RetryOOM, SplitAndRetryOOM)):
            self.target.restore()
        return False
