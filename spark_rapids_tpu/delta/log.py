"""Transaction log: ordered JSON commits with optimistic concurrency.

The GpuOptimisticTransaction equivalent (delta-lake/.../
GpuOptimisticTransaction.scala): writers prepare actions against a read
snapshot, then race to create the next numbered commit file with
O_CREAT|O_EXCL (the filesystem is the arbiter, like Delta's LogStore
contract). A loser whose read snapshot went stale raises
CommitConflict; idempotent retries re-validate against the new head.

Action vocabulary (one JSON object per line, Delta-style):
  {"metaData": {"schemaString": ..., "partitionColumns": [...]}}
  {"add":    {"path": ..., "numRecords": N, "dataChange": true}}
  {"remove": {"path": ..., "dataChange": true}}
  {"commitInfo": {"operation": ..., "timestamp": ...}}
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple


class CommitConflict(RuntimeError):
    """Another writer committed the version this transaction targeted."""


class MetadataChangedConflict(CommitConflict):
    """A concurrent transaction changed the table metadata/schema —
    not retryable (Delta's MetadataChangedException role)."""


class TransactionLog:
    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_dir = os.path.join(table_path, "_delta_log")

    def exists(self) -> bool:
        return os.path.isdir(self.log_dir)

    # --- reading ---
    def versions(self) -> List[int]:
        if not self.exists():
            return []
        out = []
        for f in os.listdir(self.log_dir):
            if f.endswith(".json"):
                try:
                    out.append(int(f[:-5]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_version(self) -> int:
        vs = self.versions()
        return vs[-1] if vs else -1

    def read_actions(self, version: int) -> List[dict]:
        path = os.path.join(self.log_dir, f"{version:020d}.json")
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    def snapshot(self, version: Optional[int] = None
                 ) -> Tuple[dict, Dict[str, dict]]:
        """Fold the log to (metadata, {path: add_action}) at ``version``
        (default: head). Time travel = pass an older version."""
        head = self.latest_version()
        if head < 0:
            raise FileNotFoundError(f"no table at {self.table_path}")
        v = head if version is None else version
        if v > head:
            raise ValueError(f"version {v} > latest {head}")
        meta: dict = {}
        files: Dict[str, dict] = {}
        for ver in self.versions():
            if ver > v:
                break
            for action in self.read_actions(ver):
                if "metaData" in action:
                    meta = action["metaData"]
                elif "add" in action:
                    files[action["add"]["path"]] = action["add"]
                elif "remove" in action:
                    files.pop(action["remove"]["path"], None)
        return meta, files

    # --- writing ---
    def commit(self, read_version: int, actions: List[dict],
               operation: str) -> int:
        """Atomically commit as version read_version+1; CommitConflict if
        that version exists (optimistic loser)."""
        os.makedirs(self.log_dir, exist_ok=True)
        version = read_version + 1
        payload = list(actions)
        payload.append({"commitInfo": {
            "operation": operation,
            "timestamp": int(time.time() * 1000),
            "readVersion": read_version,
        }})
        path = os.path.join(self.log_dir, f"{version:020d}.json")
        tmp = path + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            for a in payload:
                f.write(json.dumps(a) + "\n")
        try:
            # O_EXCL link: the filesystem arbitrates the race
            os.link(tmp, path)
        except FileExistsError:
            raise CommitConflict(
                f"version {version} already committed "
                f"(read snapshot {read_version} is stale)")
        finally:
            os.unlink(tmp)
        return version

    def history(self) -> List[dict]:
        out = []
        for v in self.versions():
            for a in self.read_actions(v):
                if "commitInfo" in a:
                    info = dict(a["commitInfo"])
                    info["version"] = v
                    out.append(info)
        return out
