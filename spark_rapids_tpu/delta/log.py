"""Transaction log: ordered JSON commits with optimistic concurrency.

The GpuOptimisticTransaction equivalent (delta-lake/.../
GpuOptimisticTransaction.scala): writers prepare actions against a read
snapshot, then race to create the next numbered commit file with
O_CREAT|O_EXCL (the filesystem is the arbiter, like Delta's LogStore
contract). A loser whose read snapshot went stale raises
CommitConflict; idempotent retries re-validate against the new head.

Action vocabulary (one JSON object per line, Delta-style):
  {"metaData": {"schemaString": ..., "partitionColumns": [...]}}
  {"add":    {"path": ..., "numRecords": N, "dataChange": true}}
  {"remove": {"path": ..., "dataChange": true}}
  {"txn":    {"appId": ..., "version": N, "epoch": E}}
  {"commitInfo": {"operation": ..., "timestamp": ...}}

Crash consistency (the transactional commit protocol):

- **Durable commits** (``srt.delta.durableCommits``): the commit file
  is fsynced before the O_EXCL link makes it the version, and the log
  directory is fsynced after — a crash immediately after ``commit()``
  returns can never lose or tear the version.
- **Idempotent txn actions**: a ``{"txn": {appId, version}}`` action
  records the highest micro-batch version an application has
  committed; ``txn_version(appId)`` lets a retried/resumed writer skip
  batches that already landed (exactly-once, Delta's SetTransaction).
  The optional ``epoch`` field carries writer-incarnation fencing for
  streaming (delta/streaming.py).
- **Log checkpoints** (``srt.delta.checkpointInterval``): every N
  commits the folded state is compacted into ``NNN.checkpoint.json``
  and ``_last_checkpoint`` points at it with a crc32 — replay reads
  the checkpoint plus the commits after it instead of the whole log.
  A torn or corrupt checkpoint fails its crc and replay silently
  falls back to the full JSON log (a checkpoint is a cache, never the
  source of truth).
- **Tmp hygiene**: commit tmps are ``<name>.<pid>[-<seq>].tmp``; listings
  ignore them and ``sweep_stale_tmp_files`` reclaims ones whose owner
  pid is dead (the spill-dir stale-pid sweep, applied to the log).
"""

from __future__ import annotations

import itertools
import json
import os
import re
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..robustness.faults import corrupt_point, fault_point

LAST_CHECKPOINT = "_last_checkpoint"

# --- commit listeners ------------------------------------------------------
# Process-wide callbacks fired after every successful commit() with
# (table_path, version). The serving result cache registers here so a
# Delta commit invalidates cached results over the table's old
# snapshot (serve/result_cache.py); listeners must never raise into
# the committer — a broken observer is not a failed commit.
_COMMIT_LISTENERS: List = []


def register_commit_listener(fn) -> None:
    """``fn(table_path: str, version: int)`` after each commit."""
    if fn not in _COMMIT_LISTENERS:
        _COMMIT_LISTENERS.append(fn)


def unregister_commit_listener(fn) -> None:
    try:
        _COMMIT_LISTENERS.remove(fn)
    except ValueError:
        pass


def _notify_commit(table_path: str, version: int) -> None:
    for fn in list(_COMMIT_LISTENERS):
        try:
            fn(table_path, version)
        except Exception:
            pass

#: per-process staging sequence: two threads racing the same commit
#: version must not share a tmp name (the loser's link would find the
#: winner already unlinked it)
_STAGE_SEQ = itertools.count()

#: ``<anything>.<pid>[-<seq>].tmp`` — the staging-name convention
#: shared by commit tmps (log dir) and staged data files (table dir);
#: the optional sequence disambiguates threads within one process
_TMP_RE = re.compile(r"\.(\d+)(?:-\d+)?\.tmp$")


class CommitConflict(RuntimeError):
    """Another writer committed the version this transaction targeted."""


class MetadataChangedConflict(CommitConflict):
    """A concurrent transaction changed the table metadata/schema —
    not retryable (Delta's MetadataChangedException role)."""


class StaleWriterEpoch(RuntimeError):
    """A newer incarnation of this streaming writer acquired the
    table; the fenced incumbent must not commit (delta/streaming.py
    writer-epoch fencing — the membership zombie-fencing pattern
    applied to the ingestion lane)."""


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # exists but not ours (EPERM etc.)
    return True


def sweep_stale_tmp_files(directory: str) -> List[str]:
    """Remove ``*.N.tmp`` files whose owning pid is dead (a committer
    or stager killed between staging and promotion). Mirrors
    ``memory.spill.sweep_stale_spill_dirs``. Returns swept names."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    swept = []
    for name in names:
        m = _TMP_RE.search(name)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(os.path.join(directory, name))
            swept.append(name)
        except OSError:
            pass
    return swept


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Persist a directory entry (the rename/link itself). Some
    filesystems refuse O_RDONLY fsync on directories — treat that as
    best-effort, like Delta's LogStore does."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class TransactionLog:
    def __init__(self, table_path: str, conf=None):
        self.table_path = table_path
        self.log_dir = os.path.join(table_path, "_delta_log")
        self._conf = conf

    def _get(self, entry):
        from ..conf import active_conf
        conf = self._conf if self._conf is not None else active_conf()
        return conf.get(entry)

    @property
    def durable(self) -> bool:
        from ..conf import DELTA_DURABLE_COMMITS
        return bool(self._get(DELTA_DURABLE_COMMITS))

    def exists(self) -> bool:
        return os.path.isdir(self.log_dir)

    # --- reading ---
    def versions(self) -> List[int]:
        if not self.exists():
            return []
        out = []
        for f in os.listdir(self.log_dir):
            # crashed committers leave NNN.json.<pid>.tmp; checkpoints
            # are NNN.checkpoint.json — neither is a commit version
            if not f.endswith(".json") or f.endswith(".checkpoint.json") \
                    or _TMP_RE.search(f):
                continue
            try:
                out.append(int(f[:-5]))
            except ValueError:
                pass
        return sorted(out)

    def latest_version(self) -> int:
        vs = self.versions()
        return vs[-1] if vs else -1

    def read_actions(self, version: int) -> List[dict]:
        path = os.path.join(self.log_dir, f"{version:020d}.json")
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]

    # --- checkpoint plumbing ---
    def _read_last_checkpoint(self) -> Optional[dict]:
        path = os.path.join(self.log_dir, LAST_CHECKPOINT)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(rec, dict) or "version" not in rec:
            return None
        return rec

    def _load_checkpoint(self, rec: dict) -> Optional[List[dict]]:
        """Read and crc-verify a checkpoint; None (full-replay
        fallback) on any mismatch or read failure."""
        path = os.path.join(self.log_dir,
                            f"{int(rec['version']):020d}.checkpoint.json")
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None
        if "crc32" in rec and zlib.crc32(raw) != int(rec["crc32"]):
            from ..obs import events as _events
            _events.emit("CorruptionDetected", kind="delta_checkpoint",
                         path=path, version=int(rec["version"]))
            return None
        try:
            return [json.loads(line) for line in raw.decode().splitlines()
                    if line.strip()]
        except (ValueError, UnicodeDecodeError):
            from ..obs import events as _events
            _events.emit("CorruptionDetected", kind="delta_checkpoint",
                         path=path, version=int(rec["version"]))
            return None

    def checkpoint(self, version: Optional[int] = None) -> int:
        """Compact the folded state at ``version`` (default head) into
        ``NNN.checkpoint.json`` and atomically repoint
        ``_last_checkpoint``. Returns the checkpointed version."""
        v = self.latest_version() if version is None else version
        if v < 0:
            raise FileNotFoundError(f"no table at {self.table_path}")
        meta, files, txns = self._fold(v, use_checkpoint=False)
        actions: List[dict] = []
        if meta:
            actions.append({"metaData": meta})
        actions.extend({"add": a} for a in files.values())
        actions.extend({"txn": dict(t, appId=app)}
                       for app, t in sorted(txns.items()))
        payload = "".join(json.dumps(a) + "\n" for a in actions).encode()
        fault_point("delta.checkpoint", f"version={v};")
        payload = corrupt_point("delta.checkpoint.bytes", payload,
                                f"version={v};")
        path = os.path.join(self.log_dir, f"{v:020d}.checkpoint.json")
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
            if self.durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
        rec = {"version": v, "size": len(actions),
               "crc32": zlib.crc32(payload)}
        ptr = os.path.join(self.log_dir, LAST_CHECKPOINT)
        ptr_tmp = f"{ptr}.{os.getpid()}.tmp"
        with open(ptr_tmp, "w") as f:
            json.dump(rec, f)
            if self.durable:
                f.flush()
                os.fsync(f.fileno())
        os.replace(ptr_tmp, ptr)
        if self.durable:
            fsync_dir(self.log_dir)
        from ..obs import events as _events
        _events.emit("DeltaLogCheckpointed", table=self.table_path,
                     version=v, actions=len(actions))
        return v

    def _fold(self, v: int, use_checkpoint: bool = True
              ) -> Tuple[dict, Dict[str, dict], Dict[str, dict]]:
        """Fold the log to (metadata, {path: add}, {appId: txn}) at
        version ``v``, starting from the newest usable checkpoint."""
        meta: dict = {}
        files: Dict[str, dict] = {}
        txns: Dict[str, dict] = {}
        start = 0
        if use_checkpoint:
            rec = self._read_last_checkpoint()
            # a checkpoint NEWER than the target version cannot seed a
            # time-travel read; fall back to full replay
            if rec is not None and int(rec["version"]) <= v:
                actions = self._load_checkpoint(rec)
                if actions is not None:
                    start = int(rec["version"]) + 1
                    for action in actions:
                        self._fold_action(action, meta, files, txns)
        for ver in self.versions():
            if ver < start:
                continue
            if ver > v:
                break
            for action in self.read_actions(ver):
                self._fold_action(action, meta, files, txns)
        return meta, files, txns

    @staticmethod
    def _fold_action(action: dict, meta: dict, files: Dict[str, dict],
                     txns: Dict[str, dict]) -> None:
        if "metaData" in action:
            meta.clear()
            meta.update(action["metaData"])
        elif "add" in action:
            files[action["add"]["path"]] = action["add"]
        elif "remove" in action:
            files.pop(action["remove"]["path"], None)
        elif "txn" in action:
            t = action["txn"]
            app = t.get("appId")
            cur = txns.setdefault(app, {"version": -1, "epoch": 0})
            # versions and epochs only ever advance (an epoch-acquire
            # commit carries version=-1; a fenced stale batch can
            # never regress either)
            cur["version"] = max(cur["version"],
                                 int(t.get("version", -1)))
            cur["epoch"] = max(cur["epoch"], int(t.get("epoch", 0)))

    def snapshot(self, version: Optional[int] = None
                 ) -> Tuple[dict, Dict[str, dict]]:
        """Fold the log to (metadata, {path: add_action}) at ``version``
        (default: head). Time travel = pass an older version."""
        head = self.latest_version()
        if head < 0:
            raise FileNotFoundError(f"no table at {self.table_path}")
        v = head if version is None else version
        if v > head:
            raise ValueError(f"version {v} > latest {head}")
        meta, files, _ = self._fold(v)
        return meta, files

    def txn_state(self, app_id: str) -> Dict[str, int]:
        """{"version": highest committed batch (-1 if none),
        "epoch": current writer epoch (0 if never acquired)}."""
        head = self.latest_version()
        if head < 0:
            return {"version": -1, "epoch": 0}
        _, _, txns = self._fold(head)
        return dict(txns.get(app_id, {"version": -1, "epoch": 0}))

    def txn_version(self, app_id: str) -> int:
        return self.txn_state(app_id)["version"]

    def txn_epoch(self, app_id: str) -> int:
        return self.txn_state(app_id)["epoch"]

    # --- writing ---
    def commit(self, read_version: int, actions: List[dict],
               operation: str) -> int:
        """Atomically commit as version read_version+1; CommitConflict
        if that version exists (optimistic loser). With
        ``srt.delta.durableCommits`` the commit file is fsynced before
        the link and the log dir after, so a returned version survives
        a machine crash."""
        os.makedirs(self.log_dir, exist_ok=True)
        version = read_version + 1
        payload = list(actions)
        payload.append({"commitInfo": {
            "operation": operation,
            "timestamp": int(time.time() * 1000),
            "readVersion": read_version,
        }})
        path = os.path.join(self.log_dir, f"{version:020d}.json")
        tmp = path + f".{os.getpid()}-{next(_STAGE_SEQ)}.tmp"
        fault_point("delta.commit", f"version={version};op={operation};")
        with open(tmp, "w") as f:
            for a in payload:
                f.write(json.dumps(a) + "\n")
            if self.durable:
                fault_point("delta.commit.fsync",
                            f"version={version};op={operation};")
                f.flush()
                os.fsync(f.fileno())
        try:
            # O_EXCL link: the filesystem arbitrates the race
            os.link(tmp, path)
        except FileExistsError:
            raise CommitConflict(
                f"version {version} already committed "
                f"(read snapshot {read_version} is stale)")
        finally:
            os.unlink(tmp)
        if self.durable:
            fsync_dir(self.log_dir)
        from ..obs import events as _events
        _events.emit("DeltaCommit", table=self.table_path,
                     version=version, operation=operation,
                     actions=len(payload))
        _notify_commit(self.table_path, version)
        self._maybe_checkpoint(version)
        return version

    def _maybe_checkpoint(self, version: int) -> None:
        from ..conf import DELTA_CHECKPOINT_INTERVAL
        interval = int(self._get(DELTA_CHECKPOINT_INTERVAL))
        if interval <= 0 or version <= 0 or version % interval != 0:
            return
        try:
            self.checkpoint(version)
        except OSError:
            pass  # a failed checkpoint is a lost optimization, not a
        #         lost commit — the JSON log remains the source of truth

    def history(self) -> List[dict]:
        out = []
        for v in self.versions():
            for a in self.read_actions(v):
                if "commitInfo" in a:
                    info = dict(a["commitInfo"])
                    info["version"] = v
                    out.append(info)
        return out
