"""Streaming micro-batch ingestion with exactly-once Delta commits.

The continuous-ingestion lane the integrity/fault/telemetry stack was
built for: an application appends micro-batches to an AcidTable, each
batch committed with an idempotent ``txn`` action carrying the app id
and the batch number. The protocol gives two crash guarantees:

- **Exactly-once resume.** A killed ingester restarts, reads
  ``txn_version(app_id)`` from the log, and re-enters the stream at
  the first uncommitted batch — batches that already landed are
  skipped without re-reading their source (the source contract is a
  replayable ``batch_fn(batch_id)``, Spark Structured Streaming's
  replayable-source requirement). Duplicated delivery is impossible
  because the batch's txn action commits atomically with its data.
- **Writer-epoch fencing.** Each ingester incarnation acquires an
  epoch by committing an epoch bump (the cluster-membership zombie-
  fencing pattern applied to the ingestion lane). A replaced
  incumbent — a zombie that lost a lease, a speculative duplicate —
  fails its next commit with ``StaleWriterEpoch`` before any data
  becomes visible, and the refusal is observable
  (``StaleWriterFenced`` event).

The module doubles as the chaos harness's ingester child::

    python -m spark_rapids_tpu.delta.streaming TABLE APP N_BATCHES \
        ROWS_PER_BATCH [--fault-plan SPEC] [--events-dir DIR] [--create]

tools/chaos_check.py SIGKILLs this process (via seeded ``crash``
clauses at the delta fault sites) mid-ingest and relaunches it,
asserting exactly-once row counts and zero orphans after resume.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Callable, Dict, Optional

from .log import StaleWriterEpoch
from .table import AcidTable


class DeltaIngestor:
    """One writer incarnation for ``app_id`` over ``table``.

    Construction commits the epoch acquisition (fencing every earlier
    incarnation); ``ingest`` then appends micro-batches exactly-once.
    """

    def __init__(self, table: AcidTable, app_id: str):
        self.table = table
        self.app_id = app_id
        self.epoch = table.acquire_writer_epoch(app_id)

    def committed_batch(self) -> int:
        """Highest batch id this app has committed (-1 if none)."""
        return self.table.log.txn_version(self.app_id)

    def ingest(self, batch_fn: Callable[[int], object],
               num_batches: int,
               on_batch: Optional[Callable[[int, int], None]] = None
               ) -> Dict[str, int]:
        """Append batches ``0..num_batches-1``, resuming past the ones
        already in the log. ``batch_fn(b)`` must be replayable: asked
        again for the same ``b`` after a crash, it must produce the
        same logical rows. Returns {"committed", "skipped"}.
        Raises StaleWriterEpoch the moment a newer incarnation fences
        this one."""
        from ..obs import events as _events
        start = self.committed_batch() + 1
        if start > 0:
            _events.emit("StreamBatchSkipped", table=self.table.path,
                         appId=self.app_id, epoch=self.epoch,
                         resumeBatch=start, skipped=start)
        stats = {"committed": 0, "skipped": max(start, 0)}
        for b in range(start, num_batches):
            df = batch_fn(b)
            t0 = time.perf_counter()
            version = self.table.append(
                df, txn_app_id=self.app_id, txn_version=b,
                txn_epoch=self.epoch,
                operation=f"STREAMING UPDATE app={self.app_id};"
                          f"batch={b};")
            stats["committed"] += 1
            _events.emit("StreamBatchCommitted", table=self.table.path,
                         appId=self.app_id, epoch=self.epoch, batch=b,
                         version=version,
                         commit_ms=round(
                             (time.perf_counter() - t0) * 1e3, 3))
            if on_batch is not None:
                on_batch(b, version)
        return stats


def ingest(table: AcidTable, app_id: str,
           batch_fn: Callable[[int], object],
           num_batches: int) -> Dict[str, int]:
    """One-shot convenience: acquire an epoch and ingest the stream."""
    return DeltaIngestor(table, app_id).ingest(batch_fn, num_batches)


# --------------------------------------------------------------------------
# Deterministic demo stream — shared by the chaos harness (parent
# asserts against the same closed-form totals the child ingested)
# --------------------------------------------------------------------------

DEMO_SCHEMA = None  # built lazily: columnar dtypes import is heavy


def demo_schema():
    from ..columnar import dtypes as dt
    return [("id", dt.INT64), ("v", dt.FLOAT64)]


def demo_batch_dict(batch: int, rows_per_batch: int) -> Dict[str, list]:
    """Batch ``b`` = ids [b*R, (b+1)*R) with v = id * 0.5 — replayable
    and closed-form checkable (sum(v) = 0.25*N*(N-1) over N total)."""
    lo = batch * rows_per_batch
    ids = list(range(lo, lo + rows_per_batch))
    return {"id": ids, "v": [i * 0.5 for i in ids]}


def demo_expected(num_batches: int, rows_per_batch: int
                  ) -> Dict[str, float]:
    n = num_batches * rows_per_batch
    return {"rows": n, "distinct_ids": n,
            "sum_v": 0.25 * n * (n - 1)}


def _child_main(argv) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="chaos-harness ingester child")
    ap.add_argument("table")
    ap.add_argument("app_id")
    ap.add_argument("num_batches", type=int)
    ap.add_argument("rows_per_batch", type=int)
    ap.add_argument("--fault-plan", default="")
    ap.add_argument("--events-dir", default="")
    ap.add_argument("--create", action="store_true",
                    help="create the table if it does not exist")
    ap.add_argument("--no-durable", action="store_true")
    ap.add_argument("--checkpoint-interval", type=int, default=4)
    args = ap.parse_args(argv)

    from ..conf import SrtConf
    from ..obs import events as _events
    from ..plan import TpuSession
    from ..robustness import faults

    settings = {
        "srt.delta.durableCommits":
            "false" if args.no_durable else "true",
        "srt.delta.checkpointInterval": str(args.checkpoint_interval),
    }
    if args.events_dir:
        settings["srt.eventLog.enabled"] = "true"
        settings["srt.eventLog.dir"] = args.events_dir
    if args.fault_plan:
        settings["srt.test.faultPlan"] = args.fault_plan
    conf = SrtConf(settings)
    faults.arm_from_conf(conf)
    _events.configure_from_conf(conf)
    session = TpuSession(conf)

    if args.create and not os.path.isdir(
            os.path.join(args.table, "_delta_log")):
        table = AcidTable.create(session, args.table, demo_schema())
    else:
        table = AcidTable.for_path(session, args.table)

    def batch_fn(b):
        return session.create_dataframe(
            demo_batch_dict(b, args.rows_per_batch), demo_schema())

    try:
        stats = DeltaIngestor(table, args.app_id).ingest(
            batch_fn, args.num_batches)
    except StaleWriterEpoch as e:
        print(f"[ingest-child] fenced: {e}", file=sys.stderr, flush=True)
        return 3
    print(f"[ingest-child] done: {stats}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1:]))
