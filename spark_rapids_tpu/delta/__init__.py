"""ACID table format: transaction log + DML (the Delta Lake layer).

Rebuild of the reference's delta-lake/ integration (36k LoC across
version shims, SURVEY §2.6): GpuOptimisticTransaction,
GpuMergeIntoCommand, GpuUpdateCommand, GpuDeleteCommand — as a
first-party table format over the framework's own parquet writer
instead of a plugin into someone else's. Same architecture:

- an append-only ``_delta_log`` of JSON commit files; a snapshot is the
  fold of add/remove actions up to a version (time travel = fold to an
  older version),
- optimistic concurrency: commit N is an O_EXCL create of
  ``N.json`` — losers re-read, re-validate, retry,
- DML rewrites data files copy-on-write and commits add+remove pairs
  atomically.
"""

from .log import (CommitConflict, MetadataChangedConflict,
                  StaleWriterEpoch, TransactionLog,
                  sweep_stale_tmp_files)
from .table import AcidTable

__all__ = ["AcidTable", "TransactionLog", "CommitConflict",
           "MetadataChangedConflict", "StaleWriterEpoch",
           "DeltaIngestor", "sweep_stale_tmp_files"]


def __getattr__(name):
    # streaming pulls in the session layer; keep it import-lazy so
    # `from ..delta import TransactionLog` deep in io/ stays cheap
    if name == "DeltaIngestor":
        from .streaming import DeltaIngestor
        return DeltaIngestor
    raise AttributeError(name)
