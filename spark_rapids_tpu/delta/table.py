"""AcidTable: DML over the transaction log.

The GpuMergeIntoCommand / GpuUpdateCommand / GpuDeleteCommand layer
(delta-lake/delta-24x/..., SURVEY §2.6). All DML is copy-on-write:
affected files are rewritten through the TPU engine (scan -> filter/
project/join on device -> parquet writer) and the log commits the
add/remove pairs in one atomic version.
"""

from __future__ import annotations

import json
import os
import random
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from ..columnar import dtypes as dt
from ..expr.conditional import If
from ..expr.core import Alias, ColumnRef, Expression, col, lit
from ..expr.predicates import Not
# plan must initialize before io (io's scan registration reaches back
# into the plan package mid-import otherwise)
from ..plan import logical as L
from ..plan.host_table import HostTable, concat_tables, empty_like
from ..io.scan import FileScan
from ..io.writer import write_host_table
from ..robustness.faults import fault_point
from .log import (_TMP_RE, CommitConflict, MetadataChangedConflict,
                  StaleWriterEpoch, TransactionLog, _pid_alive,
                  fsync_dir, fsync_file, sweep_stale_tmp_files)


def _schema_to_json(schema) -> str:
    return json.dumps([[n, repr(t) if not isinstance(t, dt.DecimalType)
                        else f"decimal({t.precision},{t.scale})"]
                       for n, t in schema])


def _schema_from_json(s: str):
    from ..parallel.serializer import _tag_dtype
    return [(n, _tag_dtype(tag)) for n, tag in json.loads(s)]


class _StagedWrite:
    """Data files written to ``<final>.<pid>.tmp`` names, promoted to
    their final paths by rename only at commit time. A crash before
    ``promote()`` leaves only tmp names (invisible to every reader,
    reclaimed by the stale-pid sweep); a crash between ``promote()``
    and the log commit leaves unreferenced final-named files, which
    VACUUM's orphan sweep reclaims behind the retention guard."""

    def __init__(self, durable: bool, detail: str = ""):
        self.pairs: List[Tuple[str, str]] = []   # (tmp, final)
        self.actions: List[dict] = []
        self.durable = durable
        self.detail = detail
        self.promoted = False

    def promote(self) -> None:
        if self.promoted:
            return
        parents = set()
        for tmp, final in self.pairs:
            fault_point("delta.rename",
                        f"{self.detail}file={os.path.basename(final)};")
            if self.durable:
                fsync_file(tmp)
            os.replace(tmp, final)
            parents.add(os.path.dirname(final))
        if self.durable:
            for d in parents:
                fsync_dir(d)
        self.promoted = True

    def discard(self) -> None:
        """Undo an uncommitted write: tmp names before promotion,
        final names after (the log never referenced either)."""
        for tmp, final in self.pairs:
            for p in ((final,) if self.promoted else (tmp,)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
        self.pairs = []
        self.actions = []


class AcidTable:
    """A transactional parquet table (DeltaTable API shape)."""

    def __init__(self, session, path: str):
        self.session = session
        self.path = path
        conf = getattr(session, "conf", None)
        self.log = TransactionLog(path, conf=conf)
        # reclaim staging leftovers from committers killed mid-write
        # (the spill-dir stale-pid sweep, applied at catalog init)
        sweep_stale_tmp_files(path)
        sweep_stale_tmp_files(self.log.log_dir)

    # --- commit-protocol conf ---
    def _conf(self, entry):
        conf = getattr(self.session, "conf", None)
        if conf is None:
            from ..conf import active_conf
            conf = active_conf()
        return conf.get(entry)

    def _retry_budget(self) -> Tuple[int, float]:
        from ..conf import DELTA_COMMIT_BACKOFF_MS, DELTA_COMMIT_MAX_RETRIES
        return (int(self._conf(DELTA_COMMIT_MAX_RETRIES)),
                float(self._conf(DELTA_COMMIT_BACKOFF_MS)) / 1e3)

    @staticmethod
    def _backoff(attempt: int, base_s: float) -> None:
        if base_s <= 0:
            return
        cap = min(base_s * (2 ** attempt), base_s * 32)
        time.sleep(cap * (0.5 + random.random()))

    # --- creation ---
    @classmethod
    def create(cls, session, path: str, schema) -> "AcidTable":
        t = cls(session, path)
        if t.log.exists():
            raise FileExistsError(f"table exists at {path}")
        os.makedirs(path, exist_ok=True)
        t.log.commit(-1, [{"metaData": {
            "schemaString": _schema_to_json(schema),
            "partitionColumns": [],
        }}], "CREATE TABLE")
        return t

    @classmethod
    def for_path(cls, session, path: str) -> "AcidTable":
        t = cls(session, path)
        if not t.log.exists():
            raise FileNotFoundError(f"no table at {path}")
        return t

    # --- reads ---
    def schema(self, version: Optional[int] = None):
        meta, _ = self.log.snapshot(version)
        return _schema_from_json(meta["schemaString"])

    def files(self, version: Optional[int] = None) -> List[str]:
        _, files = self.log.snapshot(version)
        return sorted(os.path.join(self.path, p) for p in files)

    def to_df(self, version: Optional[int] = None):
        from ..plan.session import DataFrame
        schema = self.schema(version)
        files = self.files(version)
        if not files:
            return self.session.create_dataframe(
                {n: [] for n, _ in schema}, schema)
        scan = FileScan(files, "parquet", schema)
        # snapshot provenance for the serving result cache (same
        # contract as io/delta_format.read_delta)
        pinned = version if version is not None \
            else self.log.latest_version()
        scan.delta_table = (os.path.abspath(self.path), pinned)
        return DataFrame(self.session, scan)

    def version(self) -> int:
        return self.log.latest_version()

    def history(self) -> List[dict]:
        return self.log.history()

    # --- writes ---
    def _write_files(self, table: HostTable,
                     detail: str = "") -> _StagedWrite:
        """Stage one parquet file per call as ``<final>.<pid>.tmp``;
        the add actions name the FINAL path, which exists only after
        ``promote()`` renames it at commit time."""
        staged = _StagedWrite(self.log.durable, detail)
        if table.num_rows == 0:
            return staged
        fname = f"part-{uuid.uuid4().hex[:12]}.parquet"
        from ..io.arrow_convert import host_table_to_arrow
        import pyarrow.parquet as pq
        at = host_table_to_arrow(table)
        full = os.path.join(self.path, fname)
        tmp = f"{full}.{os.getpid()}.tmp"
        fault_point("delta.stage", f"{detail}file={fname};")
        pq.write_table(at, tmp)
        staged.pairs.append((tmp, full))
        staged.actions.append(
            {"add": {"path": fname, "numRecords": table.num_rows,
                     "dataChange": True}})
        return staged

    def _winner_actions(self, read_v: int) -> List[dict]:
        """All actions committed by OTHER writers after our snapshot."""
        out: List[dict] = []
        for v in self.log.versions():
            if v > read_v:
                out.extend(self.log.read_actions(v))
        return out

    def _check_conflict(self, read_v: int, operation: str) -> None:
        """The optimistic-concurrency conflict matrix
        (GpuOptimisticTransaction / Delta's ConflictChecker):

        - winner changed METADATA (schema evolution) -> abort: our
          actions were computed against the old schema
          (MetadataChangedException role),
        - winner only APPENDED -> safe to recompute/replay (appends
          never invalidate a read file set),
        - winner REMOVED files -> a rewrite recomputes from the new
          head (the retry loop re-reads), which preserves
          serializability because build_actions is a pure function of
          the current snapshot."""
        for a in self._winner_actions(read_v):
            if "metaData" in a:
                raise MetadataChangedConflict(
                    f"{operation}: a concurrent transaction changed "
                    "the table schema; re-run against the new schema")

    def _check_txn(self, txn, staged: Optional[_StagedWrite]
                   ) -> Optional[int]:
        """Idempotency + fencing gate, re-evaluated against the LIVE
        head on every commit attempt. Returns the head version when
        the batch already committed (exactly-once no-op); raises
        StaleWriterEpoch when a newer writer incarnation holds the
        table; None means proceed."""
        app_id, batch_version, epoch = txn
        state = self.log.txn_state(app_id)
        if epoch is not None and state["epoch"] != epoch:
            if staged is not None:
                staged.discard()
            from ..obs import events as _events
            _events.emit("StaleWriterFenced", table=self.path,
                         appId=app_id, writerEpoch=epoch,
                         currentEpoch=state["epoch"],
                         batch=batch_version)
            raise StaleWriterEpoch(
                f"writer epoch {epoch} for app {app_id!r} fenced by "
                f"epoch {state['epoch']} — a replaced incumbent must "
                "not commit")
        if batch_version is not None \
                and state["version"] >= batch_version:
            if staged is not None:
                staged.discard()
            return self.log.latest_version()
        return None

    @staticmethod
    def _txn_action(txn) -> List[dict]:
        app_id, batch_version, epoch = txn
        t: dict = {"appId": app_id,
                   "version": -1 if batch_version is None
                   else batch_version,
                   "lastUpdated": int(time.time() * 1000)}
        if epoch is not None:
            t["epoch"] = epoch
        return [{"txn": t}]

    def _commit_blind(self, staged: _StagedWrite, operation: str,
                      txn: Optional[Tuple] = None) -> int:
        """Snapshot-independent commits (append): retrying the same
        actions against a newer head is safe — unless the schema
        changed underneath. ``txn=(appId, batchVersion, epoch|None)``
        adds the idempotent-transaction action and its exactly-once /
        fencing checks; staged files are promoted by rename exactly
        once, immediately before the first commit attempt."""
        retries, backoff_s = self._retry_budget()
        actions = list(staged.actions)
        if txn is not None:
            actions += self._txn_action(txn)
        for attempt in range(retries + 1):
            read_v = self.log.latest_version()
            if txn is not None:
                done = self._check_txn(txn, staged)
                if done is not None:
                    return done
            staged.promote()
            try:
                return self.log.commit(read_v, actions, operation)
            except CommitConflict:
                self._check_conflict(read_v, operation)
                if attempt == retries:
                    staged.discard()
                    raise
                self._backoff(attempt, backoff_s)
        raise AssertionError("unreachable")

    def _commit_rewrite(self, build_actions, operation: str) -> int:
        """Copy-on-write commits: ``build_actions(read_version)`` must
        read the CURRENT snapshot and return ``(actions, staged)`` —
        on conflict the whole rewrite recomputes against the winner's
        table state (optimistic losers must not replay stale file
        sets) and the loser's uncommitted files are reclaimed."""
        retries, backoff_s = self._retry_budget()
        for attempt in range(retries + 1):
            read_v = self.log.latest_version()
            actions, staged = build_actions(read_v)
            staged.promote()
            try:
                return self.log.commit(read_v,
                                       actions + staged.actions,
                                       operation)
            except CommitConflict:
                staged.discard()
                self._check_conflict(read_v, operation)
                if attempt == retries:
                    raise
                self._backoff(attempt, backoff_s)
        raise AssertionError("unreachable")

    def _remove_all_current(self, read_v: int) -> List[dict]:
        _, files = self.log.snapshot(read_v)
        return [{"remove": {"path": p, "dataChange": True}}
                for p in files]

    def append(self, df, txn_app_id: Optional[str] = None,
               txn_version: Optional[int] = None,
               txn_epoch: Optional[int] = None,
               operation: str = "WRITE (append)") -> int:
        """Append ``df``. With ``txn_app_id``/``txn_version`` the
        commit is exactly-once: a retried/resumed writer whose batch
        already landed returns without writing (Delta's
        SetTransaction idempotency); ``txn_epoch`` additionally fences
        stale writer incarnations (StaleWriterEpoch)."""
        txn = detail = None
        if txn_app_id is not None:
            txn = (txn_app_id, txn_version, txn_epoch)
            detail = f"app={txn_app_id};batch={txn_version};"
            # resumed writer: skip even the plan execution when the
            # batch is already in the log
            done = self._check_txn(txn, None)
            if done is not None:
                return done
        table = self.session.execute(df.plan)
        staged = self._write_files(table, detail or "")
        return self._commit_blind(staged, operation, txn=txn)

    def acquire_writer_epoch(self, app_id: str) -> int:
        """Claim the streaming-writer role for ``app_id``: commits an
        epoch bump that fences every earlier incarnation (their next
        commit raises StaleWriterEpoch). Returns the new epoch."""
        retries, backoff_s = self._retry_budget()
        for attempt in range(retries + 1):
            read_v = self.log.latest_version()
            epoch = self.log.txn_epoch(app_id) + 1
            actions = self._txn_action((app_id, None, epoch))
            try:
                self.log.commit(read_v, actions,
                                f"STREAM EPOCH app={app_id};")
                return epoch
            except CommitConflict:
                self._check_conflict(read_v, "STREAM EPOCH")
                if attempt == retries:
                    raise
                self._backoff(attempt, backoff_s)
        raise AssertionError("unreachable")

    def overwrite(self, df) -> int:
        table = self.session.execute(df.plan)

        def build(read_v: int):
            return (self._remove_all_current(read_v),
                    self._write_files(table))
        return self._commit_rewrite(build, "WRITE (overwrite)")

    def delete(self, condition: Expression) -> int:
        """DELETE WHERE cond (GpuDeleteCommand): rewrite surviving rows."""

        def build(read_v: int):
            keep = self.to_df(version=read_v).filter(Not(condition))
            table = self.session.execute(keep.plan)
            return (self._remove_all_current(read_v),
                    self._write_files(table))
        return self._commit_rewrite(build, "DELETE")

    def update(self, set_exprs: Dict[str, Expression],
               condition: Optional[Expression] = None) -> int:
        """UPDATE SET col=expr [WHERE cond] (GpuUpdateCommand)."""
        cond = condition if condition is not None else lit(True)

        def build(read_v: int) -> List[dict]:
            df = self.to_df(version=read_v)
            projected = []
            for name, t in self.schema(read_v):
                if name in set_exprs:
                    e = If(cond, set_exprs[name], col(name))
                    if e.data_type(df.schema) != t:
                        e = e.cast(t)
                    projected.append(Alias(e, name))
                else:
                    projected.append(col(name))
            table = self.session.execute(L.Project(df.plan, projected))
            return (self._remove_all_current(read_v),
                    self._write_files(table))
        return self._commit_rewrite(build, "UPDATE")

    def merge(self, source, on: Sequence[str],
              when_matched_update: Optional[Dict[str, Expression]] = None,
              when_matched_delete: bool = False,
              when_not_matched_insert: bool = True,
              schema_evolution: bool = False) -> int:
        """MERGE INTO target USING source ON target.k = source.k
        (GpuMergeIntoCommand shape):

        - matched + update: matched target rows take source-side values
          from ``when_matched_update`` ({target_col: expr over source
          columns prefixed 'src_'}),
        - matched + delete: matched target rows drop,
        - not matched + insert: source rows absent from the target
          insert (columns matched by name),
        - ``schema_evolution``: source columns missing from the target
          APPEND to the schema (delta.schema.autoMerge role,
          MergeIntoCommandMeta's canMergeSchema path); existing rows
          read NULL for the new columns and the commit carries the
          metaData update — which is exactly what aborts concurrent
          writers through the conflict matrix.
        """
        if when_matched_update and when_matched_delete:
            raise ValueError("update and delete are mutually exclusive")
        src_renamed = source.select(
            *[Alias(col(n), f"src_{n}") for n in source.columns])
        lk = [col(n) for n in on]
        rk = [col(f"src_{n}") for n in on]

        # Delta contract: a target row may match at most one source
        # row. Validated HOST-side over the projected keys' PHYSICAL
        # lanes (values + null mask as separate columns, so NULL stays
        # distinct from NaN and from genuine zero, matching the old
        # group-by's Spark grouping semantics) — a vectorized duplicate
        # check instead of a traced group-by+filter plan, which cost
        # more cold trace/compile than the merge rewrite itself.
        import pandas as pd
        key_ht = self.session.execute(
            source.select(*[col(n) for n in on]).plan)
        key_cols = {}
        for i, c in enumerate(key_ht.columns):
            key_cols[f"v{i}"] = c.values
            key_cols[f"m{i}"] = c.mask
        if pd.DataFrame(key_cols).duplicated().any():
            raise ValueError(
                "MERGE: multiple source rows matched the same key")

        def build(read_v: int) -> List[dict]:
            target_df = self.to_df(version=read_v)
            schema = self.schema(read_v)
            meta_actions: List[dict] = []
            if schema_evolution:
                known = {n for n, _ in schema}
                new_cols = [(n, t) for n, t in source.schema
                            if n not in known]
                if new_cols:
                    schema = list(schema) + new_cols
                    meta_actions.append({"metaData": {
                        "schemaString": _schema_to_json(schema),
                        "partitionColumns": [],
                    }})
            else:
                extra = [n for n in source.columns
                         if n not in {s for s, _ in schema}]
                if extra:
                    raise ValueError(
                        f"MERGE source columns {extra} not in the "
                        "target schema (pass schema_evolution=True)")
            target_names = {n for n, _ in self.schema(read_v)}
            if when_matched_delete:
                matched_part = None  # matched rows vanish
            elif when_matched_update:
                joined = L.Join(target_df.plan, src_renamed.plan, lk, rk,
                                "inner")
                projected = []
                for name, t in schema:
                    default = col(name) if name in target_names \
                        else col(f"src_{name}")  # evolved col: source
                    e = when_matched_update.get(name, default)
                    if e.data_type(joined.schema) != t:
                        e = e.cast(t)
                    projected.append(Alias(e, name))
                matched_part = L.Project(joined, projected)
            else:
                matched_part = None

            if matched_part is None and not when_matched_delete:
                # no matched clause: EVERY target row survives
                # unchanged (insert-only merge)
                unmatched_target = target_df.plan
            else:
                # target rows with no source match survive unchanged
                unmatched_target = L.Join(target_df.plan,
                                          src_renamed.plan,
                                          lk, rk, "left_anti")
            if len(schema) > len(self.schema(read_v)):
                # evolved columns read NULL on surviving rows
                unmatched_target = L.Project(unmatched_target, [
                    col(n) if n in target_names else
                    Alias(lit(None, t), n) for n, t in schema])
            parts = [unmatched_target]
            if matched_part is not None:
                parts.append(matched_part)
            if when_not_matched_insert:
                unmatched_src = L.Join(
                    src_renamed.plan, target_df.plan, rk, lk, "left_anti")
                insert_cols = []
                src_cols = set(source.columns)
                for name, t in schema:
                    if name in src_cols:
                        e = col(f"src_{name}")
                        if e.data_type(unmatched_src.schema) != t:
                            e = e.cast(t)
                        insert_cols.append(Alias(e, name))
                    else:
                        insert_cols.append(Alias(lit(None, t), name))
                parts.append(L.Project(unmatched_src, insert_cols))
            plan = parts[0] if len(parts) == 1 else L.Union(*parts)
            table = self.session.execute(plan)
            return (meta_actions + self._remove_all_current(read_v),
                    self._write_files(table))
        return self._commit_rewrite(build, "MERGE")

    def optimize(self, zorder_by: Optional[Sequence[str]] = None) -> int:
        """OPTIMIZE [ZORDER BY cols]: rewrite the table as one file,
        z-order-clustered when columns are given (delta-lake z-order
        optimize write, GpuOptimisticTransaction + ZOrderRules)."""
        from ..expr.bitwise import InterleaveBits

        def build(read_v: int):
            df = self.to_df(version=read_v)
            if zorder_by:
                df = df.sort(InterleaveBits(
                    *[col(c) for c in zorder_by]))
            table = self.session.execute(df.plan)
            return (self._remove_all_current(read_v),
                    self._write_files(table))
        return self._commit_rewrite(
            build, f"OPTIMIZE{' ZORDER' if zorder_by else ''}")

    def vacuum(self, retention_sec: Optional[float] = None) -> List[str]:
        """Reclaim dead bytes: data files the log has tombstoned
        (committed, then removed — always reclaimable), plus crash
        orphans the log never referenced — staged ``.tmp`` files and
        promoted-but-uncommitted data files. Orphans younger than
        ``retention_sec`` (default ``srt.delta.vacuum.retentionSec``)
        survive, because they may belong to a commit in flight;
        staging files whose owner pid is dead are swept regardless."""
        if retention_sec is None:
            from ..conf import DELTA_VACUUM_RETENTION_SEC
            retention_sec = float(self._conf(DELTA_VACUUM_RETENTION_SEC))
        _, files = self.log.snapshot()
        live = set(files)
        # every path any commit ever added: present-but-not-live means
        # tombstoned; never-referenced means a crash orphan
        referenced = set()
        for v in self.log.versions():
            for a in self.log.read_actions(v):
                if "add" in a:
                    referenced.add(a["add"]["path"])
        now = time.time()
        removed: List[str] = []
        orphans = 0
        for f in sorted(os.listdir(self.path)):
            full = os.path.join(self.path, f)
            if f in live or not os.path.isfile(full):
                continue
            m = _TMP_RE.search(f)
            if m is not None:
                pid = int(m.group(1))
                if pid != os.getpid() and not _pid_alive(pid):
                    pass          # dead stager: reclaim regardless of age
                elif self._age(full, now) < retention_sec:
                    continue      # possibly mid-commit: retention guard
                orphans += 1
            elif f.endswith(".parquet"):
                if f not in referenced \
                        and self._age(full, now) < retention_sec:
                    continue      # promoted, commit may be in flight
                if f not in referenced:
                    orphans += 1
            else:
                continue
            try:
                os.unlink(full)
                removed.append(f)
            except OSError:
                pass
        swept_log = sweep_stale_tmp_files(self.log.log_dir)
        removed.extend(swept_log)
        from ..obs import events as _events
        _events.emit("DeltaOrphanSwept", table=self.path,
                     removed=len(removed), orphans=orphans,
                     logTmps=len(swept_log),
                     retentionSec=retention_sec)
        return removed

    @staticmethod
    def _age(path: str, now: float) -> float:
        try:
            return now - os.path.getmtime(path)
        except OSError:
            return float("inf")   # gone already: no need to guard it
