"""Bloom filter kernels + runtime join filtering.

Reference surface: GpuBloomFilterAggregate.scala /
GpuBloomFilterMightContain.scala (SURVEY §2.5 aggregate exprs) — Spark
injects a bloom-filter build over the small join side and a
might_contain probe over the big side (runtime row-level join
filtering). The TPU rebuild keeps the same double-hashing scheme
(k probe positions h1 + i*h2, Spark BloomFilterImpl's structure) but
stores the filter as a bool[num_bits] lane array instead of packed
int64 words: XLA scatter-set and gather are the natural TPU ops, there
is no atomic-OR to emulate, and num_bits stays modest (8-16 bits/key).

Two consumption paths:
- exec/join.py pre-filters inner/semi probe batches against a filter
  built from the materialized build side (the planner-injected runtime
  filter role),
- expr BloomFilterMightContain(child, filter) for direct use.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..columnar.vector import Column

DEFAULT_BITS_PER_KEY = 10
NUM_HASHES = 6
MIN_BITS = 1 << 10
MAX_BITS = 1 << 24


def choose_num_bits(num_keys: int,
                    bits_per_key: int = DEFAULT_BITS_PER_KEY) -> int:
    n = max(num_keys, 1) * bits_per_key
    bits = 1
    while bits < n:
        bits <<= 1
    return min(max(bits, MIN_BITS), MAX_BITS)


def _double_hash(key_cols: Sequence[Column]):
    """(h1, h2) 32-bit hash pair per row; h2 forced odd so the probe
    sequence cycles through distinct positions (classic double
    hashing)."""
    from ..expr import hashing as H
    cap = key_cols[0].capacity
    h1 = jnp.full((cap,), 0x9E3779B9, jnp.uint32)
    h2 = jnp.full((cap,), 0x85EBCA6B, jnp.uint32)
    for c in key_cols:
        h1 = H.murmur3_column(c, h1)
        h2 = H.murmur3_column(c, h2)
    return h1, h2 | jnp.uint32(1)


def _any_null(key_cols: Sequence[Column]):
    nn = jnp.ones(key_cols[0].capacity, jnp.bool_)
    for c in key_cols:
        nn = nn & c.validity
    return ~nn


def build_bloom(key_cols: Sequence[Column], live, num_bits: int
                ) -> jnp.ndarray:
    """bool[num_bits] filter over the live non-null key rows."""
    h1, h2 = _double_hash(key_cols)
    ok = live & ~_any_null(key_cols)
    bits = jnp.zeros(num_bits, jnp.bool_)
    mask = jnp.uint32(num_bits - 1)  # num_bits is a power of two
    for i in range(NUM_HASHES):
        pos = (h1 + jnp.uint32(i) * h2) & mask
        # scatter-max of the row predicate: excluded rows contribute
        # False (identity), so no slot-routing is needed for them
        bits = bits.at[pos].max(ok)
    return bits


def might_contain(bits: jnp.ndarray, key_cols: Sequence[Column]
                  ) -> jnp.ndarray:
    """bool[cap] probe: True = possibly present. Null keys return False
    (they cannot match an inner/semi join; expression-level semantics
    layer null handling on top)."""
    h1, h2 = _double_hash(key_cols)
    num_bits = bits.shape[0]
    mask = jnp.uint32(num_bits - 1)
    hit = jnp.ones(key_cols[0].capacity, jnp.bool_)
    for i in range(NUM_HASHES):
        pos = (h1 + jnp.uint32(i) * h2) & mask
        hit = hit & jnp.take(bits, pos)
    return hit & ~_any_null(key_cols)
