"""Batch kernels: the jittable cores of the physical operators.

This module is the TPU replacement for the cuDF kernel surface the
reference calls through JNI (SURVEY §2.9: Table.gather / sort / groupBy /
hashJoinGatherMaps / partition). Everything here is a pure function over
ColumnarBatch pytrees with **static capacities**, so each operator
pipeline compiles to one XLA program per capacity bucket:

- cardinality changes (filter/join/aggregate) keep capacity and move
  ``num_rows``; dead rows carry validity=False,
- sort is a chain of stable ``argsort`` passes over int64 "rank keys"
  (IEEE total-order transform for floats, packed big-endian words for
  strings) — radix-style multi-pass, the XLA-friendly formulation,
- group-by is sort-based: sort by keys, flag segment boundaries,
  scatter-reduce into a static-capacity state table (the reference uses
  cuDF hash groupby; sorting composes better with static shapes),
- join is hash-partition-free sort-merge: sort the build side by a
  64-bit combined key hash, binary-search probes into it, expand match
  lists with a searchsorted-on-cumsum gather, then verify true key
  equality (hash collisions only waste slots, never corrupt results).

Join/expansion outputs that exceed the static output capacity report the
true row count; the host-side retry framework (memory/retry.py) splits
the probe batch and re-runs — the TPU analogue of the reference's
SplitAndRetryOOM contract (RmmRapidsRetryIterator.scala).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar import dtypes as dt
from ..columnar.vector import (Column, ColumnVector, ColumnarBatch,
                               StringColumn, compaction_indices, live_mask,
                               round_pow2, rows_from_offsets)

# ---------------------------------------------------------------------------
# Filter
# ---------------------------------------------------------------------------


def compact(batch: ColumnarBatch, keep: jnp.ndarray) -> ColumnarBatch:
    """Keep rows where ``keep`` (restricted to live rows), preserving order."""
    keep = keep & batch.live_mask()
    n = jnp.sum(keep).astype(jnp.int32)
    idx = compaction_indices(keep)
    return batch.gather(idx, n, unique=True)


def filter_batch(batch: ColumnarBatch, cond: ColumnVector) -> ColumnarBatch:
    """SQL WHERE: keep rows where the predicate is true-and-not-null."""
    return compact(batch, cond.data & cond.validity)


def bucket_compact(batch: ColumnarBatch, key_cols, num_parts: int,
                   p) -> ColumnarBatch:
    """Rows whose key-hash bucket equals ``p``, compacted.

    The hash-bucketing primitive shared by sub-partition joins and the
    aggregate re-partition merge fallback: both sides of a join (or all
    partials of a merge) bucket with the SAME chain (seed 7 — distinct
    from the shuffle partitioner's seed 42 so shuffle and sub-partition
    bucketing stay uncorrelated), so equal keys always co-locate.
    """
    from ..expr import hashing as H
    h = jnp.full((batch.capacity,), 7, jnp.uint32)
    for c in key_cols:
        h = H.murmur3_column(c, h)
    bucket = (h % jnp.uint32(num_parts)).astype(jnp.int32)
    return compact(batch, (bucket == p) & batch.live_mask())


# ---------------------------------------------------------------------------
# Sort
# ---------------------------------------------------------------------------


def _rank_keys(col: Column) -> List[jnp.ndarray]:
    """Lower a column to sort-key arrays whose ascending order equals SQL
    value order (most significant first). Floats sort natively (XLA's
    total-order comparator puts NaN last, matching Spark once NaN and
    -0.0 are normalized); strings become packed big-endian uint64 words.
    No 64-bit bitcasts — see utils/bits.py."""
    if isinstance(col, StringColumn):
        padded = col.padded()
        cap, w = padded.shape
        words = []
        for b0 in range(0, w, 8):
            chunk = padded[:, b0:b0 + 8]
            if chunk.shape[1] < 8:
                chunk = jnp.pad(chunk, ((0, 0), (0, 8 - chunk.shape[1])))
            word = jnp.zeros(cap, jnp.uint64)
            for k in range(8):
                word = word | (chunk[:, k].astype(jnp.uint64) << (8 * (7 - k)))
            words.append(word)
        return words
    d = col.data
    if jnp.issubdtype(d.dtype, jnp.floating):
        d = jnp.where(d == 0.0, jnp.zeros((), d.dtype), d)
        d = jnp.where(jnp.isnan(d), jnp.full((), jnp.nan, d.dtype), d)
        return [d]
    if d.dtype == jnp.bool_:
        return [d.astype(jnp.int8)]
    return [d]


def sort_indices(columns: Sequence[Column], ascending: Sequence[bool],
                 nulls_first: Sequence[bool], live) -> jnp.ndarray:
    """Stable multi-key sort permutation; dead rows always sort last.

    Chain of stable argsorts from least-significant to most-significant
    key (classic LSD radix structure).
    """
    cap = columns[0].capacity if columns else live.shape[0]
    perm = jnp.arange(cap, dtype=jnp.int32)
    for col, asc, nf in reversed(list(zip(columns, ascending, nulls_first))):
        keys = _rank_keys(col)
        for key in reversed(keys):
            k = jnp.take(key, perm)
            perm = jnp.take(perm, jnp.argsort(k, stable=True, descending=not asc))
        # null placement pass (most significant within this key):
        # ascending argsort puts 0 first, so the "goes first" class maps to 0
        null_key = jnp.take(col.validity, perm) if nf else ~jnp.take(col.validity, perm)
        perm = jnp.take(perm, jnp.argsort(null_key.astype(jnp.int8), stable=True))
    dead = ~jnp.take(live, perm)
    perm = jnp.take(perm, jnp.argsort(dead.astype(jnp.int8), stable=True))
    return perm


def sort_batch(batch: ColumnarBatch, key_cols: Sequence[Column],
               ascending: Sequence[bool], nulls_first: Sequence[bool]) -> ColumnarBatch:
    perm = sort_indices(key_cols, ascending, nulls_first, batch.live_mask())
    return batch.gather(perm, batch.num_rows, unique=True)


# ---------------------------------------------------------------------------
# Group-by aggregate (sort-based)
# ---------------------------------------------------------------------------


def _adjacent_equal(col: Column) -> jnp.ndarray:
    """eq[i] = row i equals row i-1 (null-safe); eq[0] = False."""
    if isinstance(col, StringColumn):
        padded = col.padded()
        data_eq = jnp.all(padded[1:] == padded[:-1], axis=1) & \
            (col.lengths()[1:] == col.lengths()[:-1])
    else:
        d = col.data
        if jnp.issubdtype(d.dtype, jnp.floating):
            # NaN == NaN for grouping (Spark normalizes NaNs in group keys)
            nan_eq = jnp.isnan(d[1:]) & jnp.isnan(d[:-1])
            data_eq = (d[1:] == d[:-1]) | nan_eq
        else:
            data_eq = d[1:] == d[:-1]
    v = col.validity
    null_safe = (v[1:] == v[:-1]) & (~v[1:] | data_eq)
    return jnp.concatenate([jnp.zeros(1, jnp.bool_), null_safe])


def group_ids(sorted_keys: Sequence[Column], live) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(gid, num_groups, boundary) for key-sorted rows."""
    cap = live.shape[0]
    if not sorted_keys:
        # global aggregate: one group holding all live rows
        gid = jnp.zeros(cap, jnp.int32)
        boundary = jnp.zeros(cap, jnp.bool_).at[0].set(True) & live
        num_groups = jnp.minimum(jnp.sum(live), 1).astype(jnp.int32)
        return gid, num_groups, boundary
    eq_prev = jnp.ones(cap, jnp.bool_)
    for col in sorted_keys:
        eq_prev = eq_prev & _adjacent_equal(col)
    boundary = live & ~eq_prev
    boundary = jnp.where(jnp.arange(cap) == 0, live, boundary)
    gid = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).clip(0)
    num_groups = jnp.sum(boundary).astype(jnp.int32)
    return gid.astype(jnp.int32), num_groups, boundary


def _gather_rows(col: Column, idx: jnp.ndarray, valid) -> Column:
    """Permutation/compaction row gather (each source row used at most
    once among valid slots) — string/list columns keep tight buffers."""
    from ..columnar.nested import ListColumn
    if isinstance(col, (StringColumn, ListColumn)):
        return col.gather(idx, valid, unique=True)
    return col.gather(idx, valid)


def _keys_eq_pairs(col: Column, ia: jnp.ndarray, ib: jnp.ndarray
                   ) -> jnp.ndarray:
    """Null-safe key equality of row pairs (ia[k], ib[k]) without
    gathering the column: strings compare via their packed big-endian
    words (dense take, no byte repack), floats collapse NaNs so
    NaN == NaN for grouping (Spark normalizes NaN group keys)."""
    va = jnp.take(col.validity, ia)
    vb = jnp.take(col.validity, ib)
    if isinstance(col, StringColumn):
        data_eq = jnp.take(col.lengths(), ia) == jnp.take(col.lengths(), ib)
        for w in _rank_keys(col):
            data_eq = data_eq & (jnp.take(w, ia) == jnp.take(w, ib))
    else:
        da = jnp.take(col.data, ia)
        db = jnp.take(col.data, ib)
        if jnp.issubdtype(da.dtype, jnp.floating):
            data_eq = (da == db) | (jnp.isnan(da) & jnp.isnan(db))
        else:
            data_eq = da == db
    return (va == vb) & (~va | data_eq)


def _group_ids_from_eq(eq_prev: jnp.ndarray, live) -> Tuple:
    """(gid, num_groups, boundary) from a rows-equal-previous mask over
    key-sorted rows."""
    cap = live.shape[0]
    boundary = live & ~eq_prev
    boundary = jnp.where(jnp.arange(cap) == 0, live, boundary)
    gid = (jnp.cumsum(boundary.astype(jnp.int32)) - 1).clip(0)
    num_groups = jnp.sum(boundary).astype(jnp.int32)
    return gid.astype(jnp.int32), num_groups, boundary


def _key_batch(key_cols, key_rows, cap, num_groups) -> ColumnarBatch:
    klm = live_mask(cap, num_groups)
    key_out = [_gather_rows(c, key_rows, klm) for c in key_cols]
    return ColumnarBatch(
        key_out, [f"k{i}" for i in range(len(key_out))], num_groups)


def _prelude_exact(batch: ColumnarBatch, key_cols: Sequence[Column]):
    """Sort-based grouping (the always-correct fallback): rank-chain
    sort, adjacent-equality boundaries, one key gather per group."""
    live = batch.live_mask()
    cap = batch.capacity
    perm = sort_indices(key_cols, [True] * len(key_cols),
                        [True] * len(key_cols), live)
    live_s = jnp.take(live, perm)
    prev = jnp.concatenate([perm[:1], perm[:-1]])
    eq = jnp.ones(cap, jnp.bool_)
    for c in key_cols:
        eq = eq & _keys_eq_pairs(c, perm, prev)
    eq = eq & (jnp.arange(cap) != 0)
    gid, num_groups, boundary = _group_ids_from_eq(eq, live_s)
    # scratch slot for dead rows; num_groups == cap implies no dead rows
    gid_safe = jnp.where(live_s, gid,
                         jnp.minimum(num_groups, cap - 1).astype(jnp.int32))
    key_rows = jnp.take(perm, compaction_indices(boundary))
    return perm, live_s, gid_safe, num_groups, \
        _key_batch(key_cols, key_rows, cap, num_groups)


# multiplicative mixers for the claim rounds (odd 64-bit constants from
# splitmix64/xxhash); one claim table per round
_CLAIM_MIXERS = (0x9E3779B97F4A7C15, 0xC2B2AE3D27D4EB4F,
                 0x165667B19E3779F9, 0x27D4EB2F165667C5)


def _prelude_fast(batch: ColumnarBatch, key_cols: Sequence[Column]):
    """Sort-free hash-claim grouping.

    Rows claim hash-table slots by scatter-min of a 64-bit key hash
    (one table per round; losers retry under a fresh mixer). Winners of
    one slot share a gid. Exactness is enforced by comparing every
    row's TRUE key against its slot representative — a 64-bit collision
    or an unclaimed row flips ``ok`` and the caller falls back to the
    sort path. Rows stay in original order (perm = iota), so this is
    only valid for scatter-style aggregates (see needs_sorted_groups).

    This replaces cuDF's iterative open-addressing hash groupby
    (GpuAggregateExec.scala:175's cudf groupBy) with a bounded-round,
    branch-free formulation XLA can fuse: every round is a scatter-min
    + gathers over static shapes.
    """
    from ..expr import hashing as H
    live = batch.live_mask()
    cap = batch.capacity
    h1 = jnp.full((cap,), 0x3C6EF372, jnp.uint32)
    h2 = jnp.full((cap,), 0xA54FF53A, jnp.uint32)
    for c in key_cols:
        h1 = H.murmur3_column(c, h1)
        h2 = H.murmur3_column(c, h2)
        # murmur3_column leaves h unchanged on null rows; fold the
        # validity bit in so null patterns hash apart from values
        h1 = jnp.where(c.validity, h1, h1 ^ jnp.uint32(0x9E3779B9))
        h2 = jnp.where(c.validity, h2,
                       h2 * jnp.uint32(2654435761) + jnp.uint32(1))
    h = (h1.astype(jnp.uint64) << 32) | h2.astype(jnp.uint64)
    INF = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    h = jnp.minimum(h, INF - 1)  # INF is the empty-slot sentinel
    T = round_pow2(cap)
    log2T = T.bit_length() - 1
    arange = jnp.arange(cap, dtype=jnp.int32)

    def one_round(mix, state):
        unresolved, gid, key_rows, offset = state
        slot = ((h * jnp.uint64(mix)) >> jnp.uint64(64 - log2T)
                ).astype(jnp.int32)
        tbl = jnp.full(T, INF, jnp.uint64).at[slot].min(
            jnp.where(unresolved, h, INF))
        won = unresolved & (jnp.take(tbl, slot) == h)
        occ = tbl != INF
        slot_gid = offset + jnp.cumsum(occ.astype(jnp.int32)) - 1
        rep_tbl = jnp.full(T, cap, jnp.int32).at[slot].min(
            jnp.where(won, arange, cap))
        gid = jnp.where(won, jnp.take(slot_gid, slot), gid)
        key_rows = key_rows.at[jnp.where(occ, slot_gid, cap)].set(
            rep_tbl, mode="drop")
        offset = offset + jnp.sum(occ).astype(jnp.int32)
        return unresolved & ~won, gid, key_rows, offset

    state = one_round(_CLAIM_MIXERS[0],
                      (live, jnp.zeros(cap, jnp.int32),
                       jnp.zeros(cap, jnp.int32), jnp.int32(0)))

    def more_rounds(s):
        for mix in _CLAIM_MIXERS[1:]:
            s = one_round(mix, s)
        return s

    # contested slots are the exception (low-cardinality groupings
    # resolve fully in round 1): skip rounds 2..R when nothing is left
    state = jax.lax.cond(jnp.any(state[0]), more_rounds, lambda s: s,
                         state)
    unresolved, gid, key_rows, num_groups = state
    # exactness check: every live row's true key must equal its slot
    # representative's (collisions merge distinct keys; catch them here)
    rep = jnp.take(key_rows, jnp.clip(gid, 0, cap - 1))
    eq = jnp.ones(cap, jnp.bool_)
    for c in key_cols:
        eq = eq & _keys_eq_pairs(c, arange, rep)
    ok = (~jnp.any(unresolved)) & (~jnp.any(live & ~eq))
    gid_safe = jnp.where(live, gid,
                         jnp.minimum(num_groups, cap - 1).astype(jnp.int32))
    return ok, (arange, live, gid_safe, num_groups,
                _key_batch(key_cols, key_rows, cap, num_groups))


def _use_hash_grouping(batch: ColumnarBatch, key_cols, agg_fns) -> bool:
    """Static (trace-time) gate for the hash-claim fast path: needs
    grouping keys, scatter-safe aggregates, hashable key types and a
    batch big enough for the claim table to pay for itself."""
    return bool(key_cols) and batch.capacity >= 1024 and \
        all(not getattr(fn, "needs_sorted_groups", False)
            for fn in agg_fns) and \
        all(isinstance(c, (StringColumn, ColumnVector)) for c in key_cols)


def _sorted_group_prelude(batch: ColumnarBatch, key_cols: Sequence[Column],
                          allow_hash: bool = False):
    """Sort-path grouping machinery for update and merge passes (the
    hash-claim fast path is dispatched by group_aggregate/group_merge
    directly so they can also skip the input gathers; ``allow_hash`` is
    kept for signature compatibility and ignored).

    Returns (perm, live_s, gid_safe, num_groups, key_batch). Dead rows
    are routed to a scratch gid just past the live groups so their
    (zeroed) values never pollute a real group. Order-sensitive
    aggregates recover each row's original position from ``perm``.
    """
    del allow_hash
    live = batch.live_mask()
    cap = batch.capacity
    if not key_cols:
        # global aggregate: live rows are a prefix already — no sort
        gid, num_groups, _ = group_ids([], live)
        gid_safe = jnp.where(
            live, gid, jnp.minimum(num_groups,
                                   max(cap - 1, 0)).astype(jnp.int32))
        return (jnp.arange(cap, dtype=jnp.int32), live, gid_safe,
                num_groups, ColumnarBatch([], [], num_groups))
    return _prelude_exact(batch, key_cols)


def group_aggregate(batch: ColumnarBatch, key_cols: Sequence[Column],
                    agg_inputs: Sequence[Optional[Column]], agg_fns: Sequence,
                    row_offset=0) -> Tuple[ColumnarBatch, List[dict]]:
    """Sort-based group-by update pass: raw rows -> per-group partial
    states. ``row_offset`` is the stream-global position of this batch's
    row 0, consumed by order-sensitive aggregates (first/last)."""
    cap = batch.capacity

    def body(prelude, fast: bool):
        perm, live_s, gid, num_groups, key_batch = prelude
        states = []
        for inp, fn in zip(agg_inputs, agg_fns):
            if inp is None:
                col_s = None
            elif fast:
                # hash path: rows untouched, perm is the identity —
                # skip the (pure-overhead) identity gathers
                col_s = inp
            else:
                col_s = _gather_rows(inp, perm, live_s)
            states.append(fn.update(gid, col_s, cap, live_s,
                                    row_offset=row_offset,
                                    perm=None if fast else perm))
        return key_batch, states

    if not _use_hash_grouping(batch, key_cols, agg_fns):
        return body(_sorted_group_prelude(batch, key_cols, False), False)
    ok, fast_prelude = _prelude_fast(batch, key_cols)
    return jax.lax.cond(
        ok, lambda _: body(fast_prelude, True),
        lambda _: body(_prelude_exact(batch, key_cols), False), None)


def pallas_group_fns_ok(agg_inputs: Sequence[Optional[Column]],
                        agg_fns: Sequence) -> bool:
    """Static gate for the MXU one-hot grouped lane: sum-decomposable
    aggregates only (the one-hot matmul is a segmented SUM), float
    inputs for sum/avg (integer sums must stay exact int64 — the f32
    tile arithmetic may drop low bits, the deviation the reference
    ships behind variableFloatAgg for floats ONLY)."""
    from ..expr import aggregates as Agg
    lanes = 0
    for inp, fn in zip(agg_inputs, agg_fns):
        if isinstance(fn, (Agg.Sum, Agg.Average)):
            if type(fn) not in (Agg.Sum, Agg.Average):
                return False  # subclasses may widen state
            if inp is None or inp.dtype not in (dt.FLOAT32, dt.FLOAT64) \
                    or not isinstance(inp, ColumnVector):
                return False
            lanes += 2  # value + count
        elif isinstance(fn, Agg.CountStar) and type(fn) is Agg.CountStar:
            lanes += 1
        elif isinstance(fn, Agg.Count) and type(fn) is Agg.Count:
            if inp is None:
                return False
            lanes += 1
        else:
            return False
    # one accumulator lane column per value column in the kernel —
    # wider aggregations degrade to the XLA path, never crash
    return lanes <= 128


#: one PallasCapacityFallback event per process: the capacity gate is
#: static per compiled program, so the event would otherwise repeat for
#: every trace of every over-capacity shape
_CAP_FALLBACK_WARNED = [False]


def group_aggregate_pallas(batch: ColumnarBatch, key_cols: Sequence[Column],
                           agg_inputs: Sequence[Optional[Column]],
                           agg_fns: Sequence, row_offset=0,
                           num_buckets: int = 1024,
                           interpret: Optional[bool] = None,
                           max_capacity: int = 1 << 24,
                           ) -> Tuple[ColumnarBatch, List[dict], jnp.ndarray]:
    """Grouped update pass with the pallas one-hot MXU lane.

    Same contract as :func:`group_aggregate` plus a traced ``used``
    flag. When the hash-claim prelude resolves exactly AND the batch
    has at most ``num_buckets`` groups, per-bucket partials come from
    ``ops/pallas_kernels.tile_group_reduce`` (a (tile, B) one-hot
    contracted on the MXU — no scatters); otherwise the stock
    scatter/sort path runs inside the same ``lax.cond``. Mirrors the
    reference's device hash groupby being THE aggregate path
    (GpuAggregateExec.scala:175) rather than a special case.

    Callers gate with :func:`pallas_group_fns_ok` — this function
    assumes every aggregate is sum-decomposable.
    """
    cap = batch.capacity

    def stock(prelude, fast: bool):
        perm, live_s, gid, num_groups, key_batch = prelude
        states = []
        for inp, fn in zip(agg_inputs, agg_fns):
            if inp is None:
                col_s = None
            elif fast:
                col_s = inp
            else:
                col_s = _gather_rows(inp, perm, live_s)
            states.append(fn.update(gid, col_s, cap, live_s,
                                    row_offset=row_offset,
                                    perm=None if fast else perm))
        return key_batch, states

    # counts accumulate in float32 lanes on the MXU: a group can hold
    # at most `cap` rows, and float32 represents integers exactly only
    # below 2^24 — batches at or past the ceiling must take the stock
    # integer path or Count/CountStar drift. The ceiling is
    # conf-controlled (srt.exec.pallas.groupAgg.maxCapacity); raising
    # it past 2^24 trades Count exactness for MXU throughput.
    cap_ok = cap < int(max_capacity)
    if not (_use_hash_grouping(batch, key_cols, agg_fns)
            and cap >= num_buckets
            and cap_ok
            and pallas_group_fns_ok(agg_inputs, agg_fns)):
        if (not cap_ok and not _CAP_FALLBACK_WARNED[0]
                and _use_hash_grouping(batch, key_cols, agg_fns)
                and cap >= num_buckets
                and pallas_group_fns_ok(agg_inputs, agg_fns)):
            # only the capacity ceiling blocked the MXU lane: surface
            # it once so fusion's terminal-stage choice is observable
            _CAP_FALLBACK_WARNED[0] = True
            from ..obs import events as _events
            _events.emit("PallasCapacityFallback", scope="pallas",
                         capacity=int(cap),
                         max_capacity=int(max_capacity))
        kb, st = group_aggregate(batch, key_cols, agg_inputs, agg_fns,
                                 row_offset)
        return kb, st, jnp.bool_(False)

    from ..expr import aggregates as Agg
    ok, fast_prelude = _prelude_fast(batch, key_cols)
    _, live, gid, num_groups, key_batch = fast_prelude
    small = ok & (num_groups <= num_buckets)

    def pallas_branch(_):
        from . import pallas_kernels as PKn
        # dead rows already land on the scratch gid (== num_groups,
        # itself < num_buckets when this branch is taken) so their
        # zeroed values accumulate into a never-live bucket
        gid_c = jnp.minimum(gid, num_buckets - 1)
        values = []
        for inp, fn in zip(agg_inputs, agg_fns):
            if isinstance(fn, (Agg.Sum, Agg.Average)):
                m = live & inp.validity
                values.append(jnp.where(m, inp.data, jnp.zeros((), inp.data.dtype)))
                values.append(m.astype(jnp.float32))
            elif isinstance(fn, Agg.CountStar):
                values.append(live.astype(jnp.float32))
            else:  # Count
                values.append((live & inp.validity).astype(jnp.float32))
        outs = PKn.tile_group_reduce(gid_c, values,
                                     num_buckets=num_buckets,
                                     interpret=interpret)
        pad = cap - num_buckets

        def to_cap(arr, dtype):
            a = arr.astype(dtype)
            return a if pad == 0 else jnp.pad(a, (0, pad))
        states = []
        i = 0
        for inp, fn in zip(agg_inputs, agg_fns):
            if isinstance(fn, (Agg.Sum, Agg.Average)):
                states.append({"sum": to_cap(outs[i], jnp.float64),
                               "count": to_cap(outs[i + 1], jnp.int64)})
                i += 2
            else:
                states.append({"count": to_cap(outs[i], jnp.int64)})
                i += 1
        return key_batch, states

    def fallback(_):
        return jax.lax.cond(
            ok, lambda __: stock(fast_prelude, True),
            lambda __: stock(_prelude_exact(batch, key_cols), False), None)

    kb, st = jax.lax.cond(small, pallas_branch, fallback, None)
    return kb, st, small


def group_merge(batch: ColumnarBatch, key_cols: Sequence[Column],
                agg_states: Sequence[dict], agg_fns: Sequence
                ) -> Tuple[ColumnarBatch, List[dict], jnp.ndarray]:
    """Merge partial aggregation states (the reference's merge pass,
    GpuMergeAggregateIterator GpuAggregateExec.scala:711).

    ``agg_states[i]`` is a dict of state arrays (capacity-length) aligned
    with ``batch`` rows; returns merged (key_batch, states, num_groups).
    Dead rows merge into the scratch gid (see _sorted_group_prelude), so
    their zeroed states cannot corrupt the last real group.
    """
    cap = batch.capacity

    def body(prelude, fast: bool):
        perm, live_s, gid, num_groups, key_batch = prelude

        def _sort_state(v):
            from ..columnar.nested import ListColumn
            if fast:
                return v  # identity perm: states already row-aligned
            if isinstance(v, (StringColumn, ListColumn)):
                return v.gather(perm, live_s, unique=True)
            return jnp.take(v, perm, axis=0)
        merged = []
        for states, fn in zip(agg_states, agg_fns):
            sorted_states = {k: _sort_state(v) for k, v in states.items()}
            merged.append(fn.merge(gid, sorted_states, cap))
        return key_batch, merged, num_groups

    if not _use_hash_grouping(batch, key_cols, agg_fns):
        return body(_sorted_group_prelude(batch, key_cols, False), False)
    ok, fast_prelude = _prelude_fast(batch, key_cols)
    return jax.lax.cond(
        ok, lambda _: body(fast_prelude, True),
        lambda _: body(_prelude_exact(batch, key_cols), False), None)


# ---------------------------------------------------------------------------
# Join (sort-merge on 64-bit combined key hash + verification)
# ---------------------------------------------------------------------------


def _join_key_hash(cols: Sequence[Column], null_sentinel: int) -> jnp.ndarray:
    """64-bit combined hash of the key columns; rows with any null key get
    the given sentinel. Probe and build use *different* null sentinels so
    null keys never pair up (SQL join semantics); a real hash landing on a
    sentinel only creates spurious candidates that the equality
    verification pass rejects."""
    from ..expr import hashing as H
    cap = cols[0].capacity
    h1 = jnp.full((cap,), 42, jnp.uint32)
    h2 = jnp.full((cap,), 0xDEADBEEF, jnp.uint32)
    for c in cols:
        h1 = H.murmur3_column(c, h1)
        h2 = H.murmur3_column(c, h2)
    h = (h1.astype(jnp.uint64) << 32) | h2.astype(jnp.uint64)
    any_null = jnp.zeros(cap, jnp.bool_)
    for c in cols:
        any_null = any_null | ~c.validity
    h_i64 = h.astype(jnp.int64)  # wrapping convert, not bitcast (TPU-legal)
    return jnp.where(any_null, jnp.int64(null_sentinel), h_i64)


def _keys_equal(a_cols: Sequence[Column], a_idx, b_cols: Sequence[Column],
                b_idx, null_safe: bool = False) -> jnp.ndarray:
    """True key equality for candidate pairs (collision verification).

    Default is JOIN equality (null matches nothing). ``null_safe=True``
    gives grouping equality — null == null, NaN == NaN — for callers
    comparing partition/group keys (e.g. the running-window carried-
    state continuation check)."""
    ok = jnp.ones(a_idx.shape[0], jnp.bool_)
    for ca, cb in zip(a_cols, b_cols):
        va = jnp.take(ca.validity, a_idx)
        vb = jnp.take(cb.validity, b_idx)
        if isinstance(ca, StringColumn):
            pa = ca.padded()
            pb = cb.padded()
            w = max(ca.pad_bucket, cb.pad_bucket)
            if ca.pad_bucket < w:
                pa = jnp.pad(pa, ((0, 0), (0, w - ca.pad_bucket)))
            if cb.pad_bucket < w:
                pb = jnp.pad(pb, ((0, 0), (0, w - cb.pad_bucket)))
            eq = jnp.all(jnp.take(pa, a_idx, axis=0) == jnp.take(pb, b_idx, axis=0),
                         axis=1)
        else:
            da = jnp.take(ca.data, a_idx)
            db = jnp.take(cb.data, b_idx)
            if da.dtype != db.dtype:
                tgt = jnp.promote_types(da.dtype, db.dtype)
                da = da.astype(tgt)
                db = db.astype(tgt)
            eq = da == db
            if null_safe and jnp.issubdtype(da.dtype, jnp.floating):
                eq = eq | (jnp.isnan(da) & jnp.isnan(db))
        if null_safe:
            ok = ok & ((va & vb & eq) | (~va & ~vb))
        else:
            ok = ok & va & vb & eq
    return ok


def join_gather_maps(probe_keys: Sequence[Column], build_keys: Sequence[Column],
                     probe_live, build_live, out_capacity: int):
    """Compute (probe_idx, build_idx, pair_valid, total_pairs) gather maps
    for matching pairs — the cuDF ``hashJoinGatherMaps`` equivalent.

    total_pairs is the true match count; if it exceeds out_capacity the
    caller must split and retry.
    """
    imax = jnp.iinfo(jnp.int64).max
    cap_b = build_keys[0].capacity
    bh = _join_key_hash(build_keys, imax - 2)
    bh = jnp.where(build_live, bh, jnp.int64(imax))
    order = jnp.argsort(bh, stable=True).astype(jnp.int32)
    bh_sorted = jnp.take(bh, order)

    ph = _join_key_hash(probe_keys, imax - 3)
    ph = jnp.where(probe_live, ph, jnp.int64(imax - 1))
    lo = jnp.searchsorted(bh_sorted, ph, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(bh_sorted, ph, side="right").astype(jnp.int32)
    counts = jnp.where(probe_live, hi - lo, 0)

    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    total_cand = offsets[-1]
    pos = jnp.arange(out_capacity, dtype=jnp.int32)
    probe_row = rows_from_offsets(offsets[:-1], counts, out_capacity)
    within = pos - jnp.take(offsets, probe_row)
    build_sorted_pos = jnp.take(lo, probe_row) + within
    build_row = jnp.take(order, jnp.clip(build_sorted_pos, 0, cap_b - 1))
    cand_valid = pos < total_cand

    true_eq = _keys_equal(probe_keys, probe_row, build_keys, build_row)
    pair_valid = cand_valid & true_eq
    return probe_row, build_row, pair_valid, total_cand, counts


def inner_join(probe: ColumnarBatch, build: ColumnarBatch,
               probe_keys: Sequence[Column], build_keys: Sequence[Column],
               out_capacity: int) -> Tuple[ColumnarBatch, jnp.ndarray]:
    """Inner join; returns (joined_batch, candidate_total) — the candidate
    total lets the host detect output-capacity overflow."""
    p_idx, b_idx, pair_valid, total_cand, _ = join_gather_maps(
        probe_keys, build_keys, probe.live_mask(), build.live_mask(), out_capacity)
    compact_idx = compaction_indices(pair_valid)
    n_out = jnp.sum(pair_valid).astype(jnp.int32)
    p_take = jnp.take(p_idx, compact_idx)
    b_take = jnp.take(b_idx, compact_idx)
    valid = live_mask(out_capacity, n_out)
    out_cols = [c.gather(p_take, valid) for c in probe.columns] + \
        [c.gather(b_take, valid) for c in build.columns]
    out_names = probe.names + build.names
    return ColumnarBatch(out_cols, out_names, n_out), total_cand


def left_join(probe: ColumnarBatch, build: ColumnarBatch,
              probe_keys: Sequence[Column], build_keys: Sequence[Column],
              out_capacity: int) -> Tuple[ColumnarBatch, jnp.ndarray]:
    """Left outer join with probe as the preserved/stream side.

    The returned size scalar is max(candidate window, true output rows
    incl. unmatched probe rows) — if it exceeds out_capacity the caller
    must retry bigger (candidates past the window are lost AND output
    rows past capacity are dropped, so both bound the retry)."""
    cap_p = probe.capacity
    p_idx, b_idx, pair_valid, total_cand, _ = join_gather_maps(
        probe_keys, build_keys, probe.live_mask(), build.live_mask(), out_capacity)
    # per-probe-row true match count
    match_per_probe = jnp.zeros(cap_p, jnp.int32).at[p_idx].add(
        pair_valid.astype(jnp.int32))
    unmatched = probe.live_mask() & (match_per_probe == 0)
    n_pairs = jnp.sum(pair_valid).astype(jnp.int32)
    n_unmatched = jnp.sum(unmatched).astype(jnp.int32)
    n_out = n_pairs + n_unmatched

    pair_order = compaction_indices(pair_valid)
    un_order = compaction_indices(unmatched)
    pos = jnp.arange(out_capacity, dtype=jnp.int32)
    from_pairs = pos < n_pairs
    p_take = jnp.where(from_pairs,
                       jnp.take(p_idx, jnp.take(pair_order, jnp.clip(pos, 0, out_capacity - 1))),
                       jnp.take(un_order, jnp.clip(pos - n_pairs, 0, cap_p - 1)))
    b_take = jnp.take(b_idx, jnp.take(pair_order, jnp.clip(pos, 0, out_capacity - 1)))
    valid = live_mask(out_capacity, n_out)
    build_valid = valid & from_pairs
    out_cols = [c.gather(p_take, valid) for c in probe.columns] + \
        [c.gather(b_take, build_valid) for c in build.columns]
    required = jnp.maximum(total_cand, n_out)
    return ColumnarBatch(out_cols, probe.names + build.names, n_out), required


def semi_anti_join(probe: ColumnarBatch, build_keys: Sequence[Column],
                   probe_keys: Sequence[Column], build_live,
                   anti: bool, scratch_capacity: Optional[int] = None
                   ) -> Tuple[ColumnarBatch, jnp.ndarray]:
    """Left semi / anti join — output rows come only from the probe side
    (no expansion), but the *candidate window* can still overflow when
    build keys are heavily duplicated. total_cand is returned so the host
    retries with a larger scratch_capacity when total_cand exceeds it."""
    cap_p = probe.capacity
    scratch = scratch_capacity or cap_p
    p_idx, b_idx, pair_valid, total_cand, counts = join_gather_maps(
        probe_keys, build_keys, probe.live_mask(), build_live, scratch)
    matched = jnp.zeros(cap_p, jnp.bool_).at[p_idx].max(pair_valid)
    keep = probe.live_mask() & (~matched if anti else matched)
    return compact(probe, keep), total_cand


# ---------------------------------------------------------------------------
# Concat / limit / slice
# ---------------------------------------------------------------------------


def concat_columns(cols: Sequence[Column], caps: Sequence[int], counts,
                   out_capacity: int) -> Column:
    """Concatenate the live prefixes of columns into one column."""
    if isinstance(cols[0], StringColumn):
        return _concat_strings(cols, caps, counts, out_capacity)
    from ..columnar.nested import ListColumn
    if isinstance(cols[0], ListColumn):
        return _concat_lists(cols, caps, counts, out_capacity)
    from ..columnar.decimal128 import Decimal128Column
    if isinstance(cols[0], Decimal128Column):
        hi = jnp.zeros(out_capacity, jnp.int64)
        lo = jnp.zeros(out_capacity, jnp.uint64)
        validity = jnp.zeros(out_capacity, jnp.bool_)
        offset = jnp.int32(0)
        for c, cap, n in zip(cols, caps, counts):
            idx = jnp.arange(out_capacity, dtype=jnp.int32) - offset
            in_range = (idx >= 0) & (idx < n)
            take = jnp.clip(idx, 0, cap - 1)
            hi = jnp.where(in_range, jnp.take(c.hi, take), hi)
            lo = jnp.where(in_range, jnp.take(c.lo, take), lo)
            validity = jnp.where(in_range, jnp.take(c.validity, take),
                                 validity)
            offset = offset + (n.astype(jnp.int32)
                               if hasattr(n, "astype") else n)
        return Decimal128Column(hi, lo, validity, cols[0].dtype)
    phys = cols[0].data.dtype
    data = jnp.zeros(out_capacity, phys)
    validity = jnp.zeros(out_capacity, jnp.bool_)
    offset = jnp.int32(0)
    for c, cap, n in zip(cols, caps, counts):
        idx = jnp.arange(out_capacity, dtype=jnp.int32) - offset
        in_range = (idx >= 0) & (idx < n)
        take = jnp.clip(idx, 0, cap - 1)
        data = jnp.where(in_range, jnp.take(c.data, take), data)
        validity = jnp.where(in_range, jnp.take(c.validity, take), validity)
        offset = offset + n.astype(jnp.int32) if hasattr(n, "astype") else offset + n
    return ColumnVector(data, validity, cols[0].dtype)


def _concat_lists(cols, caps, counts, out_capacity: int):
    """Concatenate COMPACT ListColumns (elements stored in row order
    with no gaps — the layout every builder in this codebase produces):
    children concatenate as columns, row offsets relabel by cumsum of
    gathered lengths. Dead/invalid rows must carry zero-length extents,
    the same invariant StringColumn concat relies on."""
    from ..columnar.nested import ListColumn
    lens = jnp.zeros(out_capacity, jnp.int32)
    validity = jnp.zeros(out_capacity, jnp.bool_)
    offset = jnp.int32(0)
    for c, cap, n in zip(cols, caps, counts):
        idx = jnp.arange(out_capacity, dtype=jnp.int32) - offset
        nn = n.astype(jnp.int32) if hasattr(n, "astype") else jnp.int32(n)
        in_range = (idx >= 0) & (idx < nn)
        take = jnp.clip(idx, 0, cap - 1)
        lens = jnp.where(in_range, jnp.take(c.lengths(), take), lens)
        validity = jnp.where(in_range, jnp.take(c.validity, take),
                             validity)
        offset = offset + nn
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    child_cap = sum(c.child_capacity for c in cols)
    elem_counts = [c.offsets[c.capacity] for c in cols]
    child = concat_columns([c.child for c in cols],
                           [c.child_capacity for c in cols],
                           elem_counts, child_cap)
    return ListColumn(offsets, child, validity,
                      cols[0].dtype.element_type, cols[0].pad_bucket)


def _concat_strings(cols: Sequence[StringColumn], caps, counts,
                    out_capacity: int) -> StringColumn:
    lens = jnp.zeros(out_capacity, jnp.int32)
    validity = jnp.zeros(out_capacity, jnp.bool_)
    offset = jnp.int32(0)
    for c, cap, n in zip(cols, caps, counts):
        idx = jnp.arange(out_capacity, dtype=jnp.int32) - offset
        in_range = (idx >= 0) & (idx < n)
        take = jnp.clip(idx, 0, cap - 1)
        lens = jnp.where(in_range, jnp.take(c.lengths(), take), lens)
        validity = jnp.where(in_range, jnp.take(c.validity, take), validity)
        offset = offset + (n.astype(jnp.int32) if hasattr(n, "astype") else jnp.int32(n))
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)])
    char_cap = sum(c.char_capacity for c in cols)
    pos = jnp.arange(char_cap, dtype=jnp.int32)
    row_c = rows_from_offsets(offsets[:-1], lens, char_cap)
    within = pos - jnp.take(offsets, row_c)
    # map row -> source column and source row
    byte = jnp.zeros(char_cap, jnp.uint8)
    offset = jnp.int32(0)
    for c, cap, n in zip(cols, caps, counts):
        nn = n.astype(jnp.int32) if hasattr(n, "astype") else jnp.int32(n)
        src_row = row_c - offset
        mine = (src_row >= 0) & (src_row < nn)
        src_row_c = jnp.clip(src_row, 0, cap - 1)
        src = jnp.take(c.offsets[:-1], src_row_c) + within
        b = jnp.take(c.chars, jnp.clip(src, 0, c.char_capacity - 1))
        byte = jnp.where(mine, b, byte)
        offset = offset + nn
    total = offsets[out_capacity]
    chars = jnp.where(pos < total, byte, jnp.zeros((), jnp.uint8))
    pad = max(c.pad_bucket for c in cols)
    return StringColumn(offsets, chars, validity, pad_bucket=pad)


def _concat_batches_impl(batches: Sequence[ColumnarBatch],
                         out_capacity: int) -> ColumnarBatch:
    counts = [b.num_rows for b in batches]
    total = sum(int(c) if isinstance(c, int) else c for c in counts)
    caps = [b.capacity for b in batches]
    names = batches[0].names
    out_cols = []
    for ci in range(len(names)):
        cols = [b.columns[ci] for b in batches]
        out_cols.append(concat_columns(cols, caps, counts, out_capacity))
    return ColumnarBatch(out_cols, names, total)


# one jit wrapper per output capacity; jax's trace cache inside each
# wrapper keys on the input pytree structure (schemas, per-batch
# capacities), with num_rows as TRACED leaves so varying live counts
# never retrace. Without this every concat dispatched hundreds of tiny
# eager XLA ops per call — the dominant cost of warm group-by queries.
_CONCAT_JIT: dict = {}


def concat_batches(batches: Sequence[ColumnarBatch],
                   out_capacity: int) -> ColumnarBatch:
    """Concatenate batches (same schema) into one batch of out_capacity."""
    fn = _CONCAT_JIT.get(out_capacity)
    if fn is None:
        fn = jax.jit(lambda bs, cap=out_capacity:
                     _concat_batches_impl(bs, cap))
        _CONCAT_JIT[out_capacity] = fn
    return fn(list(batches))


_COMPACT_JIT: dict = {}


def compact_for_transfer(batch: ColumnarBatch,
                         slack: int = 4) -> ColumnarBatch:
    """Shrink a sparse batch to a small power-of-two capacity before it
    crosses a serialization/transfer boundary (shuffle write, broadcast,
    collect). Operators keep their input's static capacity, so a
    partial aggregate of a 512k-row batch emits a 512k-capacity batch
    with a handful of live groups — serializing THAT pulls the whole
    padded capacity off the device. Only compacts when it saves at
    least ``slack``×; costs one host sync of the (scalar) row count."""
    from ..columnar.vector import choose_capacity
    n = int(batch.num_rows)
    cap = choose_capacity(n)
    if cap * slack > batch.capacity:
        return batch
    return repack_to(batch, cap)


def repack_to(batch: ColumnarBatch, cap: int) -> ColumnarBatch:
    """Rows [0, num_rows) re-laid into a fresh batch of capacity
    ``cap`` — one process-wide jit per target capacity (the trace cache
    inside each wrapper keys on the input batch structure). Shared by
    every repack site: join/aggregate sub-partition shrink, transfer
    compaction."""
    fn = _COMPACT_JIT.get(cap)
    if fn is None:
        fn = jax.jit(lambda b, c=cap: slice_batch(b, 0, b.num_rows, c))
        _COMPACT_JIT[cap] = fn
    return fn(batch)


def slice_batch(batch: ColumnarBatch, start: int, length,
                out_capacity: int) -> ColumnarBatch:
    """Rows [start, start+length) into a fresh batch of out_capacity.

    The split primitive behind split-and-retry (the contiguousSplit
    analogue); start/length may be traced scalars.
    """
    idx = jnp.arange(out_capacity, dtype=jnp.int32) + start
    n = jnp.minimum(length, jnp.maximum(batch.num_rows - start, 0))
    return batch.gather(idx, n, unique=True)


def local_limit(batch: ColumnarBatch, n: int) -> ColumnarBatch:
    new_n = jnp.minimum(batch.num_rows, n)
    mask = live_mask(batch.capacity, new_n)
    cols = [c.with_validity(c.validity & mask) for c in batch.columns]
    return ColumnarBatch(cols, batch.names, new_n)


# ---------------------------------------------------------------------------
# Generate / explode
# ---------------------------------------------------------------------------

def explode_batch(batch: ColumnarBatch, list_col, element_name: str,
                  out_capacity: int, outer: bool = False,
                  pos_name: str = None):
    """One output row per list element (GpuExplode / GpuGenerateExec).

    ``outer=True``: null/empty lists still produce one row with a null
    element (explode_outer). ``pos_name`` adds the 0-based element
    position column (posexplode). Returns (out_batch, total_rows);
    total may exceed out_capacity — the caller retries with a larger
    capacity bucket (the same overflow contract as the join kernels).
    """
    cap = batch.capacity
    live = batch.live_mask()
    real = jnp.where(list_col.validity & live, list_col.lengths(), 0)
    eff = jnp.maximum(real, 1) if outer else real
    eff = jnp.where(live, eff, 0)
    out_offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(eff, dtype=jnp.int32)])
    total = out_offsets[cap]
    pos = jnp.arange(out_capacity, dtype=jnp.int32)
    row_c = rows_from_offsets(out_offsets[:-1], eff, out_capacity)
    within = pos - jnp.take(out_offsets, row_c)
    n_out = jnp.minimum(total, out_capacity)
    gathered = batch.gather(row_c, n_out)
    out_live = live_mask(out_capacity, n_out)
    elem_ok = out_live & (within < jnp.take(real, row_c))
    src = jnp.take(list_col.offsets[:-1], row_c) + \
        jnp.clip(within, 0)
    element = list_col.child.gather(
        jnp.clip(src, 0, list_col.child_capacity - 1), elem_ok)
    cols = list(gathered.columns)
    names = list(gathered.names)
    if pos_name is not None:
        pdata = jnp.where(elem_ok, within, jnp.zeros((), jnp.int32))
        from ..columnar import dtypes as _dt
        cols.append(ColumnVector(pdata, elem_ok, _dt.INT32))
        names.append(pos_name)
    cols.append(element)
    names.append(element_name)
    return ColumnarBatch(cols, names, n_out), total
