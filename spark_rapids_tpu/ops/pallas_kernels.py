"""Pallas TPU kernels: fused single-HBM-pass reductions.

The XLA operator pipeline materializes intermediates between filter and
aggregate: ``FilterExec`` compacts passing rows into a fresh batch
(argsort + gather = several HBM round-trips) before ``HashAggregateExec``
reduces them. For the hottest reduction shape — scan -> filter -> global
aggregate, the TPC-H q6 spine of BASELINE.md config 1 — that traffic is
the whole cost: the aggregate output is a handful of scalars.

``tile_reduce`` fuses predicate evaluation, projection, and partial
aggregation into ONE pallas kernel: each row tile is DMA'd HBM->VMEM
once, the predicate and aggregate inputs evaluate on the VPU in VMEM,
and only per-tile partial scalars are written back. Cross-tile reduction
happens outside the kernel (a few hundred elements) in float64, which
both avoids a grid-accumulator dependence and improves numerics over a
single running float32 accumulator.

This is the TPU analogue of the fused cuDF reduction kernels behind the
reference's aggregate update pass (SURVEY §2.9; GpuAggregateExec.scala
AggHelper update); kernel structure follows the row-tile grid pattern of
/opt/skills/guides/pallas_guide.md. The exec-side wiring lives in
exec/aggregate.py (_PallasAggPlan).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE_ROWS = 8 * 1024

SUM = "sum"
MIN = "min"
MAX = "max"


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def reduce_identity(kind: str, dtype) -> float:
    """Identity element a masked-out lane must carry."""
    if kind == SUM:
        return 0.0
    if jnp.issubdtype(dtype, jnp.floating):
        return float(jnp.inf if kind == MIN else -jnp.inf)
    info = jnp.iinfo(dtype)
    return info.max if kind == MIN else info.min


def _tile_kernel(row_fn: Callable, kinds: Sequence[str], out_dtype):
    n_out = len(kinds)

    def kernel(*refs):
        in_refs, out_ref = refs[:-1], refs[-1]
        blocks = [r[...] for r in in_refs]
        vals = row_fn(blocks)
        assert len(vals) == n_out, (len(vals), n_out)
        row = jnp.zeros((1, 128), out_dtype)
        for j, (v, kind) in enumerate(zip(vals, kinds)):
            if kind == SUM:
                r = jnp.sum(v.astype(out_dtype))
            elif kind == MIN:
                r = jnp.min(v).astype(out_dtype)
            else:
                r = jnp.max(v).astype(out_dtype)
            row = row.at[0, j].set(r)
        # (8, 128) is the smallest legal f32 output tile; replicate the
        # partial row across sublanes and read sublane 0 outside.
        out_ref[...] = jnp.broadcast_to(row, (8, 128))

    return kernel


def tile_reduce(inputs: Sequence[jax.Array], row_fn: Callable,
                kinds: Sequence[str], out_dtype=None,
                tile_rows: int = TILE_ROWS,
                interpret: Optional[bool] = None) -> List[jax.Array]:
    """Fused masked reduction over row tiles.

    ``inputs``: same-length 1-D arrays (column data / validity / live
    masks). ``row_fn(blocks) -> [vals...]`` maps one tile's blocks to
    ``len(kinds)`` pre-masked 1-D value arrays — excluded rows must
    already carry the kind's identity (0 for sum, +/-inf for min/max);
    the tail padding this function appends is all-zeros, so mask inputs
    pad to False and masked values pad to the identity via row_fn.

    Returns one scalar per kind: per-tile partials from the kernel,
    reduced across tiles here (sums in float64 when x64 is live).
    """
    if interpret is None:
        interpret = not on_tpu()
    if out_dtype is None:
        out_dtype = jnp.float32 if on_tpu() else jnp.float64
    n = inputs[0].shape[0]
    tiles = max(1, -(-n // tile_rows))
    padded = tiles * tile_rows
    ins = []
    specs = []
    for a in inputs:
        if a.ndim == 2:
            # lane-block input (padded string chars): rows tile with
            # the grid, the byte axis rides whole into VMEM
            w = a.shape[1]
            if padded != n:
                a = jnp.pad(a, ((0, padded - n), (0, 0)))
            specs.append(pl.BlockSpec((tile_rows, w), lambda i: (i, 0)))
        else:
            if padded != n:
                a = jnp.pad(a, (0, padded - n))
            specs.append(pl.BlockSpec((tile_rows,), lambda i: (i,)))
        ins.append(a)
    assert len(kinds) <= 128, "one (1,128) partial row per tile"

    out = pl.pallas_call(
        _tile_kernel(row_fn, kinds, out_dtype),
        grid=(tiles,),
        in_specs=specs,
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((tiles * 8, 128), out_dtype),
        interpret=interpret,
    )(*ins)
    out = out[::8]

    acc_t = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    results = []
    for j, kind in enumerate(kinds):
        col = out[:, j]
        if kind == SUM:
            results.append(jnp.sum(col.astype(acc_t)))
        elif kind == MIN:
            results.append(jnp.min(col))
        else:
            results.append(jnp.max(col))
    return results


# ---------------------------------------------------------------------------
# grouped aggregation: one-hot matmul segmented reduction (family #2)
# ---------------------------------------------------------------------------

GROUP_BUCKETS = 1024
#: smaller row tile than tile_reduce: the (tile, B) one-hot must fit
#: VMEM — 2048x1024 f32 = 8 MiB, within the ~16 MB/core budget
#: (pallas_guide.md); 8192 rows would need 32 MiB and fail Mosaic
GROUP_TILE_ROWS = 2048


def tile_group_reduce(gid: jax.Array, values: Sequence[jax.Array],
                      num_buckets: int = GROUP_BUCKETS,
                      tile_rows: int = GROUP_TILE_ROWS,
                      interpret: Optional[bool] = None
                      ) -> List[jax.Array]:
    """Fused grouped SUM: one HBM pass, segmented reduction as a
    ONE-HOT MATMUL so the per-tile reduction runs on the MXU instead of
    a scatter (TPU scatters serialize; a (tile, B) one-hot against a
    (tile, V) value block is exactly the systolic array's shape). The
    XLA scatter-based path (ops/kernels.py group fns) stays the
    fallback for large key domains.

    ``gid``: int32[n] bucket ids in [0, num_buckets); masked-out rows
    must carry values == 0 (sum identity) — their gid may be anything
    in range. ``values``: 1-D float arrays. Returns one
    float64-accumulated array of shape [num_buckets] per value column;
    the caller maps buckets back to group keys.

    Kernel structure: one GRID-LESS pallas call per row tile (the MXU
    one-hot matmul), driven by an outer ``lax.scan`` that carries the
    accumulator at the wide dtype. Grid-less because (a) a sequential
    accumulating grid needs the output-block revisit pattern, which
    this environment's remote Mosaic compiler rejects, and (b) the
    scan carry accumulates at float64, bounding round-off per TILE
    rather than per multi-tile window. The kernel body avoids
    jnp operator sugar with Python-int operands: under x64 those
    route through jitted jnp wrappers that type the scalar operand
    int64, and Mosaic's in-kernel i64<->i32 convert recurses forever
    (jax 0.9).
    """
    if interpret is None:
        interpret = not on_tpu()
    nv = len(values)
    assert nv <= 128, "one accumulator lane column per value column"
    assert num_buckets % 8 == 0, "sublane-aligned bucket count"
    # cast OUTSIDE the kernel: Mosaic cannot lower the emulated
    # f64->f32 (or i64->i32) convert inside a TPU kernel body — it
    # recurses in _convert_element_type_lowering_rule; XLA handles the
    # emulated conversion fine in the surrounding program.
    # Interpret mode (the CPU differential lane) keeps float64 lanes so
    # exact Spark semantics are testable — same contract as tile_reduce.
    lane_t = jnp.float32
    if interpret and jax.config.jax_enable_x64:
        lane_t = jnp.float64
    gid = gid.astype(jnp.int32)
    values = [v.astype(lane_t) for v in values]
    n = gid.shape[0]
    tiles = max(1, -(-n // tile_rows))
    padded = tiles * tile_rows
    if padded != n:
        # pad rows to a full tile: gid 0 with zero values (sum identity)
        gid = jnp.pad(gid, (0, padded - n))
        values = [jnp.pad(v, (0, padded - n)) for v in values]

    def kernel(gid_ref, *refs):
        val_refs, out_ref = refs[:-1], refs[-1]
        g = gid_ref[...]
        # (tile_rows, B) one-hot on the fly; MXU contracts over rows
        oh = (g[:, None] ==
              jax.lax.broadcasted_iota(jnp.int32, (1, num_buckets), 1)
              ).astype(lane_t)
        vmat = jnp.stack(
            [v[...].astype(lane_t) for v in val_refs], axis=1)
        if nv < 128:
            vmat = jax.lax.pad(vmat, lane_t(0),
                               ((0, 0, 0), (0, 128 - nv, 0)))
        out_ref[...] = jax.lax.dot_general(
            oh, vmat, (((0,), (0,)), ((), ())),
            preferred_element_type=lane_t)   # (B, 128)

    tile_call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((num_buckets, 128), lane_t),
        interpret=interpret,
    )
    acc_t = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    gid_t = gid.reshape(tiles, tile_rows)
    vals_t = [v.reshape(tiles, tile_rows) for v in values]

    def step(acc, xs):
        g, vs = xs
        return acc + tile_call(g, *vs).astype(acc_t), None

    acc0 = jnp.zeros((num_buckets, 128), acc_t)
    out, _ = jax.lax.scan(step, acc0, (gid_t, vals_t))
    return [out[:, j] for j in range(nv)]
