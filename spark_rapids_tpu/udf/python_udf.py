"""PythonUDF expression + the user-facing ``udf`` wrapper.

compile-or-fallback: ``udf(fn)`` first tries the bytecode compiler
(compiler.py) so the function fuses into the device program; if that
fails, the call becomes a PythonUDF expression that only the CPU engine
can evaluate (row-at-a-time), and the tagging pass routes the operator
to the CPU — the reference's behavior when udf-compiler can't translate
a lambda (the original UDF stays in the plan and runs on CPU, with the
Arrow/Pandas worker machinery of SURVEY §2.8 playing the role our numpy
interpreter plays here).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..columnar import dtypes as dt
from ..expr.core import Expression, Schema
from .compiler import UdfCompileError, compile_udf


class PythonUDF(Expression):
    """Opaque python function over row values — CPU-only (no TPU rule
    registered, so operators containing it always fall back)."""

    def __init__(self, fn: Callable, return_type: dt.DType,
                 *children: Expression):
        super().__init__(*children)
        self.fn = fn
        self.return_type = return_type

    def data_type(self, schema: Schema) -> dt.DType:
        return self.return_type

    def __repr__(self):
        return f"PythonUDF({getattr(self.fn, '__name__', '<fn>')})"


class CompiledOrInterpretedUdf:
    """The object ``udf(fn)`` returns: call it with column expressions."""

    def __init__(self, fn: Callable, return_type: Optional[dt.DType]):
        self.fn = fn
        self.return_type = return_type

    def __call__(self, *args: Expression) -> Expression:
        try:
            expr = compile_udf(self.fn, list(args))
            self.compiled = True
            return expr
        except UdfCompileError:
            self.compiled = False
            if self.return_type is None:
                raise UdfCompileError(
                    f"UDF {getattr(self.fn, '__name__', '<fn>')} could "
                    "not be compiled; pass return_type= to allow the "
                    "interpreted CPU fallback")
            return PythonUDF(self.fn, self.return_type, *args)


def udf(fn: Optional[Callable] = None, *,
        return_type: Optional[dt.DType] = None):
    """Decorator/wrapper: ``my_udf = udf(lambda x: x + 1)`` or
    ``@udf(return_type=dt.FLOAT64)``."""
    if fn is None:
        return lambda f: CompiledOrInterpretedUdf(f, return_type)
    return CompiledOrInterpretedUdf(fn, return_type)
