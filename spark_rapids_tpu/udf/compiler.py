"""Symbolic executor over CPython bytecode (3.11-3.13 opcode surface).

The CatalystExpressionBuilder/CFG/State equivalent (udf-compiler/.../
CatalystExpressionBuilder.scala:35, CFG.scala, State.scala): a symbolic
stack machine where every slot holds an Expression. Conditional jumps
recursively execute both successors and join at RETURN with
``If(cond, then_value, else_value)`` — the standard tail-duplication
formulation (exponential only in branch nesting, bounded by
_MAX_BRANCH_DEPTH).
"""

from __future__ import annotations

import dis
import math
from typing import Callable, Dict, List, Optional

from ..columnar import dtypes as dt
from ..expr import mathfns as M
from ..expr import strings as S
from ..expr.arithmetic import (Abs, Add, Divide, Greatest, IntegralDivide,
                               Least, Multiply, Pmod, Remainder, Subtract,
                               UnaryMinus)
from ..expr.conditional import If
from ..expr.core import Expression, Literal, col
from ..expr.predicates import (And, EqualTo, GreaterThan,
                               GreaterThanOrEqual, InSet, IsNotNull, IsNull,
                               LessThan, LessThanOrEqual, Not, Or)

_MAX_BRANCH_DEPTH = 12


class UdfCompileError(TypeError):
    """The function uses a construct the compiler can't translate."""


class _Marker:
    """Non-expression stack values (modules, bound methods, callables)."""

    def __init__(self, kind: str, payload=None, extra=None):
        self.kind = kind
        self.payload = payload
        self.extra = extra


_BINARY = {
    "+": Add, "-": Subtract, "*": Multiply, "/": Divide,
    "//": IntegralDivide, "%": Remainder, "**": None,
}

_COMPARE = {
    "<": LessThan, "<=": LessThanOrEqual, ">": GreaterThan,
    ">=": GreaterThanOrEqual, "==": EqualTo,
}

# Python <= 3.10 emits one opcode per operator instead of BINARY_OP
_LEGACY_BINARY = {
    "BINARY_ADD": "+", "BINARY_SUBTRACT": "-", "BINARY_MULTIPLY": "*",
    "BINARY_TRUE_DIVIDE": "/", "BINARY_FLOOR_DIVIDE": "//",
    "BINARY_MODULO": "%", "BINARY_POWER": "**",
}

# callables resolvable from globals/builtins
_GLOBAL_FUNCS: Dict[object, Callable] = {
    abs: lambda a: Abs(a),
    min: lambda *a: Least(*a),
    max: lambda *a: Greatest(*a),
    len: lambda a: S.Length(a),
    math.sqrt: lambda a: M.Sqrt(a),
    math.exp: lambda a: M.Exp(a),
    math.log: lambda a: M.Log(a),
    math.log10: lambda a: M.Log10(a),
    math.log2: lambda a: M.Log2(a),
    math.sin: lambda a: M.Sin(a),
    math.cos: lambda a: M.Cos(a),
    math.tan: lambda a: M.Tan(a),
    math.floor: lambda a: M.Floor(a),
    math.ceil: lambda a: M.Ceil(a),
    math.pow: lambda a, b: M.Pow(a, b),
    math.atan2: lambda a, b: M.Atan2(a, b),
    math.hypot: lambda a, b: M.Hypot(a, b),
    round: lambda a, *s: M.Round(a, s[0].value if s else 0),
}

# str methods: name -> builder(expr, *literal_args)
_STR_METHODS: Dict[str, Callable] = {
    "upper": lambda e: S.Upper(e),
    "lower": lambda e: S.Lower(e),
    "strip": lambda e: S.StringTrim(e),
    "lstrip": lambda e: S.StringTrimLeft(e),
    "rstrip": lambda e: S.StringTrimRight(e),
    "startswith": lambda e, p: S.StartsWith(e, _const_str(p)),
    "endswith": lambda e, p: S.EndsWith(e, _const_str(p)),
    "replace": lambda e, a, b: S.StringReplace(e, _const_str(a),
                                               _const_str(b)),
    "find": lambda e, p: Add(S.StringLocate(e, _const_str(p)),
                             Literal(-1)),
}


def _const_str(e) -> str:
    if isinstance(e, Literal) and isinstance(e.value, str):
        return e.value
    raise UdfCompileError("string-method argument must be a constant")


def _to_expr(v) -> Expression:
    if isinstance(v, Expression):
        return v
    if isinstance(v, _Marker):
        raise UdfCompileError(f"cannot use {v.kind} as a value")
    return Literal(v)


class _Compiler:
    def __init__(self, fn: Callable, arg_exprs: List[Expression]):
        self.fn = fn
        code = fn.__code__
        if code.co_argcount != len(arg_exprs):
            raise UdfCompileError(
                f"UDF takes {code.co_argcount} args, got "
                f"{len(arg_exprs)}")
        if code.co_flags & 0x08 or code.co_flags & 0x04:
            raise UdfCompileError("*args/**kwargs not supported")
        self.locals: Dict[str, Expression] = {
            code.co_varnames[i]: arg_exprs[i]
            for i in range(code.co_argcount)}
        self.instrs = list(dis.get_instructions(fn))
        self.by_offset = {i.offset: idx
                          for idx, i in enumerate(self.instrs)}

    def run(self) -> Expression:
        return self._exec(0, [], dict(self.locals), 0)

    def _fail(self, instr, why: str = ""):
        raise UdfCompileError(
            f"unsupported bytecode {instr.opname} "
            f"{instr.argrepr or ''} {why}".strip())

    def _resolve_global(self, name: str):
        g = self.fn.__globals__
        if name in g:
            return g[name]
        import builtins
        if hasattr(builtins, name):
            return getattr(builtins, name)
        raise UdfCompileError(f"unresolvable global {name!r}")

    def _exec(self, idx: int, stack: list, local_vars: dict,
              depth: int) -> Expression:
        if depth > _MAX_BRANCH_DEPTH:
            raise UdfCompileError("branch nesting too deep")
        while idx < len(self.instrs):
            ins = self.instrs[idx]
            op = ins.opname
            if op in ("RESUME", "NOP", "CACHE", "PRECALL",
                      "TO_BOOL", "COPY_FREE_VARS", "PUSH_NULL",
                      "NOT_TAKEN"):
                pass
            elif op == "LOAD_FAST" or op == "LOAD_FAST_CHECK" or \
                    op == "LOAD_FAST_BORROW":
                if ins.argval not in local_vars:
                    raise UdfCompileError(
                        f"uninitialized local {ins.argval!r}")
                stack.append(local_vars[ins.argval])
            elif op == "STORE_FAST":
                local_vars[ins.argval] = _to_expr(stack.pop())
            elif op == "LOAD_FAST_LOAD_FAST":  # 3.13 superinstruction
                n1, n2 = ins.argval
                for nm in (n1, n2):
                    if nm not in local_vars:
                        raise UdfCompileError(
                            f"uninitialized local {nm!r}")
                stack.append(local_vars[n1])
                stack.append(local_vars[n2])
            elif op == "STORE_FAST_LOAD_FAST":  # 3.13
                n1, n2 = ins.argval
                local_vars[n1] = _to_expr(stack.pop())
                stack.append(local_vars[n2])
            elif op == "STORE_FAST_STORE_FAST":  # 3.13
                n1, n2 = ins.argval
                local_vars[n1] = _to_expr(stack.pop())
                local_vars[n2] = _to_expr(stack.pop())
            elif op == "LOAD_CONST":
                v = ins.argval
                if v is None or isinstance(v, (bool, int, float, str)):
                    stack.append(Literal(v) if v is not None
                                 else Literal(None))
                elif isinstance(v, tuple):
                    stack.append(_Marker("const_tuple", v))
                else:
                    self._fail(ins, f"const {type(v).__name__}")
            elif op in ("LOAD_GLOBAL", "LOAD_NAME"):
                obj = self._resolve_global(ins.argval)
                if ins.argrepr.startswith("NULL + "):
                    stack.append(_Marker("null"))  # callable marker slot
                stack.append(_Marker("global", obj))
            elif op == "LOAD_DEREF":
                # closure cell (e.g. a module imported in the enclosing
                # test/function scope)
                code = self.fn.__code__
                free = code.co_freevars
                if ins.argval in free and self.fn.__closure__:
                    cell = self.fn.__closure__[free.index(ins.argval)]
                    v = cell.cell_contents
                    if isinstance(v, (bool, int, float, str)):
                        stack.append(Literal(v))
                    else:
                        stack.append(_Marker("global", v))
                else:
                    self._fail(ins)
            elif op in ("LOAD_ATTR", "LOAD_METHOD"):
                base = stack.pop()
                if isinstance(base, _Marker) and base.kind == "global":
                    # module attr (math.sqrt)
                    stack.append(_Marker(
                        "global", getattr(base.payload, ins.argval)))
                elif isinstance(base, Expression):
                    # method on an expression (str methods)
                    stack.append(_Marker("method", base,
                                         extra=ins.argval))
                else:
                    self._fail(ins)
            elif op == "BINARY_OP":
                b = _to_expr(stack.pop())
                a = _to_expr(stack.pop())
                sym = ins.argrepr
                if sym == "**":
                    stack.append(M.Pow(a, b))
                elif sym in _BINARY and _BINARY[sym] is not None:
                    stack.append(_BINARY[sym](a, b))
                else:
                    self._fail(ins)
            elif op in _LEGACY_BINARY:  # <= 3.10
                b = _to_expr(stack.pop())
                a = _to_expr(stack.pop())
                sym = _LEGACY_BINARY[op]
                if sym == "**":
                    stack.append(M.Pow(a, b))
                elif _BINARY.get(sym) is not None:
                    stack.append(_BINARY[sym](a, b))
                else:
                    self._fail(ins)
            elif op == "CALL_FUNCTION":  # <= 3.10
                argc = ins.argval
                args = [stack.pop() for _ in range(argc)][::-1]
                callee = stack.pop()
                stack.append(self._call(ins, callee, args))
            elif op == "DUP_TOP":  # <= 3.10
                stack.append(stack[-1])
            elif op == "ROT_TWO":  # <= 3.10
                stack[-1], stack[-2] = stack[-2], stack[-1]
            elif op == "COMPARE_OP":
                b = stack.pop()
                a = stack.pop()
                sym = ins.argrepr.strip()
                if sym.startswith("bool(") and sym.endswith(")"):
                    sym = sym[5:-1]  # 3.13 argrepr form "bool(<)"
                sym = sym.split()[0]
                if sym == "!=":
                    stack.append(Not(EqualTo(_to_expr(a), _to_expr(b))))
                elif sym in _COMPARE:
                    stack.append(_COMPARE[sym](_to_expr(a), _to_expr(b)))
                else:
                    self._fail(ins)
            elif op == "IS_OP":
                b = stack.pop()
                a = _to_expr(stack.pop())
                is_none = (isinstance(b, Expression) and
                           isinstance(b, Literal) and b.value is None)
                if not is_none:
                    self._fail(ins, "only `is None` supported")
                stack.append(Not(IsNull(a)) if ins.argval == 1
                             else IsNull(a))
            elif op == "CONTAINS_OP":
                container = stack.pop()
                a = _to_expr(stack.pop())
                if isinstance(container, _Marker) and \
                        container.kind == "const_tuple":
                    e = InSet(a, list(container.payload))
                    stack.append(Not(e) if ins.argval == 1 else e)
                else:
                    self._fail(ins, "`in` needs a constant tuple")
            elif op == "UNARY_NEGATIVE":
                stack.append(UnaryMinus(_to_expr(stack.pop())))
            elif op == "UNARY_NOT":
                stack.append(Not(_to_expr(stack.pop())))
            elif op == "COPY":
                stack.append(stack[-ins.argval])
            elif op == "SWAP":
                stack[-1], stack[-ins.argval] = (stack[-ins.argval],
                                                 stack[-1])
            elif op == "POP_TOP":
                stack.pop()
            elif op == "CALL":
                argc = ins.argval
                args = [stack.pop() for _ in range(argc)][::-1]
                callee = stack.pop()
                # LOAD_GLOBAL's NULL slot sits under the callable
                if stack and isinstance(stack[-1], _Marker) and \
                        stack[-1].kind == "null":
                    stack.pop()
                stack.append(self._call(ins, callee, args))
            elif op == "CALL_METHOD":
                argc = ins.argval
                args = [stack.pop() for _ in range(argc)][::-1]
                callee = stack.pop()
                stack.append(self._call(ins, callee, args))
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_FORWARD_IF_FALSE",
                        "POP_JUMP_FORWARD_IF_TRUE"):
                cond = _to_expr(stack.pop())
                if "TRUE" in op:
                    cond = Not(cond)
                tgt = self.by_offset[ins.argval]
                then_v = self._exec(idx + 1, list(stack),
                                    dict(local_vars), depth + 1)
                else_v = self._exec(tgt, list(stack), dict(local_vars),
                                    depth + 1)
                return If(cond, then_v, else_v)
            elif op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                v = _to_expr(stack.pop())
                cond = IsNull(v) if op.endswith("IF_NONE") else \
                    IsNotNull(v)
                tgt = self.by_offset[ins.argval]
                then_v = self._exec(tgt, list(stack), dict(local_vars),
                                    depth + 1)
                else_v = self._exec(idx + 1, list(stack),
                                    dict(local_vars), depth + 1)
                return If(cond, then_v, else_v)
            elif op in ("JUMP_IF_TRUE_OR_POP", "JUMP_IF_FALSE_OR_POP"):
                v = _to_expr(stack[-1])
                cond = v if op.startswith("JUMP_IF_TRUE") else Not(v)
                tgt = self.by_offset[ins.argval]
                keep = self._exec(tgt, list(stack), dict(local_vars),
                                  depth + 1)
                popped = list(stack)[:-1]
                other = self._exec(idx + 1, popped, dict(local_vars),
                                   depth + 1)
                return If(cond, keep, other)
            elif op in ("JUMP_FORWARD", "JUMP_BACKWARD_NO_INTERRUPT"):
                idx = self.by_offset[ins.argval]
                continue
            elif op == "JUMP_BACKWARD":
                raise UdfCompileError("loops not supported")
            elif op in ("RETURN_VALUE",):
                return _to_expr(stack.pop())
            elif op == "RETURN_CONST":
                v = ins.argval
                return Literal(v)
            else:
                self._fail(ins)
            idx += 1
        raise UdfCompileError("fell off the end of the bytecode")

    def _call(self, ins, callee, args) -> Expression:
        if isinstance(callee, _Marker) and callee.kind == "method":
            builder = _STR_METHODS.get(callee.extra)
            if builder is None:
                self._fail(ins, f"method .{callee.extra}()")
            return builder(callee.payload,
                           *[_to_expr(a) for a in args])
        if isinstance(callee, _Marker) and callee.kind == "global":
            target = callee.payload
            builder = _GLOBAL_FUNCS.get(target)
            if builder is None:
                if target is float or target is int or target is bool:
                    t = {float: dt.FLOAT64, int: dt.INT64,
                         bool: dt.BOOL}[target]
                    return _to_expr(args[0]).cast(t)
                if target is str:
                    return _to_expr(args[0]).cast(dt.STRING)
                self._fail(ins, f"call to {target!r}")
            return builder(*[_to_expr(a) for a in args])
        self._fail(ins, "uncallable")


def compile_udf(fn: Callable, arg_exprs: List[Expression]) -> Expression:
    """Translate ``fn(args...)`` into an Expression over arg_exprs, or
    raise UdfCompileError."""
    return _Compiler(fn, list(arg_exprs)).run()
