"""Vectorized (pandas) UDF expression + the ``pandas_udf`` API.

Reference surface: sql-plugin/.../execution/python/GpuArrowEvalPythonExec
(scalar pandas UDFs over Arrow batches) and python/rapids/daemon.py
(worker process management — rebuilt in udf/worker.py). Where the
row-at-a-time ``udf()`` (python_udf.py) first tries the bytecode
compiler and otherwise forces a CPU fallback, a pandas UDF is
vectorized by contract: the plan stays on device and only the UDF
columns detour through Arrow IPC to a pooled worker process
(exec/python_exec.py).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ..columnar import dtypes as dt
from ..expr.core import Alias, ColumnRef, Expression, Schema, output_name


class PandasUDF(Expression):
    """fn(*pandas.Series) -> Series, applied out-of-process over Arrow
    batches. Child expressions are the UDF arguments; they evaluate on
    device and only their results cross to the worker."""

    def __init__(self, fn: Callable, return_type: dt.DType,
                 *children: Expression):
        super().__init__(*children)
        self.fn = fn
        self.return_type = return_type

    def data_type(self, schema: Schema) -> dt.DType:
        return self.return_type

    def __repr__(self):
        return f"PandasUDF({getattr(self.fn, '__name__', '<fn>')})"


class _PandasUdfWrapper:
    def __init__(self, fn: Callable, return_type: dt.DType):
        self.fn = fn
        self.return_type = return_type

    def __call__(self, *args: Expression) -> PandasUDF:
        return PandasUDF(self.fn, self.return_type, *args)


def pandas_udf(fn: Optional[Callable] = None, *,
               return_type: dt.DType):
    """``@pandas_udf(return_type=dt.FLOAT64)`` or
    ``pandas_udf(f, return_type=...)`` — f receives one pandas.Series
    per argument and must return an equal-length Series/array."""
    if fn is None:
        return lambda f: _PandasUdfWrapper(f, return_type)
    return _PandasUdfWrapper(fn, return_type)


def extract_pandas_udfs(exprs: List[Expression]
                        ) -> Tuple[List[Expression],
                                   List[Tuple[PandasUDF, str]]]:
    """The GpuExtractPythonUDFs role: pull PandasUDF subtrees out of a
    projection list, returning (rewritten exprs referencing generated
    columns, [(udf, generated_name)]). Output names of top-level UDFs
    are preserved via Alias."""
    udfs: List[Tuple[PandasUDF, str]] = []
    seen: dict = {}

    def sub(e: Expression) -> Expression:
        if isinstance(e, PandasUDF):
            name = seen.get(id(e))
            if name is None:
                name = f"__pyudf{len(udfs)}"
                seen[id(e)] = name
                udfs.append((e, name))
            return ColumnRef(name)
        kids = [sub(c) for c in e.children]
        if all(a is b for a, b in zip(kids, e.children)):
            return e
        import copy
        clone = copy.copy(e)
        clone.children = kids
        return clone

    out = []
    for i, e in enumerate(exprs):
        r = sub(e)
        if isinstance(e, PandasUDF):
            # keep the projection's output name stable
            r = Alias(r, output_name(e, i))
        out.append(r)
    return out, udfs
