"""UDF compiler: Python bytecode -> Expression IR.

Rebuild of the reference's udf-compiler module (SURVEY §2.8: JVM
bytecode -> Catalyst via LambdaReflection + CFG + symbolic execution in
CatalystExpressionBuilder). Same architecture, one VM over: ``dis`` the
function, symbolically execute the CPython stack machine, and branch-
join conditional jumps into ``If`` expressions. A compiled UDF is just
an Expression tree — it fuses into the surrounding jit like any builtin
and runs on the TPU.

Functions the compiler can't translate raise ``UdfCompileError``; the
``udf`` wrapper then degrades to a PythonUDF expression that the CPU
engine interprets row-by-row — the exact compile-or-fallback contract
of the reference (LogicalPlanRules falls back to leaving the original
UDF in place).

``pandas_udf`` is the vectorized escape hatch: the plan stays on
device and the UDF columns detour through Arrow IPC to pooled Python
worker processes (pandas_udf.py + worker.py + exec/python_exec.py —
the reference's execution/python/ + rapids daemon subsystem).
"""

from .compiler import UdfCompileError, compile_udf
from .pandas_udf import PandasUDF, pandas_udf
from .python_udf import PythonUDF, udf

__all__ = ["compile_udf", "udf", "UdfCompileError", "PythonUDF",
           "pandas_udf", "PandasUDF"]
