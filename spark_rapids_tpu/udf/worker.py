"""Python worker management for vectorized (pandas) UDFs.

Rebuild of the reference's Python-worker subsystem (SURVEY §2.8:
python/rapids/daemon.py worker forking; GpuArrowEvalPythonExec's Arrow
stream protocol in sql-plugin/.../execution/python/): vectorized UDF
input batches stream to long-lived out-of-process Python workers as
Arrow IPC and the results stream back.

The TPU design keeps the same process model — workers are plain CPython
processes that import only pyarrow/pandas (never jax, and never this
package's __init__, so a hung accelerator runtime or a crashing UDF
cannot take the engine down) — but replaces the daemon's forked-socket
negotiation with a length-prefixed frame protocol over stdin/stdout
pipes, which needs no port management and works identically under test
runners and notebooks.

Protocol (big-endian u32 length prefix per frame):

  engine -> worker, per job:  frame 1 = cloudpickle job spec
                                 [(fn, n_args, result_field), ...]
                              frame 2 = Arrow IPC stream of the input
                                 table (UDF argument columns, grouped
                                 in spec order)
  worker -> engine:           one frame, b'O' + Arrow IPC result table
                              or        b'E' + utf-8 traceback
  engine -> worker:           zero-length frame = exit

Workers are pooled and reused across jobs/execs (the daemon's worker
reuse); a worker that dies mid-job is discarded and its stderr tail
surfaces in the engine error.
"""

from __future__ import annotations

import atexit
import os
import queue
import struct
import subprocess
import sys
import threading
from typing import List, Optional, Tuple

_FRAME_LEN = struct.Struct(">I")


def _write_frame(pipe, payload: bytes) -> None:
    pipe.write(_FRAME_LEN.pack(len(payload)))
    pipe.write(payload)
    pipe.flush()


def _read_frame(pipe) -> Optional[bytes]:
    head = pipe.read(4)
    if len(head) < 4:
        return None
    (n,) = _FRAME_LEN.unpack(head)
    buf = b""
    while len(buf) < n:
        chunk = pipe.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class PythonWorkerError(RuntimeError):
    """A UDF raised inside the worker (traceback attached) or the
    worker process died."""


class PythonWorker:
    """One pooled worker process."""

    def __init__(self):
        import tempfile
        env = dict(os.environ)
        # workers never touch jax; scrub accelerator env so a stray
        # import in user UDF code stays on CPU
        env["JAX_PLATFORMS"] = "cpu"
        # stderr goes to an unbounded temp FILE, not a pipe: a pipe
        # that nobody drains wedges the worker after ~64KB of warnings
        # (the undrained-pipe deadlock class); the file is read back
        # only for the death-message tail
        self._err = tempfile.TemporaryFile(prefix="srt_udf_err_")
        self._expired = False
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._err, env=env)

    def _stderr_tail(self, n: int = 2000) -> bytes:
        try:
            self._err.seek(0, os.SEEK_END)
            size = self._err.tell()
            self._err.seek(max(0, size - n))
            return self._err.read()
        except (OSError, ValueError):
            return b""

    def run_job(self, spec_blob: bytes, arrow_blob: bytes,
                timeout: Optional[float] = None) -> bytes:
        """Returns the result Arrow IPC bytes; raises PythonWorkerError
        on UDF failure, worker death, or timeout (the worker is killed
        so _read_frame always returns instead of blocking forever)."""
        timer = None
        timed_out = [False]
        if timeout:
            def _expire():
                timed_out[0] = True
                self._expired = True  # never pool a killed worker
                self.proc.kill()
            timer = threading.Timer(timeout, _expire)
            timer.start()
        try:
            _write_frame(self.proc.stdin, spec_blob)
            _write_frame(self.proc.stdin, arrow_blob)
            reply = _read_frame(self.proc.stdout)
        except (BrokenPipeError, OSError):
            reply = None
        finally:
            if timer is not None:
                timer.cancel()
        if reply is None:
            try:
                self.proc.kill()
            except OSError:
                pass
            why = (f"python worker timed out after {timeout}s"
                   if timed_out[0] else "python worker died")
            raise PythonWorkerError(
                why + ": " + self._stderr_tail().decode(
                    "utf-8", "replace"))
        if reply[:1] == b"E":
            raise PythonWorkerError(reply[1:].decode("utf-8", "replace"))
        return reply[1:]

    def alive(self) -> bool:
        # _expired guards the race where the timeout timer killed the
        # process just as a reply landed: poll() can still say alive
        # for a moment, and pooling the dying worker would fail the
        # NEXT job spuriously
        return self.proc.poll() is None and not self._expired

    def close(self) -> None:
        try:
            if self.alive():
                _write_frame(self.proc.stdin, b"")
                self.proc.wait(timeout=2)
        except (OSError, subprocess.TimeoutExpired):
            self.proc.kill()
        try:
            self._err.close()
        except OSError:
            pass


class PythonWorkerPool:
    """Bounded worker pool with reuse (daemon.py's role)."""

    def __init__(self, max_workers: int = 4):
        self.max_workers = max_workers
        self._idle: "queue.Queue[PythonWorker]" = queue.Queue()
        self._count = 0
        self._lock = threading.Lock()
        self.closed = False

    def acquire(self) -> PythonWorker:
        while True:
            try:
                w = self._idle.get_nowait()
            except queue.Empty:
                break
            if w.alive():
                return w
            with self._lock:
                self._count -= 1
        with self._lock:
            if self._count < self.max_workers:
                self._count += 1
                return PythonWorker()
        w = self._idle.get()  # block for a released worker
        if w.alive():
            return w
        with self._lock:
            self._count -= 1
        return self.acquire()

    def release(self, w: PythonWorker, broken: bool = False) -> None:
        if broken or not w.alive() or self.closed:
            w.close()
            with self._lock:
                self._count -= 1
            return
        self._idle.put(w)

    def run_job(self, spec_blob: bytes, arrow_blob: bytes) -> bytes:
        from ..conf import PYTHON_UDF_TIMEOUT, active_conf
        timeout = active_conf().get(PYTHON_UDF_TIMEOUT) or None
        w = self.acquire()
        try:
            out = w.run_job(spec_blob, arrow_blob, timeout=timeout)
        except PythonWorkerError:
            self.release(w, broken=True)
            raise
        self.release(w)
        return out

    def close(self) -> None:
        self.closed = True
        while True:
            try:
                self._idle.get_nowait().close()
            except queue.Empty:
                break


_POOL: Optional[PythonWorkerPool] = None
_POOL_LOCK = threading.Lock()


def worker_pool() -> PythonWorkerPool:
    global _POOL
    with _POOL_LOCK:
        if _POOL is None or _POOL.closed:
            from ..conf import PYTHON_WORKERS_MAX, active_conf
            _POOL = PythonWorkerPool(
                active_conf().get(PYTHON_WORKERS_MAX))
            atexit.register(_POOL.close)
        return _POOL


def make_job_spec(udfs) -> bytes:
    """[(fn, n_args, arrow_result_field)] -> wire blob."""
    import cloudpickle
    return cloudpickle.dumps(udfs)


# ---------------------------------------------------------------------------
# worker-side main: executed as a SCRIPT (sys.executable <this file>),
# never as part of the package — stdlib + pyarrow + pandas only
# ---------------------------------------------------------------------------

def _worker_main() -> None:  # pragma: no cover - subprocess body
    import io
    import pickle
    import traceback

    import pyarrow as pa

    stdin = sys.stdin.buffer
    # The frame protocol owns the ORIGINAL stdout fd; user UDFs that
    # print() (or C libs writing to fd 1) must not corrupt it. Dup the
    # fd for the protocol, then point fd 1 — and sys.stdout, which
    # wraps fd 1 — at stderr.
    stdout = os.fdopen(os.dup(sys.stdout.fileno()), "wb")
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    while True:
        spec_blob = _read_frame(stdin)
        if not spec_blob:
            return
        arrow_blob = _read_frame(stdin)
        if arrow_blob is None:
            return
        try:
            udfs = pickle.loads(spec_blob)  # cloudpickle-compatible
            with pa.ipc.open_stream(io.BytesIO(arrow_blob)) as rd:
                table = rd.read_all()
            out_fields, out_arrays = [], []
            col = 0
            for fn, n_args, field in udfs:
                args = [table.column(col + k).to_pandas()
                        for k in range(n_args)]
                col += n_args
                res = fn(*args)
                arr = pa.Array.from_pandas(res, type=field.type) \
                    if not isinstance(res, (pa.Array, pa.ChunkedArray)) \
                    else res
                if len(arr) != table.num_rows:
                    raise ValueError(
                        f"pandas UDF returned {len(arr)} rows for "
                        f"{table.num_rows} input rows")
                out_fields.append(field)
                out_arrays.append(arr)
            out = pa.table(dict(zip([f.name for f in out_fields],
                                    out_arrays)))
            sink = io.BytesIO()
            with pa.ipc.new_stream(sink, out.schema) as wr:
                wr.write_table(out)
            _write_frame(stdout, b"O" + sink.getvalue())
        except BaseException:
            _write_frame(
                stdout,
                b"E" + traceback.format_exc().encode("utf-8", "replace"))


if __name__ == "__main__":  # pragma: no cover
    _worker_main()
