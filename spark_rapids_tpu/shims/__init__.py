"""Version shim layer + extra-plugin loader.

Reference surface (SURVEY §2.1): ShimLoader.scala + the per-version
shim source sets (sql-plugin/src/main/spark3xx/...) select
implementations by Spark version at runtime; RapidsPluginUtils
loadExtraPlugins instantiates user-supplied plugin classes.

The TPU rebuild targets one engine, so the moving ABI is the JAX API
itself (symbols migrate between jax.experimental and jax across
releases — shard_map did exactly this). ``ShimRegistry`` keeps a
version-ranged provider table per capability; ``resolve`` picks the
first provider whose range matches the running jax version and whose
probe succeeds, so the engine loads against multiple jax releases
without scattering try/except ImportError through operator code.

``load_extra_plugins`` applies srt.plugins ("pkg.module:attr" entries,
comma-separated): each attr is called with the active conf at
initialize time — the loadExtraPlugins contract for user extensions.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple


def _version_tuple(v: str) -> Tuple[int, ...]:
    parts = []
    for p in v.split("."):
        digits = ""
        for ch in p:
            if not ch.isdigit():
                break
            digits += ch
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts) or (0,)


class ShimRegistry:
    def __init__(self):
        # name -> [(min_incl, max_excl, provider)]
        self._table: Dict[str, List[Tuple[Optional[tuple],
                                          Optional[tuple],
                                          Callable]]] = {}
        self._cache: Dict[str, object] = {}

    def register(self, name: str, provider: Callable,
                 min_version: Optional[str] = None,
                 max_version: Optional[str] = None) -> None:
        lo = _version_tuple(min_version) if min_version else None
        hi = _version_tuple(max_version) if max_version else None
        self._table.setdefault(name, []).append((lo, hi, provider))

    def resolve(self, name: str):
        """First matching provider whose probe doesn't raise."""
        if name in self._cache:
            return self._cache[name]
        import jax
        cur = _version_tuple(jax.__version__)
        errors = []
        for lo, hi, provider in self._table.get(name, []):
            if lo is not None and cur < lo:
                continue
            if hi is not None and cur >= hi:
                continue
            try:
                out = provider()
            except Exception as e:  # probe failure: try older shim
                errors.append(f"{provider.__name__}: {e}")
                continue
            self._cache[name] = out
            return out
        raise ImportError(
            f"no shim for {name!r} matches jax {jax.__version__}: "
            f"{'; '.join(errors) or 'no providers registered'}")


SHIMS = ShimRegistry()


# --- registered shims ------------------------------------------------------

def _shard_map_current():
    import jax
    return jax.shard_map  # jax >= 0.6 public API


def _shard_map_experimental():
    from jax.experimental.shard_map import shard_map
    return shard_map


SHIMS.register("shard_map", _shard_map_current, min_version="0.6")
SHIMS.register("shard_map", _shard_map_experimental)


def shard_map():
    """The shard_map entry point for the running jax release."""
    return SHIMS.resolve("shard_map")


# --- extra plugin loader ---------------------------------------------------

def load_extra_plugins(conf) -> List[object]:
    """srt.plugins = 'pkg.module:attr,pkg2.mod:attr2' — import each and
    call attr(conf); returns the loaded plugin objects
    (RapidsPluginUtils.loadExtraPlugins role)."""
    import importlib

    from ..conf import EXTRA_PLUGINS
    spec = conf.get(EXTRA_PLUGINS)
    out = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        mod_name, _, attr = entry.partition(":")
        mod = importlib.import_module(mod_name)
        fn = getattr(mod, attr or "init_plugin")
        out.append(fn(conf))
    return out
