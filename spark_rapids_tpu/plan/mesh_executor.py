"""Mesh executor: lower a planner-produced physical plan to one SPMD
program over a jax.sharding.Mesh.

This is the multi-chip execution backend for the SAME physical trees the
single-process engine runs (overrides.apply_overrides output) — the
planner decides staging (exchanges, partial/final aggregates, broadcast
sides), and this module maps each staged operator onto mesh collectives:

  ShuffleExchangeExec(hash keys)   -> partition + lax.all_to_all
  ShuffleExchangeExec(range)       -> in-trace sampled bounds + all_to_all
  ShuffleExchangeExec(1 partition) -> lax.all_gather (+ shard-0 mask)
  BroadcastExchangeExec            -> lax.all_gather (replicated build)
  HashAggregateExec partial/final  -> local update / local merge of the
                                      now-disjoint key ranges
  joins                            -> shard-local gather-map joins
  global sort / TopN / limit       -> per-shard op + ordered shards

The reference's equivalent is a p2p shuffle (UCX ActiveMessages,
RapidsShuffleClient.scala:169) feeding the same staged operators; on TPU
the exchange is a compiled collective riding ICI (SURVEY §2.7 "TPU
equivalent" row, §7 hard-part #5) and the whole multi-stage query step
becomes one XLA program.

Leaves (scans, host relations) are executed on the host driver, split
into per-shard slices, and fed in stacked form (parallel/shuffle.py
stack_shards); everything above the leaves is traced into shard_map.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, StringColumn,
                               choose_capacity, column_from_numpy,
                               round_pow2)
from ..conf import SrtConf, active_conf
from ..exec.aggregate import FINAL, PARTIAL, HashAggregateExec
from ..exec.base import ExecContext, TpuExec
from ..exec.basic import (BatchScanExec, CoalesceBatchesExec, ExpandExec,
                          FilterExec, LocalLimitExec, ProjectExec, UnionExec)
from ..exec.exchange import BroadcastExchangeExec, ShuffleExchangeExec
from ..exec.join import _HashJoinBase
from ..exec.sort import SortExec, TopNExec
from ..ops import kernels as K
from ..parallel.mesh import DATA_AXIS
from ..parallel.partition import (flatten_partitions, hash_partition_ids,
                                  partition_batch, range_partition_ids,
                                  round_robin_partition_ids,
                                  string_from_padded)
from ..parallel.shuffle import (all_gather_batch, all_to_all_partitions,
                                stack_shards, unstack_shards)
from ..plan.transitions import HostToDeviceExec


class UnsupportedMeshLowering(Exception):
    """Raised for plan nodes the mesh backend cannot lower (the caller
    falls back to single-process execution)."""


def _mask_to_shard0(batch: ColumnarBatch, axis: str) -> ColumnarBatch:
    keep = lax.axis_index(axis) == 0
    return ColumnarBatch(batch.columns, batch.names,
                         jnp.where(keep, batch.num_rows, 0)
                         .astype(jnp.int32))


class MeshQueryExecutor:
    """Compiles and runs one physical plan on an n-device mesh."""

    def __init__(self, mesh: Mesh, conf: Optional[SrtConf] = None,
                 axis: str = DATA_AXIS, join_growth: int = 2):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.conf = conf or active_conf()
        self.join_growth = join_growth
        self._leaves: List[TpuExec] = []
        #: traced sufficiency flags appended during lowering-closure
        #: execution (join output capacity checks); returned from the
        #: shard program so overflow FAILS the query instead of
        #: silently dropping matches (single-stream joins grow-and-
        #: retry on the host; a traced SPMD program cannot)
        self._checks: List = []
        #: exec_ids of hash exchanges lowered as identity (co-location
        #: bypass): child rows were already on their target shard
        self.colocated_exchanges: List[str] = []

    def _hash_colocated(self, node: ShuffleExchangeExec) -> bool:
        """True when this hash exchange's all_to_all is provably the
        identity permutation on this mesh: the child's advertised
        partitioning is HashPartitioning on the SAME expr sequence
        (placement for both is pmod(murmur3(exprs), n) with n = mesh
        size — plan-level num_partitions never enters mesh placement).
        Only exchanges originate HashPartitioning here and
        partition-preserving operators propagate it, so the claim
        always traces back to a collective this executor lowered."""
        from .distribution import HashPartitioning, _expr_key
        from ..conf import SHUFFLE_PUSH_ENABLED, SHUFFLE_PUSH_LOCAL_BYPASS
        if not (self.conf.get(SHUFFLE_PUSH_ENABLED)
                and self.conf.get(SHUFFLE_PUSH_LOCAL_BYPASS)):
            return False
        p = node.children[0].output_partitioning
        if not isinstance(p, HashPartitioning):
            return False
        return ([_expr_key(e) for e in p.exprs]
                == [_expr_key(e) for e in node.key_exprs])

    # ------------------------------------------------------------------
    # host side
    # ------------------------------------------------------------------
    def run(self, physical: TpuExec) -> List[ColumnarBatch]:
        """Execute the plan; returns host-ordered result batches (shard
        order is partition order for sorted plans)."""
        from ..obs import events as _events
        _events.emit("StageSubmitted", mode="mesh",
                     num_shards=self.n, join_growth=self.join_growth)
        self._leaves = []
        fn = self._lower(physical)
        ctx = ExecContext(self.conf)
        stacks = [self._leaf_stack(leaf, ctx) for leaf in self._leaves]
        n_leaves = len(stacks)

        def shard_step(*stacked):
            env = {id(leaf): jax.tree_util.tree_map(lambda x: x[0], st)
                   for leaf, st in zip(self._leaves, stacked)}
            self._checks = []
            out = fn(env)
            ok = jnp.ones((), jnp.bool_)
            for c in self._checks:
                ok = ok & c
            return jax.tree_util.tree_map(lambda x: x[None], (out, ok))

        from ..shims import shard_map as _shard_map
        sm = _shard_map()
        # the replication-check kwarg was renamed check_rep -> check_vma
        # across jax releases; pass whichever this release understands
        import inspect
        sm_params = inspect.signature(sm).parameters
        check_kw = {}
        for name in ("check_vma", "check_rep"):
            if name in sm_params:
                check_kw[name] = False
                break
        step = jax.jit(sm(
            shard_step, mesh=self.mesh,
            in_specs=tuple(P(self.axis) for _ in range(n_leaves)),
            out_specs=P(self.axis), **check_kw))
        res, ok = step(*stacks)
        jax.block_until_ready(jax.tree_util.tree_leaves(res))
        _events.emit("StageCompleted", mode="mesh", num_shards=self.n,
                     overflowed=not bool(jnp.all(ok)))
        if not bool(jnp.all(ok)):
            raise RuntimeError(
                "mesh join output overflowed its static capacity "
                "(matches > probe_capacity * join_growth) — results "
                "would silently drop rows; raise join_growth or "
                "repartition finer")
        return [b for b in unstack_shards(res) if int(b.num_rows) > 0]

    def _leaf_stack(self, leaf: TpuExec, ctx: ExecContext):
        """Host-execute a leaf subtree and split its rows into n shard
        slices with identical shapes (contiguous split, so input order
        is preserved across the shard sequence)."""
        from .host_table import batch_to_table, concat_tables, to_pydict
        schema = leaf.output_schema
        tables = [batch_to_table(b) for b in leaf.execute(ctx)
                  if int(b.num_rows) > 0]
        if tables:
            table = concat_tables(tables)
            data = to_pydict(table)
            total = table.num_rows
        else:
            data = {n: [] for n, _ in schema}
            total = 0
        per = -(-max(total, 1) // self.n)
        cap = choose_capacity(max(per, 8))
        shard_batches = []
        names = [n for n, _ in schema]
        for s in range(self.n):
            lo, hi = min(s * per, total), min((s + 1) * per, total)
            chunk = {n: data[n][lo:hi] for n in names}
            shard_batches.append(_batch_from_pydict_typed(chunk, schema,
                                                          cap))
        _normalize_strings(shard_batches)
        return stack_shards(shard_batches)

    # ------------------------------------------------------------------
    # lowering
    # ------------------------------------------------------------------
    def _lower(self, node: TpuExec) -> Callable[[Dict], ColumnarBatch]:
        ax, n = self.axis, self.n
        if isinstance(node, (BatchScanExec, HostToDeviceExec)) or \
                not node.children:
            self._leaves.append(node)
            key = id(node)
            return lambda env: env[key]

        if isinstance(node, ProjectExec):
            if node._eager:
                raise UnsupportedMeshLowering(
                    "eager projection (uuid/input_file/raise_error)")
            child = self._lower(node.children[0])

            def proj_fn(env):
                b = child(env)
                # context expressions see shard-unique positions:
                # partition_id = shard index, row offsets disjoint
                idx = lax.axis_index(ax)
                return node._project_ctx(
                    b, idx.astype(jnp.int64) * b.capacity,
                    idx.astype(jnp.int32))
            return proj_fn

        if isinstance(node, FilterExec):
            child = self._lower(node.children[0])
            return lambda env: node._filter(child(env))

        if isinstance(node, CoalesceBatchesExec):
            return self._lower(node.children[0])

        from ..exec.pipeline import PrefetchExec
        if isinstance(node, PrefetchExec):
            # host-side pipelining has no meaning inside one traced
            # mesh program: transparent pass-through
            return self._lower(node.children[0])

        from ..exec.fused import FusedPipelineExec
        if isinstance(node, FusedPipelineExec):
            # the whole mesh program is already one traced jit, so the
            # fusion wrapper adds nothing here: lower the original
            # chain (stage nodes keep their unfused child links)
            return self._lower(node.stages[-1])

        from ..exec.fused import FusedHashJoinExec
        if isinstance(node, FusedHashJoinExec):
            # same story as FusedPipelineExec: the suffix nodes keep
            # their original child links down to the wrapped join, so
            # lowering the terminal suffix stage recovers the whole
            # join+suffix chain inside the one mesh trace
            return self._lower(node.suffix[-1])

        if isinstance(node, UnionExec):
            kids = [self._lower(c) for c in node.children]

            def union_fn(env):
                batches = [k(env) for k in kids]
                cap = round_pow2(sum(b.capacity for b in batches))
                return K.concat_batches(batches, cap)
            return union_fn

        if isinstance(node, BroadcastExchangeExec):
            child = self._lower(node.children[0])
            return lambda env: all_gather_batch(child(env), n, ax)

        if isinstance(node, ShuffleExchangeExec):
            return self._lower_shuffle(node)

        if isinstance(node, HashAggregateExec):
            return self._lower_agg(node)

        if isinstance(node, _HashJoinBase):
            return self._lower_join(node)

        if isinstance(node, TopNExec):
            child = self._lower(node.children[0])

            def topn_fn(env):
                local = node._topn(child(env))
                gathered = all_gather_batch(local, n, ax)
                return _mask_to_shard0(node._topn(gathered), ax)
            return topn_fn

        if isinstance(node, SortExec):
            child = self._lower(node.children[0])
            # child is range-partitioned (planner): local sort per shard;
            # shard order == partition order == global order
            return lambda env: node._sort_one(child(env))

        from ..exec.basic import SampleExec
        if isinstance(node, SampleExec):
            child = self._lower(node.children[0])

            def sample_fn(env):
                b = child(env)
                off = lax.axis_index(ax).astype(jnp.int64) * b.capacity
                return node._sample(b, off)
            return sample_fn

        if isinstance(node, ExpandExec):
            from ..exec.basic import _expand_project_builder
            child = self._lower(node.children[0])
            # node.projections are already dtype-unified across lists
            # (ExpandExec.__init__ casts divergent slots); build raw
            # un-jitted projectors — the mesh trace jits the whole shard
            out_names = [n for n, _ in node.output_schema]
            fns = [_expand_project_builder(p, out_names)
                   for p in node.projections]

            def expand_fn(env):
                b = child(env)
                outs = [fn(b) for fn in fns]
                cap = round_pow2(sum(o.capacity for o in outs))
                return K.concat_batches(outs, cap)
            return expand_fn

        from ..exec.window import BatchedRunningWindowExec, WindowExec
        if isinstance(node, (WindowExec, BatchedRunningWindowExec)):
            return self._lower_window(node)

        if isinstance(node, LocalLimitExec):
            child = self._lower(node.children[0])

            def limit_fn(env):
                gathered = all_gather_batch(child(env), n, ax)
                return _mask_to_shard0(K.local_limit(gathered, node.limit),
                                       ax)
            return limit_fn

        raise UnsupportedMeshLowering(type(node).__name__)

    def _lower_window(self, node):
        """Window partitions co-locate via hash all-to-all on the
        partition keys, then the whole-partition segmented-scan kernel
        runs shard-locally (GpuWindowExec's clustered-distribution
        contract on the mesh). The batched-running variant re-uses the
        same kernel here — per shard the data is ONE batch, so the
        carried-state machinery is unnecessary (its sort child is
        skipped: the kernel re-sorts internally)."""
        from ..exec.window import BatchedRunningWindowExec, WindowExec
        ax, n = self.axis, self.n
        inner = node.children[0]
        if isinstance(node, BatchedRunningWindowExec) and \
                isinstance(inner, SortExec):
            inner = inner.children[0]
        child = self._lower(inner)
        kernel = WindowExec(inner, node.window_exprs) \
            if isinstance(node, BatchedRunningWindowExec) else node
        if not node.partition_by:
            def global_fn(env):
                g = all_gather_batch(child(env), n, ax)
                return _mask_to_shard0(kernel._compute(g), ax)
            return global_fn
        keys = node.partition_by

        def win_fn(env):
            b = child(env)
            kc = [e.eval(b) for e in keys]
            pids = hash_partition_ids(kc, n)
            pb = partition_batch(b, pids, n)
            local = flatten_partitions(all_to_all_partitions(pb, ax))
            return kernel._compute(local)
        return win_fn

    def _lower_shuffle(self, node: ShuffleExchangeExec):
        ax, n = self.axis, self.n
        child = self._lower(node.children[0])
        if node.sort_orders:
            orders = node.sort_orders

            def range_fn(env):
                batch = child(env)
                bounds = _inline_range_bounds(batch, orders, n, ax)
                keys = [o.expr.eval(batch) for o in orders]
                pids = range_partition_ids(
                    keys, bounds, [o.ascending for o in orders],
                    [o.nulls_first for o in orders])
                pb = partition_batch(batch, pids, n)
                return flatten_partitions(all_to_all_partitions(pb, ax))
            return range_fn
        if node.key_exprs:
            keys = node.key_exprs
            if self._hash_colocated(node):
                # Locality bypass on the mesh lane: the child already
                # placed every row by pmod(murmur3(keys), n) on THIS
                # mesh (its partitioning came up from a lowered hash
                # exchange on the same key sequence), so the all_to_all
                # would be the identity permutation. Hand the
                # shard-local batch through untouched.
                self.colocated_exchanges.append(node.exec_id)
                from ..obs import events as _events
                _events.emit("MeshColocationBypass", exec_id=node.exec_id,
                             keys=[repr(e) for e in keys])
                return child

            def hash_fn(env):
                batch = child(env)
                kc = [e.eval(batch) for e in keys]
                pids = hash_partition_ids(kc, n)
                pb = partition_batch(batch, pids, n)
                return flatten_partitions(all_to_all_partitions(pb, ax))
            return hash_fn
        if (node.num_partitions or 1) == 1:
            # concentrate everything on shard 0
            return lambda env: _mask_to_shard0(
                all_gather_batch(child(env), n, ax), ax)

        def rr_fn(env):
            batch = child(env)
            pids = round_robin_partition_ids(batch.capacity, n)
            pb = partition_batch(batch, pids, n)
            return flatten_partitions(all_to_all_partitions(pb, ax))
        return rr_fn

    def _lower_agg(self, node: HashAggregateExec):
        ax, n = self.axis, self.n
        if node.mode == PARTIAL:
            child = self._lower(node.children[0])
            return lambda env: node._update(child(env), jnp.int64(0))
        if node.mode == FINAL:
            # FINAL-merge fusion removed any project prefix from the
            # tree (arm_merge_fusion); re-apply it here, bottom-up,
            # before the merge — the mesh trace fuses it all anyway
            prefix = list(reversed(node._merge_fusion or []))

            def pre(b):
                for p in prefix:
                    b = p._project(b)
                return b
            ex = node.children[0]
            if (not node.group_exprs and
                    isinstance(ex, ShuffleExchangeExec) and
                    (ex.num_partitions or 1) == 1):
                # global aggregate: gather all partial states, merge on
                # every shard, report from shard 0 only (the merge is
                # replicated — cheap: one row of state per shard)
                inner = self._lower(ex.children[0])

                def global_fn(env):
                    gathered = all_gather_batch(inner(env), n, ax)
                    return _mask_to_shard0(
                        node._merge_finalize(pre(gathered)), ax)
                return global_fn
            child = self._lower(ex) if isinstance(ex, ShuffleExchangeExec) \
                else self._lower(node.children[0])
            return lambda env: node._merge_finalize(pre(child(env)))
        # COMPLETE single-stage: update + merge locally is only correct
        # on one shard — require staged plans on mesh
        raise UnsupportedMeshLowering("complete-mode aggregate")

    def _lower_join(self, node: _HashJoinBase):
        left = self._lower(node.children[0])
        right = self._lower(node.children[1])
        growth = self.join_growth

        def join_fn(env):
            lb, rb = left(env), right(env)
            probe, build = (lb, rb) if node.build_side == "right" \
                else (rb, lb)
            pk = [e.eval(probe) for e in node._probe_key_exprs]
            bk = [e.eval(build) for e in node._build_key_exprs]
            out_cap = round_pow2(probe.capacity * growth)
            jt = node.join_type
            if jt in ("left_semi", "left_anti"):
                out, total = K.semi_anti_join(
                    probe, bk, pk, build.live_mask(),
                    anti=(jt == "left_anti"),
                    scratch_capacity=out_cap)
            elif jt == "inner":
                out, total = K.inner_join(probe, build, pk, bk, out_cap)
            else:
                out, total = K.left_join(probe, build, pk, bk, out_cap)
            # the kernel reports the TRUE required size; overflow fails
            # the run (checked host-side) rather than dropping matches
            self._checks.append(total <= out_cap)
            return node._reorder_columns(out)
        return join_fn


def _inline_range_bounds(batch: ColumnarBatch, orders, n: int, axis: str):
    """Compute shared range bounds inside the trace: all_gather each key
    column, sort the gathered sample with the device comparator, take
    n-1 quantile rows. Every shard computes identical bounds (the
    all_gather is symmetric), which is all correctness needs."""
    keys = [o.expr.eval(batch) for o in orders]
    live = batch.live_mask()
    g_live = lax.all_gather(live, axis, axis=0, tiled=True)
    g_keys = []
    for kc in keys:
        if isinstance(kc, StringColumn):
            padded = lax.all_gather(kc.padded(), axis, axis=0, tiled=True)
            lens = lax.all_gather(kc.lengths(), axis, axis=0, tiled=True)
            valid = lax.all_gather(kc.validity, axis, axis=0, tiled=True)
            g_keys.append(string_from_padded(padded, lens, valid))
        else:
            data = lax.all_gather(kc.data, axis, axis=0, tiled=True)
            valid = lax.all_gather(kc.validity, axis, axis=0, tiled=True)
            g_keys.append(ColumnVector(data, valid, kc.dtype))
    perm = K.sort_indices(g_keys, [o.ascending for o in orders],
                          [o.nulls_first for o in orders], g_live)
    total = jnp.sum(g_live).astype(jnp.int32)
    bounds = []
    cut = jnp.arange(1, n, dtype=jnp.int32)
    cut_pos = jnp.minimum((cut * total) // n,
                          jnp.maximum(total - 1, 0))
    idx = jnp.take(perm, cut_pos)
    for gk in g_keys:
        if isinstance(gk, StringColumn):
            starts = jnp.take(gk.offsets[:-1], idx)
            lens = jnp.take(gk.lengths(), idx)
            w = gk.pad_bucket
            k = jnp.arange(w, dtype=jnp.int32)
            rows = jnp.take(
                gk.chars,
                jnp.clip(starts[:, None] + k[None, :], 0,
                         gk.char_capacity - 1))
            rows = jnp.where(k[None, :] < lens[:, None], rows,
                             jnp.zeros((), jnp.uint8))
            bounds.append(string_from_padded(
                rows, lens, jnp.take(gk.validity, idx)))
        else:
            bounds.append(ColumnVector(jnp.take(gk.data, idx),
                                       jnp.take(gk.validity, idx),
                                       gk.dtype))
    return bounds


def _batch_from_pydict_typed(data: dict, schema, capacity: int
                             ) -> ColumnarBatch:
    names = [n for n, _ in schema]
    n_rows = len(next(iter(data.values()))) if data else 0
    cols = []
    for name, dtype in schema:
        arr = np.asarray(data[name], dtype=object)
        mask = np.array([v is not None for v in arr], dtype=bool)
        cols.append(column_from_numpy(arr, capacity, dtype=dtype,
                                      mask=mask))
    return ColumnarBatch(cols, names, n_rows)


def _normalize_strings(batches: List[ColumnarBatch]) -> None:
    """Pad every shard's string columns to common char capacity and pad
    bucket so the shards stack into one leading-dim pytree."""
    if not batches:
        return
    for ci in range(len(batches[0].columns)):
        cols = [b.columns[ci] for b in batches]
        if not isinstance(cols[0], StringColumn):
            continue
        char_cap = max(c.char_capacity for c in cols)
        pad = max(c.pad_bucket for c in cols)
        for b, c in zip(batches, cols):
            chars = c.chars
            if c.char_capacity < char_cap:
                chars = jnp.concatenate(
                    [chars, jnp.zeros(char_cap - c.char_capacity,
                                      jnp.uint8)])
            b.columns[ci] = StringColumn(c.offsets, chars, c.validity,
                                         pad_bucket=pad)


def run_on_mesh(physical: TpuExec, mesh: Mesh,
                conf: Optional[SrtConf] = None,
                join_growth: int = 2,
                max_join_growth: int = 64) -> List[ColumnarBatch]:
    """Compile + run one plan over a mesh with whole-program join
    grow-and-retry: a traced SPMD program cannot grow a join output
    mid-flight the way the single-stream exec does per batch
    (exec/join.py _join_pair), so overflow reports re-lower the WHOLE
    plan at doubled growth until the true size fits — skew-free plans
    settle on the first compile."""
    g = join_growth
    while True:
        try:
            return MeshQueryExecutor(mesh, conf, join_growth=g) \
                .run(physical)
        except RuntimeError as e:
            if "mesh join output overflowed" not in str(e) \
                    or g >= max_join_growth:
                raise
            g *= 2
            # every retry MUST reset stateful exchange/broadcast nodes
            # before leaves re-execute
            physical.reset_for_rerun()
