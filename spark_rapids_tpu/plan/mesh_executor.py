"""Mesh executor: lower planner-produced physical plans to SPMD
programs over a jax.sharding.Mesh — one compiled program PER QUERY
STAGE.

The stage cut is the one plan/adaptive.py already makes for AQE
(everything between shuffle-exchange boundaries, ``stage_dag``); this
module compiles each stage to ONE ``jax.jit``-of-``shard_map`` program
over the device mesh and keeps stage outputs **device-resident**
between programs:

  stage body (child subtree of an exchange)  -> one sharded program
  exchange collective                        -> head of the CONSUMER
                                                stage's program:
    ShuffleExchangeExec(hash keys)   -> partition + lax.all_to_all
    ShuffleExchangeExec(range)       -> in-trace sampled bounds + a2a
    ShuffleExchangeExec(1 partition) -> lax.all_gather (+ shard-0 mask)
    resident exchange                -> identity hand-through pinned by
                                        with_sharding_constraint — the
                                        planner residency rule
                                        (overrides.mesh_resident_exchanges,
                                        the generalized
                                        MeshColocationBypass)
  BroadcastExchangeExec              -> replicated host-materialized
                                        input (partition-rule table) or
                                        in-program all_gather

Stage inputs map to PartitionSpecs through the declarative partition
rules (plan/partition_rules.py): stacked per-shard trees ride the data
axis, broadcast build sides are replicated. Nothing is serialized at a
stage boundary — bytes crossing one are recorded as
``shuffleBytesBypassed`` (they bypassed the serialized shuffle write
path entirely; ``shuffleBytesWritten`` stays 0 on mesh runs), and the
subset that rode an in-program collective also counts as
``shuffleBytesWire``.

A join whose static output capacity overflows retries ONLY its own
stage at doubled growth, re-using the already-materialized stage
inputs — the whole-plan grow-and-retry ladder (which re-lowered the
entire plan and re-executed every leaf per retry, and aborted q19 at
scale with an rc=-6 rendezvous abort: divergent per-device re-traces of
an ever-growing monolithic program) is gone. Stage programs are shared
process-wide by structural shape through jit_registry.shared_stage_jit
(one compile-ledger entry per stage shape, not per device or query),
and stages that cannot retry donate their single-consumer inputs.

The reference's equivalent is a p2p shuffle (UCX ActiveMessages,
RapidsShuffleClient.scala:169) feeding the same staged operators; on
TPU the exchange is a compiled collective riding ICI (SURVEY §2.7 "TPU
equivalent" row, §7 hard-part #5).

Leaves (scans, host relations) are executed on the host driver once
per query, split into per-shard slices, and placed with a
``NamedSharding`` over the mesh (parallel/shuffle.py stacked form);
everything above the leaves is traced.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnVector, ColumnarBatch, StringColumn,
                               choose_capacity, column_from_numpy,
                               round_pow2)
from ..conf import (MESH_BROADCAST_REPLICATED, MESH_DONATION,
                    MESH_MAX_JOIN_GROWTH, MESH_PARTITION_RULES,
                    MESH_STAGE_PROGRAMS, SrtConf, active_conf)
from ..exec.aggregate import FINAL, PARTIAL, HashAggregateExec
from ..exec.base import ExecContext, TpuExec
from ..exec.basic import (BatchScanExec, CoalesceBatchesExec, ExpandExec,
                          FilterExec, LocalLimitExec, ProjectExec, UnionExec)
from ..exec.exchange import BroadcastExchangeExec, ShuffleExchangeExec
from ..exec.join import _HashJoinBase
from ..exec.sort import SortExec, TopNExec
from ..obs import events as _events
from ..ops import kernels as K
from ..parallel.mesh import DATA_AXIS, mesh_key, tree_nbytes
from ..parallel.partition import (flatten_partitions, hash_partition_ids,
                                  partition_batch, range_partition_ids,
                                  round_robin_partition_ids,
                                  string_from_padded)
from ..parallel.shuffle import (all_gather_batch, all_to_all_partitions,
                                stack_shards, unstack_shards)
from ..robustness.faults import fault_point
from .partition_rules import (constrain_tree, is_replicated,
                              match_partition_rules, parse_rules, put_tree,
                              rule_path, spec_signature)
from .transitions import HostToDeviceExec


class UnsupportedMeshLowering(Exception):
    """Raised for plan nodes the mesh backend cannot lower (the caller
    falls back to single-process execution)."""


def _mask_to_shard0(batch: ColumnarBatch, axis: str) -> ColumnarBatch:
    keep = lax.axis_index(axis) == 0
    return ColumnarBatch(batch.columns, batch.names,
                         jnp.where(keep, batch.num_rows, 0)
                         .astype(jnp.int32))


def _exchange_kind(node: ShuffleExchangeExec) -> str:
    if node.sort_orders:
        return "range"
    if node.key_exprs:
        return "hash"
    if (node.num_partitions or 1) == 1:
        return "single"
    return "rr"


def _contains_shuffle(node) -> bool:
    if isinstance(node, ShuffleExchangeExec):
        return True
    return any(_contains_shuffle(c) for c in getattr(node, "children", []))


class _ArgSlot:
    """One positional input of a stage program: a host-materialized
    leaf stack or another stage's device-resident output."""

    __slots__ = ("kind", "node", "path", "spec", "key", "index")

    def __init__(self, kind: str, node, path: str, spec: P, index: int):
        self.kind = kind            # "leaf" | "stage"
        self.node = node
        self.path = path
        self.spec = spec
        self.key = (kind, id(node))
        self.index = index


class _StageBuild:
    """Per-attempt lowering state for one stage program: the ordered
    input slots, the traced join-overflow checks, and the structural
    signature (appended branch by branch during lowering) that keys the
    shared-program registry."""

    __slots__ = ("growth", "slots", "slot_by_key", "checks", "sig",
                 "has_join")

    def __init__(self, growth: int):
        self.growth = growth
        self.slots: List[_ArgSlot] = []
        self.slot_by_key: Dict = {}
        self.checks: List = []
        self.sig: List = []
        self.has_join = False


class MeshQueryExecutor:
    """Compiles and runs one physical plan on an n-device mesh, one
    sharded program per query stage (``srt.mesh.stagePrograms.enabled``;
    off = legacy single monolithic program, the fallback boundary)."""

    def __init__(self, mesh: Mesh, conf: Optional[SrtConf] = None,
                 axis: str = DATA_AXIS, join_growth: int = 2,
                 max_join_growth: Optional[int] = None):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.conf = conf or active_conf()
        self.join_growth = join_growth
        self._max_growth_override = max_join_growth
        self.rules = parse_rules(
            self.conf.get(MESH_PARTITION_RULES) or "", axis)
        #: exec_ids of exchanges lowered as device-resident identities
        self.colocated_exchanges: List[str] = []
        #: per-stage execution records (tests/observability)
        self.stage_records: List[dict] = []
        self.stage_retries = 0
        #: distinct host leaf materializations (a stage retry must NOT
        #: re-execute leaves — the q19 fix)
        self.leaf_executions = 0
        #: stage-boundary bytes handed through device-resident (never
        #: serialized) / subset that rode an in-program collective
        self.shuffle_bytes_bypassed = 0
        self.shuffle_bytes_wire = 0
        self._registered: set = set()
        self._resident: set = set()
        self._stage_outputs: Dict[int, object] = {}
        self._leaf_cache: Dict = {}
        self._build: Optional[_StageBuild] = None

    # ------------------------------------------------------------------
    # host driver
    # ------------------------------------------------------------------
    def run(self, physical: TpuExec) -> List[ColumnarBatch]:
        """Execute the plan; returns host-ordered result batches (shard
        order is partition order for sorted plans)."""
        from .overrides import mesh_resident_exchanges
        ctx = ExecContext(self.conf)
        #: kept for callers asserting on exchange metrics after the run
        self.last_ctx = ctx
        self._resident = mesh_resident_exchanges(physical, self.conf)
        staged = bool(self.conf.get(MESH_STAGE_PROGRAMS))
        if staged:
            from .adaptive import stage_dag
            stages, self._registered = stage_dag(physical)
        else:
            stages, self._registered = [], set()
        _events.emit("StageSubmitted", mode="mesh", num_shards=self.n,
                     join_growth=self.join_growth,
                     stage_programs=len(stages) + 1)
        for st in stages:
            body = st.exchange.children[0]
            label = f"s{st.order}:{type(self._unwrap(body)).__name__}"
            self._stage_outputs[id(st.exchange)] = \
                self._run_stage(body, ctx, label)
        out = self._run_stage(physical, ctx, "root")
        jax.block_until_ready(jax.tree_util.tree_leaves(out))
        _events.emit("StageCompleted", mode="mesh", num_shards=self.n,
                     overflowed=False, retries=self.stage_retries,
                     bytes_bypassed=self.shuffle_bytes_bypassed,
                     bytes_wire=self.shuffle_bytes_wire)
        return [b for b in unstack_shards(out) if int(b.num_rows) > 0]

    def _unwrap(self, node):
        """Trace through single-box fusion wrappers: a stage program is
        already one XLA computation, so fusion adds nothing here."""
        while True:
            chain = getattr(node, "mesh_chain_root", None)
            if chain is None:
                return node
            node = chain()

    def _is_leaf(self, node) -> bool:
        return isinstance(node, (BatchScanExec, HostToDeviceExec)) or \
            not node.children

    def _run_stage(self, root, ctx: ExecContext, label: str):
        """Compile + run one stage program; returns the stacked,
        device-resident output tree. Join-overflow retries re-lower
        THIS stage only, at doubled growth, against the retained
        inputs."""
        root_u = self._unwrap(root)
        if self._is_leaf(root_u):
            # trivial stage (exchange directly over a scan): the stage
            # output IS the placed leaf stack — no program to compile
            return self._leaf_value(root_u, ctx, P(self.axis))
        if isinstance(root_u, ShuffleExchangeExec) \
                and id(root_u) in self._registered \
                and id(root_u) in self._resident \
                and id(root_u) in self._stage_outputs:
            # plan root is a resident exchange: pure hand-through
            return self._account_stage_input(root_u, ctx)
        growth = self.join_growth
        if self._max_growth_override is not None:
            max_growth = int(self._max_growth_override)
        else:
            try:
                max_growth = int(self.conf.get(MESH_MAX_JOIN_GROWTH))
            except Exception:
                max_growth = 64
        max_growth = max(max_growth, growth)
        args = None
        retries = 0
        while True:
            build = _StageBuild(growth)
            self._build = build
            try:
                fn = self._lower(root, "")
            finally:
                self._build = None
            if args is None:
                args = [self._materialize_slot(s, ctx)
                        for s in build.slots]
            program, record = self._stage_program(build, fn, label)
            fault_point("mesh.stage.run", label)
            out, ok = program(*args)
            if bool(jnp.all(ok)):
                record["retries"] = retries
                self.stage_records.append(record)
                return out
            if growth * 2 > max_growth:
                raise RuntimeError(
                    "mesh join output overflowed its static capacity "
                    f"(stage {label}) at maximum join growth "
                    f"{growth} — results would silently drop rows; "
                    "raise srt.mesh.maxJoinGrowth or repartition finer")
            growth *= 2
            retries += 1
            self.stage_retries += 1
            _events.emit("MeshStageRetry", stage=label,
                         join_growth=growth)

    # ------------------------------------------------------------------
    # stage inputs
    # ------------------------------------------------------------------
    def _slot(self, kind: str, node, path: str, spec: P) -> _ArgSlot:
        b = self._build
        key = (kind, id(node))
        slot = b.slot_by_key.get(key)
        if slot is None:
            slot = _ArgSlot(kind, node, path, spec, len(b.slots))
            b.slots.append(slot)
            b.slot_by_key[key] = slot
        return slot

    def _materialize_slot(self, slot: _ArgSlot, ctx: ExecContext):
        if slot.kind == "stage":
            return self._account_stage_input(slot.node, ctx)
        return self._leaf_value(slot.node, ctx, slot.spec)

    def _account_stage_input(self, node: ShuffleExchangeExec,
                             ctx: ExecContext):
        """Fetch a child stage's device-resident output and account its
        bytes ONCE per consuming stage (retries re-use the fetched
        value and never re-count)."""
        val = self._stage_outputs[id(node)]
        nbytes = tree_nbytes(val)
        resident = id(node) in self._resident
        node.record_mesh_exchange(ctx, nbytes, resident)
        self.shuffle_bytes_bypassed += nbytes
        if resident:
            if node.exec_id not in self.colocated_exchanges:
                self.colocated_exchanges.append(node.exec_id)
            _events.emit("MeshColocationBypass", exec_id=node.exec_id,
                         keys=[repr(e) for e in (node.key_exprs or [])])
        else:
            self.shuffle_bytes_wire += nbytes
        return val

    def _leaf_value(self, leaf, ctx: ExecContext, spec: P):
        """Host-execute a leaf subtree once and place it on the mesh:
        stacked per-shard slices split over the data axis, or one full
        replicated batch (broadcast build sides)."""
        replicated = is_replicated(spec)
        cache_key = (id(leaf), replicated)
        val = self._leaf_cache.get(cache_key)
        if val is not None:
            return val
        batches = self._leaf_batches(leaf, ctx,
                                     1 if replicated else self.n)
        self.leaf_executions += 1
        if replicated:
            val = batches[0]
        else:
            _normalize_strings(batches)
            val = stack_shards(batches)
        val = put_tree(val, self.mesh, spec)
        self._leaf_cache[cache_key] = val
        return val

    def _leaf_batches(self, leaf, ctx: ExecContext,
                      n_splits: int) -> List[ColumnarBatch]:
        """Host-execute a leaf subtree and split its rows into
        ``n_splits`` identically-shaped slices (contiguous split, so
        input order is preserved across the shard sequence)."""
        from .host_table import batch_to_table, concat_tables, to_pydict
        schema = leaf.output_schema
        tables = [batch_to_table(b) for b in leaf.execute(ctx)
                  if int(b.num_rows) > 0]
        if tables:
            table = concat_tables(tables)
            data = to_pydict(table)
            total = table.num_rows
        else:
            data = {n: [] for n, _ in schema}
            total = 0
        per = -(-max(total, 1) // n_splits)
        cap = choose_capacity(max(per, 8))
        names = [n for n, _ in schema]
        out = []
        for s in range(n_splits):
            lo, hi = min(s * per, total), min((s + 1) * per, total)
            chunk = {n: data[n][lo:hi] for n in names}
            out.append(_batch_from_pydict_typed(chunk, schema, cap))
        return out

    # ------------------------------------------------------------------
    # program assembly
    # ------------------------------------------------------------------
    def _stage_program(self, build: _StageBuild, fn: Callable,
                      label: str) -> Tuple[Callable, dict]:
        slots = list(build.slots)
        ax, mesh = self.axis, self.mesh
        donate: Tuple[int, ...] = ()
        if not build.has_join and self.conf.get(MESH_DONATION):
            # joins may overflow and retry against the same inputs, so
            # only join-free stages donate; multi-consumer exchanges
            # (full-outer sharing) are drained again by a later stage
            donate = tuple(
                s.index for s in slots
                if s.kind == "stage"
                and getattr(s.node, "_planned_consumers", 1) <= 1)
        in_specs = tuple(s.spec for s in slots)

        def shard_step(*vals):
            env = {}
            for s, v in zip(slots, vals):
                env[s.key] = v if is_replicated(s.spec) else \
                    jax.tree_util.tree_map(lambda x: x[0], v)
            build.checks = []
            out = fn(env)
            ok = jnp.ones((), jnp.bool_)
            for c in build.checks:
                ok = ok & c
            return jax.tree_util.tree_map(lambda x: x[None], (out, ok))

        def build_program():
            from ..shims import shard_map as _shard_map
            sm = _shard_map()
            # the replication-check kwarg was renamed check_rep ->
            # check_vma across jax releases; pass whichever applies
            import inspect
            sm_params = inspect.signature(sm).parameters
            check_kw = {}
            for name in ("check_vma", "check_rep"):
                if name in sm_params:
                    check_kw[name] = False
                    break
            inner = sm(shard_step, mesh=mesh, in_specs=in_specs,
                       out_specs=P(ax), **check_kw)

            def staged(*xs):
                # pin every input to its partition-rule sharding: a
                # device-resident stage output is consumed in place,
                # anything placed differently is resharded by XLA
                pinned = tuple(constrain_tree(x, mesh, s.spec)
                               for x, s in zip(xs, slots))
                return inner(*pinned)
            return staged

        key_parts = ["mesh_stage_v1", mesh_key(mesh), ax, build.growth,
                     tuple((s.kind, spec_signature(s.spec))
                           for s in slots),
                     tuple(build.sig)]
        from .. import jit_registry
        program = jit_registry.shared_stage_jit(
            build_program, key_parts, __name__, f"mesh_stage[{label}]",
            donate_argnums=donate)
        record = {
            "label": label,
            "n_inputs": len(slots),
            "donated": list(donate),
            "growth": build.growth,
            "resident": [s.node.exec_id for s in slots
                         if s.kind == "stage"
                         and id(s.node) in self._resident],
        }
        return program, record

    # ------------------------------------------------------------------
    # lowering
    # ------------------------------------------------------------------
    def _lower(self, node: TpuExec,
               path: str) -> Callable[[Dict], ColumnarBatch]:
        ax, n = self.axis, self.n
        b = self._build
        chain = getattr(node, "mesh_chain_root", None)
        if chain is not None:
            # fusion wrappers: the stage nodes keep their unfused child
            # links, so lowering the terminal recovers the whole chain
            b.sig.append(("fused", type(node).__name__))
            return self._lower(chain(), path)
        path = rule_path(path, node)
        if self._is_leaf(node):
            slot = self._slot("leaf", node, path, P(ax))
            b.sig.append(("leaf", type(node).__name__,
                          list(node.output_schema)))
            key = slot.key
            return lambda env: env[key]

        if isinstance(node, ProjectExec):
            if node._eager:
                raise UnsupportedMeshLowering(
                    "eager projection (uuid/input_file/raise_error)")
            b.sig.append(("project", node.exprs))
            child = self._lower(node.children[0], path)

            def proj_fn(env):
                batch = child(env)
                # context expressions see shard-unique positions:
                # partition_id = shard index, row offsets disjoint
                idx = lax.axis_index(ax)
                return node._project_ctx(
                    batch, idx.astype(jnp.int64) * batch.capacity,
                    idx.astype(jnp.int32))
            return proj_fn

        if isinstance(node, FilterExec):
            b.sig.append(("filter", node.condition))
            child = self._lower(node.children[0], path)
            return lambda env: node._filter(child(env))

        if isinstance(node, CoalesceBatchesExec):
            return self._lower(node.children[0], path)

        from ..exec.pipeline import PrefetchExec
        if isinstance(node, PrefetchExec):
            # host-side pipelining has no meaning inside one traced
            # mesh program: transparent pass-through
            return self._lower(node.children[0], path)

        if isinstance(node, UnionExec):
            b.sig.append(("union", len(node.children)))
            kids = [self._lower(c, path) for c in node.children]

            def union_fn(env):
                batches = [k(env) for k in kids]
                cap = round_pow2(sum(x.capacity for x in batches))
                return K.concat_batches(batches, cap)
            return union_fn

        if isinstance(node, BroadcastExchangeExec):
            return self._lower_broadcast(node, path)

        if isinstance(node, ShuffleExchangeExec):
            return self._lower_shuffle(node, path)

        if isinstance(node, HashAggregateExec):
            return self._lower_agg(node, path)

        if isinstance(node, _HashJoinBase):
            return self._lower_join(node, path)

        if isinstance(node, TopNExec):
            b.sig.append(("topn", node.order, node.limit))
            child = self._lower(node.children[0], path)

            def topn_fn(env):
                local = node._topn(child(env))
                gathered = all_gather_batch(local, n, ax)
                return _mask_to_shard0(node._topn(gathered), ax)
            return topn_fn

        if isinstance(node, SortExec):
            b.sig.append(("sort", node.order))
            child = self._lower(node.children[0], path)
            # child is range-partitioned (planner): local sort per
            # shard; shard order == partition order == global order
            return lambda env: node._sort_one(child(env))

        from ..exec.basic import SampleExec
        if isinstance(node, SampleExec):
            b.sig.append(("sample", node.fraction, node.seed))
            child = self._lower(node.children[0], path)

            def sample_fn(env):
                batch = child(env)
                off = lax.axis_index(ax).astype(jnp.int64) * batch.capacity
                return node._sample(batch, off)
            return sample_fn

        if isinstance(node, ExpandExec):
            from ..exec.basic import _expand_project_builder
            b.sig.append(("expand", node.projections))
            child = self._lower(node.children[0], path)
            # node.projections are already dtype-unified across lists
            # (ExpandExec.__init__ casts divergent slots); build raw
            # un-jitted projectors — the stage trace jits the shard
            out_names = [nm for nm, _ in node.output_schema]
            fns = [_expand_project_builder(p, out_names)
                   for p in node.projections]

            def expand_fn(env):
                batch = child(env)
                outs = [f(batch) for f in fns]
                cap = round_pow2(sum(o.capacity for o in outs))
                return K.concat_batches(outs, cap)
            return expand_fn

        from ..exec.window import BatchedRunningWindowExec, WindowExec
        if isinstance(node, (WindowExec, BatchedRunningWindowExec)):
            return self._lower_window(node, path)

        if isinstance(node, LocalLimitExec):
            b.sig.append(("limit", node.limit))
            child = self._lower(node.children[0], path)

            def limit_fn(env):
                gathered = all_gather_batch(child(env), n, ax)
                return _mask_to_shard0(
                    K.local_limit(gathered, node.limit), ax)
            return limit_fn

        raise UnsupportedMeshLowering(type(node).__name__)

    def _lower_broadcast(self, node: BroadcastExchangeExec, path: str):
        """Broadcast build sides: the partition-rule table maps the
        subtree to replicated placement — host-materialize it once and
        hand every shard the full batch, no collective at all. A
        broadcast subtree that itself contains shuffles (or a user rule
        remapping it to the data axis) lowers per-shard with an
        in-program all_gather instead."""
        ax, n = self.axis, self.n
        b = self._build
        sub = node.children[0]
        spec = match_partition_rules(self.rules, path)
        if (is_replicated(spec)
                and self.conf.get(MESH_BROADCAST_REPLICATED)
                and not _contains_shuffle(sub)):
            slot = self._slot("leaf", sub, path, P())
            b.sig.append(("bcast_replicated",
                          list(sub.output_schema)))
            key = slot.key
            return lambda env: env[key]
        b.sig.append(("bcast_gather",))
        child = self._lower(sub, path)
        return lambda env: all_gather_batch(child(env), n, ax)

    def _lower_shuffle(self, node: ShuffleExchangeExec, path: str):
        b = self._build
        ax = self.axis
        kind = _exchange_kind(node)
        resident = id(node) in self._resident
        if id(node) in self._registered:
            # stage input: the child subtree ran as its own program;
            # its output arrives device-resident
            slot = self._slot("stage", node, path, P(ax))
            b.sig.append(("stage_in", kind, resident,
                          node.key_exprs, node.sort_orders,
                          list(node.output_schema)))
            key = slot.key
            reader = lambda env: env[key]  # noqa: E731
            if resident:
                # sharding-constraint exchange: rows are already on
                # their target shard (planner residency rule); the
                # with_sharding_constraint pin in the program wrapper
                # is the whole exchange
                return reader
            return self._exchange_collective(node, reader)
        # in-program exchange: whole-plan mode, or an exchange nested
        # under a broadcast subtree (not a registered stage)
        child = self._lower(node.children[0], path)
        if resident:
            if node.exec_id not in self.colocated_exchanges:
                self.colocated_exchanges.append(node.exec_id)
                _events.emit("MeshColocationBypass",
                             exec_id=node.exec_id,
                             keys=[repr(e)
                                   for e in (node.key_exprs or [])])
            b.sig.append(("colocated", node.key_exprs))
            return child
        b.sig.append(("exchange", kind, node.key_exprs,
                      node.sort_orders))
        return self._exchange_collective(node, child)

    def _exchange_collective(self, node: ShuffleExchangeExec,
                             child: Callable):
        """The exchange's collective form, applied to the per-shard
        batch ``child`` yields (a stage-input reader or an in-program
        subtree)."""
        ax, n = self.axis, self.n
        if node.sort_orders:
            orders = node.sort_orders

            def range_fn(env):
                batch = child(env)
                bounds = _inline_range_bounds(batch, orders, n, ax)
                keys = [o.expr.eval(batch) for o in orders]
                pids = range_partition_ids(
                    keys, bounds, [o.ascending for o in orders],
                    [o.nulls_first for o in orders])
                pb = partition_batch(batch, pids, n)
                return flatten_partitions(all_to_all_partitions(pb, ax))
            return range_fn
        if node.key_exprs:
            keys = node.key_exprs

            def hash_fn(env):
                batch = child(env)
                kc = [e.eval(batch) for e in keys]
                pids = hash_partition_ids(kc, n)
                pb = partition_batch(batch, pids, n)
                return flatten_partitions(all_to_all_partitions(pb, ax))
            return hash_fn
        if (node.num_partitions or 1) == 1:
            # concentrate everything on shard 0
            return lambda env: _mask_to_shard0(
                all_gather_batch(child(env), n, ax), ax)

        def rr_fn(env):
            batch = child(env)
            pids = round_robin_partition_ids(batch.capacity, n)
            pb = partition_batch(batch, pids, n)
            return flatten_partitions(all_to_all_partitions(pb, ax))
        return rr_fn

    def _lower_agg(self, node: HashAggregateExec, path: str):
        ax, n = self.axis, self.n
        b = self._build
        if node.mode == PARTIAL:
            b.sig.append(("agg_partial", node.group_exprs,
                          node.agg_exprs))
            child = self._lower(node.children[0], path)
            return lambda env: node._update(child(env), jnp.int64(0))
        if node.mode == FINAL:
            # FINAL-merge fusion removed any project prefix from the
            # tree (arm_merge_fusion); re-apply it here, bottom-up,
            # before the merge — the stage trace fuses it all anyway
            prefix = list(reversed(node._merge_fusion or []))
            b.sig.append(("agg_final", node.group_exprs, node.agg_exprs,
                          [p.exprs for p in prefix]))

            def pre(batch):
                for p in prefix:
                    batch = p._project(batch)
                return batch
            ex = node.children[0]
            if (not node.group_exprs and
                    isinstance(ex, ShuffleExchangeExec) and
                    (ex.num_partitions or 1) == 1):
                # global aggregate: gather all partial states, merge on
                # every shard, report from shard 0 only (the merge is
                # replicated — cheap: one row of state per shard)
                if id(ex) in self._registered:
                    slot = self._slot("stage", ex,
                                      rule_path(path, ex), P(ax))
                    b.sig.append(("stage_in", "single", False,
                                  list(ex.output_schema)))
                    key = slot.key
                    inner = lambda env: env[key]  # noqa: E731
                else:
                    inner = self._lower(ex.children[0],
                                        rule_path(path, ex))

                def global_fn(env):
                    gathered = all_gather_batch(inner(env), n, ax)
                    return _mask_to_shard0(
                        node._merge_finalize(pre(gathered)), ax)
                return global_fn
            child = self._lower(ex, path) \
                if isinstance(ex, ShuffleExchangeExec) \
                else self._lower(node.children[0], path)
            return lambda env: node._merge_finalize(pre(child(env)))
        # COMPLETE single-stage: update + merge locally is only correct
        # on one shard — require staged plans on mesh
        raise UnsupportedMeshLowering("complete-mode aggregate")

    def _lower_join(self, node: _HashJoinBase, path: str):
        b = self._build
        b.has_join = True
        b.sig.append(("join", node.join_type, node.build_side,
                      node._probe_key_exprs, node._build_key_exprs))
        left = self._lower(node.children[0], path)
        right = self._lower(node.children[1], path)
        growth = b.growth

        def join_fn(env):
            lb, rb = left(env), right(env)
            probe, build = (lb, rb) if node.build_side == "right" \
                else (rb, lb)
            pk = [e.eval(probe) for e in node._probe_key_exprs]
            bk = [e.eval(build) for e in node._build_key_exprs]
            out_cap = round_pow2(probe.capacity * growth)
            jt = node.join_type
            if jt in ("left_semi", "left_anti"):
                out, total = K.semi_anti_join(
                    probe, bk, pk, build.live_mask(),
                    anti=(jt == "left_anti"),
                    scratch_capacity=out_cap)
            elif jt == "inner":
                out, total = K.inner_join(probe, build, pk, bk, out_cap)
            else:
                out, total = K.left_join(probe, build, pk, bk, out_cap)
            # the kernel reports the TRUE required size; overflow fails
            # the stage (checked host-side), which retries at doubled
            # growth instead of silently dropping matches
            b.checks.append(total <= out_cap)
            return node._reorder_columns(out)
        return join_fn

    def _lower_window(self, node, path: str):
        """Window partitions co-locate via hash all-to-all on the
        partition keys, then the whole-partition segmented-scan kernel
        runs shard-locally (GpuWindowExec's clustered-distribution
        contract on the mesh). The batched-running variant re-uses the
        same kernel here — per shard the data is ONE batch, so the
        carried-state machinery is unnecessary (its sort child is
        skipped: the kernel re-sorts internally)."""
        from ..exec.window import BatchedRunningWindowExec, WindowExec
        ax, n = self.axis, self.n
        b = self._build
        inner = node.children[0]
        if isinstance(node, BatchedRunningWindowExec) and \
                isinstance(inner, SortExec):
            inner = inner.children[0]
        b.sig.append(("window", type(node).__name__, node.window_exprs,
                      node.partition_by))
        child = self._lower(inner, path)
        kernel = WindowExec(inner, node.window_exprs) \
            if isinstance(node, BatchedRunningWindowExec) else node
        if not node.partition_by:
            def global_fn(env):
                g = all_gather_batch(child(env), n, ax)
                return _mask_to_shard0(kernel._compute(g), ax)
            return global_fn
        keys = node.partition_by

        def win_fn(env):
            batch = child(env)
            kc = [e.eval(batch) for e in keys]
            pids = hash_partition_ids(kc, n)
            pb = partition_batch(batch, pids, n)
            local = flatten_partitions(all_to_all_partitions(pb, ax))
            return kernel._compute(local)
        return win_fn


def _inline_range_bounds(batch: ColumnarBatch, orders, n: int, axis: str):
    """Compute shared range bounds inside the trace: all_gather each key
    column, sort the gathered sample with the device comparator, take
    n-1 quantile rows. Every shard computes identical bounds (the
    all_gather is symmetric), which is all correctness needs."""
    keys = [o.expr.eval(batch) for o in orders]
    live = batch.live_mask()
    g_live = lax.all_gather(live, axis, axis=0, tiled=True)
    g_keys = []
    for kc in keys:
        if isinstance(kc, StringColumn):
            padded = lax.all_gather(kc.padded(), axis, axis=0, tiled=True)
            lens = lax.all_gather(kc.lengths(), axis, axis=0, tiled=True)
            valid = lax.all_gather(kc.validity, axis, axis=0, tiled=True)
            g_keys.append(string_from_padded(padded, lens, valid))
        else:
            data = lax.all_gather(kc.data, axis, axis=0, tiled=True)
            valid = lax.all_gather(kc.validity, axis, axis=0, tiled=True)
            g_keys.append(ColumnVector(data, valid, kc.dtype))
    perm = K.sort_indices(g_keys, [o.ascending for o in orders],
                          [o.nulls_first for o in orders], g_live)
    total = jnp.sum(g_live).astype(jnp.int32)
    bounds = []
    cut = jnp.arange(1, n, dtype=jnp.int32)
    cut_pos = jnp.minimum((cut * total) // n,
                          jnp.maximum(total - 1, 0))
    idx = jnp.take(perm, cut_pos)
    for gk in g_keys:
        if isinstance(gk, StringColumn):
            starts = jnp.take(gk.offsets[:-1], idx)
            lens = jnp.take(gk.lengths(), idx)
            w = gk.pad_bucket
            k = jnp.arange(w, dtype=jnp.int32)
            rows = jnp.take(
                gk.chars,
                jnp.clip(starts[:, None] + k[None, :], 0,
                         gk.char_capacity - 1))
            rows = jnp.where(k[None, :] < lens[:, None], rows,
                             jnp.zeros((), jnp.uint8))
            bounds.append(string_from_padded(
                rows, lens, jnp.take(gk.validity, idx)))
        else:
            bounds.append(ColumnVector(jnp.take(gk.data, idx),
                                       jnp.take(gk.validity, idx),
                                       gk.dtype))
    return bounds


def _batch_from_pydict_typed(data: dict, schema, capacity: int
                             ) -> ColumnarBatch:
    names = [n for n, _ in schema]
    cols = []
    for name, dtype in schema:
        arr = np.asarray(data[name], dtype=object)
        mask = np.array([v is not None for v in arr], dtype=bool)
        cols.append(column_from_numpy(arr, capacity, dtype=dtype,
                                      mask=mask))
    n_rows = len(data[names[0]]) if names else 0
    return ColumnarBatch(cols, names, n_rows)


def _normalize_strings(batches: List[ColumnarBatch]) -> None:
    """Pad every shard's string columns to common char capacity and pad
    bucket so the shards stack into one leading-dim pytree."""
    if not batches:
        return
    for ci in range(len(batches[0].columns)):
        cols = [b.columns[ci] for b in batches]
        if not isinstance(cols[0], StringColumn):
            continue
        char_cap = max(c.char_capacity for c in cols)
        pad = max(c.pad_bucket for c in cols)
        for b, c in zip(batches, cols):
            chars = c.chars
            if c.char_capacity < char_cap:
                chars = jnp.concatenate(
                    [chars, jnp.zeros(char_cap - c.char_capacity,
                                      jnp.uint8)])
            b.columns[ci] = StringColumn(c.offsets, chars, c.validity,
                                         pad_bucket=pad)


def run_on_mesh(physical: TpuExec, mesh: Mesh,
                conf: Optional[SrtConf] = None,
                join_growth: int = 2,
                max_join_growth: Optional[int] = None
                ) -> List[ColumnarBatch]:
    """Compile + run one plan over a mesh. Join-overflow handling is
    per stage and internal: only the overflowing stage re-lowers at
    doubled growth (bounded by ``srt.mesh.maxJoinGrowth`` /
    ``max_join_growth``) against its retained inputs — leaves execute
    exactly once per query."""
    return MeshQueryExecutor(mesh, conf, join_growth=join_growth,
                             max_join_growth=max_join_growth) \
        .run(physical)


def run_on_mesh_or_fallback(physical: TpuExec, mesh: Mesh,
                            conf: Optional[SrtConf] = None
                            ) -> Tuple[List[ColumnarBatch], str]:
    """Mesh execution with clean degradation: any mesh-side failure
    (unsupported lowering, stage-program fault, overflow past the
    growth cap) emits a ``MeshFallback`` event, resets the plan's
    stateful nodes, and re-executes serialized single-stream — the
    fallback boundary tools/chaos_check.py seeds faults into. Returns
    (batches, "mesh" | "serialized")."""
    conf = conf or active_conf()
    try:
        return run_on_mesh(physical, mesh, conf), "mesh"
    except (KeyboardInterrupt, SystemExit):
        raise
    except Exception as e:
        _events.emit("MeshFallback",
                     error=f"{type(e).__name__}: {e}")
        physical.reset_for_rerun()
        ctx = ExecContext(conf)
        return list(physical.execute(ctx)), "serialized"
