"""Adaptive query execution: stage-boundary re-planning from measured
runtime statistics (Spark's AdaptiveSparkPlanExec recast for this
engine's pull-based executor).

The physical plan breaks into *query stages* at shuffle-exchange
boundaries. Stages materialize in dependency order (deepest first,
build side before probe side); after each map phase completes, the
exact per-(map, reduce) byte sizes recorded by the shuffle manager
feed four re-planning rules over the not-yet-started remainder:

* **coalescePartitions** — undersized reduce partitions group together
  until they reach a target byte size (or row floor), one grouping
  applied to every consumer of the exchange so join keys stay aligned.
* **skewJoin** — an oversized partition feeding a shuffled hash join
  splits the probe side into map-id slices, each joined against the
  full build partition (GpuSubPartitionHashJoin's decomposition driven
  from measured sizes instead of estimates).
* **joinStrategy** — a build side that materialized small demotes the
  partitioned join to a broadcast-style single stream, bypassing the
  probe-side exchange entirely; a broadcast build that materialized
  HUGE falls back to sub-partitioned joining so the single hash table
  never exceeds the configured byte bound.
* **speculation** — straggler map tasks re-execute on idle workers,
  first result wins (parallel/cluster.py's barrier owns the protocol;
  this module only defines eligibility).

Decisions are *pure functions of globally gathered statistics*: in
cluster mode every worker derives the identical decision from the
identical stats (divergent local decisions would deadlock the shuffle
barriers), so there is no decision broadcast. Each decision is
computed once, cached on the consuming node, and announced through an
``AdaptivePlanChanged`` event (plus ``SkewSplit`` per split partition)
so ``tools/history_report.py`` can reconstruct what the optimizer did
and why.

Two entry styles share the same rule functions:

* ``adaptive_execute(physical, ctx)`` — the session/cluster pull loops
  route through this; it materializes stages in dependency order and
  attaches decisions eagerly, so by the time the root pulls, the
  remainder of the plan is already re-planned.
* lazy — operators (``ShuffledHashJoinExec``, ``HashAggregateExec``)
  ask ``join_decision`` / ``stage_groups`` at first consumption; if the
  eager pass already ran, the cached decision is returned, otherwise it
  is computed on the spot. This keeps direct ``physical.execute(ctx)``
  callers (tests, embedded uses) on identical behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from ..conf import (ADAPTIVE_BROADCAST_BYTES, ADAPTIVE_BROADCAST_ROWS,
                    ADAPTIVE_COALESCE_ENABLED, ADAPTIVE_ENABLED,
                    ADAPTIVE_JOIN_ENABLED, ADAPTIVE_MIN_PARTITION_ROWS,
                    ADAPTIVE_SKEW_BYTES, ADAPTIVE_SKEW_ENABLED,
                    ADAPTIVE_SKEW_ROWS, ADAPTIVE_TARGET_BYTES,
                    BROADCAST_THRESHOLD_ROWS)
from ..obs import events as _events

#: hard cap on skew slices per partition — each slice re-reads the full
#: build partition, so unbounded fan-out would trade skew for overhead
MAX_SKEW_SLICES = 16

_UNSET = object()


# --- decisions ------------------------------------------------------------

@dataclass
class JoinDecision:
    """Cached outcome of the adaptive rules for one shuffled hash join.

    ``mode``:
      * ``"static"`` — adaptive stood down (disabled, pinned layout, or
        children are not both shuffle exchanges): plain partition zip.
      * ``"broadcast_build"`` — joinStrategy demotion: stream the full
        build side once, probe side bypasses its exchange.
      * ``"partitioned"`` — partition-wise join; ``out_groups`` is None
        when measurement changed nothing, else the coalesced/split
        grouping with ``probe_mod`` carrying skew slice specs.
    """
    mode: str
    out_groups: Optional[List[List[int]]] = None
    build_groups: Optional[List[List[int]]] = None
    probe_mod: Dict[int, Tuple[int, int]] = field(default_factory=dict)
    n_skewed: int = 0


def _conf(ctx, entry, default=None):
    try:
        return ctx.conf.get(entry)
    except Exception:
        return default


def _coalesce(ctx, exchange, rows: List[int], nbytes: List[int]):
    """Shared coalesce arithmetic: byte-target grouping with a row
    floor. Returns the grouping (possibly identity)."""
    from ..exec.exchange import ShuffleExchangeExec
    if not _conf(ctx, ADAPTIVE_COALESCE_ENABLED, True):
        return [[i] for i in range(len(rows))]
    return ShuffleExchangeExec.coalesce_groups(
        rows, _conf(ctx, ADAPTIVE_MIN_PARTITION_ROWS, 1 << 16),
        byte_counts=nbytes,
        target_bytes=_conf(ctx, ADAPTIVE_TARGET_BYTES, 0))


def stage_groups(ctx, exchange) -> Optional[List[List[int]]]:
    """coalescePartitions decision for a single-consumer exchange (a
    FINAL aggregate's input). Returns the grouping, or None when the
    measurement changed nothing. Cached on the exchange; the decision
    event fires once, at computation time."""
    cached = getattr(exchange, "_adaptive_groups_cache", _UNSET)
    if cached is not _UNSET:
        return cached
    groups = None
    if _conf(ctx, ADAPTIVE_ENABLED, False) and \
            _conf(ctx, ADAPTIVE_COALESCE_ENABLED, True) and \
            not getattr(exchange, "preserve_partitioning", False):
        rows, nbytes = exchange.materialized_stats(ctx)
        g = _coalesce(ctx, exchange, rows, nbytes)
        if len(g) < len(rows):
            groups = g
            _events.emit("AdaptivePlanChanged", rule="coalescePartitions",
                         shuffle_id=exchange.shuffle_id,
                         partitions_before=len(rows),
                         partitions_after=len(g),
                         total_rows=sum(rows), total_bytes=sum(nbytes))
    exchange._adaptive_groups_cache = groups
    return groups


def join_decision(ctx, join) -> JoinDecision:
    """All adaptive rules for one ShuffledHashJoinExec, computed from
    the measured sizes of its child exchanges and cached on the node."""
    cached = getattr(join, "_adaptive_decision", None)
    if cached is not None:
        return cached
    d = _compute_join_decision(ctx, join)
    join._adaptive_decision = d
    return d


def _compute_join_decision(ctx, join) -> JoinDecision:
    from ..exec.exchange import ShuffleExchangeExec
    if not _conf(ctx, ADAPTIVE_ENABLED, False) or join.preserve_partitioning:
        return JoinDecision("static")
    l, r = join.children[0], join.children[1]
    if not (isinstance(l, ShuffleExchangeExec) and
            isinstance(r, ShuffleExchangeExec)):
        return JoinDecision("static")
    probe_is_left = join.build_side == "right"
    build_x = r if probe_is_left else l
    probe_x = l if probe_is_left else r

    # -- joinStrategy: demote on MEASURED build size (build side
    # materializes first; on demotion the probe exchange never runs) --
    # Never demote over a stage-retry REUSED exchange: measured stats
    # count only the attempt's freshly written maps (renamed blocks are
    # invisible, so a reused build side measures near-zero and demotes
    # falsely), and the demoted path streams the probe exchange's
    # CHILD, which reuse sharded down to the freshly adopted ids —
    # silently dropping every surviving worker's own rows.
    reused_side = (ctx.cluster is not None and
                   (build_x.shuffle_id in ctx.cluster.reusable_sids or
                    probe_x.shuffle_id in ctx.cluster.reusable_sids))
    if _conf(ctx, ADAPTIVE_JOIN_ENABLED, True) and not reused_side:
        b_rows, b_bytes = build_x.materialized_stats(ctx)
        rows_thr = _conf(ctx, ADAPTIVE_BROADCAST_ROWS, 0) or \
            _conf(ctx, BROADCAST_THRESHOLD_ROWS, 0)
        bytes_thr = _conf(ctx, ADAPTIVE_BROADCAST_BYTES, 0)
        total_rows, total_bytes = sum(b_rows), sum(b_bytes)
        if total_rows <= rows_thr or (bytes_thr > 0 and
                                      total_bytes <= bytes_thr):
            _events.emit("AdaptivePlanChanged", rule="joinStrategy",
                         decision="broadcast_build",
                         join=join.node_description(),
                         build_shuffle_id=build_x.shuffle_id,
                         bypassed_shuffle_id=probe_x.shuffle_id,
                         build_rows=total_rows, build_bytes=total_bytes,
                         row_threshold=rows_thr, byte_threshold=bytes_thr)
            return JoinDecision("broadcast_build")

    lc, lb = l.materialized_stats(ctx)
    rc, rb = r.materialized_stats(ctx)
    if len(lc) != len(rc):
        return JoinDecision("static")
    combined = [a + b for a, b in zip(lc, rc)]
    combined_b = [a + b for a, b in zip(lb, rb)]
    groups = _coalesce(ctx, join, combined, combined_b)

    probe_counts = lc if probe_is_left else rc
    probe_bytes = lb if probe_is_left else rb
    skew_rows = _conf(ctx, ADAPTIVE_SKEW_ROWS, 1 << 20)
    skew_bytes = _conf(ctx, ADAPTIVE_SKEW_BYTES, 0)
    skew_on = _conf(ctx, ADAPTIVE_SKEW_ENABLED, True)
    # skew split: a group that is ONE oversized partition splits the
    # PROBE side into map slices, each joined against the full build
    # partition. Only valid when the join never emits unmatched BUILD
    # rows (slices would emit them once each).
    can_split = join.join_type in (
        "inner", "left_outer", "left_semi", "left_anti") \
        if probe_is_left else join.join_type == "inner"
    out_groups: List[List[int]] = []
    build_groups: List[List[int]] = []
    probe_mod: Dict[int, Tuple[int, int]] = {}
    n_skewed = 0
    for g in groups:
        pc = sum(probe_counts[i] for i in g)
        pb = sum(probe_bytes[i] for i in g)
        split_rows = pc > skew_rows
        split_bytes = skew_bytes > 0 and pb > skew_bytes
        if skew_on and can_split and len(g) == 1 and \
                (split_rows or split_bytes):
            s_r = -(-pc // skew_rows) if split_rows else 1
            s_b = -(-pb // skew_bytes) if split_bytes else 1
            S = min(max(s_r, s_b), MAX_SKEW_SLICES)
            n_skewed += 1
            _events.emit("SkewSplit", join=join.node_description(),
                         partition=g[0], rows=pc, bytes=pb, slices=S)
            for s in range(S):
                probe_mod[len(out_groups)] = (s, S)
                out_groups.append(g)
                build_groups.append(g)
        else:
            out_groups.append(g)
            build_groups.append(g)
    if len(out_groups) == len(combined) and not probe_mod:
        return JoinDecision("partitioned")
    _events.emit("AdaptivePlanChanged",
                 rule="skewJoin" if n_skewed else "coalescePartitions",
                 join=join.node_description(),
                 shuffle_id=probe_x.shuffle_id,
                 partitions_before=len(combined),
                 partitions_after=len(out_groups),
                 skewed_partitions=n_skewed)
    return JoinDecision("partitioned", out_groups, build_groups,
                        probe_mod, n_skewed)


def push_coverage(ctx, exchange) -> Optional[Tuple[int, int]]:
    """``(pushed_bytes, owned_bytes)`` for THIS worker's owned reduce
    partitions of one materialized exchange: how much of the next
    stage's input the push path pre-positioned into local segments
    before the stage boundary closed. Exact per-(map, reduce) sizes
    come straight from the receive-side segment index; the speculation
    winners verdict filters at segment-index granularity, so a losing
    map's pushed entries never count as coverage. None when push is
    off, local session, or the stage has not materialized."""
    from ..parallel.shuffle_manager import shuffle_manager
    from ..robustness import integrity
    if ctx.cluster is None:
        return None
    mgr = exchange.manager or shuffle_manager()
    if not getattr(mgr, "push_enabled", False):
        return None
    stats = getattr(exchange, "_global_stats", None)
    if stats is None:
        return None
    nbytes = stats[1]
    owned = ctx.cluster.assigned(len(nbytes))
    allowed = exchange._allowed_by_endpoint(ctx)
    peers = set(ctx.cluster.peers)
    pushed = 0
    for rid in owned:
        for origin, map_id, ln, _rows in mgr.segments.entries(
                exchange.shuffle_id, rid):
            if origin not in peers:
                continue  # stale entry from a replaced worker
            if allowed is not None and \
                    map_id not in allowed.get(origin, ()):
                continue
            pushed += max(ln - integrity.HEADER_SIZE, 0)
    return pushed, sum(nbytes[r] for r in owned if r < len(nbytes))


def broadcast_oversize_slices(ctx, join, build_rows: int,
                              build_bytes: int) -> int:
    """joinStrategy *promotion* guard for an already-broadcast join: a
    build side whose measured bytes exceed
    ``srt.sql.adaptive.maxBroadcastJoinBytes`` cannot be re-planned
    into a shuffle at this point (it is already materialized on every
    node), but it CAN be joined sub-partitioned so the single hash
    table never holds the whole thing. Returns the slice count (0 = no
    action)."""
    from ..conf import ADAPTIVE_MAX_BROADCAST_BYTES
    if not _conf(ctx, ADAPTIVE_ENABLED, False):
        return 0
    cap = _conf(ctx, ADAPTIVE_MAX_BROADCAST_BYTES, 0)
    if cap <= 0 or build_bytes <= cap or build_rows <= 1:
        return 0
    slices = min(-(-build_bytes // cap), MAX_SKEW_SLICES)
    _events.emit("AdaptivePlanChanged", rule="joinStrategy",
                 decision="subpartition_broadcast",
                 join=join.node_description(), build_rows=build_rows,
                 build_bytes=build_bytes, byte_cap=cap, slices=slices)
    return slices


# --- stage graph ----------------------------------------------------------

@dataclass
class QueryStage:
    """One materialization unit: a shuffle exchange and the subtree
    below it (up to deeper exchanges, which are their own stages)."""
    exchange: object
    depth: int          # exchanges on the path from the root, inclusive
    order: int          # pre-order position (tiebreak within a depth)
    role: str           # "build" | "probe" | "other"
    consumer: object    # direct parent when it is a decision point


def collect_stages(root) -> List[QueryStage]:
    """Walk the physical tree collecting shuffle-exchange stages.
    Broadcast subtrees are skipped (they materialize through their own
    lazy path); shared exchanges (full-outer lowering) appear once."""
    from ..exec.exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from ..exec.join import ShuffledHashJoinExec
    stages: List[QueryStage] = []
    seen: set = set()
    counter = [0]

    def role_of(parent, node) -> str:
        if isinstance(parent, ShuffledHashJoinExec):
            build = parent.children[1] if parent.build_side == "right" \
                else parent.children[0]
            return "build" if node is build else "probe"
        return "other"

    def walk(node, depth, parent):
        if isinstance(node, BroadcastExchangeExec):
            return
        if isinstance(node, ShuffleExchangeExec):
            if id(node) in seen:
                return
            seen.add(id(node))
            stages.append(QueryStage(node, depth + 1, counter[0],
                                     role_of(parent, node), parent))
            counter[0] += 1
            for c in getattr(node, "children", []):
                walk(c, depth + 1, node)
            return
        for c in getattr(node, "children", []):
            walk(c, depth, node)

    walk(root, 0, None)
    return stages


def execution_order(stages: List[QueryStage]) -> List[QueryStage]:
    """Dependency order: deeper exchanges first (a stage depends only
    on exchanges strictly below it), build side before probe side at
    equal depth (joinStrategy decides off the build before the probe's
    map phase is committed), then plan pre-order for determinism —
    every cluster worker derives the identical schedule."""
    rank = {"build": 0, "other": 1, "probe": 2}
    return sorted(stages, key=lambda s: (-s.depth, rank[s.role], s.order))


def stage_dag(root) -> Tuple[List[QueryStage], set]:
    """Execution-ordered stage list plus the registered exchange-id
    set — the stage-cut contract shared by the adaptive driver above
    and the mesh stage executor (plan/mesh_executor.py), which compiles
    one SPMD program per entry (body = the exchange's child subtree,
    cut at any registered exchange) plus one for the plan remainder
    above the shallowest exchanges. Sharing the cut keeps the two
    schedulers agreeing on what "a stage" is, so AQE statistics and
    mesh programs describe the same units."""
    stages = execution_order(collect_stages(root))
    return stages, {id(s.exchange) for s in stages}


class AdaptiveExecutor:
    """Eager stage-ordered driver: materialize each stage, re-plan the
    remainder from its measured sizes, then pull the root. Decisions
    land in the same per-node caches the lazy operator path reads, so
    the final ``root.execute`` consumes them without recomputation."""

    def __init__(self, physical, ctx):
        self.physical = physical
        self.ctx = ctx

    def execute(self) -> Iterator:
        from ..exec.aggregate import HashAggregateExec
        from ..exec.join import ShuffledHashJoinExec
        ctx = self.ctx
        skipped: set = set()   # exchanges bypassed by a demoted join
        for st in execution_order(collect_stages(self.physical)):
            ex = st.exchange
            if id(ex) in skipped:
                continue
            # materialize the map phase and gather global sizes; cached,
            # so consumers (and re-visits through a demoted join's
            # subtree) see the same stats without re-running anything
            ex.materialized_stats(ctx)
            cov = push_coverage(ctx, ex)
            if cov is not None and cov[1] > 0:
                _events.emit("StagePushCoverage",
                             shuffle_id=ex.shuffle_id,
                             pushed_bytes=cov[0], owned_bytes=cov[1])
            c = st.consumer
            if isinstance(c, ShuffledHashJoinExec) and st.role == "build":
                d = join_decision(ctx, c)
                if d.mode == "broadcast_build":
                    probe = c.children[0] if c.build_side == "right" \
                        else c.children[1]
                    skipped.add(id(probe))
            elif isinstance(c, HashAggregateExec):
                stage_groups(ctx, ex)
        yield from self.physical.execute(ctx)


def adaptive_execute(physical, ctx) -> Iterator:
    """Entry point for the session/cluster pull loops: stage-ordered
    adaptive execution when enabled, plain execution otherwise."""
    if not _conf(ctx, ADAPTIVE_ENABLED, False):
        yield from physical.execute(ctx)
        return
    yield from AdaptiveExecutor(physical, ctx).execute()
