"""CPU physical operators over HostTable.

The fallback execution engine — what runs a plan subtree the overrides
tagged off the TPU (the role CPU Spark plays for the reference; its exec
nodes are the analogue of Spark's row-based SparkPlan operators, but
columnar over numpy). Also the differential-test oracle (SURVEY §4).

Aggregation/join/sort semantics mirror the TPU execs:
- group nulls form their own group (Spark GROUP BY semantics),
- min/max skip nulls, NaN sorts greatest, empty-group sum/avg -> null,
- joins are equi hash joins; order of output rows is not part of the
  contract (tests sort before comparing).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..expr import aggregates as Agg
from ..expr.core import Expression, output_name
from . import cpu_eval
from .host_table import (HostColumn, HostTable, concat_tables, empty_like,
                         from_pydict)
from .logical import (Aggregate, Expand, Filter, Join, Limit, LocalRelation,
                      LogicalPlan, Project, Range, Sort, Union, Window)


def execute_cpu(plan: LogicalPlan) -> HostTable:
    """Interpret a logical plan subtree entirely on CPU."""
    return apply_cpu_node(plan, [execute_cpu(c) for c in plan.children])


def apply_cpu_node(plan: LogicalPlan,
                   children: List[HostTable]) -> HostTable:
    """Apply ONE logical node to already-evaluated child tables. The seam
    that lets mixed CPU/TPU physical trees reuse the CPU interpreter
    (transitions.py wraps TPU subtrees so they appear as child tables)."""
    if isinstance(plan, LocalRelation):
        return from_pydict(plan.data, plan.schema)
    from ..cache import CachedRelation
    if isinstance(plan, CachedRelation):
        from .host_table import batch_to_table
        tables = [batch_to_table(b) for b in plan.batches()
                  if int(b.num_rows) > 0]
        return concat_tables(tables) if tables else empty_like(plan.schema)
    from ..io.scan import FileScan
    if isinstance(plan, FileScan):
        from ..io.scan import read_file_to_tables
        tables = []
        for p in plan.pruned_paths():
            tables.extend(read_file_to_tables(
                p, plan.fmt, plan.schema, plan.options, None, 1 << 30,
                partition_values=plan.partition_values_for(p)))
        return concat_tables(tables) if tables else empty_like(plan.schema)
    if isinstance(plan, Range):
        n = max(0, -(-(plan.end - plan.start) // plan.step))
        vals = plan.start + np.arange(n, dtype=np.int64) * plan.step
        return HostTable([HostColumn(vals, np.ones(n, bool), dt.INT64)],
                         ["id"])
    if isinstance(plan, Project):
        child = children[0]
        cols = [cpu_eval.evaluate(e, child) for e in plan.exprs]
        return HostTable(cols, [n for n, _ in plan.schema])
    if isinstance(plan, Filter):
        child = children[0]
        cond = cpu_eval.evaluate(plan.condition, child)
        return child.select_rows(cond.values & cond.mask)
    if isinstance(plan, Limit):
        child = children[0]
        return child.take(np.arange(min(plan.n, child.num_rows)))
    from .logical import Sample
    if isinstance(plan, Sample):
        # the device exec's exact position-hash (bit-identical fallback)
        from ..exec.basic import sample_keep_mask
        child = children[0]
        n = child.num_rows
        keep = np.asarray(sample_keep_mask(0, max(n, 1), plan.fraction,
                                           plan.seed))[:n]
        return child.select_rows(keep)
    if isinstance(plan, Union):
        return concat_tables([_normalize(c, [n for n, _ in plan.schema])
                              for c in children])
    if isinstance(plan, Expand):
        child = children[0]
        parts = []
        for proj in plan.projections:
            cols = [cpu_eval.evaluate(e, child) for e in proj]
            cols = [_coerce_col(c, t) for c, (_, t) in zip(cols, plan.schema)]
            parts.append(HostTable(cols, [n for n, _ in plan.schema]))
        return concat_tables(parts)
    from .logical import Generate
    if isinstance(plan, Generate):
        return _generate_table(children[0], plan)
    if isinstance(plan, Sort):
        return _sort_table(children[0], plan.order)
    if isinstance(plan, Aggregate):
        return _aggregate_table(children[0], plan)
    if isinstance(plan, Join):
        return _join_tables(children[0], children[1], plan)
    if isinstance(plan, Window):
        return _window_table(children[0], plan)
    raise NotImplementedError(f"CPU executor: {type(plan).__name__}")


def _normalize(t: HostTable, names: List[str]) -> HostTable:
    return HostTable(t.columns, names)


def _coerce_col(c: HostColumn, t: dt.DType) -> HostColumn:
    if c.dtype == t or t == dt.STRING:
        return c
    if isinstance(t, dt.DecimalType):
        if isinstance(c.dtype, dt.DecimalType):
            from .cpu_eval import _rescale_np
            return HostColumn(_rescale_np(c.values.astype(np.int64),
                                          c.dtype.scale, t.scale), c.mask, t)
        return HostColumn(c.values.astype(np.int64)
                          * np.int64(10 ** t.scale), c.mask, t)
    return HostColumn(c.values.astype(np.dtype(t.physical)), c.mask, t)


# ---------------------------------------------------------------------------
# generate (explode)
# ---------------------------------------------------------------------------

def _generate_table(child: HostTable, plan) -> HostTable:
    """Explode/posexplode oracle (GpuGenerateExec semantics)."""
    from ..columnar.vector import _to_physical
    gen = plan.generator
    lists = cpu_eval.evaluate(gen.children[0], child)
    et = gen.data_type(child.schema())
    rows, positions, elems = [], [], []
    for i in range(child.num_rows):
        lst = lists.values[i] if lists.mask[i] else None
        if not lst:
            if gen.outer:
                rows.append(i)
                positions.append(None)
                elems.append(None)
            continue
        for p, e in enumerate(lst):
            rows.append(i)
            positions.append(p)
            elems.append(e)
    idx = np.array(rows, dtype=np.int64)
    out = child.take(idx)
    cols, names = list(out.columns), list(out.names)
    if plan.pos_name:
        pmask = np.array([p is not None for p in positions], dtype=bool)
        pvals = np.array([p if p is not None else 0 for p in positions],
                         dtype=np.int32)
        cols.append(HostColumn(pvals, pmask, dt.INT32))
        names.append(plan.pos_name)
    emask = np.array([e is not None for e in elems], dtype=bool)
    if et == dt.STRING or et.is_nested:
        evals = np.empty(len(elems), dtype=object)
        for i, e in enumerate(elems):
            evals[i] = e if e is not None else ("" if et == dt.STRING
                                                else None)
    else:
        evals = np.array([_to_physical(e, et) if e is not None else 0
                          for e in elems], dtype=np.dtype(et.physical))
    cols.append(HostColumn(evals, emask, et))
    names.append(plan.element_name)
    return HostTable(cols, names)


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------

def _sort_keys(col: HostColumn, ascending: bool, nulls_first: bool):
    """Build (null_rank, value_key) so np.lexsort matches Spark ordering."""
    n = len(col)
    null_rank = np.where(col.mask, 1, 0 if nulls_first else 2)
    if col.dtype == dt.STRING:
        # rank strings by sorted order (stable, handles desc via negation)
        order = np.argsort(np.where(col.mask, col.values, ""), kind="stable")
        rank = np.empty(n, np.int64)
        # equal strings must share a rank for desc negation to be correct
        vals = np.where(col.mask, col.values, "")
        sorted_vals = vals[order]
        uniq_rank = np.zeros(n, np.int64)
        if n:
            neq = np.concatenate([[0], (sorted_vals[1:] != sorted_vals[:-1])
                                  .astype(np.int64)])
            uniq_rank = np.cumsum(neq)
        rank[order] = uniq_rank
        key = rank
    elif np.issubdtype(col.values.dtype, np.floating):
        # NaN strictly greatest (> +inf): lift it into the class rank —
        # mapping it onto inf would tie with real infinities. Classes:
        # nulls-first null(0) < values(1) < NaN(2) < nulls-last null(3)
        # ascending; descending flips the value/NaN order (NaN first).
        v = col.values.astype(np.float64)
        nan = np.isnan(v)
        nan_cls = 2 if ascending else 1
        val_cls = 1 if ascending else 2
        null_rank = np.where(col.mask, np.where(nan, nan_cls, val_cls),
                             0 if nulls_first else 3)
        key = np.where(nan, 0.0, v)
        # -0.0 == 0.0 in Spark ordering; np handles that already
    else:
        key = col.values
    if not ascending:
        arr = np.asarray(key)
        if np.issubdtype(arr.dtype, np.floating):
            key = -key
        elif arr.dtype == object:
            # decimal128 unscaled ints exceed int64 — negate as python
            # ints (object lanes already sort via python compare)
            key = np.array([None if x is None else -x for x in key],
                           dtype=object)
        else:
            key = -(key.astype(np.int64))
    return null_rank, key


def _sort_table(table: HostTable, order) -> HostTable:
    if table.num_rows == 0:
        return table
    keys = []
    for o in order:
        col = cpu_eval.evaluate(o.expr, table)
        null_rank, key = _sort_keys(col, o.ascending, o.nulls_first)
        # null placement dominates the value key (nulls sort before/after
        # ALL values, including negatives)
        keys.append(null_rank)
        keys.append(key)
    # lexsort: last key is primary
    idx = np.lexsort(tuple(reversed(keys)))
    return table.take(idx)


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------

_NAN_KEY = object()  # canonical NaN grouping key: NaN == NaN in keys


def _norm_key(v):
    """Spark NormalizeFloatingNumbers for grouping/partition keys:
    every NaN is THE NaN, -0.0 is 0.0."""
    if isinstance(v, float):
        if v != v:
            return _NAN_KEY
        if v == 0.0:
            return 0.0
    return v


def _group_ids(key_cols: List[HostColumn], n: int):
    """Assign group ids; returns (gid array, representative row indices in
    first-seen order)."""
    if not key_cols:
        return np.zeros(n, np.int64), (np.array([0], np.int64) if n
                                       else np.zeros(0, np.int64))
    seen: Dict[tuple, int] = {}
    gid = np.empty(n, np.int64)
    reps: List[int] = []
    for i in range(n):
        k = tuple((None if not c.mask[i]
                   else (c.values[i] if c.dtype == dt.STRING
                         else _norm_key(c.values[i].item()
                                        if hasattr(c.values[i], "item")
                                        else c.values[i])))
                  for c in key_cols)
        g = seen.get(k)
        if g is None:
            g = len(reps)
            seen[k] = g
            reps.append(i)
        gid[i] = g
    return gid, np.asarray(reps, np.int64)


def _agg_cpu(fn: Agg.AggregateFunction, values: Optional[np.ndarray],
             mask: Optional[np.ndarray], rows: np.ndarray,
             in_dtype: Optional[dt.DType], out_t: dt.DType):
    """One aggregate over the rows of one group -> (value, valid)."""
    if isinstance(fn, Agg.CountStar):
        return len(rows), True
    v = values[rows]
    m = mask[rows]
    if isinstance(fn, Agg.Count):
        return int(m.sum()), True
    valid_v = v[m]
    if isinstance(fn, Agg.First):  # Last subclasses First
        is_last = isinstance(fn, Agg.Last)
        if fn.ignore_nulls:
            if len(valid_v) == 0:
                return 0, False
            return valid_v[-1 if is_last else 0], True
        if len(v) == 0:
            return 0, False
        i = -1 if is_last else 0
        return v[i], bool(m[i])
    if isinstance(fn, Agg.CollectList):  # CollectSet subclasses it
        vals = [v.item() if hasattr(v, "item") else v for v in valid_v]
        if isinstance(fn, Agg.CollectSet):
            seen = []
            for v in vals:
                if v not in seen:
                    seen.append(v)
            vals = seen
        return vals, True  # collect of empty group = empty array
    if len(valid_v) == 0:
        return 0, False
    if isinstance(fn, Agg.ApproxPercentile):
        # oracle: exact nearest-rank (smallest value whose cumulative
        # count reaches ceil(p*N)) — the limit the device sketch
        # approaches as K -> N
        x = np.sort(valid_v.astype(np.float64))
        outs = []
        for p in fn.percentages:
            r = max(int(np.ceil(p * len(x))) - 1, 0)
            outs.append(float(x[min(r, len(x) - 1)]))
        return (outs if fn.is_array else outs[0]), True
    if isinstance(fn, Agg.Percentile):
        x = valid_v.astype(np.float64)
        if isinstance(in_dtype, dt.DecimalType):
            x = x / (10.0 ** in_dtype.scale)
        return float(np.percentile(x, fn.percentage * 100)), True
    if isinstance(fn, Agg.Sum):
        if isinstance(out_t, dt.DecimalType):
            # exact arbitrary-precision oracle; overflow -> null like
            # the device 128-bit accumulator
            total = sum(int(x) for x in valid_v)
            if abs(total) >= 10 ** out_t.precision:
                if fn.ansi:
                    from ..expr import errors as ERR
                    raise ERR.SparkArithmeticException(
                        "Decimal sum overflow")
                return 0, False
            return total, True
        if out_t == dt.INT64:
            if fn.ansi:
                exact = sum(int(x) for x in valid_v)
                if not (-(2 ** 63) <= exact < 2 ** 63):
                    from ..expr import errors as ERR
                    raise ERR.SparkArithmeticException(
                        ERR.overflow_message("long"))
                return exact, True
            return int(valid_v.astype(np.int64).sum()), True
        return float(valid_v.astype(np.float64).sum()), True
    if isinstance(fn, Agg.Min) or isinstance(fn, Agg.Max):
        want_max = isinstance(fn, Agg.Max)
        if in_dtype == dt.STRING:
            return (max(valid_v) if want_max else min(valid_v)), True
        x = valid_v
        if np.issubdtype(x.dtype, np.floating):
            # NaN greatest (Spark ordering)
            if want_max:
                return (np.nan if np.isnan(x).any()
                        else float(x.max())), True
            non_nan = x[~np.isnan(x)]
            return ((float(non_nan.min()) if len(non_nan) else np.nan),
                    True)
        return (x.max() if want_max else x.min()), True
    if isinstance(fn, Agg.Average):
        if isinstance(in_dtype, dt.DecimalType):
            # exact decimal average at the (possibly adjusted) result
            # scale, HALF_UP; sum-buffer overflow -> null (the buffer is
            # decimal(min(p+10,38)), like the device accumulator)
            total = sum(int(x) for x in valid_v)
            sum_prec = min(in_dtype.precision + 10,
                           dt.DecimalType.MAX_PRECISION)
            if abs(total) >= 10 ** sum_prec:
                if fn.ansi:
                    from ..expr import errors as ERR
                    raise ERR.SparkArithmeticException(
                        "Decimal average overflow")
                return 0, False
            n_v = len(valid_v)
            num = abs(total) * 10 ** (out_t.scale - in_dtype.scale)
            q, r = divmod(num, n_v)
            if 2 * r >= n_v:
                q += 1
            if total < 0:
                q = -q
            if abs(q) >= 10 ** out_t.precision:
                if fn.ansi:
                    from ..expr import errors as ERR
                    raise ERR.SparkArithmeticException(
                        "Decimal average overflow")
                return 0, False
            return q, True
        x = valid_v.astype(np.float64)
        return float(x.sum() / len(x)), True
    if isinstance(fn, Agg._M2Base):
        x = valid_v.astype(np.float64)
        if isinstance(in_dtype, dt.DecimalType):
            x = x / (10.0 ** in_dtype.scale)
        n = len(x)
        mean = x.mean()
        m2 = float(((x - mean) ** 2).sum())
        ddof = fn.ddof
        if n - ddof <= 0:
            return 0.0, False
        var = m2 / (n - ddof)
        if isinstance(fn, (Agg.StddevPop, Agg.StddevSamp)):
            return float(np.sqrt(var)), True
        return var, True
    raise NotImplementedError(f"CPU aggregate {type(fn).__name__}")


def _aggregate_table(table: HostTable, plan: Aggregate) -> HostTable:
    schema_in = table.schema()
    key_cols = [cpu_eval.evaluate(e, table) for e in plan.group_exprs]
    n = table.num_rows
    gid, reps = _group_ids(key_cols, n)
    num_groups = len(reps)
    if not plan.group_exprs and n == 0:
        num_groups = 1  # global aggregate over empty input: one null row
        reps = np.zeros(0, np.int64)
        groups_rows = [np.zeros(0, np.int64)]
    else:
        groups_rows = [np.nonzero(gid == g)[0] for g in range(num_groups)]
    out_cols: List[HostColumn] = []
    names = [nm for nm, _ in plan.schema]
    # key columns: representative row of each group
    for kc in key_cols:
        if len(reps):
            out_cols.append(kc.take(reps))
        else:
            out_cols.append(HostColumn(
                np.zeros(num_groups, kc.values.dtype if
                         kc.dtype != dt.STRING else object),
                np.zeros(num_groups, bool), kc.dtype))
    # aggregates
    for fn, nm in plan.agg_exprs:
        out_t = fn.data_type(schema_in)
        if fn.children:
            in_col = cpu_eval.evaluate(fn.children[0], table)
            in_dtype = in_col.dtype
            values, mask = in_col.values, in_col.mask
        else:
            in_dtype, values, mask = None, None, None
        vals: List = []
        valids: List[bool] = []
        for rows in groups_rows:
            v, ok = _agg_cpu(fn, values, mask, rows, in_dtype, out_t)
            vals.append(v)
            valids.append(ok)
        if out_t == dt.STRING:
            arr = np.array([v if ok else "" for v, ok in zip(vals, valids)],
                           dtype=object)
        elif isinstance(out_t, dt.ArrayType):
            arr = np.empty(len(vals), dtype=object)
            for i, (v, ok) in enumerate(zip(vals, valids)):
                arr[i] = v if ok else []
        elif isinstance(out_t, dt.DecimalType) and out_t.is_wide:
            arr = np.array([int(v) if ok else 0
                            for v, ok in zip(vals, valids)], dtype=object)
        else:
            arr = np.array([v if ok else 0 for v, ok in zip(vals, valids)],
                           dtype=np.dtype(out_t.physical))
        out_cols.append(HostColumn(arr, np.asarray(valids, bool), out_t))
    return HostTable(out_cols, names)


# ---------------------------------------------------------------------------
# window (oracle: explicit per-partition python loops)
# ---------------------------------------------------------------------------

def _window_table(table: HostTable, plan: Window) -> HostTable:
    from ..expr.window import (Lag, Lead, DenseRank, NTile, PercentRank,
                               Rank, RowNumber)
    n = table.num_rows
    spec = plan.window_exprs[0][0].spec
    part_cols = [cpu_eval.evaluate(e, table) for e in spec.partition_by]
    # partition grouping
    gid, _reps = _group_ids(part_cols, n)
    # order within partition: global stable sort by order keys, then
    # walk rows partition by partition in that order
    if spec.order_fields:
        keys = []
        for o in spec.order_fields:
            c = cpu_eval.evaluate(o.expr, table)
            null_rank, key = _sort_keys(c, o.ascending, o.nulls_first)
            keys.extend([null_rank, key])
        order_perm = np.lexsort(tuple(reversed(keys)))
    else:
        order_perm = np.arange(n)
    part_rows: Dict[int, List[int]] = {}
    for i in order_perm:
        part_rows.setdefault(int(gid[i]), []).append(int(i))

    order_key_cols = [cpu_eval.evaluate(o.expr, table)
                      for o in spec.order_fields]

    def order_tuple(i):
        return tuple(
            (None if not c.mask[i] else
             (c.values[i] if c.dtype == dt.STRING else c.values[i].item()))
            for c in order_key_cols)

    out_cols = list(table.columns)
    names = [nm for nm, _ in plan.schema]
    schema_in = table.schema()
    for we, _name in plan.window_exprs:
        fn = we.func
        out_t = we.data_type(schema_in)
        if out_t == dt.STRING:
            vals = np.full(n, "", dtype=object)
        else:
            vals = np.zeros(n, np.dtype(out_t.physical))
        mask = np.zeros(n, bool)
        # hoisted RANGE-frame machinery (one column eval per window, not
        # per row): value offsets scale to decimal keys' fixed point
        frame0 = we.spec.frame
        kval = range_lo = range_hi = None
        if frame0 is not None and not frame0.row_based and \
                not (frame0.is_running or frame0.is_unbounded) and \
                we.spec.order_fields:
            of = we.spec.order_fields[0]
            kcol = cpu_eval.evaluate(of.expr, table)
            sign = 1.0 if of.ascending else -1.0
            knull = -np.inf if of.nulls_first else np.inf

            def kval(r, _kcol=kcol, _sign=sign, _knull=knull):
                if not _kcol.mask[r]:
                    return _knull
                return _sign * float(_kcol.values[r])
            scale = 10 ** kcol.dtype.scale \
                if isinstance(kcol.dtype, dt.DecimalType) else 1
            range_lo = None if frame0.lo is None else frame0.lo * scale
            range_hi = None if frame0.hi is None else frame0.hi * scale
        if fn.children:
            in_col = cpu_eval.evaluate(fn.children[0], table)
        else:
            in_col = None
        for rows in part_rows.values():
            cnt = len(rows)
            for pos, i in enumerate(rows):
                if isinstance(fn, RowNumber):
                    vals[i], mask[i] = pos + 1, True
                elif isinstance(fn, (Rank, DenseRank, PercentRank)):
                    r = d = 1
                    for p in range(1, pos + 1):
                        if order_tuple(rows[p]) != order_tuple(rows[p - 1]):
                            r = p + 1
                            d += 1
                    if isinstance(fn, Rank):
                        vals[i], mask[i] = r, True
                    elif isinstance(fn, DenseRank):
                        vals[i], mask[i] = d, True
                    else:
                        vals[i] = (r - 1) / (cnt - 1) if cnt > 1 else 0.0
                        mask[i] = True
                elif isinstance(fn, NTile):
                    q, rr = divmod(cnt, fn.n)
                    big = rr * (q + 1)
                    if pos < big:
                        b = pos // (q + 1)
                    elif q > 0:
                        b = rr + (pos - big) // q
                    else:
                        b = pos - big + rr
                    vals[i], mask[i] = b + 1, True
                elif isinstance(fn, Lead):  # Lag subclasses Lead
                    k = -fn.offset if isinstance(fn, Lag) else fn.offset
                    t = pos + k
                    if 0 <= t < cnt:
                        j = rows[t]
                        vals[i], mask[i] = in_col.values[j], in_col.mask[j]
                    elif fn.default is not None:
                        from ..columnar.vector import _to_physical
                        vals[i] = fn.default if out_t == dt.STRING else \
                            _to_physical(fn.default, out_t)
                        mask[i] = True
                else:
                    # aggregate over the frame
                    frame = we.spec.frame
                    if frame.is_unbounded:
                        lo, hi = 0, cnt - 1
                    elif frame.is_running:
                        lo, hi = 0, pos
                        if not frame.row_based:
                            # RANGE: include all peers of the current key
                            while hi + 1 < cnt and order_tuple(
                                    rows[hi + 1]) == order_tuple(rows[pos]):
                                hi += 1
                    elif frame.row_based:
                        lo = 0 if frame.lo is None else max(pos + frame.lo, 0)
                        hi = cnt - 1 if frame.hi is None else \
                            min(pos + frame.hi, cnt - 1)
                    else:
                        # RANGE with value offsets over the single
                        # numeric order key; null keys form their own
                        # peer group. kval/range_off are hoisted per
                        # window (see below the function list).
                        me = kval(rows[pos])
                        lo_v = me + range_lo if range_lo is not None \
                            else -np.inf
                        hi_v = me + range_hi if range_hi is not None \
                            else np.inf
                        lo = 0
                        while lo < cnt and kval(rows[lo]) < lo_v:
                            lo += 1
                        hi = cnt - 1
                        while hi >= 0 and kval(rows[hi]) > hi_v:
                            hi -= 1
                    frame_rows = np.asarray(rows[lo:hi + 1], np.int64) \
                        if hi >= lo else np.zeros(0, np.int64)
                    v, ok = _agg_cpu(
                        fn,
                        in_col.values if in_col is not None else None,
                        in_col.mask if in_col is not None else None,
                        frame_rows,
                        in_col.dtype if in_col is not None else None, out_t)
                    vals[i], mask[i] = (v if ok else
                                        ("" if out_t == dt.STRING else 0)), ok
        out_cols.append(HostColumn(vals, mask, out_t))
    return HostTable(out_cols, names)


# ---------------------------------------------------------------------------
# join
# ---------------------------------------------------------------------------

def _key_tuple(cols: List[HostColumn], i: int):
    out = []
    for c in cols:
        if not c.mask[i]:
            return None  # null keys never match (SQL equi-join)
        out.append(c.values[i] if c.dtype == dt.STRING
                   else c.values[i].item())
    return tuple(out)


def _join_tables(left: HostTable, right: HostTable, plan: Join) -> HostTable:
    lk = [cpu_eval.evaluate(e, left) for e in plan.left_keys]
    rk = [cpu_eval.evaluate(e, right) for e in plan.right_keys]
    ln, rn = left.num_rows, right.num_rows
    index: Dict[tuple, List[int]] = {}
    for j in range(rn):
        k = _key_tuple(rk, j)
        if k is not None:
            index.setdefault(k, []).append(j)
    jt = plan.join_type
    li: List[int] = []
    ri: List[int] = []
    for i in range(ln):
        k = _key_tuple(lk, i)
        matches = index.get(k, []) if k is not None else []
        for j in matches:
            li.append(i)
            ri.append(j)
    names = [nm for nm, _ in plan.schema]

    def gather(tbl: HostTable, idx, valid=None) -> List[HostColumn]:
        arr = np.asarray(idx, np.int64)
        return [c.take(arr, valid) for c in tbl.columns]

    # A residual condition restricts which key-matched PAIRS count as
    # matches (SQL ON semantics — affects outer/semi/anti row survival,
    # not just output filtering).
    if plan.condition is not None and li:
        paired = HostTable(gather(left, li) + gather(right, ri),
                           left.names + right.names)
        cond = cpu_eval.evaluate(plan.condition, paired)
        keep = cond.values & cond.mask
        li = [i for i, k in zip(li, keep) if k]
        ri = [j for j, k in zip(ri, keep) if k]
    l_matched = np.zeros(ln, bool)
    r_matched = np.zeros(rn, bool)
    for i in li:
        l_matched[i] = True
    for j in ri:
        r_matched[j] = True

    if jt == "inner" or jt == "cross":
        return HostTable(gather(left, li) + gather(right, ri), names)
    if jt == "left_semi":
        return left.select_rows(l_matched)
    if jt == "left_anti":
        return left.select_rows(~l_matched)
    if jt == "left_outer":
        un = np.nonzero(~l_matched)[0]
        all_li = np.concatenate([np.asarray(li, np.int64), un])
        all_ri = np.concatenate([np.asarray(ri, np.int64),
                                 np.zeros(len(un), np.int64)])
        rvalid = np.concatenate([np.ones(len(li), bool),
                                 np.zeros(len(un), bool)])
        cols = gather(left, all_li) + gather(right, all_ri, rvalid)
        return HostTable(cols, names)
    if jt == "right_outer":
        un = np.nonzero(~r_matched)[0]
        all_li = np.concatenate([np.asarray(li, np.int64),
                                 np.zeros(len(un), np.int64)])
        all_ri = np.concatenate([np.asarray(ri, np.int64), un])
        lvalid = np.concatenate([np.ones(len(li), bool),
                                 np.zeros(len(un), bool)])
        cols = gather(left, all_li, lvalid) + gather(right, all_ri)
        return HostTable(cols, names)
    if jt == "full_outer":
        lun = np.nonzero(~l_matched)[0]
        run = np.nonzero(~r_matched)[0]
        all_li = np.concatenate([np.asarray(li, np.int64), lun,
                                 np.zeros(len(run), np.int64)])
        all_ri = np.concatenate([np.asarray(ri, np.int64),
                                 np.zeros(len(lun), np.int64), run])
        lvalid = np.concatenate([np.ones(len(li) + len(lun), bool),
                                 np.zeros(len(run), bool)])
        rvalid = np.concatenate([np.ones(len(li), bool),
                                 np.zeros(len(lun), bool),
                                 np.ones(len(run), bool)])
        cols = gather(left, all_li, lvalid) + gather(right, all_ri, rvalid)
        return HostTable(cols, names)
    raise NotImplementedError(f"CPU join type {jt}")
