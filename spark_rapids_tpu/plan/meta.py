"""Meta wrapper hierarchy: tag-then-convert state.

Rebuild of RapidsMeta.scala (SURVEY §2.2): every logical node and every
expression gets wrapped in a meta that records *why* it cannot run on
TPU (``will_not_work_on_tpu``). After tagging, ``can_this_be_replaced``
drives conversion; the reasons feed the explain output
(spark.rapids.sql.explain NOT_ON_GPU equivalent).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..expr.core import Expression
from .logical import LogicalPlan


class BaseMeta:
    def __init__(self):
        self._cannot_reasons: List[str] = []

    def will_not_work_on_tpu(self, reason: str) -> None:
        if reason not in self._cannot_reasons:
            self._cannot_reasons.append(reason)

    @property
    def can_this_be_replaced(self) -> bool:
        return not self._cannot_reasons

    @property
    def reasons(self) -> List[str]:
        return list(self._cannot_reasons)


class ExprMeta(BaseMeta):
    """Wraps one Expression; child metas in ``child_exprs``."""

    def __init__(self, expr: Expression, schema):
        super().__init__()
        self.expr = expr
        self.schema = schema
        self.child_exprs = [ExprMeta(c, schema) for c in expr.children]

    def tag_for_tpu(self) -> None:
        from . import overrides
        # type the tree root-first BEFORE descending: higher-order
        # functions bind their lambda variables' dtypes in data_type,
        # and children (which reference those variables) tag after. A
        # type error here means the expression can't be planned at all
        # — fall back instead of crashing the planner.
        try:
            self.expr.data_type(self.schema)
        except Exception as e:
            self.will_not_work_on_tpu(
                f"cannot type {type(self.expr).__name__}: {e}")
            return
        for c in self.child_exprs:
            c.tag_for_tpu()
        rule = overrides.expr_rule_for(type(self.expr))
        if rule is None:
            self.will_not_work_on_tpu(
                f"expression {type(self.expr).__name__} has no TPU "
                "implementation")
            return
        rule.tag(self)

    @property
    def can_expr_tree_be_replaced(self) -> bool:
        return self.can_this_be_replaced and all(
            c.can_expr_tree_be_replaced for c in self.child_exprs)

    def tree_reasons(self) -> List[str]:
        out = list(self._cannot_reasons)
        for c in self.child_exprs:
            out.extend(c.tree_reasons())
        return out


class PlanMeta(BaseMeta):
    """Wraps one logical node; children wrapped recursively."""

    def __init__(self, plan: LogicalPlan):
        super().__init__()
        self.plan = plan
        self.child_plans = [PlanMeta(c) for c in plan.children]
        self.expr_metas = [ExprMeta(e, schema)
                           for e, schema in plan.expressions_with_schemas()]

    def tag_for_tpu(self) -> None:
        from . import overrides
        for c in self.child_plans:
            c.tag_for_tpu()
        for em in self.expr_metas:
            em.tag_for_tpu()
        rule = overrides.exec_rule_for(type(self.plan))
        if rule is None:
            self.will_not_work_on_tpu(
                f"operator {type(self.plan).__name__} has no TPU "
                "implementation")
        else:
            rule.tag(self)
        for em in self.expr_metas:
            if not em.can_expr_tree_be_replaced:
                for r in em.tree_reasons():
                    self.will_not_work_on_tpu(r)

    def explain_lines(self, indent: int = 0, only_not_on_tpu: bool = False
                      ) -> List[str]:
        mark = "*" if self.can_this_be_replaced else "!"
        line = "  " * indent + f"{mark} {self.plan.node_description()}"
        lines = []
        if not only_not_on_tpu or not self.can_this_be_replaced:
            reasons = "; ".join(self._cannot_reasons)
            lines.append(line + (f"  [cannot replace: {reasons}]"
                                 if reasons else ""))
        for c in self.child_plans:
            lines.extend(c.explain_lines(indent + 1, only_not_on_tpu))
        return lines
