"""Host-side columnar table: the CPU fallback's data representation.

A HostTable is the row-variable CPU mirror of a device ColumnarBatch:
each column is (values: np.ndarray, mask: np.ndarray bool) in the SAME
physical lane encoding the device side uses (dates = int32 days,
timestamps = int64 micros, decimals = scaled int64, strings = object
array of str). Keeping physical encodings identical makes
device<->host transitions exact bit-level copies and lets the
differential test harness compare CPU and TPU results directly.

Reference counterpart: the row<->columnar transition layer
(GpuRowToColumnarExec.scala / GpuColumnarToRowExec.scala, SURVEY §1 L2) —
except our CPU side is columnar too, so transitions are buffer copies,
not row pivots.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar import dtypes as dt
from ..columnar.vector import (ColumnarBatch, ColumnVector, StringColumn,
                               choose_capacity, column_from_numpy,
                               from_physical)

Schema = List  # [(name, DType), ...]


class HostColumn:
    __slots__ = ("values", "mask", "dtype")

    def __init__(self, values: np.ndarray, mask: np.ndarray, dtype: dt.DType):
        assert len(values) == len(mask)
        self.values = values
        self.mask = np.asarray(mask, dtype=bool)
        self.dtype = dtype

    def __len__(self):
        return len(self.values)

    def take(self, idx: np.ndarray, valid: Optional[np.ndarray] = None) -> "HostColumn":
        safe = np.clip(idx, 0, max(len(self.values) - 1, 0))
        if len(self.values) == 0:
            values = np.zeros(len(idx), dtype=self.values.dtype)
            mask = np.zeros(len(idx), dtype=bool)
        else:
            values = self.values[safe]
            mask = self.mask[safe]
        if valid is not None:
            mask = mask & valid
        return HostColumn(values, mask, self.dtype)

    def __repr__(self):
        return f"HostColumn({self.dtype}, n={len(self)})"


class HostTable:
    """Ordered named host columns; all the CPU operators' currency."""

    def __init__(self, columns: Sequence[HostColumn], names: Sequence[str]):
        assert len(columns) == len(names)
        self.columns = list(columns)
        self.names = list(names)

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    def column(self, name: str) -> HostColumn:
        return self.columns[self.names.index(name)]

    def schema(self) -> Schema:
        return [(n, c.dtype) for n, c in zip(self.names, self.columns)]

    def take(self, idx: np.ndarray, valid: Optional[np.ndarray] = None) -> "HostTable":
        return HostTable([c.take(idx, valid) for c in self.columns], self.names)

    def select_rows(self, mask: np.ndarray) -> "HostTable":
        idx = np.nonzero(mask)[0]
        return self.take(idx)

    def with_columns(self, columns: Sequence[HostColumn],
                     names: Sequence[str]) -> "HostTable":
        return HostTable(list(columns), list(names))

    def __repr__(self):
        cols = ", ".join(f"{n}:{c.dtype}" for n, c in zip(self.names, self.columns))
        return f"HostTable[{cols}](n={self.num_rows})"


def _wide_decimal(t) -> bool:
    return isinstance(t, dt.DecimalType) and t.is_wide


def empty_like(schema: Schema) -> HostTable:
    cols = []
    for _, t in schema:
        if t == dt.STRING or t.is_nested or _wide_decimal(t):
            cols.append(HostColumn(np.empty(0, object), np.empty(0, bool), t))
        else:
            cols.append(HostColumn(np.empty(0, np.dtype(t.physical)),
                                   np.empty(0, bool), t))
    return HostTable(cols, [n for n, _ in schema])


def concat_tables(tables: Sequence[HostTable]) -> HostTable:
    first = tables[0]
    cols = []
    for i in range(len(first.columns)):
        values = np.concatenate([t.columns[i].values for t in tables])
        mask = np.concatenate([t.columns[i].mask for t in tables])
        cols.append(HostColumn(values, mask, first.columns[i].dtype))
    return HostTable(cols, first.names)


def from_pydict(data: dict, schema: Schema) -> HostTable:
    """Build from {name: [python values]} using device physical encodings."""
    from ..columnar.vector import _to_physical
    n = len(next(iter(data.values()))) if data else 0
    cols = []
    for name, t in schema:
        raw = data[name]
        mask = np.array([v is not None for v in raw], dtype=bool)
        if t.is_nested:
            # nested host columns hold LOGICAL python values
            # (lists/dicts), not physical lanes
            values = np.empty(len(raw), dtype=object)
            for i, v in enumerate(raw):
                values[i] = v
        elif t == dt.STRING:
            values = np.array([v if v is not None else "" for v in raw],
                              dtype=object)
        elif _wide_decimal(t):
            # decimal128 host lanes are python ints (exact, unbounded) —
            # the oracle's arbitrary-precision mirror of the two-limb
            # device encoding (columnar/decimal128.py)
            values = np.array(
                [_to_physical(v, t) if v is not None else 0 for v in raw],
                dtype=object)
        else:
            phys = np.dtype(t.physical)
            values = np.array(
                [_to_physical(v, t) if v is not None else 0 for v in raw],
                dtype=phys)
        cols.append(HostColumn(values, mask, t))
    return HostTable(cols, [n for n, _ in schema])


def to_pydict(table: HostTable) -> dict:
    out = {}
    for name, col in zip(table.names, table.columns):
        if col.dtype == dt.STRING or col.dtype.is_nested:
            out[name] = [col.values[i] if col.mask[i] else None
                         for i in range(len(col))]
        else:
            out[name] = [from_physical(col.values[i], col.dtype)
                         if col.mask[i] else None for i in range(len(col))]
    return out


# ---------------------------------------------------------------------------
# Host <-> device transitions (GpuRowToColumnar / GpuColumnarToRow equiv)
# ---------------------------------------------------------------------------

def table_to_batch(table: HostTable,
                   capacity: Optional[int] = None) -> ColumnarBatch:
    n = table.num_rows
    cap = capacity or choose_capacity(n)
    cols = []
    for c in table.columns:
        if c.dtype.is_nested:
            from ..columnar.nested import nested_column_from_pylist
            values = [c.values[i] if c.mask[i] else None
                      for i in range(len(c))]
            cols.append(nested_column_from_pylist(
                values + [None] * (cap - n), cap, c.dtype))
        elif c.dtype == dt.STRING:
            cols.append(column_from_numpy(
                np.asarray(c.values, dtype=object), cap,
                dtype=dt.STRING, mask=c.mask))
        elif _wide_decimal(c.dtype):
            # host lanes are already unscaled ints: build limbs directly
            from ..columnar.decimal128 import from_unscaled_ints
            cols.append(from_unscaled_ints(list(c.values), cap, c.dtype,
                                           mask=c.mask))
        else:
            cols.append(column_from_numpy(c.values, cap, dtype=c.dtype,
                                          mask=c.mask))
    return ColumnarBatch(cols, table.names, n)


def batch_to_table(batch: ColumnarBatch) -> HostTable:
    from ..columnar.nested import ListColumn, StructColumn
    n = int(batch.num_rows)
    cols = []
    for c in batch.columns:
        vals, mask = c.to_numpy(n)
        if isinstance(c, (StringColumn, ListColumn, StructColumn)):
            cols.append(HostColumn(np.asarray(vals, dtype=object),
                                   np.asarray(mask), c.dtype))
        else:
            cols.append(HostColumn(np.asarray(vals), np.asarray(mask),
                                   c.dtype))
    return HostTable(cols, batch.names)
