"""CPU (numpy) expression evaluator over HostTable.

The fallback interpreter: evaluates the SAME Expression trees the TPU
path jit-compiles, but with numpy over host columns. Plays the role of
"CPU Spark" in the reference's architecture — both the destination of
unsupported-op fallback (GpuOverrides tagging, SURVEY §2.2) and the
oracle of the differential test harness (SURVEY §4: CPU plan ≡ GPU plan).

Semantics mirror the expr/ modules (which cite Spark): divide-by-zero ->
null, Java trunc-mod sign rules, Kleene AND/OR, NaN-greatest ordering,
null-iff-any-input-null for scalar fns, decimal lanes as scaled int64.
Every evaluator returns (values, mask) with device physical encodings
(see host_table.py).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Tuple, Type

import numpy as np

from ..columnar import dtypes as dt
from ..expr import arithmetic as A
from ..expr import cast as C
from ..expr import conditional as Cond
from ..expr import core as E
from ..expr import datetime as D
from ..expr import mathfns as M
from ..expr import predicates as P
from ..expr import strings as S
from .host_table import HostColumn, HostTable

Result = Tuple[np.ndarray, np.ndarray]  # (values, mask)

_EVALUATORS: Dict[Type, Callable] = {}


def cpu_supported(expr: E.Expression) -> bool:
    return type(expr) in _EVALUATORS


def evaluate(expr: E.Expression, table: HostTable) -> HostColumn:
    """Evaluate to a HostColumn (physical lanes + null mask)."""
    fn = _EVALUATORS.get(type(expr))
    if fn is None:
        raise NotImplementedError(
            f"no CPU evaluator for {type(expr).__name__}")
    values, mask = fn(expr, table)
    return HostColumn(np.asarray(values), np.asarray(mask),
                      expr.data_type(table.schema()))


def _reg(cls):
    def deco(fn):
        _EVALUATORS[cls] = fn
        return fn
    return deco


def _ev(expr, table) -> Result:
    c = evaluate(expr, table)
    return c.values, c.mask


def _zero_nulls(values, mask):
    """Zero data lanes under nulls (the device-side invariant)."""
    if values.dtype == object:
        return np.where(mask, values, "")
    return np.where(mask, values, np.zeros(1, dtype=values.dtype))


# ---------------------------------------------------------------------------
# core
# ---------------------------------------------------------------------------

@_reg(E.ColumnRef)
def _col(expr, table):
    c = table.column(expr.name)
    return c.values, c.mask


@_reg(E.Alias)
def _alias(expr, table):
    return _ev(expr.children[0], table)


@_reg(E.Literal)
def _literal(expr, table):
    n = table.num_rows
    t = expr.dtype
    if expr.value is None:
        phys = object if t == dt.STRING else np.dtype(
            (t.physical or np.int32))
        return np.zeros(n, phys), np.zeros(n, bool)
    if t == dt.STRING:
        return np.full(n, str(expr.value), dtype=object), np.ones(n, bool)
    from ..columnar.vector import _to_physical
    v = _to_physical(expr.value, t)
    if isinstance(t, dt.DecimalType) and t.is_wide:
        return np.array([v] * n, dtype=object), np.ones(n, bool)
    return (np.full(n, v, dtype=np.dtype(t.physical)), np.ones(n, bool))


# ---------------------------------------------------------------------------
# arithmetic
# ---------------------------------------------------------------------------

def _rescale_np(data, from_scale: int, to_scale: int):
    if to_scale > from_scale:
        return data * np.int64(10 ** (to_scale - from_scale))
    if to_scale < from_scale:
        return data // np.int64(10 ** (from_scale - to_scale))
    return data


def _obj_ints(a) -> np.ndarray:
    """Lanes as python ints (exact, arbitrary precision)."""
    if a.dtype == object:
        return a
    return np.array([int(x) for x in a], dtype=object)


def _half_up_obj(vals, k: int):
    """vals / 10^k with HALF_UP on python-int lanes."""
    if k <= 0:
        return vals
    p = 10 ** k
    half = p // 2
    return np.array([(abs(int(v)) + half) // p * (1 if v >= 0 else -1)
                     for v in vals], dtype=object)


_I128_MAX = 2 ** 127  # device two-limb intermediate bound


def _decimal_arith_obj(a, b, mask, op, lt, rt, out_t):
    """Exact decimal arithmetic on python-int lanes, mirroring the
    device decimal128 path including its overflow->null behavior: the
    result nulls when it exceeds 10^precision, and (add/sub only) when a
    scale-aligned operand exceeds the 128-bit intermediate range."""
    a = _obj_ints(a)
    b = _obj_ints(b)
    if op in ("add", "sub"):
        def align(v, fs):
            if out_t.scale >= fs:
                return v * 10 ** (out_t.scale - fs)
            return _half_up_obj(v, fs - out_t.scale)
        a2 = align(a, lt.scale)
        b2 = align(b, rt.scale)
        inter_ok = np.array([abs(int(x)) < _I128_MAX for x in a2], bool) & \
            np.array([abs(int(x)) < _I128_MAX for x in b2], bool)
        out = a2 - b2 if op == "sub" else a2 + b2
        mask = mask & inter_ok
    else:  # mul
        raw = a * b
        out = _half_up_obj(raw, lt.scale + rt.scale - out_t.scale)
    bound = 10 ** out_t.precision
    fits = np.array([abs(int(v)) < bound for v in out], bool)
    mask = mask & fits
    out = np.where(mask, out, 0)
    if not out_t.is_wide:
        out = np.array([int(v) for v in out], dtype=np.int64)
    return out, mask


def _coerced(expr, table):
    """(left_child, right_child, left_t, right_t) after the op's
    implicit coercion (DecimalPrecision + per-op inputType casts, e.g.
    IntegralDivide's float->long) — the ONE preamble every binary-
    arithmetic oracle evaluator must share (per-evaluator copies are
    exactly where float-mix paths got missed)."""
    lc, rc = expr.coerced_children(table.schema())
    return lc, rc, lc.data_type(table.schema()), \
        rc.data_type(table.schema())


def _spark_string_to_date(s: str) -> int:
    """DateTimeUtils.stringToDate: yyyy | yyyy-[m]m | yyyy-[m]m-[d]d
    (a trailing 'T…'/' …' time segment after a FULL date is ignored);
    real calendar validation. Returns epoch days; raises ValueError on
    any invalid form (caller maps to null / ANSI error)."""
    import datetime
    body = s
    for cut in ("T", " "):
        p = body.find(cut)
        if p >= 0:
            if body[:p].count("-") != 2:
                raise ValueError(s)
            body = body[:p]
    parts = body.split("-")
    if not 1 <= len(parts) <= 3 or len(parts[0]) != 4:
        raise ValueError(s)
    vals = []
    for seg in parts:
        if not seg.isdigit() or len(seg) == 0 or len(seg) > 4:
            raise ValueError(s)
        vals.append(int(seg))
    y = vals[0]
    mth = vals[1] if len(vals) > 1 else 1
    d = vals[2] if len(vals) > 2 else 1
    if len(vals) > 1 and len(parts[1]) > 2:
        raise ValueError(s)
    if len(vals) > 2 and len(parts[2]) > 2:
        raise ValueError(s)
    # proleptic Gregorian incl. year 0 (datetime.date rejects y < 1,
    # but Spark's LocalDate and the device lane accept it)
    if not 1 <= mth <= 12:
        raise ValueError(s)
    leap = (y % 4 == 0 and y % 100 != 0) or y % 400 == 0
    dim = [31, 29 if leap else 28, 31, 30, 31, 30, 31, 31, 30, 31, 30,
           31][mth - 1]
    if not 1 <= d <= dim:
        raise ValueError(s)
    # Howard Hinnant's days_from_civil (same formula as the device)
    yy = y - (mth <= 2)
    era = (yy if yy >= 0 else yy - 399) // 400
    yoe = yy - era * 400
    doy = (153 * (mth + (-3 if mth > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _ansi_raise_if(mask, exc) -> None:
    """Oracle-side ANSI guard: mirrors expr/ansi.guard so both engines
    raise the same error types (error-equality differential contract)."""
    if bool(np.any(mask)):
        raise exc


def _binary_arith(expr, table, op):
    lc, rc, lt, rt = _coerced(expr, table)
    out_t = expr.data_type(table.schema())
    a, am = _ev(lc, table)
    b, bm = _ev(rc, table)
    mask = am & bm
    if isinstance(out_t, dt.DecimalType):
        wide = out_t.is_wide or lt.is_wide or rt.is_wide
        if wide:
            out, omask = _decimal_arith_obj(a, b, mask, op, lt, rt, out_t)
            if expr.ansi:
                from ..expr import errors as ERR
                _ansi_raise_if(mask & ~omask, ERR.SparkArithmeticException(
                    f"{op}: decimal overflow or division by zero "
                    f"(ANSI mode)"))
            return out, omask
        a = _rescale_np(a.astype(np.int64), lt.scale, out_t.scale) \
            if op != "mul" else a.astype(np.int64)
        b = _rescale_np(b.astype(np.int64), rt.scale, out_t.scale) \
            if op != "mul" else b.astype(np.int64)
        if op == "add":
            out = a + b
        elif op == "sub":
            out = a - b
        else:
            out = _rescale_np(a * b, lt.scale + rt.scale, out_t.scale)
        return _zero_nulls(out, mask), mask
    phys = np.dtype(out_t.physical)
    a = a.astype(phys)
    b = b.astype(phys)
    with np.errstate(over="ignore"):
        if op == "add":
            out = a + b
        elif op == "sub":
            out = a - b
        else:
            out = a * b
    if expr.ansi and out_t.is_integral:
        from ..expr import errors as ERR
        ao, bo = a.astype(object), b.astype(object)
        if op == "add":
            exact = ao + bo
        elif op == "sub":
            exact = ao - bo
        else:
            exact = ao * bo
        info = np.iinfo(phys)
        bad = mask & np.array(
            [not (info.min <= int(v) <= info.max) for v in exact], bool)
        _ansi_raise_if(bad, ERR.SparkArithmeticException(
            ERR.overflow_message(str(out_t))))
    return _zero_nulls(out, mask), mask


@_reg(A.Add)
def _add(e, t):
    return _binary_arith(e, t, "add")


@_reg(A.Subtract)
def _sub(e, t):
    return _binary_arith(e, t, "sub")


@_reg(A.Multiply)
def _mul(e, t):
    return _binary_arith(e, t, "mul")


@_reg(A.Divide)
def _div(expr, table):
    lc, rc, lt, rt = _coerced(expr, table)
    out_t = expr.data_type(table.schema())
    a, am = _ev(lc, table)
    b, bm = _ev(rc, table)
    if isinstance(out_t, dt.DecimalType):
        # exact decimal division, HALF_UP at the result scale
        a = _obj_ints(a)
        b = _obj_ints(b)
        mask = am & bm & np.array([int(x) != 0 for x in b], bool)
        up = out_t.scale - lt.scale + rt.scale
        bound = 10 ** out_t.precision
        out = np.zeros(len(a), dtype=object)
        for i in range(len(a)):
            if not mask[i]:
                out[i] = 0
                continue
            n = abs(int(a[i])) * 10 ** up
            d = abs(int(b[i]))
            q, r = divmod(n, d)
            if 2 * r >= d:
                q += 1
            if (int(a[i]) < 0) != (int(b[i]) < 0):
                q = -q
            if abs(q) >= bound or abs(q) >= _I128_MAX:
                mask[i] = False
                q = 0
            out[i] = q
        if not out_t.is_wide:
            out = np.array([int(v) for v in out], dtype=np.int64)
        if expr.ansi:
            from ..expr import errors as ERR
            _ansi_raise_if(am & bm & ~mask, ERR.SparkArithmeticException(
                "/: decimal overflow or division by zero (ANSI mode)"))
        return out, mask
    a = a.astype(np.float64)
    b = b.astype(np.float64)
    if expr.ansi:
        from ..expr import errors as ERR
        _ansi_raise_if(am & bm & (b == 0.0),
                       ERR.SparkArithmeticException(ERR.DIVIDE_BY_ZERO))
    mask = am & bm & (b != 0.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        out = np.where(b != 0.0, a / np.where(b == 0.0, 1.0, b), 0.0)
    return _zero_nulls(out, mask), mask


def _trunc_div_np(a, b):
    q = a // b
    r = a - q * b
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return q + adjust.astype(q.dtype)


def _trunc_mod_np(a, b):
    r = a % b
    adjust = (r != 0) & ((a < 0) != (b < 0))
    return r - np.where(adjust, b, np.zeros(1, b.dtype))


def _decimal_divmod_obj(expr, table):
    """Common-scale exact truncating divmod for decimal operands.
    Returns (q, r, |b| at the common scale, mask, scale, base_mask)
    where base_mask is the pre-division operand validity (am & bm) —
    the ANSI guards diff it against the final mask to find
    op-introduced nulls without re-evaluating the operands."""
    lc, rc, lt, rt = _coerced(expr, table)
    a, am = _ev(lc, table)
    b, bm = _ev(rc, table)
    s = max(lt.scale, rt.scale)
    a = _obj_ints(a) * (10 ** (s - lt.scale))
    b = _obj_ints(b) * (10 ** (s - rt.scale))
    base_mask = am & bm
    mask = base_mask & np.array([int(x) != 0 for x in b], bool)
    n = len(a)
    q = np.zeros(n, dtype=object)
    r = np.zeros(n, dtype=object)
    for i in range(n):
        if not mask[i]:
            continue
        qq, rr = divmod(abs(int(a[i])), abs(int(b[i])))
        q[i] = qq if (int(a[i]) < 0) == (int(b[i]) < 0) else -qq
        r[i] = rr if int(a[i]) >= 0 else -rr
    return (q, r, np.array([abs(int(x)) for x in b], dtype=object),
            mask, s, base_mask)


@_reg(A.IntegralDivide)
def _idiv(expr, table):
    lc, rc, lt, rt = _coerced(expr, table)
    if isinstance(lt, dt.DecimalType):  # coerced: both-or-neither
        q, _, _, mask, _, base_mask = _decimal_divmod_obj(expr, table)
        fits = np.array([-(2 ** 63) <= int(v) < 2 ** 63 for v in q], bool)
        mask = mask & fits
        out = np.array([int(v) if f else 0 for v, f in zip(q, fits)],
                       dtype=np.int64)
        if expr.ansi:
            from ..expr import errors as ERR
            _ansi_raise_if(base_mask & ~mask, ERR.SparkArithmeticException(
                "div: division by zero or overflow (ANSI mode)"))
        return _zero_nulls(out, mask), mask
    a, am = _ev(lc, table)
    b, bm = _ev(rc, table)
    if expr.ansi:
        from ..expr import errors as ERR
        _ansi_raise_if(am & bm & (b == 0),
                       ERR.SparkArithmeticException(ERR.DIVIDE_BY_ZERO))
        if not np.issubdtype(a.dtype, np.floating):
            lo = np.iinfo(np.int64).min
            _ansi_raise_if(am & bm & (a.astype(np.int64) == lo)
                           & (b.astype(np.int64) == -1),
                           ERR.SparkArithmeticException(
                               ERR.overflow_message("long")))
    mask = am & bm & (b != 0)
    safe = np.where(b == 0, np.ones(1, b.dtype), b)
    if np.issubdtype(a.dtype, np.floating):
        q = np.trunc(a.astype(np.float64) / safe.astype(np.float64))
    else:
        q = _trunc_div_np(a, safe)
    return _zero_nulls(q.astype(np.int64), mask), mask


def _decimal_mod_result(expr, table, positive: bool):
    out_t = expr.data_type(table.schema())
    _, r, babs, mask, s, base_mask = _decimal_divmod_obj(expr, table)
    if positive:
        r = np.array([int(v) + int(ab) if int(v) < 0 else int(v)
                      for v, ab in zip(r, babs)], dtype=object)
    if out_t.scale != s:
        r = _half_up_obj(r, s - out_t.scale)
    bound = 10 ** out_t.precision
    fits = np.array([abs(int(v)) < bound for v in r], bool)
    mask = mask & fits
    if expr.ansi:
        from ..expr import errors as ERR
        _ansi_raise_if(base_mask & ~mask, ERR.SparkArithmeticException(
            f"{expr.op_name}: decimal overflow or division by zero "
            f"(ANSI mode)"))
    r = np.where(mask, r, 0)
    if not out_t.is_wide:
        r = np.array([int(v) for v in r], dtype=np.int64)
    return r, mask


@_reg(A.Remainder)
def _rem(expr, table):
    out_t = expr.data_type(table.schema())
    if isinstance(out_t, dt.DecimalType):
        return _decimal_mod_result(expr, table, positive=False)
    phys = np.dtype(out_t.physical)
    lc, rc, _lt, _rt = _coerced(expr, table)
    a, am = _ev(lc, table)
    b, bm = _ev(rc, table)
    a = a.astype(phys)
    b = b.astype(phys)
    if expr.ansi:
        from ..expr import errors as ERR
        _ansi_raise_if(am & bm & (b == 0),
                       ERR.SparkArithmeticException(ERR.DIVIDE_BY_ZERO))
    mask = am & bm & (b != 0)
    safe = np.where(b == 0, np.ones(1, b.dtype), b)
    if np.issubdtype(a.dtype, np.floating):
        out = np.fmod(a, safe)
    else:
        out = _trunc_mod_np(a, safe)
    return _zero_nulls(out, mask), mask


@_reg(A.Pmod)
def _pmod(expr, table):
    out_t = expr.data_type(table.schema())
    if isinstance(out_t, dt.DecimalType):
        return _decimal_mod_result(expr, table, positive=True)
    phys = np.dtype(out_t.physical)
    lc, rc, _lt, _rt = _coerced(expr, table)
    a, am = _ev(lc, table)
    b, bm = _ev(rc, table)
    a = a.astype(phys)
    b = b.astype(phys)
    if expr.ansi:
        from ..expr import errors as ERR
        _ansi_raise_if(am & bm & (b == 0),
                       ERR.SparkArithmeticException(ERR.DIVIDE_BY_ZERO))
    mask = am & bm & (b != 0)
    safe = np.where(b == 0, np.ones(1, b.dtype), b)
    if np.issubdtype(a.dtype, np.floating):
        r = np.fmod(a, safe)
    else:
        r = _trunc_mod_np(a, safe)
    r = np.where(r < 0, r + np.abs(safe), r)
    return _zero_nulls(r, mask), mask


@_reg(A.UnaryMinus)
def _neg(expr, table):
    a, m = _ev(expr.children[0], table)
    t = expr.children[0].data_type(table.schema())
    if expr.ansi and getattr(t, "is_integral", False) \
            and not isinstance(t, dt.DecimalType):
        from ..expr import errors as ERR
        _ansi_raise_if(m & (a == np.iinfo(a.dtype).min),
                       ERR.SparkArithmeticException(
                           ERR.overflow_message(str(t))))
    with np.errstate(over="ignore"):
        return _zero_nulls(-a, m), m


@_reg(A.UnaryPositive)
def _pos(expr, table):
    return _ev(expr.children[0], table)


@_reg(A.Abs)
def _abs(expr, table):
    a, m = _ev(expr.children[0], table)
    t = expr.children[0].data_type(table.schema())
    if expr.ansi and getattr(t, "is_integral", False) \
            and not isinstance(t, dt.DecimalType):
        from ..expr import errors as ERR
        _ansi_raise_if(m & (a == np.iinfo(a.dtype).min),
                       ERR.SparkArithmeticException(
                           ERR.overflow_message(str(t))))
    with np.errstate(over="ignore"):
        return _zero_nulls(np.abs(a), m), m


def _least_greatest(expr, table, largest: bool):
    out_t = expr.data_type(table.schema())
    if out_t == dt.STRING:
        # null-skipping lexicographic min/max (the device lane folds
        # the same semantics through If/IsNull over string columns)
        n = table.num_rows
        cols = [_ev(c, table) for c in expr.children]
        out = np.empty(n, object)
        valid = np.zeros(n, bool)
        for i in range(n):
            best = None
            for v, m in cols:
                if not m[i]:
                    continue
                s = v[i]
                if best is None or \
                        ((s > best) if largest else (s < best)):
                    best = s
            valid[i] = best is not None
            out[i] = best if best is not None else ""
        return out, valid
    phys = np.dtype(out_t.physical)
    n = table.num_rows
    fill = dt.max_value(out_t) if not largest else dt.min_value(out_t)
    acc = np.full(n, fill, phys)
    any_valid = np.zeros(n, bool)
    if np.issubdtype(phys, np.floating):
        # Spark float order: NaN greatest (mirrors the device lane)
        nan_seen = np.zeros(n, bool)
        num_seen = np.zeros(n, bool)
        for c in expr.children:
            v, m = _ev(c, table)
            v = v.astype(phys)
            nan = np.isnan(v)
            vv = np.where(m & ~nan, v, np.asarray(fill, phys))
            acc = np.maximum(acc, vv) if largest else np.minimum(acc, vv)
            nan_seen |= m & nan
            num_seen |= m & ~nan
            any_valid |= m
        nan_v = np.asarray(np.nan, phys)
        if largest:
            acc = np.where(nan_seen, nan_v, acc)
        else:
            acc = np.where(num_seen, acc, nan_v)
        return _zero_nulls(acc, any_valid), any_valid
    for c in expr.children:
        v, m = _ev(c, table)
        v = np.where(m, v.astype(phys), np.asarray(fill, phys))
        acc = np.maximum(acc, v) if largest else np.minimum(acc, v)
        any_valid |= m
    return _zero_nulls(acc, any_valid), any_valid


@_reg(A.Least)
def _least(e, t):
    return _least_greatest(e, t, largest=False)


@_reg(A.Greatest)
def _greatest(e, t):
    return _least_greatest(e, t, largest=True)


# ---------------------------------------------------------------------------
# predicates
# ---------------------------------------------------------------------------

def _aligned_np(expr, table):
    lt = expr.children[0].data_type(table.schema())
    rt = expr.children[1].data_type(table.schema())
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    mask = am & bm
    l_dec = isinstance(lt, dt.DecimalType)
    r_dec = isinstance(rt, dt.DecimalType)
    if lt == dt.STRING or rt == dt.STRING:
        return a, b, mask, True
    if l_dec or r_dec:
        lf = (not l_dec) and lt.is_floating
        rf = (not r_dec) and rt.is_floating
        wide = (l_dec and lt.is_wide) or (r_dec and rt.is_wide)
        if lf or rf:
            fa = np.array([float(x) for x in a]) if a.dtype == object \
                else a.astype(np.float64)
            fb = np.array([float(x) for x in b]) if b.dtype == object \
                else b.astype(np.float64)
            a = fa / (10.0 ** lt.scale if l_dec else 1.0)
            b = fb / (10.0 ** rt.scale if r_dec else 1.0)
        elif wide:
            ls = lt.scale if l_dec else 0
            rs = rt.scale if r_dec else 0
            s = max(ls, rs)
            a = _obj_ints(a) * (10 ** (s - ls))
            b = _obj_ints(b) * (10 ** (s - rs))
        else:
            ls = lt.scale if l_dec else 0
            rs = rt.scale if r_dec else 0
            s = max(ls, rs)
            a = a.astype(np.int64) * (10 ** (s - ls))
            b = b.astype(np.int64) * (10 ** (s - rs))
        return a, b, mask, False
    if a.dtype != b.dtype:
        out_t = dt.promote(lt, rt)
        phys = np.dtype(out_t.physical)
        a = a.astype(phys)
        b = b.astype(phys)
    return a, b, mask, False


def _nan_lt(a, b):
    if np.issubdtype(a.dtype, np.floating):
        a_nan = np.isnan(a)
        b_nan = np.isnan(b)
        return np.where(a_nan, False, np.where(b_nan, True, a < b))
    return a < b


def _nan_eq(a, b):
    if a.dtype != object and np.issubdtype(a.dtype, np.floating):
        return (np.isnan(a) & np.isnan(b)) | (a == b)
    return a == b


def _str_lt(a, b):
    # Python str compare is code-point order == UTF-8 byte order.
    return np.array([x < y for x, y in zip(a, b)], dtype=bool) \
        if len(a) else np.zeros(0, bool)


def _cmp(expr, table, kind):
    a, b, mask, is_str = _aligned_np(expr, table)
    if is_str:
        if kind == "eq":
            out = a == b
        elif kind == "lt":
            out = _str_lt(a, b)
        elif kind == "gt":
            out = _str_lt(b, a)
        elif kind == "le":
            out = ~_str_lt(b, a)
        else:
            out = ~_str_lt(a, b)
    else:
        if kind == "eq":
            out = _nan_eq(a, b)
        elif kind == "lt":
            out = _nan_lt(a, b)
        elif kind == "gt":
            out = _nan_lt(b, a)
        elif kind == "le":
            out = ~_nan_lt(b, a)
        else:
            out = ~_nan_lt(a, b)
    out = np.asarray(out, bool)
    return out & mask, mask


@_reg(P.EqualTo)
def _eq(e, t):
    return _cmp(e, t, "eq")


@_reg(P.LessThan)
def _lt(e, t):
    return _cmp(e, t, "lt")


@_reg(P.GreaterThan)
def _gt(e, t):
    return _cmp(e, t, "gt")


@_reg(P.LessThanOrEqual)
def _le(e, t):
    return _cmp(e, t, "le")


@_reg(P.GreaterThanOrEqual)
def _ge(e, t):
    return _cmp(e, t, "ge")


@_reg(P.EqualNullSafe)
def _eqns(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    lt = expr.children[0].data_type(table.schema())
    if lt == dt.STRING:
        eq = a == b
    else:
        eq = _nan_eq(a, b)
    out = (~am & ~bm) | (am & bm & np.asarray(eq, bool))
    return out, np.ones(table.num_rows, bool)


@_reg(P.And)
def _and(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    known_false = (am & ~a) | (bm & ~b)
    mask = (am & bm) | known_false
    return (a & b) & ~known_false & mask, mask


@_reg(P.Or)
def _or(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    known_true = (am & a) | (bm & b)
    mask = (am & bm) | known_true
    return (known_true | (a & b)) & mask, mask


@_reg(P.Not)
def _not(expr, table):
    a, m = _ev(expr.children[0], table)
    return (~a) & m, m


@_reg(P.IsNull)
def _isnull(expr, table):
    _, m = _ev(expr.children[0], table)
    return ~m, np.ones(table.num_rows, bool)


@_reg(P.IsNotNull)
def _isnotnull(expr, table):
    _, m = _ev(expr.children[0], table)
    return m, np.ones(table.num_rows, bool)


@_reg(P.IsNaN)
def _isnan(expr, table):
    a, m = _ev(expr.children[0], table)
    out = np.isnan(a.astype(np.float64)) if a.dtype != object else \
        np.zeros(len(a), bool)
    return out & m, m


@_reg(P.InSet)
def _inset(expr, table):
    a, m = _ev(expr.children[0], table)
    lt = expr.children[0].data_type(table.schema())
    vals = [v for v in expr.values if v is not None]
    if lt == dt.STRING:
        hit = np.isin(np.asarray(a, dtype=object), np.array(vals, object)) \
            if vals else np.zeros(len(a), bool)
    else:
        from ..columnar.vector import _to_physical
        phys = [_to_physical(v, lt) for v in vals]
        hit = np.isin(a, np.array(phys, a.dtype)) if phys else \
            np.zeros(len(a), bool)
    return hit & m, m


# ---------------------------------------------------------------------------
# conditionals
# ---------------------------------------------------------------------------

def _coerce_to(values, mask, from_t, to_t, n):
    """Cast already-evaluated lanes to the common output type."""
    if from_t == to_t:
        return values, mask
    if to_t == dt.STRING or from_t == dt.STRING:
        return values, mask
    if isinstance(to_t, dt.DecimalType):
        wide = to_t.is_wide or (isinstance(from_t, dt.DecimalType)
                                and from_t.is_wide)
        if wide:
            v = _obj_ints(values)
            fs = from_t.scale if isinstance(from_t, dt.DecimalType) else 0
            if to_t.scale >= fs:
                v = v * (10 ** (to_t.scale - fs))
            else:
                v = _half_up_obj(v, fs - to_t.scale)
            return v, mask
        if isinstance(from_t, dt.DecimalType):
            return _rescale_np(values.astype(np.int64), from_t.scale,
                               to_t.scale), mask
        return values.astype(np.int64) * np.int64(10 ** to_t.scale), mask
    return values.astype(np.dtype(to_t.physical)), mask


def _select_eval(expr, table, branches, default):
    """Shared CASE WHEN machinery: branches = [(cond_expr, value_expr)]."""
    schema = table.schema()
    out_t = expr.data_type(schema)
    n = table.num_rows
    if out_t == dt.STRING:
        out = np.full(n, "", dtype=object)
    elif isinstance(out_t, dt.DecimalType) and out_t.is_wide:
        out = np.zeros(n, dtype=object)
    else:
        out = np.zeros(n, np.dtype(out_t.physical))
    out_mask = np.zeros(n, bool)
    decided = np.zeros(n, bool)
    for cond_e, val_e in branches:
        cv, cm = _ev(cond_e, table)
        take = (~decided) & cm & cv
        v, m = _ev(val_e, table)
        v, m = _coerce_to(v, m, val_e.data_type(schema), out_t, n)
        out = np.where(take, v, out)
        out_mask = np.where(take, m, out_mask)
        decided |= take
    if default is not None:
        v, m = _ev(default, table)
        v, m = _coerce_to(v, m, default.data_type(schema), out_t, n)
        out = np.where(~decided, v, out)
        out_mask = np.where(~decided, m, out_mask)
    return _zero_nulls(out, out_mask), out_mask


@_reg(Cond.If)
def _if(expr, table):
    pred, a, b = expr.children
    return _select_eval(expr, table, [(pred, a)], b)


@_reg(Cond.CaseWhen)
def _casewhen(expr, table):
    return _select_eval(expr, table, expr.branches, expr.otherwise)


@_reg(Cond.Coalesce)
def _coalesce(expr, table):
    schema = table.schema()
    out_t = expr.data_type(schema)
    n = table.num_rows
    if out_t == dt.STRING:
        out = np.full(n, "", dtype=object)
    elif isinstance(out_t, dt.DecimalType) and out_t.is_wide:
        out = np.zeros(n, dtype=object)
    else:
        out = np.zeros(n, np.dtype(out_t.physical))
    out_mask = np.zeros(n, bool)
    for c in expr.children:
        v, m = _ev(c, table)
        v, m = _coerce_to(v, m, c.data_type(schema), out_t, n)
        take = (~out_mask) & m
        out = np.where(take, v, out)
        out_mask |= take
    return _zero_nulls(out, out_mask), out_mask


@_reg(Cond.Nvl)
def _nvl(expr, table):
    return _coalesce(expr, table)


@_reg(Cond.NullIf)
def _nullif(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    lt = expr.children[0].data_type(table.schema())
    eq = (a == b) if lt == dt.STRING else _nan_eq(a, b)
    mask = am & ~(am & bm & np.asarray(eq, bool))
    return _zero_nulls(a, mask), mask


@_reg(Cond.Nvl2)
def _nvl2(expr, table):
    from ..expr.predicates import IsNotNull
    x, a, b = expr.children
    return _select_eval(expr, table, [(IsNotNull(x), a)], b)


# --- timezone conversions (independent per-row zoneinfo oracle) -----------

def _tz_oracle(name: str):
    import datetime
    import zoneinfo
    from ..expr.timezone import _fixed_offset_us
    fixed = _fixed_offset_us(name)
    if fixed is not None:
        return datetime.timezone(
            datetime.timedelta(microseconds=fixed))
    return zoneinfo.ZoneInfo(name)


def _utc_offset_us(tz, us: int) -> int:
    import datetime
    from ..expr import timezone as TZX
    # clamp to the device transition tables' probe horizon (1800..2200):
    # past it the device freezes on the last known offset, so the oracle
    # asks zoneinfo for the horizon instant instead of the raw one
    lo = int((TZX._PROBE_START - TZX._EPOCH).total_seconds()) * 1_000_000
    hi = int((TZX._PROBE_END - TZX._EPOCH).total_seconds()) * 1_000_000 - 1
    us = max(lo, min(int(us), hi))
    inst = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc) + \
        datetime.timedelta(microseconds=us)
    return int(inst.astimezone(tz).utcoffset().total_seconds()) * 1_000_000


def _reg_tz():
    from ..expr import timezone as TZX

    @_reg(TZX.FromUTCTimestamp)
    def _from_utc(expr, table):
        a, m = _ev(expr.children[0], table)
        tz = _tz_oracle(expr.zone)
        out = np.array([int(v) + _utc_offset_us(tz, v) if mk else 0
                        for v, mk in zip(a, m)], np.int64)
        return out, m

    @_reg(TZX.ToUTCTimestamp)
    def _to_utc(expr, table):
        # mirror the device's two-step offset resolution, but with
        # per-row zoneinfo lookups (independent of the transition-table
        # builder the device uses)
        a, m = _ev(expr.children[0], table)
        tz = _tz_oracle(expr.zone)
        out = np.zeros(len(a), np.int64)
        for i, (v, mk) in enumerate(zip(a, m)):
            if not mk:
                continue
            o1 = _utc_offset_us(tz, v)
            o2 = _utc_offset_us(tz, int(v) - o1)
            out[i] = int(v) - o2
        return out, m


_reg_tz()


# --- JSON (independent sequential span walker as the oracle for the
# device byte-scan kernel; same raw-span envelope, see expr/json.py) --------

def _json_skip_ws(s, i):
    while i < len(s) and s[i] in " \t\n\r":
        i += 1
    return i


def _json_value_end(s, i):
    """End index (exclusive) of the JSON value starting at i."""
    import json
    if i >= len(s):
        return None
    c = s[i]
    if c == '"':
        j = i + 1
        while j < len(s):
            if s[j] == "\\":
                j += 2
                continue
            if s[j] == '"':
                return j + 1
            j += 1
        return None
    if c in "{[":
        depth = 0
        j = i
        in_str = False
        while j < len(s):
            ch = s[j]
            if in_str:
                if ch == "\\":
                    j += 2
                    continue
                if ch == '"':
                    in_str = False
            elif ch == '"':
                in_str = True
            elif ch in "{[":
                depth += 1
            elif ch in "}]":
                depth -= 1
                if depth == 0:
                    return j + 1
            j += 1
        return None
    j = i
    while j < len(s) and s[j] not in ",}] \t\n\r":
        j += 1
    # a zero-length "scalar" means the cursor sat on a delimiter —
    # malformed JSON (e.g. '{"k": ]}'), not an empty value; fuzz lane
    # caught the '' vs null divergence vs the device scanner
    return j if j > i else None


def _json_get_path(s, segments):
    """Raw span of the value at the path; None when missing/invalid."""
    import json
    i = _json_skip_ws(s, 0)
    end = _json_value_end(s, i)
    if end is None:
        return None
    for kind, arg in segments:
        i = _json_skip_ws(s, i)
        if kind == "key":
            if i >= len(s) or s[i] != "{":
                return None
            j = i + 1
            found = None
            while True:
                j = _json_skip_ws(s, j)
                if j >= len(s) or s[j] == "}":
                    break
                ke = _json_value_end(s, j)
                if ke is None:
                    return None
                try:
                    key = json.loads(s[j:ke])
                except ValueError:
                    return None
                j = _json_skip_ws(s, ke)
                if j >= len(s) or s[j] != ":":
                    return None
                j = _json_skip_ws(s, j + 1)
                ve = _json_value_end(s, j)
                if ve is None:
                    return None
                if key == arg:
                    found = (j, ve)
                    break
                j = _json_skip_ws(s, ve)
                if j < len(s) and s[j] == ",":
                    j += 1
            if found is None:
                return None
            i, end = found
        else:
            if i >= len(s) or s[i] != "[":
                return None
            j = _json_skip_ws(s, i + 1)
            n = 0
            found = None
            while j < len(s) and s[j] != "]":
                ve = _json_value_end(s, j)
                if ve is None:
                    return None
                if n == arg:
                    found = (j, ve)
                    break
                n += 1
                j = _json_skip_ws(s, ve)
                if j < len(s) and s[j] == ",":
                    j = _json_skip_ws(s, j + 1)
            if found is None:
                return None
            i, end = found
    span = s[i:end]
    if span == "null":
        return None
    if span.startswith('"'):
        # manual simple-escape decode matching the device kernel
        # (\uXXXX passes through un-decoded on both engines)
        body = span[1:-1]
        out = []
        k = 0
        esc_map = {'"': '"', "\\": "\\", "/": "/", "n": "\n",
                   "t": "\t", "r": "\r", "b": "\b", "f": "\f"}
        while k < len(body):
            c = body[k]
            if c == "\\" and k + 1 < len(body) and \
                    body[k + 1] in esc_map:
                out.append(esc_map[body[k + 1]])
                k += 2
                continue
            out.append(c)
            k += 1
        return "".join(out)
    return span


def _reg_json():
    from ..expr import json as JX

    @_reg(JX.GetJsonObject)
    def _gjo(expr, table):
        a, m = _ev(expr.children[0], table)
        out = np.empty(len(a), dtype=object)
        mask = np.zeros(len(a), bool)
        for i, (s, mk) in enumerate(zip(a, m)):
            v = _json_get_path(s, expr.segments) if mk else None
            out[i] = v if v is not None else ""
            mask[i] = mk and v is not None
        return out, mask

    @_reg(JX.JsonToStructs)
    def _from_json(expr, table):
        import json
        a, m = _ev(expr.children[0], table)
        out = np.empty(len(a), dtype=object)
        mask = np.zeros(len(a), bool)
        fields = expr.struct_schema.fields
        for i, (s, mk) in enumerate(zip(a, m)):
            if not mk:
                continue
            try:
                obj = json.loads(s)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            out[i] = {n: _json_coerce(obj.get(n), t) for n, t in fields}
            mask[i] = True
        return out, mask

    @_reg(JX.StructsToJson)
    def _to_json(expr, table):
        import json
        a, m = _ev(expr.children[0], table)
        out = np.empty(len(a), dtype=object)
        for i, (v, mk) in enumerate(zip(a, m)):
            out[i] = json.dumps(v, separators=(",", ":"),
                                default=str) if mk else ""
        return out, m


def _json_coerce(v, t):
    if v is None:
        return None
    try:
        if t == dt.STRING:
            return v if isinstance(v, str) else                 __import__("json").dumps(v, separators=(",", ":"))
        if t.is_integral:
            return int(v)
        if t.is_floating:
            return float(v)
        if isinstance(t, dt.BooleanType):
            return bool(v)
        if isinstance(t, dt.DecimalType):
            from decimal import ROUND_HALF_UP, Decimal
            d = Decimal(str(v)).quantize(Decimal(1).scaleb(-t.scale),
                                         rounding=ROUND_HALF_UP)
            # overflow past the declared precision -> null (Spark)
            if abs(d) >= Decimal(1).scaleb(t.precision - t.scale):
                return None
            return d
        if isinstance(t, dt.DateType):
            import datetime
            return datetime.date.fromisoformat(v)
        if isinstance(t, dt.TimestampType):
            import datetime
            return datetime.datetime.fromisoformat(v)
        if isinstance(t, dt.ArrayType):
            return [_json_coerce(x, t.element_type) for x in v]
        if isinstance(t, dt.StructType):
            return {n: _json_coerce(v.get(n), ft) for n, ft in t.fields}
    except (TypeError, ValueError, ArithmeticError):
        return None
    return None


_reg_json()


# ---------------------------------------------------------------------------
# math
# ---------------------------------------------------------------------------

def _unary_double(fn):
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        with np.errstate(all="ignore"):
            out = fn(a.astype(np.float64))
        return _zero_nulls(out, m), m
    return ev


_MATH_FNS = {
    M.Sqrt: np.sqrt, M.Cbrt: np.cbrt, M.Exp: np.exp, M.Expm1: np.expm1,
    M.Log1p: np.log1p,
    M.Sin: np.sin, M.Cos: np.cos, M.Tan: np.tan,
    M.Asin: np.arcsin, M.Acos: np.arccos, M.Atan: np.arctan,
    M.Sinh: np.sinh, M.Cosh: np.cosh, M.Tanh: np.tanh,
    M.Asinh: np.arcsinh, M.Acosh: np.arccosh, M.Atanh: np.arctanh,
    M.ToDegrees: np.degrees, M.ToRadians: np.radians,
    M.Signum: np.sign, M.Rint: np.rint,
}
for _cls, _fn in _MATH_FNS.items():
    _EVALUATORS[_cls] = _unary_double(_fn)


def _log_like(np_fn):
    """Spark log-family: non-positive input -> null."""
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        x = a.astype(np.float64)
        mask = m & (x > 0)
        with np.errstate(all="ignore"):
            out = np_fn(np.where(x > 0, x, 1.0))
        return _zero_nulls(out, mask), mask
    return ev


_EVALUATORS[M.Log] = _log_like(np.log)
_EVALUATORS[M.Log2] = _log_like(np.log2)
_EVALUATORS[M.Log10] = _log_like(np.log10)


@_reg(M.Floor)
def _floor(expr, table):
    a, m = _ev(expr.children[0], table)
    t = expr.children[0].data_type(table.schema())
    if isinstance(t, dt.DecimalType):
        if a.dtype == object:
            out = np.array([int(v) // 10 ** t.scale for v in a], np.int64)
        else:
            out = a.astype(np.int64) // np.int64(10 ** t.scale)
        return _zero_nulls(out, m), m
    return _zero_nulls(np.floor(a.astype(np.float64)).astype(np.int64), m), m


@_reg(M.Ceil)
def _ceil(expr, table):
    a, m = _ev(expr.children[0], table)
    t = expr.children[0].data_type(table.schema())
    if isinstance(t, dt.DecimalType):
        if a.dtype == object:
            out = np.array([-((-int(v)) // 10 ** t.scale) for v in a],
                           np.int64)
        else:
            out = -((-a.astype(np.int64)) // np.int64(10 ** t.scale))
        return _zero_nulls(out, m), m
    return _zero_nulls(np.ceil(a.astype(np.float64)).astype(np.int64), m), m


@_reg(M.Pow)
def _pow(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    with np.errstate(all="ignore"):
        out = np.power(a.astype(np.float64), b.astype(np.float64))
    return _zero_nulls(out, m), m


@_reg(M.Atan2)
def _atan2(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    out = np.arctan2(a.astype(np.float64), b.astype(np.float64))
    return _zero_nulls(out, m), m


@_reg(M.Hypot)
def _hypot(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    out = np.hypot(a.astype(np.float64), b.astype(np.float64))
    return _zero_nulls(out, m), m


def _round_half_up(x, scale):
    f = 10.0 ** scale
    return np.floor(np.abs(x) * f + 0.5) / f * np.sign(x)


def _round_common(expr, table, half_even: bool):
    a, m = _ev(expr.children[0], table)
    t = expr.children[0].data_type(table.schema())
    scale = expr.scale
    if isinstance(t, dt.DecimalType):
        # output scale = min(scale, t.scale) (scale>=0) else 0; HALF_UP on
        # the unscaled lanes (mirrors Round.eval for decimals)
        target = min(scale, t.scale) if scale >= 0 else 0
        drop = t.scale - target
        if drop <= 0:
            return a, m
        if a.dtype == object:
            pp = 10 ** drop
            hf = pp // 2
            out = np.array([(abs(int(v)) + hf) // pp *
                            (1 if int(v) >= 0 else -1) for v in a],
                           dtype=object)
            return np.where(m, out, 0), m
        p = np.int64(10 ** drop)
        half = p // 2
        av = a.astype(np.int64)
        out = np.where(av >= 0, (av + half) // p, -((-av + half) // p))
        return _zero_nulls(out, m), m
    if t.is_integral:
        if scale >= 0:
            return a, m
        p = np.int64(10 ** (-scale))
        half = p // 2
        out = np.where(a >= 0, (a + half) // p, -((-a + half) // p)) * p
        return _zero_nulls(out, m), m
    x = a.astype(np.float64)
    if half_even:
        f = 10.0 ** scale
        out = np.round(x * f) / f  # numpy round = HALF_EVEN
    else:
        out = _round_half_up(x, scale)
    return _zero_nulls(out.astype(a.dtype), m), m


@_reg(M.Round)
def _round(expr, table):
    return _round_common(expr, table, half_even=False)


@_reg(M.BRound)
def _bround(expr, table):
    return _round_common(expr, table, half_even=True)


# ---------------------------------------------------------------------------
# strings
# ---------------------------------------------------------------------------

def _str_map(fn):
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        out = np.array([fn(x) for x in a], dtype=object) if len(a) else \
            np.empty(0, object)
        return np.where(m, out, ""), m
    return ev


@_reg(S.Length)
def _length(expr, table):
    a, m = _ev(expr.children[0], table)
    out = np.array([len(x) for x in a], dtype=np.int32) if len(a) else \
        np.empty(0, np.int32)
    return _zero_nulls(out, m), m


@_reg(S.OctetLength)
def _octet_length(expr, table):
    a, m = _ev(expr.children[0], table)
    out = np.array([len(x.encode("utf-8")) for x in a], dtype=np.int32) \
        if len(a) else np.empty(0, np.int32)
    return _zero_nulls(out, m), m


_EVALUATORS[S.Upper] = _str_map(lambda s: s.upper())
_EVALUATORS[S.Lower] = _str_map(lambda s: s.lower())


@_reg(S.Substring)
def _substring(expr, table):
    a, m = _ev(expr.children[0], table)
    pos, length = expr.pos, expr.length
    def sub(s):
        # Spark 1-based substring semantics
        if pos > 0:
            start = pos - 1
        elif pos == 0:
            start = 0
        else:
            start = max(len(s) + pos, 0)
        end = min(start + length, len(s))
        return s[start:end]
    out = np.array([sub(x) for x in a], dtype=object) if len(a) else \
        np.empty(0, object)
    return np.where(m, out, ""), m


@_reg(S.Concat)
def _concat(expr, table):
    n = table.num_rows
    parts = [_ev(c, table) for c in expr.children]
    mask = np.ones(n, bool)
    for _, m in parts:
        mask &= m
    out = np.array(["".join(p[0][i] for p in parts) for i in range(n)],
                   dtype=object) if n else np.empty(0, object)
    return np.where(mask, out, ""), mask


def _str_static_predicate(attr, fn):
    # StartsWith/EndsWith/Contains carry a static pattern string
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        p = getattr(expr, attr)
        out = np.array([fn(x, p) for x in a], dtype=bool) \
            if len(a) else np.empty(0, bool)
        return out & m, m
    return ev


_EVALUATORS[S.StartsWith] = _str_static_predicate(
    "prefix", lambda s, p: s.startswith(p))
_EVALUATORS[S.EndsWith] = _str_static_predicate(
    "suffix", lambda s, p: s.endswith(p))
_EVALUATORS[S.Contains] = _str_static_predicate(
    "needle", lambda s, p: p in s)


@_reg(S.Like)
def _like(expr, table):
    import re
    a, m = _ev(expr.children[0], table)
    pat = expr.pattern
    esc = expr.escape
    regex = []
    i = 0
    while i < len(pat):
        ch = pat[i]
        if ch == esc and i + 1 < len(pat):
            regex.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if ch == "%":
            regex.append(".*")
        elif ch == "_":
            regex.append(".")
        else:
            regex.append(re.escape(ch))
        i += 1
    prog = re.compile("(?s)^" + "".join(regex) + "$")
    out = np.array([prog.match(x) is not None for x in a], dtype=bool) \
        if len(a) else np.empty(0, bool)
    return out & m, m


def _trim_eval(which):
    # TPU impl trims only ASCII space (byte 32); mirror exactly.
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        def trim(s):
            if which == "both":
                return s.strip(" ")
            if which == "left":
                return s.lstrip(" ")
            return s.rstrip(" ")
        out = np.array([trim(x) for x in a], dtype=object) if len(a) else \
            np.empty(0, object)
        return np.where(m, out, ""), m
    return ev


_EVALUATORS[S.StringTrim] = _trim_eval("both")
_EVALUATORS[S.StringTrimLeft] = _trim_eval("left")
_EVALUATORS[S.StringTrimRight] = _trim_eval("right")


# ---------------------------------------------------------------------------
# datetime (lanes: date = int32 days since epoch, ts = int64 micros UTC)
# ---------------------------------------------------------------------------

_EPOCH = np.datetime64("1970-01-01", "D")


def _days_to_ymd(days):
    d = _EPOCH + days.astype("timedelta64[D]")
    y = d.astype("datetime64[Y]").astype(np.int64) + 1970
    month = (d.astype("datetime64[M]").astype(np.int64) % 12) + 1
    day = (d - d.astype("datetime64[M]")).astype(np.int64) + 1
    return y, month, day


def _date_field(fn):
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        t = expr.children[0].data_type(table.schema())
        days = a.astype(np.int64)
        if isinstance(t, dt.TimestampType):
            days = np.floor_divide(days, 86_400_000_000)
        y, mo, dnum = _days_to_ymd(days)
        out = fn(days, y, mo, dnum).astype(np.int32)
        return _zero_nulls(out, m), m
    return ev


_EVALUATORS[D.Year] = _date_field(lambda d, y, mo, dd: y)
_EVALUATORS[D.Month] = _date_field(lambda d, y, mo, dd: mo)
_EVALUATORS[D.DayOfMonth] = _date_field(lambda d, y, mo, dd: dd)
_EVALUATORS[D.Quarter] = _date_field(lambda d, y, mo, dd: (mo - 1) // 3 + 1)
# Spark dayofweek: 1 = Sunday. Epoch (1970-01-01) was a Thursday.
_EVALUATORS[D.DayOfWeek] = _date_field(
    lambda d, y, mo, dd: ((d + 4) % 7) + 1)
# weekday(): 0 = Monday
_EVALUATORS[D.WeekDay] = _date_field(lambda d, y, mo, dd: (d + 3) % 7)
_EVALUATORS[D.DayOfYear] = _date_field(
    lambda d, y, mo, dd: d - (
        (_EPOCH + d.astype("timedelta64[D]")).astype("datetime64[Y]")
        - _EPOCH).astype(np.int64) + 1)


@_reg(D.LastDay)
def _lastday(expr, table):
    a, m = _ev(expr.children[0], table)
    d = _EPOCH + a.astype(np.int64).astype("timedelta64[D]")
    month_start = d.astype("datetime64[M]")
    next_month = month_start + np.timedelta64(1, "M")
    out = (next_month.astype("datetime64[D]") - np.timedelta64(1, "D")
           - _EPOCH).astype(np.int32)
    return _zero_nulls(out, m), m


def _time_field(fn):
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        micros = a.astype(np.int64)
        secs = np.floor_divide(micros, 1_000_000)
        out = fn(secs).astype(np.int32)
        return _zero_nulls(out, m), m
    return ev


_EVALUATORS[D.Hour] = _time_field(lambda s: (s % 86400) // 3600)
_EVALUATORS[D.Minute] = _time_field(lambda s: (s % 3600) // 60)
_EVALUATORS[D.Second] = _time_field(lambda s: s % 60)


@_reg(D.DateAdd)
def _dateadd(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    return _zero_nulls((a.astype(np.int64) + b.astype(np.int64))
                       .astype(np.int32), m), m


@_reg(D.DateSub)
def _datesub(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    return _zero_nulls((a.astype(np.int64) - b.astype(np.int64))
                       .astype(np.int32), m), m


@_reg(D.DateDiff)
def _datediff(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    return _zero_nulls((a.astype(np.int64) - b.astype(np.int64))
                       .astype(np.int32), m), m


@_reg(D.AddMonths)
def _addmonths(expr, table):
    a, am = _ev(expr.children[0], table)
    b, bm = _ev(expr.children[1], table)
    m = am & bm
    d = _EPOCH + a.astype(np.int64).astype("timedelta64[D]")
    month0 = d.astype("datetime64[M]")
    day_in_month = (d - month0).astype(np.int64)
    new_month = month0 + b.astype(np.int64).astype("timedelta64[M]")
    next_m = new_month + np.timedelta64(1, "M")
    month_len = (next_m.astype("datetime64[D]")
                 - new_month.astype("datetime64[D]")).astype(np.int64)
    day = np.minimum(day_in_month, month_len - 1)
    out = (new_month.astype("datetime64[D]") - _EPOCH).astype(np.int64) + day
    return _zero_nulls(out.astype(np.int32), m), m


@_reg(D.UnixTimestampToSeconds)
def _unixts(expr, table):
    a, m = _ev(expr.children[0], table)
    out = np.floor_divide(a.astype(np.int64), 1_000_000)
    return _zero_nulls(out, m), m


@_reg(D.FromUnixTime)
def _fromunix(expr, table):
    a, m = _ev(expr.children[0], table)
    out = a.astype(np.int64) * 1_000_000
    return _zero_nulls(out, m), m


@_reg(D.MakeDate)
def _makedate(expr, table):
    y, ym = _ev(expr.children[0], table)
    mo, mm = _ev(expr.children[1], table)
    d, dm = _ev(expr.children[2], table)
    m = ym & mm & dm
    out = np.zeros(len(y), np.int32)
    ok = np.ones(len(y), bool)
    for i in range(len(y)):
        if not m[i]:
            continue
        try:
            import datetime
            out[i] = (datetime.date(int(y[i]), int(mo[i]), int(d[i]))
                      - datetime.date(1970, 1, 1)).days
        except ValueError:
            ok[i] = False
    m = m & ok
    return _zero_nulls(out, m), m


# ---------------------------------------------------------------------------
# cast
# ---------------------------------------------------------------------------

@_reg(C.Cast)
def _cast(expr, table):
    schema = table.schema()
    from_t = expr.children[0].data_type(schema)
    to_t = expr.to
    a, m = _ev(expr.children[0], table)
    n = table.num_rows
    if from_t == to_t:
        return a, m
    # string -> X
    if from_t == dt.STRING:
        if to_t == dt.STRING:
            return a, m
        out = np.zeros(n, np.dtype(to_t.physical))
        ok = np.zeros(n, bool)
        for i in range(n):
            if not m[i]:
                continue
            s = str(a[i]).strip()
            try:
                if isinstance(to_t, dt.DecimalType):
                    import decimal
                    out[i] = int(decimal.Decimal(s)
                                 .scaleb(to_t.scale).to_integral_value())
                elif to_t.is_floating:
                    # Cast.processFloatingPointSpecialLiterals: signed
                    # inf/infinity + unsigned nan, case-insensitive;
                    # python float() would reject 'Infinity'? (it
                    # accepts 'inf'/'infinity'/'nan' — normalize anyway
                    # so both engines share one rule)
                    sl = s.lower()
                    if sl in ("inf", "+inf", "infinity", "+infinity"):
                        out[i] = np.inf
                    elif sl in ("-inf", "-infinity"):
                        out[i] = -np.inf
                    elif sl == "nan":
                        out[i] = np.nan
                    elif sl in ("+nan", "-nan"):
                        raise ValueError(s)  # Spark: nan takes no sign
                    else:
                        out[i] = float(s)
                elif to_t == dt.BOOL:
                    sl = s.lower()
                    if sl in ("t", "true", "y", "yes", "1"):
                        out[i] = True
                    elif sl in ("f", "false", "n", "no", "0"):
                        out[i] = False
                    else:
                        raise ValueError(s)
                elif to_t == dt.DATE:
                    out[i] = _spark_string_to_date(s)
                else:
                    # UTF8String.toLong semantics, mirrored exactly
                    # with the device _parse_int: optional sign, ASCII
                    # digits, one optional '.' with an all-digit
                    # fraction that TRUNCATES (no float round-trip —
                    # '1.9999999999999999' is 1, not 2); scientific
                    # notation is invalid
                    body = s
                    sign = 1
                    if body[:1] in ("+", "-"):
                        sign = -1 if body[0] == "-" else 1
                        body = body[1:]
                    intpart, _, frac = body.partition(".")
                    if not intpart or \
                            not all("0" <= ch <= "9" for ch in intpart) \
                            or not all("0" <= ch <= "9" for ch in frac):
                        raise ValueError(s)
                    iv = sign * int(intpart)
                    info = np.iinfo(np.dtype(to_t.physical))
                    if not info.min <= iv <= info.max:
                        raise ValueError(s)  # out of range -> null
                    out[i] = iv
                ok[i] = True
            except (ValueError, ArithmeticError):
                ok[i] = False
        if expr.ansi:
            from ..expr import errors as ERR
            exc_t = ERR.SparkDateTimeException if isinstance(
                to_t, (dt.DateType, dt.TimestampType)) \
                else ERR.SparkNumberFormatException
            _ansi_raise_if(m & ~ok, exc_t(
                f"invalid input syntax for type {to_t} (ANSI mode cast)"))
        m = m & ok
        return _zero_nulls(out, m), m
    # X -> string
    if to_t == dt.STRING:
        out = np.empty(n, object)
        for i in range(n):
            out[i] = _value_to_string(a[i], from_t) if m[i] else ""
        return out, m
    # decimal source (exact python-int lanes; HALF_UP rescale, matching
    # the device decimal128 path and GpuCast decimal semantics)
    if isinstance(from_t, dt.DecimalType):
        av = _obj_ints(a)
        if isinstance(to_t, dt.DecimalType):
            if to_t.scale >= from_t.scale:
                out = av * (10 ** (to_t.scale - from_t.scale))
            else:
                out = _half_up_obj(av, from_t.scale - to_t.scale)
            bound = 10 ** to_t.precision
            ok = np.array([abs(int(v)) < bound and abs(int(v)) < _I128_MAX
                           for v in out], bool)
            if expr.ansi:
                from ..expr import errors as ERR
                _ansi_raise_if(m & ~ok, ERR.SparkCastOverflowException(
                    f"cast to {to_t} causes overflow (ANSI mode)"))
            m = m & ok
            out = np.where(m, out, 0)
            if not to_t.is_wide:
                out = np.array([int(v) for v in out], dtype=np.int64)
            return out, m
        if to_t.is_floating:
            real = np.array([float(int(v)) for v in av]) / \
                (10.0 ** from_t.scale)
            return _zero_nulls(real.astype(np.dtype(to_t.physical)), m), m
        if to_t == dt.BOOL:
            return _zero_nulls(
                np.array([int(v) != 0 for v in av], bool), m), m
        # integral target: truncate toward zero, null outside the range
        p = 10 ** from_t.scale
        tv = np.array([abs(int(v)) // p * (1 if int(v) >= 0 else -1)
                       for v in av], dtype=object)
        lo_b, hi_b = int(dt.min_value(to_t)), int(dt.max_value(to_t))
        ok = np.array([lo_b <= int(v) <= hi_b for v in tv], bool)
        pre_m = m
        if expr.ansi:
            from ..expr import errors as ERR
            _ansi_raise_if(pre_m & ~ok, ERR.SparkCastOverflowException(
                f"cast to {to_t} causes overflow (ANSI mode)"))
        m = m & ok
        out = np.array([int(v) if k else 0 for v, k in zip(tv, ok)],
                       dtype=np.dtype(to_t.physical))
        return out, m
    # numeric -> decimal
    if isinstance(to_t, dt.DecimalType):
        bound = 10 ** to_t.precision
        if from_t.is_floating:
            scaled = a.astype(np.float64) * 10.0 ** to_t.scale
            ok = np.isfinite(scaled) & (np.abs(scaled) < float(bound))
            safe = np.where(ok, scaled, 0.0)
            vals = [int(np.sign(x)) * int(np.floor(abs(x) + 0.5))
                    for x in safe]
        else:
            vals = [int(x) * 10 ** to_t.scale for x in a]
            ok = np.array([abs(v) < bound for v in vals], bool)
            vals = [v if k else 0 for v, k in zip(vals, ok)]
        if expr.ansi:
            from ..expr import errors as ERR
            _ansi_raise_if(m & ~ok, ERR.SparkCastOverflowException(
                f"cast to {to_t} causes overflow (ANSI mode)"))
        m = m & ok
        if to_t.is_wide:
            return np.array(vals, dtype=object), m
        out = np.array([int(v) for v in vals], dtype=np.int64)
        return _zero_nulls(out, m), m
    # timestamp <-> date
    if from_t == dt.TIMESTAMP and to_t == dt.DATE:
        out = np.floor_divide(a.astype(np.int64),
                              86_400_000_000).astype(np.int32)
        return _zero_nulls(out, m), m
    if from_t == dt.DATE and to_t == dt.TIMESTAMP:
        out = a.astype(np.int64) * 86_400_000_000
        return _zero_nulls(out, m), m
    # numeric <-> numeric / bool
    phys = np.dtype(to_t.physical)
    if expr.ansi and getattr(to_t, "is_integral", False) \
            and getattr(from_t, "is_numeric", False) \
            and not isinstance(from_t, dt.DecimalType):
        from ..expr import errors as ERR
        info = np.iinfo(phys)
        if from_t.is_floating:
            with np.errstate(invalid="ignore"):
                bad = np.isnan(a) | (a < float(info.min)) | \
                    (a >= float(info.max) + 1.0)
        elif a.dtype.itemsize > phys.itemsize:
            bad = (a < info.min) | (a > info.max)
        else:
            bad = np.zeros(len(a), bool)
        _ansi_raise_if(m & bad, ERR.SparkCastOverflowException(
            f"casting {from_t} to {to_t} causes overflow (ANSI mode)"))
    if from_t.is_floating and not (to_t.is_floating or to_t == dt.BOOL):
        # Scala Double.toLong semantics: NaN -> 0, out-of-range
        # saturates (np.trunc(...).astype alone is UB for both)
        info = np.iinfo(phys)
        with np.errstate(invalid="ignore"):
            x = np.where(np.isnan(a), 0.0, a)
            t = np.trunc(x)
            out = np.clip(t, float(info.min), float(info.max))
            out = out.astype(phys)
            # float64(int64.max) rounds UP to 2^63: clip leaves 2^63
            # which astype wraps — pin explicitly
            out = np.where(t >= float(info.max), info.max, out)
            out = np.where(t <= float(info.min), info.min, out)
        return _zero_nulls(out.astype(phys), m), m
    with np.errstate(over="ignore"):
        out = a.astype(phys)
    return _zero_nulls(out, m), m


def _value_to_string(v, from_t) -> str:
    if isinstance(from_t, dt.BooleanType):
        return "true" if v else "false"
    if isinstance(from_t, dt.DecimalType):
        import decimal
        return str(decimal.Decimal(int(v)).scaleb(-from_t.scale))
    if isinstance(from_t, dt.DateType):
        import datetime
        return str(datetime.date(1970, 1, 1)
                   + datetime.timedelta(days=int(v)))
    if isinstance(from_t, dt.TimestampType):
        import datetime
        ts = (datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
              + datetime.timedelta(microseconds=int(v)))
        return ts.strftime("%Y-%m-%d %H:%M:%S") + (
            f".{ts.microsecond:06d}".rstrip("0")
            if ts.microsecond else "")
    if from_t.is_floating:
        f = float(v)
        if f != f or f in (float("inf"), float("-inf")):
            return {"inf": "Infinity", "-inf": "-Infinity"}.get(
                str(f), "NaN")
        if f == int(f) and abs(f) < 1e16:
            return f"{f:.1f}"
        return repr(f)
    return str(int(v))


# ---------------------------------------------------------------------------
# extended strings + regex (CPU side uses python re — the "CPU Spark"
# engine the TPU result is differentially tested against)
# ---------------------------------------------------------------------------

_EVALUATORS[S.Reverse] = _str_map(lambda s: s[::-1])


def _pad_eval(left: bool):
    def ev(expr, table):
        a, m = _ev(expr.children[0], table)
        tgt = expr.length
        pad = expr.pad.decode("utf-8")
        def one(s):
            if len(s) >= tgt:
                return s[:tgt]
            fill = (pad * tgt)[: tgt - len(s)]
            return fill + s if left else s + fill
        out = np.array([one(x) for x in a], dtype=object) if len(a) else \
            np.empty(0, object)
        return np.where(m, out, ""), m
    return ev


_EVALUATORS[S.Lpad] = _pad_eval(True)
_EVALUATORS[S.Rpad] = _pad_eval(False)


def _initcap(s: str) -> str:
    out = []
    prev_space = True
    for ch in s:
        if prev_space and "a" <= ch <= "z":
            out.append(ch.upper())
        elif not prev_space and "A" <= ch <= "Z":
            out.append(ch.lower())
        else:
            out.append(ch)
        prev_space = ch == " "
    return "".join(out)


_EVALUATORS[S.InitCap] = _str_map(_initcap)


@_reg(S.ConcatWs)
def _concat_ws(expr, table):
    n = table.num_rows
    schema = table.schema()
    parts = []
    for c in expr.children:
        v, m = _ev(c, table)
        t = c.data_type(schema)
        if t != dt.STRING:
            # mirror the TPU side's cast_column lowering, not python str()
            v = np.array([_value_to_string(x, t) for x in v], dtype=object)
        parts.append((v, m))
    out = []
    for i in range(n):
        vals = [p[0][i] for p in parts if p[1][i]]
        out.append(expr.sep.join(vals))
    return (np.array(out, dtype=object) if n else np.empty(0, object),
            np.ones(n, bool))


@_reg(S.StringLocate)
def _locate(expr, table):
    a, m = _ev(expr.children[0], table)
    sub = expr.substr
    start = max(expr.start - 1, 0)
    def one(s):
        if expr.start <= 0:
            return 0  # Spark: locate with start 0 is always 0
        if sub == "":
            return expr.start if expr.start <= len(s) + 1 else 0
        p = s.find(sub, start)
        return p + 1
    out = np.array([one(x) for x in a], dtype=np.int32) if len(a) else \
        np.empty(0, np.int32)
    return _zero_nulls(out, m), m


@_reg(S.StringRepeat)
def _repeat(expr, table):
    a, m = _ev(expr.children[0], table)
    out = np.array([x * expr.n for x in a], dtype=object) if len(a) else \
        np.empty(0, object)
    return np.where(m, out, ""), m


@_reg(S.StringReplace)
def _replace(expr, table):
    a, m = _ev(expr.children[0], table)
    search = expr.search.tobytes().decode("utf-8")
    repl = expr.replace.tobytes().decode("utf-8")
    out = np.array([x.replace(search, repl) for x in a], dtype=object) \
        if len(a) else np.empty(0, object)
    return np.where(m, out, ""), m


@_reg(S.StringTranslate)
def _translate(expr, table):
    a, m = _ev(expr.children[0], table)
    tbl = expr.table
    dele = expr.delete
    def one(s):
        bs = s.encode("utf-8")
        return bytes(tbl[b] for b in bs if not dele[b]).decode(
            "utf-8", errors="replace")
    out = np.array([one(x) for x in a], dtype=object) if len(a) else \
        np.empty(0, object)
    return np.where(m, out, ""), m


from ..expr import regex as RX  # noqa: E402


def _java_like_re(pattern: str):
    import re
    # Java regex classes (\d \w \s) are ASCII by default; python's are
    # Unicode — re.ASCII aligns the CPU engine with Java/Spark and the
    # byte-level TPU NFA.
    return re.compile(pattern, re.ASCII)


@_reg(RX.RLike)
def _rlike(expr, table):
    a, m = _ev(expr.children[0], table)
    prog = _java_like_re(expr.pattern)
    out = np.array([prog.search(x) is not None for x in a], dtype=bool) \
        if len(a) else np.empty(0, bool)
    return out & m, m


@_reg(RX.RegExpExtract)
def _regexp_extract(expr, table):
    a, m = _ev(expr.children[0], table)
    prog = _java_like_re(expr.pattern)
    def one(s):
        mt = prog.search(s)
        if mt is None:
            return ""
        try:
            g = mt.group(expr.group)
        except IndexError:
            return ""
        return g if g is not None else ""
    out = np.array([one(x) for x in a], dtype=object) if len(a) else \
        np.empty(0, object)
    return np.where(m, out, ""), m


def _java_replacement(repl: str):
    """Java replacement syntax -> python re template: $N / ${N} are
    group refs, backslash escapes the next char (incl. literal $)."""
    out = []
    i = 0
    while i < len(repl):
        ch = repl[i]
        if ch == "\\" and i + 1 < len(repl):
            nxt = repl[i + 1]
            out.append("\\\\" if nxt == "\\" else nxt.replace(
                "\\", "\\\\"))
            i += 2
            continue
        if ch == "$" and i + 1 < len(repl):
            j = i + 1
            if repl[j] == "{":
                k = repl.find("}", j)
                out.append("\\g<" + repl[j + 1:k] + ">")
                i = k + 1
                continue
            digits = ""
            while j < len(repl) and repl[j].isdigit():
                digits += repl[j]
                j += 1
            if digits:
                out.append("\\g<" + digits + ">")
                i = j
                continue
        out.append(ch if ch != "\\" else "\\\\")
        i += 1
    return "".join(out)


@_reg(RX.RegExpReplace)
def _regexp_replace(expr, table):
    import re
    a, m = _ev(expr.children[0], table)
    prog = _java_like_re(expr.pattern)
    repl = _java_replacement(expr.replacement)
    out = np.array([prog.sub(repl, x) for x in a], dtype=object) \
        if len(a) else np.empty(0, object)
    return np.where(m, out, ""), m


# ---------------------------------------------------------------------------
# interpreted python UDFs (udf/python_udf.py) — CPU-only row loop, the
# numpy stand-in for the reference's Arrow/Pandas worker path
# ---------------------------------------------------------------------------

def _register_python_udf():
    from ..udf.python_udf import PythonUDF
    from ..columnar.vector import _to_physical, from_physical

    @_reg(PythonUDF)
    def _python_udf(expr, table):
        n = table.num_rows
        schema = table.schema()
        children = []
        for c in expr.children:
            v, m = _ev(c, table)
            t = c.data_type(schema)
            children.append((v, m, t))
        out_t = expr.return_type
        if out_t == dt.STRING:
            out = np.full(n, "", dtype=object)
        else:
            out = np.zeros(n, np.dtype(out_t.physical))
        mask = np.zeros(n, bool)
        for i in range(n):
            args = []
            for v, m, t in children:
                if not m[i]:
                    args.append(None)
                elif t == dt.STRING:
                    args.append(v[i])
                else:
                    args.append(from_physical(v[i], t))
            try:
                r = expr.fn(*args)
            except (ZeroDivisionError, ValueError, OverflowError,
                    ArithmeticError):
                r = None  # data error -> null (non-ANSI UDF semantics);
                # programming errors (TypeError/NameError/...) propagate
            if r is None:
                continue
            mask[i] = True
            out[i] = r if out_t == dt.STRING else _to_physical(r, out_t)
        return _zero_nulls(out, mask), mask


_register_python_udf()


def _register_pandas_udf():
    from ..udf.pandas_udf import PandasUDF

    @_reg(PandasUDF)
    def _pandas_udf_eval(expr, table):
        """Vectorized CPU evaluation with the same Arrow<->pandas
        conversions the worker path uses, so fallback plans and
        ArrowEvalPythonExec agree on null/dtype behavior."""
        import pyarrow as pa

        from ..io.arrow_convert import (_chunked_to_column,
                                        dtype_to_arrow_type,
                                        host_table_to_arrow)
        from .host_table import HostColumn, HostTable
        schema = table.schema()
        cols, names = [], []
        for i, c in enumerate(expr.children):
            v, m = _ev(c, table)
            cols.append(HostColumn(v, m, c.data_type(schema)))
            names.append(f"a{i}")
        arrow = host_table_to_arrow(HostTable(cols, names))
        args = [arrow.column(i).to_pandas() for i in range(len(cols))]
        res = expr.fn(*args)
        arr = pa.chunked_array([pa.Array.from_pandas(
            res, type=dtype_to_arrow_type(expr.return_type))])
        if len(arr) != table.num_rows:
            raise ValueError(
                f"pandas UDF returned {len(arr)} rows for "
                f"{table.num_rows} input rows")
        out = _chunked_to_column(arr)
        return out.values, out.mask


_register_pandas_udf()


def _register_misc_exprs():
    from ..expr import misc as MX

    @_reg(MX.MonotonicallyIncreasingID)
    def _mono_id(expr, table):
        n = table.num_rows
        return np.arange(n, dtype=np.int64), np.ones(n, bool)

    @_reg(MX.SparkPartitionID)
    def _part_id(expr, table):
        n = table.num_rows
        return np.zeros(n, np.int32), np.ones(n, bool)

    @_reg(MX.InputFileName)
    def _input_file(expr, table):
        n = table.num_rows
        name = MX.current_input_file()[0]
        return np.full(n, name, dtype=object), np.ones(n, bool)

    @_reg(MX.InputFileBlockStart)
    def _block_start(expr, table):
        n = table.num_rows
        return np.full(n, MX.current_input_file()[1], np.int64), \
            np.ones(n, bool)

    @_reg(MX.InputFileBlockLength)
    def _block_len(expr, table):
        n = table.num_rows
        return np.full(n, MX.current_input_file()[2], np.int64), \
            np.ones(n, bool)

    @_reg(MX.Uuid)
    def _uuid(expr, table):
        import uuid
        n = table.num_rows
        return np.array([str(uuid.uuid4()) for _ in range(n)],
                        dtype=object), np.ones(n, bool)

    @_reg(MX.RaiseError)
    def _raise(expr, table):
        if table.num_rows > 0:
            raise MX.RaiseErrorException(expr.message)
        return np.array([], dtype=object), np.zeros(0, bool)

    @_reg(MX.Version)
    def _version(expr, table):
        from .. import __version__
        n = table.num_rows
        return np.full(n, f"spark_rapids_tpu {__version__}",
                       dtype=object), np.ones(n, bool)


_register_misc_exprs()


def _register_bloom():
    from ..expr.hashing import BloomFilterMightContain

    @_reg(BloomFilterMightContain)
    def _might_contain(expr, table):
        # the probe hash chain is jnp math; run the device kernel over a
        # host-built column so CPU fallback and device agree bit-exactly
        import jax.numpy as jnp

        from ..columnar.vector import column_from_numpy
        from ..ops import bloom as B
        schema = table.schema()
        v, m = _ev(expr.children[0], table)
        n = table.num_rows
        c = column_from_numpy(np.asarray(v), max(n, 1),
                              dtype=expr.children[0].data_type(schema),
                              mask=m)
        hit = np.asarray(B.might_contain(jnp.asarray(expr.bits), [c]))[:n]
        return hit, m.copy()


_register_bloom()


def _register_device_identical():
    """Expressions whose semantics ARE a deterministic jnp chain (hash
    functions, date truncation): the CPU engine evaluates the device
    kernel over host-built columns, so fallback is bit-identical and
    there is no second implementation to drift."""
    from ..columnar.vector import ColumnarBatch, column_from_numpy
    from ..expr.datetime import TruncDate
    from ..expr.hashing import Murmur3Hash, XxHash64

    def _device_eval(expr, table):
        import copy
        schema = table.schema()
        n = table.num_rows
        cap = max(n, 1)
        cols, names = [], []
        for i, c in enumerate(expr.children):
            v, m = _ev(c, table)
            cols.append(column_from_numpy(np.asarray(v), cap,
                                          dtype=c.data_type(schema),
                                          mask=m))
            names.append(f"a{i}")
        batch = ColumnarBatch(cols, names, n)
        # rebind child refs positionally so expr.eval sees our columns
        clone = copy.copy(expr)
        clone.children = [E.col(f"a{i}")
                          for i in range(len(expr.children))]
        out = clone.eval(batch)
        vals = np.asarray(out.data)[:n]
        mask = np.asarray(out.validity)[:n]
        return vals, mask

    for cls in (Murmur3Hash, XxHash64, TruncDate):
        _EVALUATORS[cls] = _device_eval


_register_device_identical()


# ---------------------------------------------------------------------------
# bitwise
# ---------------------------------------------------------------------------

from ..expr import bitwise as B  # noqa: E402


def _bitwise_binary(np_op):
    def ev(expr, table):
        out_t = expr.data_type(table.schema())
        phys = np.dtype(out_t.physical)
        a, am = _ev(expr.children[0], table)
        b, bm = _ev(expr.children[1], table)
        m = am & bm
        out = np_op(a.astype(phys), b.astype(phys))
        return _zero_nulls(out, m), m
    return ev


_EVALUATORS[B.BitwiseAnd] = _bitwise_binary(np.bitwise_and)
_EVALUATORS[B.BitwiseOr] = _bitwise_binary(np.bitwise_or)
_EVALUATORS[B.BitwiseXor] = _bitwise_binary(np.bitwise_xor)


@_reg(B.BitwiseNot)
def _bitwise_not(expr, table):
    a, m = _ev(expr.children[0], table)
    return _zero_nulls(~a, m), m


def _shift_eval(kind):
    def ev(expr, table):
        a, am = _ev(expr.children[0], table)
        b, bm = _ev(expr.children[1], table)
        m = am & bm
        width = 64 if a.dtype == np.int64 else 32
        n = b.astype(np.int64) & (width - 1)
        x = a.astype(np.int64) if width == 64 else a.astype(np.int32)
        if kind == "left":
            out = x << n.astype(x.dtype)
        elif kind == "right":
            out = x >> n.astype(x.dtype)
        else:  # unsigned right
            ux = x.astype(np.uint64 if width == 64 else np.uint32)
            out = (ux >> n.astype(ux.dtype)).astype(x.dtype)
        return _zero_nulls(out, m), m
    return ev


_EVALUATORS[B.ShiftLeft] = _shift_eval("left")
_EVALUATORS[B.ShiftRight] = _shift_eval("right")
_EVALUATORS[B.ShiftRightUnsigned] = _shift_eval("uright")


@_reg(B.BitCount)
def _bitcount(expr, table):
    a, m = _ev(expr.children[0], table)
    if a.dtype == np.bool_:
        return _zero_nulls(a.astype(np.int32), m), m
    u = a.astype(np.uint64 if a.dtype == np.int64 else np.uint32)
    out = np.array([bin(int(v)).count("1") for v in u], np.int32) \
        if len(u) else np.empty(0, np.int32)
    return _zero_nulls(out, m), m


@_reg(B.InterleaveBits)
def _interleave(expr, table):
    k = len(expr.children)
    bits_per = 63 // k
    parts = []
    mask = np.ones(table.num_rows, bool)
    schema = table.schema()
    for c in expr.children:
        v, m = _ev(c, table)
        mask &= m
        width = 64 if c.data_type(schema) == dt.INT64 else 32
        x = v.astype(np.int64)
        if width == 64:
            u = (x.astype(np.uint64) ^ np.uint64(1 << 63)).astype(np.int64)
        else:
            u = x + np.int64(1 << 31)
        parts.append((u >> (width - bits_per)) &
                     np.int64((1 << bits_per) - 1))
    out = np.zeros(table.num_rows, np.int64)
    for bit in range(bits_per):
        for ci, p in enumerate(parts):
            out |= ((p >> bit) & 1) << (bit * k + ci)
    return _zero_nulls(out, mask), mask


# ---------------------------------------------------------------------------
# collections (arrays/structs) — host lists/dicts of LOGICAL values
# (collectionOperations.scala / complexTypeExtractors.scala oracle)
# ---------------------------------------------------------------------------

def _obj_array(items):
    out = np.empty(len(items), dtype=object)
    for i, v in enumerate(items):
        out[i] = v
    return out


def _logical_of(col_values, col_mask, i, t: dt.DType):
    from ..columnar.vector import from_physical
    if not col_mask[i]:
        return None
    if t == dt.STRING or t.is_nested:
        return col_values[i]
    return from_physical(col_values[i], t)


def _physical_scalar(v, t: dt.DType):
    from ..columnar.vector import _to_physical
    if v is None:
        return 0
    if t == dt.STRING or t.is_nested:
        return v
    return _to_physical(v, t)


def _register_collections():
    from ..expr import collections as CX

    @_reg(CX.CreateArray)
    def _create_array(expr, table):
        schema = table.schema()
        kids = [evaluate(c, table) for c in expr.children]
        types = [c.data_type(schema) for c in expr.children]
        n = table.num_rows
        out = _obj_array([
            [_logical_of(k.values, k.mask, i, t)
             for k, t in zip(kids, types)]
            for i in range(n)])
        return out, np.ones(n, bool)

    @_reg(CX.Size)
    def _size(expr, table):
        v, m = _ev(expr.children[0], table)
        out = np.array([len(v[i]) if m[i] else 0 for i in range(len(v))],
                       dtype=np.int32)
        return out, m.copy()

    def _item(expr, table, one_based):
        schema = table.schema()
        et = expr.data_type(schema)
        arr, am = _ev(expr.children[0], table)
        idx, im = _ev(expr.children[1], table)
        n = len(arr)
        vals, mask = [], np.zeros(n, bool)
        for i in range(n):
            v = None
            if am[i] and im[i]:
                k = int(idx[i])
                lst = arr[i]
                if one_based:
                    k = k - 1 if k > 0 else len(lst) + k
                    if int(idx[i]) == 0:
                        k = -10**9
                if 0 <= k < len(lst):
                    v = lst[k]
            mask[i] = v is not None
            vals.append(_physical_scalar(v, et))
        if et == dt.STRING or et.is_nested:
            return _obj_array(vals), mask
        return np.array(vals, dtype=np.dtype(et.physical)), mask

    _EVALUATORS[CX.GetArrayItem] = \
        lambda e, t: _item(e, t, one_based=False)
    _EVALUATORS[CX.ElementAt] = lambda e, t: _item(e, t, one_based=True)

    @_reg(CX.ArrayContains)
    def _contains(expr, table):
        schema = table.schema()
        et = expr.children[0].data_type(schema).element_type
        arr, am = _ev(expr.children[0], table)
        needle = evaluate(expr.children[1], table)
        n = len(arr)
        out = np.zeros(n, bool)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not (am[i] and needle.mask[i]):
                continue
            want = _logical_of(needle.values, needle.mask, i,
                              expr.children[1].data_type(schema))
            found = any(e is not None and e == want for e in arr[i])
            has_null = any(e is None for e in arr[i])
            out[i] = found
            mask[i] = found or not has_null
        return out, mask

    def _extreme(expr, table, fn):
        schema = table.schema()
        et = expr.data_type(schema)
        arr, am = _ev(expr.children[0], table)
        n = len(arr)
        vals, mask = [], np.zeros(n, bool)
        for i in range(n):
            v = None
            if am[i]:
                elems = [e for e in arr[i] if e is not None]
                if elems:
                    v = fn(elems)
            mask[i] = v is not None
            vals.append(_physical_scalar(v, et))
        if et == dt.STRING or et.is_nested:
            return _obj_array(vals), mask
        return np.array(vals, dtype=np.dtype(et.physical)), mask

    _EVALUATORS[CX.ArrayMin] = lambda e, t: _extreme(e, t, min)
    _EVALUATORS[CX.ArrayMax] = lambda e, t: _extreme(e, t, max)

    @_reg(CX.SortArray)
    def _sort_array(expr, table):
        arr, am = _ev(expr.children[0], table)
        n = len(arr)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not am[i]:
                out[i] = None
                continue
            non_null = sorted([e for e in arr[i] if e is not None],
                              reverse=not expr.ascending)
            nulls = [None] * (len(arr[i]) - len(non_null))
            out[i] = (nulls + non_null) if expr.ascending \
                else (non_null + nulls)
        return out, am.copy()

    def _list2(expr, table):
        av, am = _ev(expr.children[0], table)
        bv, bm = _ev(expr.children[1], table)
        return av, am, bv, bm

    def _dedup_first(items):
        seen, out = [], []
        for e in items:
            if e not in seen:
                seen.append(e)
                out.append(e)
        return out

    @_reg(CX.ArrayDistinct)
    def _distinct(expr, table):
        v, m = _ev(expr.children[0], table)
        out = _obj_array([_dedup_first(v[i]) if m[i] else None
                          for i in range(len(v))])
        return out, m.copy()

    @_reg(CX.ArrayUnion)
    def _union(expr, table):
        av, am, bv, bm = _list2(expr, table)
        n = len(av)
        out = np.empty(n, dtype=object)
        mask = am & bm
        for i in range(n):
            out[i] = _dedup_first(list(av[i]) + list(bv[i])) \
                if mask[i] else None
        return out, mask

    @_reg(CX.ArrayIntersect)
    def _intersect(expr, table):
        av, am, bv, bm = _list2(expr, table)
        n = len(av)
        out = np.empty(n, dtype=object)
        mask = am & bm
        for i in range(n):
            out[i] = _dedup_first([e for e in av[i] if e in bv[i]]) \
                if mask[i] else None
        return out, mask

    @_reg(CX.ArrayExcept)
    def _except(expr, table):
        av, am, bv, bm = _list2(expr, table)
        n = len(av)
        out = np.empty(n, dtype=object)
        mask = am & bm
        for i in range(n):
            out[i] = _dedup_first([e for e in av[i]
                                   if e not in bv[i]]) \
                if mask[i] else None
        return out, mask

    @_reg(CX.ArraysOverlap)
    def _overlap(expr, table):
        av, am, bv, bm = _list2(expr, table)
        n = len(av)
        out = np.zeros(n, bool)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not (am[i] and bm[i]):
                continue
            hit = any(e is not None and e in bv[i] for e in av[i])
            nullish = bool(av[i]) and bool(bv[i]) and \
                (None in av[i] or None in bv[i])
            out[i] = hit
            mask[i] = hit or not nullish
        return out, mask

    @_reg(CX.ArrayRemove)
    def _remove(expr, table):
        schema = table.schema()
        et = expr.children[0].data_type(schema).element_type
        av, am = _ev(expr.children[0], table)
        vc = evaluate(expr.children[1], table)
        n = len(av)
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if am[i] and vc.mask[i]:
                want = _logical_of(vc.values, vc.mask, i, et)
                out[i] = [e for e in av[i]
                          if e is None or e != want]
                mask[i] = True
            else:
                out[i] = None
        return out, mask

    @_reg(CX.ArrayPosition)
    def _position(expr, table):
        schema = table.schema()
        et = expr.children[0].data_type(schema).element_type
        av, am = _ev(expr.children[0], table)
        vc = evaluate(expr.children[1], table)
        n = len(av)
        out = np.zeros(n, np.int64)
        mask = np.zeros(n, bool)
        for i in range(n):
            if am[i] and vc.mask[i]:
                want = _logical_of(vc.values, vc.mask, i, et)
                mask[i] = True
                for k, e in enumerate(av[i]):
                    if e is not None and e == want:
                        out[i] = k + 1
                        break
        return out, mask

    @_reg(CX.Slice)
    def _slice(expr, table):
        av, am = _ev(expr.children[0], table)
        sc = evaluate(expr.children[1], table)
        nc = evaluate(expr.children[2], table)
        n = len(av)
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not (am[i] and sc.mask[i] and nc.mask[i]):
                out[i] = None
                continue
            s, ln = int(sc.values[i]), int(nc.values[i])
            if s == 0 or ln < 0:
                out[i] = None
                continue
            z = s - 1 if s > 0 else len(av[i]) + s
            # window [z, z+ln) intersected with the valid index range
            out[i] = list(av[i][max(z, 0):max(z + ln, 0)])
            mask[i] = True
        return out, mask

    @_reg(CX.ArrayReverse)
    def _arr_reverse(expr, table):
        v, m = _ev(expr.children[0], table)
        out = _obj_array([list(reversed(v[i])) if m[i] else None
                          for i in range(len(v))])
        return out, m.copy()

    @_reg(CX.ArrayRepeat)
    def _repeat(expr, table):
        schema = table.schema()
        et = expr.children[0].data_type(schema)
        vc = evaluate(expr.children[0], table)
        nc = evaluate(expr.children[1], table)
        n = table.num_rows
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not nc.mask[i]:
                out[i] = None
                continue
            k = max(int(nc.values[i]), 0)
            e = _logical_of(vc.values, vc.mask, i, et)
            out[i] = [e] * k
            mask[i] = True
        return out, mask

    @_reg(CX.Flatten)
    def _flatten(expr, table):
        v, m = _ev(expr.children[0], table)
        n = len(v)
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not m[i] or any(e is None for e in v[i]):
                out[i] = None  # null inner array -> null (Spark)
                continue
            out[i] = [x for inner in v[i] for x in inner]
            mask[i] = True
        return out, mask

    @_reg(CX.ArraysZip)
    def _arrays_zip(expr, table):
        cols = [_ev(c, table) for c in expr.children]
        n = len(cols[0][0])
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not all(m[i] for _, m in cols):
                out[i] = None
                continue
            ln = max((len(v[i]) for v, _ in cols), default=0)
            out[i] = [
                {str(j): (v[i][k] if k < len(v[i]) else None)
                 for j, (v, _) in enumerate(cols)}
                for k in range(ln)]
            mask[i] = True
        return out, mask

    @_reg(CX.ArrayJoin)
    def _array_join(expr, table):
        v, m = _ev(expr.children[0], table)
        n = len(v)
        out = np.empty(n, dtype=object)
        for i in range(n):
            if not m[i]:
                out[i] = ""
                continue
            parts = [e if e is not None else expr.null_replacement
                     for e in v[i]]
            out[i] = expr.sep.join(p for p in parts if p is not None)
        return out, m.copy()

    @_reg(CX.ZipWith)
    def _zip_with(expr, table):
        schema = table.schema()
        expr.data_type(schema)  # bind lambda var dtypes
        av, am = _ev(expr.children[0], table)
        bv, bm = _ev(expr.children[1], table)
        body = expr.children[2]
        xt, yt = expr.x_var._dtype, expr.y_var._dtype
        from ..expr import higher_order as HO
        from .host_table import HostColumn, HostTable
        n = len(av)
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        lens = np.array([max(len(av[i]), len(bv[i]))
                         if am[i] and bm[i] else 0
                         for i in range(n)], dtype=np.int64)
        xs, ys = [], []
        for i in range(n):
            for k in range(lens[i]):
                xs.append(av[i][k] if k < len(av[i]) else None)
                ys.append(bv[i][k] if k < len(bv[i]) else None)

        def pc(vals, t):
            mk = np.array([v is not None for v in vals], bool)
            ph = [_physical_scalar(v, t) for v in vals]
            if t == dt.STRING or t.is_nested:
                return HostColumn(_obj_array(ph), mk, t)
            return HostColumn(np.array(ph, dtype=np.dtype(t.physical)),
                              mk, t)
        flat = HostTable([pc(xs, xt), pc(ys, yt)],
                         [expr.x_var.name, expr.y_var.name])
        res = evaluate(body, flat)
        rt = body.data_type(flat.schema())
        vals = [_logical_of(res.values, res.mask, i, rt)
                for i in range(len(res.values))]
        pos = 0
        for i in range(n):
            if am[i] and bm[i]:
                out[i] = vals[pos:pos + lens[i]]
                pos += lens[i]
                mask[i] = True
            else:
                out[i] = None
        return out, mask

    @_reg(CX.MapConcat)
    def _map_concat(expr, table):
        cols = [_ev(c, table) for c in expr.children]
        n = len(cols[0][0])
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if not all(m[i] for _, m in cols):
                out[i] = None
                continue
            merged = {}
            for v, _ in cols:
                merged.update(v[i])  # last map wins duplicates
            out[i] = merged
            mask[i] = True
        return out, mask

    @_reg(CX.CreateNamedStruct)
    def _named_struct(expr, table):
        schema = table.schema()
        kids = [evaluate(c, table) for c in expr.children]
        types = [c.data_type(schema) for c in expr.children]
        n = table.num_rows
        out = _obj_array([
            {fn: _logical_of(k.values, k.mask, i, t)
             for fn, k, t in zip(expr.names, kids, types)}
            for i in range(n)])
        return out, np.ones(n, bool)

    @_reg(CX.GetStructField)
    def _get_field(expr, table):
        schema = table.schema()
        et = expr.data_type(schema)
        sv, sm = _ev(expr.children[0], table)
        n = len(sv)
        vals, mask = [], np.zeros(n, bool)
        for i in range(n):
            v = sv[i].get(expr.field) if sm[i] else None
            mask[i] = v is not None
            vals.append(_physical_scalar(v, et))
        if et == dt.STRING or et.is_nested:
            return _obj_array(vals), mask
        return np.array(vals, dtype=np.dtype(et.physical)), mask


_register_collections()


def _register_higher_order():
    """CPU oracle for lambda expressions (higherOrderFunctions.scala
    surface): lambda bodies evaluate over a FLAT element-level table —
    one row per element, lambda-var columns plus outer columns repeated
    per element — then results regroup by the original list lengths.
    The same lowering shape as the device path, at numpy speed."""
    from ..expr import higher_order as HO
    from .host_table import HostColumn, HostTable

    def _phys_col(values, t: dt.DType) -> HostColumn:
        mask = np.array([v is not None for v in values], dtype=bool)
        phys = [_physical_scalar(v, t) for v in values]
        if t == dt.STRING or t.is_nested or \
                (isinstance(t, dt.DecimalType) and t.is_wide):
            return HostColumn(_obj_array(phys), mask, t)
        return HostColumn(np.array(phys, dtype=np.dtype(t.physical)),
                          mask, t)

    def _flat_eval(body, table, lens, bindings):
        """bindings: [(name, logical-values list, dtype)]; returns
        logical results, one per element."""
        cols, names = [], []
        for name, vals, t in bindings:
            names.append(name)
            cols.append(_phys_col(vals, t))
        outer = HO._outer_refs(body, [])  # all free ColumnRefs in body
        outer -= set(names)
        for cname in outer:
            src = table.column(cname)
            rep_m = np.repeat(src.mask, lens)
            rep_vals = np.repeat(src.values, lens)
            names.append(cname)
            cols.append(HostColumn(rep_vals, rep_m, src.dtype))
        flat = HostTable(cols, names)
        out = evaluate(body, flat)
        rt = body.data_type(flat.schema())
        return [(_logical_of(out.values, out.mask, i, rt))
                for i in range(len(out.values))]

    def _elements_of(arr, am):
        lens = np.array([len(arr[i]) if am[i] else 0
                         for i in range(len(arr))], dtype=np.int64)
        flat = []
        for i in range(len(arr)):
            if am[i]:
                flat.extend(arr[i])
        return lens, flat

    @_reg(HO.LambdaVariable)
    def _lambda_var(expr, table):
        c = table.column(expr.name)
        return c.values, c.mask

    @_reg(HO.ArrayTransform)
    def _transform(expr, table):
        expr.data_type(table.schema())  # bind lambda var dtypes
        arr, am = _ev(expr.children[0], table)
        lens, flat = _elements_of(arr, am)
        binds = [(expr.var.name, flat, expr.var._dtype)]
        if expr.idx_var is not None:
            idx = [k for n in lens for k in range(n)]
            binds.append((expr.idx_var.name, idx, dt.INT32))
        res = _flat_eval(expr.children[1], table, lens, binds)
        out = np.empty(len(arr), dtype=object)
        pos = 0
        for i in range(len(arr)):
            if am[i]:
                out[i] = res[pos:pos + lens[i]]
                pos += lens[i]
            else:
                out[i] = None
        return out, am.copy()

    def _pred_rows(expr, table):
        expr.data_type(table.schema())
        arr, am = _ev(expr.children[0], table)
        lens, flat = _elements_of(arr, am)
        binds = [(expr.var.name, flat, expr.var._dtype)]
        res = _flat_eval(expr.children[1], table, lens, binds)
        return arr, am, lens, res

    @_reg(HO.ArrayExists)
    def _exists(expr, table):
        arr, am, lens, res = _pred_rows(expr, table)
        n = len(arr)
        out = np.zeros(n, bool)
        mask = np.zeros(n, bool)
        pos = 0
        for i in range(n):
            if not am[i]:
                continue
            window = res[pos:pos + lens[i]]
            pos += lens[i]
            any_true = any(v is True for v in window)
            any_null = any(v is None for v in window)
            out[i] = any_true
            mask[i] = any_true or not any_null
        return out, mask

    @_reg(HO.ArrayForAll)
    def _forall(expr, table):
        arr, am, lens, res = _pred_rows(expr, table)
        n = len(arr)
        out = np.zeros(n, bool)
        mask = np.zeros(n, bool)
        pos = 0
        for i in range(n):
            if not am[i]:
                continue
            window = res[pos:pos + lens[i]]
            pos += lens[i]
            any_false = any(v is False for v in window)
            any_null = any(v is None for v in window)
            out[i] = not any_false
            mask[i] = any_false or not any_null
        return out, mask

    @_reg(HO.ArrayFilter)
    def _filter(expr, table):
        arr, am, lens, res = _pred_rows(expr, table)
        n = len(arr)
        out = np.empty(n, dtype=object)
        pos = 0
        for i in range(n):
            if not am[i]:
                out[i] = None
                continue
            window = res[pos:pos + lens[i]]
            pos += lens[i]
            out[i] = [e for e, keep in zip(arr[i], window)
                      if keep is True]
        return out, am.copy()

    @_reg(HO.ArrayAggregate)
    def _aggregate(expr, table):
        schema = table.schema()
        rt = expr.data_type(schema)
        arr, am = _ev(expr.children[0], table)
        zero = evaluate(expr.children[1], table)
        zt = expr.children[1].data_type(schema)
        acc_t = expr.acc_var._dtype or zt
        et = expr.elem_var._dtype
        n = len(arr)
        merge = expr.children[2]
        finish = expr.children[3] if expr.has_finish else None
        vals, mask = [], np.zeros(n, bool)
        for i in range(n):
            if not am[i]:
                vals.append(_physical_scalar(None, rt))
                continue
            acc = _logical_of(zero.values, zero.mask, i, zt)
            for x in arr[i]:
                one = HostTable(
                    [_phys_col([acc], acc_t), _phys_col([x], et)],
                    [expr.acc_var.name, expr.elem_var.name])
                r = evaluate(merge, one)
                acc = _logical_of(r.values, r.mask, 0, acc_t)
            if finish is not None:
                one = HostTable([_phys_col([acc], acc_t)],
                                [expr.acc_var.name])
                r = evaluate(finish, one)
                acc = _logical_of(r.values, r.mask, 0, rt)
            mask[i] = acc is not None
            vals.append(_physical_scalar(acc, rt))
        if rt == dt.STRING or rt.is_nested:
            return _obj_array(vals), mask
        return np.array(vals, dtype=np.dtype(rt.physical)), mask

    # --- maps (logical value = dict) ---

    @_reg(HO.MapKeys)
    def _map_keys(expr, table):
        mv, mm = _ev(expr.children[0], table)
        out = _obj_array([list(mv[i].keys()) if mm[i] else None
                          for i in range(len(mv))])
        return out, mm.copy()

    @_reg(HO.MapValues)
    def _map_values(expr, table):
        mv, mm = _ev(expr.children[0], table)
        out = _obj_array([list(mv[i].values()) if mm[i] else None
                          for i in range(len(mv))])
        return out, mm.copy()

    @_reg(HO.MapEntries)
    def _map_entries(expr, table):
        mv, mm = _ev(expr.children[0], table)
        out = _obj_array([
            [{"key": k, "value": v} for k, v in mv[i].items()]
            if mm[i] else None for i in range(len(mv))])
        return out, mm.copy()

    @_reg(HO.GetMapValue)
    def _get_map_value(expr, table):
        schema = table.schema()
        vt = expr.data_type(schema)
        kt = expr.children[1].data_type(schema)
        mv, mm = _ev(expr.children[0], table)
        kc = evaluate(expr.children[1], table)
        n = len(mv)
        vals, mask = [], np.zeros(n, bool)
        for i in range(n):
            v = None
            if mm[i] and kc.mask[i]:
                key = _logical_of(kc.values, kc.mask, i, kt)
                v = mv[i].get(key)
            mask[i] = v is not None
            vals.append(_physical_scalar(v, vt))
        if vt == dt.STRING or vt.is_nested:
            return _obj_array(vals), mask
        return np.array(vals, dtype=np.dtype(vt.physical)), mask

    @_reg(HO.MapContainsKey)
    def _map_contains(expr, table):
        schema = table.schema()
        kt = expr.children[1].data_type(schema)
        mv, mm = _ev(expr.children[0], table)
        kc = evaluate(expr.children[1], table)
        n = len(mv)
        out = np.zeros(n, bool)
        mask = np.zeros(n, bool)
        for i in range(n):
            if mm[i] and kc.mask[i]:
                key = _logical_of(kc.values, kc.mask, i, kt)
                out[i] = key in mv[i]
                mask[i] = True
        return out, mask

    def _map_lambda(expr, table, fn):
        expr.data_type(table.schema())  # bind var dtypes
        mv, mm = _ev(expr.children[0], table)
        n = len(mv)
        keys = [k for i in range(n) if mm[i] for k in mv[i].keys()]
        vals = [v for i in range(n) if mm[i] for v in mv[i].values()]
        lens = np.array([len(mv[i]) if mm[i] else 0 for i in range(n)],
                        dtype=np.int64)
        binds = [(expr.key_var.name, keys, expr.key_var._dtype),
                 (expr.val_var.name, vals, expr.val_var._dtype)]
        res = _flat_eval(expr.children[1], table, lens, binds)
        out = np.empty(n, dtype=object)
        pos = 0
        for i in range(n):
            if not mm[i]:
                out[i] = None
                continue
            window = res[pos:pos + lens[i]]
            pos += lens[i]
            out[i] = fn(mv[i], window)
        return out, mm.copy()

    @_reg(HO.TransformValues)
    def _transform_values(expr, table):
        return _map_lambda(
            expr, table,
            lambda m, rs: dict(zip(m.keys(), rs)))

    @_reg(HO.TransformKeys)
    def _transform_keys(expr, table):
        return _map_lambda(
            expr, table,
            lambda m, rs: dict(zip(rs, m.values())))

    @_reg(HO.MapFilter)
    def _map_filter(expr, table):
        return _map_lambda(
            expr, table,
            lambda m, rs: {k: v for (k, v), keep in zip(m.items(), rs)
                           if keep is True})

    @_reg(HO.CreateMap)
    def _create_map(expr, table):
        schema = table.schema()
        mt = expr.data_type(schema)
        n = table.num_rows
        keys = [evaluate(c, table) for c in expr.children[0::2]]
        vals = [evaluate(c, table) for c in expr.children[1::2]]
        out = _obj_array([
            {_logical_of(k.values, k.mask, i, mt.key_type):
             _logical_of(v.values, v.mask, i, mt.value_type)
             for k, v in zip(keys, vals)
             if k.mask[i]}
            for i in range(n)])
        return out, np.ones(n, bool)

    @_reg(HO.MapFromArrays)
    def _map_from_arrays(expr, table):
        kv, km = _ev(expr.children[0], table)
        vv, vm = _ev(expr.children[1], table)
        n = len(kv)
        out = np.empty(n, dtype=object)
        mask = np.zeros(n, bool)
        for i in range(n):
            if km[i] and vm[i] and len(kv[i]) == len(vv[i]):
                out[i] = dict(zip(kv[i], vv[i]))
                mask[i] = True
            else:
                out[i] = None
        return out, mask


_register_higher_order()
