"""Physical-plan cache: structural keys for logical plans.

Every ``collect()`` used to re-run apply_overrides and build fresh exec
instances, so each exec's ``jax.jit`` wrappers were new objects and the
in-memory pjit cache never carried across collects — a warm TPC-H query
spent more wall-clock re-tracing jaxprs than computing (the persistent
XLA compile cache only removes the *compile*, not the trace). The
reference has no analogue because Spark caches compiled RDD DAGs per
Dataset; here the session memoizes ``logical plan -> physical plan`` on
a STRUCTURAL key so re-built-but-identical DataFrames (bench loops, SQL
re-parses) reuse the exec tree and its traced jits.

Key rules (conservative by construction):
- encodes node/expression class names + full ``__dict__`` contents
  recursively; children positionally,
- file scans fold in (path, mtime, size) per file so data edits
  invalidate,
- ANY value the encoder does not recognize raises Uncachable and the
  query simply runs uncached (never a wrong reuse: unknown values can
  not silently alias),
- re-execution of a cached tree calls ``reset_for_rerun`` on every exec
  so one-shot state (shuffle writes, broadcast materialization) is
  rebuilt.
"""

from __future__ import annotations

import datetime
import decimal
import os

from ..columnar import dtypes as dt


class Uncachable(Exception):
    """Plan contains state the structural key cannot encode safely."""


_PRIMS = (str, int, float, bool, bytes, type(None), complex,
          datetime.date, datetime.datetime, datetime.timedelta,
          decimal.Decimal)

_MAX_ITEMS = 4096  # bail on huge embedded literals (LocalRelation data)


def _enc(v, depth: int = 0):
    if depth > 64:
        raise Uncachable("nesting too deep")
    if isinstance(v, _PRIMS):
        return (type(v).__name__, repr(v))
    if isinstance(v, dt.DType):
        return ("dtype", type(v).__name__,
                tuple(sorted((k, _enc(x, depth + 1))
                             for k, x in vars(v).items())))
    if isinstance(v, (list, tuple)):
        if len(v) > _MAX_ITEMS:
            raise Uncachable("sequence too large")
        return (type(v).__name__,) + tuple(_enc(x, depth + 1) for x in v)
    if isinstance(v, dict):
        if len(v) > _MAX_ITEMS:
            raise Uncachable("dict too large")
        return ("dict",) + tuple(
            sorted((_enc(k, depth + 1), _enc(x, depth + 1))
                   for k, x in v.items()))
    if isinstance(v, (set, frozenset)):
        if len(v) > _MAX_ITEMS:
            raise Uncachable("set too large")
        return ("set",) + tuple(sorted(_enc(x, depth + 1) for x in v))
    from ..exec.sort import SortOrder
    from ..expr.core import Expression
    from ..expr.window import WindowFrame, WindowSpec
    from .logical import LogicalPlan, SortField
    if isinstance(v, (LogicalPlan, Expression, SortField, SortOrder,
                      WindowSpec, WindowFrame)):
        return _enc_node(v, depth + 1)
    raise Uncachable(f"unencodable {type(v).__name__}")


def _enc_node(node, depth: int):
    from .logical import LogicalPlan
    items = []
    for k, val in sorted(vars(node).items()):
        if k == "children":
            continue
        items.append((k, _enc(val, depth)))
    key = (type(node).__module__, type(node).__name__, tuple(items),
           tuple(_enc(c, depth) for c in getattr(node, "children", ())))
    if isinstance(node, LogicalPlan) and hasattr(node, "paths"):
        # file scan: fold file identity in so on-disk edits invalidate
        stats = []
        for p in node.paths:
            try:
                st = os.stat(p)
                stats.append((p, int(st.st_mtime_ns), st.st_size))
            except OSError:
                raise Uncachable("unstatable scan path")
        key = key + (tuple(stats),)
    return key


def plan_cache_key(plan, conf):
    """Hashable structural key for (logical plan, conf), or None when
    the plan is not safely cachable."""
    try:
        conf_key = tuple(sorted(
            (k, _enc(v)) for k, v in conf._settings.items()))
        return (_enc(plan), conf_key)
    except Uncachable:
        return None
    except Exception:
        return None


class PhysicalPlanCache:
    """Small FIFO memo of structural key -> physical plan.

    Cached exec trees hold one-shot execution state (shuffle ids,
    write flags, metrics), so an entry may be EXECUTING on at most one
    thread at a time. Serial callers reuse via ``reset_for_rerun``;
    concurrent callers (the serving front door runs many sessions over
    one shared cache) take an execution *lease* — if the entry's lease
    is already held, the caller plans a fresh tree instead of racing
    on shared instances."""

    def __init__(self, max_entries: int = 32):
        import threading
        self.max_entries = max_entries
        self._entries: dict = {}
        self._leases: dict = {}
        self._mu = threading.Lock()
        # lifetime counters, reported as hit rates by the serving
        # bench (tools/serve_bench.py) alongside the jit-registry's
        self.hits = 0
        self.misses = 0
        self.busy_bypasses = 0

    def get(self, key):
        with self._mu:
            p = self._entries.get(key)
            if p is None:
                self.misses += 1
            else:
                self.hits += 1
            return p

    def lease(self, key):
        """(physical, release_fn) with the execution lease held, or
        (None, None). A busy entry — mid-execution on another thread —
        counts as a miss (the caller replans uncached)."""
        with self._mu:
            p = self._entries.get(key)
            if p is None:
                self.misses += 1
                return None, None
            lock = self._leases.get(key)
        if lock is not None and not lock.acquire(blocking=False):
            with self._mu:
                self.misses += 1
                self.busy_bypasses += 1
            return None, None
        with self._mu:
            self.hits += 1
        return p, (lock.release if lock is not None else None)

    def stats(self) -> dict:
        with self._mu:
            return {"hits": self.hits, "misses": self.misses,
                    "busy_bypasses": self.busy_bypasses,
                    "entries": len(self._entries)}

    def put(self, key, physical) -> None:
        import threading
        with self._mu:
            if key not in self._entries and \
                    len(self._entries) >= self.max_entries:
                oldest = next(iter(self._entries))
                self._entries.pop(oldest)
                self._leases.pop(oldest, None)
            self._entries[key] = physical
            self._leases[key] = threading.Lock()

    def put_leased(self, key, physical):
        """Insert with the execution lease pre-acquired: the builder
        is about to execute the very instance it cached, so no other
        thread may lease it until that run releases."""
        import threading
        lock = threading.Lock()
        lock.acquire()
        with self._mu:
            if key not in self._entries and \
                    len(self._entries) >= self.max_entries:
                oldest = next(iter(self._entries))
                self._entries.pop(oldest)
                self._leases.pop(oldest, None)
            self._entries[key] = physical
            self._leases[key] = lock
        return lock.release

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._leases.clear()
