"""Cost-based optimizer: keep tiny plans off the accelerator.

Rebuild of CostBasedOptimizer.scala (SURVEY §2.2: CpuCostModel :284 /
GpuCostModel :334). The reference estimates per-operator CPU vs GPU
cost plus row<->columnar transition overhead and re-tags sections where
the accelerator isn't worth it. Here the dominant fixed cost is XLA
compilation + host->HBM transfer, so the model is: device execution
pays off once estimated rows clear a threshold; below it, plans whose
inputs are all host-resident already (local data, tiny files) are
tagged back to the CPU engine.
"""

from __future__ import annotations

import os
from typing import Optional

from ..conf import OPTIMIZER_ENABLED, OPTIMIZER_ROW_THRESHOLD, SrtConf
from .logical import (Aggregate, Expand, Filter, Join, Limit,
                      LocalRelation, LogicalPlan, Project, Range, Sort,
                      Union, Window)
from .meta import PlanMeta

# relative per-row op weights (CostBasedOptimizer default coefficients)
_OP_WEIGHT = {
    Project: 1.0, Filter: 1.0, Limit: 0.1, Union: 0.2, Expand: 2.0,
    Sort: 4.0, Aggregate: 4.0, Join: 6.0, Window: 8.0, Range: 0.1,
    LocalRelation: 0.1,
}


def estimate_rows(plan: LogicalPlan) -> float:
    """Cardinality estimation (static, like the reference's)."""
    from ..io.scan import FileScan
    if isinstance(plan, LocalRelation):
        vals = next(iter(plan.data.values()), [])
        return float(len(vals))
    if isinstance(plan, Range):
        return float(max(0, -(-(plan.end - plan.start) // plan.step)))
    if isinstance(plan, FileScan):
        # bytes-based guess: ~64B/row parquet, ~32B/row text
        total = sum(os.path.getsize(p) for p in plan.paths
                    if os.path.exists(p))
        per_row = 64 if plan.fmt in ("parquet", "orc") else 32
        return max(total / per_row, 1.0)
    child_rows = [estimate_rows(c) for c in plan.children]
    if isinstance(plan, Filter):
        return child_rows[0] * 0.5  # default selectivity
    if isinstance(plan, Limit):
        return float(min(plan.n, child_rows[0]))
    if isinstance(plan, Aggregate):
        return max(child_rows[0] * 0.1, 1.0)
    if isinstance(plan, Join):
        return max(child_rows) if child_rows else 0.0
    if isinstance(plan, Union):
        return sum(child_rows)
    if isinstance(plan, Expand):
        return child_rows[0] * len(plan.projections)
    return child_rows[0] if child_rows else 0.0


def total_cost_rows(plan: LogicalPlan) -> float:
    """Weighted row-volume of the whole tree."""
    w = _OP_WEIGHT.get(type(plan), 1.0)
    return w * estimate_rows(plan) + sum(total_cost_rows(c)
                                         for c in plan.children)


def apply_cost_model(meta: PlanMeta, conf: SrtConf) -> None:
    """Tag the whole plan off the device when it's too small to pay for
    compile + transfer (the reference's 'force sections back to CPU')."""
    if not conf.get(OPTIMIZER_ENABLED):
        return
    threshold = conf.get(OPTIMIZER_ROW_THRESHOLD)
    cost = total_cost_rows(meta.plan)
    if cost < threshold:
        _tag_tree(meta,
                  f"cost model: estimated work {cost:.0f} rows < "
                  f"threshold {threshold} (device compile/transfer "
                  "overhead dominates)")


def _tag_tree(meta: PlanMeta, reason: str) -> None:
    meta.will_not_work_on_tpu(reason)
    for c in meta.child_plans:
        _tag_tree(c, reason)
