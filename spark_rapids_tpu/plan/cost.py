"""Cost-based optimizer: a dual CPU/device cost model over plan
sections.

Rebuild of CostBasedOptimizer.scala (SURVEY §2.2: CpuCostModel :284 /
GpuCostModel :334). The reference estimates per-operator CPU and GPU
costs plus row<->columnar transition overhead and forces plan SECTIONS
back to the CPU where the accelerator isn't worth it. The TPU model has
the same shape with different constants: the dominant device fixed cost
is XLA compilation + host->HBM transfer; per-row device throughput is
orders of magnitude higher than the interpreted CPU engine's.

Model:
- ``estimate_rows``   — static cardinality (file sizes, literals,
  default selectivities), the CostBasedOptimizer's RowCountPlanVisitor
  analogue.
- ``row_width_bytes`` — schema-derived bytes/row.
- CPU cost of a subtree  = Σ rows·width·CPU_W[op]
- device cost            = Σ rows·width·DEV_W[op]
                           + DEVICE_FIXED per op   (compile/dispatch)
                           + TRANSFER·(leaf input bytes + output bytes)
- ``apply_cost_model`` walks top-down: a subtree whose device cost
  (including the transfers its placement implies) beats CPU stays on
  the device; otherwise the NODE is tagged CPU and its children are
  reconsidered independently — so a tiny dim-table scan feeding a
  broadcast join can stay on CPU while the fact side runs on device,
  exactly the sectioning CostBasedOptimizer performs.

Everything is off unless srt.sql.optimizer.enabled is set (matching
spark.rapids.sql.optimizer.enabled's default-off posture).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ..columnar import dtypes as dt
from ..conf import OPTIMIZER_ENABLED, OPTIMIZER_ROW_THRESHOLD, SrtConf
from .logical import (Aggregate, Expand, Filter, Join, Limit,
                      LocalRelation, LogicalPlan, Project, Range, Sort,
                      Union, Window)
from .meta import PlanMeta

# per-row-byte work factors. CPU = the interpreted numpy engine
# (cpu_eval); DEV = XLA device kernels. Ratios matter, not absolutes:
# the unit is "cost of moving one byte through a projection on CPU".
_CPU_W = {
    Project: 1.0, Filter: 1.0, Limit: 0.1, Union: 0.2, Expand: 2.0,
    Sort: 12.0, Aggregate: 6.0, Join: 10.0, Window: 20.0, Range: 0.1,
    LocalRelation: 0.05,
}
_DEV_W = {
    Project: 0.02, Filter: 0.02, Limit: 0.01, Union: 0.02, Expand: 0.04,
    Sort: 0.30, Aggregate: 0.15, Join: 0.25, Window: 0.40, Range: 0.01,
    LocalRelation: 0.05,
}
#: fixed device cost per operator (compile amortization + dispatch),
#: in the same byte-cost unit (~ bytes of CPU projection work one
#: compile is worth). Dominates for small plans.
_DEVICE_FIXED = 64 * 1024
#: host<->device transfer cost per byte, relative to CPU projection
#: (PCIe/DMA streams; far cheaper than interpreted per-row CPU work)
_TRANSFER_W = 0.1


def estimate_rows(plan: LogicalPlan) -> float:
    """Cardinality estimation (static, like the reference's
    RowCountPlanVisitor)."""
    from ..io.scan import FileScan
    if isinstance(plan, LocalRelation):
        vals = next(iter(plan.data.values()), [])
        return float(len(vals))
    if isinstance(plan, Range):
        return float(max(0, -(-(plan.end - plan.start) // plan.step)))
    if isinstance(plan, FileScan):
        # bytes-based guess: ~64B/row parquet, ~32B/row text
        total = sum(os.path.getsize(p) for p in plan.paths
                    if os.path.exists(p))
        per_row = 64 if plan.fmt in ("parquet", "orc") else 32
        return max(total / per_row, 1.0)
    child_rows = [estimate_rows(c) for c in plan.children]
    if isinstance(plan, Filter):
        return child_rows[0] * 0.5  # default selectivity
    if isinstance(plan, Limit):
        return float(min(plan.n, child_rows[0]))
    if isinstance(plan, Aggregate):
        return max(child_rows[0] * 0.1, 1.0)
    if isinstance(plan, Join):
        return max(child_rows) if child_rows else 0.0
    if isinstance(plan, Union):
        return sum(child_rows)
    if isinstance(plan, Expand):
        return child_rows[0] * len(plan.projections)
    return child_rows[0] if child_rows else 0.0


def row_width_bytes(schema) -> float:
    """Estimated bytes/row of a schema (strings/nested are guesses —
    the reference costs columns the same way)."""
    total = 0.0
    for _, t in schema:
        if t == dt.STRING:
            total += 24.0
        elif t.is_nested:
            total += 64.0
        elif isinstance(t, dt.DecimalType) and t.is_wide:
            total += 16.0
        else:
            try:
                import numpy as np
                total += np.dtype(t.physical).itemsize
            except Exception:
                total += 8.0
    return max(total, 1.0)


def _subtree_costs(plan: LogicalPlan) -> Tuple[float, float, float]:
    """(cpu_cost, device_compute_cost, output_bytes) of the subtree —
    device cost EXCLUDES boundary transfers (added by the caller, which
    knows where the section boundaries land)."""
    rows = estimate_rows(plan)
    try:
        width = row_width_bytes(plan.schema)
    except Exception:
        width = 8.0
    bytes_out = rows * width
    cpu = _CPU_W.get(type(plan), 1.0) * bytes_out
    dev = _DEV_W.get(type(plan), 0.05) * bytes_out + _DEVICE_FIXED
    for c in plan.children:
        ccpu, cdev, _ = _subtree_costs(c)
        cpu += ccpu
        dev += cdev
    return cpu, dev, bytes_out


def _leaf_input_bytes(plan: LogicalPlan) -> float:
    """Bytes entering the subtree from host-resident sources (files,
    local data) — the H2D upload a device placement pays."""
    from ..io.scan import FileScan
    if isinstance(plan, (LocalRelation, FileScan)):
        rows = estimate_rows(plan)
        try:
            return rows * row_width_bytes(plan.schema)
        except Exception:
            return rows * 8.0
    return sum(_leaf_input_bytes(c) for c in plan.children)


def device_vs_cpu(plan: LogicalPlan) -> Tuple[float, float]:
    """(cpu_cost, device_cost) of running the WHOLE subtree on each
    engine, device cost including its boundary transfers."""
    cpu, dev, bytes_out = _subtree_costs(plan)
    dev += _TRANSFER_W * (_leaf_input_bytes(plan) + bytes_out)
    return cpu, dev


# floor-gate weights (round-1 heuristic, unchanged so the gate's
# behavior is stable across rounds)
_OP_WEIGHT = {
    Project: 1.0, Filter: 1.0, Limit: 0.1, Union: 0.2, Expand: 2.0,
    Sort: 4.0, Aggregate: 4.0, Join: 6.0, Window: 8.0, Range: 0.1,
    LocalRelation: 0.1,
}


def total_cost_rows(plan: LogicalPlan) -> float:
    """Weighted row-volume of the whole tree (the round-1 heuristic,
    kept as the coarse floor gate)."""
    w = _OP_WEIGHT.get(type(plan), 1.0)
    return w * estimate_rows(plan) + sum(total_cost_rows(c)
                                         for c in plan.children)


def apply_cost_model(meta: PlanMeta, conf: SrtConf) -> None:
    """Force plan sections back to the CPU engine where the dual model
    says the device doesn't pay (CostBasedOptimizer.optimize role).

    Two stages, both conservative:
    1. floor gate — the whole plan below the row threshold goes CPU
       (device compile/transfer overhead dominates tiny plans no matter
       the shape);
    2. section refinement — top-down: a node whose subtree wins on
       device is left alone; a losing node is tagged CPU and each child
       subtree is reconsidered on its own (it may still win once its
       own boundary transfers are priced)."""
    if not conf.get(OPTIMIZER_ENABLED):
        return
    threshold = conf.get(OPTIMIZER_ROW_THRESHOLD)
    cost = total_cost_rows(meta.plan)
    if cost < threshold:
        _tag_tree(meta,
                  f"cost model: estimated work {cost:.0f} rows < "
                  f"threshold {threshold} (device compile/transfer "
                  "overhead dominates)")
        return
    _refine(meta, threshold)


def _refine(meta: PlanMeta, threshold: float) -> None:
    cpu, dev = device_vs_cpu(meta.plan)
    if dev < cpu:
        return  # whole subtree stays on device
    # the dual model may only force a section back to CPU when the
    # section is ALSO small by the user's own threshold scale — above
    # it, the row-threshold contract ("big enough = device") wins, so
    # enabling the optimizer can never strand large work on the CPU
    if total_cost_rows(meta.plan) >= threshold:
        for c in meta.child_plans:
            _refine(c, threshold)
        return
    meta.will_not_work_on_tpu(
        f"cost model: CPU {cpu:.2e} < device {dev:.2e} for small "
        f"{type(meta.plan).__name__} section")
    for c in meta.child_plans:
        _refine(c, threshold)


def _tag_tree(meta: PlanMeta, reason: str) -> None:
    meta.will_not_work_on_tpu(reason)
    for c in meta.child_plans:
        _tag_tree(c, reason)
