"""Logical plan nodes.

The frontend IR that the overrides driver (overrides.py) tags and
converts — the role Catalyst's SparkPlan tree plays for the reference
(GpuOverrides.scala:4312 wrapAndTagPlan walks the physical plan; here we
walk this logical tree and emit either TpuExec or CPU fallback nodes).

Every node knows its output ``schema`` ([(name, DType), ...]) at plan
time; schema resolution errors surface when the node is built, the way
Catalyst's analyzer resolves before physical planning.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..columnar import dtypes as dt
from ..expr.aggregates import AggregateFunction
from ..expr.core import Expression, output_name

Schema = List  # [(name, DType), ...]


class LogicalPlan:
    """Base logical node; children in ``children``."""

    def __init__(self, *children: "LogicalPlan"):
        self.children: List[LogicalPlan] = list(children)

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def expressions(self) -> List[Expression]:
        """All expressions held directly by this node (for tagging)."""
        return []

    def expressions_with_schemas(self):
        """[(expr, resolution schema)] — nodes whose expressions resolve
        against different children (Join) override this."""
        schema = self.children[0].schema if self.children else self.schema
        return [(e, schema) for e in self.expressions()]

    def node_name(self) -> str:
        return type(self).__name__

    def node_description(self) -> str:
        return self.node_name()

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + "* " + self.node_description()
        return "\n".join([line] + [c.tree_string(indent + 1)
                                   for c in self.children])

    def __repr__(self):
        return self.tree_string()


class LocalRelation(LogicalPlan):
    """In-memory data: {name: [values]} with an explicit or inferred schema."""

    def __init__(self, data: dict, schema: Schema):
        super().__init__()
        self.data = data
        self._schema = list(schema)

    @property
    def schema(self) -> Schema:
        return self._schema

    def node_description(self) -> str:
        return f"LocalRelation[{', '.join(n for n, _ in self._schema)}]"


class Project(LogicalPlan):
    def __init__(self, child: LogicalPlan, exprs: Sequence[Expression]):
        super().__init__(child)
        self.exprs = list(exprs)
        in_schema = child.schema
        self._schema = [(output_name(e, i), e.data_type(in_schema))
                        for i, e in enumerate(self.exprs)]

    @property
    def schema(self) -> Schema:
        return self._schema

    def expressions(self) -> List[Expression]:
        return list(self.exprs)

    def node_description(self) -> str:
        return f"Project[{', '.join(n for n, _ in self._schema)}]"


class Filter(LogicalPlan):
    def __init__(self, child: LogicalPlan, condition: Expression):
        super().__init__(child)
        self.condition = condition
        if condition.data_type(child.schema) != dt.BOOL:
            raise TypeError(f"filter condition must be boolean, got "
                            f"{condition.data_type(child.schema)}")

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def expressions(self) -> List[Expression]:
        return [self.condition]

    def node_description(self) -> str:
        return f"Filter[{self.condition!r}]"


class Aggregate(LogicalPlan):
    """groupBy(group_exprs).agg(agg_exprs); empty group_exprs = global agg."""

    def __init__(self, child: LogicalPlan, group_exprs: Sequence[Expression],
                 agg_exprs: Sequence[Tuple[AggregateFunction, str]]):
        super().__init__(child)
        self.group_exprs = list(group_exprs)
        self.agg_exprs = list(agg_exprs)
        in_schema = child.schema
        self._schema = (
            [(output_name(e, i), e.data_type(in_schema))
             for i, e in enumerate(self.group_exprs)] +
            [(name, fn.data_type(in_schema)) for fn, name in self.agg_exprs])

    @property
    def schema(self) -> Schema:
        return self._schema

    def expressions(self) -> List[Expression]:
        return list(self.group_exprs) + [fn for fn, _ in self.agg_exprs]

    def node_description(self) -> str:
        keys = ", ".join(repr(e) for e in self.group_exprs)
        aggs = ", ".join(f"{fn.name}->{n}" for fn, n in self.agg_exprs)
        return f"Aggregate[keys=({keys}), aggs=({aggs})]"


class Join(LogicalPlan):
    """Equi-join on key expression pairs (+ optional residual condition)."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 join_type: str = "inner",
                 condition: Optional[Expression] = None):
        super().__init__(left, right)
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type
        self.condition = condition
        if len(self.left_keys) != len(self.right_keys):
            raise ValueError("left/right key counts differ")
        if join_type == "cross" and self.left_keys:
            raise ValueError("cross join takes no keys (use inner, or "
                             "cross_join with a condition)")

    @property
    def schema(self) -> Schema:
        left_s, right_s = self.children[0].schema, self.children[1].schema
        if self.join_type in ("left_semi", "left_anti"):
            return left_s
        # outer joins make the non-preserved side nullable; dtypes unchanged
        return left_s + right_s

    def expressions(self) -> List[Expression]:
        out = self.left_keys + self.right_keys
        if self.condition is not None:
            out.append(self.condition)
        return out

    def expressions_with_schemas(self):
        ls = self.children[0].schema
        rs = self.children[1].schema
        out = ([(e, ls) for e in self.left_keys] +
               [(e, rs) for e in self.right_keys])
        if self.condition is not None:
            out.append((self.condition, ls + rs))
        return out

    def node_description(self) -> str:
        keys = ", ".join(f"{l!r}={r!r}" for l, r in
                         zip(self.left_keys, self.right_keys))
        return f"Join[{self.join_type}, {keys}]"


class SortField:
    """(expr, ascending, nulls_first) — mirrors exec.sort.SortOrder but at
    the logical level (Catalyst SortOrder)."""

    def __init__(self, expr: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.expr = expr
        self.ascending = ascending
        self.nulls_first = ascending if nulls_first is None else nulls_first

    def __repr__(self):
        direction = "ASC" if self.ascending else "DESC"
        nulls = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.expr!r} {direction} {nulls}"


class Sort(LogicalPlan):
    def __init__(self, child: LogicalPlan, order: Sequence[SortField],
                 is_global: bool = True):
        super().__init__(child)
        self.order = list(order)
        self.is_global = is_global

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def expressions(self) -> List[Expression]:
        return [o.expr for o in self.order]

    def node_description(self) -> str:
        return f"Sort[{', '.join(repr(o) for o in self.order)}]"


class Limit(LogicalPlan):
    def __init__(self, child: LogicalPlan, n: int):
        super().__init__(child)
        self.n = n

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def node_description(self) -> str:
        return f"Limit[{self.n}]"


class Sample(LogicalPlan):
    """Bernoulli sample by deterministic position hash (GpuSampleExec
    role; same hash on device and CPU engine, so fallback is
    bit-identical)."""

    def __init__(self, child: LogicalPlan, fraction: float,
                 seed: int = 42):
        super().__init__(child)
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("sample fraction must be in [0, 1]")
        self.fraction = fraction
        self.seed = seed

    @property
    def schema(self) -> Schema:
        return self.children[0].schema

    def node_description(self) -> str:
        return f"Sample[{self.fraction}, seed={self.seed}]"


class Union(LogicalPlan):
    def __init__(self, *children: LogicalPlan):
        super().__init__(*children)
        first = children[0].schema
        for c in children[1:]:
            if len(c.schema) != len(first):
                raise ValueError("UNION children column counts differ")

    @property
    def schema(self) -> Schema:
        return self.children[0].schema


class Expand(LogicalPlan):
    """GROUPING SETS / rollup / cube pre-projection."""

    def __init__(self, child: LogicalPlan,
                 projections: Sequence[Sequence[Expression]],
                 names: Sequence[str]):
        super().__init__(child)
        self.projections = [list(p) for p in projections]
        self.names = list(names)
        in_schema = child.schema
        from ..expr.conditional import _common_type
        self._schema = [
            (n, _common_type([p[i].data_type(in_schema)
                              for p in self.projections]))
            for i, n in enumerate(self.names)]

    @property
    def schema(self) -> Schema:
        return self._schema

    def expressions(self) -> List[Expression]:
        return [e for p in self.projections for e in p]


class Window(LogicalPlan):
    """Append window-function columns; all entries share one
    (partition_by, order_by) spec (the planner splits differing specs
    into a chain of Window nodes, like Spark's Window exec)."""

    def __init__(self, child: LogicalPlan, window_exprs):
        super().__init__(child)
        self.window_exprs = list(window_exprs)  # [(WindowExpression, name)]
        in_schema = child.schema
        self._schema = list(in_schema) + [
            (name, we.data_type(in_schema)) for we, name in self.window_exprs]

    @property
    def schema(self) -> Schema:
        return self._schema

    def expressions(self) -> List[Expression]:
        out = []
        for we, _ in self.window_exprs:
            out.extend(we.func.children)
            out.extend(we.spec.partition_by)
            out.extend(o.expr for o in we.spec.order_fields)
        return out

    def node_description(self) -> str:
        fns = ", ".join(f"{type(we.func).__name__}->{n}"
                        for we, n in self.window_exprs)
        return f"Window[{fns}]"


class Generate(LogicalPlan):
    """Explode/posexplode generator (GpuGenerateExec): output = child
    columns (+ position) + element column, one row per list element."""

    def __init__(self, child: LogicalPlan, generator,
                 element_name: str, pos_name: Optional[str] = None):
        super().__init__(child)
        from ..expr.collections import Explode
        assert isinstance(generator, Explode)
        self.generator = generator
        self.element_name = element_name
        self.pos_name = pos_name if generator.with_position else None
        elem_t = generator.data_type(child.schema)
        self._schema = list(child.schema)
        if self.pos_name:
            self._schema.append((self.pos_name, dt.INT32))
        self._schema.append((element_name, elem_t))

    @property
    def schema(self) -> Schema:
        return self._schema

    def expressions(self) -> List[Expression]:
        return [self.generator]

    def node_description(self) -> str:
        return f"Generate[{self.generator!r} -> {self.element_name}]"


class Range(LogicalPlan):
    def __init__(self, start: int, end: int, step: int = 1):
        super().__init__()
        self.start, self.end, self.step = start, end, step

    @property
    def schema(self) -> Schema:
        return [("id", dt.INT64)]

    def node_description(self) -> str:
        return f"Range[{self.start}, {self.end}, {self.step}]"


def Distinct(child: LogicalPlan) -> Aggregate:
    """DISTINCT = group by all columns with no aggregates."""
    from ..expr.core import col
    return Aggregate(child, [col(n) for n, _ in child.schema], [])
