"""Host<->device transition nodes.

Rebuild of GpuTransitionOverrides.scala + the row<->columnar boundary
execs (GpuRowToColumnarExec / GpuColumnarToRowExec, SURVEY §2.2): the
overrides driver emits mixed trees where TPU subtrees and CPU-fallback
subtrees meet; these adapters are the seams. Both sides are columnar
(HostTable on CPU), so a transition is a buffer copy + capacity
bucketing, not a row pivot.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from ..columnar.vector import ColumnarBatch, choose_capacity
from ..exec.base import ExecContext, Schema, TpuExec
from .cpu_exec import apply_cpu_node
from .host_table import (HostTable, batch_to_table, concat_tables,
                         empty_like, table_to_batch)
from .logical import LogicalPlan


class CpuPhysical:
    """A logical node executing on CPU, with mixed-device children."""

    def __init__(self, plan: LogicalPlan, children: List):
        self.plan = plan
        self.children = children  # CpuPhysical | DeviceToHostBridge

    @property
    def output_schema(self) -> Schema:
        return self.plan.schema

    def evaluate(self, ctx: ExecContext) -> HostTable:
        tables = [c.evaluate(ctx) for c in self.children]
        return apply_cpu_node(self.plan, tables)

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + "* Cpu" + self.plan.node_description()
        return "\n".join([line] + [c.tree_string(indent + 1)
                                   for c in self.children])


class DeviceToHostBridge:
    """Drains a TPU subtree to a HostTable (GpuColumnarToRowExec role)."""

    def __init__(self, tpu_exec: TpuExec):
        self.tpu = tpu_exec
        self.children = [tpu_exec]

    @property
    def output_schema(self) -> Schema:
        return self.tpu.output_schema

    def evaluate(self, ctx: ExecContext) -> HostTable:
        tables = [batch_to_table(b) for b in self.tpu.execute(ctx)
                  if int(b.num_rows) > 0]
        if not tables:
            return empty_like(self.tpu.output_schema)
        return concat_tables(tables)

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + "* DeviceToHost"
        return "\n".join([line, self.tpu.tree_string(indent + 1)])


class HostToDeviceExec(TpuExec):
    """Feeds a CPU subtree's result to the device as ColumnarBatches
    (GpuRowToColumnarExec role). Splits the host table into
    target-batch-size chunks so device capacities stay bucketed."""

    def __init__(self, cpu_child):
        super().__init__()
        self.cpu_child = cpu_child
        self._schema = cpu_child.output_schema

    @property
    def output_schema(self) -> Schema:
        return self._schema

    def do_execute(self, ctx: ExecContext) -> Iterator[ColumnarBatch]:
        from ..conf import BATCH_SIZE_ROWS
        table = self.cpu_child.evaluate(ctx)
        per = ctx.conf.get(BATCH_SIZE_ROWS)
        n = table.num_rows
        if n == 0:
            yield table_to_batch(table, capacity=8)
            return
        for start in range(0, n, per):
            chunk = table.take(np.arange(start, min(start + per, n)))
            yield table_to_batch(chunk)

    def node_description(self) -> str:
        return "HostToDevice"

    def tree_string(self, indent: int = 0) -> str:
        line = "  " * indent + "* HostToDevice"
        return "\n".join([line, self.cpu_child.tree_string(indent + 1)])
