"""Per-op type-support matrices (TypeSig) and supported-ops doc-gen.

Rebuild of TypeChecks.scala (SURVEY §2.2, 2441 LoC): every expression
and exec declares which input dtypes it supports on TPU; the tagging
pass (meta.py) consults these to decide fallback, and
``generate_supported_ops_doc`` renders the same docs/supported_ops.md
artifact the reference generates from its matrices.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterable, Optional

from ..columnar import dtypes as dt

# type tags
BOOLEAN = "BOOLEAN"
BYTE = "BYTE"
SHORT = "SHORT"
INT = "INT"
LONG = "LONG"
FLOAT = "FLOAT"
DOUBLE = "DOUBLE"
STRING = "STRING"
DATE = "DATE"
TIMESTAMP = "TIMESTAMP"
DECIMAL_64 = "DECIMAL_64"  # long-backed decimal, precision <= 18
DECIMAL_128 = "DECIMAL_128"  # two-limb decimal, precision 19..38
NULL = "NULL"
ARRAY = "ARRAY"
STRUCT = "STRUCT"
MAP = "MAP"

ALL_TAGS = [BOOLEAN, BYTE, SHORT, INT, LONG, FLOAT, DOUBLE, STRING, DATE,
            TIMESTAMP, DECIMAL_64, DECIMAL_128, NULL, ARRAY, STRUCT, MAP]


def tag_of(t: dt.DType) -> str:
    if isinstance(t, dt.BooleanType):
        return BOOLEAN
    if isinstance(t, dt.ByteType):
        return BYTE
    if isinstance(t, dt.ShortType):
        return SHORT
    if isinstance(t, dt.IntegerType):
        return INT
    if isinstance(t, dt.LongType):
        return LONG
    if isinstance(t, dt.FloatType):
        return FLOAT
    if isinstance(t, dt.DoubleType):
        return DOUBLE
    if isinstance(t, dt.StringType):
        return STRING
    if isinstance(t, dt.DateType):
        return DATE
    if isinstance(t, dt.TimestampType):
        return TIMESTAMP
    if isinstance(t, dt.DecimalType):
        return DECIMAL_128 if t.precision > 18 else DECIMAL_64
    if isinstance(t, dt.NullType):
        return NULL
    if isinstance(t, dt.ArrayType):
        return ARRAY
    if isinstance(t, dt.StructType):
        return STRUCT
    if isinstance(t, dt.MapType):
        return MAP
    raise TypeError(f"unknown dtype {t}")


class TypeSig:
    """A set of supported type tags (TypeChecks.scala TypeSig)."""

    def __init__(self, *tags: str):
        self.tags: FrozenSet[str] = frozenset(tags)

    def __add__(self, other: "TypeSig") -> "TypeSig":
        out = TypeSig()
        out.tags = self.tags | other.tags
        return out

    def __sub__(self, other: "TypeSig") -> "TypeSig":
        out = TypeSig()
        out.tags = self.tags - other.tags
        return out

    def supports(self, t: dt.DType) -> bool:
        return tag_of(t) in self.tags

    def reason_if_unsupported(self, t: dt.DType,
                              what: str) -> Optional[str]:
        if self.supports(t):
            return None
        return f"{what}: type {t} not supported on TPU"

    def __repr__(self):
        return "TypeSig(" + ", ".join(sorted(self.tags)) + ")"


# common signatures
integral = TypeSig(BYTE, SHORT, INT, LONG)
fp = TypeSig(FLOAT, DOUBLE)
decimal128 = TypeSig(DECIMAL_128)
numeric = integral + fp + TypeSig(DECIMAL_64)
numeric_all = numeric + decimal128
numeric_no_decimal = integral + fp
comparable = numeric + TypeSig(BOOLEAN, STRING, DATE, TIMESTAMP)
orderable = comparable
all_basic = comparable + TypeSig(NULL)
all_basic_128 = all_basic + decimal128
none_sig = TypeSig()
