"""Plan layer: logical plans, tag-then-convert overrides, CPU fallback.

TPU-native rebuild of the reference's "compiler" (SURVEY §2.2):
GpuOverrides.scala's rule registry + RapidsMeta.scala's wrapper/tagging
hierarchy + TypeChecks.scala's support matrices + GpuTransitionOverrides'
host<->device transition insertion — re-shaped around our own DataFrame
frontend instead of Catalyst (there is no Spark underneath on TPU; the
framework IS the query engine, with a numpy CPU executor playing the
role of "CPU Spark" both as the fallback path and as the differential-
test oracle).
"""

from .logical import (Aggregate, Distinct, Expand, Filter, Join, Limit,
                      LocalRelation, LogicalPlan, Project, Range, Sort,
                      Union)

__all__ = [
    "LogicalPlan", "LocalRelation", "Project", "Filter", "Aggregate",
    "Join", "Sort", "Limit", "Union", "Expand", "Range", "Distinct",
    "DataFrame", "TpuSession",
]


def __getattr__(name):
    # session (and through it overrides -> io.scan) loads lazily so leaf
    # modules like plan.host_table can be imported from the io package
    # without a circular import (PEP 562)
    if name in ("DataFrame", "TpuSession"):
        from .session import DataFrame, TpuSession
        return {"DataFrame": DataFrame, "TpuSession": TpuSession}[name]
    raise AttributeError(name)
