"""Data distribution requirements and output partitionings.

The physical-planning vocabulary that lets the planner place shuffle
exchanges between pipeline stages — the role Catalyst's
``Distribution``/``Partitioning`` lattice plays for the reference
(GpuShuffleExchangeExecBase.scala:167 consumes a target partitioning,
GpuHashPartitioningBase.scala:64 implements it on device). Every
``TpuExec`` reports an ``output_partitioning`` and a per-child
``required_child_distributions`` list; ``ensure_distribution`` (in
overrides.py) walks the physical tree and inserts
``ShuffleExchangeExec`` / ``BroadcastExchangeExec`` nodes wherever a
child's partitioning does not satisfy its parent's requirement —
Spark's EnsureRequirements rule, rebuilt over our exec tree.

Expression identity is structural (by ``repr``): the frontend overrides
``__eq__`` to build predicate trees, so reprs are the canonical key.
"""

from __future__ import annotations

from typing import List, Optional, Sequence


def _expr_key(e) -> str:
    return repr(e)


# --- distributions (what a parent requires of a child) ---------------------

class Distribution:
    """Base requirement on how a child's rows are spread across
    partitions."""


class UnspecifiedDistribution(Distribution):
    """No requirement."""

    def __repr__(self):
        return "Unspecified"


class AllTuples(Distribution):
    """All rows in a single partition (global aggregates, limits)."""

    def __repr__(self):
        return "AllTuples"


class ClusteredDistribution(Distribution):
    """Rows with equal values of ``exprs`` land in the same partition
    (aggregate merge, shuffled join)."""

    def __init__(self, exprs: Sequence, num_partitions: Optional[int] = None):
        self.exprs = list(exprs)
        self.num_partitions = num_partitions

    def __repr__(self):
        return f"Clustered({', '.join(map(repr, self.exprs))})"


class OrderedDistribution(Distribution):
    """Rows are range-partitioned by the sort order: partition i holds
    rows strictly below partition i+1 (global sort)."""

    def __init__(self, sort_orders: Sequence):
        self.sort_orders = list(sort_orders)

    def __repr__(self):
        return "Ordered"


class BroadcastDistribution(Distribution):
    """Every participant holds a full copy (broadcast join build side)."""

    def __repr__(self):
        return "Broadcast"


# --- partitionings (what a node produces) ----------------------------------

class Partitioning:
    num_partitions: int = 1

    def satisfies(self, dist: Distribution) -> bool:
        if isinstance(dist, UnspecifiedDistribution):
            return True
        return False


class UnknownPartitioning(Partitioning):
    """No known structure. ``num_partitions`` is a hint only."""

    def __init__(self, num_partitions: int = 1):
        self.num_partitions = num_partitions

    def __repr__(self):
        return f"UnknownPartitioning({self.num_partitions})"


class SinglePartition(Partitioning):
    """Exactly one partition: satisfies everything except broadcast
    (matching Spark: a single partition is trivially clustered and
    ordered)."""

    num_partitions = 1

    def satisfies(self, dist: Distribution) -> bool:
        return not isinstance(dist, BroadcastDistribution)

    def __repr__(self):
        return "SinglePartition"


class HashPartitioning(Partitioning):
    """pmod(murmur3(exprs), n) row placement
    (GpuHashPartitioningBase.scala:64)."""

    def __init__(self, exprs: Sequence, num_partitions: int):
        self.exprs = list(exprs)
        self.num_partitions = num_partitions

    def satisfies(self, dist: Distribution) -> bool:
        if isinstance(dist, UnspecifiedDistribution):
            return True
        if isinstance(dist, ClusteredDistribution):
            if dist.num_partitions is not None and \
                    dist.num_partitions != self.num_partitions:
                return False
            # hash exprs must be a subset of the clustering exprs and
            # non-empty: equal cluster keys then imply equal hash keys.
            mine = [_expr_key(e) for e in self.exprs]
            theirs = {_expr_key(e) for e in dist.exprs}
            return bool(mine) and all(k in theirs for k in mine)
        return False

    def __repr__(self):
        return (f"HashPartitioning({', '.join(map(repr, self.exprs))}, "
                f"{self.num_partitions})")


class RangePartitioning(Partitioning):
    """Rows range-partitioned by sort order (GpuRangePartitioner)."""

    def __init__(self, sort_orders: Sequence, num_partitions: int):
        self.sort_orders = list(sort_orders)
        self.num_partitions = num_partitions

    def satisfies(self, dist: Distribution) -> bool:
        if isinstance(dist, UnspecifiedDistribution):
            return True
        if isinstance(dist, OrderedDistribution):
            if len(dist.sort_orders) > len(self.sort_orders):
                return False
            for want, have in zip(dist.sort_orders, self.sort_orders):
                if (_expr_key(want.expr) != _expr_key(have.expr)
                        or want.ascending != have.ascending
                        or want.nulls_first != have.nulls_first):
                    return False
            return True
        if isinstance(dist, ClusteredDistribution):
            theirs = {_expr_key(e) for e in dist.exprs}
            return all(_expr_key(o.expr) in theirs
                       for o in self.sort_orders)
        return False

    def __repr__(self):
        return f"RangePartitioning({self.num_partitions})"


class BroadcastPartitioning(Partitioning):
    """Output of a broadcast exchange: a full copy everywhere."""

    num_partitions = 1

    def satisfies(self, dist: Distribution) -> bool:
        return isinstance(dist, (BroadcastDistribution,
                                 UnspecifiedDistribution))

    def __repr__(self):
        return "BroadcastPartitioning"


# --- mesh placement equivalence --------------------------------------------

def mesh_placement_satisfied(child: Partitioning, exchange) -> bool:
    """True when ``exchange``'s mesh collective is provably the identity
    permutation for rows already placed by ``child`` — the planner
    predicate behind the device-resident exchange bypass
    (``MeshColocationBypass`` generalized).

    Mesh placement ignores plan-level ``num_partitions``: every lowered
    exchange routes with the SAME function of the mesh size (hash:
    ``pmod(murmur3(exprs), n_shards)``; range: quantile bounds of the
    same sort orders; single: everything on shard 0), so equivalence is
    purely structural on the exchange's target:

    * hash target — child is ``HashPartitioning`` on the identical expr
      sequence (subset is NOT enough here: a different expr list hashes
      rows to different shards even when clustering would be satisfied);
    * range target — child is ``RangePartitioning`` on a sort-order
      prefix at least as long as the target's (shards already globally
      ordered by those orders, which is all downstream sorts consume);
    * single-partition target — child is ``SinglePartition`` (rows are
      already concentrated on one shard).
    """
    keys = list(getattr(exchange, "key_exprs", None) or [])
    orders = list(getattr(exchange, "sort_orders", None) or [])
    if orders:
        if not isinstance(child, RangePartitioning) \
                or len(child.sort_orders) < len(orders):
            return False
        return all(
            _expr_key(w.expr) == _expr_key(h.expr)
            and w.ascending == h.ascending
            and w.nulls_first == h.nulls_first
            for w, h in zip(orders, child.sort_orders))
    if keys:
        if not isinstance(child, HashPartitioning):
            return False
        return ([_expr_key(e) for e in child.exprs]
                == [_expr_key(e) for e in keys])
    if (getattr(exchange, "num_partitions", None) or 1) == 1:
        return isinstance(child, SinglePartition)
    return False  # round-robin rebalance: always a true repartition
