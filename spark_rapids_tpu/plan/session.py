"""User-facing session + DataFrame API.

The frontend that plays Spark's role above the plan-rewrite layer: users
build DataFrames (logical plans), and ``collect`` runs them through the
overrides driver (overrides.py) onto the TPU, with CPU fallback for
anything tagged unsupported — the full tag-then-convert architecture of
the reference (Plugin.scala ColumnarOverrideRules) with our own engine
underneath instead of Spark's.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union as TUnion

import numpy as np

from ..columnar import dtypes as dt
from ..conf import SrtConf, active_conf, set_active_conf
from ..exec.base import ExecContext, TpuExec
from ..expr.aggregates import (Average, Count, CountStar, First, Last, Max,
                               Min, StddevSamp, Sum)
from ..expr.core import Alias, ColumnRef, Expression, col, lit, output_name
from . import logical as L
from . import overrides
from .host_table import HostTable, batch_to_table, concat_tables, empty_like, to_pydict
from .transitions import CpuPhysical, DeviceToHostBridge

#: re-check the map count only every N executes (reading
#: /proc/self/maps is O(mappings) — cheap, but not free per query).
#: 1-2 NDS-scale queries can add several thousand mappings when the
#: persistent cache is warm (deserialization is fast), so the window
#: must stay small.
import os as _os
import sys as _sys

try:
    _MMAP_CHECK_EVERY = max(
        1, int(_os.environ.get("SRT_MMAP_CHECK_EVERY", 2)))
except ValueError:
    _MMAP_CHECK_EVERY = 2
_mmap_counter = [0]


def _mmap_guard(session) -> None:
    """Self-defense against memory-mapping exhaustion (SURVEY §5
    failure-detection role; observed live in round 4): every compiled
    XLA executable holds mmap'd code pages, the engine mints fresh jit
    wrappers per plan, and long many-query processes (the 99-query NDS
    suite) accumulate mappings monotonically until the kernel's
    vm.max_map_count (65530 default) is hit — at which point jaxlib
    SIGSEGVs inside whatever allocation crosses the line (compile OR
    cache-load). When usage nears the limit, drop every in-memory
    executable (the persistent disk cache keeps recompiles cheap) and
    the session's plan cache (its exec trees pin traced jits)."""
    _mmap_counter[0] += 1
    if _mmap_counter[0] % _MMAP_CHECK_EVERY:
        return
    try:
        with open("/proc/self/maps", "rb") as f:
            used = sum(1 for _ in f)
        with open("/proc/sys/vm/max_map_count", "rb") as f:
            limit = int(f.read())
    except OSError:  # non-Linux: nothing to defend against
        return
    try:
        frac = float(_os.environ.get("SRT_MMAP_GUARD_FRACTION", 0.5))
    except ValueError:
        frac = 0.5
    debug = _os.environ.get("SRT_MMAP_GUARD_DEBUG")
    if used < frac * limit:
        if debug:
            print(f"[mmap_guard] used={used} limit={limit} (ok)",
                  file=_sys.stderr, flush=True)
        return
    import gc

    import jax

    from ..jit_registry import release_executables
    session._plan_cache.clear()
    jax.clear_caches()
    # the ledger wrappers hold AOT executables jax's caches don't
    # track — release those mappings too, or the guard under-frees
    release_executables()
    gc.collect()
    if debug:
        with open("/proc/self/maps", "rb") as f:
            after = sum(1 for _ in f)
        print(f"[mmap_guard] used={used} -> {after} after clear "
              f"(limit {limit})",
              file=_sys.stderr, flush=True)


class TpuSession:
    """Entry point (SparkSession analogue). Holds the active conf and
    the temp-view catalog backing ``sql()``."""

    #: process-wide query sequence — query ids stay unique across
    #: sessions within one process (event-log files are per process)
    _query_seq = [0]

    def __init__(self, conf: Optional[SrtConf] = None):
        self.conf = conf or active_conf()
        self._catalog: Dict[str, "DataFrame"] = {}
        from .plan_cache import PhysicalPlanCache
        self._plan_cache = PhysicalPlanCache()
        #: (physical, ctx, query_id, wall_ns) of the most recent
        #: execute — explain(metrics=True) renders from this
        self._last_execution = None
        #: QueryContext of the query this session is currently
        #: executing (None when idle): the cancel handle for other
        #: threads — ``session.cancel()`` / serving-tier aborts
        self._active_query = None
        #: serving-tier identity: when set (serve/server.py stamps
        #: them per client session) QueryStart/QueryEnd events carry
        #: session_id/tenant fields so per-pid event logs from a
        #: multi-session server group by tenant in profile_report /
        #: history_report instead of interleaving anonymously
        self.session_id: Optional[str] = None
        self.tenant: Optional[str] = None

    def cancel(self, reason: str = "session.cancel()") -> bool:
        """Cancel the in-flight query, if any (thread-safe; callable
        from any thread). Returns True if a query was signalled."""
        q = self._active_query
        if q is None:
            return False
        q.cancel(reason)
        return True

    # --- constructors ---
    def create_dataframe(self, data: Dict[str, list],
                         schema: Optional[List] = None) -> "DataFrame":
        if schema is None:
            schema = _infer_schema(data)
        return DataFrame(self, L.LocalRelation(data, schema))

    # --- SQL frontend (sql/parser.py; the Catalyst seam analogue) ---
    def create_or_replace_temp_view(self, name: str, df: "DataFrame"
                                    ) -> None:
        self._catalog[name.lower()] = df

    def table(self, name: str) -> "DataFrame":
        try:
            return self._catalog[name.lower()]
        except KeyError:
            raise KeyError(f"table or view {name!r} not found; register "
                           "with create_or_replace_temp_view")

    def sql(self, text: str) -> "DataFrame":
        """Run a SQL SELECT over registered temp views."""
        from ..sql import parse_sql
        return parse_sql(self, text)

    def range(self, start: int, end: Optional[int] = None,
              step: int = 1) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(self, L.Range(start, end, step))

    @property
    def read(self) -> "DataFrameReader":
        from ..io.reader import DataFrameReader
        return DataFrameReader(self)

    # --- execution ---
    def execute(self, plan: L.LogicalPlan,
                timeout: Optional[float] = None,
                query=None) -> HostTable:
        """Run a logical plan to a host table.

        Physical plans are memoized on a structural key (plan_cache.py)
        so repeated collects of an identical query — even through fresh
        DataFrame objects — reuse the exec tree and its traced jits;
        without this every collect re-traced every jaxpr (the dominant
        warm-query cost)."""
        _mmap_guard(self)
        if self.conf.ansi:
            # srt.sql.ansi.enabled: clone the plan with every Cast /
            # arithmetic / sum node ansi-marked so overflow and invalid
            # casts raise (expr/ansi.py; the conf is part of the plan
            # cache key, so ANSI and non-ANSI plans never alias)
            from ..expr.ansi import rewrite_plan
            plan = rewrite_plan(plan)
        from .plan_cache import plan_cache_key
        key = plan_cache_key(plan, self.conf)
        physical, release = (None, None)
        if key is not None:
            # execution lease: a cached tree may run on one thread at
            # a time (its shuffle ids / write flags are instance
            # state); a busy entry makes this caller plan fresh
            physical, release = self._plan_cache.lease(key)
        if physical is None:
            physical = overrides.apply_overrides(plan, self.conf)
            # only fully-device plans cache: CPU/bridge nodes hold no
            # reset protocol for their one-shot state
            if key is not None and isinstance(physical, TpuExec):
                release = self._plan_cache.put_leased(key, physical)
        elif isinstance(physical, TpuExec):
            physical.reset_for_rerun()
        try:
            return self._execute_physical(physical, plan,
                                          timeout=timeout, query=query)
        finally:
            if release is not None:
                release()

    def _execute_physical(self, physical, plan: L.LogicalPlan,
                          timeout: Optional[float] = None,
                          query=None) -> HostTable:
        """Run a planned physical tree with the query-level
        observability wrapper: QueryStart/QueryEnd events, optional
        per-query span tracer (written out as a Chrome trace), and a
        per-query metrics summary recorded in the process registry.
        When observability is off this adds one conf check and one
        per-query summary — nothing per batch.

        Concurrency contract (robustness/admission.py): the query
        first passes admission (``srt.sql.concurrentQueryTasks``
        running, bounded queue, load-shed with AdmissionRejected),
        claims a per-query budget slice, and executes under a
        QueryContext cancel token armed from ``timeout`` (collect) or
        ``srt.sql.queryTimeout`` — cancellation/deadline surface as
        QueryCancelled / DeadlineExceeded after a clean teardown
        through every producer and fetch thread."""
        import time as _time

        from ..conf import METRICS_LEVEL, QUERY_TIMEOUT_S
        from ..obs import events as _events
        from ..obs import resource as _resource
        from ..obs import roofline as _roofline
        from ..obs.registry import registry as _registry
        from ..obs.registry import summarize_metrics
        from ..obs.trace import maybe_tracer
        from ..memory.budget import device_budget, task_context
        from ..robustness.admission import (DeadlineExceeded,
                                            QueryContext,
                                            QueryInterrupted,
                                            query_scope, query_semaphore)
        _events.configure_from_conf(self.conf)
        _resource.configure_from_conf(self.conf)
        _roofline.configure_from_conf(self.conf)
        if query is not None:
            # externally-supplied cancel token (serve/server.py): the
            # caller holds the handle before admission, so a client
            # disconnect cancels a query even while it is still queued
            qctx = query
            qid = qctx.query_id
            if timeout is not None:
                qctx.set_timeout(timeout)
            elif qctx.deadline is None:
                qctx.set_timeout(self.conf.get(QUERY_TIMEOUT_S))
        else:
            TpuSession._query_seq[0] += 1
            qid = f"q{_os.getpid()}-{TpuSession._query_seq[0]}"
            qctx = QueryContext(query_id=qid)
            qctx.set_timeout(timeout if timeout is not None
                             else self.conf.get(QUERY_TIMEOUT_S))
        # admission before any work: may park this thread in the
        # bounded queue, load-shed (AdmissionRejected — retryable, no
        # resources held), or give up on cancel/deadline while queued
        sem = query_semaphore(self.conf)
        sem.acquire(qctx)
        budget = None
        try:
            budget = device_budget()
            budget.register_query(qid, slots=sem.permits)
            self._active_query = qctx
            qscope = query_scope(qctx)
            qscope.__enter__()
            # per-query roofline window: ledger counter baseline,
            # diffed in the finally into a RooflineSummary (None =
            # sampling off, and then the whole layer is skipped)
            rwin = _roofline.window()
            ctx = ExecContext(self.conf, query=qctx)
            ctx.tracer = maybe_tracer(self.conf)
        except BaseException:
            # a failed setup must not leak the admission permit —
            # that would wedge every later query behind a ghost
            if budget is not None:
                budget.unregister_query(qid)
            sem.release()
            raise
        tc = task_context()
        tc0 = (tc.spilled_bytes, tc.retry_count, tc.split_count)
        is_tpu = isinstance(physical, TpuExec)
        # serving identity fields ride on QueryStart/QueryEnd (only
        # when set: single-session logs stay byte-identical)
        ident: Dict = {}
        if self.session_id is not None:
            ident["session_id"] = self.session_id
        if self.tenant is not None:
            ident["tenant"] = self.tenant
        if _events.enabled():
            _events.emit("QueryStart", query_id=qid, device=is_tpu,
                         plan=physical.tree_string() if is_tpu
                         else type(physical).__name__, **ident)
        qspan = ctx.tracer.span(qid, kind="query") \
            if ctx.tracer is not None else None
        t0 = _time.perf_counter_ns()
        status = "ok"
        error = None
        try:
            if qspan is not None:
                qspan.__enter__()
            try:
                if is_tpu:
                    from ..memory.spill import batch_nbytes
                    from .adaptive import adaptive_execute
                    reg = _registry()
                    tables = []
                    for b in adaptive_execute(physical, ctx):
                        n = int(b.num_rows)
                        if n == 0:
                            continue
                        # output-batch shape distributions (once per
                        # OUTPUT batch, not per operator pull)
                        reg.observe("batch_rows", n, "rows")
                        reg.observe("batch_bytes", batch_nbytes(b),
                                    "bytes")
                        tables.append(batch_to_table(b))
                    result = concat_tables(tables) if tables \
                        else empty_like(plan.schema)
                else:
                    result = physical.evaluate(ctx)
                # final token check: a cancel/deadline that flipped as
                # the last producer drained must never surface as a
                # silently truncated "successful" result — a cancelled
                # query's caller gets the typed error even if the race
                # finished the pull loop first
                qctx.check()
            finally:
                if qspan is not None:
                    qspan.__exit__(None, None, None)
        except QueryInterrupted as e:
            status = "deadline_exceeded" \
                if isinstance(e, DeadlineExceeded) else "cancelled"
            error = f"{type(e).__name__}: {e}"
            _events.emit(type(e).__name__, query_id=qid,
                         reason=qctx.cancel_reason)
            raise
        except BaseException as e:
            status = "error"
            error = f"{type(e).__name__}: {e}"
            raise
        finally:
            qscope.__exit__(None, None, None)
            budget.unregister_query(qid)
            sem.release()
            if self._active_query is qctx:
                self._active_query = None
            wall_ns = _time.perf_counter_ns() - t0
            _registry().observe("task_time_ns", wall_ns, "ns")
            summary = summarize_metrics(ctx.metrics,
                                        self.conf.get(METRICS_LEVEL))
            extra = {"spilled_bytes": tc.spilled_bytes - tc0[0],
                     "oom_retries": tc.retry_count - tc0[1],
                     "oom_splits": tc.split_count - tc0[2]}
            if rwin is not None:
                rsum = rwin.finish(qid)  # emits RooflineSummary
                if rsum is not None:
                    extra["roofline"] = rsum
            rec = _registry().record_query(qid, summary, wall_ns,
                                           status, **extra)
            self._last_execution = {"physical": physical, "ctx": ctx,
                                    "query_id": qid, "wall_ns": wall_ns,
                                    "record": rec}
            if _events.enabled():
                end: Dict = {"query_id": qid, "status": status,
                             "wall_ns": wall_ns, "metrics": summary}
                end.update(ident)
                end.update(extra)
                if error is not None:
                    end["error"] = error
                _events.emit("QueryEnd", **end)
                if ctx.tracer is not None and \
                        _events.log_dir() is not None:
                    try:
                        ctx.tracer.write_chrome_trace(_os.path.join(
                            _events.log_dir(), f"trace-{qid}.json"))
                    except OSError:
                        pass
        return result


def _infer_value_type(sample, values=()):
    import datetime
    import decimal
    if sample is None:
        return dt.INT32
    if isinstance(sample, bool):
        return dt.BOOL
    if isinstance(sample, int):
        return dt.INT64
    if isinstance(sample, float):
        return dt.FLOAT64
    if isinstance(sample, str):
        return dt.STRING
    if isinstance(sample, datetime.datetime):
        return dt.TIMESTAMP
    if isinstance(sample, datetime.date):
        return dt.DATE
    if isinstance(sample, decimal.Decimal):
        exp = -sample.as_tuple().exponent
        return dt.DecimalType(18, max(exp, 0))
    if isinstance(sample, (list, tuple)):
        elems = [e for v in values if v is not None for e in v
                 if e is not None] or \
            [e for e in sample if e is not None]
        et = _infer_value_type(elems[0], elems) if elems else dt.INT64
        return dt.ArrayType(et)
    if isinstance(sample, dict):
        return dt.StructType(tuple(
            (k, _infer_value_type(v)) for k, v in sample.items()))
    raise TypeError(f"cannot infer dtype for value {sample!r}")


def _infer_schema(data: Dict[str, list]) -> List:
    schema = []
    for name, values in data.items():
        sample = next((v for v in values if v is not None), None)
        schema.append((name, _infer_value_type(sample, values)))
    return schema


def _to_expr(c) -> Expression:
    if isinstance(c, Expression):
        return c
    if isinstance(c, str):
        return col(c)
    return lit(c)


class DeviceColumns(dict):
    """Mapping of {name: (data, validity)} device arrays with the live
    row count — arrays are capacity-padded past ``num_rows``."""

    def __init__(self, cols: dict, num_rows: int):
        super().__init__(cols)
        self.num_rows = num_rows


def _extract_windows(plan: L.LogicalPlan, exprs):
    """Pull WindowExpressions out of a projection list into Window nodes
    (the analyzer step Spark performs for window functions in select):
    one Window node per distinct (partition_by, order_by) spec, chained;
    the projection then references the produced columns by name. Window
    expressions NESTED inside larger expressions (the TPC-DS
    ``sum(x)*100/sum(sum(x)) over (...)`` ratio shape) extract the same
    way — the surrounding arithmetic stays in the projection and reads
    the generated column."""
    from ..expr import conditional as Cond
    from ..expr.window import WindowExpression
    groups = {}  # spec signature -> [(WindowExpression, gen_name)]
    counter = [0]

    def pull(e):
        if isinstance(e, WindowExpression):
            # always a fresh internal name: a user alias may collide
            # with an input column, and name lookup resolves
            # first-match
            gen = f"__w{counter[0]}"
            counter[0] += 1
            sig = (repr(e.spec.partition_by),
                   repr([(repr(o.expr), o.ascending, o.nulls_first)
                         for o in e.spec.order_fields]))
            groups.setdefault(sig, []).append((e, gen))
            return col(gen)
        if isinstance(e, Cond.CaseWhen):
            return Cond.CaseWhen(
                [(pull(c), pull(v)) for c, v in e.branches],
                pull(e.otherwise) if e.otherwise is not None else None)
        if not e.children:
            return e
        out = e.__class__.__new__(e.__class__)
        out.__dict__.update(e.__dict__)
        out.children = [pull(c) for c in e.children]
        return out

    out_exprs = []
    for i, e in enumerate(exprs):
        if isinstance(e, Alias):
            out_exprs.append(Alias(pull(e.children[0]), e.name))
        elif isinstance(e, WindowExpression):
            out_exprs.append(Alias(pull(e), f"_w{i}"))
        else:
            out_exprs.append(pull(e))
    for _, wexprs in groups.items():
        plan = L.Window(plan, wexprs)
    return plan, out_exprs


def _extract_generators(plan: L.LogicalPlan, exprs):
    """Pull Explode generators out of a projection into a Generate node
    (the analyzer step Spark performs for explode() in select): at most
    one generator per projection, like Spark."""
    from ..expr.collections import Explode
    out_exprs = []
    gen_count = 0
    for i, e in enumerate(exprs):
        inner = e.children[0] if isinstance(e, Alias) else e
        if isinstance(inner, Explode):
            gen_count += 1
            if gen_count > 1:
                raise ValueError("only one generator allowed per select")
            user = e.name if isinstance(e, Alias) else "col"
            if inner.with_position:
                pos_name = f"__gpos{i}"
                plan = L.Generate(plan, inner, f"__gen{i}", pos_name)
                out_exprs.append(Alias(col(pos_name), "pos"))
            else:
                plan = L.Generate(plan, inner, f"__gen{i}")
            out_exprs.append(Alias(col(f"__gen{i}"), user))
        else:
            out_exprs.append(e)
    return plan, out_exprs


class DataFrame:
    """Lazy logical-plan builder (Spark DataFrame analogue)."""

    def __init__(self, session: TpuSession, plan: L.LogicalPlan):
        self.session = session
        self.plan = plan

    # --- transformations ---
    def select(self, *cols) -> "DataFrame":
        exprs = [_to_expr(c) for c in cols]
        plan, exprs = _extract_generators(self.plan, exprs)
        plan, exprs = _extract_windows(plan, exprs)
        return DataFrame(self.session, L.Project(plan, exprs))

    def with_column(self, name: str, expr) -> "DataFrame":
        existing = [col(n) for n, _ in self.plan.schema if n != name]
        exprs = existing + [Alias(_to_expr(expr), name)]
        plan, exprs = _extract_windows(self.plan, exprs)
        return DataFrame(self.session, L.Project(plan, exprs))

    def filter(self, condition) -> "DataFrame":
        return DataFrame(self.session,
                         L.Filter(self.plan, _to_expr(condition)))

    where = filter

    def group_by(self, *cols) -> "GroupedData":
        return GroupedData(self, [_to_expr(c) for c in cols])

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData(self, []).agg(*aggs)

    def join(self, other: "DataFrame", on, how: str = "inner"
             ) -> "DataFrame":
        how = {"inner": "inner", "left": "left_outer",
               "left_outer": "left_outer", "right": "right_outer",
               "right_outer": "right_outer", "full": "full_outer",
               "full_outer": "full_outer", "outer": "full_outer",
               "semi": "left_semi", "left_semi": "left_semi",
               "anti": "left_anti", "left_anti": "left_anti",
               "cross": "cross"}[how]
        if isinstance(on, str):
            on = [on]
        using: List[str] = []
        if isinstance(on, (list, tuple)) and on and isinstance(on[0], str):
            using = list(on)
            lk = [col(n) for n in on]
            rk = [col(n) for n in on]
        elif isinstance(on, tuple) and len(on) == 2:
            lk, rk = [_to_expr(e) for e in on[0]], \
                [_to_expr(e) for e in on[1]]
        else:
            raise TypeError("join `on`: column name(s) or (left_exprs, "
                            "right_exprs)")
        joined = L.Join(self.plan, other.plan, lk, rk, how)
        # USING semantics: emit the key once. left's copy is the correct
        # survivor for inner/left/semi/anti; other types keep both.
        if using and how in ("inner", "left_outer", "left_semi",
                             "left_anti"):
            keep = [col(n) for n in self.columns]
            if how in ("inner", "left_outer"):
                keep += [col(n) for n in other.columns if n not in using]
                # name-based refs resolve to the first (left) occurrence;
                # right non-key columns are unique by assumption
            joined = L.Project(joined, keep)
        return DataFrame(self.session, joined)

    def cross_join(self, other: "DataFrame",
                   condition: Optional[Expression] = None) -> "DataFrame":
        """Cartesian product, optionally with a non-equi condition
        (nested-loop join on device)."""
        how = "cross" if condition is None else "inner"
        return DataFrame(self.session,
                         L.Join(self.plan, other.plan, [], [], how,
                                condition=condition))

    def sort(self, *cols, ascending: TUnion[bool, Sequence[bool]] = True
             ) -> "DataFrame":
        exprs = [_to_expr(c) for c in cols]
        if isinstance(ascending, bool):
            ascending = [ascending] * len(exprs)
        order = [L.SortField(e, a) for e, a in zip(exprs, ascending)]
        return DataFrame(self.session, L.Sort(self.plan, order))

    order_by = sort

    def sort_desc(self, *cols) -> "DataFrame":
        return self.sort(*cols, ascending=False)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(self.session, L.Limit(self.plan, n))

    def sample(self, fraction: float, seed: int = 42) -> "DataFrame":
        return DataFrame(self.session, L.Sample(self.plan, fraction,
                                                seed))

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(self.session, L.Union(self.plan, other.plan))

    def distinct(self) -> "DataFrame":
        return DataFrame(self.session, L.Distinct(self.plan))

    # --- metadata ---
    @property
    def schema(self) -> List:
        return self.plan.schema

    @property
    def columns(self) -> List[str]:
        return [n for n, _ in self.plan.schema]

    def __getitem__(self, name: str) -> ColumnRef:
        if name not in self.columns:
            raise KeyError(name)
        return col(name)

    # --- actions ---
    def collect(self, timeout: Optional[float] = None) -> List[dict]:
        """Run the query and return rows. ``timeout`` (seconds) arms a
        per-call deadline — the query tears down cleanly and raises
        DeadlineExceeded on expiry; overrides ``srt.sql.queryTimeout``."""
        table = self.session.execute(self.plan, timeout=timeout)
        data = to_pydict(table)
        names = list(data.keys())
        n = table.num_rows
        return [{k: data[k][i] for k in names} for i in range(n)]

    def to_pydict(self) -> dict:
        return to_pydict(self.session.execute(self.plan))

    def to_pandas(self):
        import pandas as pd
        return pd.DataFrame(self.to_pydict())

    def count(self) -> int:
        return self.session.execute(self.plan).num_rows

    @property
    def write(self):
        from ..io.writer import DataFrameWriter
        return DataFrameWriter(self)

    def cache(self) -> "DataFrame":
        """Materialize once into compressed host blocks; further use
        re-reads the cache (ParquetCachedBatchSerializer role)."""
        from ..cache import cache_dataframe
        return cache_dataframe(self)

    def unpersist(self) -> "DataFrame":
        """Release a cached DataFrame's blocks (memory + disk) and
        unregister it from the session cache registry."""
        from ..cache import CachedRelation
        if isinstance(self.plan, CachedRelation):
            self.plan.unpersist()
        return self

    def to_device_arrays(self) -> "DeviceColumns":
        """Zero-copy ML export (ColumnarRdd.scala:42 role — the
        reference hands cuDF tables to XGBoost; here downstream jax ML
        code consumes the columns directly). Returns a DeviceColumns:
        mapping of {name: (data jax.Array, validity)} plus ``num_rows``
        — arrays are capacity-padded, so consumers MUST slice to
        num_rows (padding rows are indistinguishable from nulls by
        validity alone)."""
        from .. import ops  # noqa: F401
        from ..exec.base import ExecContext, TpuExec
        from ..ops import kernels as K
        from ..columnar.vector import choose_capacity
        from . import overrides as O
        physical = O.apply_overrides(self.plan, self.session.conf)
        ctx = ExecContext(self.session.conf)
        if isinstance(physical, TpuExec):
            batches = [b for b in physical.execute(ctx)
                       if int(b.num_rows) > 0]
        else:
            from .host_table import table_to_batch
            batches = [table_to_batch(physical.evaluate(ctx))]
        if not batches:
            return DeviceColumns({}, 0)
        total = sum(int(b.num_rows) for b in batches)
        merged = batches[0] if len(batches) == 1 else \
            K.concat_batches(batches, choose_capacity(total))
        cols = {name: (c.data if not hasattr(c, "chars") else
                       (c.offsets, c.chars), c.validity)
                for name, c in zip(merged.names, merged.columns)}
        return DeviceColumns(cols, int(merged.num_rows))

    def explain(self, mode: str = "ALL", metrics: bool = False) -> str:
        if metrics:
            return self._explain_metrics()
        meta = overrides.tag_only(self.plan)
        out = "\n".join(meta.explain_lines(
            only_not_on_tpu=(mode == "NOT_ON_TPU")))
        print(out)
        return out

    def _explain_metrics(self) -> str:
        """Execute the query, then render the physical tree with each
        operator's accumulated metrics (rows / batches / op-time /
        shuffle bytes; the reference SQL-UI annotation role) plus a
        query-level footer with wall time and spill totals."""
        from ..conf import METRICS_LEVEL
        self.session.execute(self.plan)
        last = self.session._last_execution
        physical, ctx = last["physical"], last["ctx"]
        level = self.session.conf.get(METRICS_LEVEL)
        if isinstance(physical, TpuExec):
            body = _metrics_tree_lines(physical, ctx.metrics, level)
        else:
            body = [f"* {type(physical).__name__} (CPU fallback path)"]
        rec = last["record"]
        totals = rec["totals"]
        footer = (f"query {last['query_id']}: "
                  f"wall={last['wall_ns'] / 1e6:.1f}ms "
                  f"opTime={totals['opTimeNs'] / 1e6:.1f}ms "
                  f"rows={totals['numOutputRows']} "
                  f"shuffleBytes={totals['shuffleBytesWritten']} "
                  f"spilledBytes={rec.get('spilled_bytes', 0)} "
                  f"oomRetries={rec.get('oom_retries', 0)}")
        out = "\n".join(body + [footer])
        print(out)
        return out

    def __repr__(self):
        cols = ", ".join(f"{n}: {t}" for n, t in self.plan.schema)
        return f"DataFrame[{cols}]"


def _metrics_tree_lines(node: TpuExec, metrics: Dict, level: str,
                        indent: int = 0) -> List[str]:
    """Physical tree lines with per-operator metric annotations,
    filtered by the configured metrics level."""
    from ..obs.registry import level_allows
    line = "  " * indent + "* " + node.node_description()
    m = metrics.get(node.exec_id, {})
    parts = []
    for name in sorted(m):
        met = m[name]
        if not level_allows(level, met.level):
            continue
        if met.unit == "ns":
            parts.append(f"{name}={met.value / 1e6:.1f}ms")
        else:
            parts.append(f"{name}={met.value}{met.unit}")
    if parts:
        line += "  [" + ", ".join(parts) + "]"
    lines = [line]
    for c in node.children:
        if isinstance(c, TpuExec):
            lines.extend(_metrics_tree_lines(c, metrics, level,
                                             indent + 1))
    return lines


class GroupedData:
    def __init__(self, df: DataFrame, keys: List[Expression]):
        self.df = df
        self.keys = keys

    def agg(self, *aggs) -> DataFrame:
        pairs = []
        for i, a in enumerate(aggs):
            if isinstance(a, Alias):
                pairs.append((a.children[0], a.name))
            else:
                pairs.append((a, output_name(a, len(self.keys) + i)))
        return DataFrame(self.df.session,
                         L.Aggregate(self.df.plan, self.keys, pairs))

    def count(self) -> DataFrame:
        return self.agg(Alias(CountStar(), "count"))

    def _simple(self, fn_cls, cols) -> DataFrame:
        return self.agg(*[Alias(fn_cls(_to_expr(c)), f"{fn_cls.name}({c})")
                          for c in cols])

    def sum(self, *cols) -> DataFrame:
        return self._simple(Sum, cols)

    def min(self, *cols) -> DataFrame:
        return self._simple(Min, cols)

    def max(self, *cols) -> DataFrame:
        return self._simple(Max, cols)

    def avg(self, *cols) -> DataFrame:
        return self._simple(Average, cols)

    mean = avg
