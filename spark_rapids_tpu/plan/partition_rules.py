"""Declarative plan-property -> PartitionSpec rules for the mesh lane.

The SPMD stage executor (plan/mesh_executor.py) feeds every stage
program a tuple of concrete inputs: host-materialized leaf stacks and
device-resident outputs of earlier stages. Each input needs a
``PartitionSpec`` twice — once as the ``NamedSharding`` it is placed
with (``jax.device_put`` / ``with_sharding_constraint``) and once as
the ``shard_map`` in_spec that splits it across the mesh. Instead of
hard-coding that mapping per operator, this module matches each
input's *rule path* (the ``/``-joined class names from the stage root
down to the input node, e.g.
``HashAggregateExec/ShuffleExchangeExec``) against an ordered regex
rule table, first match wins — the same shape as the flax-ecosystem
``match_partition_rules`` helpers that map parameter path regexes to
PartitionSpecs for pjit.

Default table:

* anything under a ``BroadcastExchangeExec`` is **replicated**
  (``P()``): the broadcast build side is placed whole on every device,
  so the in-program ``all_gather`` disappears;
* everything else rides the data axis (``P(axis)``): stacked per-shard
  batches with the leading shard dim split across the mesh.

``srt.mesh.partitionRules`` prepends user rules
(``"regex=data;regex=replicated"``) — an escape hatch to pin a
misbehaving input without editing planner code.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import DATA_AXIS

#: rule table entry: (compiled regex, PartitionSpec)
Rule = Tuple["re.Pattern", P]


def default_rules(axis: str = DATA_AXIS) -> List[Rule]:
    """The built-in table. Order matters: first match wins."""
    return [
        (re.compile(r".*BroadcastExchangeExec(/.*)?$"), P()),
        (re.compile(r".*"), P(axis)),
    ]


def parse_rules(text: str, axis: str = DATA_AXIS) -> List[Rule]:
    """Parse ``srt.mesh.partitionRules``: ``;``-separated
    ``regex=data|replicated`` clauses, prepended to the defaults.
    Malformed clauses raise ValueError at plan time (never mid-trace).
    """
    rules: List[Rule] = []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(
                f"partition rule needs regex=placement — {clause!r}")
        pat, _, placement = clause.rpartition("=")
        placement = placement.strip().lower()
        if placement in ("data", "sharded", axis):
            spec = P(axis)
        elif placement in ("replicated", "replicate", "full"):
            spec = P()
        else:
            raise ValueError(
                f"unknown placement {placement!r} in {clause!r} "
                f"(want data|replicated)")
        rules.append((re.compile(pat.strip()), spec))
    return rules + default_rules(axis)


def match_partition_rules(rules: Sequence[Rule], path: str) -> P:
    """First-match-wins lookup of ``path`` in the rule table."""
    for pat, spec in rules:
        if pat.search(path):
            return spec
    return P(DATA_AXIS)


def rule_path(parent_path: str, node) -> str:
    """Extend a rule path by one plan node (class name)."""
    name = type(node).__name__
    return f"{parent_path}/{name}" if parent_path else name


def is_replicated(spec: P) -> bool:
    """True when the spec shards over no axis (full copy per device)."""
    return not any(ax is not None for ax in tuple(spec))


def sharding_for(mesh: Mesh, spec: P) -> NamedSharding:
    """NamedSharding placing a stacked (or replicated) input tree.

    The leading dim of a stacked tree is the shard dim; trailing dims
    are always replicated, so a rank-polymorphic leaf sharding must be
    minted per leaf — callers go through :func:`put_tree`.
    """
    return NamedSharding(mesh, spec)


def put_tree(tree, mesh: Mesh, spec: P):
    """``device_put`` every leaf of ``tree`` with ``spec`` padded to
    the leaf's rank (leading shard dim split, trailing dims
    replicated). Replicated specs place the full tree per device."""
    import jax

    def _put(x):
        if is_replicated(spec):
            s = NamedSharding(mesh, P())
        else:
            pad = (None,) * max(getattr(x, "ndim", 1) - len(tuple(spec)),
                                0)
            s = NamedSharding(mesh, P(*tuple(spec), *pad))
        return jax.device_put(x, s)
    return jax.tree_util.tree_map(_put, tree)


def constrain_tree(tree, mesh: Mesh, spec: P):
    """``with_sharding_constraint`` analogue of :func:`put_tree`, used
    INSIDE the stage program's jit (outside its shard_map): pins each
    stage input to the sharding the partition rule assigned, so a
    stage output handed device-resident to its consumer is consumed
    in place and anything else is resharded by XLA instead of raising.
    Outside a trace (eager debugging) it degrades to device_put."""
    import jax

    def _pin(x):
        if is_replicated(spec):
            s = NamedSharding(mesh, P())
        else:
            pad = (None,) * max(getattr(x, "ndim", 1) - len(tuple(spec)),
                                0)
            s = NamedSharding(mesh, P(*tuple(spec), *pad))
        try:
            return jax.lax.with_sharding_constraint(x, s)
        except Exception:
            return jax.device_put(x, s)
    return jax.tree_util.tree_map(_pin, tree)


def spec_signature(spec: P) -> Tuple:
    """Hashable form of a spec for structural program keys."""
    return tuple("*" if ax is None else ax for ax in tuple(spec))
