"""Rule registry + tag-then-convert driver.

Rebuild of GpuOverrides.scala (SURVEY §2.2, 4668 LoC): a registry of
expression rules and exec rules, the wrap/tag pass (meta.py), and the
conversion of tagged logical trees into mixed TPU/CPU physical trees
with transitions at the seams (GpuTransitionOverrides role).

Where the reference registers ~215 expression rules mapping Catalyst
Expressions to Gpu* implementations, our frontend expressions ARE the
TPU implementations, so an expression rule here carries only the
support metadata: TypeSig + extra plan-time checks. Fallback maps the
expression to the CPU interpreter (cpu_eval.py) instead.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Type

from ..columnar import dtypes as dt
from ..conf import (BROADCAST_THRESHOLD_ROWS, EXCHANGE_ENABLED, EXPLAIN,
                    FUSION_DONATE, FUSION_ENABLED, FUSION_EXCLUDE_EXECS,
                    FUSION_FINAL_AGG, FUSION_JOINS, PALLAS_ENABLED,
                    PALLAS_GROUP_MAX_CAPACITY, PALLAS_GROUPED_ENABLED,
                    PIPELINE_ENABLED, SHUFFLE_PARTITIONS, SQL_ENABLED,
                    SrtConf, active_conf)
from ..exec.aggregate import HashAggregateExec
from ..exec.base import TpuExec
from ..exec.basic import (BatchScanExec, CoalesceBatchesExec, ExpandExec,
                          FilterExec, LocalLimitExec, ProjectExec, RangeExec,
                          UnionExec)
from ..exec.join import ShuffledHashJoinExec
from ..exec.sort import SortExec, SortOrder, TopNExec
from ..expr import aggregates as Agg
from ..expr import arithmetic as A
from ..expr import cast as C
from ..expr import conditional as Cond
from ..expr import core as E
from ..expr import datetime as D
from ..expr import hashing as H
from ..expr import mathfns as M
from ..expr import predicates as P
from ..expr import strings as S
from . import cpu_eval, typechecks as ts
from .logical import (Aggregate, Expand, Filter, Join, Limit, LocalRelation,
                      LogicalPlan, Project, Range, Sample, Sort, Union,
                      Window)
from .meta import ExprMeta, PlanMeta
from .transitions import (CpuPhysical, DeviceToHostBridge, HostToDeviceExec)


class ExprRule:
    """Support metadata for one expression class (GpuOverrides.expr)."""

    def __init__(self, cls: Type, sig: ts.TypeSig,
                 extra_tag: Optional[Callable[[ExprMeta], None]] = None,
                 description: str = ""):
        self.cls = cls
        self.sig = sig
        self.extra_tag = extra_tag
        self.description = description or cls.__doc__ or ""

    def tag(self, meta: ExprMeta) -> None:
        for child in meta.expr.children:
            t = child.data_type(meta.schema)
            reason = self.sig.reason_if_unsupported(
                t, f"{type(meta.expr).__name__} input")
            if reason:
                meta.will_not_work_on_tpu(reason)
        if self.extra_tag is not None:
            self.extra_tag(meta)


class ExecRule:
    """Support metadata for one logical-plan class (GpuOverrides.exec)."""

    def __init__(self, cls: Type,
                 tag_fn: Optional[Callable[[PlanMeta], None]] = None,
                 description: str = ""):
        self.cls = cls
        self.tag_fn = tag_fn
        self.description = description

    def tag(self, meta: PlanMeta) -> None:
        if self.tag_fn is not None:
            self.tag_fn(meta)


_EXPR_RULES: Dict[Type, ExprRule] = {}
_EXEC_RULES: Dict[Type, ExecRule] = {}


def expr_rule_for(cls: Type) -> Optional[ExprRule]:
    return _EXPR_RULES.get(cls)


def exec_rule_for(cls: Type) -> Optional[ExecRule]:
    return _EXEC_RULES.get(cls)


def _expr(cls, sig: ts.TypeSig, extra=None):
    _EXPR_RULES[cls] = ExprRule(cls, sig, extra)


# --- expression rules ------------------------------------------------------

_expr(E.ColumnRef, ts.all_basic_128)
_expr(E.Alias, ts.all_basic_128 + ts.TypeSig(ts.ARRAY, ts.STRUCT))


def _register_pandas_udf_rule():
    # vectorized UDFs stay in device plans: the Project conversion
    # extracts them into ArrowEvalPythonExec (GpuExtractPythonUDFs role)
    from ..udf.pandas_udf import PandasUDF
    _expr(PandasUDF, ts.all_basic)


_register_pandas_udf_rule()


def _register_bloom_rule():
    from ..expr.hashing import BloomFilterMightContain
    _expr(BloomFilterMightContain,
          ts.integral + ts.TypeSig(ts.DATE, ts.TIMESTAMP, ts.STRING))


_register_bloom_rule()


def _register_misc_rules():
    # execution-context expressions (expr/misc.py): leaf exprs, no
    # input types to check; eager-only ones are handled by Project
    from ..expr import misc as MX
    for cls in (MX.MonotonicallyIncreasingID, MX.SparkPartitionID,
                MX.InputFileName, MX.InputFileBlockStart,
                MX.InputFileBlockLength, MX.Uuid, MX.RaiseError,
                MX.Version):
        _expr(cls, ts.all_basic)


_register_misc_rules()


def device_type_ok(t: dt.DType) -> Optional[str]:
    """Recursive device support for a column type (TypeSig nested
    checks): arrays/structs of supported types flow through
    project/filter/generate; maps are CPU-only for now."""
    if isinstance(t, dt.ArrayType):
        return device_type_ok(t.element_type)
    if isinstance(t, dt.StructType):
        for _, ft in t.fields:
            reason = device_type_ok(ft)
            if reason:
                return reason
        return None
    if isinstance(t, dt.MapType):
        for part in (t.key_type, t.value_type):
            reason = device_type_ok(part)
            if reason:
                return reason
        return None
    return ts.all_basic_128.reason_if_unsupported(t, "column")


def _tag_literal(meta: ExprMeta):
    t = meta.expr.data_type(meta.schema)
    reason = ts.all_basic_128.reason_if_unsupported(t, "literal")
    if reason:
        meta.will_not_work_on_tpu(reason)


_expr(E.Literal, ts.all_basic_128, _tag_literal)

for _cls in (A.Add, A.Subtract, A.Multiply, A.Divide):
    _expr(_cls, ts.numeric_all)
# mod/div on decimal128 needs >128-bit scale alignment: CPU fallback
for _cls in (A.IntegralDivide, A.Remainder, A.Pmod):
    _expr(_cls, ts.numeric)
for _cls in (A.UnaryMinus, A.UnaryPositive, A.Abs):
    _expr(_cls, ts.numeric_all)
for _cls in (A.Least, A.Greatest):
    # decimal64 reduces on the int64 physical; strings + decimal128
    # fall back to the CPU lane (the If-fold device lane for strings
    # exists but mis-selects on some null patterns — planner-gated off
    # until debugged; the CPU oracle string lane is the active path)
    _expr(_cls, ts.numeric_no_decimal + ts.TypeSig(
        ts.DATE, ts.TIMESTAMP, ts.BOOLEAN, ts.DECIMAL_64))

for _cls in (P.EqualTo, P.LessThan, P.GreaterThan, P.LessThanOrEqual,
             P.GreaterThanOrEqual, P.EqualNullSafe):
    _expr(_cls, ts.comparable + ts.decimal128)
for _cls in (P.And, P.Or, P.Not):
    _expr(_cls, ts.TypeSig(ts.BOOLEAN))
for _cls in (P.IsNull, P.IsNotNull):
    _expr(_cls, ts.all_basic_128)
_expr(P.IsNaN, ts.fp)
_expr(P.InSet, ts.comparable)

for _cls in (Cond.If, Cond.CaseWhen, Cond.Coalesce, Cond.NullIf, Cond.Nvl,
             Cond.Nvl2):
    _expr(_cls, ts.all_basic)


def _tag_cast(meta: ExprMeta):
    try:
        meta.expr.check_supported(meta.schema)
    except TypeError as e:
        meta.will_not_work_on_tpu(f"cast: {e}")


_expr(C.Cast, ts.all_basic_128, _tag_cast)

for _cls in list(cpu_eval._MATH_FNS) + [M.Log, M.Log2, M.Log10, M.Floor,
                                        M.Ceil, M.Pow, M.Atan2, M.Hypot,
                                        M.Round, M.BRound]:
    _expr(_cls, ts.numeric)

for _cls in (S.Length, S.OctetLength, S.Upper, S.Lower, S.Substring,
             S.Concat, S.StartsWith, S.EndsWith, S.Contains, S.StringTrim,
             S.StringTrimLeft, S.StringTrimRight):
    _expr(_cls, ts.TypeSig(ts.STRING))


def _tag_like(meta: ExprMeta):
    for ch in meta.expr.pattern:
        if ch not in ("%", "_") and len(ch.encode("utf-8")) != 1:
            meta.will_not_work_on_tpu(
                "LIKE: multi-byte pattern literals not supported on TPU")
            return


_expr(S.Like, ts.TypeSig(ts.STRING), _tag_like)

for _cls in (S.Reverse, S.Lpad, S.Rpad, S.InitCap, S.ConcatWs,
             S.StringLocate, S.StringRepeat, S.StringReplace,
             S.StringTranslate):
    _expr(_cls, ts.TypeSig(ts.STRING))


def _tag_rlike(meta: ExprMeta):
    """transpile-or-fallback (RegexParser.transpile contract): patterns
    the NFA engine rejects run on CPU via python re."""
    from ..expr.regex import RegexUnsupported, transpile
    try:
        transpile(meta.expr.pattern)
    except RegexUnsupported as e:
        meta.will_not_work_on_tpu(f"rlike: {e}")


def _tag_regexp_extract(meta: ExprMeta):
    from ..expr.regex import RegexUnsupported, check_submatch_supported
    try:
        check_submatch_supported(meta.expr.pattern, meta.expr.group)
    except RegexUnsupported as e:
        meta.will_not_work_on_tpu(f"regexp_extract: {e}")


def _tag_regexp_replace(meta: ExprMeta):
    from ..expr.regex import RegexUnsupported, check_submatch_supported
    if meta.expr._repl_refs:
        meta.will_not_work_on_tpu(
            "regexp_replace: group references in the replacement run "
            "on CPU")
        return
    try:
        check_submatch_supported(meta.expr.pattern, 0)
    except RegexUnsupported as e:
        meta.will_not_work_on_tpu(f"regexp_replace: {e}")


def _register_regex_rules():
    from ..expr import regex as RX
    _EXPR_RULES[RX.RLike] = ExprRule(RX.RLike, ts.TypeSig(ts.STRING),
                                     _tag_rlike)
    # extract/replace run on device via span finding + greedy segment
    # splits (expr/regex.py submatch machinery); patterns outside that
    # envelope tag to CPU `re` (transpile-or-fallback)
    _EXPR_RULES[RX.RegExpExtract] = ExprRule(
        RX.RegExpExtract, ts.TypeSig(ts.STRING), _tag_regexp_extract)
    _EXPR_RULES[RX.RegExpReplace] = ExprRule(
        RX.RegExpReplace, ts.TypeSig(ts.STRING), _tag_regexp_replace)


_register_regex_rules()

# date fields accept timestamps too (micros -> days in _to_days)
for _cls in (D.Year, D.Month, D.DayOfMonth, D.Quarter, D.DayOfWeek,
             D.WeekDay, D.DayOfYear, D.LastDay):
    _expr(_cls, ts.TypeSig(ts.DATE, ts.TIMESTAMP))
for _cls in (D.Hour, D.Minute, D.Second, D.UnixTimestampToSeconds):
    _expr(_cls, ts.TypeSig(ts.TIMESTAMP))
for _cls in (D.DateAdd, D.DateSub, D.DateDiff):
    _expr(_cls, ts.TypeSig(ts.DATE) + ts.integral)
_expr(D.AddMonths, ts.TypeSig(ts.DATE) + ts.integral)
_expr(D.FromUnixTime, ts.integral)

from ..expr import timezone as TZX  # noqa: E402

for _cls in (TZX.FromUTCTimestamp, TZX.ToUTCTimestamp):
    _expr(_cls, ts.TypeSig(ts.TIMESTAMP))
_expr(D.MakeDate, ts.integral)
_expr(D.TruncDate, ts.TypeSig(ts.DATE, ts.STRING))

from ..expr import json as JX  # noqa: E402

_expr(JX.GetJsonObject, ts.TypeSig(ts.STRING))
# from_json/to_json: CPU engine (no device JSON tokenizer yet) — no
# rule registered routes them to cpu_eval

_expr(H.Murmur3Hash, ts.comparable)
_expr(H.XxHash64, ts.comparable)

from ..expr import bitwise as BW  # noqa: E402

for _cls in (BW.BitwiseAnd, BW.BitwiseOr, BW.BitwiseXor, BW.BitwiseNot,
             BW.BitCount):
    _expr(_cls, ts.integral + ts.TypeSig(ts.BOOLEAN))
for _cls in (BW.ShiftLeft, BW.ShiftRight, BW.ShiftRightUnsigned):
    _expr(_cls, ts.integral)
_expr(BW.InterleaveBits, ts.integral)

# --- collections (arrays/structs) ---
from ..expr import collections as CX  # noqa: E402

_nested_ok = ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT)


def _primitive_elements(meta: ExprMeta):
    """Lane-kernel exprs need a primitive (non-string) element type."""
    t = meta.expr.children[0].data_type(meta.schema)
    if isinstance(t, dt.ArrayType) and (t.element_type.is_nested or
                                        t.element_type == dt.STRING):
        meta.will_not_work_on_tpu(
            f"{type(meta.expr).__name__}: element type "
            f"{t.element_type} needs lane lowering not yet on TPU")


_expr(CX.CreateArray, ts.numeric + ts.TypeSig(ts.BOOLEAN, ts.DATE,
                                              ts.TIMESTAMP, ts.NULL))
_expr(CX.Size, _nested_ok)
_expr(CX.GetArrayItem, _nested_ok)
_expr(CX.ElementAt, _nested_ok)
_expr(CX.ArrayContains, _nested_ok, _primitive_elements)
_expr(CX.ArrayMin, _nested_ok, _primitive_elements)
_expr(CX.ArrayMax, _nested_ok, _primitive_elements)
_expr(CX.SortArray, _nested_ok, _primitive_elements)
_expr(CX.CreateNamedStruct, ts.all_basic)
_expr(CX.GetStructField, ts.TypeSig(ts.STRUCT))
_expr(CX.ArrayDistinct, _nested_ok, _primitive_elements)
_expr(CX.ArrayUnion, _nested_ok, _primitive_elements)
_expr(CX.ArrayIntersect, _nested_ok, _primitive_elements)
_expr(CX.ArrayExcept, _nested_ok, _primitive_elements)
_expr(CX.ArraysOverlap, _nested_ok, _primitive_elements)
_expr(CX.ArrayRemove, _nested_ok, _primitive_elements)
_expr(CX.ArrayPosition, _nested_ok, _primitive_elements)
_expr(CX.Slice, _nested_ok, _primitive_elements)
_expr(CX.ArrayReverse, _nested_ok, _primitive_elements)


def _tag_array_repeat(meta: ExprMeta):
    from ..expr.core import Literal
    if not isinstance(meta.expr.children[1], Literal):
        meta.will_not_work_on_tpu(
            "array_repeat: non-literal count needs dynamic list "
            "extents (static-shape device lowering); runs on CPU")
    t = meta.expr.children[0].data_type(meta.schema)
    if t.is_nested or t == dt.STRING:
        meta.will_not_work_on_tpu(
            f"array_repeat of {t} needs lane lowering not yet on TPU")


_expr(CX.ArrayRepeat, ts.all_basic + ts.TypeSig(ts.ARRAY),
      _tag_array_repeat)


def _cpu_only_collection(meta: ExprMeta):
    meta.will_not_work_on_tpu(
        f"{type(meta.expr).__name__}: ragged/nested lane lowering not "
        "yet on TPU; runs on the CPU engine")


def _tag_zip_with(meta: ExprMeta):
    # lane evaluation binds the lambda vars as primitive element lanes;
    # the lambda RESULT must be primitive too (the repack builds a
    # flat ColumnVector child)
    for child in meta.expr.children[:2]:
        t = child.data_type(meta.schema)
        et = t.element_type if isinstance(t, dt.ArrayType) else t
        if et.is_nested or et == dt.STRING:
            meta.will_not_work_on_tpu(
                f"zip_with over {et} elements needs non-primitive lane "
                "lowering; runs on CPU")
    out_t = meta.expr.data_type(meta.schema)  # binds lambda var dtypes
    rt = out_t.element_type if isinstance(out_t, dt.ArrayType) else out_t
    if rt.is_nested or rt == dt.STRING:
        meta.will_not_work_on_tpu(
            f"zip_with producing {rt} needs non-primitive lane "
            "lowering; runs on CPU")


_expr(CX.Flatten, ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT, ts.MAP),
      None)
_expr(CX.ArraysZip,
      ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT, ts.MAP), None)
_expr(CX.ArrayJoin,
      ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT, ts.MAP), None)
_expr(CX.ZipWith,
      ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT, ts.MAP),
      _tag_zip_with)
_expr(CX.MapConcat,
      ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT, ts.MAP),
      _cpu_only_collection)


# --- higher-order functions + maps ---
from ..expr import higher_order as HO  # noqa: E402

_hof_ok = ts.all_basic + ts.TypeSig(ts.ARRAY, ts.STRUCT, ts.MAP)


def _lambda_primitive_elements(meta: ExprMeta):
    """Lane-lowered lambdas need primitive (non-string, non-nested)
    element/key/value types on device; everything else falls back
    (the reference runs these through cuDF's list lowering —
    higherOrderFunctions.scala TypeSigs gate similarly)."""
    parts = []
    for child in meta.expr.children:
        t = child.data_type(meta.schema)
        if isinstance(t, dt.MapType):
            parts += [t.key_type, t.value_type]
        elif isinstance(t, dt.ArrayType):
            parts.append(t.element_type)
    for p in parts:
        if p.is_nested or p == dt.STRING:
            meta.will_not_work_on_tpu(
                f"{type(meta.expr).__name__}: element type {p} needs "
                "lane lowering not yet on TPU")
    # lambda RESULT must also be a primitive lane type
    from ..expr.higher_order import (ArrayFilter, ArrayTransform,
                                     MapFilter, TransformKeys,
                                     TransformValues)
    if isinstance(meta.expr, (ArrayTransform, TransformKeys,
                              TransformValues)):
        rt = meta.expr.children[1].data_type(meta.schema)
        if rt.is_nested or rt == dt.STRING:
            meta.will_not_work_on_tpu(
                f"{type(meta.expr).__name__}: lambda result type {rt} "
                "needs lane lowering not yet on TPU")


def _no_outer_refs_in_aggregate(meta: ExprMeta):
    from ..expr.higher_order import _outer_refs
    expr: HO.ArrayAggregate = meta.expr
    for body in expr._bodies():
        if _outer_refs(body, expr.lambda_vars):
            meta.will_not_work_on_tpu(
                "aggregate() lambda referencing outer columns runs on "
                "CPU (scan-carried outer state not lowered)")
            return
    _lambda_primitive_elements(meta)


_expr(HO.LambdaVariable, ts.all_basic)
_expr(HO.ArrayTransform, _hof_ok, _lambda_primitive_elements)
_expr(HO.ArrayExists, _hof_ok, _lambda_primitive_elements)
_expr(HO.ArrayForAll, _hof_ok, _lambda_primitive_elements)
_expr(HO.ArrayFilter, _hof_ok, _lambda_primitive_elements)
_expr(HO.ArrayAggregate, _hof_ok, _no_outer_refs_in_aggregate)
_expr(HO.MapKeys, ts.TypeSig(ts.MAP))
_expr(HO.MapValues, ts.TypeSig(ts.MAP))
_expr(HO.MapEntries, ts.TypeSig(ts.MAP))
_expr(HO.GetMapValue, ts.TypeSig(ts.MAP) + ts.all_basic,
      _lambda_primitive_elements)
_expr(HO.MapContainsKey, ts.TypeSig(ts.MAP) + ts.all_basic,
      _lambda_primitive_elements)
_expr(HO.TransformValues, ts.TypeSig(ts.MAP), _lambda_primitive_elements)
_expr(HO.TransformKeys, ts.TypeSig(ts.MAP), _lambda_primitive_elements)
_expr(HO.MapFilter, ts.TypeSig(ts.MAP), _lambda_primitive_elements)
_expr(HO.CreateMap, ts.numeric + ts.TypeSig(ts.BOOLEAN, ts.DATE,
                                            ts.TIMESTAMP))
_expr(HO.MapFromArrays, ts.TypeSig(ts.ARRAY), _lambda_primitive_elements)


def _tag_explode(meta: ExprMeta):
    t = meta.expr.children[0].data_type(meta.schema)
    if not isinstance(t, dt.ArrayType):
        meta.will_not_work_on_tpu(f"explode of {t} not supported on TPU")


_expr(CX.Explode, _nested_ok, _tag_explode)

for _cls in (Agg.First, Agg.Last):
    _expr(_cls, ts.comparable)
# collect_list/set build ListColumn states on device; set dedupe sorts
# elements, so string sets stay on CPU (char-buffer churn)
_expr(Agg.CollectList, ts.numeric + ts.TypeSig(ts.BOOLEAN, ts.DATE,
                                               ts.TIMESTAMP, ts.STRING))
_expr(Agg.CollectSet, ts.numeric + ts.TypeSig(ts.BOOLEAN, ts.DATE,
                                              ts.TIMESTAMP))
for _cls in (Agg.Count, Agg.CountStar):
    _expr(_cls, ts.comparable + ts.decimal128)
# sum/avg on decimal128 run on the two-limb segmented accumulator
# (expr/aggregates.py _Decimal128SumMixin); variance family stays
# double-only like the reference's GpuM2
for _cls in (Agg.Sum, Agg.Average):
    _expr(_cls, ts.numeric_all)
for _cls in (Agg.VariancePop, Agg.VarianceSamp,
             Agg.StddevPop, Agg.StddevSamp):
    _expr(_cls, ts.numeric)
# t-digest sketch states (ListColumn centroids) on device; exact
# Percentile remains CPU-only (not decomposable into bounded states)
_expr(Agg.ApproxPercentile, ts.numeric)
# min/max cover strings via sort-rank selection (expr/aggregates.py
# _string_reduce)
for _cls in (Agg.Min, Agg.Max):
    _expr(_cls, ts.numeric_all + ts.TypeSig(ts.BOOLEAN, ts.DATE,
                                            ts.TIMESTAMP, ts.STRING))


# --- exec rules ------------------------------------------------------------

_TPU_JOIN_TYPES = ("inner", "left_outer", "right_outer", "left_semi",
                   "left_anti", "full_outer", "cross")


def _tag_join(meta: PlanMeta):
    plan: Join = meta.plan
    if plan.join_type not in _TPU_JOIN_TYPES:
        meta.will_not_work_on_tpu(
            f"join type {plan.join_type} not supported on TPU yet")
    if plan.condition is not None and plan.join_type not in ("inner",
                                                            "cross"):
        # residual conditions on outer/semi/anti change match semantics
        # (not merely filter output) — CPU engine handles those
        meta.will_not_work_on_tpu(
            f"join residual condition on {plan.join_type} not supported "
            "on TPU yet")
    if not plan.left_keys and plan.join_type not in ("inner", "cross"):
        meta.will_not_work_on_tpu(
            f"keyless {plan.join_type} join not supported on TPU yet")


def _wide_decimal(t: dt.DType) -> bool:
    return isinstance(t, dt.DecimalType) and t.is_wide


def _tag_agg(meta: PlanMeta):
    plan: Aggregate = meta.plan
    in_schema = plan.children[0].schema
    for e in plan.group_exprs:
        t = e.data_type(in_schema)
        if t.is_nested:
            meta.will_not_work_on_tpu(
                f"group-by key of type {t} not supported on TPU yet")
        if _wide_decimal(t):
            meta.will_not_work_on_tpu(
                "group-by key of type decimal128 not supported on TPU "
                "yet (two-limb sort keys)")


def _tag_file_scan(meta: PlanMeta):
    from ..io.scan import FileScan
    plan: FileScan = meta.plan
    for name, t in plan.schema:
        reason = device_type_ok(t)
        if reason:
            meta.will_not_work_on_tpu(f"scan column {name}: {reason}")


def _no_nested_inputs(what: str):
    """Execs whose kernels concat/partition/sort batches don't take
    nested payload columns yet (the reference gates the same surface
    per-op via TypeSig; GpuHashJoin/GpuSortExec nested support)."""
    def tag(meta: PlanMeta):
        for c in meta.plan.children:
            for name, t in c.schema:
                if t.is_nested:
                    meta.will_not_work_on_tpu(
                        f"{what}: nested column {name} ({t}) not "
                        "supported on TPU yet")
                    return
    return tag


def _tag_sort(meta: PlanMeta):
    _no_nested_inputs("sort")(meta)
    plan = meta.plan
    in_schema = plan.children[0].schema
    for f in plan.order:
        if _wide_decimal(f.expr.data_type(in_schema)):
            meta.will_not_work_on_tpu(
                "sort key of type decimal128 not supported on TPU yet "
                "(two-limb sort keys)")
            return


def _tag_window(meta: PlanMeta):
    from ..expr.window import (Lag, Lead, DenseRank, NTile, PercentRank,
                               Rank, RowNumber)
    plan: Window = meta.plan
    in_schema = plan.children[0].schema
    supported_rank = (RowNumber, Rank, DenseRank, PercentRank, NTile,
                      Lead, Lag)
    spec0 = plan.window_exprs[0][0].spec if plan.window_exprs else None
    if spec0 is not None:
        key_exprs = list(spec0.partition_by) + \
            [o.expr for o in spec0.order_fields]
        for e in key_exprs:
            if _wide_decimal(e.data_type(in_schema)):
                meta.will_not_work_on_tpu(
                    "window partition/order key of type decimal128 not "
                    "supported on TPU yet")
                return
    for we, name in plan.window_exprs:
        fn = we.func
        if isinstance(fn, supported_rank):
            continue
        if isinstance(fn, (Agg.Sum, Agg.Count, Agg.CountStar, Agg.Average)):
            out_t = fn.data_type(in_schema) \
                if not isinstance(fn, Agg.CountStar) else dt.INT64
            in_wide = any(_wide_decimal(c.data_type(in_schema))
                          for c in fn.children)
            if in_wide or _wide_decimal(out_t):
                meta.will_not_work_on_tpu(
                    f"window {name}: decimal128 aggregation windows "
                    "not on TPU yet")
                continue
        elif isinstance(fn, (Agg.Min, Agg.Max)):
            t0 = fn.children[0].data_type(in_schema) if fn.children else None
            if t0 == dt.STRING:
                meta.will_not_work_on_tpu(
                    f"window {name}: string min/max not on TPU yet")
                continue
            if t0 is not None and _wide_decimal(t0):
                meta.will_not_work_on_tpu(
                    f"window {name}: decimal128 aggregation windows "
                    "not on TPU yet")
                continue
        else:
            meta.will_not_work_on_tpu(
                f"window function {type(fn).__name__} not on TPU yet")
            continue
        frame = we.spec.frame
        if frame is not None and not frame.row_based and not (
                frame.is_running or frame.is_unbounded):
            # bounded RANGE frames: one numeric/date/timestamp order key
            # (binary-searchable values; exec/window.py _range_sliding)
            ofs = we.spec.order_fields
            kt = ofs[0].expr.data_type(in_schema) if len(ofs) == 1 else None
            key_ok = (kt is not None and not _wide_decimal(kt) and (
                kt.is_numeric or
                isinstance(kt, (dt.DateType, dt.TimestampType))))
            if not key_ok:
                meta.will_not_work_on_tpu(
                    f"window {name}: RANGE frames need a single "
                    "numeric/date order key on TPU")
        if frame is not None and frame.row_based and \
                isinstance(fn, (Agg.Min, Agg.Max)) and \
                not (frame.is_running or frame.is_unbounded) and \
                (frame.lo is None or frame.hi is None):
            meta.will_not_work_on_tpu(
                f"window {name}: min/max sliding frames need bounded "
                "ROWS offsets")


def _tag_join_all(meta: PlanMeta):
    _tag_join(meta)
    _no_nested_inputs("join")(meta)
    plan: Join = meta.plan
    lschema = plan.children[0].schema
    rschema = plan.children[1].schema
    for e in plan.left_keys:
        if _wide_decimal(e.data_type(lschema)):
            meta.will_not_work_on_tpu(
                "join key of type decimal128 not supported on TPU yet "
                "(two-limb hash keys)")
            return
    for e in plan.right_keys:
        if _wide_decimal(e.data_type(rschema)):
            meta.will_not_work_on_tpu(
                "join key of type decimal128 not supported on TPU yet "
                "(two-limb hash keys)")
            return


def _register_exec_rules():
    from ..cache import CachedRelation
    from ..io.scan import FileScan
    from .logical import Generate
    _EXEC_RULES[CachedRelation] = ExecRule(CachedRelation)
    _EXEC_RULES.update({
        LocalRelation: ExecRule(LocalRelation),
        Range: ExecRule(Range),
        Project: ExecRule(Project),
        Filter: ExecRule(Filter),
        Limit: ExecRule(Limit),
        Union: ExecRule(Union, _no_nested_inputs("union")),
        Expand: ExecRule(Expand, _no_nested_inputs("expand")),
        Sort: ExecRule(Sort, _tag_sort),
        Sample: ExecRule(Sample),
        Aggregate: ExecRule(Aggregate, _tag_agg),
        Join: ExecRule(Join, _tag_join_all),
        Window: ExecRule(Window, _tag_window),
        FileScan: ExecRule(FileScan, _tag_file_scan),
        Generate: ExecRule(Generate),
    })


_register_exec_rules()


# --- conversion ------------------------------------------------------------

def _build_tpu_exec(plan: LogicalPlan, children: List[TpuExec],
                    conf: SrtConf) -> TpuExec:
    from ..cache import CachedRelation
    from ..io.scan import FileScan, FileSourceScanExec
    if isinstance(plan, CachedRelation):
        return BatchScanExec(plan.batches(), plan.schema)
    if isinstance(plan, FileScan):
        return FileSourceScanExec(plan)
    if isinstance(plan, (LocalRelation, Range)) :
        # host-resident leaves enter the device through the transition
        return HostToDeviceExec(CpuPhysical(plan, []))
    if isinstance(plan, Sample):
        from ..exec.basic import SampleExec
        return SampleExec(children[0], plan.fraction, plan.seed)
    if isinstance(plan, Project):
        from ..udf.pandas_udf import extract_pandas_udfs
        exprs, pyudfs = extract_pandas_udfs(plan.exprs)
        if pyudfs:
            # GpuExtractPythonUDFs role: UDFs evaluate in a pooled
            # python worker between the child and the projection
            from ..exec.python_exec import ArrowEvalPythonExec
            return ProjectExec(
                ArrowEvalPythonExec(children[0], pyudfs), exprs)
        return ProjectExec(children[0], plan.exprs)
    if isinstance(plan, Filter):
        return FilterExec(children[0], plan.condition)
    if isinstance(plan, Limit):
        return LocalLimitExec(children[0], plan.n)
    if isinstance(plan, Union):
        return UnionExec(*children)
    if isinstance(plan, Expand):
        return ExpandExec(children[0], plan.projections, plan.names)
    if isinstance(plan, Sort):
        return SortExec(children[0],
                        [SortOrder(o.expr, o.ascending, o.nulls_first)
                         for o in plan.order],
                        global_sort=plan.is_global)
    if isinstance(plan, Aggregate):
        # staged (GpuAggregateExec partial -> exchange -> final); the
        # ensure_distribution pass places the exchange between them.
        # collect_list/set carry ListColumn states the exchange
        # partitioner doesn't pack yet -> single-stage COMPLETE
        from ..exec.aggregate import COMPLETE, FINAL, PARTIAL

        def _single_stage(fn) -> bool:
            # list states shuffle via the packed child-plane layout
            # (parallel/partition.py), but only for PRIMITIVE elements;
            # string/nested-element collects stay single-stage
            if isinstance(fn, (Agg.CollectList, Agg.ApproxPercentile)):
                if isinstance(fn, Agg.ApproxPercentile):
                    return False
                t = fn.children[0].data_type(plan.children[0].schema)
                return t == dt.STRING or t.is_nested or \
                    (isinstance(t, dt.DecimalType) and t.is_wide)
            return False
        if any(_single_stage(fn) for fn, _ in plan.agg_exprs):
            return HashAggregateExec(children[0], plan.group_exprs,
                                     plan.agg_exprs, mode=COMPLETE)
        partial = HashAggregateExec(children[0], plan.group_exprs,
                                    plan.agg_exprs, mode=PARTIAL)
        return HashAggregateExec(partial, plan.group_exprs, plan.agg_exprs,
                                 mode=FINAL,
                                 input_schema=plan.children[0].schema)
    if isinstance(plan, Window):
        from ..conf import WINDOW_BATCHED_RUNNING
        from ..exec.window import (BatchedRunningWindowExec, WindowExec,
                                   running_compatible)
        in_schema = plan.children[0].schema
        if conf.get(WINDOW_BATCHED_RUNNING) and \
                running_compatible(plan.window_exprs, in_schema):
            # running-only windows stream batch-at-a-time over a sorted
            # child with carried state (GpuRunningWindowExec role)
            spec = plan.window_exprs[0][0].spec
            orders = ([SortOrder(e, True, True)
                       for e in spec.partition_by] +
                      [SortOrder(o.expr, o.ascending, o.nulls_first)
                       for o in spec.order_fields])
            sorted_child = SortExec(children[0], orders)
            return BatchedRunningWindowExec(sorted_child,
                                            plan.window_exprs)
        return WindowExec(children[0], plan.window_exprs)
    from .logical import Generate
    if isinstance(plan, Generate):
        from ..exec.generate import GenerateExec
        return GenerateExec(children[0], plan.generator,
                            plan.element_name, plan.pos_name)
    if isinstance(plan, Join):
        return _build_join(plan, children, conf)
    raise NotImplementedError(type(plan).__name__)


def _coerce_join_keys(plan: Join):
    """Join keys must share a dtype across sides: the partitioner hashes
    key *values*, and murmur3 is width-sensitive (Spark's analyzer
    inserts these casts before planning)."""
    from ..expr.conditional import _common_type
    ls, rs = plan.children[0].schema, plan.children[1].schema
    lk, rk = [], []
    for l, r in zip(plan.left_keys, plan.right_keys):
        lt, rt = l.data_type(ls), r.data_type(rs)
        if lt == rt:
            lk.append(l)
            rk.append(r)
            continue
        ct = _common_type([lt, rt])
        lk.append(l if lt == ct else C.Cast(l, ct))
        rk.append(r if rt == ct else C.Cast(r, ct))
    return lk, rk


def _join_cls(plan: Join, build: str, conf: SrtConf):
    """Broadcast when the build side's estimated rows are small
    (spark.sql.autoBroadcastJoinThreshold role)."""
    from .cost import estimate_rows
    build_plan = plan.children[1] if build == "right" else plan.children[0]
    if estimate_rows(build_plan) <= conf.get(BROADCAST_THRESHOLD_ROWS):
        from ..exec.join import BroadcastHashJoinExec
        return BroadcastHashJoinExec
    return ShuffledHashJoinExec


def _build_join(plan: Join, children: List[TpuExec],
                conf: SrtConf) -> TpuExec:
    from ..exec.nested_loop_join import (BroadcastNestedLoopJoinExec,
                                         CartesianProductExec)
    from .cost import estimate_rows
    left, right = children
    if not plan.left_keys:
        # keyless: cartesian / conditioned nested loop
        if plan.condition is None:
            return CartesianProductExec(left, right)
        return BroadcastNestedLoopJoinExec(left, right, plan.condition,
                                           "inner")
    left_keys, right_keys = _coerce_join_keys(plan)
    if plan.join_type == "full_outer":
        # full outer = left_outer(L,R) UNION null-extended anti(R,L)
        # (both pieces are device-supported; the Union concatenates)
        lo = ShuffledHashJoinExec(left, right, left_keys, right_keys,
                                  join_type="left_outer",
                                  build_side="right")
        anti = ShuffledHashJoinExec(right, left, right_keys, left_keys,
                                    join_type="left_anti",
                                    build_side="right")
        left_schema = plan.children[0].schema
        null_left = [E.Literal(None, t) for _, t in left_schema]
        exprs = ([E.Alias(e, n) for e, (n, _) in
                  zip(null_left, left_schema)] +
                 [E.Alias(E.col(n), n)
                  for n, _ in plan.children[1].schema])
        extended = ProjectExec(anti, exprs)
        return UnionExec(lo, extended)
    build = "left" if plan.join_type == "right_outer" else "right"
    cls = _join_cls(plan, build, conf)
    joined = cls(left, right, left_keys, right_keys,
                 join_type=plan.join_type, build_side=build)
    if plan.condition is not None and plan.join_type == "inner":
        # residual condition = post-join filter (sound for inner)
        return FilterExec(joined, plan.condition)
    return joined


def _to_physical(meta: PlanMeta, conf: SrtConf):
    # TopN fusion: Limit(Sort) both replaceable -> TopNExec
    if (isinstance(meta.plan, Limit) and len(meta.child_plans) == 1
            and isinstance(meta.child_plans[0].plan, Sort)
            and meta.can_this_be_replaced
            and meta.child_plans[0].can_this_be_replaced
            and conf.get(SQL_ENABLED)):
        sort_meta = meta.child_plans[0]
        grandkids = [_to_physical(c, conf)
                     for c in sort_meta.child_plans]
        dev = [c if isinstance(c, TpuExec) else HostToDeviceExec(c)
               for c in grandkids]
        order = [SortOrder(o.expr, o.ascending, o.nulls_first)
                 for o in sort_meta.plan.order]
        return TopNExec(dev[0], order, meta.plan.n)
    children = [_to_physical(c, conf) for c in meta.child_plans]
    if meta.can_this_be_replaced and conf.get(SQL_ENABLED):
        dev = [c if isinstance(c, TpuExec) else HostToDeviceExec(c)
               for c in children]
        return _build_tpu_exec(meta.plan, dev, conf)
    host = [c if not isinstance(c, TpuExec) else DeviceToHostBridge(c)
            for c in children]
    return CpuPhysical(meta.plan, host)


# --- EnsureRequirements: place exchanges ----------------------------------

def _pin_partitioning(node: TpuExec) -> None:
    """Disable partition-count-changing AQE transforms in ``node`` and
    every descendant down to (and including) the first exchange — a
    partition-wise parent depends on the advertised layout."""
    from ..exec.exchange import ShuffleExchangeExec
    node.preserve_partitioning = True
    if isinstance(node, ShuffleExchangeExec):
        return
    for c in node.children:
        _pin_partitioning(c)


def ensure_distribution(node: TpuExec, conf: SrtConf) -> TpuExec:
    """Insert shuffle/broadcast exchanges wherever a child's output
    partitioning does not satisfy its parent's required distribution
    (Spark EnsureRequirements; reference stages are glued the same way —
    GpuShuffleExchangeExecBase between partial and final aggregates,
    co-partitioning for GpuShuffledHashJoinExec, GpuRangePartitioner
    under global sort)."""
    from ..exec.exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from .distribution import (AllTuples, BroadcastDistribution,
                               ClusteredDistribution, OrderedDistribution)
    # recurse into device children (and through host islands)
    node.children = [ensure_distribution(c, conf) for c in node.children]
    if hasattr(node, "cpu_child"):
        node.cpu_child = _ensure_physical(node.cpu_child, conf)
    if not conf.get(EXCHANGE_ENABLED):
        return node
    reqs = node.required_child_distributions()
    n_parts = conf.get(SHUFFLE_PARTITIONS)
    clustered = [r for r in reqs if isinstance(r, ClusteredDistribution)]
    if len(clustered) > 1:
        # co-partitioning (join): all clustered children must agree on
        # the partition count, so pin it in the requirement
        for r in clustered:
            r.num_partitions = n_parts
    out_children = []
    for child, req in zip(node.children, reqs):
        if child.output_partitioning.satisfies(req):
            # the parent will consume this child partition-wise WITHOUT
            # a re-exchange: AQE transforms inside the child (partition
            # coalescing, adaptive broadcast) must not change its
            # partition count/grouping
            if isinstance(req, ClusteredDistribution):
                _pin_partitioning(child)
            out_children.append(child)
        elif isinstance(req, BroadcastDistribution):
            out_children.append(BroadcastExchangeExec(child))
        elif isinstance(req, AllTuples):
            out_children.append(ShuffleExchangeExec(child, [],
                                                    num_partitions=1))
        elif isinstance(req, ClusteredDistribution):
            out_children.append(ShuffleExchangeExec(
                child, req.exprs, num_partitions=n_parts))
        elif isinstance(req, OrderedDistribution):
            if n_parts > 1:
                out_children.append(ShuffleExchangeExec(
                    child, [], num_partitions=n_parts,
                    sort_orders=req.sort_orders))
            else:
                out_children.append(child)
        else:
            out_children.append(child)
    node.children = out_children
    return node


def _ensure_physical(physical, conf: SrtConf):
    """Walk a mixed host/device physical tree applying
    ensure_distribution to every device island."""
    if isinstance(physical, TpuExec):
        return ensure_distribution(physical, conf)
    if isinstance(physical, DeviceToHostBridge):
        physical.tpu = ensure_distribution(physical.tpu, conf)
        physical.children = [physical.tpu]
        return physical
    if isinstance(physical, CpuPhysical):
        physical.children = [_ensure_physical(c, conf)
                             for c in physical.children]
        return physical
    return physical


def push_down_filters(plan: LogicalPlan) -> None:
    """Filter-over-scan pushdown (ParquetFilters role): the scan prunes
    row groups/files with the translatable conjuncts; the Filter node
    stays, so device-side semantics are unchanged."""
    from ..io.scan import FileScan
    for i, c in enumerate(plan.children):
        push_down_filters(c)
        if isinstance(plan, Filter) and isinstance(c, FileScan) \
                and c.pushed_filter is None:
            plan.children[i] = c.with_pushed_filter(plan.condition)


def prune_scan_columns(plan: LogicalPlan) -> None:
    """ColumnPruning (Spark's rule of the same name): narrow each
    FileScan's schema to the columns referenced between it and the
    nearest column-REPLACING ancestor (Project/Aggregate/Expand). A q6
    over a 16-column lineitem then decodes 4 columns instead of 16 —
    on the host-decode scan path this is the single largest I/O lever.
    Scans are replaced by narrowed COPIES (they're shared across
    DataFrames). CachedRelation prunes the same way — a projection over
    df.cache() decompresses only the referenced column blocks
    (ParquetCachedBatchSerializer selectedAttributes role)."""
    from ..cache import CachedRelation
    from ..io.scan import FileScan

    def node_refs(node: LogicalPlan) -> set:
        refs = set()
        for e in node.expressions():
            refs |= e.references()
        return refs

    def walk(node: LogicalPlan, required) -> None:
        # required: set of column names the PARENT needs from this
        # node's output; None = everything (no boundary seen yet)
        for i, c in enumerate(node.children):
            creq = _child_required(node, c, required)
            if isinstance(c, (FileScan, CachedRelation)):
                if creq is None:
                    continue
                keep = [(n, t) for n, t in c.schema if n in creq]
                if not keep:
                    # count(*)-style: keep one spine column (narrowest)
                    keep = [min(c.schema, key=lambda nt:
                                8 if nt[1].is_nested else
                                4 if nt[1] == dt.STRING else 1)]
                if len(keep) < len(c.schema):
                    node.children[i] = c.with_schema(keep)
                continue
            walk(c, creq)

    def _child_required(node, child, required):
        from .logical import (Aggregate, Expand, Generate, Project,
                              Union, Window)
        if isinstance(node, (Project, Aggregate, Expand)):
            # boundary: output is fully determined by the expressions
            return node_refs(node)
        if isinstance(node, Union):
            # positional semantics: never narrow below a union
            return None
        if required is None:
            return None
        if isinstance(node, (Window, Generate)):
            gen = {n for n, _ in node.schema} - \
                  {n for n, _ in child.schema}
            return (required - gen) | node_refs(node)
        return required | node_refs(node)

    walk(plan, None)


def _force_perfile_for_input_file(plan: LogicalPlan) -> None:
    """InputFileBlockRule (GpuOverrides.scala InputFileBlockRule role):
    input_file_name()/input_file_block_* need a single source file per
    batch, so scans below such expressions must not use the coalescing
    (file-mixing) reader. Marks every FileScan in the subtree."""
    from ..expr.misc import contains_input_file
    from ..io.scan import FileScan

    def mark(node: LogicalPlan) -> None:
        if isinstance(node, FileScan):
            node.options["_reader_override"] = "PERFILE"
        for c in node.children:
            mark(c)

    def walk(node: LogicalPlan) -> None:
        exprs = [e for e, _ in node.expressions_with_schemas()]
        if contains_input_file(exprs):
            mark(node)
        for c in node.children:
            walk(c)

    walk(plan)


def apply_overrides(plan: LogicalPlan, conf: Optional[SrtConf] = None):
    """wrap -> tag -> convert (GpuOverrides.applyWithContext equivalent).

    Returns the physical root: a TpuExec (device result) or a
    CpuPhysical/DeviceToHostBridge (host result).
    """
    conf = conf or active_conf()
    push_down_filters(plan)
    prune_scan_columns(plan)
    _force_perfile_for_input_file(plan)
    meta = PlanMeta(plan)
    meta.tag_for_tpu()
    from .cost import apply_cost_model
    apply_cost_model(meta, conf)
    mode = conf.get(EXPLAIN)
    if mode == "ALL":
        print("\n".join(meta.explain_lines()))
    elif mode == "NOT_ON_TPU":
        lines = meta.explain_lines(only_not_on_tpu=True)
        if lines:
            print("\n".join(lines))
    root = _ensure_physical(_to_physical(meta, conf), conf)
    _count_exchange_consumers(root)
    root = _insert_fusion(root, conf)
    root = _insert_pipeline(plan, root, conf)
    _tag_push(root, conf)
    return root


def _fusion_blocked_exprs(exprs) -> bool:
    """Expressions a fused program cannot reproduce: eager trees (must
    evaluate un-jitted so data-dependent raises reach the caller) and
    partition-context expressions (read ``ctx.partition_id`` / the
    input-file TLS through ``traced_context``, which the fused program
    does not thread)."""
    from ..expr.misc import (InputFileName, MonotonicallyIncreasingID,
                             SparkPartitionID, _InputFileBlock,
                             contains_eager)
    if contains_eager(exprs):
        return True
    ctx_types = (InputFileName, _InputFileBlock, SparkPartitionID,
                 MonotonicallyIncreasingID)

    def walk(e) -> bool:
        if isinstance(e, ctx_types):
            return True
        return any(walk(c) for c in e.children)

    return any(walk(e) for e in exprs)


def _insert_fusion(root, conf: SrtConf):
    """Operator-fusion pass (exec/fused.py): collapse linear
    scan -> filter -> project -> partial-aggregate chains (and their
    filter/project-only prefixes) into one FusedPipelineExec whose
    per-batch compute is a single shared-jit program, so intermediate
    batches never materialize between operators and XLA schedules the
    whole chain as one program.

    Matching is top-down from each chain terminal (a PARTIAL
    HashAggregateExec, else the topmost Filter/Project): consecutive
    Filter/Project stages are absorbed downward until the chain bottoms
    out at a scan; a chain shorter than two stages, or whose ultimate
    source is not a scan, stays unfused. A no-op CoalesceBatchesExec
    (target_rows=None — re-batches to the session default without
    changing boundaries' semantics) does not break the match: it stays
    in place as (part of) the fused node's source subtree and the
    matcher looks through it when checking for the scan.

    Opt-outs: ``srt.exec.fusion.enabled`` kills the pass;
    ``srt.exec.fusion.excludeExecs`` breaks chains at the named
    classes; stages with eager or partition-context expressions never
    fuse (``_fusion_blocked_exprs``); a terminal aggregate eligible for
    the global-agg pallas lane stays unfused so
    ``_pallas_stream_or_none`` keeps its direct Filter-child peek.
    When the grouped pallas lane is fully enabled the fused program
    uses ``_update_pallas`` as its terminal stage instead of the stock
    update — pallas_agg as a fusable terminal.

    Fusion v2 extends the same matcher beyond linear scan chains:

    - **hash-join fusion** (``srt.exec.fusion.joins``): a chain whose
      ultimate source is a hash join wraps the join in a
      FusedHashJoinExec — build+probe and the suffix compile into one
      program per probe batch, while the join node keeps ALL of its
      orchestration (adaptive demotion/skew splits, sub-partitioning,
      bloom, DPP, growth retries). The matcher then keeps walking the
      join's children, so scan chains on the exchanges' map sides
      still fuse. Fusion arms at execute time through the join's
      ``_fusion`` hook, which is what lets plan/adaptive.py decisions
      re-evaluate after adaptive rewrites, never before.
    - **FINAL-aggregate fusion** (``srt.exec.fusion.finalAgg``): a
      FINAL HashAggregateExec whose child chain reaches its shuffle
      exchange through only no-op coalesces and fusable projects is
      armed (``arm_merge_fusion``) so the per-partition concat +
      projection prefix + merge+finalize runs as one program.
    - sort-prefix fusion (``srt.exec.fusion.sort``) needs no planner
      work — exec/sort.py self-arms from the conf at execute time."""
    if not conf.get(FUSION_ENABLED):
        return root
    from ..exec import pallas_agg
    from ..exec.aggregate import FINAL, PARTIAL
    from ..exec.fused import FusedHashJoinExec, FusedPipelineExec
    from ..exec.join import _HashJoinBase
    from ..io.scan import FileSourceScanExec
    excludes = {s.strip() for s in
                conf.get(FUSION_EXCLUDE_EXECS).split(",") if s.strip()}
    pallas_on = conf.get(PALLAS_ENABLED)
    grouped_conf = pallas_on and conf.get(PALLAS_GROUPED_ENABLED)
    donate_conf = conf.get(FUSION_DONATE)
    max_cap = conf.get(PALLAS_GROUP_MAX_CAPACITY)
    join_conf = conf.get(FUSION_JOINS)
    final_conf = conf.get(FUSION_FINAL_AGG)

    def stage_ok(n) -> bool:
        if type(n).__name__ in excludes:
            return False
        if isinstance(n, FilterExec):
            return not _fusion_blocked_exprs([n.condition])
        if isinstance(n, ProjectExec):
            return not _fusion_blocked_exprs(n.exprs)
        return False

    def agg_ok(a) -> bool:
        if type(a).__name__ in excludes or a.mode != PARTIAL or a._eager:
            return False
        if _fusion_blocked_exprs(list(a.group_exprs) +
                                 [fn for fn, _ in a.agg_exprs]):
            return False
        # the global-aggregate pallas lane peeks at the agg's direct
        # Filter child (_pallas_stream_or_none); fusing would steal it
        if a._pallas_gate and pallas_on:
            return False
        return True

    def through_noop_coalesce(n):
        while isinstance(n, CoalesceBatchesExec) and n.target_rows is None:
            n = n.children[0]
        return n

    def join_ok(j) -> bool:
        # a post-join condition or eager key expressions need the
        # unfused host-side evaluation; an already-armed join never
        # re-arms (idempotency)
        return (type(j).__name__ not in excludes
                and j.condition is None
                and j._fusion is None
                and not j._eager_keys())

    def try_fuse(n):
        stages = []
        cur = n
        if isinstance(cur, HashAggregateExec):
            if not agg_ok(cur):
                return n
            stages.append(cur)
            cur = cur.children[0]
        while stage_ok(cur):
            stages.append(cur)
            cur = cur.children[0]
        if not stages:
            return n
        src = through_noop_coalesce(cur)
        stages.reverse()  # application order, bottom-up
        terminal = stages[-1]
        use_pallas = bool(
            isinstance(terminal, HashAggregateExec) and grouped_conf
            and terminal._pallas_grouped_gate
            and pallas_agg.grouped_lane_on()
            and pallas_agg.grouped_kernel_ok())
        if len(stages) >= 2 and isinstance(src, (BatchScanExec,
                                                 FileSourceScanExec)):
            # donation is sound only when the source's buffers are
            # single-use: file scans decode fresh arrays per run;
            # BatchScanExec re-yields the same in-memory arrays on
            # re-runs
            donate = bool(donate_conf
                          and isinstance(src, FileSourceScanExec))
            return FusedPipelineExec(cur, stages, use_pallas=use_pallas,
                                     pallas_max_cap=max_cap,
                                     donate=donate)
        if join_conf and isinstance(src, _HashJoinBase) and join_ok(src):
            # a single suffix stage is already worth it (join+stage is
            # two operators in one program); the no-op coalesce between
            # join and suffix (if any) is dropped — the fused program
            # consumes join pairs directly and re-batching boundaries
            # carry no semantics the suffix observes
            return FusedHashJoinExec(src, stages, use_pallas=use_pallas,
                                     pallas_max_cap=max_cap,
                                     donate=donate_conf)
        return n

    def try_fuse_final(a) -> None:
        if not final_conf or type(a).__name__ in excludes or a._eager \
                or a._merge_fusion is not None:
            return
        if _fusion_blocked_exprs(list(a.group_exprs) +
                                 [fn for fn, _ in a.agg_exprs]):
            return
        from ..exec.exchange import ShuffleExchangeExec
        projs = []
        cur = through_noop_coalesce(a.children[0])
        while isinstance(cur, ProjectExec) and stage_ok(cur):
            projs.append(cur)
            cur = through_noop_coalesce(cur.children[0])
        if not isinstance(cur, ShuffleExchangeExec):
            return
        # arm the fused concat+prefix+merge program and rewire the agg
        # straight onto its exchange (the absorbed coalesce/projects
        # run inside the fused program; projs stay in top-down order)
        a.arm_merge_fusion(projs)
        a.children[0] = cur

    def walk(n):
        if isinstance(n, (HashAggregateExec, FilterExec, ProjectExec)):
            fused = try_fuse(n)
            if fused is not n:
                if isinstance(fused, FusedHashJoinExec):
                    # keep walking below the join — the exchanges' map
                    # sides hold fusable scan chains of their own
                    kids = fused.join.children
                    for i, c in enumerate(kids):
                        kids[i] = walk(c)
                # below a fused scan chain only scan-ish sources remain
                # (scan, or no-op coalesce over scan) — nothing fusable
                return fused
        if isinstance(n, HashAggregateExec) and n.mode == FINAL:
            try_fuse_final(n)
        kids = getattr(n, "children", None)
        if kids:
            for i, c in enumerate(kids):
                kids[i] = walk(c)
        return n

    return walk(root)


def _plan_is_pipeline_safe(plan: LogicalPlan) -> bool:
    """Partition-context expressions — spark_partition_id(),
    monotonically_increasing_id(), input_file_*() — read state the
    consuming thread mutates while iterating (``ctx.partition_id``,
    the input-file TLS), which a background producer running ahead
    would race. Plans holding any of them run synchronously."""
    from ..expr.misc import (InputFileName, MonotonicallyIncreasingID,
                             SparkPartitionID, _InputFileBlock)
    ctx_types = (InputFileName, _InputFileBlock, SparkPartitionID,
                 MonotonicallyIncreasingID)

    def expr_has(e) -> bool:
        if isinstance(e, ctx_types):
            return True
        return any(expr_has(c) for c in e.children)

    def walk(node) -> bool:
        if any(expr_has(e) for e in node.expressions()):
            return False
        return all(walk(c) for c in node.children)

    return walk(plan)


def _insert_pipeline(plan: LogicalPlan, root, conf: SrtConf):
    """Pipelining pass (exec/pipeline.py): wrap every eligible
    FileSourceScanExec in a PrefetchExec (decode overlaps compute) and
    tag exchange instances ``_pipeline_ok`` so their read side / the
    broadcast build drains through a background producer. Exchanges
    are TAGGED rather than wrapped: AQE transforms locate them with
    direct-child isinstance checks that an interposed node would break.
    Scans already forced to the PERFILE reader by an input_file_name()
    ancestor stay synchronous (the expression reads per-batch TLS the
    producer thread would own), and whole plans with partition-context
    expressions opt out via ``_plan_is_pipeline_safe``."""
    if not conf.get(PIPELINE_ENABLED) or not _plan_is_pipeline_safe(plan):
        return root
    from ..exec.exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from ..exec.pipeline import PrefetchExec
    from ..io.scan import FileSourceScanExec

    def walk(n):
        kids = getattr(n, "children", None)
        if kids:
            for i, c in enumerate(kids):
                kids[i] = walk(c)
        if isinstance(n, (ShuffleExchangeExec, BroadcastExchangeExec)):
            n._pipeline_ok = True
        elif isinstance(n, FileSourceScanExec) and \
                n.scan.options.get("_reader_override") != "PERFILE":
            return PrefetchExec(n)
        return n

    return walk(root)


def _tag_push(root, conf: SrtConf) -> None:
    """Push-based-shuffle pass: tag every planned ShuffleExchangeExec
    ``_push_ok`` so its map phase eagerly pushes blocks to the owning
    reducers' endpoints (exec/exchange.py ``_push_route``). Tagged, not
    wrapped, for the same reason as ``_pipeline_ok`` — AQE locates
    exchanges by direct isinstance checks. Range (sort_orders)
    exchanges are tagged too: their partition ownership follows the
    same contiguous arithmetic. Hand-built plans that skip the planner
    opt in by setting the attribute themselves."""
    from ..conf import SHUFFLE_PUSH_ENABLED
    if not conf.get(SHUFFLE_PUSH_ENABLED):
        return
    from ..exec.exchange import ShuffleExchangeExec

    def walk(n) -> None:
        if isinstance(n, ShuffleExchangeExec):
            n._push_ok = True
        for c in getattr(n, "children", []):
            walk(c)

    walk(root)


def _count_exchange_consumers(root) -> None:
    """Count, per ShuffleExchangeExec INSTANCE, how many tree edges
    drain it. Full expansion, no dedup: a subtree shared by the two
    halves of a full-outer union (``_build_join``) really is drained
    twice per run. The exchange frees its shuffle blocks only after
    that many full drains (exec/exchange.py ``_release``)."""
    from ..exec.exchange import ShuffleExchangeExec
    counts: Dict[int, int] = {}
    insts: Dict[int, object] = {}

    def walk(n) -> None:
        if isinstance(n, ShuffleExchangeExec):
            counts[id(n)] = counts.get(id(n), 0) + 1
            insts[id(n)] = n
        for c in getattr(n, "children", []):
            walk(c)

    walk(root)
    for k, x in insts.items():
        x._planned_consumers = counts[k]


def mesh_resident_exchanges(root, conf: Optional[SrtConf] = None) -> set:
    """Planner residency rule for the mesh lane: the set of
    ``ShuffleExchangeExec`` ids (``id(node)``) whose collective is the
    identity on the mesh, because the child's advertised partitioning
    already satisfies the exchange's target placement
    (distribution.mesh_placement_satisfied). The mesh stage executor
    lowers these as device-resident hand-throughs pinned with
    ``with_sharding_constraint`` — whole stage DAGs stay on device
    until a true repartition forces an in-program ``all_to_all``.

    This is the generalization of the old ``_hash_colocated`` special
    case (hash-over-hash only) to range-over-range and
    single-over-single, promoted from the lowering into the planner so
    the decision is visible (MeshResidencyPlanned event) before any
    program compiles. Gated by ``srt.mesh.residency.enabled`` and the
    push-shuffle locality confs the single-box bypass honors — the
    placement contract is the same one.
    """
    from ..conf import (MESH_RESIDENCY, SHUFFLE_PUSH_ENABLED,
                        SHUFFLE_PUSH_LOCAL_BYPASS, active_conf)
    from ..exec.exchange import ShuffleExchangeExec
    from ..obs import events as _events
    from .distribution import mesh_placement_satisfied
    conf = conf or active_conf()
    if not (conf.get(MESH_RESIDENCY) and conf.get(SHUFFLE_PUSH_ENABLED)
            and conf.get(SHUFFLE_PUSH_LOCAL_BYPASS)):
        return set()
    resident: set = set()

    def walk(n) -> None:
        if isinstance(n, ShuffleExchangeExec) and id(n) not in resident:
            child = n.children[0]
            if mesh_placement_satisfied(child.output_partitioning, n):
                resident.add(id(n))
        for c in getattr(n, "children", []):
            walk(c)

    walk(root)
    if resident:
        _events.emit("MeshResidencyPlanned", count=len(resident))
    return resident


def tag_only(plan: LogicalPlan,
             conf: Optional[SrtConf] = None) -> PlanMeta:
    """Tagging pass without conversion (explain-only mode — the
    reference's spark.rapids.sql.mode=explainOnly). Applies the cost
    model too when a conf enables it, so explain output matches what
    apply_overrides would actually plan."""
    meta = PlanMeta(plan)
    meta.tag_for_tpu()
    from .cost import apply_cost_model
    apply_cost_model(meta, conf or active_conf())
    return meta


# --- supported-ops doc-gen (TypeChecks.scala doc generation) ---------------

def generate_supported_ops_doc() -> str:
    """Reference-style per-op support matrices (TypeChecks doc-gen ->
    docs/supported_ops.md): one row per expression, one column per type
    tag. The cells come straight from each registered rule's TypeSig —
    the SAME object the tagging pass enforces at plan time, so the doc
    cannot over-promise relative to the planner."""
    tags = ts.ALL_TAGS
    short = {ts.BOOLEAN: "BOOL", ts.BYTE: "I8", ts.SHORT: "I16",
             ts.INT: "I32", ts.LONG: "I64", ts.FLOAT: "F32",
             ts.DOUBLE: "F64", ts.STRING: "STR", ts.DATE: "DATE",
             ts.TIMESTAMP: "TS", ts.DECIMAL_64: "DEC64",
             ts.DECIMAL_128: "DEC128", ts.NULL: "NULL",
             ts.ARRAY: "ARR", ts.STRUCT: "STRUCT", ts.MAP: "MAP"}
    header = "| Expression | " + " | ".join(short[t] for t in tags) + " |"
    sep = "|---" * (len(tags) + 1) + "|"
    lines = [
        "# Supported ops on TPU", "",
        "Generated from the expression/exec rule registries "
        "(`spark_rapids_tpu/plan/overrides.py`) — do not edit. The "
        "matrices render the exact TypeSig objects the tagging pass "
        "enforces, so plan-time behavior and this document cannot "
        "diverge.", "",
        "`S` = supported input type on device; `NS` = the containing "
        "operator falls back to the CPU engine for that input type.",
        "", "## Expressions", "", header, sep]
    for cls in sorted(_EXPR_RULES, key=lambda c: c.__name__):
        rule = _EXPR_RULES[cls]
        cells = [" S " if t in rule.sig.tags else "NS" for t in tags]
        lines.append(f"| {cls.__name__} | " + " | ".join(cells) + " |")
    lines += [
        "", "## Operators", "",
        "Column types flowing THROUGH an operator follow "
        "`device_type_ok` (all basic types + decimal128; arrays and "
        "structs of those through project/filter/generate; maps on "
        "CPU). Operator-specific key restrictions are tagged at plan "
        "time (e.g. no nested/decimal128 group-by or join keys).", "",
        "| Operator | Notes |", "|---|---|"]
    for cls in sorted(_EXEC_RULES, key=lambda c: c.__name__):
        rule = _EXEC_RULES[cls]
        desc = (rule.description or (cls.__doc__ or "").strip()
                .split("\n")[0])
        lines.append(f"| {cls.__name__} | {desc} |")
    return "\n".join(lines) + "\n"
