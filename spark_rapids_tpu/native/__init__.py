"""ctypes bindings for the tpu-table native host runtime.

Builds native/tputable.cpp with g++ on first import (content-hashed so
rebuilds happen only when the source changes) and exposes:

- lz4_compress / lz4_decompress — LZ4 block codec (shuffle/spill)
- columns_to_rows / rows_to_columns — fixed-width row<->columnar
  conversion (CudfUnsafeRow / RowConversion role)
- HostMemoryPool — aligned slab allocator with alloc-failure signaling
  (HostAlloc / PinnedMemoryPool role)
- direct_write / direct_read — O_DIRECT spill-file transfer (the
  GDS-spill role: bulk spills bypass the page cache; buffered fallback
  when the filesystem refuses O_DIRECT)

SURVEY §2.9: these are the framework's native equivalents of the
reference's external C++/CUDA artifacts.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRCS = [os.path.join(_REPO_ROOT, "native", "tputable.cpp"),
         os.path.join(_REPO_ROOT, "native", "parquet_decode.cpp"),
         os.path.join(_REPO_ROOT, "native", "orc_decode.cpp")]
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")

_LIB = None
_LIB_LOCK = threading.Lock()


def _zstd_link_args():
    """Link zstd however this box provides it: ``-lzstd`` when the dev
    package's unversioned symlink exists, else the runtime soname by
    path (images often ship libzstd.so.1 without zstd-dev; the two
    simple-API symbols we call are ABI-stable)."""
    try:
        out = subprocess.run(["ldconfig", "-p"], capture_output=True,
                             text=True).stdout
    except Exception:
        return ["-lzstd"]
    soname = None
    for line in out.splitlines():
        if "libzstd.so" not in line or "=>" not in line:
            continue
        path = line.split("=>")[-1].strip()
        if path.endswith("libzstd.so"):
            return ["-lzstd"]
        soname = soname or path
    return [soname] if soname else ["-lzstd"]


def _build_lib() -> str:
    h = hashlib.sha256()
    for src in _SRCS:
        with open(src, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()[:16]
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so = os.path.join(_BUILD_DIR, f"libtputable-{digest}.so")
    if not os.path.exists(so):
        tmp = so + ".tmp"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp]
            + _SRCS + ["-lz"] + _zstd_link_args(),
            check=True, capture_output=True)
        os.replace(tmp, so)
    return so


def _lib() -> ctypes.CDLL:
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            lib = ctypes.CDLL(_build_lib())
            u8p = ctypes.POINTER(ctypes.c_uint8)
            lib.slz4_max_compressed_size.restype = ctypes.c_int64
            lib.slz4_max_compressed_size.argtypes = [ctypes.c_int64]
            lib.slz4_compress.restype = ctypes.c_int64
            lib.slz4_compress.argtypes = [u8p, ctypes.c_int64, u8p,
                                          ctypes.c_int64]
            lib.slz4_decompress.restype = ctypes.c_int64
            lib.slz4_decompress.argtypes = [u8p, ctypes.c_int64, u8p,
                                            ctypes.c_int64]
            lib.hostpool_create.restype = ctypes.c_void_p
            lib.hostpool_create.argtypes = [ctypes.c_int64]
            lib.hostpool_destroy.argtypes = [ctypes.c_void_p]
            lib.hostpool_alloc.restype = ctypes.c_void_p
            lib.hostpool_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.hostpool_free.restype = ctypes.c_int
            lib.hostpool_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.hostpool_stats.argtypes = [ctypes.c_void_p,
                                           ctypes.POINTER(ctypes.c_int64)]
            u8pp = ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))
            i32p = ctypes.POINTER(ctypes.c_int32)
            lib.columns_to_rows.restype = None
            lib.columns_to_rows.argtypes = [
                u8pp, u8pp, i32p, i32p, ctypes.c_int32, ctypes.c_int64,
                u8p, ctypes.c_int64]
            lib.rows_to_columns.restype = None
            lib.rows_to_columns.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int64, i32p, i32p,
                ctypes.c_int32, u8pp, u8pp]
            # int64 size/return: default c_int truncation silently broke
            # >=2GiB O_DIRECT spills (the size compare always failed and
            # fell back to buffered npz)
            lib.direct_write_file.restype = ctypes.c_int64
            lib.direct_write_file.argtypes = [ctypes.c_char_p, u8p,
                                              ctypes.c_int64]
            lib.direct_read_file.restype = ctypes.c_int64
            lib.direct_read_file.argtypes = [ctypes.c_char_p, u8p,
                                             ctypes.c_int64]
            lib.parquet_decode_chunk.restype = ctypes.c_int64
            lib.parquet_decode_chunk.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int32, u8p, ctypes.c_int64,
                u8p, u8p, ctypes.c_int64]
            lib.orc_deframe.restype = ctypes.c_int64
            lib.orc_deframe.argtypes = [u8p, ctypes.c_int64,
                                        ctypes.c_int32, u8p,
                                        ctypes.c_int64]
            lib.orc_bool_rle.restype = ctypes.c_int64
            lib.orc_bool_rle.argtypes = [u8p, ctypes.c_int64, u8p,
                                         ctypes.c_int64]
            lib.orc_rlev2.restype = ctypes.c_int64
            lib.orc_rlev2.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
            lib.orc_decimal64.restype = ctypes.c_int64
            lib.orc_decimal64.argtypes = [
                u8p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
            lib.parquet_decode_chunk_binary.restype = ctypes.c_int64
            lib.parquet_decode_chunk_binary.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int64,
                ctypes.c_int32, i32p, u8p, ctypes.c_int64, u8p, u8p,
                ctypes.c_int64]
            _LIB = lib
        return _LIB


def _u8ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def lz4_compress(data: bytes) -> bytes:
    lib = _lib()
    src = np.frombuffer(data, np.uint8)
    cap = int(lib.slz4_max_compressed_size(len(src)))
    dst = np.empty(cap, np.uint8)
    n = int(lib.slz4_compress(_u8ptr(src), len(src), _u8ptr(dst), cap))
    if n < 0:
        raise RuntimeError("lz4 compression overflow")
    return dst[:n].tobytes()


def lz4_decompress(data: bytes, decompressed_size: int) -> bytes:
    lib = _lib()
    src = np.frombuffer(data, np.uint8)
    dst = np.empty(decompressed_size, np.uint8)
    n = int(lib.slz4_decompress(_u8ptr(src), len(src), _u8ptr(dst),
                                decompressed_size))
    if n != decompressed_size:
        raise RuntimeError(
            f"lz4 decompression produced {n}, expected "
            f"{decompressed_size}")
    return dst.tobytes()


def columns_to_rows(col_data, col_valid, field_sizes) -> np.ndarray:
    """Pack columnar buffers into fixed-width rows.

    col_data: list of contiguous np arrays (one per column)
    col_valid: list of uint8/bool arrays
    Returns (rows bytes ndarray, row_stride, field_offsets).
    """
    lib = _lib()
    n_cols = len(col_data)
    n_rows = len(col_data[0]) if n_cols else 0
    null_bytes = (n_cols + 7) // 8
    # 8-byte aligned fields after the null bitset (CudfUnsafeRow layout)
    offsets = []
    pos = (null_bytes + 7) // 8 * 8
    for s in field_sizes:
        pos = (pos + s - 1) // s * s  # natural alignment
        offsets.append(pos)
        pos += s
    stride = (pos + 7) // 8 * 8
    rows = np.zeros(n_rows * stride, np.uint8)
    data_arrs = [np.ascontiguousarray(a).view(np.uint8).reshape(-1)
                 for a in col_data]
    valid_arrs = [np.ascontiguousarray(v, dtype=np.uint8)
                  for v in col_valid]
    DataPtrs = ctypes.POINTER(ctypes.c_uint8) * n_cols
    dp = DataPtrs(*[_u8ptr(a) for a in data_arrs])
    vp = DataPtrs(*[_u8ptr(v) for v in valid_arrs])
    fs = (ctypes.c_int32 * n_cols)(*field_sizes)
    fo = (ctypes.c_int32 * n_cols)(*offsets)
    lib.columns_to_rows(dp, vp, fs, fo, n_cols, n_rows, _u8ptr(rows),
                        stride)
    return rows, stride, offsets


def rows_to_columns(rows: np.ndarray, stride: int, n_rows: int,
                    field_sizes, field_offsets, np_dtypes):
    """Unpack fixed-width rows into columnar (data, valid) pairs."""
    lib = _lib()
    n_cols = len(field_sizes)
    outs = [np.zeros(n_rows, np.dtype(d)) for d in np_dtypes]
    valids = [np.zeros(n_rows, np.uint8) for _ in range(n_cols)]
    DataPtrs = ctypes.POINTER(ctypes.c_uint8) * n_cols
    dp = DataPtrs(*[_u8ptr(a.view(np.uint8).reshape(-1)) for a in outs])
    vp = DataPtrs(*[_u8ptr(v) for v in valids])
    fs = (ctypes.c_int32 * n_cols)(*field_sizes)
    fo = (ctypes.c_int32 * n_cols)(*field_offsets)
    lib.rows_to_columns(_u8ptr(rows), stride, n_rows, fs, fo, n_cols,
                        dp, vp)
    return outs, [v.astype(bool) for v in valids]


class HostMemoryPool:
    """Aligned slab allocator; alloc returns None when exhausted so the
    caller can spill-and-retry (DeviceMemoryEventHandler pattern on the
    host side)."""

    def __init__(self, size: int):
        self._lib = _lib()
        self._pool = self._lib.hostpool_create(size)
        if not self._pool:
            raise MemoryError(f"hostpool_create({size})")
        self.size = size

    def alloc(self, size: int) -> Optional[int]:
        p = self._lib.hostpool_alloc(self._pool, size)
        return p or None

    def free(self, ptr: int) -> None:
        if self._lib.hostpool_free(self._pool, ptr) != 0:
            raise ValueError("hostpool_free: unknown pointer")

    def stats(self) -> dict:
        out = (ctypes.c_int64 * 4)()
        self._lib.hostpool_stats(self._pool, out)
        return {"in_use": out[0], "peak": out[1],
                "alloc_count": out[2], "fail_count": out[3]}

    def close(self) -> None:
        if self._pool:
            self._lib.hostpool_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def direct_write(path: str, ptr: int, size: int) -> bool:
    """Write ``size`` bytes at address ``ptr`` to ``path`` with
    O_DIRECT when the filesystem allows (GDS-spill role)."""
    lib = _lib()
    buf = ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8))
    return lib.direct_write_file(path.encode(), buf, size) == size


def direct_read(path: str, ptr: int, size: int) -> bool:
    lib = _lib()
    buf = ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8))
    return lib.direct_read_file(path.encode(), buf, size) == size


def parquet_decode_chunk(chunk: bytes, codec: int, phys_type: int,
                         num_rows: int, max_def_level: int,
                         values: np.ndarray, validity: np.ndarray,
                         scratch: np.ndarray) -> int:
    """Decode one parquet column chunk's pages into ``values`` (dense
    fixed-width rows, zeros under nulls) + ``validity`` (u8/row).
    Returns rows decoded; negative = malformed(-1) / unsupported(-2) /
    buffer too small(-3) — the caller falls back to pyarrow."""
    lib = _lib()
    buf = np.frombuffer(chunk, dtype=np.uint8)
    return lib.parquet_decode_chunk(
        _u8ptr(buf), len(chunk), codec, phys_type, num_rows,
        max_def_level, _u8ptr(values), values.nbytes,
        _u8ptr(validity), _u8ptr(scratch), scratch.nbytes)


def parquet_decode_chunk_binary(chunk: bytes, codec: int, num_rows: int,
                                max_def_level: int, offsets: np.ndarray,
                                out_bytes: np.ndarray,
                                validity: np.ndarray,
                                scratch: np.ndarray) -> int:
    """Decode one BYTE_ARRAY column chunk into offsets[num_rows+1]
    (int32) + concatenated bytes. PLAIN / dictionary /
    DELTA_LENGTH_BYTE_ARRAY / DELTA_BYTE_ARRAY. Returns rows decoded;
    -3 also signals out_bytes too small (caller may retry bigger)."""
    import ctypes as _ct
    lib = _lib()
    buf = np.frombuffer(chunk, dtype=np.uint8)
    return int(lib.parquet_decode_chunk_binary(
        _u8ptr(buf), len(chunk), codec, num_rows, max_def_level,
        offsets.ctypes.data_as(_ct.POINTER(_ct.c_int32)),
        _u8ptr(out_bytes), out_bytes.nbytes, _u8ptr(validity),
        _u8ptr(scratch), scratch.nbytes))


def orc_decimal64(src: np.ndarray, out: np.ndarray, count: int) -> int:
    """ORC decimal DATA stream: zigzag unbounded varints -> int64
    unscaled values (precision <= 18)."""
    import ctypes as _ct
    lib = _lib()
    return int(lib.orc_decimal64(
        _u8ptr(src), len(src),
        out.ctypes.data_as(_ct.POINTER(_ct.c_int64)), count))


def native_available() -> bool:
    try:
        _lib()
        return True
    except Exception:
        return False


def orc_deframe(src: np.ndarray, codec: int, dst: np.ndarray) -> int:
    """ORC compression deframing (3-byte chunk headers over
    zlib/snappy/zstd); returns decompressed length or negative error."""
    lib = _lib()
    return int(lib.orc_deframe(_u8ptr(src), len(src), codec,
                               _u8ptr(dst), len(dst)))


def orc_bool_rle(src: np.ndarray, out_valid: np.ndarray,
                 count: int) -> int:
    """PRESENT stream decode: byte-RLE bit bytes -> one u8 per value."""
    lib = _lib()
    return int(lib.orc_bool_rle(_u8ptr(src), len(src),
                                _u8ptr(out_valid), count))


def orc_rlev2(src: np.ndarray, is_signed: int, out: np.ndarray,
              count: int) -> int:
    """Integer RLEv2 decode into an int64 array."""
    import ctypes as _ct
    lib = _lib()
    return int(lib.orc_rlev2(
        _u8ptr(src), len(src), is_signed,
        out.ctypes.data_as(_ct.POINTER(_ct.c_int64)), count))
