"""Driver-side SQL server: the serving front door.

``SqlServer`` listens on the framed serving protocol
(serve/protocol.py) and routes every submitted query through the
engine's existing serving machinery — nothing here re-implements
admission or isolation, it only gives them a socket:

- **Admission**: execution goes through ``TpuSession.execute``, so
  each request passes the QuerySemaphore (FIFO tickets, bounded
  queue). ``AdmissionRejected`` surfaces to the client as a retryable
  SHED frame; the admission tier the query took (immediate vs queued,
  stamped on its QueryContext) rides back on the EOS frame so clients
  and the bench bucket latency per tier.
- **Memory isolation**: per-query MemoryBudget slices are claimed and
  released inside execute, exactly as for in-process callers.
- **Cancel/deadline**: the server creates the QueryContext *before*
  calling execute and keeps the handle, so a client disconnect — EOF
  on the session socket or a send failure mid-stream — cancels the
  query server-side even while it is still queued for admission. A
  ``timeout_ms`` on SUBMIT arms the same deadline clients get from
  ``collect(timeout=)``.
- **Teardown hygiene**: per-session teardown cancels in-flight
  queries, joins their request threads, and closes any live
  PrefetchIterators the abandoned streams left behind
  (exec/pipeline.close_live_iterators) — zero leaked producer
  threads is asserted by tests and the chaos sweep.

Result streams go back in the serializer's columnar wire format, one
BATCH frame per ``srt.serve.streamChunkRows`` rows. With
``srt.sql.resultCache.enabled`` the server consults the cross-tenant
result cache (serve/result_cache.py) first: a verified hit replays
the exact frames of the original fill — bypassing admission entirely
— and a miss refills the cache after streaming.

Tenancy: each connection is one session; its HELLO names the tenant.
The per-request engine sessions share the server session's catalog
and plan cache (cross-tenant reuse of compiled plans is the point),
and carry ``session_id``/``tenant`` so QueryStart/QueryEnd events
group by tenant in the report tools.
"""

from __future__ import annotations

import itertools
import socketserver
import threading
import time
from typing import Dict, List, Optional

from ..conf import (RESULT_CACHE_ENABLED, RESULT_CACHE_MAX_BYTES,
                    SERVE_AUTH_TOKEN, SERVE_HOST, SERVE_MAX_SESSIONS,
                    SERVE_PORT, SERVE_STREAM_CHUNK_ROWS, SrtConf)
from ..obs import events as _events
from ..robustness.admission import (AdmissionRejected, QueryContext,
                                    QueryInterrupted)
from . import protocol as P
from .result_cache import ResultCache, fingerprint


class _SessionState:
    """One connected client session."""

    def __init__(self, session_id: int, tenant: str, peer: str):
        self.session_id = session_id
        self.tenant = tenant
        self.peer = peer
        self.inflight: Dict[int, QueryContext] = {}
        self.threads: List[threading.Thread] = []
        self.requests = 0
        self.lock = threading.Lock()


class _SessionHandler(socketserver.BaseRequestHandler):
    def handle(self):
        self.server.sql_server._handle_connection(self.request)  # type: ignore


class SqlServer:
    """Networked SQL service over one engine session.

    >>> server = SqlServer(session); server.start()
    >>> client = SqlClient(server.endpoint)   # serve/client.py
    """

    def __init__(self, session, host: Optional[str] = None,
                 port: Optional[int] = None):
        self.session = session
        conf: SrtConf = session.conf
        self.conf = conf
        self.auth_token = conf.get(SERVE_AUTH_TOKEN)
        self.max_sessions = conf.get(SERVE_MAX_SESSIONS)
        self.chunk_rows = conf.get(SERVE_STREAM_CHUNK_ROWS)
        self.result_cache: Optional[ResultCache] = None
        if conf.get(RESULT_CACHE_ENABLED) \
                and conf.get(RESULT_CACHE_MAX_BYTES) > 0:
            self.result_cache = ResultCache(
                conf.get(RESULT_CACHE_MAX_BYTES))
        self._host = host if host is not None else conf.get(SERVE_HOST)
        self._port = port if port is not None else conf.get(SERVE_PORT)
        self._server: Optional[socketserver.ThreadingTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._sessions: Dict[int, _SessionState] = {}
        self._session_seq = itertools.count(1)
        self._lock = threading.Lock()
        # lifetime counters (tests/chaos/bench)
        self.requests = 0
        self.load_shed = 0
        self.auth_failures = 0
        self.disconnect_cancels = 0

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "SqlServer":
        # the session installs the event sink lazily at first execute;
        # a server emits session-lifecycle events before any query
        # runs, so configure observability up front
        _events.configure_from_conf(self.conf)
        srv = socketserver.ThreadingTCPServer(
            (self._host, self._port), _SessionHandler,
            bind_and_activate=True)
        srv.daemon_threads = True
        srv.sql_server = self  # type: ignore
        self._server = srv
        self._thread = threading.Thread(target=srv.serve_forever,
                                        daemon=True,
                                        name="srt-sql-server")
        self._thread.start()
        return self

    @property
    def endpoint(self) -> str:
        assert self._server is not None, "server not started"
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self.result_cache is not None:
            self.result_cache.close()

    def __enter__(self) -> "SqlServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def open_sessions(self) -> int:
        with self._lock:
            return len(self._sessions)

    # --- connection loop --------------------------------------------------
    def _handle_connection(self, sock) -> None:
        send_lock = threading.Lock()
        state: Optional[_SessionState] = None
        try:
            op, _sid, rid, payload = P.recv_frame(sock)
            if op != P.OP_HELLO:
                P.send_json(sock, P.OP_ERR, 0, rid,
                            {"error": "expected HELLO", "retryable": False},
                            lock=send_lock)
                return
            hello = P.decode_json(payload)
            if self.auth_token and hello.get("token") != self.auth_token:
                with self._lock:
                    self.auth_failures += 1
                P.send_json(sock, P.OP_ERR, 0, rid,
                            {"error": "authentication failed",
                             "type": "AuthError", "retryable": False},
                            lock=send_lock)
                return
            with self._lock:
                if len(self._sessions) >= self.max_sessions:
                    P.send_json(sock, P.OP_ERR, 0, rid,
                                {"error": "session limit reached",
                                 "type": "SessionLimit",
                                 "retryable": True}, lock=send_lock)
                    return
                sid = next(self._session_seq)
                try:
                    pn = sock.getpeername()
                    peer = f"{pn[0]}:{pn[1]}" if isinstance(pn, tuple) \
                        and len(pn) >= 2 else str(pn)
                except OSError:
                    peer = "?"
                state = _SessionState(
                    sid, str(hello.get("tenant") or f"tenant-{sid}"),
                    peer)
                self._sessions[sid] = state
            _events.emit("ServeSessionOpen", session_id=sid,
                         tenant=state.tenant, peer=state.peer)
            P.send_json(sock, P.OP_OK, sid, rid,
                        {"session_id": sid}, lock=send_lock)
            while True:
                op, _sid, rid, payload = P.recv_frame(sock)
                if op == P.OP_CLOSE:
                    P.send_json(sock, P.OP_OK, sid, rid, {},
                                lock=send_lock)
                    return
                if op == P.OP_CANCEL:
                    with state.lock:
                        qctx = state.inflight.get(rid)
                    if qctx is not None:
                        qctx.cancel("client cancel")
                    continue
                if op != P.OP_SUBMIT:
                    P.send_json(sock, P.OP_ERR, sid, rid,
                                {"error": f"unexpected opcode {op}",
                                 "retryable": False}, lock=send_lock)
                    continue
                req = P.decode_json(payload)
                t = threading.Thread(
                    target=self._run_request,
                    args=(state, sock, send_lock, rid, req),
                    daemon=True, name=f"srt-serve-s{sid}r{rid}")
                with state.lock:
                    state.threads.append(t)
                    state.requests += 1
                t.start()
        except (ConnectionError, OSError, P.ProtocolError):
            pass  # disconnect; teardown below cancels in-flight work
        finally:
            if state is not None:
                self._teardown_session(state)

    # --- request execution ------------------------------------------------
    def _run_request(self, state: _SessionState, sock, send_lock,
                     rid: int, req: dict) -> None:
        import os as _os

        qid = f"q{_os.getpid()}-s{state.session_id}r{rid}"
        qctx = QueryContext(query_id=qid)
        timeout_ms = req.get("timeout_ms")
        if timeout_ms:
            qctx.set_timeout(float(timeout_ms) / 1000.0)
        with state.lock:
            state.inflight[rid] = qctx
        with self._lock:
            self.requests += 1
        sid = state.session_id
        t0 = time.perf_counter_ns()
        try:
            sess = self._request_session(state)
            df = sess.sql(str(req.get("sql", "")))
            plan = df.plan
            use_cache = self.result_cache is not None \
                and req.get("cache", True)
            fp = fingerprint(plan, sess.conf) if use_cache else None
            if fp is not None:
                cached = self.result_cache.get(fp)
                if cached is not None:
                    for payload in cached:
                        P.send_frame(sock, P.OP_BATCH, sid, rid,
                                     payload, lock=send_lock)
                    P.send_json(sock, P.OP_EOS, sid, rid, {
                        "status": "ok", "cache": "hit",
                        "tier": "cached", "wait_ns": 0,
                        "wall_ns": time.perf_counter_ns() - t0,
                    }, lock=send_lock)
                    return
            table = sess.execute(plan, query=qctx)
            payloads = self._serialize_result(table)
            for payload in payloads:
                P.send_frame(sock, P.OP_BATCH, sid, rid, payload,
                             lock=send_lock)
            if fp is not None:
                self.result_cache.put(fp, payloads, table.num_rows)
            P.send_json(sock, P.OP_EOS, sid, rid, {
                "status": "ok",
                "cache": "miss" if fp is not None else "off",
                "tier": qctx.admission_tier,
                "wait_ns": qctx.admission_wait_ns or 0,
                "rows": table.num_rows,
                "wall_ns": time.perf_counter_ns() - t0,
            }, lock=send_lock)
        except AdmissionRejected as e:
            with self._lock:
                self.load_shed += 1
            _events.emit("ServeLoadShed", session_id=sid,
                         tenant=state.tenant, request_id=rid)
            self._safe_send(sock, P.OP_SHED, sid, rid,
                            {"error": str(e),
                             "type": "AdmissionRejected",
                             "retryable": True}, send_lock)
        except QueryInterrupted as e:
            self._safe_send(sock, P.OP_ERR, sid, rid,
                            {"error": str(e),
                             "type": type(e).__name__,
                             "retryable": False}, send_lock)
        except (ConnectionError, OSError):
            # client went away mid-stream: cancel our own query so the
            # engine tears down (budget slice, admission permit) and
            # leaves nothing running for a dead socket
            qctx.cancel("client disconnected mid-stream")
            with self._lock:
                self.disconnect_cancels += 1
        except Exception as e:
            self._safe_send(sock, P.OP_ERR, sid, rid,
                            {"error": f"{e}", "type": type(e).__name__,
                             "retryable": False}, send_lock)
        finally:
            with state.lock:
                state.inflight.pop(rid, None)
            # reap prefetch producers an abandoned stream left behind
            from ..exec.pipeline import close_live_iterators
            close_live_iterators(qctx)

    def _request_session(self, state: _SessionState):
        """Per-request engine session: shares the server session's
        catalog and plan cache (cross-tenant plan reuse), carries the
        client's identity for event tagging."""
        from ..plan.session import TpuSession
        sess = TpuSession(self.session.conf)
        sess._catalog = self.session._catalog
        sess._plan_cache = self.session._plan_cache
        sess.session_id = f"s{state.session_id}"
        sess.tenant = state.tenant
        return sess

    def _serialize_result(self, table) -> List[bytes]:
        """HostTable -> serialized columnar frames of at most
        ``srt.serve.streamChunkRows`` rows each (always at least one
        frame, so empty results still carry their schema)."""
        from ..parallel.serializer import serialize_batch
        from ..plan.host_table import (HostColumn, HostTable,
                                       table_to_batch)
        n = table.num_rows
        chunk = max(int(self.chunk_rows), 1)
        payloads: List[bytes] = []
        if n <= chunk:
            payloads.append(serialize_batch(table_to_batch(table)))
            return payloads
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            cols = [HostColumn(c.values[lo:hi], c.mask[lo:hi], c.dtype)
                    for c in table.columns]
            payloads.append(serialize_batch(
                table_to_batch(HostTable(cols, table.names))))
        return payloads

    def _safe_send(self, sock, opcode, sid, rid, obj, lock) -> None:
        try:
            P.send_json(sock, opcode, sid, rid, obj, lock=lock)
        except (ConnectionError, OSError):
            pass

    # --- teardown ---------------------------------------------------------
    def _teardown_session(self, state: _SessionState) -> None:
        """Cancel in-flight queries, join request threads, close any
        abandoned prefetch iterators, drop the session."""
        from ..exec.pipeline import close_live_iterators
        with state.lock:
            inflight = dict(state.inflight)
            threads = list(state.threads)
        for qctx in inflight.values():
            qctx.cancel("client disconnected")
        if inflight:
            with self._lock:
                self.disconnect_cancels += len(inflight)
        for t in threads:
            t.join(timeout=30)
        for qctx in inflight.values():
            close_live_iterators(qctx)
        with self._lock:
            self._sessions.pop(state.session_id, None)
        _events.emit("ServeSessionClose", session_id=state.session_id,
                     tenant=state.tenant, requests=state.requests,
                     cancelled=len(inflight))
