"""Cross-tenant result reuse: plan fingerprint -> cached result bytes.

At production traffic the same dashboard queries recur constantly
across tenants; re-executing them burns admission permits and device
time to recompute bytes the server already streamed. This cache keys
completed result sets on a **canonicalized-plan fingerprint** — the
structural plan-cache key (plan/plan_cache.py: operators, expressions,
conf, and per-file ``(path, mtime_ns, size)`` snapshots) plus the
**Delta snapshot versions** of every Delta-provenanced scan — so a hit
is only possible for a byte-identical plan over byte-identical data.

Correctness levers:

- **Invalidation feed**: the Delta commit protocol (delta/log.py
  ``register_commit_listener``; the standard-format writer feeds it
  too). A commit to any table a cached plan scanned evicts the entry
  immediately — staleness is bounded by commit publication, not TTL.
- **Integrity**: every cached payload is crc-framed with the shared
  integrity envelope (robustness/integrity.py). A mismatch on read
  (bit rot, or the chaos sweep's seeded ``serve.result_cache``
  corruption) evicts the entry and reports a miss, so the server
  recomputes bit-identically instead of serving garbage.
- **Bounds**: byte-accounted LRU; an insert past
  ``srt.sql.resultCache.maxBytes`` evicts least-recently-used entries
  first, and a single result larger than the cap is never cached.

Because entries hold the exact serialized frames the server streamed
on the fill, a hit replays the same bytes — cache on/off is
bit-identical by construction.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from ..delta import log as delta_log
from ..obs import events as _events
from ..robustness.faults import corrupt_point
from ..robustness.integrity import DataCorruption, unwrap, wrap


class Fingerprint:
    """Hashable cache key + the Delta provenance it pinned."""

    __slots__ = ("digest", "delta_roots")

    def __init__(self, digest: str,
                 delta_roots: Tuple[Tuple[str, int], ...]):
        self.digest = digest
        self.delta_roots = delta_roots  # ((abs_root, version), ...)

    def __repr__(self):
        return f"Fingerprint({self.digest[:12]}..., {self.delta_roots})"


def _delta_scans(plan) -> List[Tuple[str, int]]:
    """Collect ``(abs_root, version)`` provenance from every scan the
    Delta readers stamped (io/delta_format.read_delta,
    delta/table.AcidTable.to_df)."""
    out: List[Tuple[str, int]] = []
    stack = [plan]
    while stack:
        node = stack.pop()
        prov = getattr(node, "delta_table", None)
        if prov is not None:
            out.append((os.path.abspath(prov[0]), int(prov[1])))
        stack.extend(getattr(node, "children", ()))
    return out


def fingerprint(plan, conf) -> Optional[Fingerprint]:
    """Canonical fingerprint for (logical plan, conf), or None when
    the plan is not safely cachable (plan_cache.Uncachable: local
    data, non-deterministic expressions...)."""
    from ..plan.plan_cache import plan_cache_key
    key = plan_cache_key(plan, conf)
    if key is None:
        return None
    roots = tuple(sorted(set(_delta_scans(plan))))
    digest = hashlib.sha256(
        repr((key, roots)).encode("utf-8")).hexdigest()
    return Fingerprint(digest, roots)


class _Entry:
    __slots__ = ("framed", "nbytes", "rows", "delta_roots")

    def __init__(self, framed: List[bytes], rows: int,
                 delta_roots: Tuple[Tuple[str, int], ...]):
        self.framed = framed  # integrity-wrapped serialized batches
        self.nbytes = sum(len(p) for p in framed)
        self.rows = rows
        self.delta_roots = delta_roots


class ResultCache:
    """Byte-bounded LRU of fingerprint -> integrity-framed result
    frames, invalidated by Delta commits. Thread-safe (the server's
    request threads share one instance)."""

    def __init__(self, max_bytes: int, subscribe: bool = True):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._by_root: Dict[str, Set[str]] = {}
        self.bytes = 0
        # lifetime counters (tests/chaos/bench read these)
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.invalidations = 0
        self.corrupt_evictions = 0
        self._subscribed = False
        if subscribe:
            delta_log.register_commit_listener(self._on_delta_commit)
            self._subscribed = True

    # --- lookup/fill ------------------------------------------------------
    def get(self, fp: Fingerprint) -> Optional[List[bytes]]:
        """Verified raw result frames for ``fp``, or None. A checksum
        mismatch evicts the entry and reports a miss (the caller
        recomputes and refills)."""
        with self._lock:
            entry = self._entries.get(fp.digest)
            if entry is not None:
                self._entries.move_to_end(fp.digest)
        if entry is None:
            with self._lock:
                self.misses += 1
            _events.emit("ResultCacheMiss", fingerprint=fp.digest)
            return None
        payloads: List[bytes] = []
        try:
            for framed in entry.framed:
                framed = corrupt_point("serve.result_cache", framed,
                                       f"fp={fp.digest[:12]};")
                payloads.append(unwrap(framed, "cached result batch"))
        except DataCorruption:
            # integrity.unwrap already emitted CorruptionDetected;
            # drop the entry so the recompute path refills it clean
            with self._lock:
                self._evict_locked(fp.digest)
                self.corrupt_evictions += 1
                self.misses += 1
            _events.emit("ResultCacheCorrupt", fingerprint=fp.digest)
            return None
        with self._lock:
            self.hits += 1
        _events.emit("ResultCacheHit", fingerprint=fp.digest,
                     rows=entry.rows, nbytes=entry.nbytes)
        return payloads

    def put(self, fp: Fingerprint, payloads: List[bytes],
            rows: int) -> bool:
        """Insert the serialized result frames for ``fp``; False when
        the result alone exceeds the byte budget."""
        framed = [wrap(p) for p in payloads]
        entry = _Entry(framed, rows, fp.delta_roots)
        if entry.nbytes > self.max_bytes:
            return False
        with self._lock:
            if fp.digest in self._entries:
                self._evict_locked(fp.digest, count=False)
            while self.bytes + entry.nbytes > self.max_bytes \
                    and self._entries:
                oldest = next(iter(self._entries))
                self._evict_locked(oldest)
                self.evictions += 1
                _events.emit("ResultCacheEvict", fingerprint=oldest,
                             reason="lru")
            self._entries[fp.digest] = entry
            self.bytes += entry.nbytes
            for root, _v in fp.delta_roots:
                self._by_root.setdefault(root, set()).add(fp.digest)
            self.puts += 1
        return True

    def _evict_locked(self, digest: str, count: bool = True) -> None:
        entry = self._entries.pop(digest, None)
        if entry is None:
            return
        self.bytes -= entry.nbytes
        for root, _v in entry.delta_roots:
            keys = self._by_root.get(root)
            if keys is not None:
                keys.discard(digest)
                if not keys:
                    del self._by_root[root]

    # --- invalidation -----------------------------------------------------
    def _on_delta_commit(self, table_path: str, version: int) -> None:
        self.invalidate_table(table_path, version)

    def invalidate_table(self, table_path: str,
                         version: Optional[int] = None) -> int:
        """Evict every entry whose plan scanned ``table_path``.
        Returns the eviction count."""
        root = os.path.abspath(table_path)
        with self._lock:
            digests = list(self._by_root.get(root, ()))
            for d in digests:
                self._evict_locked(d)
            self.invalidations += len(digests)
        if digests:
            _events.emit("ResultCacheInvalidate", table=root,
                         version=version, entries=len(digests))
        return len(digests)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._by_root.clear()
            self.bytes = 0

    def close(self) -> None:
        if self._subscribed:
            delta_log.unregister_commit_listener(self._on_delta_commit)
            self._subscribed = False

    # --- introspection ----------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / lookups) if lookups else 0.0,
                "puts": self.puts,
                "evictions": self.evictions,
                "invalidations": self.invalidations,
                "corrupt_evictions": self.corrupt_evictions,
            }
