"""Serving front door: networked SQL service over the framed
transport, with cross-tenant result reuse.

- protocol.py — the multiplexed session wire protocol
- server.py — SqlServer: sessions -> admission/budget/cancel tokens
- client.py — SqlClient: socket client for tests, benches, tools
- result_cache.py — plan-fingerprint result cache with Delta
  commit-version invalidation
"""

from .client import ServeError, ServeLoadShed, ServeResult, SqlClient
from .result_cache import ResultCache, fingerprint
from .server import SqlServer

__all__ = ["SqlServer", "SqlClient", "ServeResult", "ServeError",
           "ServeLoadShed", "ResultCache", "fingerprint"]
