"""Socket client for the SQL serving front door.

``SqlClient`` speaks the session protocol (serve/protocol.py): one
TCP connection is one authenticated session; ``submit`` streams the
result back as serializer-format batches and returns a
``ServeResult``. ``cancel_active`` may be called from another thread
to interrupt an in-flight submit (the CANCEL frame interleaves on the
same socket under the send lock).

Errors are typed: a load-shed (admission queue full server-side)
raises ``ServeLoadShed`` with ``retryable=True`` so replay clients
can back off and retry; everything else raises ``ServeError`` with
the server-reported type.
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Dict, List, Optional

from . import protocol as P


class ServeError(RuntimeError):
    def __init__(self, message: str, kind: str = "ServeError",
                 retryable: bool = False):
        super().__init__(message)
        self.kind = kind
        self.retryable = retryable


class ServeLoadShed(ServeError):
    def __init__(self, message: str):
        super().__init__(message, kind="AdmissionRejected",
                         retryable=True)


class ServeResult:
    """One query's result: host tables (one per streamed frame), the
    raw wire payloads (bit-identity checks), and the EOS info dict
    ({"status", "cache", "tier", "wait_ns", "wall_ns", ...})."""

    def __init__(self, tables: List, payloads: List[bytes],
                 info: Dict):
        self.tables = tables
        self.payloads = payloads
        self.info = info

    @property
    def num_rows(self) -> int:
        return sum(t.num_rows for t in self.tables)

    def table(self):
        from ..plan.host_table import concat_tables
        if not self.tables:
            raise ValueError("empty result stream")
        return concat_tables(self.tables)

    def to_pydict(self) -> dict:
        from ..plan.host_table import to_pydict
        return to_pydict(self.table())


class SqlClient:
    def __init__(self, endpoint: str, token: str = "",
                 tenant: Optional[str] = None,
                 connect_timeout: float = 10.0,
                 sock_timeout: Optional[float] = 300.0):
        host, _, port = endpoint.rpartition(":")
        self._sock = socket.create_connection(
            (host, int(port)), timeout=connect_timeout)
        self._sock.settimeout(sock_timeout)
        self._send_lock = threading.Lock()
        self._rid = itertools.count(1)
        self._active_rid: Optional[int] = None
        self.session_id = 0
        P.send_json(self._sock, P.OP_HELLO, 0, 0,
                    {"token": token, "tenant": tenant},
                    lock=self._send_lock)
        op, sid, _rid, payload = P.recv_frame(self._sock)
        if op != P.OP_OK:
            err = P.decode_json(payload)
            raise ServeError(err.get("error", "connect failed"),
                             kind=err.get("type", "ServeError"),
                             retryable=bool(err.get("retryable")))
        self.session_id = sid

    # --- requests ---------------------------------------------------------
    def submit(self, sql: str, timeout_ms: Optional[int] = None,
               cache: bool = True) -> ServeResult:
        """Run ``sql`` server-side; blocks until EOS. Raises
        ``ServeLoadShed`` (retryable) on admission shed, ``ServeError``
        on any other failure (including cancel/deadline)."""
        from ..parallel.serializer import deserialize_batch
        from ..plan.host_table import batch_to_table
        rid = next(self._rid)
        req: Dict = {"sql": sql, "cache": cache}
        if timeout_ms is not None:
            req["timeout_ms"] = int(timeout_ms)
        self._active_rid = rid
        try:
            P.send_json(self._sock, P.OP_SUBMIT, self.session_id, rid,
                        req, lock=self._send_lock)
            tables: List = []
            payloads: List[bytes] = []
            while True:
                op, _sid, got_rid, payload = P.recv_frame(self._sock)
                if got_rid != rid:
                    continue  # stale frame from a cancelled request
                if op == P.OP_BATCH:
                    payloads.append(payload)
                    tables.append(batch_to_table(
                        deserialize_batch(payload)))
                elif op == P.OP_EOS:
                    return ServeResult(tables, payloads,
                                       P.decode_json(payload))
                elif op == P.OP_SHED:
                    err = P.decode_json(payload)
                    raise ServeLoadShed(err.get("error", "load shed"))
                elif op == P.OP_ERR:
                    err = P.decode_json(payload)
                    raise ServeError(
                        err.get("error", "request failed"),
                        kind=err.get("type", "ServeError"),
                        retryable=bool(err.get("retryable")))
                else:
                    raise P.ProtocolError(f"unexpected opcode {op}")
        finally:
            self._active_rid = None

    def cancel_active(self) -> bool:
        """Ask the server to cancel the in-flight submit (call from
        another thread). True if a request was active."""
        rid = self._active_rid
        if rid is None:
            return False
        P.send_json(self._sock, P.OP_CANCEL, self.session_id, rid, {},
                    lock=self._send_lock)
        return True

    # --- lifecycle --------------------------------------------------------
    def close(self) -> None:
        try:
            rid = next(self._rid)
            P.send_json(self._sock, P.OP_CLOSE, self.session_id, rid,
                        {}, lock=self._send_lock)
            P.recv_frame(self._sock)  # OK ack
        except (ConnectionError, OSError, P.ProtocolError):
            pass
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def __enter__(self) -> "SqlClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
