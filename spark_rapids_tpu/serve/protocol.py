"""Wire protocol for the SQL serving front door.

One frame shape in both directions, over the same framed-TCP idiom as
the shuffle transport (parallel/transport.py — little-endian structs,
a u32 magic registered alongside the shuffle magics, and the
cancel-aware ``recv_exact``):

    | magic u32 | opcode u8 | session u32 | request u32 | len u32 |
    | payload: ``len`` bytes |

A connection is one session. Requests multiplex on it by request id
(client-assigned, monotonically increasing): SUBMIT responses —
result-batch frames in the serializer's columnar wire format
(parallel/serializer.py), then one terminal EOS/ERR/SHED — carry the
request id they answer, and a CANCEL for an in-flight request id can
interleave with another request's response stream.

Opcodes (client -> server):
    HELLO   auth token + tenant, before anything else
    SUBMIT  {"sql": ..., "timeout_ms"?: int, "cache"?: bool}
    CANCEL  the request id in the header names the target
    CLOSE   orderly goodbye

Opcodes (server -> client):
    OK      HELLO/CLOSE ack ({"session_id": ...} on HELLO)
    BATCH   one serialized result batch (raw serializer bytes)
    EOS     end of a result stream: {"status", "rows", "wall_ns",
            "cache": hit|miss|off, "tier": cached|immediate|queued,
            "wait_ns"}
    ERR     {"error", "type", "retryable"} — terminal for its request
    SHED    admission load-shed; like ERR but always retryable
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Optional, Tuple

from ..parallel.transport import MAGIC_SERVE, recv_exact

# client -> server
OP_HELLO = 1
OP_SUBMIT = 2
OP_CANCEL = 3
OP_CLOSE = 4
# server -> client
OP_OK = 16
OP_BATCH = 17
OP_EOS = 18
OP_ERR = 19
OP_SHED = 20

_HDR = struct.Struct("<IBIII")

#: refuse frames beyond this (a corrupted length must not allocate
#: unbounded memory server-side)
MAX_PAYLOAD = 1 << 28


class ProtocolError(ConnectionError):
    """Malformed frame on the serving wire."""


def send_frame(sock: socket.socket, opcode: int, session_id: int,
               request_id: int, payload: bytes = b"",
               lock: Optional[threading.Lock] = None) -> None:
    """Write one frame; ``lock`` serializes concurrent writers (the
    per-connection send lock — response streams for multiplexed
    requests interleave at frame granularity, never inside one)."""
    buf = _HDR.pack(MAGIC_SERVE, opcode, session_id, request_id,
                    len(payload)) + payload
    if lock is None:
        sock.sendall(buf)
    else:
        with lock:
            sock.sendall(buf)


def recv_frame(sock: socket.socket) -> Tuple[int, int, int, bytes]:
    """Read one frame -> (opcode, session_id, request_id, payload).

    Uses the transport's cancel-aware exact read, so a server-side
    reader whose thread carries a query token unwinds on cancel."""
    hdr = recv_exact(sock, _HDR.size)
    magic, opcode, session_id, request_id, n = _HDR.unpack(hdr)
    if magic != MAGIC_SERVE:
        raise ProtocolError(f"bad serve frame magic {magic:#x}")
    if n > MAX_PAYLOAD:
        raise ProtocolError(f"serve frame of {n} bytes exceeds cap")
    payload = recv_exact(sock, n) if n else b""
    return opcode, session_id, request_id, payload


def send_json(sock: socket.socket, opcode: int, session_id: int,
              request_id: int, obj: dict,
              lock: Optional[threading.Lock] = None) -> None:
    send_frame(sock, opcode, session_id, request_id,
               json.dumps(obj).encode("utf-8"), lock=lock)


def decode_json(payload: bytes) -> dict:
    try:
        d = json.loads(payload.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise ProtocolError(f"bad json payload: {e}")
    if not isinstance(d, dict):
        raise ProtocolError("json payload is not an object")
    return d
