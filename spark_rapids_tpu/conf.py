"""Configuration system — the RapidsConf equivalent.

TPU-native analogue of the reference's config layer
(sql-plugin/.../RapidsConf.scala: ConfBuilder/TypedConfBuilder DSL at
lines 200-310, ~300 ``spark.rapids.*`` entries, doc generation via
``RapidsConf.main`` at :2214). Same shape here: a typed builder DSL that
registers every config with type, default, validation, and doc string
under the ``srt.`` prefix (``spark_rapids_tpu``), plus markdown doc-gen
so docs never drift from code.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional


class ConfEntry:
    """One registered configuration key."""

    def __init__(self, key: str, conv: Callable[[str], Any], default: Any,
                 doc: str, is_internal: bool, is_startup_only: bool,
                 commonly_used: bool,
                 checker: Optional[Callable[[Any], Optional[str]]] = None):
        self.key = key
        self.conv = conv
        self.default = default
        self.doc = doc
        self.is_internal = is_internal
        self.is_startup_only = is_startup_only
        self.commonly_used = commonly_used
        self.checker = checker

    def get(self, settings: Dict[str, str]) -> Any:
        raw = settings.get(self.key)
        if raw is None:
            raw = os.environ.get(self.key.replace(".", "_").upper())
        if raw is None:
            return self.default
        value = self.conv(raw) if isinstance(raw, str) else raw
        if self.checker is not None:
            err = self.checker(value)
            if err:
                raise ValueError(f"{self.key}={value!r}: {err}")
        return value


_REGISTRY: Dict[str, ConfEntry] = {}


class ConfBuilder:
    """Typed builder DSL (TypedConfBuilder in the reference)."""

    def __init__(self, key: str):
        assert key.startswith("srt."), key
        self.key = key
        self._doc = ""
        self._internal = False
        self._startup_only = False
        self._commonly_used = False
        self._checker: Optional[Callable[[Any], Optional[str]]] = None

    def doc(self, text: str) -> "ConfBuilder":
        self._doc = text
        return self

    def internal(self) -> "ConfBuilder":
        self._internal = True
        return self

    def startup_only(self) -> "ConfBuilder":
        self._startup_only = True
        return self

    def commonly_used(self) -> "ConfBuilder":
        self._commonly_used = True
        return self

    def check(self, fn: Callable[[Any], Optional[str]]) -> "ConfBuilder":
        self._checker = fn
        return self

    def check_values(self, allowed: List[Any]) -> "ConfBuilder":
        return self.check(
            lambda v: None if v in allowed else f"must be one of {allowed}")

    def _register(self, conv, default) -> ConfEntry:
        entry = ConfEntry(self.key, conv, default, self._doc, self._internal,
                          self._startup_only, self._commonly_used, self._checker)
        _REGISTRY[self.key] = entry
        return entry

    def boolean(self, default: bool) -> ConfEntry:
        return self._register(
            lambda s: s.strip().lower() in ("true", "1", "yes"), default)

    def integer(self, default: int) -> ConfEntry:
        return self._register(int, default)

    def double(self, default: float) -> ConfEntry:
        return self._register(float, default)

    def string(self, default: Optional[str]) -> ConfEntry:
        return self._register(str, default)

    def bytes_(self, default: int) -> ConfEntry:
        return self._register(parse_bytes, default)


def conf(key: str) -> ConfBuilder:
    return ConfBuilder(key)


def parse_bytes(s: str) -> int:
    """'512m', '2g', '1024' -> bytes."""
    s = s.strip().lower()
    units = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40, "b": 1}
    if s and s[-1] in units:
        return int(float(s[:-1]) * units[s[-1]])
    return int(s)


def _positive(v) -> Optional[str]:
    return None if v > 0 else "must be positive"


def _non_negative(v) -> Optional[str]:
    return None if v >= 0 else "must be non-negative"


def _fraction(v) -> Optional[str]:
    return None if 0.0 < v <= 1.0 else "must be in (0, 1]"


# ---------------------------------------------------------------------------
# Registered configs. Reference counterparts cited per entry.
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("srt.sql.enabled") \
    .doc("Enable TPU acceleration of SQL operators. When false every plan "
         "runs on the CPU oracle path. (spark.rapids.sql.enabled)") \
    .commonly_used().boolean(True)

EXPLAIN = conf("srt.sql.explain") \
    .doc("Explain mode: NONE, NOT_ON_TPU (log only operators that could not "
         "be placed on TPU and why), ALL. (spark.rapids.sql.explain, "
         "RapidsConf.scala:1807)") \
    .check_values(["NONE", "NOT_ON_TPU", "ALL"]).string("NONE")

BATCH_SIZE_ROWS = conf("srt.sql.batchSizeRows") \
    .doc("Target rows per columnar batch; capacities are bucketed to powers "
         "of two at or below this. (spark.rapids.sql.batchSizeBytes, "
         "RapidsConf.scala:550 — rows not bytes because XLA buffers are "
         "statically shaped per column)") \
    .check(_positive).commonly_used().integer(1 << 20)

BATCH_SIZE_BYTES = conf("srt.sql.batchSizeBytes") \
    .doc("Soft cap on bytes per batch used by the coalesce planner. "
         "(spark.rapids.sql.batchSizeBytes)") \
    .check(_positive).bytes_(1 << 30)

CACHE_HOST_LIMIT_BYTES = conf("srt.cache.hostLimitBytes") \
    .doc("Host-memory budget for df.cache() compressed blocks; overflow "
         "tiers to an append-only disk file read back per block. "
         "(ParquetCachedBatchSerializer host blob management)") \
    .check(_positive).bytes_(256 << 20)

CONCURRENT_TASKS = conf("srt.sql.concurrentTpuTasks") \
    .doc("Number of host threads allowed to submit device work "
         "concurrently. (spark.rapids.sql.concurrentGpuTasks, "
         "RapidsConf.scala:535)") \
    .check(_positive).commonly_used().integer(2)

DEVICE_MEMORY_LIMIT = conf("srt.memory.tpu.poolSize") \
    .doc("HBM budget in bytes for columnar batches; 0 means derive from the "
         "device. Exceeding it triggers spill-and-retry. "
         "(spark.rapids.memory.gpu.allocFraction / pool init, "
         "GpuDeviceManager.scala:275)") \
    .startup_only().bytes_(0)

DEVICE_MEMORY_FRACTION = conf("srt.memory.tpu.allocFraction") \
    .doc("Fraction of device HBM usable for batches when poolSize=0. "
         "(spark.rapids.memory.gpu.allocFraction)") \
    .check(_fraction).double(0.75)

HOST_SPILL_LIMIT = conf("srt.memory.host.spillStorageSize") \
    .doc("Host memory for spilled buffers before overflowing to disk. "
         "(spark.rapids.memory.host.spillStorageSize)") \
    .bytes_(4 << 30)

SPILL_DIR = conf("srt.memory.spill.dir") \
    .doc("Directory for disk-tier spill files. (Spark local dirs in the "
         "reference, RapidsDiskStore.scala)") \
    .string("/tmp/srt_spill")

RETRY_MAX_SPLITS = conf("srt.memory.retry.maxSplits") \
    .doc("Max recursive halvings of an input batch under "
         "split-and-retry before giving up. (RmmRapidsRetryIterator "
         "semantics)") \
    .check(_positive).integer(8)

OOM_INJECTION_MODE = conf("srt.test.oomInjection.mode") \
    .doc("Test-only: inject synthetic OOM on the Nth allocation "
         "(RmmSpark.forceRetryOOM analogue). NONE|RETRY|SPLIT") \
    .internal().check_values(["NONE", "RETRY", "SPLIT"]).string("NONE")

READER_TYPE = conf("srt.sql.format.parquet.reader.type") \
    .doc("Parquet reader strategy: PERFILE, COALESCING, or MULTITHREADED "
         "(cloud). (spark.rapids.sql.format.parquet.reader.type, "
         "GpuParquetScan.scala:1862,2057)") \
    .check_values(["PERFILE", "COALESCING", "MULTITHREADED"]) \
    .string("COALESCING")

READER_THREADS = conf("srt.sql.multiThreadedRead.numThreads") \
    .doc("Host threads for the multithreaded reader pool. "
         "(spark.rapids.sql.multiThreadedRead.numThreads)") \
    .check(_positive).integer(8)

MAX_READER_BATCH_SIZE_ROWS = conf("srt.sql.reader.batchSizeRows") \
    .doc("Soft cap on rows per scan batch. "
         "(spark.rapids.sql.reader.batchSizeRows)") \
    .check(_positive).integer(1 << 20)

SHUFFLE_MODE = conf("srt.shuffle.mode") \
    .doc("Shuffle transport: MESH (XLA all-to-all over ICI/DCN), "
         "MULTITHREADED (host partition exchange), CACHE_ONLY (single "
         "process). (spark.rapids.shuffle.mode, RapidsConf.scala:1495)") \
    .check_values(["MESH", "MULTITHREADED", "CACHE_ONLY"]).string("CACHE_ONLY")

SHUFFLE_PARTITIONS = conf("srt.shuffle.partitions") \
    .doc("Default shuffle partition count (spark.sql.shuffle.partitions)") \
    .check(_positive).integer(8)

EXCHANGE_ENABLED = conf("srt.shuffle.exchange.enabled") \
    .doc("Plan shuffle/broadcast exchanges between pipeline stages "
         "(EnsureRequirements): hash exchange before aggregate merge and "
         "shuffled joins, range exchange before global sort, broadcast "
         "exchange for small build sides. When false the staged "
         "operators run single-stream. "
         "(GpuShuffleExchangeExecBase.scala:167)") \
    .commonly_used().boolean(True)

BROADCAST_THRESHOLD_ROWS = conf("srt.sql.broadcastRowThreshold") \
    .doc("Estimated build-side row count at or below which a join uses a "
         "broadcast exchange instead of shuffling both sides. "
         "(spark.sql.autoBroadcastJoinThreshold, bytes there — rows here "
         "because batch capacities are row-bucketed)") \
    .check(_positive).integer(100_000)

JOIN_SUB_PARTITION_ROWS = conf("srt.sql.join.subPartitionRows") \
    .doc("Join build sides above this many rows are hash-split into "
         "sub-partitions and joined pair-wise so the build working set "
         "stays bounded instead of requiring the whole side in one "
         "device batch. (spark.rapids.sql.test.subPartitioning / "
         "GpuSubPartitionHashJoin.scala)") \
    .check(_positive).integer(1 << 22)

AGG_MERGE_PARTITION_ROWS = conf("srt.sql.agg.mergePartitionRows") \
    .doc("Aggregate merge passes whose concatenated partial rows exceed "
         "this are hash-re-partitioned by group key and merged bucket "
         "by bucket (the reference's re-partition merge fallback, "
         "GpuAggregateExec.scala:711,792).") \
    .check(_positive).integer(1 << 22)

SORT_OOC_ROWS = conf("srt.sql.sort.oocRowBudget") \
    .doc("Sort partitions whose total rows exceed this merge their "
         "spilled sorted runs with a bounded-memory k-way chunk merge "
         "instead of one full-size concat+sort — device residency "
         "stays O(budget) regardless of partition size (the "
         "out-of-core iterator of GpuSortExec.scala:242).") \
    .check(_positive).integer(1 << 22)

_SHUFFLE_CODECS = ("NONE", "LZ4", "ZSTD")

SHUFFLE_COMPRESS = conf("srt.shuffle.compression.codec") \
    .doc("Codec for serialized shuffle buffers: NONE, LZ4 (native "
         "codec), or ZSTD. "
         "(spark.rapids.shuffle.compression.codec, nvcomp LZ4 in the "
         "reference)") \
    .check(lambda v: None if str(v).upper() in _SHUFFLE_CODECS
           else f"unknown codec {v!r}; allowed (case-insensitive): "
                f"{list(_SHUFFLE_CODECS)}").string("NONE")

SHUFFLE_PUSH_ENABLED = conf("srt.shuffle.push.enabled") \
    .doc("Push-based shuffle: map tasks eagerly push their compressed "
         "blocks to the owning reducer's endpoint at map completion, "
         "and the receiving side consolidates them into per-reducer "
         "segments so a reduce read is one sequential scan plus a "
         "pull of whatever was never pushed (the pull path is the "
         "always-correct fallback). (Spark's push-based shuffle / "
         "magnet role)") \
    .commonly_used().boolean(True)

SHUFFLE_PUSH_IN_FLIGHT_BYTES = conf("srt.shuffle.push.maxInFlightBytes") \
    .doc("Per-endpoint cap on un-acknowledged pushed bytes; map tasks "
         "block pushing to a slow reducer past this window so push "
         "memory stays bounded regardless of fan-out "
         "(BounceBufferManager role on the push side).") \
    .check(_positive).bytes_(32 << 20)

SHUFFLE_PUSH_LOCAL_BYPASS = conf("srt.shuffle.push.localBypass") \
    .doc("Locality bypass: when producer and consumer share a process "
         "(driver-local session; mesh-co-located partitions in MESH "
         "mode) the live ColumnarBatch is handed through a zero-copy "
         "local channel, skipping serializer+socket+deserializer. "
         "Bypassed bytes are reported as shuffleBytesBypassed.") \
    .boolean(True)

ADAPTIVE_ENABLED = conf("srt.sql.adaptive.enabled") \
    .doc("Adaptive query execution: re-plan stages on runtime shuffle "
         "statistics — coalesce small reduce partitions and switch "
         "shuffled joins to broadcast when the materialized build side "
         "is small. (spark.sql.adaptive.enabled; "
         "GpuQueryStagePrepOverrides / GpuCustomShuffleReaderExec)") \
    .commonly_used().boolean(True)

ADAPTIVE_MIN_PARTITION_ROWS = conf(
    "srt.sql.adaptive.coalescePartitions.minPartitionRows") \
    .doc("AQE merges adjacent reduce partitions until each group holds "
         "at least this many rows "
         "(spark.sql.adaptive.coalescePartitions.minPartitionSize, rows "
         "here because batch capacities are row-bucketed).") \
    .check(_positive).integer(1 << 16)

ADAPTIVE_BROADCAST_ROWS = conf("srt.sql.adaptive.autoBroadcastJoinRows") \
    .doc("A shuffled join whose materialized build side has at most "
         "this many rows switches to broadcast at runtime, skipping "
         "the probe-side shuffle (spark.sql.adaptive."
         "autoBroadcastJoinThreshold). 0 falls back to "
         "srt.sql.broadcastRowThreshold.") \
    .integer(0)

ADAPTIVE_SKEW_ROWS = conf("srt.sql.adaptive.skewJoin.partitionRows") \
    .doc("A reduce partition whose PROBE side exceeds this many rows "
         "in a shuffled join splits into map-slices joined separately "
         "against the full build partition (spark.sql.adaptive."
         "skewJoin.skewedPartitionThreshold; the "
         "GpuCustomShuffleReaderExec skewed-partition-spec role).") \
    .check(_positive).integer(1 << 20)

ADAPTIVE_COALESCE_ENABLED = conf(
    "srt.sql.adaptive.coalescePartitions.enabled") \
    .doc("AQE rule 1: merge adjacent small reduce partitions after the "
         "map side materializes, using measured per-partition rows and "
         "bytes (spark.sql.adaptive.coalescePartitions.enabled).") \
    .boolean(True)

ADAPTIVE_TARGET_BYTES = conf(
    "srt.sql.adaptive.coalescePartitions.targetBytes") \
    .doc("Coalesced partition groups close once they reach this many "
         "measured shuffle bytes, even below minPartitionRows — the "
         "byte-size generalization of the row floor "
         "(spark.sql.adaptive.advisoryPartitionSizeInBytes). 0 keeps "
         "the rows-only behavior.") \
    .check(_non_negative).bytes_(8 << 20)

ADAPTIVE_SKEW_ENABLED = conf("srt.sql.adaptive.skewJoin.enabled") \
    .doc("AQE rule 2: split skewed reduce partitions of a shuffled "
         "join into map-slices replicated against the other side "
         "(spark.sql.adaptive.skewJoin.enabled).") \
    .boolean(True)

ADAPTIVE_SKEW_BYTES = conf("srt.sql.adaptive.skewJoin.partitionBytes") \
    .doc("A reduce partition whose PROBE side exceeds this many "
         "measured shuffle bytes is skew-split, independent of the row "
         "threshold (spark.sql.adaptive.skewJoin."
         "skewedPartitionThresholdInBytes). 0 disables the byte "
         "trigger.") \
    .check(_non_negative).bytes_(64 << 20)

ADAPTIVE_JOIN_ENABLED = conf("srt.sql.adaptive.join.enabled") \
    .doc("AQE rule 3: demote a shuffled join to broadcast (or cap an "
         "oversized broadcast build via sub-partitioning) when the "
         "MEASURED build side contradicts the plan-time estimate "
         "(DynamicJoinSelection / spark.sql.adaptive."
         "autoBroadcastJoinThreshold direction flips).") \
    .boolean(True)

ADAPTIVE_BROADCAST_BYTES = conf("srt.sql.adaptive.autoBroadcastJoinBytes") \
    .doc("A shuffled join whose materialized build side has at most "
         "this many measured shuffle bytes switches to broadcast at "
         "runtime, in addition to the autoBroadcastJoinRows row "
         "trigger. 0 disables the byte trigger.") \
    .check(_non_negative).bytes_(0)

ADAPTIVE_MAX_BROADCAST_BYTES = conf(
    "srt.sql.adaptive.maxBroadcastBuildBytes") \
    .doc("A plan-time broadcast join whose MATERIALIZED build side "
         "exceeds this many bytes is forced onto the bounded "
         "sub-partition join path (the broadcast->shuffle 'promote' "
         "mitigation: the exchange topology is fixed per attempt, so "
         "the memory-safety half of promotion is what AQE can still "
         "deliver mid-flight). 0 disables.") \
    .check(_non_negative).bytes_(0)

ADAPTIVE_SPECULATION_ENABLED = conf("srt.sql.adaptive.speculation.enabled") \
    .doc("AQE rule 4: when a heartbeat-alive worker lags the map side "
         "of a shuffle stage, the driver re-executes its map shards on "
         "an idle worker; first result wins in the map-output registry "
         "and losing blocks are never fetched "
         "(spark.speculation; default off, matching Spark).") \
    .boolean(False)

ADAPTIVE_SPECULATION_FACTOR = conf(
    "srt.sql.adaptive.speculation.slowWorkerFactor") \
    .doc("A worker is a straggler once its barrier arrival lags the "
         "median arrived worker by this multiple "
         "(spark.speculation.multiplier).") \
    .check(_positive).double(3.0)

ADAPTIVE_SPECULATION_MIN_WAIT_S = conf(
    "srt.sql.adaptive.speculation.minWaitSec") \
    .doc("Never speculate before the first arrival has waited this "
         "many seconds — bounds wasted duplicate work on naturally "
         "short stages.") \
    .check(_non_negative).double(1.0)

LEGACY_ADAPTIVE_BROADCAST_ROWS = conf("srt.sql.adaptiveBroadcastRows") \
    .doc("DEPRECATED alias for srt.sql.adaptive.autoBroadcastJoinRows "
         "(pre-AQE-subsystem name). Setting it forwards to the new key "
         "and warns once per process.") \
    .integer(0)

SESSION_TIMEZONE = conf("srt.sql.session.timeZone") \
    .doc("Session timezone id used by timezone-aware SQL functions "
         "(spark.sql.session.timeZone). Conversions run on device "
         "against materialized transition tables (GpuTimeZoneDB "
         "analogue, expr/timezone.py).") \
    .string("UTC")

PARQUET_REBASE_READ = conf("srt.sql.parquet.datetimeRebaseModeInRead") \
    .doc("How to treat pre-1582-10-15 dates/timestamps in parquet "
         "reads: CORRECTED (as written, proleptic Gregorian), LEGACY "
         "(rebase from the hybrid Julian calendar), EXCEPTION (fail). "
         "(spark.sql.parquet.datetimeRebaseModeInRead, "
         "datetimeRebaseUtils.scala)") \
    .check_values(["CORRECTED", "LEGACY", "EXCEPTION"]) \
    .string("CORRECTED")

PARQUET_REBASE_WRITE = conf("srt.sql.parquet.datetimeRebaseModeInWrite") \
    .doc("Calendar for pre-1582-10-15 dates/timestamps in parquet "
         "writes: CORRECTED, LEGACY (rebase to hybrid Julian), or "
         "EXCEPTION. (spark.sql.parquet.datetimeRebaseModeInWrite)") \
    .check_values(["CORRECTED", "LEGACY", "EXCEPTION"]) \
    .string("CORRECTED")

METRICS_LEVEL = conf("srt.metrics.level") \
    .doc("Operator metric detail kept in per-query summaries and the "
         "metrics registry: ESSENTIAL, MODERATE, DEBUG. "
         "(spark.rapids.sql.metrics.level, GpuExec.scala:36-49)") \
    .check_values(["ESSENTIAL", "MODERATE", "DEBUG"]).string("MODERATE")

EVENT_LOG_ENABLED = conf("srt.eventLog.enabled") \
    .doc("Write a structured JSONL event log (QueryStart/End, "
         "StageSubmitted/Completed, TaskEnd, SpillToHost/Disk, "
         "FetchFailed, RetryAttempt, FaultInjected, "
         "CorruptionDetected...) to srt.eventLog.dir — one "
         "events-<pid>.jsonl per process, Spark history-server role. "
         "Off by default: when disabled no event sink is instantiated "
         "and every emit site is a single None check "
         "(obs/events.py).") \
    .boolean(False)

EVENT_LOG_DIR = conf("srt.eventLog.dir") \
    .doc("Directory for event-log files (and per-query Chrome traces "
         "when srt.eventLog.trace.enabled). Created on first emit; "
         "defaults to ./srt-events when enabled without a dir. Feed "
         "it to tools/profile_report.py for an offline per-query "
         "report (spark.eventLog.dir role).") \
    .string("")

TRACE_ENABLED = conf("srt.eventLog.trace.enabled") \
    .doc("Record per-query spans (query -> stage -> task -> operator) "
         "and write a Chrome-trace (catapult) JSON file "
         "trace-<query_id>.json next to the event log. Requires "
         "srt.eventLog.enabled for the file to land; spans add one "
         "object per operator pull, so leave off for benchmarking "
         "(NvtxWithMetrics.scala role). On a cluster the driver ships "
         "its trace context with each job so worker spans parent "
         "under the driver's; tools/history_report.py clock-aligns "
         "and merges the per-process trace-*.json files.") \
    .boolean(False)

EVENT_LOG_MAX_BYTES = conf("srt.eventLog.maxBytes") \
    .doc("Rotate events-<pid>.jsonl when it exceeds this many bytes: "
         "the live file rolls to .1 (and .1 to .2, which is dropped "
         "on the next roll), bounding a long-running process to about "
         "three segments of this size. 0 disables rotation. Readers "
         "(tools/profile_report.py, tools/history_report.py) stitch "
         "rolled segments back in order (spark.eventLog.rolling role).") \
    .check(_non_negative).bytes_(0)

RESOURCE_SAMPLE_INTERVAL_MS = conf("srt.obs.resource.intervalMs") \
    .doc("Period of the background resource sampler, which records "
         "ResourceSample events (RSS, device memory in use, spill-pool "
         "occupancy, fetch-pool queue depth, prefetch buffer bytes) to "
         "the event log so stalls can be correlated with memory "
         "pressure. Requires srt.eventLog.enabled. 0 (default) "
         "disables sampling: no thread is started and the hot path "
         "stays a module-global None check.") \
    .check(_non_negative).integer(0)

ROOFLINE_ENABLED = conf("srt.obs.roofline.enabled") \
    .doc("Master switch for the roofline observability layer: "
         "ProgramCompiled events on every shared-program compile "
         "(trace/lower/compile wall time + XLA cost_analysis flops/"
         "bytes), per-launch device-time sampling, and per-query "
         "RooflineSummary events. The compile ledger itself (counters "
         "in obs/roofline.py) always records — it costs one dict "
         "update per program COMPILE, never per batch — but with this "
         "off nothing is sampled and no roofline events are emitted.") \
    .boolean(True)

ROOFLINE_SAMPLE_EVERY = conf("srt.obs.roofline.sampleEvery") \
    .doc("Device-time sampling stride for shared jit programs: every "
         "Nth launch of each program is timed with a device sync and "
         "joined with the compile ledger's bytes/flops to produce "
         "achieved GB/s and FLOP/s (effective_gb_s histograms, "
         "per-query RooflineSummary). Steady-state cost is one counter "
         "increment per launch plus one block_until_ready per N "
         "launches — under 2 percent at the default. 0 disables "
         "sampling (and "
         "per-query roofline summaries) entirely.") \
    .check(_non_negative).integer(32)

ROOFLINE_CALIBRATE = conf("srt.obs.roofline.calibrate") \
    .doc("Measure this process's peak copy bandwidth once (a ~64MB "
         "jitted copy probe, the tools/roofline.py denominator moved "
         "in-engine) so roofline summaries report utilization — "
         "achieved GB/s over measured peak — instead of raw rates. "
         "Off by default: the probe costs a one-time device "
         "allocation + a few launches, which benchmarks may not "
         "want.") \
    .boolean(False)

CPU_ORACLE_STRICT = conf("srt.test.cpuOracle.strict") \
    .doc("Test-only: fail instead of falling back when an operator cannot "
         "run on TPU (assert_tpu_fallback analogue).") \
    .internal().boolean(False)

ALLOW_INCOMPAT = conf("srt.sql.incompatibleOps.enabled") \
    .doc("Enable operators whose semantics differ from Spark in corner "
         "cases. (spark.rapids.sql.incompatibleOps.enabled)") \
    .boolean(True)

ANSI_ENABLED = conf("srt.sql.ansi.enabled") \
    .doc("ANSI mode: arithmetic overflow and invalid casts raise instead "
         "of returning null/wrapping (spark.sql.ansi.enabled semantics; "
         "GpuCast.scala AnsiCast paths).") \
    .boolean(False)

IGNORE_CORRUPT_FILES = conf("srt.sql.ignoreCorruptFiles") \
    .doc("Skip-and-warn instead of failing when a file is corrupt "
         "(unreadable, truncated, bad checksum) during a scan — "
         "Spark's spark.sql.files.ignoreCorruptFiles semantics: rows "
         "already decoded from the broken file are kept, the rest of "
         "the file is dropped with a warning. Default FAILFAST "
         "(raise).") \
    .boolean(False)

IGNORE_MISSING_FILES = conf("srt.sql.ignoreMissingFiles") \
    .doc("Skip-and-warn instead of failing when a scan file has been "
         "deleted between planning and execution — Spark's "
         "spark.sql.files.ignoreMissingFiles semantics.") \
    .boolean(False)

DELTA_DURABLE_COMMITS = conf("srt.delta.durableCommits") \
    .doc("Crash-durable Delta commits: every transaction-log commit "
         "fsyncs the commit file and its parent directory (and every "
         "staged data file before its rename promotes it), so a "
         "machine crash immediately after commit() returns can never "
         "lose or tear the version. Disable only to A/B the fsync "
         "overhead (the ingest_rows_per_s bench lane measures it).") \
    .boolean(True)

DELTA_COMMIT_MAX_RETRIES = conf("srt.delta.commit.maxRetries") \
    .doc("How many times an optimistic Delta committer re-validates "
         "and retries after losing the O_EXCL race for its target "
         "version before surfacing CommitConflict.") \
    .check(lambda v: None if v >= 0 else "must be >= 0").integer(10)

DELTA_COMMIT_BACKOFF_MS = conf("srt.delta.commit.backoffMs") \
    .doc("Base backoff in milliseconds between Delta commit-conflict "
         "retries; grows exponentially per attempt with +-50% jitter, "
         "capped at 32x the base. 0 retries immediately.") \
    .check(lambda v: None if v >= 0 else "must be >= 0").integer(15)

DELTA_CHECKPOINT_INTERVAL = conf("srt.delta.checkpointInterval") \
    .doc("Write a compacted log checkpoint (NNN.checkpoint.json + "
         "_last_checkpoint pointer) every this many commits, bounding "
         "snapshot replay to the commits after the checkpoint. The "
         "checkpoint carries a crc32 — a torn/corrupt checkpoint is "
         "detected and replay falls back to the full JSON log. "
         "0 disables checkpointing.") \
    .check(lambda v: None if v >= 0 else "must be >= 0").integer(10)

DELTA_VACUUM_RETENTION_SEC = conf("srt.delta.vacuum.retentionSec") \
    .doc("VACUUM's retention guard for files the log has never "
         "referenced (crash orphans: staged .tmp files and promoted-"
         "but-uncommitted data files): younger files survive the "
         "sweep because they may belong to a commit in flight. "
         "Staging files whose owning pid is provably dead are swept "
         "regardless of age. Files tombstoned by a committed remove "
         "action are always reclaimable.") \
    .check(lambda v: None if v >= 0 else "must be >= 0").double(600.0)

INTEGRITY_CHECKSUM = conf("srt.integrity.checksum.enabled") \
    .doc("Verify crc32c-style checksums on every off-device byte path "
         "(shuffle blocks at serve/fetch/local read, host+disk spill "
         "entries at re-materialization, file-cache entries on hit). "
         "Corruption converts to a retryable fetch failure on the "
         "transport and raises DataCorruption from storage tiers — "
         "no silent wrong answers. Disable only to A/B the (noise-"
         "level) checksum overhead.") \
    .boolean(True)

MESH_DATA_AXIS = conf("srt.mesh.dataAxis") \
    .doc("Name of the mesh axis partitions are sharded over.") \
    .internal().string("data")

MESH_STAGE_PROGRAMS = conf("srt.mesh.stagePrograms.enabled") \
    .doc("Compile one SPMD program per query stage (everything between "
         "shuffle boundaries, as cut by plan/adaptive.py) instead of "
         "one monolithic program for the whole plan. Stage outputs "
         "stay device-resident between programs, exchange collectives "
         "run at the consumer stage's head (or vanish entirely under "
         "the residency rule), and a join-overflow retry re-runs ONLY "
         "the overflowing stage at doubled growth from its retained "
         "inputs — the whole-plan retry ladder that re-executed every "
         "leaf (and aborted q19 at scale) is gone. Off = legacy "
         "whole-plan lowering, kept as the fallback boundary.") \
    .boolean(True)

MESH_RESIDENCY = conf("srt.mesh.residency.enabled") \
    .doc("Planner residency rule for mesh exchanges: an exchange whose "
         "child already satisfies the target placement (hash on the "
         "same key exprs, range on the same orders, single partition "
         "over single partition) lowers to a device-resident identity "
         "hand-through pinned by with_sharding_constraint instead of "
         "an in-program all_to_all — the generalized "
         "MeshColocationBypass. Also respects "
         "srt.shuffle.push.localBypass (the single-box face of the "
         "same locality contract).") \
    .boolean(True)

MESH_DONATION = conf("srt.mesh.donation.enabled") \
    .doc("Donate consumed stage inputs to the stage program "
         "(jit donate_argnums) so XLA reuses their buffers in place. "
         "Only applied when the stage cannot retry (no join-overflow "
         "check) and the input has exactly one consumer.") \
    .boolean(True)

MESH_BROADCAST_REPLICATED = conf("srt.mesh.broadcastReplicated") \
    .doc("Place shuffle-free broadcast build subtrees host-executed "
         "and replicated (PartitionSpec()) on every device instead of "
         "lowering them per-shard and all_gathering inside the "
         "program — the partition-rule table's "
         "BroadcastExchangeExec -> replicated row.") \
    .boolean(True)

MESH_PARTITION_RULES = conf("srt.mesh.partitionRules") \
    .doc("Extra partition rules prepended to the built-in table: "
         "';'-separated 'regex=data|replicated' clauses matched "
         "against each stage input's rule path (class names joined "
         "with '/', stage root first). First match wins; the built-in "
         "table replicates broadcast subtrees and shards everything "
         "else over the data axis.") \
    .string("")

MESH_MAX_JOIN_GROWTH = conf("srt.mesh.maxJoinGrowth") \
    .doc("Upper bound on the per-stage join output growth factor the "
         "overflow retry may reach before the query fails (each retry "
         "doubles the factor for the overflowing stage only).") \
    .check(lambda v: None if v >= 1 else "must be >= 1").integer(64)

URI_REWRITE_RULES = conf("srt.io.uriRewrite") \
    .doc("Ordered 'FROM->TO;FROM2->TO2' prefix rewrite rules applied to "
         "scan paths before file resolution — mount-style remote-store "
         "acceleration (spark.rapids.alluxio.pathsToReplace role).") \
    .string("")

FILECACHE_ENABLED = conf("srt.filecache.enabled") \
    .doc("Cache scanned input files on local disk with LRU eviction "
         "(spark.rapids.filecache.enabled role).") \
    .boolean(False)

FILECACHE_DIR = conf("srt.filecache.dir") \
    .doc("Directory for the scan file cache.") \
    .string("/tmp/srt_filecache")

FILECACHE_MAX_SIZE = conf("srt.filecache.maxSize") \
    .doc("File-cache capacity in bytes; least-recently-used files are "
         "evicted past this size.") \
    .bytes_(1 << 30)

FILECACHE_LOCAL_FS = conf("srt.filecache.useForLocalFiles") \
    .doc("Also cache local-filesystem files (the reference caches only "
         "remote filesystems by default; this knob exists for tests and "
         "for slow network mounts that look local).") \
    .boolean(False)

DEBUG_DUMP_PATH = conf("srt.debug.dumpPath") \
    .doc("When set, each operator keeps its most recent output batch "
         "and an execution failure dumps them all (plus the plan tree "
         "and error) under this directory as parquet for offline "
         "replay (DumpUtils.scala crash-dump role). Debug tool: holds "
         "one extra batch per operator alive.") \
    .string("")

EXTRA_PLUGINS = conf("srt.plugins") \
    .doc("Comma-separated 'pkg.module:attr' entries loaded at "
         "initialize: each attr is called with the active conf "
         "(spark.rapids.sql.plugins / RapidsPluginUtils "
         "loadExtraPlugins role).") \
    .string("")

LEAK_DETECTION = conf("srt.memory.leakDetection.enabled") \
    .doc("Track the creation stack of every SpillableBatch and report "
         "entries still registered at shutdown/reset "
         "(MemoryCleaner/RapidsBufferCatalog leak-detection role). "
         "Adds per-allocation traceback capture cost; test/debug "
         "tool.") \
    .boolean(False)

WINDOW_BATCHED_RUNNING = conf("srt.sql.window.batchedRunning.enabled") \
    .doc("Stream running-frame window functions (rank family, ROWS "
         "unbounded-preceding..current-row aggregates) batch-at-a-time "
         "over a sorted child with carried state instead of "
         "materializing whole partitions "
         "(GpuRunningWindowExec/BatchedRunningWindowFixer role).") \
    .boolean(True)

JOIN_BLOOM_BITS_PER_KEY = conf("srt.sql.join.bloomFilter.bitsPerKey") \
    .doc("Bloom filter sizing: bits per build-side key (rounded up to a "
         "power of two, clamped to [2^10, 2^24] bits).") \
    .check(_positive).integer(10)

JOIN_GROWTH_STEPS = conf("srt.sql.join.outputGrowthSteps") \
    .doc("Max output-capacity doublings for a join whose true match "
         "count overflows the estimate before the probe batch splits "
         "(SplitAndRetryOOM contract).") \
    .check(_positive).integer(4)

RANGE_SAMPLE_SIZE = conf("srt.shuffle.sample.sizePerPartition") \
    .doc("Range-partitioner sketch size: sample rows per output "
         "partition used to derive bounds "
         "(spark.sql.execution.rangeExchange.sampleSizePerPartition).") \
    .check(_positive).integer(40)

CLUSTER_BARRIER_TIMEOUT = conf("srt.cluster.barrierTimeoutSec") \
    .doc("Seconds a cluster worker waits on a driver shuffle barrier / "
         "gather before treating the attempt as failed.") \
    .check(_positive).integer(120)

PALLAS_TILE_ROWS = conf("srt.sql.pallas.tileRows") \
    .doc("Row-tile size for fused pallas reductions (one HBM->VMEM DMA "
         "per tile; must be a multiple of 1024).") \
    .check(lambda v: None if v % 1024 == 0 and v > 0
           else "must be a positive multiple of 1024") \
    .integer(8192)

JOIN_BLOOM_ENABLED = conf("srt.sql.join.bloomFilter.enabled") \
    .doc("Build a bloom filter over the materialized build side of "
         "inner/semi hash joins and pre-filter probe batches with it "
         "(GpuBloomFilterAggregate/MightContain runtime-filter role). "
         "Pays one hash pass per side; wins when most probe rows have "
         "no match.") \
    .boolean(True)

JOIN_BLOOM_MIN_PROBE_ROWS = conf("srt.sql.join.bloomFilter.minProbeRows") \
    .doc("Skip the bloom pre-filter when a probe batch is smaller than "
         "this (filter overhead would exceed the join saving).") \
    .check(_positive).integer(4096)

PYTHON_WORKERS_MAX = conf("srt.python.workers.max") \
    .doc("Maximum pooled Python worker processes for vectorized pandas "
         "UDFs (ArrowEvalPython). Workers are reused across batches and "
         "queries. (python/rapids/daemon.py worker pool role)") \
    .check(_positive).integer(4)

PARQUET_NATIVE_DECODE = conf("srt.sql.format.parquet.nativeDecode.enabled") \
    .doc("Decode eligible parquet column chunks (fixed-width types, "
         "Snappy/uncompressed, PLAIN/RLE_DICTIONARY, v1 pages) in the "
         "native C++ runtime without the GIL; ineligible columns and "
         "files fall back to pyarrow per column/file. "
         "(GpuParquetScan.scala:2624 device-decode role, host-native "
         "stage.)") \
    .boolean(True)

ORC_NATIVE_DECODE = conf("srt.sql.format.orc.nativeDecode.enabled") \
    .doc("Decode eligible ORC files (flat numeric schemas, "
         "DIRECT_V2/RLEv2 with PRESENT streams, "
         "NONE/ZLIB/SNAPPY/ZSTD) in the native C++ runtime; anything "
         "outside the envelope falls back to pyarrow per file. "
         "(GpuOrcScan.scala device-decode role, host-native stage.)") \
    .boolean(True)

SHUFFLE_FETCH_MAX_CONCURRENT = conf("srt.shuffle.fetch.maxConcurrent") \
    .doc("Peers fetched in parallel per reduce partition over the TCP "
         "shuffle transport (RapidsShuffleClient maxInFlight role).") \
    .check(_positive).integer(4)

SHUFFLE_FETCH_IN_FLIGHT_BYTES = conf("srt.shuffle.fetch.inFlightBytes") \
    .doc("Byte budget for fetched-but-not-yet-consumed shuffle blocks "
         "per reduce partition (BounceBufferManager window role): "
         "producers stall when the window is full, bounding reduce "
         "fan-in host memory.") \
    .check(_positive).integer(128 * 1024 * 1024)

SHUFFLE_FETCH_POOL_SIZE = conf("srt.shuffle.fetch.poolSize") \
    .doc("Worker threads in the process-wide shuffle fetch pool shared "
         "by every reduce partition (replaces per-endpoint one-shot "
         "thread churn; RapidsShuffleClient exec pool role). Per-reduce "
         "concurrency is still capped by "
         "srt.shuffle.fetch.maxConcurrent.") \
    .check(_positive).integer(8)

PIPELINE_ENABLED = conf("srt.exec.pipeline.enabled") \
    .doc("Run blocking plan edges (scan decode, shuffle fetch/"
         "deserialize, broadcast materialization) on background "
         "producer threads behind a bounded prefetch queue so host I/O "
         "overlaps device compute (exec/pipeline.py; multithreaded "
         "reader + RapidsShuffleIterator fetch-ahead role). Queued "
         "batches register as on-deck spillable; producer-side "
         "failures re-raise on the consuming thread at the same plan "
         "node as synchronous mode.") \
    .commonly_used().boolean(True)

PIPELINE_DEPTH = conf("srt.exec.pipeline.depth") \
    .doc("Max batches queued per pipelined edge. 2 double-buffers: the "
         "producer stages batch N+1 while the consumer computes on "
         "batch N; higher values smooth bursty sources at the cost of "
         "more on-deck memory (bounded by "
         "srt.exec.pipeline.maxBytesInFlight).") \
    .check(_positive).integer(2)

PIPELINE_MAX_BYTES = conf("srt.exec.pipeline.maxBytesInFlight") \
    .doc("Byte budget for batches queued per pipelined edge; the "
         "producer stalls while the queue holds this much. A single "
         "batch over the budget is admitted alone into an empty queue "
         "(progress guarantee). Accepts k/m/g suffixes.") \
    .check(_positive).bytes_(256 * 1024 * 1024)

FETCH_MAX_RETRIES = conf("srt.shuffle.fetch.maxRetries") \
    .doc("Reconnect attempts per peer when a shuffle block fetch fails "
         "mid-stream (connection refused/reset, timeout). Already-"
         "received blocks are skipped on the retried stream, so a "
         "retry never duplicates a block "
         "(RapidsShuffleClient retry discipline).") \
    .check(lambda v: None if v >= 0 else "must be >= 0").integer(3)

FETCH_BACKOFF_BASE_S = conf("srt.shuffle.fetch.backoffBaseSec") \
    .doc("Base delay for exponential backoff between shuffle fetch "
         "retries; attempt n sleeps base * 2^(n-1) * (1 + jitter), "
         "jitter in [0, 0.25).") \
    .check(_positive).double(0.05)

FETCH_TIMEOUT_S = conf("srt.shuffle.fetch.timeoutSec") \
    .doc("Per-ATTEMPT socket timeout for shuffle block fetches (connect "
         "and each read); a stalled peer costs one attempt, not the "
         "whole fetch.") \
    .check(_positive).double(30.0)

HEARTBEAT_INTERVAL_S = conf("srt.cluster.heartbeatIntervalSec") \
    .doc("Seconds between a cluster worker's liveness heartbeats to the "
         "driver's ShuffleHeartbeatManager "
         "(RapidsShuffleHeartbeatManager executorHeartbeatInterval).") \
    .check(_positive).double(2.0)

HEARTBEAT_TIMEOUT_S = conf("srt.cluster.heartbeatTimeoutSec") \
    .doc("Seconds of heartbeat silence before the driver declares a "
         "worker dead, evicts it, and breaks its barriers (failure "
         "detection instead of waiting out barrierTimeoutSec). Keep "
         "comfortably above the longest GIL-bound stall (XLA compiles "
         "block the heartbeat thread).") \
    .check(_positive).double(30.0)

DECOMMISSION_ENABLED = conf("srt.cluster.decommission.enabled") \
    .doc("Workers install a SIGTERM handler for graceful decommission "
         "(Spark's spark.decommission.enabled role): on SIGTERM or a "
         "driver 'decommission' frame the worker finishes its in-flight "
         "job, drains pending pushes, migrates its completed map-output "
         "blocks to a live buddy peer as replicas, and deregisters — so "
         "a planned shutdown costs zero stage re-executions.") \
    .boolean(True)

DECOMMISSION_TIMEOUT_S = conf("srt.cluster.decommission.timeoutSec") \
    .doc("Wall-clock budget in seconds for a decommissioning worker's "
         "drain + block-migration phase; on expiry the remaining blocks "
         "are abandoned to normal recovery (buddy replicas if "
         "replicated, else stage re-execution).") \
    .check(_positive).double(30.0)

SHUFFLE_REPLICATION_FACTOR = conf("srt.shuffle.replication.factor") \
    .doc("Copies of each completed map-output block across the cluster: "
         "1 keeps the origin worker authoritative (classic); 2 also "
         "pushes every block to a deterministic buddy worker over the "
         "eager-push framing, so a hard worker kill degrades to a "
         "buddy replica fetch instead of a stage re-execution. Replicas "
         "are addressed by (origin, shuffle, map, reduce) and never "
         "serve normal fetches, so map-id collisions across workers "
         "are impossible.") \
    .check(lambda v: None if v >= 1 else "must be >= 1").integer(1)

FAULT_PLAN_SPEC = conf("srt.test.faultPlan") \
    .doc("Fault-injection plan spec (robustness/faults.py grammar), "
         "armed in every process that executes with this conf — cluster "
         "workers arm it from the job conf. Empty disables injection.") \
    .internal().string("")

DPP_ENABLED = conf("srt.sql.dpp.enabled") \
    .doc("Runtime dynamic partition pruning: when a broadcast join's "
         "probe side scans a partitioned table on a partition column, "
         "the materialized build side's distinct keys prune the scan's "
         "file list before any probe file opens "
         "(GpuSubqueryBroadcastExec / DynamicPruningExpression role).") \
    .boolean(True)

PYTHON_UDF_TIMEOUT = conf("srt.python.udf.timeoutSec") \
    .doc("Seconds a single pandas-UDF batch may run in a worker before "
         "the worker is killed and the job fails (guards against hung "
         "UDFs wedging the engine; 0 disables).") \
    .check(lambda v: v >= 0).integer(600)

PALLAS_ENABLED = conf("srt.sql.pallas.enabled") \
    .doc("Execute eligible global filter+aggregate pipelines as fused "
         "pallas TPU kernels (one HBM pass, no filtered intermediate). "
         "On TPU the fused kernel computes float sums in float32 with "
         "float64 cross-tile combination — the same corner-case "
         "deviation class as spark.rapids.sql.variableFloatAgg.enabled; "
         "on CPU (interpret mode) arithmetic stays float64-exact.") \
    .boolean(True)

PALLAS_GROUPED_ENABLED = conf("srt.sql.pallas.groupedAgg.enabled") \
    .doc("Execute eligible grouped aggregations (sum/avg over floats, "
         "count) through the one-hot MXU pallas kernel "
         "(ops/pallas_kernels.tile_group_reduce) when a batch resolves "
         "to <= 1024 groups via the hash-claim prelude; larger key "
         "domains and non-sum-decomposable aggregates keep the XLA "
         "scatter path inside the same traced program. Active on TPU "
         "(or with SRT_PALLAS_GROUPED_FORCE=1, the CPU interpret-mode "
         "test lane). Float sums share srt.sql.pallas.enabled's "
         "variableFloatAgg-class deviation on TPU.") \
    .boolean(True)

PALLAS_GROUP_MAX_CAPACITY = conf("srt.exec.pallas.groupAgg.maxCapacity") \
    .doc("Batch-capacity ceiling for the grouped pallas MXU lane. "
         "Per-bucket counts accumulate in float32 lanes on the MXU and "
         "float32 represents integers exactly only below 2^24, so "
         "batches at or above this capacity take the stock integer "
         "scatter/sort path (Count/CountStar would otherwise drift). "
         "Raising it past 2^24 trades count exactness for MXU "
         "coverage; a forced fallback logs one PallasCapacityFallback "
         "event per process.") \
    .check(_positive).integer(1 << 24)

FUSION_ENABLED = conf("srt.exec.fusion.enabled") \
    .doc("Operator-fusion pass (plan/overrides.py -> exec/fused.py): "
         "collapse scan -> filter -> project -> partial-aggregate "
         "chains into one jitted program per chain so intermediate "
         "batches never round-trip through HBM (cuDF fused "
         "filter/project + GpuHashAggregateExec partial-on-scan role). "
         "Chains holding eager or partition-context expressions "
         "(input_file_name, spark_partition_id, ...) always stay "
         "unfused.") \
    .commonly_used().boolean(True)

FUSION_EXCLUDE_EXECS = conf("srt.exec.fusion.excludeExecs") \
    .doc("Comma-separated exec class names (FilterExec, ProjectExec, "
         "HashAggregateExec) the fusion matcher must not absorb into a "
         "FusedPipelineExec — an opt-out list for isolating a "
         "suspected fusion miscompare without turning the whole pass "
         "off. An excluded class breaks the chain at that node.") \
    .string("")

FUSION_DONATE = conf("srt.exec.fusion.donateInputs") \
    .doc("Donate the input batch's device buffers to the fused program "
         "(jax.jit donate_argnums) so XLA reuses them for the output "
         "instead of allocating fresh HBM. Applied only on non-CPU "
         "backends and only when the chain's source produces "
         "single-use buffers (file scans, not in-memory tables whose "
         "batches are re-executed). For fused joins the probe batch is "
         "donated only on capacity-measured relaunches, where the "
         "launch is provably final and the batch provably dead.") \
    .boolean(True)

FUSION_JOINS = conf("srt.exec.fusion.joins") \
    .doc("Hash-join fusion (fusion v2): compile build+probe plus the "
         "filter/project/partial-aggregate suffix above the join into "
         "one jitted program per probe batch, so the joined batch "
         "never materializes in HBM between operators. The join node "
         "keeps all of its own orchestration — broadcast demotion, "
         "skew splits, sub-partitioning, bloom prefilter, DPP and "
         "capacity-growth retries (plan/adaptive.py decisions apply "
         "unchanged; only the per-pair program is swapped). Joins "
         "with eager key expressions or a post-join condition stay "
         "unfused.") \
    .commonly_used().boolean(True)

FUSION_FINAL_AGG = conf("srt.exec.fusion.finalAgg") \
    .doc("FINAL-mode HashAggregate fusion (fusion v2): compile the "
         "post-shuffle merge pass together with its upstream "
         "coalesce/project — partial batches concatenate, project and "
         "merge+finalize inside one jitted program instead of an "
         "eager concat followed by a separate merge launch. Falls "
         "back to an eager pre-concat above "
         "srt.exec.fusion.finalAgg.maxMergeInputs batches.") \
    .commonly_used().boolean(True)

FUSION_MERGE_MAX_INPUTS = conf("srt.exec.fusion.finalAgg.maxMergeInputs") \
    .doc("Largest number of partial batches handed to the fused "
         "FINAL-merge program as separate arguments (each distinct "
         "count is its own cached program signature). Above this the "
         "batches are eagerly concatenated first and the single-input "
         "fused program runs — correctness is unchanged, one extra "
         "HBM materialization is paid.") \
    .check(_positive).integer(8)

FUSION_SORT = conf("srt.exec.fusion.sort") \
    .doc("Sort-prefix fusion (fusion v2) for the out-of-core sorter "
         "(exec/sort.py): chunk slicing + head-row extraction, "
         "carry+chunk concat + key-extraction + local sort, and the "
         "bound-row safe-prefix count each run as one jitted program "
         "instead of eager kernel calls between separate launches.") \
    .boolean(True)

OPTIMIZER_ENABLED = conf("srt.sql.optimizer.enabled") \
    .doc("Cost-based optimizer: keep plans below the row threshold on "
         "the CPU engine where device compile/transfer overhead "
         "dominates. (spark.rapids.sql.optimizer.enabled, "
         "CostBasedOptimizer.scala:54)") \
    .boolean(False)

OPTIMIZER_ROW_THRESHOLD = conf("srt.sql.optimizer.rowThreshold") \
    .doc("Weighted row-volume below which the cost model keeps a plan "
         "on CPU (only with srt.sql.optimizer.enabled).") \
    .check(_positive).integer(10_000)

CONCURRENT_QUERY_TASKS = conf("srt.sql.concurrentQueryTasks") \
    .doc("Number of queries admitted to execute concurrently against "
         "the device pool; further queries wait in a bounded admission "
         "queue. Also sets the number of per-query memory-budget "
         "slices. (spark.rapids.sql.concurrentGpuTasks / "
         "GpuSemaphore.scala, lifted from task to query granularity)") \
    .check(_positive).commonly_used().integer(4)

ADMISSION_MAX_QUEUE_DEPTH = conf("srt.sql.admission.maxQueueDepth") \
    .doc("Maximum queries allowed to WAIT for admission on top of the "
         "running set; arrivals beyond this are load-shed with a "
         "retryable AdmissionRejected instead of queueing unboundedly.") \
    .check(_non_negative).integer(16)

ADMISSION_BACKOFF_BASE_S = conf("srt.sql.admission.backoffBaseSec") \
    .doc("Base seconds for the exponential backoff (with jitter) a "
         "queued query sleeps between admission re-checks; doubles per "
         "attempt up to a small cap. Bounds cancellation/deadline "
         "latency while queued.") \
    .check(_positive).double(0.05)

QUERY_TIMEOUT_S = conf("srt.sql.queryTimeout") \
    .doc("Per-query deadline in seconds, measured from admission "
         "request to last batch; 0 disables. On expiry the query tears "
         "down through every pipeline/fetch thread and raises "
         "DeadlineExceeded. df.collect(timeout=...) overrides per "
         "call.") \
    .check(_non_negative).commonly_used().double(0.0)

SERVE_HOST = conf("srt.serve.host") \
    .doc("Interface the SQL serving front door (serve/server.py) binds "
         "its listening socket to.") \
    .string("127.0.0.1")

SERVE_PORT = conf("srt.serve.port") \
    .doc("TCP port for the SQL serving front door; 0 picks an "
         "ephemeral port (the bound port is on SqlServer.endpoint).") \
    .check(_non_negative).integer(0)

SERVE_AUTH_TOKEN = conf("srt.serve.authToken") \
    .doc("Shared-secret token clients must present in their HELLO "
         "frame; empty disables authentication. A mismatch closes the "
         "connection with a non-retryable error before any session "
         "state is created.") \
    .string("")

SERVE_MAX_SESSIONS = conf("srt.serve.maxSessions") \
    .doc("Maximum concurrently open client sessions; connections "
         "beyond this are refused at HELLO with a retryable error "
         "(session-level load shed, upstream of query admission).") \
    .check(_positive).integer(64)

SERVE_STREAM_CHUNK_ROWS = conf("srt.serve.streamChunkRows") \
    .doc("Maximum rows per result-batch frame streamed back to a "
         "client; larger results split into multiple frames in the "
         "serializer's columnar wire format.") \
    .check(_positive).integer(1 << 16)

RESULT_CACHE_ENABLED = conf("srt.sql.resultCache.enabled") \
    .doc("Cross-tenant result reuse in the serving tier: completed "
         "result sets are cached under a canonicalized-plan "
         "fingerprint (plan_cache.py structural key: file snapshots "
         "fold in mtime/size, Delta scans their commit version) and "
         "replayed for identical resubmissions without re-executing "
         "or re-passing admission. Entries are crc-framed "
         "(robustness/integrity.py) and invalidated by Delta commits "
         "to any scanned table. Bit-identical on/off.") \
    .commonly_used().boolean(False)

RESULT_CACHE_MAX_BYTES = conf("srt.sql.resultCache.maxBytes") \
    .doc("Byte budget for the serving result cache; inserting past "
         "the cap evicts least-recently-used entries first. 0 "
         "disables caching even when enabled.") \
    .check(_non_negative).bytes_(64 << 20)

SHUFFLE_HEARTBEAT_TIMEOUT_S = conf("srt.shuffle.heartbeat.timeoutSec") \
    .doc("DEPRECATED alias for srt.cluster.heartbeatTimeoutSec (the "
         "standalone shuffle service and the cluster driver once read "
         "different keys). Setting it forwards to the new key and warns "
         "once per process.") \
    .check(_positive).double(30.0)


# (key, replacement) pairs resolved in SrtConf.__init__: the old key's
# value forwards to the new key when the new key is unset, with a
# once-per-process deprecation warning.
_DEPRECATED_ALIASES = {
    "srt.sql.adaptiveBroadcastRows": "srt.sql.adaptive.autoBroadcastJoinRows",
    "srt.shuffle.heartbeat.timeoutSec": "srt.cluster.heartbeatTimeoutSec",
}
_ALIAS_WARNED: set = set()


class SrtConf:
    """Immutable snapshot of settings, one per session (RapidsConf)."""

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})
        for k in self._settings:
            if k.startswith("srt.") and k not in _REGISTRY:
                raise KeyError(f"unknown config {k!r}; registered: "
                               f"{sorted(_REGISTRY)}")
            if k in _REGISTRY and _REGISTRY[k].checker is not None:
                # fail fast AT SET TIME, not at first read deep inside a
                # query: run the entry's converter+checker now so e.g. an
                # unknown srt.shuffle.compression.codec raises here with
                # the allowed set in the message
                _REGISTRY[k].get({k: self._settings[k]})
        for old, new in _DEPRECATED_ALIASES.items():
            if old not in self._settings:
                continue
            if old not in _ALIAS_WARNED:
                _ALIAS_WARNED.add(old)
                import warnings
                warnings.warn(f"config {old!r} is deprecated; use {new!r}",
                              DeprecationWarning, stacklevel=2)
            self._settings.setdefault(new, self._settings[old])

    def get(self, entry: ConfEntry):
        return entry.get(self._settings)

    def with_settings(self, **kv) -> "SrtConf":
        s = dict(self._settings)
        s.update({k.replace("_", "."): v for k, v in kv.items()})
        return SrtConf(s)

    def set(self, key: str, value) -> "SrtConf":
        s = dict(self._settings)
        s[key] = value
        return SrtConf(s)

    # Property shorthands used across the codebase
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return self.get(EXPLAIN)

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def shuffle_partitions(self) -> int:
        return self.get(SHUFFLE_PARTITIONS)

    @property
    def ansi(self) -> bool:
        return self.get(ANSI_ENABLED)


_ACTIVE = threading.local()


def active_conf() -> SrtConf:
    c = getattr(_ACTIVE, "conf", None)
    if c is None:
        c = SrtConf()
        _ACTIVE.conf = c
    return c


def set_active_conf(c: SrtConf) -> None:
    _ACTIVE.conf = c


def generate_docs() -> str:
    """Markdown table of all public configs (RapidsConf.main doc-gen,
    RapidsConf.scala:2214 -> docs/configs.md)."""
    lines = ["# spark_rapids_tpu configuration", "",
             "Generated from `spark_rapids_tpu/conf.py` — do not edit.", "",
             "| Name | Default | Description |", "|---|---|---|"]
    for key in sorted(_REGISTRY):
        e = _REGISTRY[key]
        if e.is_internal:
            continue
        doc = e.doc.replace("\n", " ")
        lines.append(f"| {e.key} | {e.default!r} | {doc} |")
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    import sys
    out = sys.argv[1] if len(sys.argv) > 1 else "docs/configs.md"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        f.write(generate_docs())
    print(f"wrote {out}")
