"""SQL parser + analyzer: SELECT text -> logical plan (via DataFrame).

Two phases, mirroring Catalyst's parse -> analyze split (the reference
rides Spark's: SURVEY §2.1-2.2; GpuOverrides.scala:4312 receives the
analyzed physical plan):

1. a recursive-descent parser produces a neutral AST (no schema
   knowledge),
2. the analyzer resolves names against the session catalog / FROM
   scope, plans comma-joins from WHERE equi-conjuncts (left-deep,
   single-table filters pushed below the joins), splits aggregates out
   of SELECT/HAVING/ORDER BY, and lowers everything onto the engine's
   Expression / LogicalPlan layer.
"""

from __future__ import annotations

import datetime
from typing import List, Optional, Sequence, Tuple

from ..columnar import dtypes as dt
from ..expr import aggregates as Agg
from ..expr import arithmetic as A
from ..expr import conditional as Cond
from ..expr import datetime as D
from ..expr import hashing as H
from ..expr import mathfns as M
from ..expr import predicates as P
from ..expr import strings as S
from ..expr.cast import Cast
from ..expr.core import Alias, ColumnRef, Expression, Literal, col, lit, \
    output_name
from .lexer import Token, tokenize


class SqlError(ValueError):
    pass


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

class Ast:
    pass


def _ast_repr(a) -> str:
    """Canonical structural repr for AST equality (GROUP BY dedupe,
    correlated-conjunct matching)."""
    if isinstance(a, Ast) or type(a).__name__ in (
            "TableRefA", "SubqueryA", "JoinA", "SelectA", "UnionA",
            "SetOpA"):
        items = sorted(vars(a).items())
        body = ", ".join(f"{k}={_ast_repr(v)}" for k, v in items)
        return f"{type(a).__name__}({body})"
    if isinstance(a, (list, tuple)):
        return "[" + ", ".join(_ast_repr(x) for x in a) + "]"
    return repr(a)


class ColA(Ast):
    def __init__(self, name, qualifier=None):
        self.name = name
        self.qualifier = qualifier


class StarA(Ast):
    def __init__(self, qualifier=None):
        self.qualifier = qualifier


class LitA(Ast):
    def __init__(self, value):
        self.value = value


class IntervalA(Ast):
    def __init__(self, n, unit):
        self.n = n
        self.unit = unit


class FnA(Ast):
    def __init__(self, name, args, star=False, distinct=False):
        self.name = name
        self.args = args
        self.star = star
        self.distinct = distinct


class BinA(Ast):
    def __init__(self, op, l, r):
        self.op = op
        self.l = l
        self.r = r


class UnA(Ast):
    def __init__(self, op, e):
        self.op = op
        self.e = e


class BetweenA(Ast):
    def __init__(self, e, lo, hi, neg):
        self.e, self.lo, self.hi, self.neg = e, lo, hi, neg


class InA(Ast):
    def __init__(self, e, items, neg):
        self.e, self.items, self.neg = e, items, neg


class LikeA(Ast):
    def __init__(self, e, pattern, neg):
        self.e, self.pattern, self.neg = e, pattern, neg


class IsNullA(Ast):
    def __init__(self, e, neg):
        self.e, self.neg = e, neg


class CaseA(Ast):
    def __init__(self, branches, els):
        self.branches, self.els = branches, els


class CastA(Ast):
    def __init__(self, e, to):
        self.e, self.to = e, to


class OverA(Ast):
    """fn OVER (PARTITION BY ... ORDER BY ... [ROWS|RANGE BETWEEN ...])"""

    def __init__(self, fn, partition, order, frame):
        self.fn = fn
        self.partition = partition    # [Ast]
        self.order = order            # [(Ast, asc, nulls_first)]
        self.frame = frame            # (row_based, lo, hi) | None


class ScalarSubqueryA(Ast):
    def __init__(self, stmt):
        self.stmt = stmt


class _PreLowered(Ast):
    """AST leaf carrying an already-lowered Expression (injected by the
    subquery rewrites); ``lower`` unwraps it."""

    def __init__(self, expr):
        self.expr = expr


def _and_all(conjs):
    out = None
    for c in conjs:
        out = c if out is None else BinA("and", out, c)
    return out


class _GroupingMarker(Expression):
    """GROUPING(key) placeholder; the aggregate-lowering replace() pass
    resolves it to a bit of __grouping_id (0 for plain GROUP BY)."""

    def __init__(self, child: Expression):
        super().__init__(child)

    def data_type(self, schema) -> dt.DType:
        return dt.INT64


class ExistsA(Ast):
    """EXISTS (subquery) — possibly correlated."""

    def __init__(self, stmt):
        self.stmt = stmt


class InSubqueryA(Ast):
    """expr IN (subquery) — possibly correlated."""

    def __init__(self, e, stmt, neg):
        self.e = e
        self.stmt = stmt
        self.neg = neg


class TableRefA:
    def __init__(self, name, alias):
        self.name = name
        self.alias = alias or name


class SubqueryA:
    def __init__(self, stmt, alias):
        self.stmt = stmt
        self.alias = alias


class JoinA:
    def __init__(self, ref, how, on):
        self.ref = ref      # TableRefA | SubqueryA
        self.how = how      # None (comma) | inner|left|right|full|cross
        self.on = on


class SelectA:
    def __init__(self):
        self.distinct = False
        self.items: List[Tuple[Ast, Optional[str]]] = []
        self.from_: List[JoinA] = []
        self.where: Optional[Ast] = None
        self.group_by: List[Ast] = []
        #: GROUPING SETS / ROLLUP / CUBE: list of grouping sets, each a
        #: list of indexes into group_by; None = plain GROUP BY
        self.group_sets: Optional[List[List[int]]] = None
        self.having: Optional[Ast] = None
        self.order_by: List[Tuple[Ast, bool, Optional[bool]]] = []
        self.limit: Optional[int] = None
        #: WITH name AS (...) bindings visible to this statement
        self.ctes: List[Tuple[str, "Ast"]] = []


class UnionA:
    def __init__(self, left, right, all_):
        self.left, self.right, self.all = left, right, all_
        self.order_by: List = []
        self.limit = None
        self.ctes: List = []


class SetOpA:
    """INTERSECT / EXCEPT (set semantics follow ``all``)."""

    def __init__(self, op, left, right, all_):
        self.op = op            # "intersect" | "except"
        self.left, self.right, self.all = left, right, all_
        self.order_by: List = []
        self.limit = None
        self.ctes: List = []


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

_JOIN_KINDS = {"inner": "inner", "left": "left", "right": "right",
               "full": "full", "cross": "cross"}


class Parser:
    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.i = 0

    # --- token helpers ---
    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.value.lower() in kws

    def accept_kw(self, *kws: str) -> Optional[str]:
        if self.at_kw(*kws):
            return self.next().value.lower()
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlError(f"expected {kw.upper()} near "
                           f"{self.peek().value!r} @{self.peek().pos}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value in ops

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.next().value
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlError(f"expected {op!r} near {self.peek().value!r} "
                           f"@{self.peek().pos}")

    # --- statements ---
    def parse_statement(self):
        stmt = self.parse_set_expr()
        self.accept_op(";")
        if self.peek().kind != "EOF":
            raise SqlError(f"unexpected trailing input "
                           f"{self.peek().value!r} @{self.peek().pos}")
        return stmt

    def parse_set_expr(self):
        """[WITH ...] select-term {UNION|EXCEPT [ALL] select-term}
        with INTERSECT binding tighter (SQL precedence), then trailing
        ORDER BY / LIMIT on the whole set expression."""
        ctes = []
        if self.accept_kw("with"):
            while True:
                name = self.next().value
                self.expect_kw("as")
                self.expect_op("(")
                sub = self.parse_set_expr()
                self.expect_op(")")
                ctes.append((name, sub))
                if not self.accept_op(","):
                    break
        stmt = self.parse_intersect_term()
        while self.at_kw("union", "except", "minus"):
            op = self.next().value.lower()
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self.parse_intersect_term()
            if op == "union":
                u = UnionA(stmt, right, all_)
            else:
                u = SetOpA("except", stmt, right, all_)
            self._hoist_order_limit(u, right)
            stmt = u
        # trailing ORDER BY / LIMIT apply to the whole set expression
        if self.at_kw("order"):
            stmt.order_by = self.parse_order_by()
        if self.accept_kw("limit"):
            stmt.limit = int(self.next().value)
        stmt.ctes = ctes + getattr(stmt, "ctes", [])
        return stmt

    # select-terms that came from "( ... )": their ORDER BY/LIMIT are
    # legitimately inner and must NOT hoist to the set expression
    _parenthesized: set = None

    def _hoist_order_limit(self, u, right) -> None:
        """A trailing ORDER BY/LIMIT greedily parsed into the LAST
        unparenthesized branch binds to the whole set expression."""
        if id(right) in (self._parenthesized or ()):
            return
        if isinstance(right, (SelectA, UnionA, SetOpA)):
            u.order_by, right.order_by = right.order_by, []
            u.limit, right.limit = right.limit, None

    def parse_intersect_term(self):
        stmt = self.parse_select_term()
        while self.at_kw("intersect"):
            self.next()
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self.parse_select_term()
            u = SetOpA("intersect", stmt, right, all_)
            self._hoist_order_limit(u, right)
            stmt = u
        return stmt

    def parse_select_term(self):
        if self.at_op("("):
            self.next()
            inner = self.parse_set_expr()
            self.expect_op(")")
            if self._parenthesized is None:
                self._parenthesized = set()
            self._parenthesized.add(id(inner))
            return inner
        return self.parse_select_core()

    def parse_select_core(self) -> SelectA:
        self.expect_kw("select")
        s = SelectA()
        if self.accept_kw("distinct"):
            s.distinct = True
        else:
            self.accept_kw("all")
        # select list
        while True:
            item = self.parse_expr()
            alias = None
            if self.accept_kw("as"):
                alias = self.next().value
            elif self.peek().kind == "IDENT" and not self.at_kw(
                    "from", "where", "group", "having", "order", "limit",
                    "union", "except", "minus", "intersect",
                    "inner", "left", "right", "full", "cross",
                    "join", "on"):
                alias = self.next().value
            s.items.append((item, alias))
            if not self.accept_op(","):
                break
        if self.accept_kw("from"):
            s.from_.append(JoinA(self.parse_table_ref(), None, None))
            while True:
                if self.accept_op(","):
                    s.from_.append(JoinA(self.parse_table_ref(), None, None))
                    continue
                how = None
                for kw, mapped in _JOIN_KINDS.items():
                    if self.at_kw(kw):
                        self.next()
                        how = mapped
                        break
                if how in ("left", "right", "full"):
                    self.accept_kw("outer")
                if how is not None:
                    self.expect_kw("join")
                elif self.at_kw("join"):
                    self.next()
                    how = "inner"
                else:
                    break
                ref = self.parse_table_ref()
                on = None
                if how != "cross" and self.accept_kw("on"):
                    on = self.parse_expr()
                s.from_.append(JoinA(ref, how, on))
        if self.accept_kw("where"):
            s.where = self.parse_expr()
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            self._parse_group_by(s)
        if self.accept_kw("having"):
            s.having = self.parse_expr()
        if self.at_kw("order") and self._lookahead_is_order_by():
            s.order_by = self.parse_order_by()
        if self.accept_kw("limit"):
            s.limit = int(self.next().value)
        return s

    def _parse_group_by(self, s: SelectA) -> None:
        """Plain exprs, optionally mixed with ONE of ROLLUP(...),
        CUBE(...), GROUPING SETS((...),...). ``s.group_by`` collects the
        distinct key exprs in order; ``s.group_sets`` (when non-plain)
        holds index lists into group_by per output grouping set, with
        plain exprs present in every set."""
        base: List[Ast] = []
        construct = None  # (kind, [expr or [exprs]])
        while True:
            if self.at_kw("rollup", "cube"):
                if construct is not None:
                    raise SqlError("multiple ROLLUP/CUBE/GROUPING SETS "
                                   "constructs in one GROUP BY are not "
                                   "supported")
                kind = self.next().value.lower()
                self.expect_op("(")
                exprs = [self.parse_expr()]
                while self.accept_op(","):
                    exprs.append(self.parse_expr())
                self.expect_op(")")
                construct = (kind, exprs)
            elif self.at_kw("grouping"):
                if construct is not None:
                    raise SqlError("multiple ROLLUP/CUBE/GROUPING SETS "
                                   "constructs in one GROUP BY are not "
                                   "supported")
                self.next()
                self.expect_kw("sets")
                self.expect_op("(")
                sets = []
                while True:
                    if self.accept_op("("):
                        grp = []
                        if not self.at_op(")"):
                            grp.append(self.parse_expr())
                            while self.accept_op(","):
                                grp.append(self.parse_expr())
                        self.expect_op(")")
                        sets.append(grp)
                    else:
                        sets.append([self.parse_expr()])
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                construct = ("sets", sets)
            else:
                base.append(self.parse_expr())
            if not self.accept_op(","):
                break
        if construct is None:
            s.group_by = base
            return
        kind, payload = construct
        if kind == "rollup":
            variable = [payload[:i] for i in range(len(payload), -1, -1)]
        elif kind == "cube":
            variable = []
            n = len(payload)
            for m in range((1 << n) - 1, -1, -1):
                variable.append([payload[i] for i in range(n)
                                 if m & (1 << (n - 1 - i))])
        else:
            variable = payload
        # distinct keys in first-appearance order; sets as index lists
        keys: List[Ast] = list(base)

        def key_idx(e: Ast) -> int:
            for i, k in enumerate(keys):
                if _ast_repr(k) == _ast_repr(e):
                    return i
            keys.append(e)
            return len(keys) - 1
        base_idx = [key_idx(e) for e in base]
        sets_idx = []
        for grp in variable:
            sets_idx.append(base_idx + [key_idx(e) for e in grp])
        s.group_by = keys
        s.group_sets = sets_idx

    def _lookahead_is_order_by(self) -> bool:
        t = self.toks[self.i + 1]
        return t.kind == "IDENT" and t.value.lower() == "by"

    def parse_order_by(self):
        self.expect_kw("order")
        self.expect_kw("by")
        out = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.accept_kw("desc"):
                asc = False
            else:
                self.accept_kw("asc")
            nulls_first = None
            if self.accept_kw("nulls"):
                which = self.next().value.lower()
                nulls_first = which == "first"
            out.append((e, asc, nulls_first))
            if not self.accept_op(","):
                break
        return out

    def _maybe_over(self, fn: FnA) -> Ast:
        if not self.at_kw("over"):
            return fn
        self.next()
        self.expect_op("(")
        partition = []
        order = []
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        if self.at_kw("order"):
            order = self.parse_order_by()
        kind = self.accept_kw("rows", "range")
        if kind:
            self.expect_kw("between")
            lo = self._parse_frame_bound()
            self.expect_kw("and")
            hi = self._parse_frame_bound()
            frame = (kind == "rows", lo, hi)
        self.expect_op(")")
        return OverA(fn, partition, order, frame)

    def _parse_frame_bound(self):
        """UNBOUNDED PRECEDING/FOLLOWING | CURRENT ROW | n PRECEDING |
        n FOLLOWING -> None or signed int offset."""
        if self.accept_kw("unbounded"):
            self.next()  # preceding / following
            return None
        if self.accept_kw("current"):
            self.expect_kw("row")
            return 0
        t = self.next()
        if t.kind != "NUMBER":
            raise SqlError(f"bad frame bound {t.value!r}")
        n = int(t.value)
        which = self.next().value.lower()
        return -n if which == "preceding" else n

    def parse_table_ref(self):
        if self.accept_op("("):
            stmt = self.parse_set_expr()
            self.expect_op(")")
            if self.accept_kw("as"):
                alias = self.next().value
            elif self.peek().kind == "IDENT" and not self.at_kw(
                    "where", "group", "having", "order", "limit", "union",
                    "except", "minus", "intersect",
                    "inner", "left", "right", "full", "cross", "join",
                    "on"):
                alias = self.next().value
            else:
                alias = f"__subq{self.i}"
            return SubqueryA(stmt, alias)
        name = self.next().value
        alias = None
        if self.accept_kw("as"):
            alias = self.next().value
        elif self.peek().kind == "IDENT" and not self.at_kw(
                "where", "group", "having", "order", "limit", "union",
                "except", "minus", "intersect",
                "inner", "left", "right", "full", "cross", "join", "on"):
            alias = self.next().value
        return TableRefA(name, alias)

    # --- expressions (precedence climbing) ---
    def parse_expr(self) -> Ast:
        return self.parse_or()

    def parse_or(self) -> Ast:
        e = self.parse_and()
        while self.accept_kw("or"):
            e = BinA("or", e, self.parse_and())
        return e

    def parse_and(self) -> Ast:
        e = self.parse_not()
        while self.accept_kw("and"):
            e = BinA("and", e, self.parse_not())
        return e

    def parse_not(self) -> Ast:
        if self.accept_kw("not"):
            return UnA("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Ast:
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            stmt = self.parse_set_expr()
            self.expect_op(")")
            return ExistsA(stmt)
        e = self.parse_additive()
        neg = bool(self.accept_kw("not"))
        if self.accept_kw("between"):
            lo = self.parse_additive()
            self.expect_kw("and")
            hi = self.parse_additive()
            return BetweenA(e, lo, hi, neg)
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.at_kw("select", "with"):
                stmt = self.parse_set_expr()
                self.expect_op(")")
                return InSubqueryA(e, stmt, neg)
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return InA(e, items, neg)
        if self.accept_kw("like"):
            pat = self.next()
            if pat.kind != "STRING":
                raise SqlError("LIKE pattern must be a string literal")
            return LikeA(e, pat.value, neg)
        if self.accept_kw("rlike", "regexp"):
            pat = self.next()
            if pat.kind != "STRING":
                raise SqlError("RLIKE pattern must be a string literal")
            return FnA("rlike", [e, LitA(pat.value)]) if not neg else \
                UnA("not", FnA("rlike", [e, LitA(pat.value)]))
        if neg:
            raise SqlError("dangling NOT before non-predicate")
        if self.accept_kw("is"):
            isneg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return IsNullA(e, isneg)
        op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
        if op:
            return BinA(op, e, self.parse_additive())
        return e

    def parse_additive(self) -> Ast:
        e = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-", "||")
            if not op:
                return e
            e = BinA(op, e, self.parse_multiplicative())

    def parse_multiplicative(self) -> Ast:
        e = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                if self.at_kw("div"):  # integral division keyword op
                    self.next()
                    e = BinA("div", e, self.parse_unary())
                    continue
                return e
            e = BinA(op, e, self.parse_unary())

    def parse_unary(self) -> Ast:
        if self.accept_op("-"):
            t = self.peek()
            if t.kind == "NUMBER":
                # fold the sign into the literal (Spark AstBuilder does
                # this so Long.MinValue is a VALID literal rather than
                # -(9223372036854775808) overflowing to decimal)
                self.next()
                if "." in t.value or "e" in t.value.lower():
                    return LitA(-float(t.value))
                return LitA(-int(t.value))
            return UnA("-", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Ast:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                return LitA(float(t.value))
            return LitA(int(t.value))
        if t.kind == "STRING":
            self.next()
            return LitA(t.value)
        if t.kind == "OP" and t.value == "(":
            self.next()
            if self.at_kw("select", "with"):
                stmt = self.parse_set_expr()
                self.expect_op(")")
                return ScalarSubqueryA(stmt)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "OP" and t.value == "*":
            self.next()
            return StarA()
        if t.kind != "IDENT":
            raise SqlError(f"unexpected token {t.value!r} @{t.pos}")
        word = t.value
        lower = word.lower()
        # typed literals
        if lower == "date" and self.toks[self.i + 1].kind == "STRING":
            self.next()
            s = self.next().value
            return LitA(datetime.date.fromisoformat(s))
        if lower == "timestamp" and self.toks[self.i + 1].kind == "STRING":
            self.next()
            s = self.next().value
            v = datetime.datetime.fromisoformat(s)
            if v.tzinfo is None:
                v = v.replace(tzinfo=datetime.timezone.utc)
            return LitA(v)
        if lower == "interval":
            self.next()
            nt = self.next()
            if nt.kind == "STRING":
                n = int(nt.value)
            elif nt.kind == "NUMBER":
                n = int(nt.value)
            else:
                raise SqlError("bad INTERVAL quantity")
            unit = self.next().value.lower().rstrip("s")
            return IntervalA(n, unit)
        if lower in ("true", "false"):
            self.next()
            return LitA(lower == "true")
        if lower == "null":
            self.next()
            return LitA(None)
        if lower == "case":
            return self.parse_case()
        if lower == "cast":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            to = self.parse_type()
            self.expect_op(")")
            return CastA(e, to)
        if lower == "extract":
            self.next()
            self.expect_op("(")
            field = self.next().value.lower()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_op(")")
            return FnA(field, [e])
        self.next()
        # function call?
        if self.at_op("("):
            self.next()
            if self.accept_op("*"):
                self.expect_op(")")
                return self._maybe_over(FnA(lower, [], star=True))
            if self.at_op(")"):
                self.next()
                return self._maybe_over(FnA(lower, []))
            distinct = bool(self.accept_kw("distinct"))
            args = [self.parse_expr()]
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
            return self._maybe_over(FnA(lower, args, distinct=distinct))
        # qualified name / star
        if self.at_op("."):
            self.next()
            if self.accept_op("*"):
                return StarA(qualifier=word)
            return ColA(self.next().value, qualifier=word)
        return ColA(word)

    def parse_case(self) -> Ast:
        self.expect_kw("case")
        branches = []
        base = None
        if not self.at_kw("when"):
            base = self.parse_expr()
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            if base is not None:
                cond = BinA("=", base, cond)
            branches.append((cond, val))
        els = None
        if self.accept_kw("else"):
            els = self.parse_expr()
        self.expect_kw("end")
        return CaseA(branches, els)

    def parse_type(self) -> dt.DType:
        name = self.next().value.lower()
        simple = {
            "boolean": dt.BOOL, "bool": dt.BOOL,
            "tinyint": dt.INT8, "byte": dt.INT8,
            "smallint": dt.INT16, "short": dt.INT16,
            "int": dt.INT32, "integer": dt.INT32,
            "bigint": dt.INT64, "long": dt.INT64,
            "float": dt.FLOAT32, "real": dt.FLOAT32,
            "double": dt.FLOAT64,
            "string": dt.STRING, "varchar": dt.STRING, "text": dt.STRING,
            "date": dt.DATE, "timestamp": dt.TIMESTAMP,
        }
        if name in simple:
            if name == "varchar" and self.accept_op("("):
                self.next()
                self.expect_op(")")
            return simple[name]
        if name in ("decimal", "numeric"):
            p, s = 10, 0
            if self.accept_op("("):
                p = int(self.next().value)
                if self.accept_op(","):
                    s = int(self.next().value)
                self.expect_op(")")
            return dt.DecimalType(p, s)
        raise SqlError(f"unknown type {name!r}")


# ---------------------------------------------------------------------------
# Analyzer: AST -> DataFrame
# ---------------------------------------------------------------------------

_AGG_FNS = {
    "sum": Agg.Sum, "min": Agg.Min, "max": Agg.Max,
    "avg": Agg.Average, "mean": Agg.Average,
    "stddev": Agg.StddevSamp, "stddev_samp": Agg.StddevSamp,
    "stddev_pop": Agg.StddevPop,
    "variance": Agg.VarianceSamp, "var_samp": Agg.VarianceSamp,
    "var_pop": Agg.VariancePop,
    "first": Agg.First, "last": Agg.Last,
    "collect_list": Agg.CollectList, "collect_set": Agg.CollectSet,
}

_UNARY_FNS = {
    "abs": A.Abs, "sqrt": M.Sqrt, "cbrt": M.Cbrt, "exp": M.Exp,
    "ln": M.Log, "log": M.Log, "log2": M.Log2, "log10": M.Log10,
    "sin": M.Sin, "cos": M.Cos, "tan": M.Tan, "asin": M.Asin,
    "acos": M.Acos, "atan": M.Atan, "sinh": M.Sinh, "cosh": M.Cosh,
    "tanh": M.Tanh, "degrees": M.ToDegrees, "radians": M.ToRadians,
    "sign": M.Signum, "signum": M.Signum, "floor": M.Floor,
    "ceil": M.Ceil, "ceiling": M.Ceil,
    "length": S.Length, "char_length": S.Length,
    "octet_length": S.OctetLength,
    "upper": S.Upper, "ucase": S.Upper, "lower": S.Lower,
    "lcase": S.Lower, "trim": S.StringTrim, "ltrim": S.StringTrimLeft,
    "rtrim": S.StringTrimRight, "reverse": S.Reverse,
    "initcap": S.InitCap, "isnan": P.IsNaN,
    "year": D.Year, "month": D.Month, "day": D.DayOfMonth,
    "dayofmonth": D.DayOfMonth, "quarter": D.Quarter,
    "dayofweek": D.DayOfWeek, "dayofyear": D.DayOfYear,
    "weekday": D.WeekDay, "last_day": D.LastDay,
    "hour": D.Hour, "minute": D.Minute, "second": D.Second,
}

_BINARY_FNS = {
    "pow": M.Pow, "power": M.Pow, "atan2": M.Atan2, "hypot": M.Hypot,
    "pmod": A.Pmod, "date_add": D.DateAdd, "date_sub": D.DateSub,
    "datediff": D.DateDiff, "add_months": D.AddMonths,
    "nullif": Cond.NullIf, "nvl": Cond.Nvl, "ifnull": Cond.Nvl,
}

_VARARG_FNS = {
    "concat": S.Concat, "coalesce": Cond.Coalesce,
    "least": A.Least, "greatest": A.Greatest,
    "hash": H.Murmur3Hash, "xxhash64": H.XxHash64,
}


class _Scope:
    """FROM-clause name resolution.

    Entries are ``(alias, [(user_name, internal_name)])``: when two FROM
    tables share a column name, the analyzer renames the physical
    columns to unique internal names before joining (our plans use flat
    column names), and this mapping resolves qualified references to the
    right copy."""

    def __init__(self, entries, types=None):
        self.entries = list(entries)
        #: internal column name -> DType (for type-dependent lowering)
        self.types = dict(types or {})

    def type_schema(self):
        return list(self.types.items())

    def resolve(self, name: str, qualifier: Optional[str]) -> str:
        if qualifier is not None:
            for alias, cols in self.entries:
                if alias.lower() == qualifier.lower():
                    for user, internal in cols:
                        if user.lower() == name.lower():
                            return internal
                    raise SqlError(f"column {qualifier}.{name} not found")
            raise SqlError(f"unknown table alias {qualifier!r}")
        hits = []
        for alias, cols in self.entries:
            for user, internal in cols:
                if user.lower() == name.lower():
                    hits.append(internal)
                    break
        if not hits:
            raise SqlError(f"column {name!r} not found in scope "
                           f"{[a for a, _ in self.entries]}")
        if len(set(hits)) > 1:
            raise SqlError(f"ambiguous column {name!r}")
        return hits[0]

    def all_columns(self, qualifier: Optional[str] = None):
        """[(user_name, internal_name)] for star expansion."""
        out = []
        for alias, cols in self.entries:
            if qualifier is None or alias.lower() == qualifier.lower():
                out.extend(cols)
        if not out:
            raise SqlError(f"unknown table alias {qualifier!r}")
        return out


class Analyzer:
    def __init__(self, session):
        self.session = session
        #: WITH-binding scopes, innermost last (CTEs see earlier CTEs)
        self._cte_frames: List[dict] = []

    # --- entry ---
    def analyze(self, stmt):
        ctes = getattr(stmt, "ctes", [])
        frame = {}
        if ctes:
            self._cte_frames.append(frame)
            for name, sub in ctes:
                frame[name.lower()] = self.analyze(sub)
        try:
            return self._analyze_body(stmt)
        finally:
            if ctes:
                self._cte_frames.pop()

    def _analyze_body(self, stmt):
        if isinstance(stmt, (UnionA, SetOpA)):
            left = self.analyze_select(stmt.left) if \
                isinstance(stmt.left, SelectA) else self.analyze(stmt.left)
            right = self.analyze_select(stmt.right) if \
                isinstance(stmt.right, SelectA) else self.analyze(stmt.right)
            if isinstance(stmt, UnionA):
                df = left.union(right)
                if not stmt.all:
                    df = df.distinct()
            else:
                df = self._set_op(left, right, stmt.op, stmt.all)
            df = self._order_limit(df, stmt.order_by, stmt.limit,
                                   scope=None)
            return df
        return self.analyze_select(stmt)

    def _set_op(self, left, right, op: str, all_: bool):
        """INTERSECT / EXCEPT via tagged union + group-by (group keys
        treat NULLs as equal — exactly SQL set-op semantics). The
        reference accelerates these through Spark's rewrite onto
        joins/aggregates; this IS that rewrite, engine-side."""
        if all_:
            raise SqlError(f"{op.upper()} ALL is not supported")
        if len(left.schema) != len(right.schema):
            raise SqlError(f"{op.upper()} branches have different "
                           "column counts")
        lnames = [n for n, _ in left.schema]
        right2 = right.select(*[Alias(col(rn), ln)
                                for (ln, _), (rn, _) in
                                zip(left.schema, right.schema)])
        ltag = left.select(*([col(n) for n in lnames] +
                             [Alias(lit(1), "__setl"),
                              Alias(lit(0), "__setr")]))
        rtag = right2.select(*([col(n) for n in lnames] +
                               [Alias(lit(0), "__setl"),
                                Alias(lit(1), "__setr")]))
        u = ltag.union(rtag)
        from ..plan.session import GroupedData
        g = GroupedData(u, [col(n) for n in lnames]).agg(
            Alias(Agg.Sum(col("__setl")), "__cl"),
            Alias(Agg.Sum(col("__setr")), "__cr"))
        if op == "intersect":
            g = g.filter(P.And(P.GreaterThan(col("__cl"), lit(0)),
                               P.GreaterThan(col("__cr"), lit(0))))
        else:
            g = g.filter(P.And(P.GreaterThan(col("__cl"), lit(0)),
                               P.EqualTo(col("__cr"), lit(0))))
        return g.select(*[col(n) for n in lnames])

    # --- FROM resolution + join planning ---
    def _resolve_ref(self, ref):
        if isinstance(ref, SubqueryA):
            return ref.alias, self.analyze(ref.stmt)
        for frame in reversed(self._cte_frames):
            if ref.name.lower() in frame:
                return ref.alias, frame[ref.name.lower()]
        df = self.session.table(ref.name)
        return ref.alias, df

    def _conjuncts(self, ast) -> List[Ast]:
        if isinstance(ast, BinA) and ast.op == "and":
            return self._conjuncts(ast.l) + self._conjuncts(ast.r)
        return [ast] if ast is not None else []

    def _ast_tables(self, ast, scope: _Scope) -> set:
        """Aliases referenced by an AST (for join planning)."""
        out = set()

        def walk(a):
            if isinstance(a, ColA):
                if a.qualifier is not None:
                    out.add(a.qualifier.lower())
                else:
                    for alias, cols in scope.entries:
                        if any(u.lower() == a.name.lower()
                               for u, _ in cols):
                            out.add(alias.lower())
                            break
            elif isinstance(a, BinA):
                walk(a.l)
                walk(a.r)
            elif isinstance(a, UnA):
                walk(a.e)
            elif isinstance(a, BetweenA):
                walk(a.e), walk(a.lo), walk(a.hi)
            elif isinstance(a, InA):
                walk(a.e)
                for x in a.items:
                    walk(x)
            elif isinstance(a, (LikeA, IsNullA)):
                walk(a.e)
            elif isinstance(a, CastA):
                walk(a.e)
            elif isinstance(a, FnA):
                for x in a.args:
                    walk(x)
            elif isinstance(a, CaseA):
                for c, v in a.branches:
                    walk(c), walk(v)
                if a.els is not None:
                    walk(a.els)
        walk(ast)
        return out

    def analyze_select(self, s: SelectA):
        if not s.from_:
            # SELECT without FROM: single-row relation
            base = self.session.create_dataframe({"__one": [1]},
                                                 [("__one", dt.INT32)])
            scope = _Scope([("", [("__one", "__one")])],
                           {"__one": dt.INT32})
            return self._finish(base, scope, s)

        entries = []           # [(alias, DataFrame)]
        for j in s.from_:
            entries.append(self._resolve_ref(j.ref))

        # duplicate column names across FROM entries get unique internal
        # names (flat-name plans can't hold two columns called "v")
        seen_names = {}
        for alias, df in entries:
            for n, _ in df.schema:
                seen_names[n.lower()] = seen_names.get(n.lower(), 0) + 1
        scope_entries = []
        renamed_entries = []
        type_map = {}
        for alias, df in entries:
            cols = []
            renames = []
            for n, t in df.schema:
                if seen_names[n.lower()] > 1:
                    internal = f"__{alias}__{n}"
                    renames.append(Alias(col(n), internal))
                    cols.append((n, internal))
                else:
                    renames.append(col(n))
                    cols.append((n, n))
                type_map[cols[-1][1]] = t
            if any(isinstance(r, Alias) for r in renames):
                df = df.select(*renames)
            scope_entries.append((alias, cols))
            renamed_entries.append((alias, df))
        entries = renamed_entries
        scope = _Scope(scope_entries, type_map)

        # conjuncts holding subquery predicates (EXISTS / IN (SELECT) /
        # correlated scalar comparisons) lower via joins after the base
        # join tree is built; everything else flows the normal path
        all_conjuncts = self._conjuncts(s.where)
        conjuncts, subq_preds = [], []
        for c in all_conjuncts:
            if self._has_subquery_pred(c):
                subq_preds.append(c)
            else:
                conjuncts.append(c)
        used = [False] * len(conjuncts)

        # WHERE predicates may only be pushed below the joins into
        # tables never on a null-supplying join side (pushing into the
        # right leg of a LEFT JOIN would let null-extended rows through)
        preserved = {entries[0][0].lower()}
        for j, (alias, _) in zip(s.from_[1:], entries[1:]):
            al = alias.lower()
            if j.how in (None, "inner", "cross"):
                preserved.add(al)
            elif j.how == "left":
                pass                      # right leg null-supplied
            elif j.how == "right":
                preserved = {al}          # accumulated left null-supplied
            else:                         # full
                preserved = set()

        table_df = {}
        for idx, (alias, df) in enumerate(entries):
            preds = []
            for ci, c in enumerate(conjuncts):
                if used[ci]:
                    continue
                tabs = self._ast_tables(c, scope)
                if tabs == {alias.lower()} and alias.lower() in preserved:
                    preds.append(c)
                    used[ci] = True
            sub = _Scope([e for e in scope.entries if e[0] == alias],
                         scope.types)
            for p in preds:
                df = df.filter(self.lower(p, sub))
            table_df[alias.lower()] = df

        # left-deep join: explicit JOIN ... ON first, then comma joins
        # connected through WHERE equi-conjuncts
        joined_aliases = [entries[0][0].lower()]
        current = table_df[joined_aliases[0]]

        def current_scope():
            return _Scope([(a, cs) for a, cs in scope.entries
                           if a.lower() in joined_aliases], scope.types)

        def equi_keys(on_conjs, other_alias):
            """Split conjuncts into equi key pairs vs residual."""
            lk, rk, residual = [], [], []
            right_scope = _Scope([(a, cs) for a, cs in scope.entries
                                  if a.lower() == other_alias],
                                 scope.types)
            left_scope = current_scope()
            for c in on_conjs:
                if isinstance(c, BinA) and c.op == "=":
                    lt = self._ast_tables(c.l, scope)
                    rt_ = self._ast_tables(c.r, scope)
                    if lt <= set(joined_aliases) and rt_ == {other_alias}:
                        lk.append(self.lower(c.l, left_scope))
                        rk.append(self.lower(c.r, right_scope))
                        continue
                    if rt_ <= set(joined_aliases) and lt == {other_alias}:
                        lk.append(self.lower(c.r, left_scope))
                        rk.append(self.lower(c.l, right_scope))
                        continue
                residual.append(c)
            return lk, rk, residual

        remaining = [(j, alias) for j, (alias, _) in
                     list(zip(s.from_, entries))[1:]]
        force_cross = False
        while remaining:
            progressed = False
            for k, (j, alias) in enumerate(remaining):
                al = alias.lower()
                if j.how is not None and j.how != "cross":
                    if k != 0:
                        # explicit joins keep declaration order: wait
                        # until everything declared before them is joined
                        continue
                    on_conjs = self._conjuncts(j.on)
                    lk, rk, residual = equi_keys(on_conjs, al)
                    how = {"left": "left_outer", "right": "right_outer",
                           "full": "full_outer"}.get(j.how, j.how)
                    other = table_df[al]
                    if lk:
                        if residual and how != "inner":
                            # a residual ON conjunct changes outer-join
                            # match semantics; filtering after the join
                            # would silently produce inner-join results
                            raise SqlError(
                                f"non-equi ON condition on {j.how} JOIN "
                                "not supported")
                        current = current.join(other, (lk, rk), how=how)
                        joined_aliases.append(al)
                        if residual:
                            sc = current_scope()
                            for c in residual:
                                current = current.filter(self.lower(c, sc))
                    else:
                        if how != "inner":
                            raise SqlError(
                                f"{j.how} JOIN without equi-condition not "
                                "supported")
                        current = current.cross_join(other)
                        joined_aliases.append(al)
                        if on_conjs:
                            sc = current_scope()
                            for c in on_conjs:
                                current = current.filter(self.lower(c, sc))
                    progressed = True
                elif j.how == "cross":
                    current = current.cross_join(table_df[al])
                    joined_aliases.append(al)
                    progressed = True
                else:
                    # comma join: connect via WHERE equi-conjuncts
                    cand = []
                    for ci, c in enumerate(conjuncts):
                        if used[ci]:
                            continue
                        tabs = self._ast_tables(c, scope)
                        if al in tabs and \
                                tabs <= set(joined_aliases + [al]):
                            cand.append((ci, c))
                    lk, rk, residual = equi_keys([c for _, c in cand], al)
                    if not lk and len(remaining) > 1 and not force_cross:
                        continue  # try a better-connected table first
                    for ci, _ in cand:
                        used[ci] = True
                    other = table_df[al]
                    if lk:
                        current = current.join(other, (lk, rk), how="inner")
                    else:
                        current = current.cross_join(other)
                    joined_aliases.append(al)
                    if residual:
                        sc = current_scope()
                        for c in residual:
                            current = current.filter(self.lower(c, sc))
                    progressed = True
                if progressed:
                    remaining.pop(k)
                    break
            if not progressed:
                if not force_cross and any(j.how is None
                                           for j, _ in remaining):
                    # disconnected comma entry: fall back to a cartesian
                    # product rather than failing
                    force_cross = True
                    continue
                raise SqlError("could not order joins (disconnected FROM "
                               "without equi-conditions)")
            force_cross = False

        # leftover WHERE conjuncts (multi-table non-equi)
        full_scope = current_scope()
        for ci, c in enumerate(conjuncts):
            if not used[ci]:
                current = current.filter(self.lower(c, full_scope))
        for c in subq_preds:
            current = self._apply_subquery_pred(current, full_scope, c)
        return self._finish(current, full_scope, s)

    # --- subquery predicates (EXISTS / IN (SELECT) / correlated scalar) ---
    _subq_n = 0

    def _has_subquery_pred(self, a) -> bool:
        if isinstance(a, (ExistsA, InSubqueryA)):
            return True
        if isinstance(a, ScalarSubqueryA):
            return self._is_correlated(a.stmt)
        for v in vars(a).values() if isinstance(a, Ast) else ():
            if isinstance(v, Ast) and self._has_subquery_pred(v):
                return True
            if isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, Ast) and self._has_subquery_pred(x):
                        return True
                    if isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, Ast) and \
                                    self._has_subquery_pred(y):
                                return True
        return False

    def _inner_scope_of(self, stmt) -> Optional[_Scope]:
        """Resolution scope of a subquery's own FROM (schemas only).
        Memoized per stmt object: correlation classification asks for
        it repeatedly and derived-table refs are costly to resolve."""
        cache = getattr(self, "_inner_scope_cache", None)
        if cache is None:
            cache = self._inner_scope_cache = {}
        if id(stmt) in cache:
            return cache[id(stmt)]
        if not isinstance(stmt, SelectA) or not stmt.from_:
            scope = None
        else:
            entries, types = [], {}
            for j in stmt.from_:
                alias, df = self._resolve_ref(j.ref)
                cols = [(n, n) for n, _ in df.schema]
                types.update({n: t for n, t in df.schema})
                entries.append((alias, cols))
            scope = _Scope(entries, types)
        cache[id(stmt)] = scope
        return scope

    def _is_correlated(self, stmt) -> bool:
        """Does the subquery's WHERE reference columns outside its own
        FROM scope?"""
        inner = self._inner_scope_of(stmt)
        if inner is None:
            return False
        for c in self._conjuncts(stmt.where):
            if self._outer_refs(c, inner):
                return True
        return False

    def _outer_refs(self, ast, inner_scope: _Scope) -> bool:
        """True when ``ast`` references a column the inner scope cannot
        resolve (i.e. a correlated outer reference)."""
        found = [False]

        def walk(a):
            if found[0]:
                return
            if isinstance(a, ColA):
                try:
                    inner_scope.resolve(a.name, a.qualifier)
                except SqlError:
                    found[0] = True
                except KeyError:
                    found[0] = True
                return
            if isinstance(a, (ScalarSubqueryA, ExistsA, InSubqueryA)):
                return  # nested subqueries resolve their own scopes
            if isinstance(a, Ast):
                for v in vars(a).items():
                    _walk_val(v[1])

        def _walk_val(v):
            if isinstance(v, Ast):
                walk(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    _walk_val(x)
        walk(ast)
        return found[0]

    def _correlation_split(self, stmt: "SelectA", outer_scope: _Scope):
        """Split a subquery's WHERE into (inner conjuncts, correlation
        pairs [(outer_ast, inner_ast)], outer-only conjuncts, residual
        conjuncts). Residuals reference BOTH scopes non-equi (q94's
        ``ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk``); EXISTS lowers
        them as a post-join filter (``_apply_exists_residual``), other
        shapes reject them."""
        inner = self._inner_scope_of(stmt)
        if inner is None:
            raise SqlError("correlated subquery needs a FROM clause")
        inner_c, pairs, outer_c, residuals = [], [], [], []
        for c in self._conjuncts(stmt.where):
            if not self._outer_refs(c, inner):
                inner_c.append(c)
                continue
            if isinstance(c, BinA) and c.op == "=":
                l_out = self._outer_refs(c.l, inner)
                r_out = self._outer_refs(c.r, inner)
                if l_out and not r_out:
                    pairs.append((c.l, c.r))
                    continue
                if r_out and not l_out:
                    pairs.append((c.r, c.l))
                    continue
            if not self._outer_refs_any_inner(c, inner):
                outer_c.append(c)
                continue
            residuals.append(c)
        return inner_c, pairs, outer_c, residuals

    def _outer_refs_any_inner(self, ast, inner_scope: _Scope) -> bool:
        """Does ``ast`` reference ANY column the inner scope resolves?"""
        found = [False]

        def walk(a):
            if found[0] or not isinstance(a, Ast):
                return
            if isinstance(a, ColA):
                try:
                    inner_scope.resolve(a.name, a.qualifier)
                    found[0] = True
                except (SqlError, KeyError):
                    pass
                return
            for v in vars(a).values():
                if isinstance(v, Ast):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, Ast):
                            walk(x)
        walk(ast)
        return found[0]

    def _plan_semi_source(self, stmt: "SelectA", outer_scope: _Scope,
                          value_ast: Optional[Ast]):
        """Build (sub_df, left_key_exprs, right_key_names) for an
        EXISTS/IN predicate; ``value_ast`` is the outer expression of an
        IN (its match column is the subquery's single select item)."""
        if not isinstance(stmt, SelectA):
            if value_ast is None:
                raise SqlError("EXISTS over set operations is not "
                               "supported")
            # uncorrelated IN over a set expression
            sub_df = self.analyze(stmt)
            if len(sub_df.schema) != 1:
                raise SqlError("IN subquery must return one column")
            n = Analyzer._subq_n = Analyzer._subq_n + 1
            key = f"__sqv{n}"
            sub_df = sub_df.select(
                Alias(col(sub_df.schema[0][0]), key))
            return (sub_df, [self.lower(value_ast, outer_scope)],
                    [key], [])
        inner_c, pairs, outer_c, residuals = self._correlation_split(
            stmt, outer_scope)
        if outer_c:
            raise SqlError("outer-only conjunct inside subquery not "
                           "supported")
        if residuals and value_ast is not None:
            raise SqlError("non-equi correlated predicates are only "
                           "supported in EXISTS")
        if (stmt.group_by or stmt.having) and (pairs or residuals):
            raise SqlError("correlated subquery with GROUP BY/HAVING "
                           "not supported in EXISTS/IN")
        n = Analyzer._subq_n = Analyzer._subq_n + 1
        s2 = SelectA()
        s2.from_ = stmt.from_
        s2.where = _and_all(inner_c)
        s2.group_by = list(stmt.group_by)
        s2.having = stmt.having
        items = []
        left_keys, right_names = [], []
        if value_ast is not None:
            if len(stmt.items) != 1 or isinstance(stmt.items[0][0],
                                                  StarA):
                raise SqlError("IN subquery must select exactly one "
                               "column")
            vname = f"__sqv{n}"
            items.append((stmt.items[0][0], vname))
            left_keys.append(self.lower(value_ast, outer_scope))
            right_names.append(vname)
        for i, (o_ast, i_ast) in enumerate(pairs):
            kname = f"__sqk{n}_{i}"
            items.append((i_ast, kname))
            left_keys.append(self.lower(o_ast, outer_scope))
            right_names.append(kname)
        res_asts = []
        if residuals:
            # project every inner column a residual references under a
            # fresh name and rewrite the residual to reference it; the
            # EXISTS rewrite filters on it post-join
            import copy
            inner_scope = self._inner_scope_of(stmt)
            mapping: dict = {}

            def rw(a):
                if isinstance(a, ColA):
                    try:
                        internal = inner_scope.resolve(a.name,
                                                       a.qualifier)
                    except (SqlError, KeyError):
                        return a
                    if internal not in mapping:
                        fresh = f"__sqr{n}_{len(mapping)}"
                        mapping[internal] = fresh
                        items.append((ColA(a.name, a.qualifier), fresh))
                    return ColA(mapping[internal], None)
                if isinstance(a, (ScalarSubqueryA, ExistsA,
                                  InSubqueryA)):
                    raise SqlError("nested subquery inside a "
                                   "correlated predicate is not "
                                   "supported")
                if not isinstance(a, Ast):
                    return a
                b = copy.copy(a)
                for k, v in vars(a).items():
                    if isinstance(v, Ast):
                        setattr(b, k, rw(v))
                    elif isinstance(v, (list, tuple)):
                        setattr(b, k, type(v)(
                            rw(x) if isinstance(x, Ast) else x
                            for x in v))
                return b

            res_asts = [rw(c) for c in residuals]
        if not items:
            # uncorrelated EXISTS: non-emptiness only
            items.append((LitA(1), f"__sq1_{n}"))
            right_names, left_keys = [], []
        s2.items = items
        sub_df = self.analyze_select(s2)
        return sub_df, left_keys, right_names, res_asts

    def _apply_subquery_pred(self, df, scope: _Scope, ast):
        """Lower one WHERE conjunct containing subquery predicates onto
        joins (the engine-side version of Spark's RewritePredicate
        Subquery, whose output the reference accelerates as
        GpuBroadcastHashJoin left-semi/anti)."""
        neg = False
        inner = ast
        while isinstance(inner, UnA) and inner.op == "not":
            neg = not neg
            inner = inner.e
        if isinstance(inner, ExistsA):
            sub_df, lk, rk, res = self._plan_semi_source(
                inner.stmt, scope, None)
            if res:
                return self._apply_exists_residual(
                    df, scope, sub_df, lk, rk, res, neg)
            if not lk:
                # uncorrelated: EXISTS is a plan-time boolean
                nonempty = len(sub_df.limit(1).collect()) > 0
                keep = nonempty != neg
                return df if keep else df.filter(
                    P.EqualTo(lit(1), lit(0)))
            return df.join(sub_df, (lk, [col(n) for n in rk]),
                           how="left_anti" if neg else "left_semi")
        if isinstance(inner, InSubqueryA):
            effective_neg = neg != inner.neg
            sub_df, lk, rk, _res = self._plan_semi_source(
                inner.stmt, scope, inner.e)
            if effective_neg:
                return self._apply_not_in(df, scope, inner, sub_df, lk,
                                          rk)
            return df.join(sub_df, (lk, [col(n) for n in rk]),
                           how="left_semi")
        if neg:
            raise SqlError("NOT over this subquery predicate shape is "
                           "not supported")
        return self._apply_general_subquery_expr(df, scope, ast)

    def _apply_exists_residual(self, df, scope: _Scope, sub_df, lk, rk,
                               res_asts, neg: bool):
        """EXISTS whose correlation has non-equi conjuncts (q94's
        ``ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk``): tag each outer
        row with a unique id, inner-join to the subquery on the equi
        pairs, filter on the residual, and semi/anti-join the surviving
        ids back. The reference plans this same shape as a conditional
        existence join (GpuBroadcastHashJoinExec with a bound AST
        condition)."""
        from ..expr.misc import monotonically_increasing_id
        n = Analyzer._subq_n = Analyzer._subq_n + 1
        rid = f"__srid{n}"
        out_names = [nm for nm, _t in df.schema]
        df_id = df.with_column(rid, monotonically_increasing_id())
        if lk:
            joined = df_id.join(sub_df, (lk, [col(k) for k in rk]),
                                how="inner")
        else:
            joined = df_id.cross_join(sub_df)
        comb = _Scope(
            scope.entries + [(f"__sub{n}",
                              [(nm, nm) for nm, _t in sub_df.schema])],
            {**scope.types, **dict(sub_df.schema)})
        cond = None
        for a in res_asts:
            e = self.lower(a, comb)
            cond = e if cond is None else P.And(cond, e)
        matched = joined.filter(cond).select(Alias(col(rid), rid))
        kept = df_id.join(matched, ([col(rid)], [col(rid)]),
                          how="left_anti" if neg else "left_semi")
        return kept.select(*[Alias(col(nm), nm) for nm in out_names])

    def _apply_not_in(self, df, scope, inner: "InSubqueryA", sub_df, lk,
                      rk):
        """NOT IN (subquery) with SQL null semantics: any NULL in the
        subquery result ⇒ no row qualifies; a NULL probe value only
        qualifies when the subquery is empty (GpuBroadcastNestedLoopJoin
        null-aware anti join in the reference)."""
        if len(lk) > 1:
            raise SqlError("correlated NOT IN is not supported")
        vname = rk[0]
        from ..plan.session import GroupedData
        agg = GroupedData(sub_df, []).agg(
            Alias(Agg.CountStar(), "__n"),
            Alias(Agg.Count(col(vname)), "__nn"))
        row = agg.collect()[0]
        total, nonnull = row["__n"], row["__nn"]
        if total == 0:
            return df                     # NOT IN ∅ is TRUE
        if nonnull < total:
            return df.filter(P.EqualTo(lit(1), lit(0)))  # NULL ⇒ empty
        out = df.join(sub_df, (lk, [col(n) for n in rk]),
                      how="left_anti")
        return out.filter(P.Not(P.IsNull(lk[0])))

    def _apply_general_subquery_expr(self, df, scope: _Scope, ast):
        """Subquery predicates nested under OR (q10/q35 shape: EXISTS
        (...) OR EXISTS (...)) lower as existence-join markers, plus
        correlated scalar subqueries rewritten to grouped-aggregate
        joins; the rewritten conjunct then filters normally."""
        out_names = [n for n, _ in df.schema]
        repl: dict = {}

        def rewrite(a):
            nonlocal df
            if isinstance(a, ExistsA):
                sub_df, lk, rk, res = self._plan_semi_source(
                    a.stmt, scope, None)
                if res:
                    raise SqlError("non-equi correlated EXISTS under "
                                   "OR is not supported")
                if not lk:
                    nonempty = len(sub_df.limit(1).collect()) > 0
                    return LitA(nonempty)
                n = Analyzer._subq_n = Analyzer._subq_n + 1
                marker = f"__exists{n}"
                sub_m = sub_df.select(
                    *[Alias(col(k), k) for k in rk] +
                    [Alias(lit(True), marker)]).distinct()
                df = df.join(sub_m, (lk, [col(k) for k in rk]),
                             how="left_outer")
                return _PreLowered(Cond.Coalesce(col(marker),
                                                 lit(False)))
            if isinstance(a, InSubqueryA):
                if a.neg:
                    raise SqlError("NOT IN under OR is not supported")
                sub_df, lk, rk, _res = self._plan_semi_source(
                    a.stmt, scope, a.e)
                n = Analyzer._subq_n = Analyzer._subq_n + 1
                marker = f"__exists{n}"
                sub_m = sub_df.select(
                    *[Alias(col(k), k) for k in rk] +
                    [Alias(lit(True), marker)]).distinct()
                df = df.join(sub_m, (lk, [col(k) for k in rk]),
                             how="left_outer")
                return _PreLowered(Cond.Coalesce(col(marker),
                                                 lit(False)))
            if isinstance(a, ScalarSubqueryA) and \
                    self._is_correlated(a.stmt):
                # correlated scalar: rewrite to a grouped aggregate
                # joined on the correlation keys; no match ⇒ NULL ⇒
                # the comparison is UNKNOWN and the row filters out,
                # exactly SQL semantics
                stmt = a.stmt
                if not isinstance(stmt, SelectA) or len(stmt.items) != 1:
                    raise SqlError("correlated scalar subquery must "
                                   "select one expression")
                inner_c, pairs, outer_c, residuals = \
                    self._correlation_split(stmt, scope)
                if outer_c or residuals or not pairs or stmt.group_by:
                    raise SqlError("unsupported correlated scalar "
                                   "subquery shape")
                n = Analyzer._subq_n = Analyzer._subq_n + 1
                s2 = SelectA()
                s2.from_ = stmt.from_
                s2.where = _and_all(inner_c)
                s2.group_by = [i_ast for _, i_ast in pairs]
                vname = f"__scv{n}"
                knames = [f"__sck{n}_{i}" for i in range(len(pairs))]
                s2.items = [(i_ast, kn)
                            for (_, i_ast), kn in zip(pairs, knames)] + \
                    [(stmt.items[0][0], vname)]
                sub_df = self.analyze_select(s2)
                lk = [self.lower(o_ast, scope) for o_ast, _ in pairs]
                df = df.join(sub_df, (lk, [col(k) for k in knames]),
                             how="left_outer")
                return _PreLowered(col(vname))
            if not isinstance(a, Ast):
                return a
            clone = a.__class__.__new__(a.__class__)
            for k, v in vars(a).items():
                if isinstance(v, Ast):
                    setattr(clone, k, rewrite(v))
                elif isinstance(v, list):
                    setattr(clone, k, [
                        rewrite(x) if isinstance(x, Ast) else
                        (tuple(rewrite(y) if isinstance(y, Ast) else y
                               for y in x) if isinstance(x, tuple) else x)
                        for x in v])
                else:
                    setattr(clone, k, v)
            return clone

        new_ast = rewrite(ast)
        cond = self.lower(new_ast, scope)
        df = df.filter(cond)
        # drop the helper columns the joins added
        return df.select(*[col(n) for n in out_names])

    # --- SELECT/GROUP BY/HAVING/ORDER BY lowering ---
    def _finish(self, df, scope: _Scope, s: SelectA):
        # expand stars (user-facing names become the output aliases)
        items: List[Tuple[Ast, Optional[str]]] = []
        for ast, alias in s.items:
            if isinstance(ast, StarA):
                for user, internal in scope.all_columns(ast.qualifier):
                    items.append((ColA(user), user))
            else:
                items.append((ast, alias))

        # group-by ordinals -> select items
        group_asts = []
        for g in s.group_by:
            if isinstance(g, LitA) and isinstance(g.value, int):
                if not 1 <= g.value <= len(items):
                    raise SqlError(f"GROUP BY position {g.value} is not "
                                   f"in the select list (1..{len(items)})")
                group_asts.append(items[g.value - 1][0])
            else:
                group_asts.append(g)

        lowered = [self.lower(a, scope) for a, _ in items]
        names = [alias or self._default_name(a, i)
                 for i, ((a, alias), e) in enumerate(zip(items, lowered))]
        has_agg = any(self._find_aggs(e) for e in lowered) or \
            bool(group_asts) or \
            (s.having is not None)

        if not has_agg:
            pre_sort = []
            post_sort = []
            out_like = list(names)
            for (oast, asc, nf) in s.order_by:
                if self._resolves_in_output(oast, out_like):
                    post_sort.append((oast, asc, nf))
                else:
                    pre_sort.append((oast, asc, nf))
            if pre_sort:
                df = self._order_limit(df, pre_sort, None, scope)
            out = df.select(*[Alias(e, n)
                              for e, n in zip(lowered, names)])
            if s.distinct:
                out = out.distinct()
            out = self._order_limit(out, post_sort, s.limit, scope, items)
            return out

        # aggregate path: split aggs out of select/having/order exprs
        keys_src = [self.lower(g, scope) for g in group_asts]
        n_keys = len(keys_src)
        if s.group_sets is None:
            keys = keys_src
            key_names = [output_name(k, i) for i, k in enumerate(keys)]
        else:
            # GROUPING SETS / ROLLUP / CUBE: pre-expand each row once
            # per grouping set (key slots NULLed where absent + a
            # grouping-id), then group by (keys..., __grouping_id) so
            # subtotal NULLs never merge with natural NULL key values —
            # GpuExpandExec's role in the reference
            from ..plan import logical as L
            in_names = [n for n, _ in df.schema]
            key_names = [f"__gk{i}" for i in range(n_keys)]
            in_schema = df.schema
            projections = []
            for idxs in s.group_sets:
                gid_val = 0
                proj = [col(n) for n in in_names]
                for i, ke in enumerate(keys_src):
                    if i in idxs:
                        proj.append(ke)
                    else:
                        proj.append(Literal(None,
                                            ke.data_type(in_schema)))
                        gid_val |= 1 << (n_keys - 1 - i)
                proj.append(lit(gid_val))
                projections.append(proj)
            df = type(df)(df.session, L.Expand(
                df.plan, projections,
                in_names + key_names + ["__grouping_id"]))
            keys = [col(kn) for kn in key_names] + [col("__grouping_id")]
            key_names = list(key_names) + ["__grouping_id"]
        agg_fns: List[Tuple[Agg.AggregateFunction, str]] = []

        def replace(e: Expression) -> Expression:
            """Replace aggregate subtrees with refs to computed columns,
            and group-key subtrees with refs to key output columns."""
            if isinstance(e, _GroupingMarker):
                if s.group_sets is None:
                    return lit(0)
                from ..expr import bitwise as B_
                for i, k in enumerate(keys_src):
                    if repr(e.children[0]) == repr(k):
                        return B_.BitwiseAnd(
                            B_.ShiftRight(col("__grouping_id"),
                                          lit(n_keys - 1 - i)),
                            lit(1))
                raise SqlError("GROUPING() argument is not a grouping "
                               "key")
            for k, kn in zip(keys_src, key_names):
                if repr(e) == repr(k):
                    return col(kn)
            for k, kn in zip(keys, key_names):
                if repr(e) == repr(k):
                    return col(kn)
            from ..expr.window import WindowExpression
            if isinstance(e, WindowExpression):
                # window OVER aggregated output (SUM(SUM(x)) OVER
                # (PARTITION BY k), RANK() OVER (ORDER BY SUM(x))):
                # Spark evaluates the window AFTER the aggregate, so
                # only the window function's OPERANDS and the spec's
                # partition/order expressions get substituted — the
                # window function itself stays, applied over the
                # aggregate's rows
                nf = e.func.__class__.__new__(e.func.__class__)
                nf.__dict__.update(e.func.__dict__)
                nf.children = [replace(c) for c in e.func.children]
                spec = e.spec.__class__.__new__(e.spec.__class__)
                spec.__dict__.update(e.spec.__dict__)
                spec.partition_by = [replace(p)
                                     for p in e.spec.partition_by]
                new_orders = []
                for o in e.spec.order_fields:
                    no = o.__class__.__new__(o.__class__)
                    no.__dict__.update(o.__dict__)
                    no.expr = replace(o.expr)
                    new_orders.append(no)
                spec.order_fields = new_orders
                return WindowExpression(nf, spec)
            if isinstance(e, Agg.AggregateFunction):
                for fn, n in agg_fns:
                    if repr(fn) == repr(e):
                        return col(n)
                n = f"__agg{len(agg_fns)}"
                agg_fns.append((e, n))
                return col(n)
            if isinstance(e, Cond.CaseWhen):
                # CaseWhen evaluates via .branches/.otherwise, not
                # .children — rebuild it so aggregates inside CASE are
                # substituted too
                return Cond.CaseWhen(
                    [(replace(c), replace(v)) for c, v in e.branches],
                    replace(e.otherwise)
                    if e.otherwise is not None else None)
            out = e.__class__.__new__(e.__class__)
            out.__dict__.update(e.__dict__)
            out.children = [replace(c) for c in e.children]
            return out

        post = [replace(e) for e in lowered]
        having_e = None
        if s.having is not None:
            having_e = replace(self.lower(s.having, scope))

        # ORDER BY expressions not present in the output (e.g. ORDER BY
        # sum(x) when only avg(x) is selected) ride along as hidden
        # projection columns, then get dropped after the sort
        proj = [Alias(e, n) for e, n in zip(post, names)]
        order_post = []
        hidden = 0
        for (oast, asc, nf) in s.order_by:
            if self._resolves_in_output(oast, names):
                order_post.append((oast, asc, nf))
            else:
                e = replace(self.lower(oast, scope))
                hname = f"__ord{hidden}"
                hidden += 1
                proj.append(Alias(e, hname))
                order_post.append((ColA(hname), asc, nf))

        from ..plan.session import GroupedData
        agg_df = GroupedData(df, keys).agg(
            *[Alias(fn, n) for fn, n in agg_fns])
        if having_e is not None:
            agg_df = agg_df.filter(having_e)
        if s.distinct and hidden:
            # standard SQL: with DISTINCT, ORDER BY items must appear in
            # the select list
            raise SqlError("ORDER BY expression must be in the select "
                           "list when DISTINCT is used")
        out = agg_df.select(*proj)
        if s.distinct:
            out = out.distinct()
        out = self._order_limit(out, order_post, s.limit, scope, items,
                                agg_replace=replace)
        if hidden:
            out = out.select(*[col(n) for n in names])
        return out

    def _order_limit(self, df, order_by, limit, scope, items=None,
                     agg_replace=None):
        if order_by:
            from ..plan import logical as L
            out_names = [n for n, _ in df.schema]
            fields = []
            for (oast, asc, nf) in order_by:
                e = self._resolve_order_expr(oast, out_names, scope,
                                             items, agg_replace)
                fields.append(L.SortField(e, asc, nf))
            df = type(df)(df.session, L.Sort(df.plan, fields))
        if limit is not None:
            df = df.limit(limit)
        return df

    def _resolves_in_output(self, oast, out_names) -> bool:
        if isinstance(oast, LitA) and isinstance(oast.value, int):
            return 1 <= oast.value <= len(out_names)
        return isinstance(oast, ColA) and oast.qualifier is None and \
            any(n.lower() == oast.name.lower() for n in out_names)

    def _resolve_order_expr(self, oast, out_names, scope, items,
                            agg_replace):
        # ordinal
        if isinstance(oast, LitA) and isinstance(oast.value, int) and \
                1 <= oast.value <= len(out_names):
            return col(out_names[oast.value - 1])
        # output column / select alias
        if isinstance(oast, ColA) and oast.qualifier is None:
            for n in out_names:
                if n.lower() == oast.name.lower():
                    return col(n)
        # general expression against the input scope
        if scope is None:
            raise SqlError("ORDER BY of a UNION must reference output "
                           "columns")
        e = self.lower(oast, scope)
        if agg_replace is not None:
            e = agg_replace(e)
        return e

    def _default_name(self, ast, i) -> str:
        if isinstance(ast, ColA):
            return ast.name
        return f"_c{i}"

    def _find_aggs(self, e: Expression) -> List:
        out = []
        if isinstance(e, Agg.AggregateFunction):
            out.append(e)
        for c in e.children:
            out.extend(self._find_aggs(c))
        return out

    # --- expression lowering ---
    def lower(self, ast: Ast, scope: _Scope) -> Expression:
        if isinstance(ast, _PreLowered):
            return ast.expr
        if isinstance(ast, (ExistsA, InSubqueryA)):
            raise SqlError("EXISTS / IN (SELECT ...) is only supported "
                           "in WHERE conjuncts")
        if isinstance(ast, ColA):
            return col(scope.resolve(ast.name, ast.qualifier))
        if isinstance(ast, ScalarSubqueryA):
            # scalar subquery: execute now, inline the value (the
            # uncorrelated-subquery path of SURVEY §2.4 #43; correlated
            # subqueries are not supported)
            sub = self.analyze(ast.stmt)
            rows = sub.collect()
            if len(sub.schema) != 1:
                raise SqlError("scalar subquery must return one column")
            if len(rows) > 1:
                raise SqlError("scalar subquery returned more than one "
                               "row")
            name = sub.schema[0][0]
            value = rows[0][name] if rows else None
            from ..expr.core import Literal
            return Literal(value, sub.schema[0][1]) \
                if value is not None else Literal(None, sub.schema[0][1])
        if isinstance(ast, OverA):
            return self._lower_over(ast, scope)
        if isinstance(ast, LitA):
            return lit(ast.value)
        if isinstance(ast, IntervalA):
            raise SqlError("INTERVAL only supported in +/- date arithmetic")
        if isinstance(ast, UnA):
            if ast.op == "not":
                return P.Not(self.lower(ast.e, scope))
            return A.UnaryMinus(self.lower(ast.e, scope))
        if isinstance(ast, BinA):
            return self._lower_bin(ast, scope)
        if isinstance(ast, BetweenA):
            e = self.lower(ast.e, scope)
            lo = self.lower(ast.lo, scope)
            hi = self.lower(ast.hi, scope)
            out = P.And(P.GreaterThanOrEqual(e, lo),
                        P.LessThanOrEqual(e, hi))
            return P.Not(out) if ast.neg else out
        if isinstance(ast, InA):
            vals = []
            for x in ast.items:
                if not isinstance(x, LitA):
                    raise SqlError("IN list items must be literals")
                vals.append(x.value)
            out = P.InSet(self.lower(ast.e, scope), vals)
            return P.Not(out) if ast.neg else out
        if isinstance(ast, LikeA):
            out = S.Like(self.lower(ast.e, scope), ast.pattern)
            return P.Not(out) if ast.neg else out
        if isinstance(ast, IsNullA):
            e = self.lower(ast.e, scope)
            return P.IsNotNull(e) if ast.neg else P.IsNull(e)
        if isinstance(ast, CaseA):
            branches = [(self.lower(c, scope), self.lower(v, scope))
                        for c, v in ast.branches]
            els = self.lower(ast.els, scope) if ast.els is not None else None
            return Cond.CaseWhen(branches, els)
        if isinstance(ast, CastA):
            return Cast(self.lower(ast.e, scope), ast.to)
        if isinstance(ast, FnA):
            return self._lower_fn(ast, scope)
        if isinstance(ast, StarA):
            raise SqlError("* only valid in SELECT list or COUNT(*)")
        raise SqlError(f"cannot lower {type(ast).__name__}")

    def _lower_bin(self, ast: BinA, scope) -> Expression:
        op = ast.op
        if op == "and":
            return P.And(self.lower(ast.l, scope), self.lower(ast.r, scope))
        if op == "or":
            return P.Or(self.lower(ast.l, scope), self.lower(ast.r, scope))
        # date +/- interval
        if op in ("+", "-"):
            if isinstance(ast.r, IntervalA):
                base = self.lower(ast.l, scope)
                return self._date_shift(base, ast.r, negate=(op == "-"))
            if isinstance(ast.l, IntervalA) and op == "+":
                base = self.lower(ast.r, scope)
                return self._date_shift(base, ast.l, negate=False)
        l = self.lower(ast.l, scope)
        r = self.lower(ast.r, scope)
        if op == "+":
            return A.Add(l, r)
        if op == "-":
            return A.Subtract(l, r)
        if op == "*":
            return A.Multiply(l, r)
        if op == "/":
            return A.Divide(l, r)
        if op == "div":
            return A.IntegralDivide(l, r)
        if op == "%":
            return A.Remainder(l, r)
        if op == "||":
            return S.Concat(l, r)
        if op == "=":
            return P.EqualTo(l, r)
        if op in ("<>", "!="):
            return P.Not(P.EqualTo(l, r))
        if op == "<":
            return P.LessThan(l, r)
        if op == "<=":
            return P.LessThanOrEqual(l, r)
        if op == ">":
            return P.GreaterThan(l, r)
        if op == ">=":
            return P.GreaterThanOrEqual(l, r)
        raise SqlError(f"unknown operator {op!r}")

    def _date_shift(self, base: Expression, iv: IntervalA,
                    negate: bool) -> Expression:
        n = -iv.n if negate else iv.n
        if iv.unit in ("day",):
            return D.DateAdd(base, lit(n))
        if iv.unit in ("week",):
            return D.DateAdd(base, lit(n * 7))
        if iv.unit in ("month",):
            return D.AddMonths(base, lit(n))
        if iv.unit in ("year",):
            return D.AddMonths(base, lit(n * 12))
        raise SqlError(f"unsupported interval unit {iv.unit!r}")

    def _lower_over(self, ast: OverA, scope) -> Expression:
        from ..expr import window as W
        from ..plan.logical import SortField
        fn = ast.fn
        name = fn.name
        args = [self.lower(a, scope) for a in fn.args]
        if name == "row_number":
            func = W.RowNumber()
        elif name == "rank":
            func = W.Rank()
        elif name == "dense_rank":
            func = W.DenseRank()
        elif name == "percent_rank":
            func = W.PercentRank()
        elif name == "ntile":
            from .parser import LitA as _L
            if not fn.args or not isinstance(fn.args[0], LitA):
                raise SqlError("ntile(n) needs an integer literal")
            func = W.NTile(int(fn.args[0].value))
        elif name in ("lead", "lag"):
            off = 1
            default = None
            if len(fn.args) >= 2:
                if not isinstance(fn.args[1], LitA):
                    raise SqlError(f"{name} offset must be a literal")
                off = int(fn.args[1].value)
            if len(fn.args) >= 3:
                if not isinstance(fn.args[2], LitA):
                    raise SqlError(f"{name} default must be a literal")
                default = fn.args[2].value
            cls = W.Lead if name == "lead" else W.Lag
            func = cls(args[0], off, default)
        elif name in _AGG_FNS or name in ("count",):
            func = self._lower_fn(fn, scope)
            if not isinstance(func, Agg.AggregateFunction):
                raise SqlError(f"{name} is not a window function")
        else:
            raise SqlError(f"unsupported window function {name!r}")
        spec = W.WindowSpec(
            [self.lower(p, scope) for p in ast.partition],
            [SortField(self.lower(o, scope), asc,
                       asc if nf is None else nf)
             for o, asc, nf in ast.order])
        if ast.frame is not None:
            row_based, lo, hi = ast.frame
            spec = spec.with_frame(W.WindowFrame(lo, hi,
                                                 row_based=row_based))
        return func.over(spec)

    def _lower_fn(self, ast: FnA, scope) -> Expression:
        name = ast.name
        if name == "grouping":
            if len(ast.args) != 1:
                raise SqlError("GROUPING takes one argument")
            return _GroupingMarker(self.lower(ast.args[0], scope))
        if name == "count":
            if ast.star or not ast.args:
                return Agg.CountStar()
            if ast.distinct:
                # COUNT(DISTINCT x) = size(collect_set(x)): collect_set
                # drops nulls and dedups — exactly distinct-count
                # semantics; the aggregate-split pass substitutes the
                # inner CollectSet and Size applies post-aggregation
                from ..expr import collections as Coll
                return Cast(Coll.Size(
                    Agg.CollectSet(self.lower(ast.args[0], scope))),
                    dt.INT64)
            return Agg.Count(self.lower(ast.args[0], scope))
        if name in _AGG_FNS:
            if ast.distinct:
                raise SqlError(f"{name}(DISTINCT ...) not supported yet")
            return _AGG_FNS[name](self.lower(ast.args[0], scope))
        args = [self.lower(a, scope) for a in ast.args]
        _TS_FIELD_FNS = ("hour", "minute", "second", "year", "month",
                         "day", "dayofmonth", "quarter", "dayofweek",
                         "dayofyear", "weekday", "last_day")
        if name in _TS_FIELD_FNS:
            # field extraction follows the session timezone
            # (spark.sql.session.timeZone) when the input is a
            # timestamp; date inputs and UTC sessions skip the convert
            from ..conf import SESSION_TIMEZONE
            self._arity(ast, 1)
            zone = self.session.conf.get(SESSION_TIMEZONE)
            arg = args[0]
            is_ts = name in ("hour", "minute", "second")
            if not is_ts:
                try:
                    is_ts = isinstance(arg.data_type(scope.type_schema()),
                                       dt.TimestampType)
                except Exception:
                    is_ts = False
            if is_ts and zone not in ("UTC", "GMT", "+00:00", "Z"):
                from ..expr import timezone as TZX
                try:
                    arg = TZX.FromUTCTimestamp(arg, zone)
                except Exception as e:
                    raise SqlError(
                        f"session timezone {zone!r}: {e}")
            return _UNARY_FNS[name](arg)
        if name in _UNARY_FNS:
            self._arity(ast, 1)
            return _UNARY_FNS[name](args[0])
        if name in _BINARY_FNS:
            self._arity(ast, 2)
            return _BINARY_FNS[name](args[0], args[1])
        if name in _VARARG_FNS:
            return _VARARG_FNS[name](*args)
        if name in ("substring", "substr"):
            pos = self._lit_value(ast.args[1], "substring position")
            if len(ast.args) >= 3:
                ln = self._lit_value(ast.args[2], "substring length")
                return S.Substring(args[0], pos, ln)
            return S.Substring(args[0], pos)
        if name == "round":
            scale = self._lit_value(ast.args[1], "round scale") \
                if len(ast.args) > 1 else 0
            return M.Round(args[0], scale)
        if name == "bround":
            scale = self._lit_value(ast.args[1], "bround scale") \
                if len(ast.args) > 1 else 0
            return M.BRound(args[0], scale)
        if name in ("lpad", "rpad"):
            ln = self._lit_value(ast.args[1], "pad length")
            pad = self._lit_value(ast.args[2], "pad string") \
                if len(ast.args) > 2 else " "
            cls = S.Lpad if name == "lpad" else S.Rpad
            return cls(args[0], ln, pad)
        if name == "replace":
            return S.StringReplace(
                args[0], self._lit_value(ast.args[1], "search"),
                self._lit_value(ast.args[2], "replacement")
                if len(ast.args) > 2 else "")
        if name == "translate":
            return S.StringTranslate(
                args[0], self._lit_value(ast.args[1], "from"),
                self._lit_value(ast.args[2], "to"))
        if name in ("locate", "position"):
            return S.StringLocate(
                args[1], self._lit_value(ast.args[0], "substring"))
        if name == "concat_ws":
            sep = self._lit_value(ast.args[0], "separator")
            return S.ConcatWs(sep, *args[1:])
        if name == "if":
            self._arity(ast, 3)
            return Cond.If(args[0], args[1], args[2])
        if name == "nvl2":
            self._arity(ast, 3)
            return Cond.Nvl2(args[0], args[1], args[2])
        if name == "from_unixtime":
            return D.FromUnixTime(args[0])
        if name == "make_date":
            self._arity(ast, 3)
            return D.MakeDate(args[0], args[1], args[2])
        if name == "trunc":
            fmt = self._lit_value(ast.args[1], "trunc format")
            return D.TruncDate(args[0], lit(fmt))
        if name == "get_json_object":
            from ..expr import json as JX
            self._arity(ast, 2)
            try:
                return JX.GetJsonObject(
                    args[0], self._lit_value(ast.args[1], "JSON path"))
            except TypeError as e:
                raise SqlError(str(e))
        if name == "from_json":
            from ..expr import json as JX
            self._arity(ast, 2)
            schema_s = self._lit_value(ast.args[1], "schema")
            fields = []
            # split on commas OUTSIDE parens (decimal(10,2) stays whole)
            parts, depth_, cur = [], 0, []
            for ch in schema_s:
                if ch == "(":
                    depth_ += 1
                elif ch == ")":
                    depth_ -= 1
                if ch == "," and depth_ == 0:
                    parts.append("".join(cur))
                    cur = []
                else:
                    cur.append(ch)
            if cur:
                parts.append("".join(cur))
            for part in parts:
                fname, _, ftype = part.strip().partition(" ")
                fields.append((fname, Parser(ftype.strip()).parse_type()))
            return JX.JsonToStructs(args[0],
                                    dt.StructType(tuple(fields)))
        if name == "to_json":
            from ..expr import json as JX
            self._arity(ast, 1)
            return JX.StructsToJson(args[0])
        if name == "regexp_extract":
            from ..expr import regex as RX
            if len(ast.args) not in (2, 3):
                raise SqlError("regexp_extract expects 2 or 3 arguments, "
                               f"got {len(ast.args)}")
            pat = self._lit_value(ast.args[1], "pattern")
            grp = self._lit_value(ast.args[2], "group") \
                if len(ast.args) > 2 else 1
            return RX.RegExpExtract(args[0], pat, grp)
        if name == "regexp_replace":
            from ..expr import regex as RX
            self._arity(ast, 3)
            return RX.RegExpReplace(
                args[0], self._lit_value(ast.args[1], "pattern"),
                self._lit_value(ast.args[2], "replacement"))
        if name in ("rlike", "regexp_like", "regexp"):
            from ..expr import regex as RX
            self._arity(ast, 2)
            return RX.RLike(args[0], self._lit_value(ast.args[1],
                                                     "pattern"))
        if name in ("from_utc_timestamp", "to_utc_timestamp"):
            from ..expr import timezone as TZX
            self._arity(ast, 2)
            zone = self._lit_value(ast.args[1], "timezone")
            cls = TZX.FromUTCTimestamp if name == "from_utc_timestamp" \
                else TZX.ToUTCTimestamp
            try:
                return cls(args[0], zone)
            except Exception as e:
                raise SqlError(f"{name}: {e}")
        raise SqlError(f"unknown function {name!r}")

    def _arity(self, ast: FnA, n: int):
        if len(ast.args) != n:
            raise SqlError(f"{ast.name} expects {n} argument(s), got "
                           f"{len(ast.args)}")

    def _lit_value(self, ast, what: str):
        if not isinstance(ast, LitA):
            raise SqlError(f"{what} must be a literal")
        return ast.value


def parse_sql(session, text: str):
    """Parse + analyze SQL text into a DataFrame on ``session``."""
    stmt = Parser(text).parse_statement()
    return Analyzer(session).analyze(stmt)
