"""SQL lexer: text -> token stream.

Keywords are not tokenized specially — the parser matches IDENT tokens
case-insensitively, which keeps the keyword set in one place (the
grammar) and lets non-reserved words double as identifiers.
"""

from __future__ import annotations

from typing import List, NamedTuple


class Token(NamedTuple):
    kind: str   # IDENT | NUMBER | STRING | OP | EOF
    value: str
    pos: int


class SqlLexError(ValueError):
    pass


_TWO_CHAR_OPS = ("<=", ">=", "<>", "!=", "||")
_ONE_CHAR_OPS = "+-*/%(),.<>=;"


def tokenize(text: str) -> List[Token]:
    out: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if text.startswith("--", i):            # line comment
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if text.startswith("/*", i):            # block comment
            j = text.find("*/", i + 2)
            if j < 0:
                raise SqlLexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":                            # string literal, '' escape
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise SqlLexError(f"unterminated string at {i}")
                if text[j] == "'":
                    if j + 1 < n and text[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            out.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        if c == '"' or c == "`":                # quoted identifier
            end = text.find(c, i + 1)
            if end < 0:
                raise SqlLexError(f"unterminated quoted identifier at {i}")
            out.append(Token("IDENT", text[i + 1:end], i))
            i = end + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                ch = text[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i and \
                        j + 1 < n and (text[j + 1].isdigit()
                                       or text[j + 1] in "+-"):
                    seen_exp = True
                    j += 2 if text[j + 1] in "+-" else 1
                else:
                    break
            out.append(Token("NUMBER", text[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            out.append(Token("IDENT", text[i:j], i))
            i = j
            continue
        if text[i:i + 2] in _TWO_CHAR_OPS:
            out.append(Token("OP", text[i:i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            out.append(Token("OP", c, i))
            i += 1
            continue
        raise SqlLexError(f"unexpected character {c!r} at {i}")
    out.append(Token("EOF", "", n))
    return out
