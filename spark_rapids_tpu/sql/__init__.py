"""SQL string frontend: ``session.sql("SELECT ...")``.

The reference accelerates SQL text through Spark's Catalyst stack and
hooks physical planning at the columnOverrides seam
(GpuOverrides.scala:4515 GpuQueryStagePrepOverrides /
:4312 wrapAndTagPlan). This package is that frontend re-built for the
TPU engine: a hand-written lexer + recursive-descent parser lowers a
SQL SELECT dialect onto the same logical-plan/DataFrame layer the
Python DSL uses (plan/session.py), so everything downstream — the
tag-then-convert overrides driver, staged exchanges, CPU fallback —
is shared with the DSL path.

Dialect (grows as needed): SELECT [DISTINCT] with expressions/aliases,
FROM with table refs, comma joins, and INNER/LEFT/RIGHT/FULL/CROSS
JOIN ... ON, WHERE, GROUP BY (names or ordinals), HAVING, ORDER BY
[ASC|DESC] [NULLS FIRST|LAST] (names, aliases, or ordinals), LIMIT,
UNION [ALL], scalar/aggregate function calls, CASE WHEN, CAST, BETWEEN,
IN, LIKE, IS [NOT] NULL, EXTRACT, date/timestamp/interval literals,
and derived tables (subqueries in FROM).
"""

from .parser import SqlError, parse_sql  # noqa: F401
