"""End-to-end data integrity for every off-device byte path.

The reference engine checksums shuffle blocks (SPARK-35275: Spark's
shuffle checksum support, surfaced through RapidsShuffleManager) and
trusts its device->host->disk store chain to the filesystem; a flipped
bit in a serialized shuffle block, a spilled batch, or a cached input
file otherwise produces a silently wrong SQL answer — the worst failure
mode a columnar engine can have. This module is the TPU rebuild's
integrity layer:

- ``checksum(data)``: a crc32c-style masked CRC over any buffer
  (stdlib ``zlib.crc32`` polynomial — the hardware-crc32c package is
  not a dependency — with the snappy/LevelDB rotation mask applied so
  a CRC stored next to its own payload never checksums to itself).
- ``wrap(payload)`` / ``unwrap(framed)``: a framed checksum envelope
  (magic | length | masked-crc | payload). Shuffle blocks live in the
  host store in this frame; verification happens at every consumption
  point (server serve, remote fetch, local read).
- ``DataCorruption``: the error type every verification failure
  raises. It deliberately does NOT subclass OSError: transport code
  *converts* it into a retryable fetch failure where regeneration is
  possible, while storage tiers surface it directly so the caller
  recomputes instead of retrying a read that can never succeed.

The contract threaded through transport/shuffle/spill/filecache/scan:
**no silent wrong answers** — corruption anywhere off-device is either
recovered (refetch, stage rerun, recompute, cache re-read) or raised
cleanly as ``DataCorruption``.
"""

from __future__ import annotations

import struct
import zlib
from typing import Optional, Union

Buffer = Union[bytes, bytearray, memoryview]

#: envelope magic "SRTC" (SRT + Checksum), little-endian u32
MAGIC = 0x53525443
#: magic u32 | payload_len u64 | masked crc u32
_HDR = struct.Struct("<IQI")
HEADER_SIZE = _HDR.size

# snappy/LevelDB CRC mask constant: storing crc(data) adjacent to data
# makes crc(data || crc) degenerate; the rotation+offset mask breaks
# that self-similarity (the "crc32c-style" masked form).
_MASK_DELTA = 0xA282EAD8


class DataCorruption(RuntimeError):
    """Off-device bytes failed verification (checksum/length/magic).

    Carries enough context to attribute the corruption to a tier and
    entry. Storage tiers raise it directly (the data is gone — only a
    recompute helps); the shuffle transport converts it into a fetch
    failure so retry/failover/stage-rerun machinery regenerates the
    block.
    """

    def __init__(self, what: str, expected: Optional[int] = None,
                 actual: Optional[int] = None, detail: str = ""):
        msg = f"DataCorruption: {what}"
        if expected is not None or actual is not None:
            msg += (f" (expected={_hex(expected)} actual={_hex(actual)})")
        if detail:
            msg += f" [{detail}]"
        super().__init__(msg)
        self.what = what
        self.expected = expected
        self.actual = actual
        self.detail = detail
        # central choke point: every verification failure in the engine
        # constructs one of these, so the event log sees them all
        from ..obs import events as _events
        _events.emit("CorruptionDetected", what=what,
                     expected=_hex(expected), actual=_hex(actual),
                     detail=detail)


def _hex(v: Optional[int]) -> str:
    return "?" if v is None else f"0x{v:08x}"


def checksum(data: Buffer, value: int = 0) -> int:
    """Masked crc32c-style checksum of a buffer (incremental via
    ``value``: pass a previous UNMASKED running crc from
    :func:`checksum_update` only — this function masks its output)."""
    return mask_crc(zlib.crc32(data, value) & 0xFFFFFFFF)


def checksum_update(value: int, data: Buffer) -> int:
    """Running (unmasked) crc for chunked streams; finish with
    :func:`mask_crc`."""
    return zlib.crc32(data, value) & 0xFFFFFFFF


def mask_crc(crc: int) -> int:
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def wrap(payload: bytes) -> bytes:
    """Frame ``payload`` with the checksum envelope."""
    return _HDR.pack(MAGIC, len(payload), checksum(payload)) + payload


def unwrap(framed: Buffer, what: str = "block") -> bytes:
    """Verify and strip the envelope; raises :class:`DataCorruption`
    on any mismatch (magic, length, checksum)."""
    if len(framed) < HEADER_SIZE:
        raise DataCorruption(
            f"{what}: framed envelope truncated to {len(framed)} bytes "
            f"(header needs {HEADER_SIZE})")
    magic, length, crc = _HDR.unpack_from(framed, 0)
    if magic != MAGIC:
        raise DataCorruption(f"{what}: bad envelope magic",
                             expected=MAGIC, actual=magic)
    payload = bytes(memoryview(framed)[HEADER_SIZE:])
    if len(payload) != length:
        raise DataCorruption(
            f"{what}: payload length mismatch "
            f"(declared {length}, got {len(payload)})")
    actual = checksum(payload)
    if actual != crc:
        raise DataCorruption(f"{what}: checksum mismatch",
                             expected=crc, actual=actual)
    return payload


def strip(framed: Buffer) -> bytes:
    """Remove the envelope WITHOUT verification — the
    srt.integrity.checksum.enabled=false path (storage format stays
    framed either way)."""
    return bytes(memoryview(framed)[HEADER_SIZE:])


def verify_framed(framed: Buffer, what: str = "block") -> None:
    """Checksum-verify an envelope without copying the payload out —
    the server-side pre-serve check."""
    if len(framed) < HEADER_SIZE:
        raise DataCorruption(
            f"{what}: framed envelope truncated to {len(framed)} bytes "
            f"(header needs {HEADER_SIZE})")
    magic, length, crc = _HDR.unpack_from(framed, 0)
    if magic != MAGIC:
        raise DataCorruption(f"{what}: bad envelope magic",
                             expected=MAGIC, actual=magic)
    payload = memoryview(framed)[HEADER_SIZE:]
    if len(payload) != length:
        raise DataCorruption(
            f"{what}: payload length mismatch "
            f"(declared {length}, got {len(payload)})")
    actual = checksum(payload)
    if actual != crc:
        raise DataCorruption(f"{what}: checksum mismatch",
                             expected=crc, actual=actual)


def array_checksum(arr) -> int:
    """Masked checksum of a numpy array's bytes (C-order; non-contiguous
    inputs are compacted first so views checksum identically to their
    copies)."""
    import numpy as np
    a = np.ascontiguousarray(arr)
    return checksum(a.view(np.uint8).reshape(-1))


def file_checksum(path: str, chunk: int = 1 << 20) -> int:
    """Masked checksum of a file's contents, read in chunks."""
    crc = 0
    with open(path, "rb", buffering=0) as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = checksum_update(crc, block)
    return mask_crc(crc)
