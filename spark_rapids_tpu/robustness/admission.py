"""Query admission control and the cancellation/deadline contract.

The reference serializes device access with ``GpuSemaphore`` — a
1000-permit semaphore carved into ``spark.rapids.sql.concurrentGpuTasks``
shares so the config can over/under-subscribe (GpuSemaphore.scala:106).
``exec/base.py``'s ``TpuSemaphore`` already plays that role at *task*
granularity; this module lifts the same idea to *query* granularity for
the serving tier (ROADMAP item 1):

  * ``QuerySemaphore`` — ``srt.sql.concurrentQueryTasks`` queries run;
    up to ``srt.sql.admission.maxQueueDepth`` more wait FIFO with
    exponential backoff + jitter between re-checks; arrivals beyond the
    queue are load-shed with a retryable ``AdmissionRejected`` so an
    overloaded server degrades by refusing work, not by queueing
    unboundedly.
  * ``QueryContext`` — the cancel token threaded through the session,
    operator pull loops, prefetch producers, and transport fetch
    workers. ``cancel()`` and deadlines both funnel into ``check()``,
    which raises the typed ``QueryCancelled`` / ``DeadlineExceeded``
    that the session surfaces (and cluster drivers broadcast).

Admission states (each transition emits a JSONL event):

    submit -> ADMITTED                       (QueryAdmitted)
    submit -> QUEUED -> ADMITTED             (AdmissionQueued, QueryAdmitted)
    submit -> QUEUED -> cancel/deadline      (AdmissionAbandoned)
    submit -> REJECTED (queue full)          (AdmissionRejected)

The thread-local "current query" mirrors ``active_conf``: worker
threads spawned on a query's behalf (prefetch producers, fetch pool
workers) enter ``query_scope(token)`` so deep code — budget slices,
spill victim selection, retry backoff sleeps — can find the owning
query without threading a parameter through every signature.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Optional

from ..conf import (ADMISSION_BACKOFF_BASE_S, ADMISSION_MAX_QUEUE_DEPTH,
                    CONCURRENT_QUERY_TASKS, active_conf)
from ..obs import events as _events

__all__ = ["AdmissionRejected", "QueryInterrupted", "QueryCancelled",
           "DeadlineExceeded", "QueryContext", "QuerySemaphore",
           "current_query", "set_current_query", "query_scope",
           "query_semaphore", "reset_query_semaphore"]


class AdmissionRejected(RuntimeError):
    """Load-shed: the admission queue is full. Retryable — the query
    did no work and held no resources; resubmit after backoff."""


class QueryInterrupted(RuntimeError):
    """Base for clean query teardown (cancel or deadline). NOT a bug:
    the engine unwinds through every thread and stays serviceable."""


class QueryCancelled(QueryInterrupted):
    """The query's cancel token fired (user abort, driver broadcast)."""


class DeadlineExceeded(QueryInterrupted):
    """srt.sql.queryTimeout / collect(timeout=...) expired."""


class QueryContext:
    """Cancel token + deadline for one query, shared across every
    thread working on its behalf (consumer, prefetch producers, fetch
    pool workers, cluster worker job threads).

    ``check()`` is the single choke point: cheap enough for per-batch
    pull loops (one Event.is_set + one clock read when a deadline is
    armed), and every blocking wait in the engine either polls it or
    waits on ``_cancelled`` directly (``sleep``)."""

    __slots__ = ("query_id", "deadline", "cancel_reason", "_cancelled",
                 "admission_wait_ns")

    def __init__(self, query_id: str = "",
                 deadline: Optional[float] = None):
        self.query_id = query_id
        #: absolute time.monotonic() deadline; None = no deadline
        self.deadline = deadline
        self.cancel_reason = ""
        self._cancelled = threading.Event()
        #: ns spent queued for admission, stamped by
        #: QuerySemaphore.acquire: None = never admitted, 0 = admitted
        #: on the fast path, >0 = waited in the FIFO. The serving tier
        #: reads this to bucket latency per admission tier.
        self.admission_wait_ns: Optional[int] = None

    @property
    def admission_tier(self) -> str:
        """'immediate' | 'queued' | 'unadmitted' — which admission
        path this query took (serving-tier latency bucketing)."""
        w = self.admission_wait_ns
        if w is None:
            return "unadmitted"
        return "queued" if w > 0 else "immediate"

    def set_timeout(self, seconds: Optional[float]) -> None:
        if seconds is not None and seconds > 0:
            self.deadline = time.monotonic() + float(seconds)

    def cancel(self, reason: str = "cancelled") -> None:
        if not self._cancelled.is_set():
            self.cancel_reason = reason
            self._cancelled.set()

    def is_cancelled(self) -> bool:
        return self._cancelled.is_set()

    def expired(self) -> bool:
        return self.deadline is not None and \
            time.monotonic() > self.deadline

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def check(self) -> None:
        """Raise the typed teardown error if this query should stop."""
        if self._cancelled.is_set():
            raise QueryCancelled(
                f"query {self.query_id or '?'} cancelled"
                + (f": {self.cancel_reason}" if self.cancel_reason
                   else ""))
        if self.expired():
            raise DeadlineExceeded(
                f"query {self.query_id or '?'} exceeded its deadline")

    def sleep(self, seconds: float) -> None:
        """Cancel-aware sleep: wake early on cancel() and never sleep
        past the deadline; raises via check() if either fired."""
        t = seconds
        r = self.remaining()
        if r is not None:
            t = min(t, max(r, 0.0))
        if t > 0:
            self._cancelled.wait(t)
        self.check()


# --- thread-local current query (mirrors conf.set_active_conf) -------------
_TL = threading.local()


def current_query() -> Optional[QueryContext]:
    return getattr(_TL, "query", None)


def set_current_query(q: Optional[QueryContext]) -> None:
    _TL.query = q


class query_scope:
    """Bind ``token`` as this thread's current query for the duration;
    restores the previous binding on exit (nested queries, reused pool
    threads)."""

    def __init__(self, token: Optional[QueryContext]):
        self._token = token
        self._prev: Optional[QueryContext] = None

    def __enter__(self) -> Optional[QueryContext]:
        self._prev = current_query()
        set_current_query(self._token)
        return self._token

    def __exit__(self, *exc) -> bool:
        set_current_query(self._prev)
        return False


def check_current_query() -> None:
    """Convenience for deep call sites: check the thread's current
    query token, if any. Zero-cost shape when no query is bound."""
    q = current_query()
    if q is not None:
        q.check()


class QuerySemaphore:
    """Bounded query admission (GpuSemaphore at query granularity).

    Like the reference's 1000-permit pool split ``concurrentGpuTasks``
    ways, ``TOTAL_PERMITS`` is carved into ``permits`` equal shares so
    a future weighted-admission tier (big queries take several shares)
    slots in without changing the protocol. Re-entrant per thread, like
    ``TpuSemaphore``: a nested ``session.execute`` on an admitted
    thread (cache materialization, explain(metrics=True)) must not
    deadlock behind itself.
    """

    TOTAL_PERMITS = 1000

    def __init__(self, permits: int, max_queue_depth: int = 16,
                 backoff_base_s: float = 0.05):
        self.permits = max(int(permits), 1)
        self.share = self.TOTAL_PERMITS // self.permits
        self.max_queue_depth = max(int(max_queue_depth), 0)
        self.backoff_base_s = float(backoff_base_s)
        self._cv = threading.Condition()
        self._active = 0
        self._queue: deque = deque()  # FIFO tickets (opaque objects)
        self._holders = {}  # tid -> depth (re-entrancy)
        # counters for tests/chaos: lifetime admitted/queued/rejected
        self.admitted = 0
        self.queued = 0
        self.rejected = 0

    # --- introspection ---
    def active(self) -> int:
        with self._cv:
            return self._active

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def acquire(self, token: Optional[QueryContext] = None) -> None:
        """Admit one query, waiting FIFO if the running set is full.

        Raises ``AdmissionRejected`` when the wait queue is at
        capacity, and ``QueryCancelled`` / ``DeadlineExceeded`` if the
        token fires while queued (the query never ran; it abandons its
        queue slot)."""
        tid = threading.get_ident()
        qid = token.query_id if token is not None else ""
        with self._cv:
            if self._holders.get(tid, 0) > 0:
                self._holders[tid] += 1
                return
            if self._active < self.permits and not self._queue:
                self._active += 1
                self._holders[tid] = 1
                self.admitted += 1
                if token is not None:
                    token.admission_wait_ns = 0
                _events.emit("QueryAdmitted", query_id=qid,
                             active=self._active, queued_ns=0)
                return
            if len(self._queue) >= self.max_queue_depth:
                self.rejected += 1
                _events.emit("AdmissionRejected", query_id=qid,
                             queue_depth=len(self._queue))
                raise AdmissionRejected(
                    f"admission queue full "
                    f"({len(self._queue)}/{self.max_queue_depth} "
                    f"queued, {self._active} running); retry later")
            ticket = object()
            self._queue.append(ticket)
            self.queued += 1
            _events.emit("AdmissionQueued", query_id=qid,
                         queue_depth=len(self._queue))
            t0 = time.perf_counter_ns()
            attempt = 0
            try:
                while not (self._queue[0] is ticket
                           and self._active < self.permits):
                    if token is not None:
                        token.check()  # cancel/deadline while queued
                    # backoff + jitter bounds how stale a deadline
                    # check can get; release() notifies so an open
                    # slot is claimed immediately, not at backoff
                    attempt += 1
                    backoff = (self.backoff_base_s
                               * min(2 ** (attempt - 1), 64)
                               * (1.0 + random.random() * 0.25))
                    self._cv.wait(timeout=backoff)
                self._queue.popleft()
                self._active += 1
                self._holders[tid] = 1
                self.admitted += 1
                wait_ns = time.perf_counter_ns() - t0
                if token is not None:
                    token.admission_wait_ns = wait_ns
                from ..memory.budget import task_context
                task_context().semaphore_wait_ns += wait_ns
                _events.emit("QueryAdmitted", query_id=qid,
                             active=self._active, queued_ns=wait_ns)
            except BaseException:
                try:
                    self._queue.remove(ticket)
                except ValueError:
                    pass
                _events.emit("AdmissionAbandoned", query_id=qid)
                self._cv.notify_all()
                raise

    def release(self) -> None:
        tid = threading.get_ident()
        with self._cv:
            n = self._holders.get(tid, 0)
            if n == 0:
                return
            if n > 1:
                self._holders[tid] = n - 1
                return
            del self._holders[tid]
            self._active = max(0, self._active - 1)
            self._cv.notify_all()

    def __enter__(self) -> "QuerySemaphore":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


_QUERY_SEM: Optional[QuerySemaphore] = None
_QS_LOCK = threading.Lock()


def query_semaphore(conf=None) -> QuerySemaphore:
    """Process-wide admission semaphore, sized from config on first
    use (device_semaphore idiom — one pool per device pool)."""
    global _QUERY_SEM
    with _QS_LOCK:
        if _QUERY_SEM is None:
            c = conf or active_conf()
            _QUERY_SEM = QuerySemaphore(
                c.get(CONCURRENT_QUERY_TASKS),
                max_queue_depth=c.get(ADMISSION_MAX_QUEUE_DEPTH),
                backoff_base_s=c.get(ADMISSION_BACKOFF_BASE_S))
        return _QUERY_SEM


def reset_query_semaphore(conf=None) -> QuerySemaphore:
    """Test hook: drop the singleton (resized from conf on next use,
    or immediately when a conf is given)."""
    global _QUERY_SEM
    with _QS_LOCK:
        _QUERY_SEM = None
    return query_semaphore(conf) if conf is not None else None
