"""Deterministic, seeded fault injection for the distributed runtime.

The reference exercises its resilience paths with JVM-side forced
failures (RmmSpark.forceRetryOOM / RmmSparkRetrySuiteBase.scala:48) and
real UCX peer loss in integration runs; HERE the runtime is the engine,
so this module provides the whole harness: named ``fault_point("site")``
hooks threaded through transport, cluster, shuffle-manager, and memory
code, and a seeded ``FaultPlan`` that decides — reproducibly — which
hits fire which fault.

Design contract:

- **Zero overhead unarmed.** ``fault_point`` is a module-global ``None``
  check when no plan is armed; production code pays one attribute load
  and a compare per site hit.
- **Deterministic.** Firing decisions come from a per-plan
  ``random.Random(seed)`` plus exact hit counters — re-running the same
  workload with the same spec replays the same faults (seeded-replay
  tests assert on ``plan.log``).
- **Conf-activated.** ``srt.test.faultPlan`` (an internal string conf)
  ships the spec to cluster workers inside the job's conf dict, so a
  driver-side test can arm faults in every worker process without any
  side channel.

Spec grammar (clauses joined by ``|``; first clause may be ``seed=N``)::

    site ':' kind ['@' nth] ['%' prob] ['*' count] ['+' delay_s] ['~' match]

- ``kind``: ``refuse`` (ConnectionRefusedError), ``reset``
  (ConnectionResetError), ``delay`` (sleep ``delay_s``), ``crash``
  (``os._exit(137)``), ``retry_oom`` / ``split_oom`` (RetryOOM /
  SplitAndRetryOOM), ``drop`` (FaultDrop — sites that poll, e.g. the
  heartbeat loop, treat it as "skip this beat"), ``corrupt`` /
  ``truncate`` (data corruption: at a data-bearing
  ``corrupt_point(site, data)`` the bytes are deterministically
  byte-flipped / tail-truncated; at a plain ``fault_point`` site both
  raise ``DataCorruption`` — a file that reads as garbage).
- ``@nth`` fires on exactly the nth *matching* hit (1-based);
  ``%prob`` fires each matching hit with probability ``prob`` from the
  plan's seeded RNG. Exactly one of the two; ``@1`` assumed otherwise.
- ``*count`` caps total fires for the clause (default 1).
- ``~match`` (must be last): substring filter against the hit's detail
  string (or the current operator scope when the site passes none).

Example — one refused connect, then a worker crash at the second
shuffle barrier of attempt 0 on logical worker 1::

    seed=7|transport.connect:refuse@1|cluster.barrier:crash@1~attempt=0;workers=1;pos=1;

Fault-site catalog: docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional


class FaultDrop(Exception):
    """Raised by ``drop`` faults; polling sites catch it and skip one
    iteration (e.g. a heartbeat beat) instead of failing."""


@dataclass
class FaultSpec:
    site: str
    kind: str                    # refuse|reset|delay|crash|retry_oom|split_oom|drop
    nth: Optional[int] = None    # fire on the nth matching hit (1-based)
    prob: float = 0.0            # else: fire each matching hit w.p. prob
    count: int = 1               # max total fires for this clause
    delay_s: float = 0.05        # sleep for kind == "delay"
    match: str = ""              # substring filter on the hit detail

    _KINDS = ("refuse", "reset", "delay", "crash", "retry_oom",
              "split_oom", "drop", "corrupt", "truncate")
    #: kinds that mutate data at corrupt_point sites (all other kinds
    #: are ignored there; at plain fault_point sites these raise
    #: DataCorruption instead)
    _DATA_KINDS = ("corrupt", "truncate")

    @classmethod
    def parse(cls, clause: str) -> "FaultSpec":
        body = clause.strip()
        match = ""
        if "~" in body:
            body, match = body.split("~", 1)
        if ":" not in body:
            raise ValueError(f"fault clause needs site:kind — {clause!r}")
        site, rest = body.split(":", 1)
        spec = cls(site=site.strip(), kind="", match=match)
        # kind runs until the first modifier char
        i = 0
        while i < len(rest) and rest[i] not in "@%*+":
            i += 1
        spec.kind = rest[:i].strip()
        if spec.kind not in cls._KINDS:
            raise ValueError(f"unknown fault kind {spec.kind!r} in "
                             f"{clause!r} (expected one of {cls._KINDS})")
        rest = rest[i:]
        while rest:
            mod, rest = rest[0], rest[1:]
            j = 0
            while j < len(rest) and rest[j] not in "@%*+":
                j += 1
            val, rest = rest[:j], rest[j:]
            if mod == "@":
                spec.nth = int(val)
            elif mod == "%":
                spec.prob = float(val)
            elif mod == "*":
                spec.count = int(val)
            elif mod == "+":
                spec.delay_s = float(val)
        if spec.nth is None and spec.prob <= 0.0:
            spec.nth = 1
        return spec

    def unparse(self) -> str:
        out = f"{self.site}:{self.kind}"
        if self.nth is not None:
            out += f"@{self.nth}"
        elif self.prob > 0.0:
            out += f"%{self.prob}"
        if self.count != 1:
            out += f"*{self.count}"
        if self.kind == "delay" and self.delay_s != 0.05:
            out += f"+{self.delay_s}"
        if self.match:
            out += f"~{self.match}"
        return out


@dataclass
class FaultEvent:
    """One fired fault — ``plan.log`` entries for seeded-replay asserts."""
    site: str
    kind: str
    detail: str
    hit: int                     # which matching hit fired (1-based)
    pid: int = field(default_factory=os.getpid)


class FaultPlan:
    """A set of FaultSpecs plus the seeded state deciding which site
    hits fire. One plan per process; hit counters persist across jobs in
    the same process (so a crash clause that fired in attempt 0 does not
    re-fire on the surviving workers' attempt 1)."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._hits = [0] * len(self.specs)
        self._fires = [0] * len(self.specs)
        self.log: List[FaultEvent] = []
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec_str: str) -> "FaultPlan":
        seed = 0
        specs = []
        for clause in spec_str.split("|"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                seed = int(clause[len("seed="):])
                continue
            specs.append(FaultSpec.parse(clause))
        return cls(specs, seed=seed)

    def spec_string(self) -> str:
        return "|".join([f"seed={self.seed}"]
                        + [s.unparse() for s in self.specs])

    def fired(self, site: Optional[str] = None) -> List[FaultEvent]:
        with self._lock:
            return [e for e in self.log if site is None or e.site == site]

    def _note(self, ev: FaultEvent) -> None:
        """Record a fired fault in the replay log AND the observability
        event log. Called with the plan lock held, BEFORE the fault
        actually fires — the event writer flushes per line, so even a
        ``crash`` clause (os._exit) leaves its FaultInjected on disk."""
        self.log.append(ev)
        from ..obs import events as _events
        _events.emit("FaultInjected", site=ev.site, kind=ev.kind,
                     detail=ev.detail, hit=ev.hit, seed=self.seed)

    def hit(self, site: str, detail: Optional[str]) -> None:
        to_fire: Optional[FaultSpec] = None
        hit_no = 0
        ref = detail if detail is not None else current_op()
        with self._lock:
            for i, sp in enumerate(self.specs):
                if sp.site != site:
                    continue
                if sp.match and sp.match not in ref:
                    continue
                self._hits[i] += 1
                if self._fires[i] >= sp.count:
                    continue
                if sp.nth is not None:
                    fire = self._hits[i] == sp.nth
                else:
                    fire = self._rng.random() < sp.prob
                if not fire:
                    continue
                self._fires[i] += 1
                hit_no = self._hits[i]
                to_fire = sp
                self._note(FaultEvent(site, sp.kind, ref, hit_no))
                break
        if to_fire is not None:
            self._fire(to_fire, site, ref)

    def _fire(self, sp: FaultSpec, site: str, ref: str) -> None:
        msg = f"[fault-injection] {sp.kind} at {site} ({ref})"
        if sp.kind == "refuse":
            raise ConnectionRefusedError(msg)
        if sp.kind == "reset":
            raise ConnectionResetError(msg)
        if sp.kind == "delay":
            time.sleep(sp.delay_s)
            return
        if sp.kind == "drop":
            raise FaultDrop(msg)
        if sp.kind == "retry_oom":
            from ..memory.budget import RetryOOM
            raise RetryOOM(msg)
        if sp.kind == "split_oom":
            from ..memory.budget import SplitAndRetryOOM
            raise SplitAndRetryOOM(msg)
        if sp.kind in FaultSpec._DATA_KINDS:
            # a corrupt/truncate clause armed on a plain (non-data)
            # fault site models a file/entry that reads as garbage
            from .integrity import DataCorruption
            raise DataCorruption(msg)
        if sp.kind == "crash":
            print(msg, file=sys.stderr, flush=True)
            os._exit(137)

    def mutate(self, site: str, data, detail: Optional[str]):
        """corrupt_point dispatch: find the first armed corrupt/truncate
        clause matching this data-bearing site hit and apply it. The
        flip position comes from the plan's seeded RNG, so replays with
        the same spec over the same workload corrupt the same byte;
        every mutation is recorded in ``plan.log`` with its position."""
        to_fire: Optional[FaultSpec] = None
        hit_no = 0
        ref = detail if detail is not None else current_op()
        with self._lock:
            for i, sp in enumerate(self.specs):
                if sp.site != site or sp.kind not in FaultSpec._DATA_KINDS:
                    continue
                if sp.match and sp.match not in ref:
                    continue
                self._hits[i] += 1
                if self._fires[i] >= sp.count:
                    continue
                if sp.nth is not None:
                    fire = self._hits[i] == sp.nth
                else:
                    fire = self._rng.random() < sp.prob
                if not fire:
                    continue
                self._fires[i] += 1
                hit_no = self._hits[i]
                to_fire = sp
                break
            if to_fire is None:
                return data
            n = int(data.nbytes) if hasattr(data, "nbytes") else len(data)
            if n == 0:
                self._note(FaultEvent(site, to_fire.kind,
                                      f"{ref};empty;", hit_no))
                return data
            if to_fire.kind == "truncate":
                cut = max(n // 2, 1) if n > 1 else 0
                self._note(FaultEvent(site, "truncate",
                                      f"{ref};cut={cut};", hit_no))
                return data[:cut]
            pos = self._rng.randrange(n)
            self._note(FaultEvent(site, "corrupt",
                                  f"{ref};byte={pos};", hit_no))
            if hasattr(data, "dtype"):   # numpy array: mutate in place
                import numpy as np
                if not data.flags.writeable:
                    # device->host leaves can be read-only views; the
                    # caller must adopt the returned copy
                    data = data.copy()
                if data.flags["C_CONTIGUOUS"]:
                    data.view(np.uint8).reshape(-1)[pos] ^= 0xFF
                else:   # rare: perturb one element instead
                    idx = tuple(np.unravel_index(pos % data.size,
                                                 data.shape))
                    data[idx] = data[idx] + type(data[idx].item())(1)
                return data
            out = bytearray(data)
            out[pos] ^= 0xFF
            return bytes(out)


_PLAN: Optional[FaultPlan] = None
_SCOPE = threading.local()


def fault_point(site: str, detail: Optional[str] = None) -> None:
    """Hook call at a named fault site. No-op (one global load + `is`
    compare) unless a plan is armed in this process."""
    if _PLAN is None:
        return
    _PLAN.hit(site, detail)


def corrupt_point(site: str, data, detail: Optional[str] = None):
    """Data-bearing fault hook: returns ``data`` unchanged (one global
    load + `is` compare) unless an armed plan has a ``corrupt`` /
    ``truncate`` clause matching this site hit, in which case the
    returned bytes are deterministically mutated (numpy arrays are
    mutated in place). Non-data fault kinds never fire here."""
    if _PLAN is None:
        return data
    return _PLAN.mutate(site, data, detail)


def armed() -> bool:
    return _PLAN is not None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def arm_fault_plan(plan: "FaultPlan | str") -> FaultPlan:
    global _PLAN
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _PLAN = plan
    return plan


def disarm_fault_plan() -> None:
    global _PLAN
    _PLAN = None


def arm_from_conf(conf) -> Optional[FaultPlan]:
    """Arm (or keep, or disarm) the process plan from an SrtConf. The
    SAME spec keeps the existing plan — hit/fire counters must survive
    job retries within one worker process so one-shot clauses stay
    one-shot across attempts."""
    from ..conf import FAULT_PLAN_SPEC
    spec = conf.get(FAULT_PLAN_SPEC)
    global _PLAN
    if not spec:
        _PLAN = None
        return None
    if _PLAN is not None and _PLAN.spec_string() == \
            FaultPlan.parse(spec).spec_string():
        return _PLAN
    _PLAN = FaultPlan.parse(spec)
    return _PLAN


class op_scope:
    """Context manager tagging the current thread with the operator it
    is executing — gives ``memory.reserve`` hits operator granularity
    (``~match`` against the exec_id). Only entered when a plan is armed
    (exec/base.py), so the unarmed path never touches the TLS."""

    __slots__ = ("name", "prev")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.prev = getattr(_SCOPE, "op", "")
        _SCOPE.op = self.name
        return self

    def __exit__(self, *exc):
        _SCOPE.op = self.prev
        return False


def current_op() -> str:
    return getattr(_SCOPE, "op", "")
