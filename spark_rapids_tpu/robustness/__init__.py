"""Robustness layer: deterministic fault injection (faults.py) used to
prove out the transport/cluster/memory hardening paths."""

from .faults import (FaultPlan, FaultSpec, active_plan, arm_fault_plan,
                     arm_from_conf, current_op, disarm_fault_plan,
                     fault_point, op_scope)

__all__ = ["FaultPlan", "FaultSpec", "fault_point", "arm_fault_plan",
           "disarm_fault_plan", "arm_from_conf", "active_plan",
           "op_scope", "current_op"]
