"""Robustness layer: deterministic fault injection (faults.py) used to
prove out the transport/cluster/memory hardening paths, and the data
integrity layer (integrity.py: checksummed shuffle/spill/cache tiers
with DataCorruption detection and recovery)."""

from .faults import (FaultPlan, FaultSpec, active_plan, arm_fault_plan,
                     arm_from_conf, corrupt_point, current_op,
                     disarm_fault_plan, fault_point, op_scope)
from .integrity import (DataCorruption, checksum, unwrap, verify_framed,
                        wrap)

__all__ = ["FaultPlan", "FaultSpec", "fault_point", "corrupt_point",
           "arm_fault_plan", "disarm_fault_plan", "arm_from_conf",
           "active_plan", "op_scope", "current_op",
           "DataCorruption", "checksum", "wrap", "unwrap",
           "verify_framed"]
