"""Plugin shell: process lifecycle for the TPU engine.

Rebuild of Plugin.scala (SURVEY §2.1: RapidsDriverPlugin :282 /
RapidsExecutorPlugin :348): one idempotent initialization that
a) verifies the software stack (jax version gate — the reference's
   checkCudfVersion, Plugin.scala:444),
b) acquires the device and sizes the HBM batch budget from conf
   (GpuDeviceManager.initializeGpuAndMemory, :150),
c) initializes the concurrency semaphore,
d) installs the fatal-error contract: an unrecoverable device error
   logs diagnostics and (configurably) exits the process so an external
   supervisor replaces the worker (Plugin.scala:518-541 exit-code
   behavior).
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import List, Optional

from .conf import (CONCURRENT_TASKS, DEVICE_MEMORY_FRACTION,
                   DEVICE_MEMORY_LIMIT, SrtConf, active_conf, conf)

log = logging.getLogger("spark_rapids_tpu")

MIN_JAX_VERSION = (0, 4, 30)

# exit codes mirroring the reference's fatal-error contract
EXIT_FATAL_DEVICE_ERROR = 20


@dataclass
class DeviceInfo:
    platform: str
    device_kind: str
    num_local_devices: int
    hbm_bytes: Optional[int]


_STATE = {"initialized": False, "info": None}
_LOCK = threading.Lock()


class TpuVersionError(RuntimeError):
    pass


def _check_versions() -> None:
    import jax
    ver = tuple(int(x) for x in jax.__version__.split(".")[:3])
    if ver < MIN_JAX_VERSION:
        raise TpuVersionError(
            f"jax {jax.__version__} < required "
            f"{'.'.join(map(str, MIN_JAX_VERSION))}")


def _device_memory_bytes(device) -> Optional[int]:
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


def initialize(conf_obj: Optional[SrtConf] = None) -> DeviceInfo:
    """Idempotent executor-side init (RapidsExecutorPlugin.init)."""
    with _LOCK:
        if _STATE["initialized"]:
            return _STATE["info"]
        c = conf_obj or active_conf()
        _check_versions()
        import jax
        devices = jax.devices()
        dev = devices[0]
        hbm = _device_memory_bytes(dev)
        # HBM budget: explicit poolSize, else allocFraction of device
        from .memory.budget import reset_device_budget
        limit = c.get(DEVICE_MEMORY_LIMIT)
        if limit <= 0 and hbm:
            limit = int(hbm * c.get(DEVICE_MEMORY_FRACTION))
        if limit > 0:
            reset_device_budget(limit)
        # concurrency semaphore warms up from conf
        from .exec.base import device_semaphore
        device_semaphore()
        info = DeviceInfo(platform=dev.platform,
                          device_kind=getattr(dev, "device_kind", "?"),
                          num_local_devices=len(devices),
                          hbm_bytes=hbm)
        from .shims import load_extra_plugins
        _STATE["extra_plugins"] = load_extra_plugins(conf_obj
                                                     or active_conf())
        _STATE["initialized"] = True
        _STATE["info"] = info
        log.info("spark_rapids_tpu initialized: %s", info)
        return info


def shutdown() -> None:
    with _LOCK:
        from .memory.spill import _CATALOG
        if _CATALOG is not None:
            n = _CATALOG.log_leaks()
            if n:
                log.warning("%d spillable batches leaked (enable "
                            "srt.memory.leakDetection.enabled for "
                            "creation stacks)", n)
        _STATE["initialized"] = False
        _STATE["info"] = None


class FatalDeviceError(RuntimeError):
    """Unrecoverable accelerator failure (CudaFatalException role)."""


def handle_fatal_error(exc: BaseException,
                       exit_process: bool = False) -> None:
    """Log diagnostics and optionally exit so the cluster manager
    replaces this worker (Plugin.scala:518-541: the executor must NOT
    keep running on a wedged device)."""
    log.error("FATAL device error: %s", exc, exc_info=exc)
    try:
        import jax
        for d in jax.devices():
            log.error("device %s stats: %s", d,
                      getattr(d, "memory_stats", lambda: None)())
    except Exception:
        pass
    if exit_process:
        os._exit(EXIT_FATAL_DEVICE_ERROR)


def is_fatal(exc: BaseException) -> bool:
    """Classify accelerator errors the way the reference classifies
    CudaFatalException vs retryable OOMs."""
    from .memory.budget import OutOfDeviceMemory
    if isinstance(exc, OutOfDeviceMemory):
        return False
    text = str(exc).lower()
    return any(s in text for s in ("internal: ", "device halt",
                                   "data loss", "hardware"))
