"""Process-wide shared-kernel jit registry.

Each exec instance used to mint its own ``jax.jit`` wrappers in
``__init__``, so two structurally identical operators — the same
projection over the same schema in two different queries, the same
hash-partition function over the same table, the same join probe shape
— each paid a full trace + lower even though the persistent XLA cache
deduped the *compile*. Across a 99-query NDS sweep that re-trace cost
dominates wall-clock on the CPU lane (docs/PERF_NOTES.md). The registry
maps a STRUCTURAL key -> one jitted callable shared process-wide, so
trace/lower happens once per distinct (program, shapes) rather than
once per plan node.

Two entry points:

- ``shared_method_jit(obj, method, fields)`` — jit a *detached* bound
  method: a shell instance carrying only ``fields`` (copied off
  ``obj``) backs the traced function, so the registry never pins an
  exec tree (children, scan batches, broadcast state) in memory, and
  the key covers exactly the state the method may read. A field the
  method needs but that isn't listed fails loudly (AttributeError at
  trace time) — never a silent alias.
- ``shared_fn_jit(builder, *key_args)`` — jit ``builder(*key_args)``
  where ``builder`` is a MODULE-LEVEL factory whose output depends only
  on its arguments; the key is the builder's qualified name plus the
  structural encoding of ``key_args``.

Anything the structural encoder (plan/plan_cache._enc) cannot encode
falls back to a private ``jax.jit`` — unshared, never wrong.

Reference role: the spark-rapids plugin loads/caches each cuDF kernel
once per JVM, not once per operator instance
(sql-plugin/src/main/scala/.../GpuOverrides.scala module-level kernel
dispatch); here the shared unit is the traced jaxpr.

Disable with ``SRT_JIT_REGISTRY=0`` (every call falls back to a
private ``jax.jit``) when isolating trace-level bugs.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Sequence

import jax

_REGISTRY: Dict = {}
_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0, "uncached": 0}
# per defining module (builder's or method class's __module__), so a
# subsystem can report ITS share — e.g. bench reads the fused-pipeline
# compile reuse rate from module "spark_rapids_tpu.exec.fused"
_MODULE_STATS: Dict[str, Dict[str, int]] = {}


def _count(module: str, kind: str) -> None:
    _STATS[kind] += 1
    m = _MODULE_STATS.setdefault(
        module, {"hits": 0, "misses": 0, "uncached": 0})
    m[kind] += 1

_ENABLED = os.environ.get("SRT_JIT_REGISTRY", "1") != "0"

# Soft cap: parameterized workloads (distinct literals, growing
# out_capacity buckets) mint unbounded distinct keys; past the cap the
# oldest entries are evicted FIFO (re-registration later is only a
# re-trace, never wrong). dict preserves insertion order.
_MAX_ENTRIES = int(os.environ.get("SRT_JIT_REGISTRY_MAX", 8192))


def _put(key, fn) -> None:
    while len(_REGISTRY) >= _MAX_ENTRIES:
        _REGISTRY.pop(next(iter(_REGISTRY)))
    _REGISTRY[key] = fn


def _encode(parts):
    """Structural key for ``parts`` or None when not safely encodable."""
    from .plan.plan_cache import Uncachable, _enc
    try:
        return _enc(parts)
    except Uncachable:
        return None
    except Exception:
        return None


def shared_method_jit(obj, method_name: str, fields: Sequence[str],
                      extra=(), **jit_kwargs) -> Callable:
    """Shared jit of ``type(obj).<method_name>`` bound to a detached
    shell holding only ``fields`` (copied from ``obj``).

    ``extra`` folds additional hashables (e.g. a static capacity) into
    the key when the method's builder varies on them.
    """
    cls = type(obj)
    enc = _encode([getattr(obj, f) for f in fields]) if _ENABLED else None
    if enc is None:
        with _LOCK:
            _count(cls.__module__, "uncached")
        return jax.jit(getattr(obj, method_name), **jit_kwargs)
    key = (cls.__module__, cls.__qualname__, method_name, tuple(fields),
           enc, tuple(extra),
           tuple(sorted(jit_kwargs.items())) if jit_kwargs else ())
    with _LOCK:
        fn = _REGISTRY.get(key)
        if fn is not None:
            _count(cls.__module__, "hits")
            return fn
        shell = object.__new__(cls)
        for f in fields:
            setattr(shell, f, getattr(obj, f))
        fn = jax.jit(getattr(shell, method_name), **jit_kwargs)
        _put(key, fn)
        _count(cls.__module__, "misses")
    return fn


def shared_fn_jit(builder: Callable, *key_args, **jit_kwargs) -> Callable:
    """Shared jit of ``builder(*key_args)``.

    ``builder`` must be module-level and pure: its returned function
    may depend only on ``key_args`` (and module globals that never
    change). Closures defined inside methods must NOT be passed here —
    refactor them into module-level factories first.
    """
    enc = _encode(list(key_args)) if _ENABLED else None
    if enc is None:
        with _LOCK:
            _count(builder.__module__, "uncached")
        return jax.jit(builder(*key_args), **jit_kwargs)
    key = (builder.__module__,
           getattr(builder, "__qualname__", builder.__name__), enc,
           tuple(sorted(jit_kwargs.items())) if jit_kwargs else ())
    with _LOCK:
        fn = _REGISTRY.get(key)
        if fn is not None:
            _count(builder.__module__, "hits")
            return fn
        fn = jax.jit(builder(*key_args), **jit_kwargs)
        _put(key, fn)
        _count(builder.__module__, "misses")
    return fn


def stats(module: Optional[str] = None) -> dict:
    """Registry counters; with ``module``, only the hits/misses/
    uncached charged to wrappers defined in that module (plus the
    module's live entry count)."""
    with _LOCK:
        if module is not None:
            s = dict(_MODULE_STATS.get(
                module, {"hits": 0, "misses": 0, "uncached": 0}))
            s["entries"] = sum(1 for k in _REGISTRY if k[0] == module)
            return s
        s = dict(_STATS)
        s["entries"] = len(_REGISTRY)
        return s


def clear() -> None:
    """Drop every shared wrapper (next use re-registers). The mmap
    guard (plan/session.py) calls jax.clear_caches(), which empties the
    wrappers' trace caches in place — that alone releases the compiled
    executables, so this is only for tests needing a cold registry."""
    with _LOCK:
        _REGISTRY.clear()
        _STATS.update(hits=0, misses=0, uncached=0)
        _MODULE_STATS.clear()
