"""Process-wide shared-kernel jit registry.

Each exec instance used to mint its own ``jax.jit`` wrappers in
``__init__``, so two structurally identical operators — the same
projection over the same schema in two different queries, the same
hash-partition function over the same table, the same join probe shape
— each paid a full trace + lower even though the persistent XLA cache
deduped the *compile*. Across a 99-query NDS sweep that re-trace cost
dominates wall-clock on the CPU lane (docs/PERF_NOTES.md). The registry
maps a STRUCTURAL key -> one jitted callable shared process-wide, so
trace/lower happens once per distinct (program, shapes) rather than
once per plan node.

Two entry points:

- ``shared_method_jit(obj, method, fields)`` — jit a *detached* bound
  method: a shell instance carrying only ``fields`` (copied off
  ``obj``) backs the traced function, so the registry never pins an
  exec tree (children, scan batches, broadcast state) in memory, and
  the key covers exactly the state the method may read. A field the
  method needs but that isn't listed fails loudly (AttributeError at
  trace time) — never a silent alias.
- ``shared_fn_jit(builder, *key_args)`` — jit ``builder(*key_args)``
  where ``builder`` is a MODULE-LEVEL factory whose output depends only
  on its arguments; the key is the builder's qualified name plus the
  structural encoding of ``key_args``.

Anything the structural encoder (plan/plan_cache._enc) cannot encode
falls back to a private ``jax.jit`` — unshared, never wrong.

Every shared program is wrapped in a :class:`_SharedProgram` — the
compile-ledger hook (obs/roofline.py): the wrapper AOT-compiles each
new input signature through ``trace()/lower()/compile()`` with each
phase wall-timed, captures XLA ``cost_analysis()`` flops/bytes, and
keeps the compiled executable for direct dispatch (so the AOT step
REPLACES jit's internal first-call trace, it does not duplicate it).
Launches are counted on the ledger entry, and every Nth launch
(``srt.obs.roofline.sampleEvery``) is timed with a device sync and
joined with the program's bytes/flops into achieved GB/s. Disable
just the ledger with ``SRT_JIT_LEDGER=0`` (plain ``jax.jit`` wrappers,
pre-ledger behavior).

Reference role: the spark-rapids plugin loads/caches each cuDF kernel
once per JVM, not once per operator instance
(sql-plugin/src/main/scala/.../GpuOverrides.scala module-level kernel
dispatch); here the shared unit is the traced jaxpr.

Disable with ``SRT_JIT_REGISTRY=0`` (every call falls back to a
private ``jax.jit``) when isolating trace-level bugs.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Callable, Dict, Optional, Sequence

import jax

_REGISTRY: Dict = {}
# RLock so the counter helpers may take it even when the caller
# already holds it for a lookup+insert critical section.
_LOCK = threading.RLock()
_STATS = {"hits": 0, "misses": 0, "uncached": 0}
# per defining module (builder's or method class's __module__), so a
# subsystem can report ITS share — e.g. bench reads the fused-pipeline
# compile reuse rate from module "spark_rapids_tpu.exec.fused"
_MODULE_STATS: Dict[str, Dict[str, int]] = {}


def _count(module: str, kind: str) -> None:
    """Count one hit/miss/uncached for ``module``. Takes ``_LOCK``
    itself (reentrant), so every mutation of ``_STATS``/
    ``_MODULE_STATS`` is race-free regardless of the call site."""
    with _LOCK:
        _STATS[kind] += 1
        m = _MODULE_STATS.setdefault(
            module, {"hits": 0, "misses": 0, "uncached": 0})
        m[kind] += 1

_ENABLED = os.environ.get("SRT_JIT_REGISTRY", "1") != "0"
_LEDGER_ENABLED = os.environ.get("SRT_JIT_LEDGER", "1") != "0"

# Soft cap: parameterized workloads (distinct literals, growing
# out_capacity buckets) mint unbounded distinct keys; past the cap the
# oldest entries are evicted FIFO (re-registration later is only a
# re-trace, never wrong). dict preserves insertion order.
_MAX_ENTRIES = int(os.environ.get("SRT_JIT_REGISTRY_MAX", 8192))


def _put(key, fn) -> None:
    while len(_REGISTRY) >= _MAX_ENTRIES:
        _REGISTRY.pop(next(iter(_REGISTRY)))
    _REGISTRY[key] = fn


def _encode(parts):
    """Structural key for ``parts`` or None when not safely encodable."""
    from .plan.plan_cache import Uncachable, _enc
    try:
        return _enc(parts)
    except Uncachable:
        return None
    except Exception:
        return None


# --- compile ledger / roofline instrumentation (obs/roofline.py) ---

def _key_hash(key) -> str:
    """Stable short id for a structural key (ledger/event correlation
    across processes of the same build)."""
    try:
        return hashlib.sha1(repr(key).encode()).hexdigest()[:16]
    except Exception:
        return hex(id(key))[2:]


def _cost_of(compiled):
    """(flops, bytes_accessed) from ``compiled.cost_analysis()``, each
    None when the backend/jaxlib does not report it (CPU backends and
    older jaxlibs return None, a bare dict, or miss keys) — graceful
    degradation, never an error."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None

    def _num(k):
        v = ca.get(k)
        try:
            v = float(v)
        except (TypeError, ValueError):
            return None
        return v if v >= 0 else None
    return _num("flops"), _num("bytes accessed")


def _signature(args):
    """Hashable input signature (treedef + per-leaf aval incl. weak
    type) — the AOT executable cache key. Raises when any leaf has no
    aval (caller falls back to the plain jit path)."""
    from jax.api_util import shaped_abstractify
    leaves, treedef = jax.tree_util.tree_flatten(args)
    for leaf in leaves:
        if isinstance(leaf, jax.core.Tracer):
            # called under an enclosing trace (mesh lowering): jit
            # inlines fine, an AOT executable cannot run on tracers
            return None
    return treedef, tuple(shaped_abstractify(x) for x in leaves)


class _SharedProgram:
    """Callable wrapper around one shared jitted program that owns its
    compile-ledger entry.

    First call per input signature AOT-compiles (trace -> lower ->
    compile, each phase wall-timed, ``cost_analysis`` captured) and
    caches the compiled executable; later matching calls dispatch the
    executable directly — no re-trace, same steady-state as jit's own
    C++ cache. Unmatchable calls (kwargs, tracer args, signature-cache
    overflow, any AOT failure) fall back to the inner ``jax.jit``
    wrapper, so behavior never depends on the ledger. Every launch
    increments the entry's launch counter; every Nth launch
    (``roofline.sample_every()``) is synced and timed into the
    achieved-GB/s join.

    Holds only the jit wrapper, avals, and compiled executables —
    never the exec tree (the shell-detachment contract above stands).
    """

    #: distinct input signatures AOT-cached per program; beyond this
    #: (unbounded capacity buckets) calls run through the inner jit
    _SIG_CAP = 16

    __slots__ = ("fn", "entry", "_sigs", "_n", "_lock")

    def __init__(self, fn, entry):
        self.fn = fn
        self.entry = entry
        self._sigs: Dict = {}
        self._n = 0
        self._lock = threading.Lock()

    # attribute pass-through (e.g. .lower on the inner jit wrapper)
    def __getattr__(self, name):
        return getattr(self.fn, name)

    def drop_executables(self) -> None:
        """Release AOT executables (mmap-guard / cache hygiene; the
        next call re-compiles through the ledger, which records it as
        the recompile it is)."""
        with self._lock:
            self._sigs.clear()

    def _aot(self, args):
        """Timed trace/lower/compile for ``args``; returns
        (compiled, bytes, flops) or None when AOT is not possible."""
        from .obs import roofline
        try:
            t0 = time.perf_counter_ns()
            tracer = getattr(self.fn, "trace", None)
            if tracer is not None:
                traced = tracer(*args)
                t1 = time.perf_counter_ns()
                lowered = traced.lower()
            else:  # older jax: trace folded into lower
                traced = None
                t1 = t0
                lowered = self.fn.lower(*args)
            t2 = time.perf_counter_ns()
            compiled = lowered.compile()
            t3 = time.perf_counter_ns()
        except Exception:
            return None
        flops, nbytes = _cost_of(compiled)
        try:
            roofline.record_compile(self.entry, trace_ns=t1 - t0,
                                    lower_ns=t2 - t1,
                                    compile_ns=t3 - t2, flops=flops,
                                    bytes_accessed=nbytes)
        except Exception:
            pass
        return compiled, nbytes, flops

    def _launch(self, runner, args, kwargs, nbytes, flops):
        from .obs import roofline
        entry = self.entry
        entry.count_launch()
        self._n += 1
        stride = roofline.sample_every()
        if stride > 0 and self._n % stride == 1 % stride:
            t0 = time.perf_counter_ns()
            out = runner(*args, **kwargs)
            try:
                jax.block_until_ready(out)
                roofline.record_sample(
                    entry, time.perf_counter_ns() - t0, nbytes, flops)
            except Exception:
                pass
            return out
        return runner(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        if not kwargs:
            try:
                sig = _signature(args)
            except Exception:
                sig = None
            if sig is not None:
                rec = self._sigs.get(sig)
                if rec is None and sig not in self._sigs:
                    with self._lock:
                        rec = self._sigs.get(sig)
                        if rec is None and sig not in self._sigs:
                            if len(self._sigs) < self._SIG_CAP:
                                rec = self._aot(args)
                                self._sigs[sig] = rec
                if rec is not None:
                    compiled, nbytes, flops = rec
                    try:
                        return self._launch(compiled, args, {},
                                            nbytes, flops)
                    except (TypeError, ValueError):
                        # aval/placement mismatch the signature missed:
                        # the inner jit re-specializes, always right
                        pass
        # fallback: kwargs, tracers, unsignable leaves, sig overflow,
        # or failed AOT — plain shared jit, still launch-counted (no
        # per-sig cost known, so samples join with bytes=None)
        return self._launch(self.fn, args, kwargs, None, None)


def _wrap_program(fn, key, module: str, label: str):
    """Attach the compile-ledger wrapper to a fresh shared jit (miss
    path). With the ledger disabled the raw jit is stored instead."""
    if not _LEDGER_ENABLED:
        return fn
    try:
        from .obs import roofline
        entry = roofline.ensure_entry(_key_hash(key), module, label)
    except Exception:
        return fn
    return _SharedProgram(fn, entry)


def annotate(fn, display: str) -> None:
    """Set the operator-facing display label on a shared program's
    ledger entry (e.g. the fused chain description). No-op for plain
    jits (uncached fallbacks, ledger disabled)."""
    entry = getattr(fn, "entry", None)
    if entry is not None:
        entry.display = str(display)


def rebind_ledger_entries() -> None:
    """Give every live wrapper a FRESH ledger entry under its original
    key. ``roofline.reset()`` (tests) calls this after dropping the
    ledger: without it, wrappers registered before the reset would keep
    counting into orphaned entries the new ledger never sees."""
    with _LOCK:
        fns = [f for f in _REGISTRY.values()
               if isinstance(f, _SharedProgram)]
    try:
        from .obs import roofline
    except Exception:
        return
    for f in fns:
        old = f.entry
        new = roofline.ensure_entry(old.key, old.module, old.label)
        if new is not old:
            new.display = old.display
            f.entry = new


def release_executables() -> None:
    """Drop every shared program's AOT executables (companion to
    ``jax.clear_caches()`` in the mmap guard and bench sweeps — the
    wrappers hold compiled programs jax's own caches do not track).
    Ledger counters and the registry itself survive; next launches
    re-compile and are ledgered as recompiles."""
    with _LOCK:
        fns = list(_REGISTRY.values())
    for fn in fns:
        drop = getattr(fn, "drop_executables", None)
        if drop is not None:
            try:
                drop()
            except Exception:
                pass


def shared_method_jit(obj, method_name: str, fields: Sequence[str],
                      extra=(), **jit_kwargs) -> Callable:
    """Shared jit of ``type(obj).<method_name>`` bound to a detached
    shell holding only ``fields`` (copied from ``obj``).

    ``extra`` folds additional hashables (e.g. a static capacity) into
    the key when the method's builder varies on them.
    """
    cls = type(obj)
    enc = _encode([getattr(obj, f) for f in fields]) if _ENABLED else None
    if enc is None:
        _count(cls.__module__, "uncached")
        return jax.jit(getattr(obj, method_name), **jit_kwargs)
    key = (cls.__module__, cls.__qualname__, method_name, tuple(fields),
           enc, tuple(extra),
           tuple(sorted(jit_kwargs.items())) if jit_kwargs else ())
    with _LOCK:
        fn = _REGISTRY.get(key)
        if fn is not None:
            _count(cls.__module__, "hits")
            return fn
        shell = object.__new__(cls)
        for f in fields:
            setattr(shell, f, getattr(obj, f))
        fn = _wrap_program(
            jax.jit(getattr(shell, method_name), **jit_kwargs), key,
            cls.__module__, f"{cls.__qualname__}.{method_name}")
        _put(key, fn)
        _count(cls.__module__, "misses")
    return fn


def shared_fn_jit(builder: Callable, *key_args, **jit_kwargs) -> Callable:
    """Shared jit of ``builder(*key_args)``.

    ``builder`` must be module-level and pure: its returned function
    may depend only on ``key_args`` (and module globals that never
    change). Closures defined inside methods must NOT be passed here —
    refactor them into module-level factories first.
    """
    enc = _encode(list(key_args)) if _ENABLED else None
    if enc is None:
        _count(builder.__module__, "uncached")
        return jax.jit(builder(*key_args), **jit_kwargs)
    key = (builder.__module__,
           getattr(builder, "__qualname__", builder.__name__), enc,
           tuple(sorted(jit_kwargs.items())) if jit_kwargs else ())
    with _LOCK:
        fn = _REGISTRY.get(key)
        if fn is not None:
            _count(builder.__module__, "hits")
            return fn
        fn = _wrap_program(
            jax.jit(builder(*key_args), **jit_kwargs), key,
            builder.__module__,
            getattr(builder, "__qualname__", builder.__name__))
        _put(key, fn)
        _count(builder.__module__, "misses")
    return fn


def shared_stage_jit(build: Callable[[], Callable], key_parts,
                     module: str, label: str, **jit_kwargs) -> Callable:
    """Shared jit for a mesh STAGE program (plan/mesh_executor.py).

    Stage programs are built from closures over live plan nodes, so the
    ``shared_fn_jit`` contract (module-level builder, args-only key)
    cannot apply; instead the CALLER passes ``key_parts`` — the stage's
    structural signature (operator classes, expression reprs, schemas,
    mesh identity, growth factor, donation layout). Two plans whose
    stages match structurally share ONE jitted wrapper and ONE
    compile-ledger entry per stage shape — not per device, not per
    query — and jit's own aval cache handles row-capacity variation
    beneath that. Unencodable key parts fall back to a private jit
    (unshared, never wrong). ``build`` is only invoked on a miss.
    """
    enc = _encode(list(key_parts)) if _ENABLED else None
    if enc is None:
        _count(module, "uncached")
        return jax.jit(build(), **jit_kwargs)
    key = (module, "stage_program", enc,
           tuple(sorted(jit_kwargs.items())) if jit_kwargs else ())
    with _LOCK:
        fn = _REGISTRY.get(key)
        if fn is not None:
            _count(module, "hits")
            return fn
        fn = _wrap_program(jax.jit(build(), **jit_kwargs), key, module,
                           label)
        _put(key, fn)
        _count(module, "misses")
    return fn


def stats(module: Optional[str] = None) -> dict:
    """Registry counters; with ``module``, only the hits/misses/
    uncached charged to wrappers defined in that module (plus the
    module's live entry count). The whole snapshot is built under
    ``_LOCK`` — one consistent point in time, with the per-module
    dicts copied so callers never alias live counters."""
    with _LOCK:
        if module is not None:
            s = dict(_MODULE_STATS.get(
                module, {"hits": 0, "misses": 0, "uncached": 0}))
            s["entries"] = sum(1 for k in _REGISTRY if k[0] == module)
            return s
        s = dict(_STATS)
        s["entries"] = len(_REGISTRY)
        s["modules"] = {m: dict(d) for m, d in _MODULE_STATS.items()}
        return s


def clear() -> None:
    """Drop every shared wrapper (next use re-registers). The mmap
    guard (plan/session.py) calls jax.clear_caches(), which empties the
    wrappers' trace caches in place — that alone releases the compiled
    executables, so this is only for tests needing a cold registry."""
    with _LOCK:
        _REGISTRY.clear()
        _STATS.update(hits=0, misses=0, uncached=0)
        _MODULE_STATS.clear()
