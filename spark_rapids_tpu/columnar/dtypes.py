"""Logical SQL data types for the TPU columnar engine.

This is the TPU-native analogue of the Spark<->cuDF type mapping that the
reference implements in ``GpuColumnVector.java`` (``toRapidsOrNull``,
sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java:360).
Instead of mapping Spark Catalyst types onto cuDF native types, we map SQL
logical types onto JAX/XLA physical dtypes:

- integers/floats/bool map 1:1 onto jnp dtypes,
- DATE is days-since-epoch int32, TIMESTAMP is micros-since-epoch int64
  (matching Spark's internal representation),
- DECIMAL(p<=18) is a scaled int64 (Spark's "long-backed" decimals); p>18
  uses a two-limb int64 encoding (see decimal128 module),
- STRING is not a single array: it lowers to (offsets:int32[n+1], bytes:uint8)
  pairs handled by the string columns in vector.py.

Everything here is static/host-side metadata: inside ``jax.jit`` only the
physical jnp dtypes exist.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np


class DType:
    """Base class for logical SQL types."""

    #: jnp dtype of the primary physical buffer (None for nested/string).
    physical: Any = None
    #: Spark SQL name, used by Explain/TypeSig docs.
    sql_name: str = "?"

    def __repr__(self) -> str:
        return self.sql_name

    def __eq__(self, other: object) -> bool:
        return type(self) is type(other) and dataclasses.asdict(self) == dataclasses.asdict(other) \
            if dataclasses.is_dataclass(self) else type(self) is type(other)

    def __hash__(self) -> int:
        return hash(repr(self))

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def is_integral(self) -> bool:
        return False

    @property
    def is_floating(self) -> bool:
        return False

    @property
    def is_nested(self) -> bool:
        return False


class BooleanType(DType):
    physical = jnp.bool_
    sql_name = "boolean"


class _IntegralType(DType):
    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_integral(self) -> bool:
        return True


class ByteType(_IntegralType):
    physical = jnp.int8
    sql_name = "tinyint"


class ShortType(_IntegralType):
    physical = jnp.int16
    sql_name = "smallint"


class IntegerType(_IntegralType):
    physical = jnp.int32
    sql_name = "int"


class LongType(_IntegralType):
    physical = jnp.int64
    sql_name = "bigint"


class _FloatingType(DType):
    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_floating(self) -> bool:
        return True


class FloatType(_FloatingType):
    physical = jnp.float32
    sql_name = "float"


class DoubleType(_FloatingType):
    physical = jnp.float64
    sql_name = "double"


class StringType(DType):
    physical = None  # offsets+bytes pair; see StringColumn
    sql_name = "string"


class DateType(DType):
    """Days since unix epoch, int32 — Spark's internal DateType layout."""

    physical = jnp.int32
    sql_name = "date"


class TimestampType(DType):
    """Microseconds since unix epoch (UTC), int64 — Spark's internal layout."""

    physical = jnp.int64
    sql_name = "timestamp"


@dataclasses.dataclass(frozen=True, eq=False)
class DecimalType(DType):
    """Fixed-point decimal.

    precision<=18 is a scaled int64 ("long-backed", like Spark's internal
    Decimal with ``changePrecision``); larger precisions use the two-limb
    int128 emulation in ``decimal128.py`` (the reference leans on cuDF's
    native DECIMAL128 columns, e.g. GpuCast.scala / decimalExpressions).
    """

    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_LONG_PRECISION = 18

    def __post_init__(self):
        object.__setattr__(self, "sql_name", f"decimal({self.precision},{self.scale})")

    @property
    def physical(self):  # type: ignore[override]
        return jnp.int64

    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_wide(self) -> bool:
        return self.precision > self.MAX_LONG_PRECISION


class NullType(DType):
    physical = jnp.bool_
    sql_name = "void"


@dataclasses.dataclass(frozen=True, eq=False)
class ArrayType(DType):
    """List column: offsets + child column (cuDF LIST layout)."""

    element_type: DType = None  # type: ignore[assignment]
    contains_null: bool = True

    def __post_init__(self):
        object.__setattr__(self, "sql_name", f"array<{self.element_type.sql_name}>")

    @property
    def is_nested(self) -> bool:
        return True


@dataclasses.dataclass(frozen=True, eq=False)
class StructType(DType):
    """Struct column: named child columns sharing the parent validity."""

    fields: tuple = ()  # tuple[(name, DType), ...]

    def __post_init__(self):
        inner = ",".join(f"{n}:{t.sql_name}" for n, t in self.fields)
        object.__setattr__(self, "sql_name", f"struct<{inner}>")

    @property
    def is_nested(self) -> bool:
        return True

    def field_names(self):
        return [n for n, _ in self.fields]

    def field_types(self):
        return [t for _, t in self.fields]


@dataclasses.dataclass(frozen=True, eq=False)
class MapType(DType):
    """Map column: list<struct<key,value>> layout, as in cuDF/Arrow."""

    key_type: DType = None  # type: ignore[assignment]
    value_type: DType = None  # type: ignore[assignment]

    def __post_init__(self):
        object.__setattr__(
            self, "sql_name", f"map<{self.key_type.sql_name},{self.value_type.sql_name}>")

    @property
    def is_nested(self) -> bool:
        return True

    @property
    def element_type(self) -> "DType":
        """The physical entry type — maps ARE list<struct<key,value>>,
        so list machinery that asks for the element type keeps working
        on map-typed columns."""
        return StructType((("key", self.key_type),
                           ("value", self.value_type)))


# Singletons (Spark-style)
BOOL = BooleanType()
INT8 = ByteType()
INT16 = ShortType()
INT32 = IntegerType()
INT64 = LongType()
FLOAT32 = FloatType()
FLOAT64 = DoubleType()
STRING = StringType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

_NUMPY_TO_DTYPE = {
    np.dtype(np.bool_): BOOL,
    np.dtype(np.int8): INT8,
    np.dtype(np.int16): INT16,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.float32): FLOAT32,
    np.dtype(np.float64): FLOAT64,
}


def from_numpy_dtype(dt) -> DType:
    dt = np.dtype(dt)
    if dt.kind in ("U", "S", "O"):
        return STRING
    if dt.kind == "M":  # datetime64
        return TIMESTAMP
    try:
        return _NUMPY_TO_DTYPE[dt]
    except KeyError:
        raise TypeError(f"unsupported numpy dtype {dt}")


def adjust_decimal_precision(precision: int, scale: int) -> "DecimalType":
    """Spark's DecimalPrecision.adjustPrecisionScale with
    allowPrecisionLoss=true: cap at MAX_PRECISION, keeping at least 6
    fractional digits (or the natural scale if smaller)."""
    if precision <= DecimalType.MAX_PRECISION:
        return DecimalType(precision, scale)
    digits = precision - scale  # integral digits, preserved
    min_scale = min(scale, 6)
    adj_scale = max(DecimalType.MAX_PRECISION - digits, min_scale)
    return DecimalType(DecimalType.MAX_PRECISION, adj_scale)


def decimal_result_type(op: str, a: "DecimalType", b: "DecimalType"
                        ) -> "DecimalType":
    """Spark DecimalPrecision result types for binary arithmetic
    (add/sub/mul/div/mod), allowPrecisionLoss=true semantics."""
    p1, s1, p2, s2 = a.precision, a.scale, b.precision, b.scale
    if op in ("add", "sub"):
        scale = max(s1, s2)
        prec = max(p1 - s1, p2 - s2) + scale + 1
    elif op == "mul":
        scale = s1 + s2
        prec = p1 + p2 + 1
    elif op == "div":
        scale = max(6, s1 + p2 + 1)
        prec = p1 - s1 + s2 + scale
    elif op == "mod":
        scale = max(s1, s2)
        prec = min(p1 - s1, p2 - s2) + scale
    else:
        raise TypeError(f"decimal {op} unsupported")
    return adjust_decimal_precision(prec, scale)


_PROMOTION_ORDER = [INT8, INT16, INT32, INT64, FLOAT32, FLOAT64]


def promote(a: DType, b: DType) -> DType:
    """Numeric promotion for binary arithmetic, Spark-style."""
    if a == b:
        return a
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        # Decimal arithmetic result types are computed per-op in expr/arithmetic.
        raise TypeError("decimal promotion is handled per-operator")
    if a in _PROMOTION_ORDER and b in _PROMOTION_ORDER:
        return _PROMOTION_ORDER[max(_PROMOTION_ORDER.index(a), _PROMOTION_ORDER.index(b))]
    raise TypeError(f"cannot promote {a} and {b}")


def min_value(dt: DType):
    if dt.is_integral or isinstance(dt, (DateType, TimestampType)) or \
            isinstance(dt, DecimalType):
        return np.iinfo(np.dtype(dt.physical)).min
    if dt.is_floating:
        return -np.inf
    if dt == BOOL:
        return False
    raise TypeError(f"no min for {dt}")


def max_value(dt: DType):
    if dt.is_integral or isinstance(dt, (DateType, TimestampType)) or \
            isinstance(dt, DecimalType):
        return np.iinfo(np.dtype(dt.physical)).max
    if dt.is_floating:
        return np.inf
    if dt == BOOL:
        return True
    raise TypeError(f"no max for {dt}")
