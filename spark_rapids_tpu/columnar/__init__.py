from . import dtypes
from .vector import (
    Column,
    ColumnVector,
    ColumnarBatch,
    StringColumn,
    batch_from_pydict,
    batch_to_pydict,
    choose_capacity,
    column_from_numpy,
    live_mask,
)

__all__ = [
    "dtypes",
    "Column",
    "ColumnVector",
    "ColumnarBatch",
    "StringColumn",
    "batch_from_pydict",
    "batch_to_pydict",
    "choose_capacity",
    "column_from_numpy",
    "live_mask",
]
