"""Two-limb int128 decimal column and arithmetic.

The device representation for DECIMAL(p>18): an unscaled 128-bit signed
integer split into ``hi`` (int64, sign-carrying) and ``lo`` (uint64)
limbs — the layout cuDF's DECIMAL128 columns use natively and the
reference leans on throughout (decimalExpressions.scala, GpuCast.scala
decimal paths, SURVEY §7 hard-part 6). TPU constraint: XLA's x64
rewriting has no 64-bit bitcast and no 128-bit integers, so every
operation here is built from wrapping 64-bit adds/multiplies and 32-bit
limb decompositions (utils/bits.py conventions).

Key ops: add/sub with carry, full 128x128 multiply (truncated, with
overflow detection), scale by 10^k, divide by 10^k with HALF_UP
rounding (chunked 32-bit schoolbook division so no intermediate exceeds
64 bits), comparisons, and precision-overflow checks against 10^p
bounds.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt

_U32 = jnp.uint64(0xFFFFFFFF)


def _u(x):
    return x.astype(jnp.uint64)


def _s(x):
    return x.astype(jnp.int64)


class Decimal128Column:
    """DECIMAL(p>18) column: hi:int64 + lo:uint64 unscaled limbs."""

    __slots__ = ("hi", "lo", "validity", "dtype")

    def __init__(self, hi: jax.Array, lo: jax.Array, validity: jax.Array,
                 dtype: dt.DecimalType):
        self.hi = hi
        self.lo = lo
        self.validity = validity
        self.dtype = dtype

    @property
    def capacity(self) -> int:
        return self.hi.shape[0]

    def with_validity(self, validity: jax.Array) -> "Decimal128Column":
        return Decimal128Column(self.hi, self.lo, validity, self.dtype)

    def gather(self, indices: jax.Array,
               valid: Optional[jax.Array] = None) -> "Decimal128Column":
        safe = jnp.clip(indices, 0, self.capacity - 1)
        hi = jnp.take(self.hi, safe)
        lo = jnp.take(self.lo, safe)
        validity = jnp.take(self.validity, safe)
        if valid is not None:
            validity = validity & valid
            hi = jnp.where(validity, hi, jnp.zeros((), hi.dtype))
            lo = jnp.where(validity, lo, jnp.zeros((), lo.dtype))
        return Decimal128Column(hi, lo, validity, self.dtype)

    def to_numpy(self, num_rows: Optional[int] = None):
        n = self.capacity if num_rows is None else int(num_rows)
        hi = np.asarray(self.hi)[:n].astype(object)
        lo = np.asarray(self.lo)[:n].astype(object)
        vals = np.empty(n, dtype=object)
        for i in range(n):
            vals[i] = int(hi[i]) * (1 << 64) + int(lo[i])
        return vals, np.asarray(self.validity)[:n]

    def __repr__(self):
        return f"Decimal128Column({self.dtype}, capacity={self.capacity})"


def _d128_flatten(v: Decimal128Column):
    return (v.hi, v.lo, v.validity), v.dtype


def _d128_unflatten(dtype, children):
    return Decimal128Column(*children, dtype=dtype)


jax.tree_util.register_pytree_node(Decimal128Column, _d128_flatten,
                                   _d128_unflatten)


# ---------------------------------------------------------------------------
# limb arithmetic ((hi:int64, lo:uint64) pairs; wrapping semantics)
# ---------------------------------------------------------------------------

def d128_from_i64(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sign-extend an int64 into two limbs."""
    return jnp.where(x < 0, jnp.int64(-1), jnp.int64(0)), _u(x)


def d128_add(ah, al, bh, bl):
    lo = al + bl  # wrapping uint64
    carry = (lo < al).astype(jnp.int64)
    hi = ah + bh + carry
    return hi, lo


def d128_neg(h, l):
    nl = (~l) + jnp.uint64(1)
    nh = (~h) + jnp.where(nl == 0, jnp.int64(1), jnp.int64(0))
    return nh, nl


def d128_sub(ah, al, bh, bl):
    nh, nl = d128_neg(bh, bl)
    return d128_add(ah, al, nh, nl)


def d128_abs(h, l):
    neg = h < 0
    nh, nl = d128_neg(h, l)
    return jnp.where(neg, nh, h), jnp.where(neg, nl, l)


def d128_lt(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def d128_eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def _mul_u64(a, b):
    """Full 64x64 -> 128 unsigned multiply via 32-bit limbs."""
    a0, a1 = a & _U32, a >> jnp.uint64(32)
    b0, b1 = b & _U32, b >> jnp.uint64(32)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> jnp.uint64(32)) + (p01 & _U32) + (p10 & _U32)
    lo = (p00 & _U32) | (mid << jnp.uint64(32))
    hi = p11 + (p01 >> jnp.uint64(32)) + (p10 >> jnp.uint64(32)) + \
        (mid >> jnp.uint64(32))
    return hi, lo


def d128_mul(ah, al, bh, bl):
    """Signed 128x128 multiply, truncated to 128 bits, with an overflow
    flag (true when the mathematical product does not fit in 128 bits).
    Operates on magnitudes, reapplies sign — overflow detection is then
    a check on the high magnitude limbs."""
    sa, sb = ah < 0, bh < 0
    ah1, al1 = d128_abs(ah, al)
    bh1, bl1 = d128_abs(bh, bl)
    uah, ubh = _u(ah1), _u(bh1)
    # |a| * |b| = (ah*2^64 + al)(bh*2^64 + bl)
    p_hi, p_lo = _mul_u64(al1, bl1)          # al*bl -> (hi, lo)
    cross1 = uah * bl1                        # wraps; overflow checked below
    cross2 = ubh * al1
    hi = p_hi + cross1 + cross2
    # overflow if: both highs nonzero, or cross terms overflow 64 bits,
    # or result hi exceeds the signed-positive range
    c1h, _ = _mul_u64(uah, bl1)
    c2h, _ = _mul_u64(ubh, al1)
    overflow = (uah != 0) & (ubh != 0)
    overflow |= (c1h != 0) | (c2h != 0)
    overflow |= (hi < p_hi)  # wrapped on accumulate (approximate)
    neg = sa ^ sb
    nh, nl = d128_neg(_s(hi), p_lo)
    rh = jnp.where(neg, nh, _s(hi))
    rl = jnp.where(neg, nl, p_lo)
    overflow |= (_s(hi) < 0)  # magnitude spilled into the sign bit
    return rh, rl, overflow


_POW10_U64 = [10 ** k for k in range(20)]


def d128_mul_pow10(h, l, k: int):
    """(h, l) * 10^k, k static >= 0; overflow flag like d128_mul."""
    overflow = jnp.zeros(h.shape, jnp.bool_)
    while k > 0:
        step = min(k, 18)
        m = jnp.uint64(_POW10_U64[step])
        sa = h < 0
        h1, l1 = d128_abs(h, l)
        phi, plo = _mul_u64(l1, m)
        cross = _u(h1) * m
        chk, _ = _mul_u64(_u(h1), m)
        hi = phi + cross
        overflow |= (chk != 0) | (hi < phi) | (_s(hi) < 0)
        nh, nl = d128_neg(_s(hi), plo)
        h = jnp.where(sa, nh, _s(hi))
        l = jnp.where(sa, nl, plo)
        k -= step
    return h, l, overflow


def _divmod_small(h, l, d: int):
    """Unsigned (h:uint64, l:uint64) // d for d < 2^31, via 32-bit
    schoolbook division (no intermediate exceeds 64 bits)."""
    dd = jnp.uint64(d)
    limbs = [h >> jnp.uint64(32), h & _U32, l >> jnp.uint64(32), l & _U32]
    rem = jnp.zeros(h.shape, jnp.uint64)
    qs = []
    for limb in limbs:
        cur = (rem << jnp.uint64(32)) | limb
        q = cur // dd
        rem = cur - q * dd
        qs.append(q & _U32)
    qh = (qs[0] << jnp.uint64(32)) | qs[1]
    ql = (qs[2] << jnp.uint64(32)) | qs[3]
    return qh, ql, rem


def d128_div_pow10_half_up(h, l, k: int):
    """(h, l) / 10^k with HALF_UP rounding, k static >= 0."""
    if k == 0:
        return h, l
    neg = h < 0
    mh, ml = d128_abs(h, l)
    uh, ul = _u(mh), _u(ml)
    # add 10^k / 2 for HALF_UP before truncating division
    half = 10 ** k // 2
    add_h = jnp.uint64(half >> 64)
    add_l = jnp.uint64(half & ((1 << 64) - 1))
    nl = ul + add_l
    carry = (nl < ul).astype(jnp.uint64)
    nh = uh + add_h + carry
    uh, ul = nh, nl
    kk = k
    while kk > 0:
        step = min(kk, 9)
        uh, ul, _ = _divmod_small(uh, ul, 10 ** step)
        kk -= step
    rh, rl = _s(uh), ul
    nh2, nl2 = d128_neg(rh, rl)
    return jnp.where(neg, nh2, rh), jnp.where(neg, nl2, rl)


def _pow10_limbs(p: int) -> Tuple[int, int]:
    v = 10 ** p
    return v >> 64, v & ((1 << 64) - 1)


def d128_fits_precision(h, l, precision: int):
    """|x| < 10^precision (Spark changePrecision overflow check)."""
    if precision >= 39:
        return jnp.ones(h.shape, jnp.bool_)
    bh, bl = _pow10_limbs(precision)
    mh, ml = d128_abs(h, l)
    return d128_lt(mh, ml, jnp.int64(bh), jnp.uint64(bl))


def d128_rescale(h, l, from_scale: int, to_scale: int):
    """Change scale; returns (h, l, overflow_from_upscale)."""
    if to_scale == from_scale:
        return h, l, jnp.zeros(h.shape, jnp.bool_)
    if to_scale > from_scale:
        return d128_mul_pow10(h, l, to_scale - from_scale)
    h2, l2 = d128_div_pow10_half_up(h, l, from_scale - to_scale)
    return h2, l2, jnp.zeros(h.shape, jnp.bool_)


# ---------------------------------------------------------------------------
# host <-> device
# ---------------------------------------------------------------------------

def from_unscaled_ints(values, capacity: int, dtype: dt.DecimalType,
                       mask: Optional[np.ndarray] = None
                       ) -> Decimal128Column:
    """Build from python unscaled ints (arbitrary precision)."""
    n = len(values)
    valid = np.array([v is not None for v in values], dtype=bool) \
        if mask is None else np.asarray(mask, dtype=bool)
    hi = np.zeros(capacity, np.int64)
    lo = np.zeros(capacity, np.uint64)
    for i in range(n):
        if not valid[i] or values[i] is None:
            continue
        v = int(values[i])
        hi[i] = np.int64(v >> 64)  # python >> is arithmetic: sign-correct
        lo[i] = np.uint64(v & ((1 << 64) - 1))
    validity = np.zeros(capacity, bool)
    validity[:n] = valid
    return Decimal128Column(jnp.asarray(hi), jnp.asarray(lo),
                            jnp.asarray(validity), dtype)
