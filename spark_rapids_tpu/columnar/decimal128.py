"""Two-limb int128 decimal column and arithmetic.

The device representation for DECIMAL(p>18): an unscaled 128-bit signed
integer split into ``hi`` (int64, sign-carrying) and ``lo`` (uint64)
limbs — the layout cuDF's DECIMAL128 columns use natively and the
reference leans on throughout (decimalExpressions.scala, GpuCast.scala
decimal paths, SURVEY §7 hard-part 6). TPU constraint: XLA's x64
rewriting has no 64-bit bitcast and no 128-bit integers, so every
operation here is built from wrapping 64-bit adds/multiplies and 32-bit
limb decompositions (utils/bits.py conventions).

Key ops: add/sub with carry, full 128x128 multiply (truncated, with
overflow detection), scale by 10^k, divide by 10^k with HALF_UP
rounding (chunked 32-bit schoolbook division so no intermediate exceeds
64 bits), comparisons, and precision-overflow checks against 10^p
bounds.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt

_U32 = jnp.uint64(0xFFFFFFFF)


def _u(x):
    return x.astype(jnp.uint64)


def _s(x):
    return x.astype(jnp.int64)


class Decimal128Column:
    """DECIMAL(p>18) column: hi:int64 + lo:uint64 unscaled limbs."""

    __slots__ = ("hi", "lo", "validity", "dtype")

    def __init__(self, hi: jax.Array, lo: jax.Array, validity: jax.Array,
                 dtype: dt.DecimalType):
        self.hi = hi
        self.lo = lo
        self.validity = validity
        self.dtype = dtype

    @property
    def capacity(self) -> int:
        return self.hi.shape[0]

    def with_validity(self, validity: jax.Array) -> "Decimal128Column":
        return Decimal128Column(self.hi, self.lo, validity, self.dtype)

    def gather(self, indices: jax.Array,
               valid: Optional[jax.Array] = None) -> "Decimal128Column":
        safe = jnp.clip(indices, 0, self.capacity - 1)
        hi = jnp.take(self.hi, safe)
        lo = jnp.take(self.lo, safe)
        validity = jnp.take(self.validity, safe)
        if valid is not None:
            validity = validity & valid
            hi = jnp.where(validity, hi, jnp.zeros((), hi.dtype))
            lo = jnp.where(validity, lo, jnp.zeros((), lo.dtype))
        return Decimal128Column(hi, lo, validity, self.dtype)

    def to_numpy(self, num_rows: Optional[int] = None):
        n = self.capacity if num_rows is None else int(num_rows)
        hi = np.asarray(self.hi)[:n].astype(object)
        lo = np.asarray(self.lo)[:n].astype(object)
        vals = np.empty(n, dtype=object)
        for i in range(n):
            vals[i] = int(hi[i]) * (1 << 64) + int(lo[i])
        return vals, np.asarray(self.validity)[:n]

    def __repr__(self):
        return f"Decimal128Column({self.dtype}, capacity={self.capacity})"


def _d128_flatten(v: Decimal128Column):
    return (v.hi, v.lo, v.validity), v.dtype


def _d128_unflatten(dtype, children):
    return Decimal128Column(*children, dtype=dtype)


jax.tree_util.register_pytree_node(Decimal128Column, _d128_flatten,
                                   _d128_unflatten)


# ---------------------------------------------------------------------------
# limb arithmetic ((hi:int64, lo:uint64) pairs; wrapping semantics)
# ---------------------------------------------------------------------------

def d128_from_i64(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Sign-extend an int64 into two limbs."""
    return jnp.where(x < 0, jnp.int64(-1), jnp.int64(0)), _u(x)


def d128_add(ah, al, bh, bl):
    lo = al + bl  # wrapping uint64
    carry = (lo < al).astype(jnp.int64)
    hi = ah + bh + carry
    return hi, lo


def d128_neg(h, l):
    nl = (~l) + jnp.uint64(1)
    nh = (~h) + jnp.where(nl == 0, jnp.int64(1), jnp.int64(0))
    return nh, nl


def d128_sub(ah, al, bh, bl):
    nh, nl = d128_neg(bh, bl)
    return d128_add(ah, al, nh, nl)


def d128_abs(h, l):
    neg = h < 0
    nh, nl = d128_neg(h, l)
    return jnp.where(neg, nh, h), jnp.where(neg, nl, l)


def d128_lt(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & (al < bl))


def d128_eq(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def _mul_u64(a, b):
    """Full 64x64 -> 128 unsigned multiply via 32-bit limbs."""
    a0, a1 = a & _U32, a >> jnp.uint64(32)
    b0, b1 = b & _U32, b >> jnp.uint64(32)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> jnp.uint64(32)) + (p01 & _U32) + (p10 & _U32)
    lo = (p00 & _U32) | (mid << jnp.uint64(32))
    hi = p11 + (p01 >> jnp.uint64(32)) + (p10 >> jnp.uint64(32)) + \
        (mid >> jnp.uint64(32))
    return hi, lo


def d128_mul(ah, al, bh, bl):
    """Signed 128x128 multiply, truncated to 128 bits, with an overflow
    flag (true when the mathematical product does not fit in 128 bits).
    Operates on magnitudes, reapplies sign — overflow detection is then
    a check on the high magnitude limbs."""
    sa, sb = ah < 0, bh < 0
    ah1, al1 = d128_abs(ah, al)
    bh1, bl1 = d128_abs(bh, bl)
    uah, ubh = _u(ah1), _u(bh1)
    # |a| * |b| = (ah*2^64 + al)(bh*2^64 + bl)
    p_hi, p_lo = _mul_u64(al1, bl1)          # al*bl -> (hi, lo)
    cross1 = uah * bl1                        # wraps; overflow checked below
    cross2 = ubh * al1
    hi = p_hi + cross1 + cross2
    # overflow if: both highs nonzero, or cross terms overflow 64 bits,
    # or result hi exceeds the signed-positive range
    c1h, _ = _mul_u64(uah, bl1)
    c2h, _ = _mul_u64(ubh, al1)
    overflow = (uah != 0) & (ubh != 0)
    overflow |= (c1h != 0) | (c2h != 0)
    overflow |= (hi < p_hi)  # wrapped on accumulate (approximate)
    neg = sa ^ sb
    nh, nl = d128_neg(_s(hi), p_lo)
    rh = jnp.where(neg, nh, _s(hi))
    rl = jnp.where(neg, nl, p_lo)
    overflow |= (_s(hi) < 0)  # magnitude spilled into the sign bit
    return rh, rl, overflow


_POW10_U64 = [10 ** k for k in range(20)]


def d128_mul_pow10(h, l, k: int):
    """(h, l) * 10^k, k static >= 0; overflow flag like d128_mul."""
    overflow = jnp.zeros(h.shape, jnp.bool_)
    while k > 0:
        step = min(k, 18)
        m = jnp.uint64(_POW10_U64[step])
        sa = h < 0
        h1, l1 = d128_abs(h, l)
        phi, plo = _mul_u64(l1, m)
        cross = _u(h1) * m
        chk, _ = _mul_u64(_u(h1), m)
        hi = phi + cross
        overflow |= (chk != 0) | (hi < phi) | (_s(hi) < 0)
        nh, nl = d128_neg(_s(hi), plo)
        h = jnp.where(sa, nh, _s(hi))
        l = jnp.where(sa, nl, plo)
        k -= step
    return h, l, overflow


def _divmod_small(h, l, d: int):
    """Unsigned (h:uint64, l:uint64) // d for d < 2^31, via 32-bit
    schoolbook division (no intermediate exceeds 64 bits)."""
    dd = jnp.uint64(d)
    limbs = [h >> jnp.uint64(32), h & _U32, l >> jnp.uint64(32), l & _U32]
    rem = jnp.zeros(h.shape, jnp.uint64)
    qs = []
    for limb in limbs:
        cur = (rem << jnp.uint64(32)) | limb
        q = cur // dd
        rem = cur - q * dd
        qs.append(q & _U32)
    qh = (qs[0] << jnp.uint64(32)) | qs[1]
    ql = (qs[2] << jnp.uint64(32)) | qs[3]
    return qh, ql, rem


def d128_div_pow10_half_up(h, l, k: int):
    """(h, l) / 10^k with HALF_UP rounding, k static >= 0."""
    if k == 0:
        return h, l
    neg = h < 0
    mh, ml = d128_abs(h, l)
    uh, ul = _u(mh), _u(ml)
    # add 10^k / 2 for HALF_UP before truncating division
    half = 10 ** k // 2
    add_h = jnp.uint64(half >> 64)
    add_l = jnp.uint64(half & ((1 << 64) - 1))
    nl = ul + add_l
    carry = (nl < ul).astype(jnp.uint64)
    nh = uh + add_h + carry
    uh, ul = nh, nl
    kk = k
    while kk > 0:
        step = min(kk, 9)
        uh, ul, _ = _divmod_small(uh, ul, 10 ** step)
        kk -= step
    rh, rl = _s(uh), ul
    nh2, nl2 = d128_neg(rh, rl)
    return jnp.where(neg, nh2, rh), jnp.where(neg, nl2, rl)


def d128_div_pow10_trunc(h, l, k: int):
    """(h, l) / 10^k truncating toward zero, k static >= 0."""
    if k == 0:
        return h, l
    neg = h < 0
    mh, ml = d128_abs(h, l)
    uh, ul = _u(mh), _u(ml)
    kk = k
    while kk > 0:
        step = min(kk, 9)
        uh, ul, _ = _divmod_small(uh, ul, 10 ** step)
        kk -= step
    rh, rl = _s(uh), ul
    nh2, nl2 = d128_neg(rh, rl)
    return jnp.where(neg, nh2, rh), jnp.where(neg, nl2, rl)


def _u128_ge(ah, al, bh, bl):
    """Unsigned (ah,al) >= (bh,bl); all uint64."""
    return (ah > bh) | ((ah == bh) & (al >= bl))


def _u128_sub(ah, al, bh, bl):
    lo = al - bl
    borrow = (al < bl).astype(jnp.uint64)
    return ah - bh - borrow, lo


def d128_divmod_u(nh, nl, dh, dl):
    """Unsigned 128/128 long division: returns (qh, ql, rh, rl), all
    uint64. Division by zero yields garbage — callers must mask.

    Shift-subtract restoring division, 128 fixed iterations under
    ``lax.fori_loop`` — data-independent control flow, so XLA compiles
    one small loop body instead of a 128-step unrolled graph."""
    zero = jnp.zeros_like(nh)

    def body(i, st):
        qh, ql, rh, rl = st
        k = jnp.uint64(127) - jnp.uint64(i)
        # bit k of the dividend
        bit = jnp.where(
            k >= 64,
            (nh >> jnp.where(k >= 64, k - jnp.uint64(64), jnp.uint64(0)))
            & jnp.uint64(1),
            (nl >> jnp.where(k >= 64, jnp.uint64(0), k)) & jnp.uint64(1))
        # remainder <<= 1 | bit
        rh = (rh << jnp.uint64(1)) | (rl >> jnp.uint64(63))
        rl = (rl << jnp.uint64(1)) | bit
        ge = _u128_ge(rh, rl, dh, dl)
        sh, sl = _u128_sub(rh, rl, dh, dl)
        rh = jnp.where(ge, sh, rh)
        rl = jnp.where(ge, sl, rl)
        qbit = ge.astype(jnp.uint64)
        qh = qh | jnp.where(
            k >= 64,
            qbit << jnp.where(k >= 64, k - jnp.uint64(64), jnp.uint64(0)),
            jnp.uint64(0))
        ql = ql | jnp.where(
            k >= 64, jnp.uint64(0),
            qbit << jnp.where(k >= 64, jnp.uint64(0), k))
        return qh, ql, rh, rl

    qh, ql, rh, rl = jax.lax.fori_loop(
        0, 128, body, (zero, zero, zero, zero))
    return qh, ql, rh, rl


def d128_div_trunc(ah, al, bh, bl):
    """Signed truncating 128/128 divide; returns (q_hi, q_lo, r_hi,
    r_lo) with the remainder taking the dividend's sign (Java %)."""
    qneg = (ah < 0) ^ (bh < 0)
    rneg = ah < 0
    mah, mal = d128_abs(ah, al)
    mbh, mbl = d128_abs(bh, bl)
    qh, ql, rh, rl = d128_divmod_u(_u(mah), _u(mal), _u(mbh), _u(mbl))
    sqh, sql = _s(qh), ql
    srh, srl = _s(rh), rl
    nqh, nql = d128_neg(sqh, sql)
    nrh, nrl = d128_neg(srh, srl)
    return (jnp.where(qneg, nqh, sqh), jnp.where(qneg, nql, sql),
            jnp.where(rneg, nrh, srh), jnp.where(rneg, nrl, srl))


# ---------------------------------------------------------------------------
# 256-bit intermediates (Spark-exact wide multiply / divide)
#
# decimal(38)*decimal(38) products and scaled-up division numerators
# exceed 128 bits before the result scale is applied — the reference
# leans on cuDF's __int128/256-bit fixed-point paths for the same reason
# (decimalExpressions.scala, GpuDecimalMultiply/GpuDecimalDivide). Here a
# 256-bit magnitude is four uint64 limbs, little-endian.
# ---------------------------------------------------------------------------

def _mul_u128_to_256(ah, al, bh, bl):
    """Unsigned 128x128 -> 256-bit product as 4 uint64 limbs (LE)."""
    p0h, p0l = _mul_u64(al, bl)          # al*bl -> limbs 0,1
    p1h, p1l = _mul_u64(al, bh)          # -> limbs 1,2
    p2h, p2l = _mul_u64(ah, bl)          # -> limbs 1,2
    p3h, p3l = _mul_u64(ah, bh)          # -> limbs 2,3
    w0 = p0l
    w1 = p0h + p1l
    c1 = (w1 < p0h).astype(jnp.uint64)
    w1b = w1 + p2l
    c1 = c1 + (w1b < w1).astype(jnp.uint64)
    w2 = p1h + p2h
    c2 = (w2 < p1h).astype(jnp.uint64)
    w2b = w2 + p3l
    c2 = c2 + (w2b < w2).astype(jnp.uint64)
    w2c = w2b + c1
    c2 = c2 + (w2c < w2b).astype(jnp.uint64)
    w3 = p3h + c2
    return w0, w1b, w2c, w3


def _d256_divmod_small(limbs, d: int):
    """(4xuint64 LE) // d for d < 2^31 via 32-bit schoolbook division.
    Returns (quotient limbs, remainder)."""
    dd = jnp.uint64(d)
    w0, w1, w2, w3 = limbs
    chunks = []
    for w in (w3, w2, w1, w0):
        chunks.extend([w >> jnp.uint64(32), w & _U32])
    rem = jnp.zeros(w0.shape, jnp.uint64)
    qs = []
    for c in chunks:
        cur = (rem << jnp.uint64(32)) | c
        q = cur // dd
        rem = cur - q * dd
        qs.append(q & _U32)
    out = []
    for i in (3, 2, 1, 0):
        out.append((qs[2 * i] << jnp.uint64(32)) | qs[2 * i + 1])
    return tuple(out), rem


def _d256_add_small(limbs, const: int):
    """Add a python-int constant (< 2^256) to a 256-bit magnitude."""
    out = []
    carry = jnp.zeros(limbs[0].shape, jnp.uint64)
    for i, w in enumerate(limbs):
        a = jnp.uint64((const >> (64 * i)) & ((1 << 64) - 1))
        r = w + a
        c_new = (r < w).astype(jnp.uint64)
        r2 = r + carry
        c_new = c_new + (r2 < carry).astype(jnp.uint64)
        out.append(r2)
        carry = c_new
    return tuple(out)


def d256_div_pow10_half_up(limbs, k: int):
    """256-bit magnitude / 10^k with HALF_UP rounding."""
    if k == 0:
        return limbs
    limbs = _d256_add_small(limbs, 10 ** k // 2)
    kk = k
    while kk > 0:
        step = min(kk, 9)
        limbs, _ = _d256_divmod_small(limbs, 10 ** step)
        kk -= step
    return limbs


def _d256_mul_small(limbs, m: int):
    """256-bit magnitude * m (m < 2^31). Returns (limbs, overflow)."""
    mm = jnp.uint64(m)
    w0, w1, w2, w3 = limbs
    chunks = []
    for w in (w0, w1, w2, w3):
        chunks.extend([w & _U32, w >> jnp.uint64(32)])
    carry = jnp.zeros(w0.shape, jnp.uint64)
    outc = []
    for c in chunks:
        cur = c * mm + carry
        outc.append(cur & _U32)
        carry = cur >> jnp.uint64(32)
    out = tuple((outc[2 * i + 1] << jnp.uint64(32)) | outc[2 * i]
                for i in range(4))
    return out, carry != 0


def d256_mul_pow10(limbs, k: int):
    """256-bit magnitude * 10^k with overflow detection."""
    overflow = jnp.zeros(limbs[0].shape, jnp.bool_)
    while k > 0:
        step = min(k, 9)
        limbs, o = _d256_mul_small(limbs, 10 ** step)
        overflow |= o
        k -= step
    return limbs, overflow


def d256_fits_128(limbs):
    """Magnitude fits a signed 128-bit value (< 2^127)."""
    w0, w1, w2, w3 = limbs
    return (w2 == 0) & (w3 == 0) & ((w1 >> jnp.uint64(63)) == 0)


def d256_divmod_u128(n_limbs, dh, dl):
    """Unsigned 256-bit / 128-bit long division. Returns (overflow,
    qh, ql, rh, rl): ``overflow`` is set when the quotient exceeds 128
    bits. Division by zero yields garbage — callers must mask."""
    w0, w1, w2, w3 = n_limbs
    zero = jnp.zeros_like(w0)

    def bit_of(k):
        """bit k (0..255) of the 256-bit dividend; k traced uint64."""
        limb_idx = k >> jnp.uint64(6)
        sh = k & jnp.uint64(63)
        v0 = (w0 >> sh) & jnp.uint64(1)
        v1 = (w1 >> sh) & jnp.uint64(1)
        v2 = (w2 >> sh) & jnp.uint64(1)
        v3 = (w3 >> sh) & jnp.uint64(1)
        return jnp.where(limb_idx == 0, v0,
                         jnp.where(limb_idx == 1, v1,
                                   jnp.where(limb_idx == 2, v2, v3)))

    def body(i, st):
        qh, ql, rh, rl, ovf = st
        k = jnp.uint64(255) - jnp.uint64(i)
        bit = bit_of(k)
        rh = (rh << jnp.uint64(1)) | (rl >> jnp.uint64(63))
        rl = (rl << jnp.uint64(1)) | bit
        ge = _u128_ge(rh, rl, dh, dl)
        sh_, sl_ = _u128_sub(rh, rl, dh, dl)
        rh = jnp.where(ge, sh_, rh)
        rl = jnp.where(ge, sl_, rl)
        # shift a new bit into the quotient; anything pushed past bit
        # 127 is overflow
        ovf = ovf | ((qh >> jnp.uint64(63)) & jnp.uint64(1)).astype(jnp.bool_)
        qh = (qh << jnp.uint64(1)) | (ql >> jnp.uint64(63))
        ql = (ql << jnp.uint64(1)) | ge.astype(jnp.uint64)
        return qh, ql, rh, rl, ovf

    qh, ql, rh, rl, ovf = jax.lax.fori_loop(
        0, 256, body, (zero, zero, zero, zero,
                       jnp.zeros(w0.shape, jnp.bool_)))
    return ovf, qh, ql, rh, rl


def d128_mul_exact(ah, al, bh, bl, drop_scale: int):
    """Spark-exact wide multiply: |a|*|b| in 256 bits, divide by
    10^drop_scale with HALF_UP, reapply sign. Returns (hi, lo,
    overflow) where overflow = the rounded product exceeds 128 bits."""
    neg = (ah < 0) ^ (bh < 0)
    mah, mal = d128_abs(ah, al)
    mbh, mbl = d128_abs(bh, bl)
    limbs = _mul_u128_to_256(_u(mah), _u(mal), _u(mbh), _u(mbl))
    limbs = d256_div_pow10_half_up(limbs, drop_scale)
    ok = d256_fits_128(limbs)
    w0, w1 = limbs[0], limbs[1]
    sh, sl = _s(w1), w0
    nh, nl = d128_neg(sh, sl)
    return jnp.where(neg, nh, sh), jnp.where(neg, nl, sl), ~ok


def d128_div_exact(ah, al, bh, bl, up_scale: int):
    """Spark-exact wide divide: (|a| * 10^up_scale) / |b| with HALF_UP
    rounding via 256-bit numerator. Returns (hi, lo, overflow);
    division by zero must be masked by the caller."""
    neg = (ah < 0) ^ (bh < 0)
    mah, mal = d128_abs(ah, al)
    mbh, mbl = d128_abs(bh, bl)
    k0 = min(up_scale, 38)
    ph, pl = _pow10_limbs(k0)
    n_limbs = _mul_u128_to_256(_u(mah), _u(mal),
                               jnp.full(ah.shape, np.uint64(ph)),
                               jnp.full(ah.shape, np.uint64(pl)))
    num_ovf = jnp.zeros(ah.shape, jnp.bool_)
    if up_scale > k0:
        n_limbs, num_ovf = d256_mul_pow10(n_limbs, up_scale - k0)
    ubh, ubl = _u(mbh), _u(mbl)
    ovf, qh, ql, rh, rl = d256_divmod_u128(n_limbs, ubh, ubl)
    ovf = ovf | num_ovf
    # HALF_UP on the remainder
    r2h = (rh << jnp.uint64(1)) | (rl >> jnp.uint64(63))
    r2l = rl << jnp.uint64(1)
    bump = _u128_ge(r2h, r2l, ubh, ubl).astype(jnp.uint64)
    ql2 = ql + bump
    qh2 = qh + (ql2 < ql).astype(jnp.uint64)
    ovf = ovf | ((qh2 >> jnp.uint64(63)) != 0)
    sh, sl = _s(qh2), ql2
    nh, nl = d128_neg(sh, sl)
    return jnp.where(neg, nh, sh), jnp.where(neg, nl, sl), ovf


def d128_to_f64(h, l):
    """Approximate float64 value of the signed 128-bit integer.

    Convert SIGN-MAGNITUDE, not h*2^64+l directly: for small negative
    values (h = -1, l = 2^64 - v) the direct form cancels two ~2^64
    floats whose difference is far below their ulp (2048 at 2^64), so
    e.g. -350 rounded to exactly 0.0 (round-4 bug: every small negative
    decimal cast to double collapsed to zero)."""
    neg = h < 0
    nh, nl = d128_neg(h, l)
    mh = jnp.where(neg, nh, h)
    ml = jnp.where(neg, nl, l)
    m = mh.astype(jnp.float64) * (2.0 ** 64) + ml.astype(jnp.float64)
    return jnp.where(neg, -m, m)


def f64_to_d128(x):
    """Round a float64 to the nearest signed 128-bit integer limbs.
    Precision is inherently float64's 53 bits; out-of-range values wrap
    (callers bound-check via the float before converting)."""
    neg = x < 0
    m = jnp.abs(x)
    hi_f = jnp.floor(m / (2.0 ** 64))
    lo_f = m - hi_f * (2.0 ** 64)
    # round lo; a carry can push it to exactly 2^64
    lo_f = jnp.floor(lo_f + 0.5)
    carry = lo_f >= 2.0 ** 64
    hi_f = hi_f + carry
    lo_f = jnp.where(carry, 0.0, lo_f)
    h = jnp.clip(hi_f, 0.0, 2.0 ** 63).astype(jnp.uint64)
    l = lo_f.astype(jnp.uint64)
    sh, sl = _s(h), l
    nh, nl = d128_neg(sh, sl)
    return jnp.where(neg, nh, sh), jnp.where(neg, nl, sl)


def _pow10_limbs(p: int) -> Tuple[int, int]:
    v = 10 ** p
    return v >> 64, v & ((1 << 64) - 1)


def d128_fits_precision(h, l, precision: int):
    """|x| < 10^precision (Spark changePrecision overflow check)."""
    if precision >= 39:
        return jnp.ones(h.shape, jnp.bool_)
    bh, bl = _pow10_limbs(precision)
    mh, ml = d128_abs(h, l)
    return d128_lt(mh, ml, jnp.int64(bh), jnp.uint64(bl))


def d128_rescale(h, l, from_scale: int, to_scale: int):
    """Change scale; returns (h, l, overflow_from_upscale)."""
    if to_scale == from_scale:
        return h, l, jnp.zeros(h.shape, jnp.bool_)
    if to_scale > from_scale:
        return d128_mul_pow10(h, l, to_scale - from_scale)
    h2, l2 = d128_div_pow10_half_up(h, l, from_scale - to_scale)
    return h2, l2, jnp.zeros(h.shape, jnp.bool_)


# ---------------------------------------------------------------------------
# host <-> device
# ---------------------------------------------------------------------------

def limbs_of(col) -> Tuple[jax.Array, jax.Array]:
    """(hi:int64, lo:uint64) limbs of any decimal column — sign-extends
    long-backed (int64) decimals, passes wide columns through."""
    if isinstance(col, Decimal128Column):
        return col.hi, col.lo
    return d128_from_i64(col.data.astype(jnp.int64))


def build_decimal_column(hi, lo, validity, dtype: dt.DecimalType):
    """Materialize limbs as the physical column for ``dtype``: a
    Decimal128Column when wide, otherwise an int64 ColumnVector (the
    value is known to fit by the caller's precision check). Lanes under
    nulls are zeroed (the engine-wide invariant)."""
    from .vector import ColumnVector
    z64 = jnp.zeros((), jnp.int64)
    if dtype.is_wide:
        zu = jnp.zeros((), jnp.uint64)
        return Decimal128Column(jnp.where(validity, hi, z64),
                                jnp.where(validity, lo, zu),
                                validity, dtype)
    data = lo.astype(jnp.int64)  # wrapping; exact when |v| < 2^63
    return ColumnVector(jnp.where(validity, data, z64), validity, dtype)


def seg_sum128(hi, lo, gid, num_groups):
    """Segmented 128-bit sum. Decomposes each two's-complement value
    into four 32-bit limbs, segment-sums each into uint64 accumulators
    (exact for < 2^32 rows), then carry-propagates back to (hi, lo).
    The result is the true sum mod 2^128 — wrap detection is the
    caller's job (see expr/aggregates.py decimal sum)."""
    uh, ul = _u(hi), lo
    limbs = [ul & _U32, ul >> jnp.uint64(32), uh & _U32,
             uh >> jnp.uint64(32)]
    sums = []
    for w in limbs:
        acc = jnp.zeros(num_groups, jnp.uint64)
        sums.append(acc.at[gid].add(w))
    acc = sums[0]
    w0 = acc & _U32
    acc = (acc >> jnp.uint64(32)) + sums[1]
    w1 = acc & _U32
    acc = (acc >> jnp.uint64(32)) + sums[2]
    w2 = acc & _U32
    acc = (acc >> jnp.uint64(32)) + sums[3]
    w3 = acc & _U32
    out_lo = w0 | (w1 << jnp.uint64(32))
    out_hi = _s(w2 | (w3 << jnp.uint64(32)))
    return out_hi, out_lo


def sort_key_bias(h):
    """Order-preserving uint64 image of the hi limb: flip the sign bit
    so (biased_hi, lo) lexicographic unsigned order == signed 128-bit
    numeric order. Used by segmented min/max and sort-key expansion."""
    return _u(h) ^ jnp.uint64(1 << 63)


def seg_minmax128(hi, lo, valid, gid, num_groups, largest: bool):
    """Segmented 128-bit min/max via two lexicographic passes: first
    reduce the biased hi limb, then reduce lo among rows whose hi limb
    equals the group winner."""
    bh = sort_key_bias(hi)
    hi_fill = jnp.uint64(0) if largest else jnp.uint64(0xFFFFFFFFFFFFFFFF)
    lo_fill = hi_fill
    bh_m = jnp.where(valid, bh, hi_fill)
    acc = jnp.full(num_groups, hi_fill, jnp.uint64)
    best_hi = (acc.at[gid].max(bh_m) if largest else acc.at[gid].min(bh_m))
    on_best = valid & (bh_m == best_hi[gid])
    lo_m = jnp.where(on_best, lo, lo_fill)
    acc2 = jnp.full(num_groups, lo_fill, jnp.uint64)
    best_lo = (acc2.at[gid].max(lo_m) if largest else acc2.at[gid].min(lo_m))
    out_hi = _s(best_hi ^ jnp.uint64(1 << 63))
    return out_hi, best_lo


def from_unscaled_ints(values, capacity: int, dtype: dt.DecimalType,
                       mask: Optional[np.ndarray] = None
                       ) -> Decimal128Column:
    """Build from python unscaled ints (arbitrary precision)."""
    n = len(values)
    valid = np.array([v is not None for v in values], dtype=bool) \
        if mask is None else np.asarray(mask, dtype=bool)
    hi = np.zeros(capacity, np.int64)
    lo = np.zeros(capacity, np.uint64)
    for i in range(n):
        if not valid[i] or values[i] is None:
            continue
        v = int(values[i])
        hi[i] = np.int64(v >> 64)  # python >> is arithmetic: sign-correct
        lo[i] = np.uint64(v & ((1 << 64) - 1))
    validity = np.zeros(capacity, bool)
    validity[:n] = valid
    return Decimal128Column(jnp.asarray(hi), jnp.asarray(lo),
                            jnp.asarray(validity), dtype)
