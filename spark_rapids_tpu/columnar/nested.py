"""Nested device columns: lists and structs.

TPU-native rebuild of cuDF's LIST/STRUCT column model as consumed by the
reference (GpuColumnVector.java type mapping :360, collectionOperations
/ complexTypeCreator / complexTypeExtractors.scala). Layouts follow
Arrow/cuDF:

- ``ListColumn``: ``offsets:int32[capacity+1]`` into a child Column
  holding the flattened elements; row i's elements are
  ``child[offsets[i]:offsets[i+1]]``. Null/dead rows have zero-length
  extents. ``pad_bucket`` is a static power-of-two bound on the longest
  list, the same static-shape device lowering trick StringColumn uses:
  element-wise kernels (contains/min/max/sort/get) view the list as a
  dense ``(capacity, pad_bucket)`` lane block.
- ``StructColumn``: parallel child columns sharing the parent validity;
  a null struct row nulls every child lane (the zero-under-null
  invariant from vector.py holds recursively).

Both register as JAX pytrees so nested batches flow through jit /
shard_map unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as dt


class ListColumn:
    __slots__ = ("offsets", "child", "validity", "dtype", "pad_bucket")

    def __init__(self, offsets: jax.Array, child, validity: jax.Array,
                 element_type: dt.DType, pad_bucket: int = 16,
                 map_type: Optional[dt.MapType] = None):
        self.offsets = offsets
        self.child = child
        self.validity = validity
        # maps ARE list<struct<key,value>> physically; map_type keeps
        # the logical map-ness through transformations so host
        # round-trips rebuild dicts (GpuColumnVector's LIST-backed MAP)
        self.dtype = map_type or dt.ArrayType(element_type)
        self.pad_bucket = pad_bucket

    @property
    def _map_type(self) -> Optional[dt.MapType]:
        return self.dtype if isinstance(self.dtype, dt.MapType) else None

    @property
    def capacity(self) -> int:
        return self.offsets.shape[0] - 1

    @property
    def child_capacity(self) -> int:
        return self.child.capacity

    def lengths(self) -> jax.Array:
        return self.offsets[1:] - self.offsets[:-1]

    def with_validity(self, validity: jax.Array) -> "ListColumn":
        return ListColumn(self.offsets, self.child, validity,
                          self.dtype.element_type, self.pad_bucket,
                          map_type=self._map_type)

    def element_lanes(self):
        """Dense (capacity, pad_bucket) view of a primitive child:
        (values, lane_ok, elem_ok) where lane_ok marks in-bounds lanes
        and elem_ok additionally requires a non-null element. The list
        analogue of StringColumn.padded()."""
        from .vector import ColumnVector
        assert isinstance(self.child, ColumnVector), \
            "element_lanes requires a primitive element type"
        cap = self.capacity
        starts = self.offsets[:-1]
        lens = self.lengths()
        k = jnp.arange(self.pad_bucket, dtype=jnp.int32)
        idx = jnp.clip(starts[:, None] + k[None, :], 0,
                       self.child_capacity - 1)
        vals = jnp.take(self.child.data, idx)
        lane_ok = k[None, :] < lens[:, None]
        elem_ok = lane_ok & jnp.take(self.child.validity, idx)
        vals = jnp.where(elem_ok, vals, jnp.zeros((), vals.dtype))
        return vals, lane_ok, elem_ok

    def gather(self, indices: jax.Array, valid: Optional[jax.Array] = None,
               unique: bool = False) -> "ListColumn":
        """Gather list rows, repacking the child (same scatter-free
        searchsorted pattern as StringColumn.gather)."""
        from .vector import round_pow2
        src_cap = self.capacity
        out_cap = indices.shape[0]
        if unique:
            child_cap = self.child_capacity
        else:
            child_cap = round_pow2(max(out_cap * self.pad_bucket, 8))
        safe = jnp.clip(indices, 0, src_cap - 1)
        starts = jnp.take(self.offsets[:-1], safe)
        lens = jnp.take(self.lengths(), safe)
        validity = jnp.take(self.validity, safe)
        if valid is not None:
            validity = validity & valid
            lens = jnp.where(valid, lens, 0)
        ends = jnp.cumsum(lens, dtype=jnp.int32)
        lens = jnp.where(ends <= child_cap, lens, 0)
        new_offsets = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(lens, dtype=jnp.int32)])
        from .vector import rows_from_offsets
        pos = jnp.arange(child_cap, dtype=jnp.int32)
        row_c = rows_from_offsets(new_offsets[:-1], lens, child_cap)
        within = pos - jnp.take(new_offsets, row_c)
        src_idx = jnp.take(starts, row_c) + within
        total = new_offsets[out_cap]
        elem_valid = pos < total
        new_child = self.child.gather(
            jnp.clip(src_idx, 0, self.child_capacity - 1), elem_valid)
        return ListColumn(new_offsets, new_child, validity,
                          self.dtype.element_type, self.pad_bucket,
                          map_type=self._map_type)

    def to_numpy(self, num_rows: Optional[int] = None):
        """Host copy: object array of python lists (logical values);
        map-typed columns rebuild dicts from their entry structs."""
        from .vector import from_physical
        n = self.capacity if num_rows is None else int(num_rows)
        offs = np.asarray(self.offsets)
        child_vals, child_mask = self.child.to_numpy()
        et = self.dtype.element_type
        as_map = self._map_type is not None
        out = np.empty(n, dtype=object)
        for i in range(n):
            lo, hi = int(offs[i]), int(offs[i + 1])
            items = [
                (_child_value(child_vals, child_mask, j, et))
                for j in range(lo, hi)]
            if as_map:
                out[i] = {e["key"]: e["value"] for e in items
                          if e is not None}
            else:
                out[i] = items
        return out, np.asarray(self.validity)[:n]

    def __repr__(self):
        return (f"ListColumn({self.dtype}, capacity={self.capacity}, "
                f"child_capacity={self.child_capacity})")


def _child_value(vals, mask, j, et):
    from .vector import from_physical
    if not mask[j]:
        return None
    v = vals[j]
    if isinstance(et, (dt.ArrayType, dt.StructType)):
        return v  # already logical (recursion happened in child.to_numpy)
    if et == dt.STRING:
        return v
    return from_physical(v, et)


class StructColumn:
    __slots__ = ("children", "validity", "dtype")

    def __init__(self, children: Sequence, validity: jax.Array,
                 struct_type: dt.StructType):
        self.children = list(children)
        self.validity = validity
        self.dtype = struct_type

    @property
    def capacity(self) -> int:
        return self.validity.shape[0]

    def field(self, name: str):
        return self.children[self.dtype.field_names().index(name)]

    def with_validity(self, validity: jax.Array) -> "StructColumn":
        return StructColumn(self.children, validity, self.dtype)

    def gather(self, indices: jax.Array, valid: Optional[jax.Array] = None,
               unique: bool = False) -> "StructColumn":
        safe = jnp.clip(indices, 0, self.capacity - 1)
        validity = jnp.take(self.validity, safe)
        if valid is not None:
            validity = validity & valid
        kids = []
        for c in self.children:
            if hasattr(c, "chars") or isinstance(c, ListColumn):
                kids.append(c.gather(indices, validity, unique=unique))
            else:
                kids.append(c.gather(indices, validity))
        return StructColumn(kids, validity, self.dtype)

    def to_numpy(self, num_rows: Optional[int] = None):
        n = self.capacity if num_rows is None else int(num_rows)
        field_data = []
        for c, (fname, ftype) in zip(self.children, self.dtype.fields):
            vals, mask = c.to_numpy(n)
            field_data.append((fname, ftype, vals, mask))
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = {fname: _child_value(vals, mask, i, ftype)
                      for fname, ftype, vals, mask in field_data}
        return out, np.asarray(self.validity)[:n]

    def __repr__(self):
        return f"StructColumn({self.dtype}, capacity={self.capacity})"


# ---------------------------------------------------------------------------
# pytree registration
# ---------------------------------------------------------------------------

def _lc_flatten(v: ListColumn):
    return ((v.offsets, v.child, v.validity),
            (v.dtype, v.pad_bucket))


def _lc_unflatten(aux, children):
    dtype, pad = aux
    offsets, child, validity = children
    mt = dtype if isinstance(dtype, dt.MapType) else None
    return ListColumn(offsets, child, validity, dtype.element_type,
                      pad, map_type=mt)


jax.tree_util.register_pytree_node(ListColumn, _lc_flatten, _lc_unflatten)


def _st_flatten(v: StructColumn):
    return (tuple(v.children), v.validity), v.dtype


def _st_unflatten(dtype, children):
    kids, validity = children
    return StructColumn(list(kids), validity, dtype)


jax.tree_util.register_pytree_node(StructColumn, _st_flatten, _st_unflatten)


# ---------------------------------------------------------------------------
# host -> device construction
# ---------------------------------------------------------------------------

def nested_column_from_pylist(values, capacity: int, dtype: dt.DType,
                              mask: Optional[np.ndarray] = None):
    """Build a device column for any (possibly nested) dtype from python
    values (None = null). Lists are python lists; structs are dicts (or
    tuples in field order)."""
    from .vector import column_from_numpy, round_pow2
    n = len(values)
    valid = np.array([v is not None for v in values], dtype=bool) \
        if mask is None else np.asarray(mask, dtype=bool)
    if isinstance(dtype, dt.MapType):
        # map = list<struct<key,value>>: values are dicts (or pair
        # sequences, the form pyarrow's to_pylist yields for pa.map_)
        def entries(v):
            pairs = v.items() if isinstance(v, dict) else v
            return [{"key": k, "value": val} for k, val in pairs]
        as_lists = [None if v is None else entries(v) for v in values]
        inner = dt.StructType((("key", dtype.key_type),
                               ("value", dtype.value_type)))
        lc = nested_column_from_pylist(as_lists, capacity,
                                       dt.ArrayType(inner), valid)
        return ListColumn(lc.offsets, lc.child, lc.validity, inner,
                          lc.pad_bucket, map_type=dtype)
    if isinstance(dtype, dt.ArrayType):
        lens = np.array([0 if v is None else len(v) for v in values],
                        dtype=np.int32)
        offsets = np.zeros(capacity + 1, dtype=np.int32)
        offsets[1:n + 1] = np.cumsum(lens)
        offsets[n + 1:] = offsets[n] if n else 0
        flat = []
        for v in values:
            if v is not None:
                flat.extend(v)
        child_cap = round_pow2(max(len(flat), 8))
        child = nested_column_from_pylist(flat + [None] * (child_cap -
                                                           len(flat)),
                                          child_cap, dtype.element_type)
        pad = round_pow2(max(int(lens.max()) if n else 1, 1))
        validity = np.zeros(capacity, dtype=bool)
        validity[:n] = valid
        return ListColumn(jnp.asarray(offsets), child,
                          jnp.asarray(validity), dtype.element_type,
                          pad_bucket=pad)
    if isinstance(dtype, dt.StructType):
        kids = []
        for fi, (fname, ftype) in enumerate(dtype.fields):
            fvals = []
            for v in values:
                if v is None:
                    fvals.append(None)
                elif isinstance(v, dict):
                    fvals.append(v.get(fname))
                else:
                    fvals.append(v[fi])
            kids.append(nested_column_from_pylist(fvals, capacity, ftype))
        validity = np.zeros(capacity, dtype=bool)
        validity[:n] = valid
        return StructColumn(kids, jnp.asarray(validity), dtype)
    # leaf
    arr = np.asarray(list(values), dtype=object)
    return column_from_numpy(arr, capacity, dtype=dtype, mask=valid)
